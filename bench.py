"""Benchmark: fused embed+classify throughput (posts/sec) on real hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the BASELINE.md north star — posts/sec through the fused
multilingual-E5-small-class encoder (embed + classify in a single encoder
pass, batch=256, seq=128, bf16).  ``vs_baseline`` is measured against the
reference's de-facto crawl ceiling of 3 000 msgs/min/connection = 50
posts/sec (BASELINE.md "Implied crawl ceiling"): the reference can only
*fetch* at 50/s/conn, so every multiple here is headroom the TPU stage has
over the crawl side it serves.
"""

from __future__ import annotations

import json
import time

# Reference ceiling: 3000 msgs/min/connection (BASELINE.md) -> 50 posts/sec.
REFERENCE_POSTS_PER_SEC = 50.0

BATCH = 256
SEQ = 128
# Two-point fit: total(N) = overhead + N * t_iter, so t_iter comes from the
# difference and the RPC/readback overhead cancels.  Iterations are chained
# through a data dependency (next ids derived from the previous output) and
# closed with a host readback — plain block_until_ready can return early
# through remote-execution relays, which would overstate throughput ~100x.
N_SHORT = 5
N_LONG = 25


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dataclasses import replace

    from distributed_crawler_tpu.models import E5_SMALL
    from distributed_crawler_tpu.models.encoder import EmbedderClassifier

    cfg = replace(E5_SMALL, n_labels=8)
    model = EmbedderClassifier(cfg)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(BATCH, SEQ)),
                      jnp.int32)
    mask = jnp.ones((BATCH, SEQ), jnp.bool_)
    params = model.init(jax.random.PRNGKey(0), ids, mask)

    n_dev = len(jax.devices())
    if n_dev > 1:
        from distributed_crawler_tpu.parallel import (
            best_mesh_config, make_mesh, shard_batch, shard_params,
        )

        mesh = make_mesh(best_mesh_config(n_dev))
        params = shard_params(params, mesh)
        placed = shard_batch({"ids": ids, "mask": mask}, mesh)
        ids, mask = placed["ids"], placed["mask"]

    @jax.jit
    def chained(p, ids, mask, n):
        def body(_, ids):
            emb, _logits = model.apply(p, ids, mask)
            delta = (emb[:, :1] * 1000).astype(jnp.int32) % cfg.vocab_size
            return (ids + delta) % cfg.vocab_size
        return jax.lax.fori_loop(0, n, body, ids)

    float(chained(params, ids, mask, 1).sum())  # warmup + compile

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        float(chained(params, ids, mask, n).sum())
        return time.perf_counter() - t0

    t_short = min(timed(N_SHORT) for _ in range(3))
    t_long = min(timed(N_LONG) for _ in range(3))
    t_iter = (t_long - t_short) / (N_LONG - N_SHORT)
    posts_per_sec = BATCH / t_iter
    print(json.dumps({
        "metric": "embed_classify_posts_per_sec",
        "value": round(posts_per_sec, 1),
        "unit": "posts/sec",
        "vs_baseline": round(posts_per_sec / REFERENCE_POSTS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
