"""Benchmark: fused embed+classify throughput (posts/sec) on real hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The headline metric is the BASELINE.md north star — posts/sec through the
fused multilingual-E5-small-class encoder (embed + classify in a single
encoder pass, batch=256, seq=128).  ``vs_baseline`` is measured against the
reference's de-facto crawl ceiling of 3 000 msgs/min/connection = 50
posts/sec (BASELINE.md "Implied crawl ceiling").  Extra fields carry the
rest of the north-star table: tokens/sec, model FLOPs utilisation (MFU,
TPU only), p50/p99 per-batch latency, and a dp-scaling efficiency row
measured on a virtual 8-device CPU mesh.

Robustness: the measurement runs in a CHILD process under a hard timeout;
whatever happens — wedged TPU backend, compile hang, import error — the
parent always emits exactly one parseable JSON line (with an ``error``
field carrying the diagnostic when the run failed).  Progress goes to
stderr so a watching driver can see where time is spent.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Reference ceiling: 3000 msgs/min/connection (BASELINE.md) -> 50 posts/sec.
REFERENCE_POSTS_PER_SEC = 50.0

BATCH = 256
SEQ = 128
# Two-point fit: total(N) = overhead + N * t_iter, so t_iter comes from the
# difference and the RPC/readback overhead cancels.  Iterations are chained
# through a data dependency (next ids derived from the previous output) and
# closed with a host readback — plain block_until_ready can return early
# through remote-execution relays, which would overstate throughput ~100x.
N_SHORT = 5
N_LONG = 25
LATENCY_SAMPLES = 30

# The per-chip peak-FLOPs table lives in
# `distributed_crawler_tpu/utils/costmodel.py` now (promoted so running
# workers share it); bench legs import it lazily, keeping this module's
# top level package-free — the parent must be able to emit its error JSON
# even when the package (or its jax import) is broken.

# A healthy chip finishes the whole measurement in <6 min (three compiles
# — bf16 + int8 + int8_static — at ~10-30 s each plus ~60-90 s of timing
# per model); the chip has been observed to wedge BETWEEN a passing probe
# and the main child, so the budget is sized to cut over to the CPU
# fallback while the driver's patience lasts, not to wait out a wedge.
CHILD_TIMEOUT_S = int(os.environ.get("BENCH_TIMEOUT_S", "560"))
SCALE_TIMEOUT_S = int(os.environ.get("BENCH_SCALE_TIMEOUT_S", "240"))
MESH_TIMEOUT_S = int(os.environ.get("BENCH_MESH_TIMEOUT_S", "300"))
# Pre-flight probe: one tiny jitted matmul on the default backend.  A wedged
# chip is discovered here in ≤PROBE_TIMEOUT_S instead of burning the full
# child budget, and the headline falls back to a CPU-labelled measurement.
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75"))
# A wedged chip sometimes recovers within a minute or two; one retry after
# a cooldown buys a second shot at a LIVE headline before surrendering the
# window to the CPU fallback (VERDICT r03: 2 of 3 rounds fell back).
PROBE_RETRY_COOLDOWN_S = int(os.environ.get("BENCH_PROBE_RETRY_S", "60"))
CPU_FALLBACK_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT_S", "300"))
ASR_TIMEOUT_S = int(os.environ.get("BENCH_ASR_TIMEOUT_S", "240"))
ASR_TINY_TIMEOUT_S = int(os.environ.get("BENCH_ASR_TINY_TIMEOUT_S", "120"))
CLUSTER_TIMEOUT_S = int(os.environ.get("BENCH_CLUSTER_TIMEOUT_S", "180"))
CLUSTER_TINY_TIMEOUT_S = int(
    os.environ.get("BENCH_CLUSTER_TINY_TIMEOUT_S", "120"))
XLMR_TIMEOUT_S = int(os.environ.get("BENCH_XLMR_TIMEOUT_S", "300"))
MOE_TIMEOUT_S = int(os.environ.get("BENCH_MOE_TIMEOUT_S", "420"))


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# The TPU behind the tunnel wedges intermittently (a bare matmul can hang
# HOURS, then recover).  Every successful TPU measurement is cached here
# so a run that samples a wedged window still carries the most recent REAL
# TPU number — clearly labelled as a prior measurement (measured_at), never
# as the live headline.  The file is git-tracked: the measurement is of the
# same tunneled chip class and must survive container rotation, where a
# wedged day would otherwise erase the only real number.
TPU_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_tpu_cache.json")


def _cache_tpu_result(result: dict) -> None:
    if result.get("platform") != "tpu":
        return
    try:
        # Merge over the prior entry: a run whose ASR (or int8) leg hit a
        # wedge keeps the last good values for those rows instead of
        # erasing them — every cached field is still a real TPU
        # measurement, just possibly from an earlier healthy window.
        # EVERY optional leg keeps its OWN timestamp so a carried-forward
        # row never wears a fresher run's measured_at (measured_at itself
        # covers only the always-fresh headline keys).
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        entry = _load_tpu_cache() or {}
        entry.update({k: v for k, v in result.items() if v is not None})
        entry["measured_at"] = now
        for probe_key, stamp in (
                ("asr_rtfx", "asr_measured_at"),
                ("xlmr_base_posts_per_sec", "xlmr_measured_at"),
                # The xlmr static sub-cell is best-effort within its leg
                # and can lag the rest of it — its own stamp keeps a
                # carried-forward cell honest.
                ("xlmr_base_int8_static_posts_per_sec",
                 "xlmr_static_measured_at"),
                ("int8_posts_per_sec", "int8_measured_at"),
                ("int8_static_posts_per_sec", "int8_static_measured_at"),
                ("moe_capacity_posts_per_sec", "moe_measured_at"),
                ("cluster_assign_vectors_per_s", "cluster_measured_at"),
                ("serving_posts_per_sec", "serving_measured_at")):
            if result.get(probe_key) is not None:
                entry[stamp] = now
        with open(TPU_CACHE_PATH, "w", encoding="utf-8") as f:
            json.dump(entry, f)
    except OSError as exc:
        _log(f"could not write TPU cache: {exc}")


def _load_tpu_cache() -> dict | None:
    try:
        with open(TPU_CACHE_PATH, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _fit_int8_static(cfg, params, ids, mask, fit):
    """Calibrate static activation scales, build the int8_static model,
    return (posts/sec numerator is the caller's) its fitted t_iter — the
    ONE static-leg recipe shared by the E5-small and XLM-R bench legs."""
    from dataclasses import replace

    from distributed_crawler_tpu.models.encoder import EmbedderClassifier
    from distributed_crawler_tpu.models.quant import (
        calibrate_activation_scales,
        quantize_encoder_params,
    )

    calib_model = EmbedderClassifier(replace(cfg, calibrate=True))
    scales = calibrate_activation_scales(
        calib_model, params, ids[:min(64, ids.shape[0])],
        mask[:min(64, mask.shape[0])])
    smodel = EmbedderClassifier(replace(cfg, quant="int8_static"))
    sparams = quantize_encoder_params(params, act_scales=scales)
    return fit(smodel, sparams)


def _zipf_text(i: int, n_words: int) -> str:
    """Zipf-ish synthetic post text: a 997-word vocabulary with per-text
    phase — real text re-uses words (the memo helps) but no two texts are
    identical (no all-same best case).  Shared by the serving-e2e and
    bus-codec legs so both measure the same text distribution."""
    return " ".join(f"w{(i * 31 + j * 7) % 997}" for j in range(n_words))


def _chained_t_iter(model, params, ids, mask, vocab: int,
                    n_short: int, n_long: int, repeats: int,
                    label: str = "") -> float:
    """Per-iteration time of the fused embed+classify step.

    Two-point fit: total(N) = overhead + N * t_iter, so t_iter comes from
    the difference and the RPC/readback overhead cancels.  Iterations are
    chained through a data dependency (next ids derived from the previous
    output) and closed with a host readback — plain block_until_ready can
    return early through remote-execution relays, which would overstate
    throughput ~100x.  The ONE timing methodology every bench leg uses.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chained(p, ids, mask, n):
        def body(_, ids):
            emb, _logits = model.apply(p, ids, mask)
            delta = (emb[:, :1] * 1000).astype(jnp.int32) % vocab
            return (ids + delta) % vocab
        return jax.lax.fori_loop(0, n, body, ids)

    t0 = time.perf_counter()
    float(chained(params, ids, mask, 1).sum())  # warmup + compile
    _log(f"{label or 'model'} compile+warmup done in "
         f"{time.perf_counter() - t0:.1f}s")

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        float(chained(params, ids, mask, n).sum())
        return time.perf_counter() - t0

    t_short = t_long = 0.0
    for _ in range(3):  # scheduler noise can invert the two-point fit
        t_short = min(timed(n_short) for _ in range(repeats))
        t_long = min(timed(n_long) for _ in range(repeats))
        t_iter = (t_long - t_short) / (n_long - n_short)
        if t_iter > 0:
            return t_iter
        _log("two-point fit inverted (noise); re-measuring")
    raise RuntimeError(
        f"timing fit stayed non-positive (t_short={t_short:.4f}s, "
        f"t_long={t_long:.4f}s): host too noisy for a measurement")


def _encoder_forward_flops(cfg, batch: int, seq: int) -> float:
    """Analytic forward FLOPs for one embed+classify batch — promoted to
    `utils/costmodel.py` (the serving cost model's fallback); kept here as
    a delegate so the bench's own call sites and tests keep their path."""
    from distributed_crawler_tpu.utils.costmodel import (
        encoder_forward_flops,
    )

    return encoder_forward_flops(cfg, batch, seq)


def _probe() -> dict:
    """Tiny jitted matmul on the default backend — proves the chip answers."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    x = jnp.ones((128, 128), jnp.bfloat16)
    y = float(jax.jit(lambda a: (a @ a).sum())(x))
    return {"ok": True, "platform": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "probe_s": round(time.perf_counter() - t0, 2), "sum": y}


def _measure(scale_devices: int | None = None,
             batch: int | None = None, seq: int = SEQ,
             n_short: int = N_SHORT, n_long: int = N_LONG,
             latency_samples: int = LATENCY_SAMPLES,
             repeats: int = 3, with_int8: bool = True,
             with_serving: bool = True) -> dict:
    """Run the measurement in-process; returns the result dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dataclasses import replace

    from distributed_crawler_tpu.models import E5_SMALL
    from distributed_crawler_tpu.models.encoder import EmbedderClassifier

    _log(f"jax ready: platform={jax.default_backend()} "
         f"devices={len(jax.devices())}")

    cfg = replace(E5_SMALL, n_labels=8)
    model = EmbedderClassifier(cfg)

    if batch is None:
        batch = BATCH if scale_devices is None else 64 * max(scale_devices, 1)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(batch, seq)),
                      jnp.int32)
    mask = jnp.ones((batch, seq), jnp.bool_)
    params = model.init(jax.random.PRNGKey(0), ids, mask)
    _log("params initialized")

    n_dev = len(jax.devices())
    use_dev = scale_devices or n_dev
    mesh = None
    if use_dev > 1:
        from distributed_crawler_tpu.parallel import (
            best_mesh_config, make_mesh, shard_batch, shard_params,
        )

        mesh = make_mesh(best_mesh_config(use_dev),
                         devices=jax.devices()[:use_dev])
        params = shard_params(params, mesh)
        placed = shard_batch({"ids": ids, "mask": mask}, mesh)
        ids, mask = placed["ids"], placed["mask"]
        _log(f"sharded over mesh {dict(mesh.shape)}")

    t_iter = _chained_t_iter(model, params, ids, mask, cfg.vocab_size,
                             n_short, n_long, repeats, label="bf16")
    posts_per_sec = batch / t_iter
    _log(f"throughput: {posts_per_sec:.1f} posts/sec (t_iter={t_iter*1e3:.2f}ms)")

    if scale_devices is not None:
        return {"posts_per_sec": posts_per_sec}

    # Int8 serving path (ops/quant.py): same chained methodology over the
    # quantized model.  Best-effort — an exception here never costs the
    # bf16 headline — and skipped entirely in the CPU fallback
    # (``with_int8=False``), whose timeout budget is sized for ONE
    # compile+fit; only the TPU child pays for the second model.
    int8_pps = None
    int8_static_pps = None
    if with_int8:
        try:
            from distributed_crawler_tpu.models.quant import (
                quantize_encoder_params,
            )

            qmodel = EmbedderClassifier(replace(cfg, quant="int8"))
            qparams = quantize_encoder_params(params)
            t_iter_q = _chained_t_iter(qmodel, qparams, ids, mask,
                                       cfg.vocab_size, n_short, n_long,
                                       repeats, label="int8")
            int8_pps = batch / t_iter_q
            _log(f"int8 throughput: {int8_pps:.1f} posts/sec "
                 f"(speedup {int8_pps / posts_per_sec:.2f}x)")
        except Exception as exc:  # noqa: BLE001 — int8 row is best-effort
            _log(f"int8 measurement skipped: {exc}")
        try:
            # Static activation scales (fused quantize — the attack on the
            # dynamic path's 0.79x at this width; ops/quant.py).
            t_iter_s = _fit_int8_static(
                cfg, params, ids, mask,
                lambda m, p: _chained_t_iter(m, p, ids, mask,
                                             cfg.vocab_size, n_short,
                                             n_long, repeats,
                                             label="int8_static"))
            int8_static_pps = batch / t_iter_s
            _log(f"int8_static throughput: {int8_static_pps:.1f} posts/sec"
                 f" (speedup {int8_static_pps / posts_per_sec:.2f}x)")
        except Exception as exc:  # noqa: BLE001 — best-effort row
            _log(f"int8_static measurement skipped: {exc}")

    # Serving-path throughput: the ACTUAL InferenceEngine.run_tokenized
    # loop (bucketing, one-deep dispatch/readback pipeline, softmax,
    # result dicts) — what a TPUWorker batch stream achieves end to end,
    # as opposed to the chained pure-device number above.  Best-effort.
    serving_pps = None
    serving_e2e_pps = None
    serving_busy = None
    serving_overlap = None
    serving_bubble_ms = None
    if with_serving:
        try:
            from distributed_crawler_tpu.inference.engine import (
                EngineConfig,
                InferenceEngine,
            )
            from distributed_crawler_tpu.utils.metrics import MetricsRegistry

            # Same mesh as the chained baseline (None single-device), so
            # the "x of chained" ratio compares like for like.
            eng = InferenceEngine(
                EngineConfig(model="e5_small", n_labels=8, batch_size=batch,
                             buckets=(seq,)),
                mesh=mesh, params=params, registry=MetricsRegistry())
            toks = [[7] * (seq - 2)] * (batch * 8)
            eng.run_tokenized(toks[:batch])  # compile+warm
            eng.timeline.reset()  # compile interval isn't pipeline signal
            t0 = time.perf_counter()
            out = eng.run_tokenized(toks)
            dt = time.perf_counter() - t0
            assert len(out) == len(toks)
            serving_pps = len(toks) / dt
            _log(f"serving path: {serving_pps:.1f} posts/sec "
                 f"({serving_pps / posts_per_sec:.2f}x of chained)")
            # End-to-end variant: raw TEXT in (tokenize included) — what
            # a worker consuming post bodies actually sustains.  A 997-word
            # vocabulary with per-text phase gives Zipf-ish repeats (real
            # text re-uses words; the memo helps but isn't handed an
            # all-identical best case).  Lengths land in the same bucket.
            n_words = (seq - 2) // 2
            texts = [_zipf_text(i, n_words) for i in range(batch * 4)]
            eng.run(texts[:batch])  # warm the tokenizer memo
            t0 = time.perf_counter()
            out = eng.run(texts)
            dt = time.perf_counter() - t0
            assert len(out) == len(texts)
            serving_e2e_pps = len(texts) / dt
            _log(f"serving e2e (text in): {serving_e2e_pps:.1f} posts/sec")
            # Pipeline-efficiency rows from the engine's DeviceTimeline
            # (utils/occupancy.py): how busy the device envelope was over
            # the serving runs, how much host/device overlap the one-deep
            # pipeline achieved, and the bubble cost per batch — the
            # numbers the continuous-batching rebuild must move.
            occ = eng.timeline.snapshot() or {}
            serving_busy = occ.get("busy_fraction")
            serving_overlap = occ.get("overlap_fraction")
            serving_bubble_ms = occ.get("bubble_ms_per_batch")
            _log(f"pipeline: busy={serving_busy} overlap={serving_overlap}"
                 f" bubble_ms_per_batch={serving_bubble_ms}")
        except Exception as exc:  # noqa: BLE001 — best-effort row
            _log(f"serving-path measurement skipped: {exc}")

    # Per-batch latency: one step closed with a scalar readback each time —
    # the latency a TPUWorker batch actually experiences (includes RPC).
    @jax.jit
    def one_step(p, ids, mask):
        emb, logits = model.apply(p, ids, mask)
        return emb.sum() + logits.sum()

    float(one_step(params, ids, mask))  # compile
    lats = []
    for _ in range(latency_samples):
        t0 = time.perf_counter()
        float(one_step(params, ids, mask))
        lats.append(time.perf_counter() - t0)
    lats.sort()
    p50 = lats[len(lats) // 2] * 1e3
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e3
    _log(f"latency: p50={p50:.2f}ms p99={p99:.2f}ms")

    flops = _encoder_forward_flops(cfg, batch, seq)
    from distributed_crawler_tpu.utils.costmodel import peak_flops

    peak, peak_source = peak_flops(jax.devices()[0].device_kind,
                                   jax.default_backend(), use_dev)
    # "mfu" stays TPU-only (vs a real chip peak); "mfu_estimate" always
    # lands when ANY peak is resolvable — on CPU against the deliberately
    # conservative estimate — so the perf trajectory has an mfu_* row on
    # every run, wedged chip or not (peak_source labels which it was).
    mfu = ((flops / t_iter) / peak
           if peak and jax.default_backend() == "tpu" else None)
    mfu_estimate = (flops / t_iter) / peak if peak else None

    return {
        "metric": "embed_classify_posts_per_sec",
        "value": round(posts_per_sec, 1),
        "unit": "posts/sec",
        "vs_baseline": round(posts_per_sec / REFERENCE_POSTS_PER_SEC, 2),
        "tokens_per_sec": round(posts_per_sec * seq, 1),
        "batch_latency_p50_ms": round(p50, 2),
        "batch_latency_p99_ms": round(p99, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_estimate": round(mfu_estimate, 6)
        if mfu_estimate is not None else None,
        "mfu_peak_source": peak_source if peak else None,
        "int8_posts_per_sec": round(int8_pps, 1) if int8_pps else None,
        "int8_speedup": round(int8_pps / posts_per_sec, 2) if int8_pps
        else None,
        "int8_static_posts_per_sec": round(int8_static_pps, 1)
        if int8_static_pps else None,
        "int8_static_speedup": round(int8_static_pps / posts_per_sec, 2)
        if int8_static_pps else None,
        "serving_e2e_posts_per_sec": round(serving_e2e_pps, 1)
        if serving_e2e_pps else None,
        "serving_posts_per_sec": round(serving_pps, 1) if serving_pps
        else None,
        "device_busy_fraction": round(serving_busy, 6)
        if serving_busy is not None else None,
        "overlap_fraction": round(serving_overlap, 6)
        if serving_overlap is not None else None,
        "bubble_ms_per_batch": round(serving_bubble_ms, 4)
        if serving_bubble_ms is not None else None,
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": use_dev,
        "batch": batch,
        "seq": seq,
    }


def _measure_xlmr_int8(batch: int = 256, seq: int = SEQ,
                       n_short: int = 3, n_long: int = 12,
                       repeats: int = 3) -> dict:
    """BASELINE config #3 width: bf16 vs int8 at XLM-R-base.

    `ops/quant.py` predicts int8 pays off once the projection GEMMs
    dominate (hidden 768 vs E5-small's 384); this leg measures that claim
    where BASELINE cares about it (VERDICT r03 #1).  Small vocab: the
    embedding gather is width-independent and a 250k-row table adds ~20x
    init time for zero timing signal.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dataclasses import replace

    from distributed_crawler_tpu.models.encoder import (
        XLMR_BASE,
        EmbedderClassifier,
    )
    from distributed_crawler_tpu.models.quant import quantize_encoder_params

    vocab = 32768
    cfg = replace(XLMR_BASE, vocab_size=vocab, n_labels=8)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, size=(batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.bool_)
    model = EmbedderClassifier(cfg)
    params = model.init(jax.random.PRNGKey(0), ids, mask)
    _log("xlmr params initialized")

    def fit(m, p, label):
        return _chained_t_iter(m, p, ids, mask, vocab, n_short, n_long,
                               repeats, label=f"xlmr {label}")

    t_bf16 = fit(model, params, "bf16")
    qmodel = EmbedderClassifier(replace(cfg, quant="int8"))
    qparams = quantize_encoder_params(params)
    t_int8 = fit(qmodel, qparams, "int8")
    out = {
        "xlmr_base_posts_per_sec": round(batch / t_bf16, 1),
        "xlmr_base_int8_posts_per_sec": round(batch / t_int8, 1),
        "xlmr_base_int8_speedup": round(t_bf16 / t_int8, 2),
        "xlmr_batch": batch,
    }
    _log(f"xlmr: bf16 {batch / t_bf16:.1f} posts/s, "
         f"int8 {batch / t_int8:.1f} posts/s "
         f"(speedup {t_bf16 / t_int8:.2f}x)")
    try:
        # Static-scale variant (fused quantize): best-effort third cell.
        t_static = _fit_int8_static(
            cfg, params, ids, mask,
            lambda m, p: fit(m, p, "int8_static"))
        out["xlmr_base_int8_static_posts_per_sec"] = round(
            batch / t_static, 1)
        out["xlmr_base_int8_static_speedup"] = round(t_bf16 / t_static, 2)
        _log(f"xlmr int8_static: {batch / t_static:.1f} posts/s "
             f"(speedup {t_bf16 / t_static:.2f}x)")
    except Exception as exc:  # noqa: BLE001 — best-effort row
        _log(f"xlmr int8_static skipped: {exc}")
    return out


def _measure_moe(batch: int = 256, seq: int = SEQ, n_experts: int = 8,
                 n_short: int = 3, n_long: int = 12, repeats: int = 3,
                 base_cfg=None) -> dict:
    """Switch-MoE dispatch cost: dense vs capacity at XLM-R width, E=8.

    `models/encoder.py` predicts capacity dispatch runs ~cf× the MLP FLOPs
    where dense-dispatch runs E× (every token through every expert); this
    leg measures that claim with the bench's one timing methodology so the
    ratio is a number, not an argument from the FLOPs table (VERDICT r04
    missing #5).  The same trained weights serve both cells — dispatch is
    a runtime choice (`--infer-moe-dispatch`).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dataclasses import replace

    from distributed_crawler_tpu.models.encoder import (
        XLMR_BASE,
        EmbedderClassifier,
    )

    vocab = 32768
    base = base_cfg or replace(XLMR_BASE, vocab_size=vocab)
    cfg = replace(base, n_labels=8, n_experts=n_experts,
                  moe_dispatch="dense")
    vocab = cfg.vocab_size
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, size=(batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.bool_)
    model = EmbedderClassifier(cfg)
    params = model.init(jax.random.PRNGKey(0), ids, mask)
    _log(f"moe params initialized (E={n_experts})")

    def fit(m, label):
        return _chained_t_iter(m, params, ids, mask, vocab, n_short,
                               n_long, repeats, label=f"moe {label}")

    t_dense = fit(model, "dense-dispatch")
    cmodel = EmbedderClassifier(replace(cfg, moe_dispatch="capacity"))
    t_cap = fit(cmodel, "capacity-dispatch")
    _log(f"moe: dense {batch / t_dense:.1f} posts/s, "
         f"capacity {batch / t_cap:.1f} posts/s "
         f"(speedup {t_dense / t_cap:.2f}x)")
    return {
        "moe_dense_posts_per_sec": round(batch / t_dense, 1),
        "moe_capacity_posts_per_sec": round(batch / t_cap, 1),
        "moe_capacity_speedup": round(t_dense / t_cap, 2),
        "moe_experts": n_experts,
        "moe_capacity_factor": cfg.moe_capacity_factor,
        "moe_batch": batch,
    }


def _measure_bus_codec(batch: int = 256, n_batches: int = 40,
                       text_words: int = 60) -> dict:
    """Distributed-path codec throughput: Post -> record-batch frame
    (zstd/gzip) -> wire bytes -> back, on the host CPU.

    The reference ships crawl output through Dapr pubsub with no framing
    of its own; this framework's gRPC bus rides `bus/codec.py` record
    batches, so codec posts/sec is the distributed pipeline's host-side
    ceiling per worker.  CPU-only by nature — measured on every bench run
    (wedged chip or not) and reported next to the device rows.
    """
    from distributed_crawler_tpu.bus.codec import (
        RecordBatch,
        decode_frame,
        default_compression,
        encode_frame,
    )
    from distributed_crawler_tpu.datamodel.post import Post

    # Zipf-ish DISTINCT texts per post: identical (or cross-record
    # repeated) texts would let zstd dedup across records and report
    # fantasy bytes/post — disjoint phase ranges keep every text unique.
    posts = [Post(post_uid=f"p{i}", channel_id="c1",
                  post_link=f"https://t.me/c1/{i}",
                  description=_zipf_text(i, text_words),
                  searchable_text=_zipf_text(i + batch, text_words))
             for i in range(batch)]
    rb = RecordBatch.from_posts(posts, crawl_id="bench")
    payload = rb.to_dict()
    comp = default_compression()
    # Warm once (zstd context, dict caches), then time the loop.
    buf = encode_frame(payload, comp)
    decode_frame(buf)
    t0 = time.perf_counter()
    for _ in range(n_batches):
        buf = encode_frame(payload, comp)
        decode_frame(buf)
    dt = time.perf_counter() - t0
    pps = batch * n_batches / dt
    _log(f"bus codec ({comp}): {pps:.0f} posts/sec roundtrip, "
         f"{len(buf)} B/frame ({len(buf) / batch:.0f} B/post)")
    return {
        "bus_codec_posts_per_sec": round(pps, 1),
        "bus_codec_compression": comp,
        "bus_codec_bytes_per_post": round(len(buf) / batch, 1),
    }


# Shard counts the bus-throughput leg measures — ONE constant shared by
# the measurement and the skip→None fallback so they can't desync.
BUS_SHARD_COUNTS = (1, 2, 4)


def _measure_bus_shards(counts=BUS_SHARD_COUNTS, frames: int = 2400,
                        leg_timeout_s: float = 240.0) -> dict:
    """Partitioned-bus throughput scaling: aggregate publish→pull→ack
    frames/sec through 1, 2, and 4 broker shards.

    Each shard is its OWN OS process (`python -m
    distributed_crawler_tpu.bus.partition --bench-child`) hosting a
    stock GrpcBusServer on a loopback port — the deployment shape, one
    broker per process — publishing its consistent-hash-ring-owned slice
    of one FIXED seeded uid space (same total work at every shard
    count) and pulling+acking every frame back over real gRPC.

    Methodology (the `dp_sharding_efficiency_*` discipline — measure
    honestly, label the same-host caveat): the headline
    ``bus_frames_per_s_shards{N}`` rows are aggregate CAPACITY — each
    shard measured in ISOLATION (sequentially) and the rates summed,
    because production broker shards do not share a host core, while
    this bench box may have as few as ONE (``bus_shard_host_cores``
    records it).  The same-host CONCURRENT run of the largest fleet is
    reported next to it (``bus_shard_concurrent_scaling``) so the pair
    separates the sharding win (per-broker ceiling × N) from this
    host's core budget.  CPU-only by nature — measured on every bench
    run, wedged chip or not.
    """
    import subprocess
    import threading as _threading

    repo = os.path.dirname(os.path.abspath(__file__))

    def _read_line(proc, box, key):
        try:
            box[key] = proc.stdout.readline()
        except Exception as exc:  # noqa: BLE001 — reader thread
            box[key] = ""
            box[f"{key}_err"] = str(exc)

    def _reap(p) -> None:
        # kill AND wait: an unreaped child is a zombie for the rest of
        # the (15-20 min) bench run.
        try:
            p.kill()
        except OSError:
            pass
        try:
            p.wait(timeout=5)
        except Exception as exc:  # noqa: BLE001 — best-effort reap
            _log(f"bus shard child reap failed: {exc}")

    def _child(i: int, n: int) -> "subprocess.Popen":
        return subprocess.Popen(
            [sys.executable, "-m",
             "distributed_crawler_tpu.bus.partition",
             "--bench-child", "--shard-index", str(i),
             "--shard-count", str(n), "--frames", str(frames),
             "--seed", "7"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, cwd=repo)

    def _await(procs, box, phase: str, deadline: float) -> None:
        readers = []
        for i, p in enumerate(procs):
            t = _threading.Thread(target=_read_line,
                                  args=(p, box, f"{phase}{i}"),
                                  daemon=True)
            t.start()
            readers.append(t)
        for t in readers:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def _results(procs, box, deadline: float) -> list:
        _await(procs, box, "result", deadline)
        results = []
        for i in range(len(procs)):
            line = box.get(f"result{i}", "")
            if not line.strip():
                raise RuntimeError(
                    f"shard child {i}/{len(procs)} produced no result")
            results.append(json.loads(line))
        if not all(r.get("completed") for r in results):
            raise RuntimeError(f"shard child timed out: {results}")
        return results

    def _run_isolated(n: int) -> float:
        """Sum of per-shard rates, each shard measured alone (the
        capacity of an n-broker fleet whose brokers don't share a
        core)."""
        rate = 0.0
        for i in range(n):
            deadline = time.monotonic() + leg_timeout_s
            p = _child(i, n)
            try:
                box: dict = {}
                _await([p], box, "ready", deadline)
                if box.get("ready0", "").strip() != "READY":
                    raise RuntimeError(
                        f"shard child {i}/{n} not READY: {box}")
                p.stdin.write("GO\n")
                p.stdin.flush()
                r = _results([p], box, deadline)[0]
                rate += r["frames"] / r["wall_s"]
            finally:
                _reap(p)
        return rate

    def _run_concurrent(n: int) -> float:
        """Total frames / slowest shard wall with every shard live at
        once on THIS host — the same-host number."""
        deadline = time.monotonic() + leg_timeout_s
        procs = [_child(i, n) for i in range(n)]
        try:
            box = {}
            _await(procs, box, "ready", deadline)
            if not all(box.get(f"ready{i}", "").strip() == "READY"
                       for i in range(n)):
                raise RuntimeError(f"shard children not READY: {box}")
            for p in procs:
                p.stdin.write("GO\n")
                p.stdin.flush()
            results = _results(procs, box, deadline)
            return sum(r["frames"] for r in results) \
                / max(r["wall_s"] for r in results)
        finally:
            for p in procs:
                _reap(p)

    rates = {}
    for n in counts:
        rates[n] = _run_isolated(n)
        _log(f"bus shards x{n}: {rates[n]:.0f} frames/s aggregate "
             f"capacity ({frames} frames fixed, shards isolated)")
    biggest = max(counts)
    concurrent = _run_concurrent(biggest)
    _log(f"bus shards x{biggest} same-host concurrent: "
         f"{concurrent:.0f} frames/s")
    out = {f"bus_frames_per_s_shards{n}": round(r, 1)
           for n, r in rates.items()}
    out["bus_shard_frames"] = frames
    out["bus_shard_host_cores"] = os.cpu_count()
    if rates.get(1):
        if rates.get(4):
            out["bus_shard_scaling_4x"] = round(rates[4] / rates[1], 2)
        out["bus_shard_concurrent_scaling"] = round(
            concurrent / rates[1], 2)
    return out


def _measure_padding_efficiency(n_texts: int = 2048, batch: int = 256,
                                max_segments: int = 8) -> dict:
    """Padding efficiency: real tokens / total slot tokens, packed vs
    unpacked, on a Zipf-LENGTH workload (most posts far below their
    bucket — the distribution the tentpole attacks).

    Pure host arithmetic over the REAL packer (`ops/padding.pack_rows`)
    and the real bucket ladder, so the row lands on every run (wedged chip
    or not).  Slot tokens = bucket rows x bucket length, at the coalesced
    steady state (`worker.coalesce_batches` keeps the row stream full, so
    partial final device batches amortize to nothing and are excluded —
    they would charge both modes the same constant); the gain is the
    fraction of MXU/HBM work `run_tokenized(..., pack=True)` stops
    spending on pad tokens.
    """
    import numpy as np

    from distributed_crawler_tpu.inference.tokenizer import HashingTokenizer
    from distributed_crawler_tpu.ops.padding import (
        BucketSpec,
        bucket_for,
        pack_rows,
    )

    rng = np.random.default_rng(0)
    # Zipf-ish post lengths in words (mean ~12, long tail to the ladder's
    # reach) — the reference's crawl stream is short-message-dominated.
    words = np.minimum(rng.zipf(1.7, size=n_texts), 500)
    tok = HashingTokenizer(vocab_size=250037)
    toks = tok.encode_batch([_zipf_text(i, int(w))
                             for i, w in enumerate(words)])
    spec = BucketSpec()
    groups: dict = {}
    for i, t in enumerate(toks):
        groups.setdefault(bucket_for(len(t), spec), []).append(i)
    real = unpacked_slots = packed_slots = 0
    for bucket, idx in sorted(groups.items()):
        real += sum(min(len(toks[i]), bucket) for i in idx)
        packed = pack_rows([toks[i] for i in idx], bucket,
                           max_segments=max_segments)
        unpacked_slots += len(idx) * bucket
        packed_slots += packed.n_rows * bucket
    d_unpacked = real / unpacked_slots
    d_packed = real / packed_slots
    _log(f"padding efficiency: unpacked {d_unpacked:.3f}, "
         f"packed {d_packed:.3f} ({d_packed / d_unpacked:.2f}x density)")
    return {
        "padding_density_unpacked": round(d_unpacked, 4),
        "padding_density_packed": round(d_packed, 4),
        "padding_packed_density_gain": round(d_packed / d_unpacked, 2),
        "padding_pack_max_segments": max_segments,
    }


def _measure_cost_model(batch: int = BATCH,
                        buckets=(64, 128, 256, 512)) -> dict:
    """Per-bucket forward-FLOP rows from the serving cost model's analytic
    formula (`utils/costmodel.py`) — pure host arithmetic, so the bench
    trajectory carries ``bucket_flops_*`` on EVERY run (wedged chip or
    not).  A live worker's ``/costs`` endpoint upgrades the same buckets
    to XLA ``cost_analysis`` numbers; the source field keeps the two
    provenances distinguishable."""
    from dataclasses import replace

    from distributed_crawler_tpu.models import E5_SMALL
    from distributed_crawler_tpu.utils.costmodel import (
        encoder_forward_flops,
    )

    cfg = replace(E5_SMALL, n_labels=8)
    out = {f"bucket_flops_{b}": encoder_forward_flops(cfg, batch, b)
           for b in buckets}
    out["bucket_flops_batch"] = batch
    out["bucket_flops_source"] = "analytic"
    return out


def _measure_tokenizer(batch: int = 1024, text_words: int = 63,
                       trials: int = 4) -> dict:
    """Host-side tokenize throughput: the serving pipeline's text-in front
    door (`inference/tokenizer.py`), warm memo, Zipf-varied texts — the
    rate the host must sustain so text-in serving doesn't bottleneck
    before the chip does.  CPU-only by nature; measured on every run."""
    from distributed_crawler_tpu.inference.tokenizer import HashingTokenizer

    tok = HashingTokenizer(vocab_size=250037)
    texts = [_zipf_text(i, text_words) for i in range(batch)]
    tok.encode_batch(texts)  # warm the memo
    best = 0.0
    for _ in range(trials):
        t0 = time.perf_counter()
        out = tok.encode_batch(texts)
        dt = time.perf_counter() - t0
        best = max(best, len(out) / dt)
    _log(f"tokenizer: {best:.0f} posts/sec warm "
         f"({text_words}-word Zipf posts)")
    return {"tokenizer_posts_per_sec": round(best, 1),
            "tokenizer_text_words": text_words}


def _measure_asr(batch: int = 8, decode_len: int = 48,
                 samples: int = 5, model_cfg=None) -> dict:
    """BASELINE config #4: Whisper ASR throughput on the default backend.

    Synthetic weights + noise audio (throughput does not depend on weight
    values) and a FIXED ``decode_len``-token greedy decode — random weights
    never emit EOT, so every run times the identical worst-case workload.
    Reported as RTFx: seconds of audio transcribed per wall-clock second
    (each 30 s window counts fully; the per-call host readback is included,
    matching what a media-transcription worker experiences) — plus
    ``asr_windows_per_s``, the unit the serving ASR worker's scheduler
    (`media/chunker.py`) and efficiency meters speak: fixed audio
    windows through the device per wall-clock second.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_crawler_tpu.models.whisper import (
        SAMPLE_RATE,
        WHISPER_SMALL,
        Whisper,
        audio_window_samples,
        transcribe_features,
    )

    cfg = model_cfg or WHISPER_SMALL
    model = Whisper(cfg)
    win = audio_window_samples(cfg)
    rng = np.random.default_rng(0)
    mel_probe = jnp.asarray(
        rng.standard_normal((1, cfg.n_audio_ctx * 2, cfg.n_mels)),
        jnp.float32)
    params = model.init(jax.random.PRNGKey(0), mel_probe,
                        jnp.zeros((1, 4), jnp.int32))
    _log(f"asr params initialized ({cfg.n_audio_state}-wide)")
    audio = jnp.asarray(rng.standard_normal((batch, win)) * 0.1, jnp.float32)
    step = jax.jit(lambda p, a: transcribe_features(model, p, a,
                                                    max_len=decode_len))
    t0 = time.perf_counter()
    np.asarray(step(params, audio))
    _log(f"asr compile+warmup done in {time.perf_counter() - t0:.1f}s")
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        np.asarray(step(params, audio))  # host readback closes the call
        times.append(time.perf_counter() - t0)
    t_call = sorted(times)[len(times) // 2]
    audio_sec = batch * (win / float(SAMPLE_RATE))
    _log(f"asr: {audio_sec / t_call:.1f}x realtime "
         f"(t_call={t_call * 1e3:.1f}ms)")
    # greedy_decode scans max_len-1 steps (the SOT token is free), so
    # decode_len-1 decoder forwards actually ran.
    return {
        "asr_rtfx": round(audio_sec / t_call, 1),
        "asr_windows_per_s": round(batch / t_call, 2),
        "asr_decode_tokens_per_sec": round(
            batch * (decode_len - 1) / t_call, 1),
        "asr_batch": batch,
        "asr_decode_len": decode_len,
        "asr_model": "whisper-small" if model_cfg is None else "custom",
        "asr_window_s": round(win / float(SAMPLE_RATE), 2),
    }


def _measure_asr_tiny(batch: int = 4, decode_len: int = 6,
                      samples: int = 3) -> dict:
    """Sized-down ASR leg for non-TPU hosts: the WHISPER_TEST config
    (millisecond-scale decode on CPU) keeps the ``asr_windows_per_s`` /
    RTFx rows present in every BENCH json — clearly labelled, never
    comparable to the whisper-small TPU numbers."""
    from distributed_crawler_tpu.models.whisper import WHISPER_TEST

    out = _measure_asr(batch=batch, decode_len=decode_len,
                       samples=samples, model_cfg=WHISPER_TEST)
    out["asr_model"] = "whisper-test-cpu"
    return out


def _measure_cluster(k: int = 256, dim: int = 1024, rows: int = 4096,
                     samples: int = 5) -> dict:
    """Streaming-clustering leg (BASELINE config #5's serving math): one
    online mini-batch k-means step on the `cluster/engine.py` serving
    engine — assignment is the [rows, dim] x [dim, k] MXU matmul, the
    update a one-hot einsum — timed end to end (host padding + dispatch
    + blocking readback, what the ClusterWorker's feed loop pays).
    Reported in the units the serving meters speak:
    ``cluster_assign_vectors_per_s`` (embedding rows through the step per
    wall-clock second) and ``cluster_step_ms`` (median step wall)."""
    import numpy as np

    from distributed_crawler_tpu.cluster.engine import (
        ClusterEngine,
        ClusterEngineConfig,
    )
    from distributed_crawler_tpu.utils.metrics import MetricsRegistry

    eng = ClusterEngine(ClusterEngineConfig(k=k, buckets=(rows,), seed=0),
                        registry=MetricsRegistry())
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    eng.observe(rng.standard_normal((rows, dim)).astype(np.float32))
    _log(f"cluster seed+compile done in {time.perf_counter() - t0:.1f}s "
         f"(k={k} dim={dim} rows={rows})")
    times = []
    for _ in range(samples):
        batch = rng.standard_normal((rows, dim)).astype(np.float32)
        t0 = time.perf_counter()
        eng.observe(batch)  # block_until_ready inside closes the call
        times.append(time.perf_counter() - t0)
    t_step = sorted(times)[len(times) // 2]
    _log(f"cluster: {rows / t_step:.0f} vectors/s "
         f"(t_step={t_step * 1e3:.1f}ms)")
    return {
        "cluster_assign_vectors_per_s": round(rows / t_step, 1),
        "cluster_step_ms": round(t_step * 1e3, 2),
        "cluster_k": k,
        "cluster_dim": dim,
        "cluster_rows": rows,
    }


def _measure_cluster_tiny() -> dict:
    """Sized-down clustering leg for non-TPU hosts: keeps the
    ``cluster_assign_vectors_per_s`` / ``cluster_step_ms`` rows present
    in every BENCH json — clearly labelled, never comparable to the
    full-width TPU numbers."""
    out = _measure_cluster(k=16, dim=64, rows=256, samples=3)
    out["cluster_model"] = "kmeans-tiny-cpu"
    return out


def _cpu_env(n_devices: int) -> dict:
    # Strip accelerator-tunnel vars so the host sitecustomize doesn't claim
    # a device session in a CPU-only child (it would block on the tunnel's
    # single session slot).
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("AXON", "PALLAS_AXON", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    prior = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(prior + [flag]).strip()
    return env


def _run_child(argv: list, env: dict, timeout: int):
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + argv,
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )


def _last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _dp_sharding_overhead(mesh8_pps: "float | None" = None) -> float | None:
    """Work-normalized dp-sharding efficiency on virtual CPU devices.

    Both runs push the SAME total batch (128) through the SAME host cores —
    once unsharded on 1 virtual device, once dp-sharded over 8 — so host
    core contention cancels and the ratio isolates what sharding itself
    costs (partitioning + collectives).  ~1.0 = free; this intentionally
    says NOTHING about real multi-chip scaling (that needs ICI), unlike the
    naive 8-dev/1-dev throughput ratio it replaces, which mostly measured
    core oversubscription (r03's misleading 0.107).

    ``mesh8_pps`` seeds the n=8 point when the mesh-scaling leg already
    measured it: that leg's n=8 child is argv/env-identical (batch
    16·8 = 128 on 8 forced devices), so re-spawning it would burn up to
    SCALE_TIMEOUT_S on a byte-for-byte duplicate measurement.
    """
    try:
        per_mode = {8: mesh8_pps} if mesh8_pps else {}
        for n in (1, 8):
            if n in per_mode:
                continue
            proc = _run_child(["--scale", str(n), "--scale-batch", "128"],
                              _cpu_env(n), SCALE_TIMEOUT_S)
            sys.stderr.write(proc.stderr)
            got = _last_json_line(proc.stdout)
            if proc.returncode != 0 or not got:
                _log(f"scale run n={n} failed rc={proc.returncode}")
                return None
            per_mode[n] = got["posts_per_sec"]
        return per_mode[8] / per_mode[1]
    except Exception as exc:  # noqa: BLE001 — scaling row is best-effort
        _log(f"dp scaling skipped: {exc}")
        return None


def _mesh_scaling_rows() -> dict:
    """The BASELINE north-star trajectory: posts/sec at mesh sizes
    1/2/4/8 (``posts_per_s_mesh{1,2,4,8}`` rows).

    Each point is its own child on n forced virtual CPU devices — the
    same dp mesh construction + param/batch sharding a mesh-configured
    tpu-worker serves with — sized down like every CPU leg (the --scale
    child's two-point bf16 fit, batch 16·n so per-chip work stays
    constant across points).  On a real v5e slice the curve IS the
    headline metric; on CPU the virtual devices share host cores, so
    these rows carry the trajectory and prove the sharding machinery,
    never a scaling claim (``mesh_platform`` labels which).
    Guaranteed-JSON: a failed point degrades to None, never a crash.
    """
    out: dict = {"mesh_platform": "cpu_virtual"}
    for n in (1, 2, 4, 8):
        key = f"posts_per_s_mesh{n}"
        try:
            got, err = _try_child(
                ["--scale", str(n), "--scale-batch", str(16 * n)],
                _cpu_env(n), MESH_TIMEOUT_S)
        except Exception as exc:  # noqa: BLE001 — guaranteed-JSON leg
            got, err = None, f"{type(exc).__name__}: {exc}"
        if got is None or "posts_per_sec" not in got:
            _log(f"mesh scaling point n={n} skipped: {err}")
            out[key] = None
        else:
            out[key] = round(got["posts_per_sec"], 1)
            _log(f"mesh scaling n={n}: {out[key]} posts/sec")
    if out.get("posts_per_s_mesh1") and out.get("posts_per_s_mesh8"):
        out["mesh_scaling_8x"] = round(
            out["posts_per_s_mesh8"] / out["posts_per_s_mesh1"], 3)
    else:
        out["mesh_scaling_8x"] = None
    return out


def _try_child(argv: list, env: dict, timeout: int):
    """Run a child; return (result_dict_or_None, error_str_or_None)."""
    try:
        proc = _run_child(argv, env, timeout)
        sys.stderr.write(proc.stderr)
        got = _last_json_line(proc.stdout)
        if proc.returncode != 0 or got is None:
            tail = "\n".join(proc.stderr.strip().splitlines()[-8:])
            return None, f"child rc={proc.returncode}: {tail[-1500:]}"
        return got, None
    except subprocess.TimeoutExpired as exc:
        tail = ""
        if exc.stderr:
            s = exc.stderr if isinstance(exc.stderr, str) else \
                exc.stderr.decode("utf-8", "replace")
            tail = "\n".join(s.strip().splitlines()[-8:])
        return None, f"timeout after {timeout}s: {tail[-1500:]}"
    except Exception as exc:  # noqa: BLE001 — must still emit JSON
        return None, f"{type(exc).__name__}: {exc}"


def main() -> None:
    """Child modes dispatch directly (their rc is the parent's signal);
    the parent path runs under a catch-all so `python bench.py` NEVER
    exits non-zero without a parseable JSON last line (BENCH_r01 died
    rc=1 with `parsed: null` when the tunneled backend wedged between a
    passing probe and a parent-side jax touch)."""
    if any(f in sys.argv for f in ("--child", "--asr", "--scale",
                                   "--xlmr", "--moe", "--probe",
                                   "--cluster-bench")):
        _child_main()
        return
    try:
        _parent()
    except BaseException as exc:
        if isinstance(exc, (SystemExit, KeyboardInterrupt)):
            raise
        import traceback

        _log("parent measurement crashed:\n"
             + "".join(traceback.format_exc())[-1500:])
        diag = f"parent crashed: {type(exc).__name__}: {exc}"
        # The probe passed but the backend (or anything else) blew up in
        # THIS process mid-measure: re-run the sized-down measurement in
        # a guaranteed-CPU child and still emit one parseable line.
        result, cerr = _try_child(["--child", "--fast"], _cpu_env(1),
                                  CPU_FALLBACK_TIMEOUT_S)
        if result is not None:
            result["platform"] = "cpu"
            result["mfu"] = None
            result["wedge_diagnostic"] = diag
            try:
                result.update(_measure_cost_model())
            except Exception as row_exc:  # noqa: BLE001 — best-effort row
                _log(f"cost model row skipped: {row_exc}")
            print(json.dumps(result))
        else:
            print(json.dumps({
                "metric": "embed_classify_posts_per_sec",
                "value": 0.0,
                "unit": "posts/sec",
                "vs_baseline": 0.0,
                "error": f"{diag}; cpu fallback: {cerr}",
            }))


def _child_main() -> None:
    if any(f in sys.argv for f in ("--child", "--asr", "--scale",
                                   "--xlmr", "--moe", "--cluster-bench")):
        # Persistent XLA cache: repeat benches skip the 10-30 s compiles,
        # shrinking each child's time-on-chip (less exposure to the
        # intermittent wedge).  Compile time is excluded from the timing
        # methodology either way, so cached runs measure identically.
        from distributed_crawler_tpu.inference.engine import (
            enable_compilation_cache,
        )

        enable_compilation_cache(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".xla_bench_cache"), min_compile_time_s=5.0)
    if "--child" in sys.argv:
        if "--fast" in sys.argv:
            # CPU-fallback workload: same model, same methodology, smaller
            # batch/iteration counts so the number lands inside the fallback
            # timeout on a laptop-class host.
            print(json.dumps(_measure(batch=64, n_short=2, n_long=6,
                                      latency_samples=5, with_int8=False,
                                      with_serving=False)), flush=True)
        else:
            print(json.dumps(_measure()), flush=True)
        return
    if "--probe" in sys.argv:
        print(json.dumps(_probe()), flush=True)
        return
    if "--asr" in sys.argv:
        if "--asr-tiny" in sys.argv:
            print(json.dumps(_measure_asr_tiny()), flush=True)
        else:
            print(json.dumps(_measure_asr()), flush=True)
        return
    if "--cluster-bench" in sys.argv:
        if "--cluster-tiny" in sys.argv:
            print(json.dumps(_measure_cluster_tiny()), flush=True)
        else:
            print(json.dumps(_measure_cluster()), flush=True)
        return
    if "--xlmr" in sys.argv:
        print(json.dumps(_measure_xlmr_int8()), flush=True)
        return
    if "--moe" in sys.argv:
        print(json.dumps(_measure_moe()), flush=True)
        return
    if "--scale" in sys.argv:
        # dp-scaling rows run on virtual CPU devices — keep them light so
        # the pair of runs fits SCALE_TIMEOUT_S on a laptop-class host.
        n = int(sys.argv[sys.argv.index("--scale") + 1])
        b = (int(sys.argv[sys.argv.index("--scale-batch") + 1])
             if "--scale-batch" in sys.argv else 16 * n)
        # (no int8/serving flags needed: _measure returns right after the
        # bf16 fit when scale_devices is set — the dp row is the only
        # thing a scale child computes)
        print(json.dumps(_measure(scale_devices=n, batch=b,
                                  n_short=1, n_long=5, repeats=1)),
              flush=True)
        return


def _parent() -> None:
    # 1. Pre-flight: is the default backend answering at all?  A wedged TPU
    #    costs PROBE_TIMEOUT_S here instead of the whole child budget; a
    #    failed probe gets ONE retry after a cooldown (the wedge sometimes
    #    clears in under a couple of minutes) before the window is
    #    surrendered to the CPU fallback.
    wedge = None
    for attempt in range(2):
        _log(f"pre-flight probe (timeout {PROBE_TIMEOUT_S}s, "
             f"attempt {attempt + 1}/2)")
        probe, perr = _try_child(["--probe"], dict(os.environ),
                                 PROBE_TIMEOUT_S)
        if probe is not None:
            wedge = None
            _log(f"probe ok: {probe['platform']} ({probe['device_kind']}) "
                 f"in {probe['probe_s']}s")
            break
        wedge = f"backend probe failed: {perr}"
        _log(wedge)
        if attempt == 0:
            _log(f"cooling down {PROBE_RETRY_COOLDOWN_S}s before "
                 f"probe retry")
            time.sleep(PROBE_RETRY_COOLDOWN_S)

    # 2. Headline measurement: real backend when the probe passed, else a
    #    CPU-labelled fallback so the line still carries a real number.
    #    A probe that answered but is NOT a TPU (JAX_PLATFORMS=cpu runs,
    #    hosts without the tunnel) goes straight to the sized-down CPU
    #    measurement: the full-size child exists to amortize a real
    #    chip's compiles, and on a CPU host it only burns the timeout
    #    budget before falling back to the same number.
    result = None
    err = None
    if wedge is None and probe.get("platform") == "tpu":
        _log(f"spawning measurement child (timeout {CHILD_TIMEOUT_S}s)")
        result, err = _try_child(["--child"], dict(os.environ),
                                 CHILD_TIMEOUT_S)
    elif wedge is None:
        _log(f"default backend is {probe.get('platform')!r} — running "
             f"the sized-down CPU measurement directly")
    if result is None:
        _log(f"falling back to CPU measurement "
             f"(timeout {CPU_FALLBACK_TIMEOUT_S}s)")
        for attempt in range(2):  # noisy-host timing can abort one run
            result, cerr = _try_child(["--child", "--fast"], _cpu_env(1),
                                      CPU_FALLBACK_TIMEOUT_S)
            if result is not None:
                break
            _log(f"cpu fallback attempt {attempt + 1} failed: "
                 f"{(cerr or '')[-200:]}")
        if result is not None:
            result["platform"] = "cpu"
            result["mfu"] = None
            if wedge or err:
                result["wedge_diagnostic"] = wedge or err
            cached = _load_tpu_cache()
            if cached is not None:
                # A prior successful TPU run from this environment; the
                # live headline above stays the CPU fallback.
                result["last_measured_tpu"] = cached
        else:
            err = f"{wedge or err}; cpu fallback: {cerr}"

    if result is None:
        print(json.dumps({
            "metric": "embed_classify_posts_per_sec",
            "value": 0.0,
            "unit": "posts/sec",
            "vs_baseline": 0.0,
            "error": err or "unknown failure",
        }))
        return

    if result.get("platform") == "tpu":
        # BASELINE config #4 row — TPU only (whisper-small greedy decode on
        # a CPU host would blow the fallback budget for no signal).
        _log(f"measuring ASR row (timeout {ASR_TIMEOUT_S}s)")
        asr, aerr = _try_child(["--asr"], dict(os.environ), ASR_TIMEOUT_S)
        if asr is not None:
            result.update(asr)
        else:
            _log(f"asr row skipped: {aerr}")
        # BASELINE config #3 width: int8-vs-bf16 at XLM-R-base (VERDICT
        # r03 #1's done-criterion) — own child, own budget.
        _log(f"measuring XLM-R int8 row (timeout {XLMR_TIMEOUT_S}s)")
        xlmr, xerr = _try_child(["--xlmr"], dict(os.environ),
                                XLMR_TIMEOUT_S)
        if xlmr is not None:
            result.update(xlmr)
        else:
            _log(f"xlmr row skipped: {xerr}")
        # Switch-MoE dispatch row (dense vs capacity at XLM-R width, E=8):
        # own child, own budget (VERDICT r04 missing #5).
        _log(f"measuring MoE dispatch row (timeout {MOE_TIMEOUT_S}s)")
        moe, merr = _try_child(["--moe"], dict(os.environ), MOE_TIMEOUT_S)
        if moe is not None:
            result.update(moe)
        else:
            _log(f"moe row skipped: {merr}")
        # BASELINE config #5 row: streaming-clustering step throughput at
        # serving width (k=256, 1024-dim embeddings) — own child, own
        # budget.
        _log(f"measuring clustering row (timeout {CLUSTER_TIMEOUT_S}s)")
        clus, cerr2 = _try_child(["--cluster-bench"], dict(os.environ),
                                 CLUSTER_TIMEOUT_S)
        if clus is not None:
            result.update(clus)
        else:
            _log(f"cluster row skipped: {cerr2}")

    _cache_tpu_result(result)
    if "asr_rtfx" not in result:
        # The ASR leg missed its window (wedge mid-run, or CPU fallback):
        # surface the last REAL TPU ASR measurement, clearly labelled.
        cached = _load_tpu_cache() or {}
        if "asr_rtfx" in cached:
            for k in ("asr_rtfx", "asr_windows_per_s",
                      "asr_decode_tokens_per_sec", "asr_batch",
                      "asr_decode_len", "asr_model", "asr_window_s"):
                if k in cached:
                    result[k] = cached[k]
            result["asr_from_cache_measured_at"] = cached.get(
                "asr_measured_at", cached.get("measured_at"))
    if "asr_rtfx" not in result:
        # Still no ASR row (no cache yet, or it predates the leg): run
        # the sized-down tiny-config leg on CPU so BENCH json tracks the
        # ASR workload from this PR onward — guaranteed-JSON like every
        # other leg (a failed child just logs and skips the row).
        _log(f"measuring tiny-ASR CPU row (timeout {ASR_TINY_TIMEOUT_S}s)")
        asr, aerr = _try_child(["--asr", "--asr-tiny"], _cpu_env(1),
                               ASR_TINY_TIMEOUT_S)
        if asr is not None:
            result.update(asr)
        else:
            _log(f"tiny asr row skipped: {aerr}")
    if "cluster_assign_vectors_per_s" not in result:
        # The clustering leg missed its window (wedge mid-run, or CPU
        # fallback): surface the last REAL TPU measurement first …
        cached = _load_tpu_cache() or {}
        if "cluster_assign_vectors_per_s" in cached:
            for k in ("cluster_assign_vectors_per_s", "cluster_step_ms",
                      "cluster_k", "cluster_dim", "cluster_rows",
                      "cluster_model"):
                if k in cached:
                    result[k] = cached[k]
            result["cluster_from_cache_measured_at"] = cached.get(
                "cluster_measured_at", cached.get("measured_at"))
    if "cluster_assign_vectors_per_s" not in result:
        # … else the sized-down tiny leg on CPU, so BENCH json tracks
        # the clustering workload from this PR onward — guaranteed-JSON
        # like every other leg (a failed child logs and skips the row).
        _log(f"measuring tiny-cluster CPU row "
             f"(timeout {CLUSTER_TINY_TIMEOUT_S}s)")
        clus, cerr3 = _try_child(["--cluster-bench", "--cluster-tiny"],
                                 _cpu_env(1), CLUSTER_TINY_TIMEOUT_S)
        if clus is not None:
            result.update(clus)
        else:
            _log(f"tiny cluster row skipped: {cerr3}")
            result.setdefault("cluster_assign_vectors_per_s", None)
            result.setdefault("cluster_step_ms", None)
    if "xlmr_base_posts_per_sec" not in result:
        cached = _load_tpu_cache() or {}
        if "xlmr_base_posts_per_sec" in cached:
            for k in ("xlmr_base_posts_per_sec",
                      "xlmr_base_int8_posts_per_sec",
                      "xlmr_base_int8_speedup",
                      "xlmr_base_int8_static_posts_per_sec",
                      "xlmr_base_int8_static_speedup", "xlmr_batch"):
                if k in cached:
                    result[k] = cached[k]
            result["xlmr_from_cache_measured_at"] = cached.get(
                "xlmr_measured_at", cached.get("measured_at"))
            if "xlmr_base_int8_static_posts_per_sec" in cached:
                result["xlmr_static_from_cache_measured_at"] = cached.get(
                    "xlmr_static_measured_at",
                    result["xlmr_from_cache_measured_at"])
    if "moe_capacity_posts_per_sec" not in result:
        cached = _load_tpu_cache() or {}
        if "moe_capacity_posts_per_sec" in cached:
            for k in ("moe_dense_posts_per_sec",
                      "moe_capacity_posts_per_sec", "moe_capacity_speedup",
                      "moe_experts", "moe_capacity_factor", "moe_batch"):
                if k in cached:
                    result[k] = cached[k]
            result["moe_from_cache_measured_at"] = cached.get(
                "moe_measured_at", cached.get("measured_at"))
    # Host-side rows (CPU-only by nature, measured every run): the
    # cost-model bucket FLOPs, the distributed-path codec ceiling, and
    # the text-in tokenize rate.
    try:
        result.update(_measure_cost_model())
    except Exception as exc:  # noqa: BLE001 — best-effort row
        _log(f"cost model row skipped: {exc}")
    try:
        result.update(_measure_bus_codec())
    except Exception as exc:  # noqa: BLE001 — best-effort row
        _log(f"bus codec row skipped: {exc}")
    _log("measuring partitioned-bus throughput (1/2/4 broker shards)")
    try:
        result.update(_measure_bus_shards())
    except Exception as exc:  # noqa: BLE001 — best-effort rows
        _log(f"bus shard rows skipped: {exc}")
        # skip→None for every row the leg owns: schema-stable JSON even
        # when the whole leg fails.
        for n in BUS_SHARD_COUNTS:
            result.setdefault(f"bus_frames_per_s_shards{n}", None)
        for key in ("bus_shard_scaling_4x", "bus_shard_concurrent_scaling",
                    "bus_shard_frames", "bus_shard_host_cores"):
            result.setdefault(key, None)
    try:
        result.update(_measure_tokenizer())
    except Exception as exc:  # noqa: BLE001 — best-effort row
        _log(f"tokenizer row skipped: {exc}")
    try:
        result.update(_measure_padding_efficiency())
    except Exception as exc:  # noqa: BLE001 — best-effort row
        _log(f"padding efficiency row skipped: {exc}")
    _log("measuring mesh scaling curve (1/2/4/8 virtual devices)")
    try:
        result.update(_mesh_scaling_rows())
    except Exception as exc:  # noqa: BLE001 — best-effort rows
        _log(f"mesh scaling rows skipped: {exc}")
        # skip→None for EVERY row the leg owns: schema-stable JSON even
        # when the whole leg (not just one child) fails.
        result.setdefault("mesh_platform", None)
        for n in (1, 2, 4, 8):
            result.setdefault(f"posts_per_s_mesh{n}", None)
        result.setdefault("mesh_scaling_8x", None)
    _log("measuring dp sharding overhead on virtual CPU mesh")
    eff = _dp_sharding_overhead(mesh8_pps=result.get("posts_per_s_mesh8"))
    # Work-normalized (same batch, same host cores, 1 vs 8 virtual CPU
    # devices): isolates dp-sharding overhead; deliberately NOT a claim
    # about multi-chip scaling, which needs real ICI.
    result["dp_sharding_efficiency_same_host_work_normalized"] = (
        round(eff, 3) if eff is not None else None)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
