// dct native network layer: the transport seam of the TDLib-class client.
//
// The reference linked TDLib, whose MTProto stack owns sockets/TLS
// (Dockerfile.tdlib builds it from source).  This build's equivalent is a
// pluggable connection layer speaking the DCT wire protocol v1:
//
//     frame := uint32 big-endian payload length || payload (UTF-8 JSON)
//
// over either a plain TCP stream or a TLS 1.2/1.3 stream (OpenSSL) whose
// ClientHello is shaped like Chrome's — Chrome's TLS 1.2 cipher ordering,
// Chrome's TLS 1.3 suite ordering, X25519-first groups, ALPN h2+http/1.1,
// SNI — the same blend-into-browser-traffic property the reference got
// from uTLS (`telegramhelper/utlstransport.go:19-57`).  (Deltas from a
// byte-exact Chrome JA3: no GREASE values and no extension-order
// permutation — OpenSSL 3.0 exposes neither.)
//
// Threading contract: one writer thread and one reader thread may use a
// Connection concurrently; shutdown() unblocks a reader stuck in recv.

#ifndef DCT_NATIVE_NET_H_
#define DCT_NATIVE_NET_H_

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>

namespace dctnet {

// ---------------------------------------------------------------------------
// OpenSSL via dlopen: the build image ships libssl.so.3 but no dev headers,
// so the ~20 functions used here are declared against OpenSSL 3's stable
// ABI and resolved at first use.  A missing libssl degrades to a clear
// runtime error on TLS connects only; plain TCP never touches this.
// ---------------------------------------------------------------------------

struct OpenSsl {
  // libssl
  const void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(const void*);
  void (*SSL_CTX_free)(void*);
  int (*SSL_CTX_set_cipher_list)(void*, const char*);
  int (*SSL_CTX_set_ciphersuites)(void*, const char*);
  long (*SSL_CTX_ctrl)(void*, int, long, void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  int (*SSL_CTX_set_alpn_protos)(void*, const unsigned char*, unsigned);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  long (*SSL_ctrl)(void*, int, long, void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_get_error)(const void*, int);
  int (*SSL_pending)(const void*);
  void (*SSL_get0_alpn_selected)(const void*, const unsigned char**,
                                 unsigned*);
  void* (*SSL_get0_param)(void*);
  // libcrypto
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);
  int (*X509_VERIFY_PARAM_set1_host)(void*, const char*, size_t);

  // OpenSSL 3 ABI constants (ssl.h values; stable across 3.x).
  static constexpr int kCtrlSetMinProtoVersion = 123;
  static constexpr int kCtrlSetGroupsList = 92;
  static constexpr int kCtrlSetTlsextHostname = 55;
  static constexpr int kTlsextNametypeHostName = 0;
  static constexpr long kTls12Version = 0x0303;
  static constexpr int kVerifyNone = 0x00;
  static constexpr int kVerifyPeer = 0x01;
  static constexpr int kErrorZeroReturn = 6;
  static constexpr int kErrorSyscall = 5;

  static OpenSsl& get() {
    static OpenSsl instance;
    return instance;
  }

  bool ok() const { return err_.empty(); }
  const std::string& error() const { return err_; }

 private:
  OpenSsl() {
    void* ssl = nullptr;
    for (const char* name : {"libssl.so.3", "libssl.so"}) {
      ssl = ::dlopen(name, RTLD_NOW | RTLD_GLOBAL);
      if (ssl) break;
    }
    void* crypto = nullptr;
    for (const char* name : {"libcrypto.so.3", "libcrypto.so"}) {
      crypto = ::dlopen(name, RTLD_NOW | RTLD_GLOBAL);
      if (crypto) break;
    }
    if (!ssl || !crypto) {
      err_ = "libssl/libcrypto not found for TLS transport";
      return;
    }
    auto need = [this](void* lib, const char* sym) -> void* {
      void* fn = ::dlsym(lib, sym);
      if (!fn && err_.empty())
        err_ = std::string("missing OpenSSL symbol: ") + sym;
      return fn;
    };
#define DCT_SYM(lib, name) \
  name = reinterpret_cast<decltype(name)>(need(lib, #name))
    DCT_SYM(ssl, TLS_client_method);
    DCT_SYM(ssl, SSL_CTX_new);
    DCT_SYM(ssl, SSL_CTX_free);
    DCT_SYM(ssl, SSL_CTX_set_cipher_list);
    DCT_SYM(ssl, SSL_CTX_set_ciphersuites);
    DCT_SYM(ssl, SSL_CTX_ctrl);
    DCT_SYM(ssl, SSL_CTX_set_verify);
    DCT_SYM(ssl, SSL_CTX_set_default_verify_paths);
    DCT_SYM(ssl, SSL_CTX_set_alpn_protos);
    DCT_SYM(ssl, SSL_new);
    DCT_SYM(ssl, SSL_free);
    DCT_SYM(ssl, SSL_ctrl);
    DCT_SYM(ssl, SSL_set_fd);
    DCT_SYM(ssl, SSL_connect);
    DCT_SYM(ssl, SSL_read);
    DCT_SYM(ssl, SSL_write);
    DCT_SYM(ssl, SSL_get_error);
    DCT_SYM(ssl, SSL_pending);
    DCT_SYM(ssl, SSL_get0_alpn_selected);
    DCT_SYM(ssl, SSL_get0_param);
    DCT_SYM(crypto, ERR_get_error);
    DCT_SYM(crypto, ERR_error_string_n);
    DCT_SYM(crypto, X509_VERIFY_PARAM_set1_host);
#undef DCT_SYM
  }

  std::string err_;
};

// Chrome's TLS 1.2 cipher suite ordering (desktop Chrome, stable channel).
inline const char* kChromeTls12Ciphers =
    "ECDHE-ECDSA-AES128-GCM-SHA256:ECDHE-RSA-AES128-GCM-SHA256:"
    "ECDHE-ECDSA-AES256-GCM-SHA384:ECDHE-RSA-AES256-GCM-SHA384:"
    "ECDHE-ECDSA-CHACHA20-POLY1305:ECDHE-RSA-CHACHA20-POLY1305:"
    "ECDHE-RSA-AES128-SHA:ECDHE-RSA-AES256-SHA:"
    "AES128-GCM-SHA256:AES256-GCM-SHA384:AES128-SHA:AES256-SHA";

// Chrome's TLS 1.3 suite ordering (OpenSSL default puts AES-256 first).
inline const char* kChromeTls13Suites =
    "TLS_AES_128_GCM_SHA256:TLS_AES_256_GCM_SHA384:"
    "TLS_CHACHA20_POLY1305_SHA256";

inline const char* kChromeGroups = "X25519:P-256:P-384";

class NetError : public std::runtime_error {
 public:
  explicit NetError(const std::string& what) : std::runtime_error(what) {}
};

class Stream {
 public:
  virtual ~Stream() = default;
  // Read up to `len` bytes; returns 0 on orderly EOF, throws on error.
  virtual size_t read_some(char* buf, size_t len) = 0;
  virtual void write_all(const char* buf, size_t len) = 0;
  virtual void shutdown() = 0;  // unblock any reader; idempotent
  // True when read_some would make progress.  Readers MUST gate blocking
  // reads on this: TlsStream serializes SSL_read/SSL_write with a mutex
  // (OpenSSL forbids concurrent use of one SSL*), so a reader parked
  // inside a blocking SSL_read would deadlock every writer.
  virtual bool wait_readable(int timeout_ms) = 0;
};

inline bool poll_readable(int fd, int timeout_ms) {
  struct pollfd pfd;
  pfd.fd = fd;
  pfd.events = POLLIN;
  pfd.revents = 0;
  for (;;) {
    int rc = ::poll(&pfd, 1, timeout_ms);
    if (rc < 0 && errno == EINTR) continue;
    return rc > 0;
  }
}

inline int tcp_connect(const std::string& host, int port) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_s = std::to_string(port);
  int rc = ::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res);
  if (rc != 0)
    throw NetError("resolve " + host + ": " + gai_strerror(rc));
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd < 0)
    throw NetError("connect " + host + ":" + port_s + " failed");
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
  return fd;
}

class TcpStream : public Stream {
 public:
  TcpStream(const std::string& host, int port)
      : fd_(tcp_connect(host, port)) {}

  ~TcpStream() override {
    shutdown();
    if (fd_ >= 0) ::close(fd_);
  }

  size_t read_some(char* buf, size_t len) override {
    for (;;) {
      ssize_t n = ::recv(fd_, buf, len, 0);
      if (n >= 0) return static_cast<size_t>(n);
      if (errno == EINTR) continue;
      throw NetError(std::string("recv: ") + std::strerror(errno));
    }
  }

  void write_all(const char* buf, size_t len) override {
    size_t off = 0;
    while (off < len) {
      ssize_t n = ::send(fd_, buf + off, len - off, 0);
      if (n < 0) {
        if (errno == EINTR) continue;
        throw NetError(std::string("send: ") + std::strerror(errno));
      }
      off += static_cast<size_t>(n);
    }
  }

  void shutdown() override {
    if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
  }

  bool wait_readable(int timeout_ms) override {
    return poll_readable(fd_, timeout_ms);
  }

  int fd() const { return fd_; }

 private:
  int fd_;
};

// TLS client stream with the Chrome-shaped ClientHello parameters above.
class TlsStream : public Stream {
 public:
  // `http11_only` narrows ALPN to http/1.1 for the native HTTP fetch path
  // (we do not speak h2); the wire-protocol client keeps Chrome's full
  // h2+http/1.1 advertisement.
  TlsStream(const std::string& host, int port, const std::string& sni,
            bool insecure, bool http11_only = false)
      : api_(OpenSsl::get()) {
    if (!api_.ok()) throw NetError(api_.error());
    fd_ = tcp_connect(host, port);
    ctx_ = api_.SSL_CTX_new(api_.TLS_client_method());
    if (!ctx_) {
      ::close(fd_);
      throw NetError("SSL_CTX_new failed");
    }
    api_.SSL_CTX_ctrl(ctx_, OpenSsl::kCtrlSetMinProtoVersion,
                      OpenSsl::kTls12Version, nullptr);
    api_.SSL_CTX_set_cipher_list(ctx_, kChromeTls12Ciphers);
    api_.SSL_CTX_set_ciphersuites(ctx_, kChromeTls13Suites);
    api_.SSL_CTX_ctrl(ctx_, OpenSsl::kCtrlSetGroupsList, 0,
                      const_cast<char*>(kChromeGroups));
    api_.SSL_CTX_set_verify(
        ctx_, insecure ? OpenSsl::kVerifyNone : OpenSsl::kVerifyPeer,
        nullptr);
    if (!insecure) api_.SSL_CTX_set_default_verify_paths(ctx_);
    static const unsigned char alpn_full[] = {2, 'h', '2',
                                              8, 'h', 't', 't', 'p', '/',
                                              '1', '.', '1'};
    static const unsigned char alpn_h1[] = {8, 'h', 't', 't', 'p', '/',
                                            '1', '.', '1'};
    if (http11_only)
      api_.SSL_CTX_set_alpn_protos(ctx_, alpn_h1, sizeof(alpn_h1));
    else
      api_.SSL_CTX_set_alpn_protos(ctx_, alpn_full, sizeof(alpn_full));

    ssl_ = api_.SSL_new(ctx_);
    if (!ssl_) {
      cleanup();
      throw NetError("SSL_new failed");
    }
    const std::string& name = sni.empty() ? host : sni;
    api_.SSL_ctrl(ssl_, OpenSsl::kCtrlSetTlsextHostname,
                  OpenSsl::kTlsextNametypeHostName,
                  const_cast<char*>(name.c_str()));
    if (!insecure) {
      void* param = api_.SSL_get0_param(ssl_);
      api_.X509_VERIFY_PARAM_set1_host(param, name.c_str(), 0);
    }
    api_.SSL_set_fd(ssl_, fd_);
    if (api_.SSL_connect(ssl_) != 1) {
      char buf[256];
      api_.ERR_error_string_n(api_.ERR_get_error(), buf, sizeof(buf));
      cleanup();
      throw NetError(std::string("TLS handshake failed: ") + buf);
    }
  }

  ~TlsStream() override {
    shutdown();
    cleanup();
  }

  size_t read_some(char* buf, size_t len) override {
    std::lock_guard<std::mutex> lock(ssl_mu_);
    int n = api_.SSL_read(ssl_, buf, static_cast<int>(len));
    if (n > 0) return static_cast<size_t>(n);
    int err = api_.SSL_get_error(ssl_, n);
    if (err == OpenSsl::kErrorZeroReturn || err == OpenSsl::kErrorSyscall)
      return 0;
    throw NetError("SSL_read error " + std::to_string(err));
  }

  void write_all(const char* buf, size_t len) override {
    std::lock_guard<std::mutex> lock(ssl_mu_);
    size_t off = 0;
    while (off < len) {
      int n = api_.SSL_write(ssl_, buf + off,
                             static_cast<int>(len - off));
      if (n <= 0)
        throw NetError("SSL_write error " +
                       std::to_string(api_.SSL_get_error(ssl_, n)));
      off += static_cast<size_t>(n);
    }
  }

  void shutdown() override {
    if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
  }

  bool wait_readable(int timeout_ms) override {
    {
      std::lock_guard<std::mutex> lock(ssl_mu_);
      if (ssl_ && api_.SSL_pending(ssl_) > 0) return true;
    }
    return poll_readable(fd_, timeout_ms);
  }

  std::string alpn_selected() const {
    const unsigned char* data = nullptr;
    unsigned int len = 0;
    api_.SSL_get0_alpn_selected(ssl_, &data, &len);
    return data ? std::string(reinterpret_cast<const char*>(data), len)
                : std::string();
  }

 private:
  void cleanup() {
    if (ssl_) {
      api_.SSL_free(ssl_);
      ssl_ = nullptr;
    }
    if (ctx_) {
      api_.SSL_CTX_free(ctx_);
      ctx_ = nullptr;
    }
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

  OpenSsl& api_;
  int fd_ = -1;
  void* ctx_ = nullptr;
  void* ssl_ = nullptr;
  std::mutex ssl_mu_;  // SSL objects are not thread-safe for r/w overlap
};

// Length-prefixed JSON frames over a Stream.
class Connection {
 public:
  static constexpr size_t kMaxFrame = 64 * 1024 * 1024;

  explicit Connection(std::unique_ptr<Stream> stream)
      : stream_(std::move(stream)) {}

  void send_frame(const std::string& payload) {
    if (payload.size() > kMaxFrame) throw NetError("frame too large");
    char header[4];
    const uint32_t n = static_cast<uint32_t>(payload.size());
    header[0] = static_cast<char>((n >> 24) & 0xff);
    header[1] = static_cast<char>((n >> 16) & 0xff);
    header[2] = static_cast<char>((n >> 8) & 0xff);
    header[3] = static_cast<char>(n & 0xff);
    std::lock_guard<std::mutex> lock(write_mu_);
    stream_->write_all(header, 4);
    stream_->write_all(payload.data(), payload.size());
  }

  // Blocking read of one frame; empty string on orderly close.
  std::string recv_frame() {
    char header[4];
    if (!read_exact(header, 4)) return std::string();
    const uint32_t n = (static_cast<uint32_t>(
                            static_cast<unsigned char>(header[0])) << 24) |
                       (static_cast<uint32_t>(
                            static_cast<unsigned char>(header[1])) << 16) |
                       (static_cast<uint32_t>(
                            static_cast<unsigned char>(header[2])) << 8) |
                       static_cast<uint32_t>(
                           static_cast<unsigned char>(header[3]));
    if (n > kMaxFrame) throw NetError("oversized frame");
    std::string payload(n, '\0');
    if (n > 0 && !read_exact(&payload[0], n))
      throw NetError("truncated frame");
    return payload;
  }

  void shutdown() { stream_->shutdown(); }

  bool wait_readable(int timeout_ms) {
    return stream_->wait_readable(timeout_ms);
  }

 private:
  bool read_exact(char* buf, size_t len) {
    size_t off = 0;
    while (off < len) {
      size_t n = stream_->read_some(buf + off, len - off);
      if (n == 0) return false;  // EOF
      off += n;
    }
    return true;
  }

  std::unique_ptr<Stream> stream_;
  std::mutex write_mu_;
};

}  // namespace dctnet

#endif  // DCT_NATIVE_NET_H_
