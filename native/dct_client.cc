// dct native Telegram-class client core.
//
// The reference's one native component is TDLib (C++, built in
// Dockerfile.tdlib, linked via cgo; Go binding zelenin/go-tdlib).  This is
// the TPU build's equivalent native boundary: a C++ client engine exposing
// TDLib's td_json_client-style C ABI —
//
//   void*  dct_client_create(const char* config_json);
//   void   dct_client_send(void* client, const char* request_json);
//   const char* dct_client_receive(void* client, double timeout_s);
//   const char* dct_client_execute(void* client, const char* request_json);
//   void   dct_client_destroy(void* client);
//
// Requests carry "@type" (the 16 methods of crawler.TDLibClient,
// crawler/crawler.go:109-126) and an optional "@extra" echoed on the
// response for correlation, exactly like TDLib.  Internally: an actor-style
// worker thread drains a request queue and posts responses/updates to a
// response queue (receive() blocks with a timeout); a chat/message store
// (the client database) loads from a JSON seed file — the analog of the
// reference's pre-seeded TDLib DB tarballs (telegramhelper/client.go:232-260)
// — and a file manager materializes downloads on the local filesystem.
// The network backend is pluggable at the store layer; this build ships the
// offline store (no egress in the build environment) with the ABI shaped so
// an MTProto transport can replace it without touching the Python side.
//
// Error model matches the crawl engine's taxonomy: {"@type":"error",
// "code":400,"message":"USERNAME_NOT_OCCUPIED"} for missing channels,
// FLOOD_WAIT via {"code":429,"message":"Too Many Requests: retry after N"}
// injectable per method through the seed config ("flood_wait" rules).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "json.h"
#include "mtproto.h"
#include "net.h"
#include "tl_api.h"

using dctjson::Array;
using dctjson::Object;
using dctjson::Value;

namespace {

// ---------------------------------------------------------------------------
// Wire connections: DCT-v1 JSON frames, or MTProto 2.0 (mtproto.h) — the
// reference's TDLib↔DC protocol.  One interface so the client core doesn't
// care which envelope its JSON rides in.
// ---------------------------------------------------------------------------

struct WireConn {
  virtual ~WireConn() = default;
  virtual void send_frame(const std::string& payload) = 0;
  virtual std::string recv_frame() = 0;
  virtual void shutdown() = 0;
  virtual bool wait_readable(int timeout_ms) = 0;
};

struct DctWire : WireConn {
  explicit DctWire(std::unique_ptr<dctnet::Stream> stream)
      : conn(std::move(stream)) {}
  void send_frame(const std::string& p) override { conn.send_frame(p); }
  std::string recv_frame() override { return conn.recv_frame(); }
  void shutdown() override { conn.shutdown(); }
  bool wait_readable(int ms) override { return conn.wait_readable(ms); }
  dctnet::Connection conn;
};

// MTProto wire with the TL API layer (native/tl_api.h): JSON requests are
// serialized as TL constructor frames (typed for the hot crawl RPCs,
// dct.rawRequest for the tail), @extra stays CLIENT-LOCAL — correlation
// rides rpc_result's req_msg_id exactly as in real MTProto, and this
// adapter reattaches the stored @extra when the result returns.
struct MtprotoWire : WireConn {
  MtprotoWire(std::unique_ptr<dctnet::Stream> stream,
              std::vector<dctmtp::RsaPub> keys)
      : conn(std::move(stream), std::move(keys)) {}

  void send_frame(const std::string& p) override {
    Value req = dctjson::parse(p);
    std::string extra;
    const Value& ev = req.get("@extra");
    if (!ev.is_null()) {
      extra = ev.as_string();
      req.obj().erase("@extra");
    }
    dctmtp::Bytes payload = dcttl::serialize_request(req);
    // The extra must be registered under the SAME lock window as the
    // send: two racing senders must not cross-file their msg_ids.
    std::lock_guard<std::mutex> lock(extra_mu_);
    int64_t msg_id = conn.send_payload(payload);
    if (!extra.empty()) {
      extra_by_msg_id_[msg_id] = extra;
      if (extra_by_msg_id_.size() > 4096)  // dropped-request hygiene
        extra_by_msg_id_.erase(extra_by_msg_id_.begin());
    }
  }

  std::string recv_frame() override {
    dctmtp::Bytes payload = conn.recv_payload();
    if (payload.empty()) return std::string();
    bool has_req = false;
    int64_t req_msg_id = 0;
    Value obj = dcttl::deserialize_frame(payload, &has_req, &req_msg_id);
    if (has_req) {
      std::lock_guard<std::mutex> lock(extra_mu_);
      auto it = extra_by_msg_id_.find(req_msg_id);
      if (it != extra_by_msg_id_.end()) {
        obj.obj()["@extra"] = Value(it->second);
        extra_by_msg_id_.erase(it);
      }
    }
    return dctjson::dump(obj);
  }

  void shutdown() override { conn.shutdown(); }
  bool wait_readable(int ms) override { return conn.wait_readable(ms); }

  dctmtp::MtprotoConnection conn;
  std::mutex extra_mu_;
  std::map<int64_t, std::string> extra_by_msg_id_;
};

// ---------------------------------------------------------------------------
// Store: channels, messages, files (the client database)
// ---------------------------------------------------------------------------

struct StoredMessage {
  int64_t id = 0;
  int64_t chat_id = 0;
  int64_t date = 0;
  Value content;  // tagged content object, passed through verbatim
  int64_t view_count = 0;
  int64_t forward_count = 0;
  int64_t reply_count = 0;
  Object reactions;
  int64_t message_thread_id = 0;
  int64_t reply_to_message_id = 0;
  int64_t sender_id = 0;
  std::string sender_username;
};

struct StoredChannel {
  int64_t chat_id = 0;
  int64_t supergroup_id = 0;
  std::string username;
  std::string title;
  std::string type = "supergroup";
  std::string description;
  int64_t member_count = 0;
  bool is_channel = true;
  bool is_verified = false;
  int64_t date = 0;
  std::string photo_remote_id;
  std::vector<StoredMessage> messages;  // sorted newest-first
  std::map<int64_t, std::vector<StoredMessage>> comments;  // by thread root
};

struct StoredFile {
  int64_t id = 0;
  std::string remote_id;
  std::string local_path;
  int64_t size = 0;
  bool downloaded = false;
};

struct FloodRule {
  std::string method;
  int64_t seconds = 0;
  int64_t remaining = 0;  // fire this many times, then stop
};

class Store {
 public:
  std::map<std::string, StoredChannel> by_username;
  std::map<int64_t, std::string> username_by_chat_id;
  std::map<int64_t, std::string> username_by_supergroup_id;
  std::map<std::string, StoredFile> files_by_remote_id;
  std::map<int64_t, StoredFile> files_by_id;
  std::vector<FloodRule> flood_rules;
  std::string files_dir;
  int64_t me_id = 7700000001;
  std::string me_username = "dct_native_client";
  int64_t next_file_id = 1;

  void load_seed(const std::string& path) {
    std::ifstream in(path);
    if (!in.good()) throw std::runtime_error("cannot open seed db: " + path);
    std::string text((std::istreambuf_iterator<char>(in)),
                     std::istreambuf_iterator<char>());
    load_seed_text(text);
  }

  void load_seed_text(const std::string& text) {
    Value root = dctjson::parse(text);
    int64_t auto_chat_id = 1000;
    for (const auto& ch : root.get("channels").as_array()) {
      StoredChannel c;
      c.username = ch.get("username").as_string();
      c.chat_id = ch.get("id").as_int(++auto_chat_id);
      c.supergroup_id = ch.get("supergroup_id").as_int(c.chat_id + 500000);
      c.title = ch.get("title").as_string().empty()
                    ? c.username
                    : ch.get("title").as_string();
      c.type = ch.get("type").as_string().empty() ? "supergroup"
                                                  : ch.get("type").as_string();
      c.description = ch.get("description").as_string();
      c.member_count = ch.get("member_count").as_int();
      c.is_channel = ch.get("is_channel").is_null()
                         ? true
                         : ch.get("is_channel").as_bool(true);
      c.is_verified = ch.get("is_verified").as_bool(false);
      c.date = ch.get("date").as_int();
      c.photo_remote_id = ch.get("photo_remote_id").as_string();
      int64_t auto_msg_id = 0;
      for (const auto& m : ch.get("messages").as_array()) {
        StoredMessage sm;
        // Public message ids shift by 2^20 (telegramhelper/tdutils.go:1005).
        auto_msg_id += (1 << 20);
        sm.id = m.get("id").as_int(auto_msg_id);
        // Hand-written seeds often number messages 1, 2, 3…; real TDLib
        // channel ids are always n·2^20, and the crawl engine estimates a
        // channel's post count as max_id >> 20 — a raw small id would read
        // as zero posts and deadend the channel.  Normalize into the
        // public form (reply/thread references below get the same shift so
        // intra-seed message links stay consistent).
        if (sm.id > 0 && sm.id < (1 << 20)) sm.id <<= 20;
        sm.chat_id = c.chat_id;
        sm.date = m.get("date").as_int();
        sm.content = m.get("content");
        sm.view_count = m.get("view_count").as_int();
        sm.forward_count = m.get("forward_count").as_int();
        sm.reply_count = m.get("reply_count").as_int();
        sm.reactions = m.get("reactions").as_object();
        sm.message_thread_id = m.get("message_thread_id").as_int();
        sm.reply_to_message_id = m.get("reply_to_message_id").as_int();
        if (sm.message_thread_id > 0 && sm.message_thread_id < (1 << 20))
          sm.message_thread_id <<= 20;
        if (sm.reply_to_message_id > 0 && sm.reply_to_message_id < (1 << 20))
          sm.reply_to_message_id <<= 20;
        sm.sender_id = m.get("sender_id").as_int();
        sm.sender_username = m.get("sender_username").as_string();
        c.messages.push_back(std::move(sm));
      }
      // Newest first, like GetChatHistory returns.
      std::sort(c.messages.begin(), c.messages.end(),
                [](const StoredMessage& a, const StoredMessage& b) {
                  return a.id > b.id;
                });
      username_by_chat_id[c.chat_id] = c.username;
      username_by_supergroup_id[c.supergroup_id] = c.username;
      by_username[c.username] = std::move(c);
    }
    for (const auto& f : root.get("files").as_array()) {
      StoredFile sf;
      sf.remote_id = f.get("remote_id").as_string();
      sf.id = next_file_id++;
      sf.size = f.get("size").as_int();
      sf.local_path = f.get("local_path").as_string();
      files_by_id[sf.id] = sf;
      files_by_remote_id[sf.remote_id] = sf;
    }
    for (const auto& fr : root.get("flood_wait").as_array()) {
      FloodRule rule;
      rule.method = fr.get("method").as_string();
      rule.seconds = fr.get("seconds").as_int();
      rule.remaining = fr.get("count").as_int(1);
      flood_rules.push_back(rule);
    }
    files_dir = root.get("files_dir").as_string();
  }

  // Returns >0 retry-after seconds if this call should FLOOD_WAIT.
  int64_t check_flood(const std::string& method) {
    for (auto& rule : flood_rules) {
      if (rule.method == method && rule.remaining > 0) {
        --rule.remaining;
        return rule.seconds;
      }
    }
    return 0;
  }
};

// ---------------------------------------------------------------------------
// Response/message building
// ---------------------------------------------------------------------------

Value make_error(int64_t code, const std::string& message) {
  Object o;
  o["@type"] = Value("error");
  o["code"] = Value(code);
  o["message"] = Value(message);
  return Value(std::move(o));
}

// Connection-level failure: no @extra can be attached (the error isn't a
// reply to one request), so it carries a marker the binding uses to fail
// ALL in-flight and future calls fast instead of timing out.
Value make_transport_error(const std::string& message) {
  Value v = make_error(500, message);
  v.obj()["transport"] = Value(true);
  return v;
}

Value message_to_json(const StoredMessage& m) {
  Object o;
  o["@type"] = Value("message");
  o["id"] = Value(m.id);
  o["chat_id"] = Value(m.chat_id);
  o["date"] = Value(m.date);
  o["content"] = m.content;
  o["view_count"] = Value(m.view_count);
  o["forward_count"] = Value(m.forward_count);
  o["reply_count"] = Value(m.reply_count);
  o["reactions"] = Value(m.reactions);
  o["message_thread_id"] = Value(m.message_thread_id);
  o["reply_to_message_id"] = Value(m.reply_to_message_id);
  o["sender_id"] = Value(m.sender_id);
  o["sender_username"] = Value(m.sender_username);
  o["is_channel_post"] = Value(true);
  return Value(std::move(o));
}

Value messages_to_json(const std::vector<StoredMessage>& msgs,
                       int64_t total) {
  Object o;
  o["@type"] = Value("messages");
  o["total_count"] = Value(total);
  Array arr;
  for (const auto& m : msgs) arr.push_back(message_to_json(m));
  o["messages"] = Value(std::move(arr));
  return Value(std::move(o));
}

Value chat_to_json(const StoredChannel& c) {
  Object o;
  o["@type"] = Value("chat");
  o["id"] = Value(c.chat_id);
  o["title"] = Value(c.title);
  o["type"] = Value(c.type);
  o["supergroup_id"] = Value(c.type == "supergroup" ? c.supergroup_id : 0);
  o["basic_group_id"] =
      Value(c.type == "basic_group" ? c.supergroup_id : int64_t(0));
  o["photo_remote_id"] = Value(c.photo_remote_id);
  return Value(std::move(o));
}

Value file_to_json(const StoredFile& f) {
  Object o;
  o["@type"] = Value("file");
  o["id"] = Value(f.id);
  o["remote_id"] = Value(f.remote_id);
  o["local_path"] = Value(f.local_path);
  o["size"] = Value(f.size);
  o["downloaded"] = Value(f.downloaded);
  return Value(std::move(o));
}

// ---------------------------------------------------------------------------
// The client engine: request router + actor thread + queues
// ---------------------------------------------------------------------------

class Client {
 public:
  explicit Client(const std::string& config_json) {
    Value cfg = dctjson::parse(
        config_json.empty() ? std::string("{}") : config_json);
    const std::string server_addr = cfg.get("server_addr").as_string();
    if (!server_addr.empty()) {
      // Remote mode: all requests ride the wire protocol to a DC server
      // (the MTProto-transport seam made real; the server owns the store
      // and the auth ladder).
      connect_remote(server_addr, cfg);
      return;
    }
    const std::string seed_path = cfg.get("seed_db").as_string();
    const std::string seed_inline = cfg.get("seed_json").as_string();
    if (!seed_inline.empty()) {
      store_.load_seed_text(seed_inline);
    } else if (!seed_path.empty()) {
      store_.load_seed(seed_path);
    }
    require_auth_ = cfg.get("require_auth").as_bool(false);
    expected_code_ = cfg.get("expected_code").as_string();
    expected_password_ = cfg.get("expected_password").as_string();
    running_ = true;
    worker_ = std::thread([this] { run(); });
    if (require_auth_) {
      // Full TDLib-style auth ladder: WaitTdlibParameters ->
      // WaitPhoneNumber -> WaitCode [-> WaitPassword] -> Ready
      // (telegramhelper/client.go's CLI interactor walks exactly these
      // states; password = the 2FA leg of standalone/runner.go:77-192).
      auth_state_ = AuthState::WaitTdlibParameters;
      push_auth_update("authorizationStateWaitTdlibParameters");
    } else {
      auth_state_ = AuthState::Ready;
      push_auth_update("authorizationStateReady");
    }
  }

  ~Client() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      running_ = false;
      cv_requests_.notify_all();
    }
    reader_stop_.store(true);
    if (conn_) conn_->shutdown();
    if (reader_.joinable()) reader_.join();
    if (worker_.joinable()) worker_.join();
  }

  void send(const std::string& request_json) {
    if (conn_) {
      try {
        conn_->send_frame(request_json);
      } catch (const std::exception& e) {
        push_response(make_transport_error(
            std::string("transport: ") + e.what()));
      }
      return;
    }
    std::lock_guard<std::mutex> lock(mu_);
    requests_.push_back(request_json);
    cv_requests_.notify_one();
  }

  // Blocking receive with timeout; returns empty string on timeout.
  std::string receive(double timeout_s) {
    std::unique_lock<std::mutex> lock(mu_);
    if (!cv_responses_.wait_for(
            lock, std::chrono::duration<double>(timeout_s),
            [this] { return !responses_.empty(); }))
      return std::string();
    std::string out = std::move(responses_.front());
    responses_.pop_front();
    return out;
  }

  // Synchronous execute (no queue round trip) for local-only requests.
  std::string execute(const std::string& request_json) {
    Value req;
    try {
      req = dctjson::parse(request_json);
    } catch (const std::exception& e) {
      return dctjson::dump(make_error(400, e.what()));
    }
    if (conn_)
      return dctjson::dump(make_error(
          400, "execute is local-only; remote clients must use send"));
    Value resp = route(req);
    attach_extra(resp, req);
    return dctjson::dump(resp);
  }

 private:
  enum class AuthState { WaitTdlibParameters, WaitPhoneNumber, WaitCode,
                         WaitPassword, Ready };

  Store store_;
  std::mutex mu_;
  std::condition_variable cv_requests_;
  std::condition_variable cv_responses_;
  std::deque<std::string> requests_;
  std::deque<std::string> responses_;
  bool running_ = false;
  bool require_auth_ = false;
  AuthState auth_state_ = AuthState::Ready;
  std::string expected_code_;
  std::string expected_password_;
  std::string phone_number_;
  std::thread worker_;
  // Remote mode: wire connection + its reader thread.
  std::unique_ptr<WireConn> conn_;
  std::thread reader_;
  std::atomic<bool> reader_stop_{false};

  void connect_remote(const std::string& server_addr, const Value& cfg) {
    auto colon = server_addr.rfind(':');
    if (colon == std::string::npos)
      throw std::runtime_error("server_addr must be host:port");
    const std::string host = server_addr.substr(0, colon);
    const int port = std::stoi(server_addr.substr(colon + 1));
    std::unique_ptr<dctnet::Stream> stream;
    if (cfg.get("tls").as_bool(false)) {
      stream.reset(new dctnet::TlsStream(
          host, port, cfg.get("sni").as_string(),
          cfg.get("tls_insecure").as_bool(false)));
    } else {
      stream.reset(new dctnet::TcpStream(host, port));
    }
    if (cfg.get("wire").as_string() == "mtproto") {
      // MTProto 2.0 envelope (mtproto.h): auth-key handshake on connect,
      // AES-IGE-encrypted messages after — the reference's TDLib↔DC wire.
      // Keys ride in config as a keyring ("server_pubkeys": [{n,e},…]) or
      // a single "server_pubkey" — the same role as the several long-lived
      // DC keys baked into Telegram clients; the handshake selects by the
      // fingerprint the server offers in resPQ.
      auto parse_key = [](const Value& pk) {
        dctmtp::RsaPub key;
        key.n = dctmtp::hex_to_bytes(pk.get("n").as_string());
        int64_t e = pk.get("e").as_int(65537);
        key.e = dctmtp::be_bytes_u64(static_cast<uint64_t>(e));
        return key;
      };
      std::vector<dctmtp::RsaPub> keys;
      const Value& ring = cfg.get("server_pubkeys");
      if (!ring.is_null()) {
        for (const auto& pk : ring.as_array()) keys.push_back(parse_key(pk));
      } else {
        const Value& pk = cfg.get("server_pubkey");
        if (pk.is_null())
          throw std::runtime_error(
              "wire=mtproto needs server_pubkey {n,e} or server_pubkeys");
        keys.push_back(parse_key(pk));
      }
      conn_.reset(new MtprotoWire(std::move(stream), std::move(keys)));
    } else {
      conn_.reset(new DctWire(std::move(stream)));
    }
    Object hello;
    hello["@type"] = Value("handshake");
    hello["transport_version"] = Value(int64_t(1));
    conn_->send_frame(dctjson::dump(Value(std::move(hello))));
    reader_ = std::thread([this] { remote_read_loop(); });
  }

  void remote_read_loop() {
    try {
      for (;;) {
        if (reader_stop_.load()) return;
        if (!conn_->wait_readable(200)) continue;
        std::string frame = conn_->recv_frame();
        if (frame.empty()) break;  // orderly close
        std::lock_guard<std::mutex> lock(mu_);
        responses_.push_back(std::move(frame));
        cv_responses_.notify_one();
      }
    } catch (const std::exception& e) {
      push_response(make_transport_error(
          std::string("connection lost: ") + e.what()));
      return;
    }
    push_response(make_transport_error("connection closed by server"));
  }

  void push_auth_update(const std::string& state) {
    Object upd;
    upd["@type"] = Value("updateAuthorizationState");
    Object st;
    st["@type"] = Value(state);
    upd["authorization_state"] = Value(std::move(st));
    push_response(Value(std::move(upd)));
  }

  void push_response(const Value& v) {
    std::lock_guard<std::mutex> lock(mu_);
    responses_.push_back(dctjson::dump(v));
    cv_responses_.notify_one();
  }

  void run() {
    while (true) {
      std::string request_json;
      {
        std::unique_lock<std::mutex> lock(mu_);
        cv_requests_.wait(
            lock, [this] { return !running_ || !requests_.empty(); });
        if (!running_ && requests_.empty()) return;
        request_json = std::move(requests_.front());
        requests_.pop_front();
      }
      Value req;
      Value resp;
      try {
        req = dctjson::parse(request_json);
        resp = route(req);
      } catch (const std::exception& e) {
        resp = make_error(400, e.what());
      }
      attach_extra(resp, req);
      push_response(resp);
    }
  }

  static void attach_extra(Value& resp, const Value& req) {
    const Value& extra = req.get("@extra");
    if (!extra.is_null() && resp.type() == dctjson::Type::Object)
      resp.obj()["@extra"] = extra;
  }

  StoredChannel* channel_by_chat_id(int64_t chat_id) {
    auto it = store_.username_by_chat_id.find(chat_id);
    if (it == store_.username_by_chat_id.end()) return nullptr;
    return &store_.by_username[it->second];
  }

  Value flood_or_null(const std::string& method) {
    int64_t secs = store_.check_flood(method);
    if (secs > 0)
      return make_error(429,
                        "Too Many Requests: retry after " +
                            std::to_string(secs));
    return Value();
  }

  Value ok_value() {
    Object o;
    o["@type"] = Value("ok");
    return Value(std::move(o));
  }

  // Auth ladder requests, valid only in their matching state.
  Value route_auth(const std::string& type, const Value& req) {
    if (type == "setTdlibParameters") {
      if (auth_state_ != AuthState::WaitTdlibParameters)
        return make_error(400, "setTdlibParameters not expected now");
      auth_state_ = AuthState::WaitPhoneNumber;
      push_auth_update("authorizationStateWaitPhoneNumber");
      return ok_value();
    }
    if (type == "setAuthenticationPhoneNumber") {
      if (auth_state_ != AuthState::WaitPhoneNumber)
        return make_error(400, "phone number not expected now");
      phone_number_ = req.get("phone_number").as_string();
      if (phone_number_.empty())
        return make_error(400, "PHONE_NUMBER_INVALID");
      auth_state_ = AuthState::WaitCode;
      push_auth_update("authorizationStateWaitCode");
      return ok_value();
    }
    if (type == "checkAuthenticationCode") {
      if (auth_state_ != AuthState::WaitCode)
        return make_error(400, "code not expected now");
      const std::string& code = req.get("code").as_string();
      if (code.empty() ||
          (!expected_code_.empty() && code != expected_code_))
        return make_error(400, "PHONE_CODE_INVALID");
      if (!expected_password_.empty()) {
        auth_state_ = AuthState::WaitPassword;
        push_auth_update("authorizationStateWaitPassword");
      } else {
        auth_state_ = AuthState::Ready;
        push_auth_update("authorizationStateReady");
      }
      return ok_value();
    }
    if (type == "checkAuthenticationPassword") {
      if (auth_state_ != AuthState::WaitPassword)
        return make_error(400, "password not expected now");
      if (req.get("password").as_string() != expected_password_)
        return make_error(400, "PASSWORD_HASH_INVALID");
      auth_state_ = AuthState::Ready;
      push_auth_update("authorizationStateReady");
      return ok_value();
    }
    return make_error(400, "unknown auth request: " + type);
  }

  static bool is_auth_request(const std::string& type) {
    return type == "setTdlibParameters" ||
           type == "setAuthenticationPhoneNumber" ||
           type == "checkAuthenticationCode" ||
           type == "checkAuthenticationPassword";
  }

  // The 16-method router (crawler/crawler.go:109-126 surface).
  Value route(const Value& req) {
    const std::string& type = req.get("@type").as_string();
    if (is_auth_request(type)) return route_auth(type, req);
    if (auth_state_ != AuthState::Ready && type != "close")
      return make_error(401, "UNAUTHORIZED: complete authorization first");
    Value flood = flood_or_null(type);
    if (!flood.is_null()) return flood;

    if (type == "searchPublicChat") return search_public_chat(req);
    if (type == "getChat") return get_chat(req);
    if (type == "getChatHistory") return get_chat_history(req);
    if (type == "getMessage") return get_message(req);
    if (type == "getMessageLink") return get_message_link(req);
    if (type == "getMessageThread") return get_message_thread(req);
    if (type == "getMessageThreadHistory") return get_message_thread_history(req);
    if (type == "getSupergroup") return get_supergroup(req);
    if (type == "getSupergroupFullInfo") return get_supergroup_full_info(req);
    if (type == "getBasicGroupFullInfo") return get_basic_group_full_info(req);
    if (type == "getRemoteFile") return get_remote_file(req);
    if (type == "downloadFile") return download_file(req);
    if (type == "deleteFile") return delete_file(req);
    if (type == "getMe") return get_me();
    if (type == "getUser") return get_user(req);
    if (type == "close") {
      Object o;
      o["@type"] = Value("ok");
      return Value(std::move(o));
    }
    return make_error(400, "unknown request @type: " + type);
  }

  Value search_public_chat(const Value& req) {
    const std::string& username = req.get("username").as_string();
    auto it = store_.by_username.find(username);
    if (it == store_.by_username.end())
      return make_error(400, "USERNAME_NOT_OCCUPIED");
    return chat_to_json(it->second);
  }

  Value get_chat(const Value& req) {
    StoredChannel* c = channel_by_chat_id(req.get("chat_id").as_int());
    if (!c) return make_error(400, "CHANNEL_INVALID");
    return chat_to_json(*c);
  }

  Value get_chat_history(const Value& req) {
    StoredChannel* c = channel_by_chat_id(req.get("chat_id").as_int());
    if (!c) return make_error(400, "CHANNEL_INVALID");
    int64_t from_message_id = req.get("from_message_id").as_int();
    int64_t limit = req.get("limit").as_int(100);
    std::vector<StoredMessage> page;
    for (const auto& m : c->messages) {
      if (from_message_id != 0 && m.id >= from_message_id) continue;
      page.push_back(m);
      if (static_cast<int64_t>(page.size()) >= limit) break;
    }
    return messages_to_json(page,
                            static_cast<int64_t>(c->messages.size()));
  }

  StoredMessage* find_message(int64_t chat_id, int64_t message_id) {
    StoredChannel* c = channel_by_chat_id(chat_id);
    if (!c) return nullptr;
    for (auto& m : c->messages)
      if (m.id == message_id) return &m;
    return nullptr;
  }

  Value get_message(const Value& req) {
    StoredMessage* m = find_message(req.get("chat_id").as_int(),
                                    req.get("message_id").as_int());
    if (!m) return make_error(400, "MESSAGE_NOT_FOUND");
    return message_to_json(*m);
  }

  Value get_message_link(const Value& req) {
    int64_t chat_id = req.get("chat_id").as_int();
    int64_t message_id = req.get("message_id").as_int();
    StoredChannel* c = channel_by_chat_id(chat_id);
    if (!c || !find_message(chat_id, message_id))
      return make_error(400, "MESSAGE_NOT_FOUND");
    Object o;
    o["@type"] = Value("messageLink");
    // Public t.me links shift the internal id by 2^20
    // (telegramhelper/tdutils.go:1005).
    o["link"] = Value("https://t.me/" + c->username + "/" +
                      std::to_string(message_id >> 20));
    o["is_public"] = Value(true);
    return Value(std::move(o));
  }

  Value get_message_thread(const Value& req) {
    int64_t chat_id = req.get("chat_id").as_int();
    int64_t message_id = req.get("message_id").as_int();
    StoredChannel* c = channel_by_chat_id(chat_id);
    if (!c) return make_error(400, "CHANNEL_INVALID");
    auto it = c->comments.find(message_id);
    Object o;
    o["@type"] = Value("messageThreadInfo");
    o["chat_id"] = Value(chat_id);
    o["message_thread_id"] = Value(message_id);
    o["reply_count"] =
        Value(it == c->comments.end()
                  ? int64_t(0)
                  : static_cast<int64_t>(it->second.size()));
    return Value(std::move(o));
  }

  Value get_message_thread_history(const Value& req) {
    int64_t chat_id = req.get("chat_id").as_int();
    int64_t message_id = req.get("message_id").as_int();
    StoredChannel* c = channel_by_chat_id(chat_id);
    if (!c) return make_error(400, "CHANNEL_INVALID");
    auto it = c->comments.find(message_id);
    if (it == c->comments.end()) return messages_to_json({}, 0);
    return messages_to_json(it->second,
                            static_cast<int64_t>(it->second.size()));
  }

  Value get_supergroup(const Value& req) {
    int64_t sg_id = req.get("supergroup_id").as_int();
    auto it = store_.username_by_supergroup_id.find(sg_id);
    if (it == store_.username_by_supergroup_id.end())
      return make_error(400, "SUPERGROUP_INVALID");
    const StoredChannel& c = store_.by_username[it->second];
    Object o;
    o["@type"] = Value("supergroup");
    o["id"] = Value(c.supergroup_id);
    o["username"] = Value(c.username);
    o["member_count"] = Value(c.member_count);
    o["is_channel"] = Value(c.is_channel);
    o["date"] = Value(c.date);
    o["is_verified"] = Value(c.is_verified);
    return Value(std::move(o));
  }

  Value get_supergroup_full_info(const Value& req) {
    int64_t sg_id = req.get("supergroup_id").as_int();
    auto it = store_.username_by_supergroup_id.find(sg_id);
    if (it == store_.username_by_supergroup_id.end())
      return make_error(400, "SUPERGROUP_INVALID");
    const StoredChannel& c = store_.by_username[it->second];
    Object o;
    o["@type"] = Value("supergroupFullInfo");
    o["description"] = Value(c.description);
    o["member_count"] = Value(c.member_count);
    o["photo_remote_id"] = Value(c.photo_remote_id);
    return Value(std::move(o));
  }

  Value get_basic_group_full_info(const Value& req) {
    int64_t bg_id = req.get("basic_group_id").as_int();
    auto it = store_.username_by_supergroup_id.find(bg_id);
    if (it == store_.username_by_supergroup_id.end())
      return make_error(400, "GROUP_INVALID");
    const StoredChannel& c = store_.by_username[it->second];
    Object o;
    o["@type"] = Value("basicGroupFullInfo");
    o["description"] = Value(c.description);
    o["members_count"] = Value(c.member_count);
    return Value(std::move(o));
  }

  Value get_remote_file(const Value& req) {
    const std::string& remote_id = req.get("remote_file_id").as_string();
    auto it = store_.files_by_remote_id.find(remote_id);
    if (it == store_.files_by_remote_id.end())
      return make_error(400, "FILE_NOT_FOUND");
    return file_to_json(it->second);
  }

  Value download_file(const Value& req) {
    int64_t file_id = req.get("file_id").as_int();
    auto it = store_.files_by_id.find(file_id);
    if (it == store_.files_by_id.end())
      return make_error(400, "FILE_NOT_FOUND");
    StoredFile& f = it->second;
    if (f.local_path.empty()) {
      // Materialize into files_dir (the download manager leg).
      f.local_path = (store_.files_dir.empty() ? std::string("/tmp")
                                               : store_.files_dir) +
                     "/dct_file_" + std::to_string(f.id) + ".bin";
      std::ofstream out(f.local_path, std::ios::binary);
      std::string blob(static_cast<size_t>(f.size > 0 ? f.size : 1), '\0');
      out.write(blob.data(), static_cast<std::streamsize>(blob.size()));
    }
    f.downloaded = true;
    store_.files_by_remote_id[f.remote_id] = f;
    return file_to_json(f);
  }

  Value delete_file(const Value& req) {
    int64_t file_id = req.get("file_id").as_int();
    auto it = store_.files_by_id.find(file_id);
    if (it != store_.files_by_id.end() && !it->second.local_path.empty()) {
      std::remove(it->second.local_path.c_str());
      it->second.local_path.clear();
      it->second.downloaded = false;
      store_.files_by_remote_id[it->second.remote_id] = it->second;
    }
    Object o;
    o["@type"] = Value("ok");
    return Value(std::move(o));
  }

  Value get_me() {
    Object o;
    o["@type"] = Value("user");
    o["id"] = Value(store_.me_id);
    o["username"] = Value(store_.me_username);
    o["first_name"] = Value("dct");
    o["last_name"] = Value("native");
    return Value(std::move(o));
  }

  Value get_user(const Value& req) {
    Object o;
    o["@type"] = Value("user");
    o["id"] = req.get("user_id");
    o["username"] = Value("user" + std::to_string(req.get("user_id").as_int()));
    o["first_name"] = Value("");
    o["last_name"] = Value("");
    return Value(std::move(o));
  }
};

// ---------------------------------------------------------------------------
// Native HTTPS GET over the Chrome-shaped TLS stream — the validator's
// fingerprint-matched transport (`telegramhelper/utlstransport.go:19-57`).
// HTTP/1.1 with Connection: close; ALPN is restricted to http/1.1 here
// (we do not speak h2), the one documented delta from Chrome's ALPN.
// ---------------------------------------------------------------------------

const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string base64_encode(const std::string& in) {
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = (static_cast<unsigned char>(in[i]) << 16) |
                 (static_cast<unsigned char>(in[i + 1]) << 8) |
                 static_cast<unsigned char>(in[i + 2]);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += kB64[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = static_cast<unsigned char>(in[i]) << 16;
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (static_cast<unsigned char>(in[i]) << 16) |
                 (static_cast<unsigned char>(in[i + 1]) << 8);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += '=';
  }
  return out;
}

// RFC 7230 §4.1 de-chunking: hex size line CRLF data CRLF ... 0 CRLF CRLF.
// Trailers (rare) are ignored; a malformed chunk header stops decoding at
// what was parsed so far rather than returning framing bytes as content.
// Walk RFC 7230 chunk framing from `start` in place (no copies).  The
// single walker serves both the completion check in the read loop and the
// decoder, so the two can never disagree.  When `out` is non-null the
// chunk DATA is appended to it (a truncated final chunk is appended as-is,
// matching a Connection: close cutoff).  Returns true once the terminal
// 0-size chunk has been seen — determined by walking the framing, not by
// substring search (chunk DATA may legitimately contain "\r\n0\r\n").
bool walk_chunks(const std::string& raw, size_t start, std::string* out) {
  size_t pos = start;
  while (pos < raw.size()) {
    size_t line_end = raw.find("\r\n", pos);
    if (line_end == std::string::npos) return false;  // size line cut off
    const std::string size_line = raw.substr(pos, line_end - pos);
    char* endp = nullptr;
    const long long size = std::strtoll(size_line.c_str(), &endp, 16);
    if (endp == size_line.c_str() || size < 0) return false;  // malformed
    if (size == 0) return true;  // terminal chunk reached
    pos = line_end + 2;
    if (pos + static_cast<size_t>(size) > raw.size()) {
      if (out) out->append(raw, pos, raw.size() - pos);  // truncated tail
      return false;
    }
    if (out) out->append(raw, pos, static_cast<size_t>(size));
    pos += static_cast<size_t>(size) + 2;  // skip data + CRLF
  }
  return false;
}

std::string dechunk_body(const std::string& raw) {
  std::string out;
  walk_chunks(raw, 0, &out);
  return out;
}

std::string https_get_impl(const std::string& config_json) {
  Value cfg = dctjson::parse(config_json);
  const std::string host = cfg.get("host").as_string();
  const int port = static_cast<int>(cfg.get("port").as_int(443));
  std::string path = cfg.get("path").as_string();
  if (path.empty()) path = "/";
  const std::string sni = cfg.get("sni").as_string();
  const bool insecure = cfg.get("tls_insecure").as_bool(false);
  const bool plain = cfg.get("plain").as_bool(false);
  const int64_t max_body = cfg.get("max_body").as_int(1 << 20);

  std::unique_ptr<dctnet::Stream> stream;
  if (plain) {
    stream.reset(new dctnet::TcpStream(host, port));
  } else {
    stream.reset(new dctnet::TlsStream(host, port, sni, insecure,
                                       /*http11_only=*/true));
  }

  std::string req = "GET " + path + " HTTP/1.1\r\n";
  // Chrome's header ORDER for a navigation fetch; values supplied by the
  // caller (the validator's rotating UA pool) with sane defaults.
  req += "Host: " + (sni.empty() ? host : sni) + "\r\n";
  req += "Connection: close\r\n";
  const Value& headers = cfg.get("headers");
  bool has_ua = false, has_accept = false;
  if (headers.type() == dctjson::Type::Object) {
    for (const auto& kv : headers.as_object()) {
      req += kv.first + ": " + kv.second.as_string() + "\r\n";
      std::string lower = kv.first;
      std::transform(lower.begin(), lower.end(), lower.begin(), ::tolower);
      if (lower == "user-agent") has_ua = true;
      if (lower == "accept") has_accept = true;
    }
  }
  if (!has_ua)
    req += "User-Agent: Mozilla/5.0 (X11; Linux x86_64) "
           "AppleWebKit/537.36 (KHTML, like Gecko) Chrome/124.0.0.0 "
           "Safari/537.36\r\n";
  if (!has_accept)
    req += "Accept: text/html,application/xhtml+xml,application/"
           "xml;q=0.9,image/avif,image/webp,*/*;q=0.8\r\n";
  req += "Accept-Encoding: identity\r\n\r\n";
  stream->write_all(req.data(), req.size());

  std::string data;
  char buf[16384];
  size_t header_end = std::string::npos;
  int64_t content_length = -1;
  bool chunked = false;
  std::string head_lower;
  while (static_cast<int64_t>(data.size()) < max_body + 65536) {
    size_t n = 0;
    try {
      n = stream->read_some(buf, sizeof(buf));
    } catch (const dctnet::NetError&) {
      // Unclean close (no close_notify) after the response started:
      // tolerate, like every browser/curl does for Connection: close.
      if (header_end != std::string::npos) break;
      throw;
    }
    if (n == 0) break;
    data.append(buf, n);
    if (header_end == std::string::npos) {
      header_end = data.find("\r\n\r\n");
      if (header_end != std::string::npos) {
        // Scan framing headers so we stop exactly at body end instead of
        // waiting on a server that keeps the connection open.
        head_lower = data.substr(0, header_end);
        std::transform(head_lower.begin(), head_lower.end(),
                       head_lower.begin(), ::tolower);
        // Anchor on the preceding CRLF so e.g. "x-content-length:" can
        // never mis-frame the body (every real header follows one — the
        // status line ends with CRLF).
        size_t cl = head_lower.find("\r\ncontent-length:");
        if (cl != std::string::npos)
          content_length =
              std::strtoll(head_lower.c_str() + cl + 17, nullptr, 10);
        chunked = head_lower.find("\r\ntransfer-encoding: chunked") !=
                  std::string::npos;
      }
    }
    if (header_end != std::string::npos) {
      if (!chunked && content_length >= 0 &&
          static_cast<int64_t>(data.size() - header_end - 4) >=
              content_length)
        break;
      // Cheap gate first: a complete chunked message always ends with
      // "\r\n" after the 0-chunk (+ optional trailers), so most mid-
      // stream segments skip the framing walk entirely — and the walk
      // itself is in-place (no body copy per recv).
      if (chunked && data.size() >= 2 &&
          data.compare(data.size() - 2, 2, "\r\n") == 0 &&
          walk_chunks(data, header_end + 4, nullptr))
        break;  // terminal chunk reached (framing-walked, not substring)
    }
  }
  if (data.size() < 12 || data.compare(0, 5, "HTTP/") != 0 ||
      header_end == std::string::npos)
    throw std::runtime_error("malformed HTTP response");
  const int status = std::stoi(data.substr(9, 3));
  std::string body = data.substr(header_end + 4);
  if (chunked) body = dechunk_body(body);
  if (static_cast<int64_t>(body.size()) > max_body) body.resize(max_body);

  Object out;
  out["status"] = Value(int64_t(status));
  out["body_b64"] = Value(base64_encode(body));
  // Location surfaced so the caller can follow redirects (keeps the
  // selectable transports behaviorally equivalent: urllib follows 3xx).
  size_t loc = head_lower.find("\r\nlocation:");
  if (loc != std::string::npos) {
    size_t vstart = loc + 11;
    size_t vend = head_lower.find("\r\n", vstart);
    // Location as the LAST header has no trailing CRLF inside head_lower;
    // clamp to the header block so the substr never swallows the body.
    if (vend == std::string::npos) vend = header_end;
    std::string value = data.substr(vstart, vend - vstart);
    value.erase(0, value.find_first_not_of(" \t"));
    out["location"] = Value(value);
  }
  auto* tls = dynamic_cast<dctnet::TlsStream*>(stream.get());
  if (tls) out["alpn"] = Value(tls->alpn_selected());
  return dctjson::dump(Value(std::move(out)));
}

// Thread-local receive buffer, exactly like td_json_client_receive's
// contract: the returned pointer is valid until the next call on the same
// client from the same thread.
thread_local std::string g_receive_buffer;
thread_local std::string g_execute_buffer;
thread_local std::string g_https_buffer;

}  // namespace

extern "C" {

void* dct_client_create(const char* config_json) {
  try {
    return new Client(config_json ? config_json : "{}");
  } catch (const std::exception&) {
    return nullptr;
  }
}

void dct_client_send(void* client, const char* request_json) {
  if (!client || !request_json) return;
  static_cast<Client*>(client)->send(request_json);
}

const char* dct_client_receive(void* client, double timeout_s) {
  if (!client) return nullptr;
  g_receive_buffer = static_cast<Client*>(client)->receive(timeout_s);
  return g_receive_buffer.empty() ? nullptr : g_receive_buffer.c_str();
}

const char* dct_client_execute(void* client, const char* request_json) {
  if (!client || !request_json) return nullptr;
  g_execute_buffer = static_cast<Client*>(client)->execute(request_json);
  return g_execute_buffer.c_str();
}

void dct_client_destroy(void* client) {
  delete static_cast<Client*>(client);
}

// Fingerprint-matched HTTP fetch (see https_get_impl above).  Returns a
// JSON string {"status": N, "body_b64": "..."} or {"error": "..."};
// thread-local buffer, same lifetime contract as receive().
const char* dct_https_get(const char* config_json) {
  try {
    g_https_buffer = https_get_impl(config_json ? config_json : "{}");
  } catch (const std::exception& e) {
    Object o;
    o["error"] = Value(std::string(e.what()));
    g_https_buffer = dctjson::dump(Value(std::move(o)));
  }
  return g_https_buffer.c_str();
}

}  // extern "C"
