// MTProto 2.0 client transport for the dct native client.
//
// The reference's native boundary is TDLib, whose wire protocol to
// Telegram's data centers is MTProto (built in Dockerfile.tdlib:19-36 and
// driven through the auth ladder by telegramhelper/client.go:319-377).
// This header implements the client side of that protocol faithfully at
// the transport + crypto layers:
//
//   - intermediate transport framing (0xeeeeeeee init, 4-byte LE length);
//   - the creating-an-auth-key handshake with the published TL schema
//     constructors (req_pq_multi/resPQ/req_DH_params/server_DH_params_ok/
//     set_client_DH_params/dh_gen_ok), RSA(SHA1 ‖ data ‖ pad) for
//     p_q_inner_data, Pollard-rho pq factorization, SHA1-derived tmp
//     AES-IGE keys for the DH answer, 2048-bit DH;
//   - MTProto 2.0 message encryption: msg_key = SHA256(auth_key[88+x..]
//     ‖ padded plaintext)[8:24], SHA256-based key/iv derivation (x=0
//     client→server, 8 server→client), AES-256-IGE.
//
// The payload inside the encrypted envelope is a TL API constructor
// layer (tl_api.h): typed TL functions for the hot crawl RPCs, a
// schema-declared raw fallback for the tail, rpc_result#f35c6d01
// correlation by msg_id.  The schema covers the framework's 16-method
// surface rather than Telegram's ~3000 TDLib constructors — those feed
// TDLib's client database, which this framework replaces with the
// gateway-side store.  The Python twin (clients/mtproto_wire.py +
// clients/tl_api.py) implements both sides; the cross-implementation
// handshake + typed-constructor e2es in tests/test_mtproto.py and
// tests/test_tl_api.py are the parity proof.
//
// Crypto comes from libcrypto.so.3 via dlopen (no dev headers in the
// image), mirroring net.h's OpenSSL loading pattern.

#ifndef DCT_NATIVE_MTPROTO_H_
#define DCT_NATIVE_MTPROTO_H_

#include <dlfcn.h>

#include <cstdint>
#include <cstring>
#include <ctime>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

#include "net.h"

namespace dctmtp {

class MtprotoError : public std::runtime_error {
 public:
  explicit MtprotoError(const std::string& what)
      : std::runtime_error(what) {}
};

// ---------------------------------------------------------------------------
// libcrypto via dlopen (SHA/AES/BN/RAND) — same degradation policy as
// net.h: a missing libcrypto fails MTProto connects with a clear error;
// the plain DCT-v1 wire never touches this.
// ---------------------------------------------------------------------------

// Layout-compatible with OpenSSL's aes_key_st (AES_MAXNR = 14).
struct AesKey {
  unsigned int rd_key[60];
  int rounds;
};

struct BnCtx;   // opaque
struct BigNum;  // opaque

struct CryptoLib {
  unsigned char* (*SHA1)(const unsigned char*, size_t, unsigned char*);
  unsigned char* (*SHA256)(const unsigned char*, size_t, unsigned char*);
  int (*AES_set_encrypt_key)(const unsigned char*, int, AesKey*);
  int (*AES_set_decrypt_key)(const unsigned char*, int, AesKey*);
  void (*AES_ige_encrypt)(const unsigned char*, unsigned char*, size_t,
                          const AesKey*, unsigned char*, int);
  int (*RAND_bytes)(unsigned char*, int);
  BigNum* (*BN_new)();
  void (*BN_free)(BigNum*);
  BigNum* (*BN_bin2bn)(const unsigned char*, int, BigNum*);
  int (*BN_bn2bin)(const BigNum*, unsigned char*);
  int (*BN_num_bits)(const BigNum*);
  BnCtx* (*BN_CTX_new)();
  void (*BN_CTX_free)(BnCtx*);
  int (*BN_mod_exp)(BigNum*, const BigNum*, const BigNum*, const BigNum*,
                    BnCtx*);

  static CryptoLib& get() {
    static CryptoLib instance;
    return instance;
  }

  bool ok() const { return err_.empty(); }
  const std::string& error() const { return err_; }

 private:
  CryptoLib() {
    void* crypto = nullptr;
    for (const char* name : {"libcrypto.so.3", "libcrypto.so"}) {
      crypto = ::dlopen(name, RTLD_NOW | RTLD_GLOBAL);
      if (crypto) break;
    }
    if (!crypto) {
      err_ = "libcrypto not found for MTProto transport";
      return;
    }
    auto need = [this, crypto](const char* sym) -> void* {
      void* fn = ::dlsym(crypto, sym);
      if (!fn && err_.empty())
        err_ = std::string("missing libcrypto symbol: ") + sym;
      return fn;
    };
#define DCT_SYM(name) \
  name = reinterpret_cast<decltype(name)>(need(#name))
    DCT_SYM(SHA1);
    DCT_SYM(SHA256);
    DCT_SYM(AES_set_encrypt_key);
    DCT_SYM(AES_set_decrypt_key);
    DCT_SYM(AES_ige_encrypt);
    DCT_SYM(RAND_bytes);
    DCT_SYM(BN_new);
    DCT_SYM(BN_free);
    DCT_SYM(BN_bin2bn);
    DCT_SYM(BN_bn2bin);
    DCT_SYM(BN_num_bits);
    DCT_SYM(BN_CTX_new);
    DCT_SYM(BN_CTX_free);
    DCT_SYM(BN_mod_exp);
#undef DCT_SYM
  }

  std::string err_;
};

using Bytes = std::string;  // byte strings throughout (match json.h style)

inline CryptoLib& crypto() {
  CryptoLib& c = CryptoLib::get();
  if (!c.ok()) throw MtprotoError(c.error());
  return c;
}

inline Bytes sha1(const Bytes& in) {
  unsigned char out[20];
  crypto().SHA1(reinterpret_cast<const unsigned char*>(in.data()),
                in.size(), out);
  return Bytes(reinterpret_cast<char*>(out), 20);
}

inline Bytes sha256(const Bytes& in) {
  unsigned char out[32];
  crypto().SHA256(reinterpret_cast<const unsigned char*>(in.data()),
                  in.size(), out);
  return Bytes(reinterpret_cast<char*>(out), 32);
}

inline Bytes random_bytes(size_t n) {
  Bytes out(n, '\0');
  if (crypto().RAND_bytes(reinterpret_cast<unsigned char*>(&out[0]),
                          static_cast<int>(n)) != 1)
    throw MtprotoError("RAND_bytes failed");
  return out;
}

inline Bytes ige(const Bytes& key32, const Bytes& iv32, const Bytes& data,
                 bool encrypt) {
  if (data.size() % 16) throw MtprotoError("IGE needs 16-byte alignment");
  AesKey k;
  std::memset(&k, 0, sizeof(k));
  const unsigned char* kp =
      reinterpret_cast<const unsigned char*>(key32.data());
  if (encrypt)
    crypto().AES_set_encrypt_key(kp, 256, &k);
  else
    crypto().AES_set_decrypt_key(kp, 256, &k);
  Bytes iv = iv32;  // AES_ige_encrypt mutates the iv buffer
  Bytes out(data.size(), '\0');
  crypto().AES_ige_encrypt(
      reinterpret_cast<const unsigned char*>(data.data()),
      reinterpret_cast<unsigned char*>(&out[0]), data.size(), &k,
      reinterpret_cast<unsigned char*>(&iv[0]), encrypt ? 1 : 0);
  return out;
}

// mod_exp over big-endian byte strings: base^exp mod mod.
inline Bytes bn_mod_exp(const Bytes& base, const Bytes& exp,
                        const Bytes& mod, size_t out_len = 0) {
  CryptoLib& c = crypto();
  auto mk = [&c](const Bytes& b) {
    return c.BN_bin2bn(reinterpret_cast<const unsigned char*>(b.data()),
                       static_cast<int>(b.size()), nullptr);
  };
  BigNum* bb = mk(base);
  BigNum* be = mk(exp);
  BigNum* bm = mk(mod);
  BigNum* br = c.BN_new();
  BnCtx* ctx = c.BN_CTX_new();
  int ok = c.BN_mod_exp(br, bb, be, bm, ctx);
  Bytes out;
  if (ok == 1) {
    int nbytes = (c.BN_num_bits(br) + 7) / 8;
    Bytes raw(nbytes > 0 ? nbytes : 1, '\0');
    c.BN_bn2bin(br, reinterpret_cast<unsigned char*>(&raw[0]));
    if (out_len > raw.size())
      out = Bytes(out_len - raw.size(), '\0') + raw;  // left-pad
    else
      out = raw;
  }
  c.BN_CTX_free(ctx);
  c.BN_free(br);
  c.BN_free(bm);
  c.BN_free(be);
  c.BN_free(bb);
  if (ok != 1) throw MtprotoError("BN_mod_exp failed");
  return out;
}

// ---------------------------------------------------------------------------
// TL serialization (the handful of primitives the handshake uses)
// ---------------------------------------------------------------------------

inline void tl_u32(Bytes* out, uint32_t v) {
  char b[4] = {static_cast<char>(v & 0xff),
               static_cast<char>((v >> 8) & 0xff),
               static_cast<char>((v >> 16) & 0xff),
               static_cast<char>((v >> 24) & 0xff)};
  out->append(b, 4);
}

inline void tl_i64(Bytes* out, int64_t v) {
  for (int i = 0; i < 8; ++i)
    out->push_back(static_cast<char>((static_cast<uint64_t>(v) >> (8 * i)) &
                                     0xff));
}

inline void tl_bytes(Bytes* out, const Bytes& b) {
  if (b.size() >= (size_t(1) << 24))
    // The TL long form carries a 3-byte length; a silent wrap would
    // corrupt the frame.  >=16 MiB payloads belong on the DCT-v1 wire.
    throw MtprotoError("payload exceeds the TL bytes limit (2^24-1)");
  size_t head;
  if (b.size() < 254) {
    out->push_back(static_cast<char>(b.size()));
    head = 1;
  } else {
    out->push_back(static_cast<char>(0xfe));
    out->push_back(static_cast<char>(b.size() & 0xff));
    out->push_back(static_cast<char>((b.size() >> 8) & 0xff));
    out->push_back(static_cast<char>((b.size() >> 16) & 0xff));
    head = 4;
  }
  out->append(b);
  size_t pad = (4 - (head + b.size()) % 4) % 4;
  out->append(pad, '\0');
}

class TlReader {
 public:
  explicit TlReader(const Bytes& data) : data_(data) {}

  uint32_t u32() {
    const unsigned char* p = take(4);
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
  }

  int64_t i64() {
    const unsigned char* p = take(8);
    uint64_t v = 0;
    for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
    return static_cast<int64_t>(v);
  }

  Bytes raw(size_t n) {
    const unsigned char* p = take(n);
    return Bytes(reinterpret_cast<const char*>(p), n);
  }

  Bytes bytes() {
    size_t n = take(1)[0];
    size_t head = 1;
    if (n == 254) {
      const unsigned char* p = take(3);
      n = static_cast<size_t>(p[0]) | (static_cast<size_t>(p[1]) << 8) |
          (static_cast<size_t>(p[2]) << 16);
      head = 4;
    }
    Bytes b = raw(n);
    take((4 - (head + n) % 4) % 4);
    return b;
  }

  size_t offset() const { return off_; }

 private:
  const unsigned char* take(size_t n) {
    if (off_ + n > data_.size()) throw MtprotoError("TL underrun");
    const unsigned char* p =
        reinterpret_cast<const unsigned char*>(data_.data()) + off_;
    off_ += n;
    return p;
  }

  const Bytes& data_;
  size_t off_ = 0;
};

// TL constructor ids (public MTProto schema).
constexpr uint32_t kReqPqMulti = 0xBE7E8EF1u;
constexpr uint32_t kResPQ = 0x05162463u;
constexpr uint32_t kPQInnerData = 0x83C95AECu;
constexpr uint32_t kReqDHParams = 0xD712E4BEu;
constexpr uint32_t kServerDHParamsOk = 0xD0E8075Cu;
constexpr uint32_t kServerDHInnerData = 0xB5890DBAu;
constexpr uint32_t kClientDHInnerData = 0x6643B654u;
constexpr uint32_t kSetClientDHParams = 0xF5045F1Fu;
constexpr uint32_t kDhGenOk = 0x3BCBF734u;
constexpr uint32_t kVector = 0x1CB5C415u;

// ---------------------------------------------------------------------------
// Pollard's rho (pq fits 63 bits; __int128 keeps mulmod exact)
// ---------------------------------------------------------------------------

inline uint64_t mulmod_u64(uint64_t a, uint64_t b, uint64_t m) {
  return static_cast<uint64_t>(
      (static_cast<unsigned __int128>(a) * b) % m);
}

inline uint64_t gcd_u64(uint64_t a, uint64_t b) {
  while (b) {
    uint64_t t = a % b;
    a = b;
    b = t;
  }
  return a;
}

inline void factor_pq(uint64_t pq, uint64_t* p_out, uint64_t* q_out) {
  if (pq % 2 == 0) {
    *p_out = 2;
    *q_out = pq / 2;
    return;
  }
  uint64_t seed = 0xDC7DC7DC7ull;
  for (int attempt = 0; attempt < 64; ++attempt) {
    uint64_t x = 2 + (seed = seed * 6364136223846793005ull + 1442695040888963407ull) % (pq - 3);
    uint64_t c = 1 + (seed = seed * 6364136223846793005ull + 1442695040888963407ull) % (pq - 1);
    uint64_t y = x, d = 1;
    while (d == 1) {
      x = (mulmod_u64(x, x, pq) + c) % pq;
      y = (mulmod_u64(y, y, pq) + c) % pq;
      y = (mulmod_u64(y, y, pq) + c) % pq;
      d = gcd_u64(x > y ? x - y : y - x, pq);
    }
    if (d != pq) {
      uint64_t p = d, q = pq / d;
      if (p > q) std::swap(p, q);
      *p_out = p;
      *q_out = q;
      return;
    }
  }
  throw MtprotoError("pq factorization failed");
}

inline Bytes be_bytes_u64(uint64_t v) {
  Bytes out;
  bool started = false;
  for (int i = 7; i >= 0; --i) {
    unsigned char b = (v >> (8 * i)) & 0xff;
    if (b || started || i == 0) {
      out.push_back(static_cast<char>(b));
      started = true;
    }
  }
  return out;
}

inline uint64_t u64_from_be(const Bytes& b) {
  if (b.size() > 8) throw MtprotoError("big-endian value exceeds 64 bits");
  uint64_t v = 0;
  for (unsigned char c : b) v = (v << 8) | c;
  return v;
}

// Strip leading zero bytes (big-endian canonical form).
inline Bytes be_strip(const Bytes& b) {
  size_t i = 0;
  while (i + 1 < b.size() && b[i] == '\0') ++i;
  return b.substr(i);
}

// Compare big-endian byte strings as unsigned integers: -1/0/+1.
inline int be_cmp(const Bytes& a_raw, const Bytes& b_raw) {
  Bytes a = be_strip(a_raw), b = be_strip(b_raw);
  if (a.size() != b.size()) return a.size() < b.size() ? -1 : 1;
  for (size_t i = 0; i < a.size(); ++i) {
    unsigned char ca = static_cast<unsigned char>(a[i]);
    unsigned char cb = static_cast<unsigned char>(b[i]);
    if (ca != cb) return ca < cb ? -1 : 1;
  }
  return 0;
}

// Constant-time equality for MACs/digests: a forged frame's rejection time
// must not leak how many bytes matched (parity: hmac.compare_digest in the
// Python twin).
inline bool ct_eq(const Bytes& a, const Bytes& b) {
  if (a.size() != b.size()) return false;
  volatile unsigned char acc = 0;
  for (size_t i = 0; i < a.size(); ++i)
    acc |= static_cast<unsigned char>(a[i]) ^ static_cast<unsigned char>(b[i]);
  return acc == 0;
}

// Big-endian minus one (input > 0).
inline Bytes be_minus_one(const Bytes& in) {
  Bytes out = in;
  for (size_t i = out.size(); i-- > 0;) {
    unsigned char c = static_cast<unsigned char>(out[i]);
    if (c != 0) {
      out[i] = static_cast<char>(c - 1);
      break;
    }
    out[i] = '\xff';
  }
  return out;
}

// ---------------------------------------------------------------------------
// MTProto 2.0 message crypto
// ---------------------------------------------------------------------------

inline void kdf2(const Bytes& auth_key, const Bytes& msg_key, bool to_server,
                 Bytes* key, Bytes* iv) {
  size_t x = to_server ? 0 : 8;
  Bytes a = sha256(msg_key + auth_key.substr(x, 36));
  Bytes b = sha256(auth_key.substr(40 + x, 36) + msg_key);
  *key = a.substr(0, 8) + b.substr(8, 16) + a.substr(24, 8);
  *iv = b.substr(0, 8) + a.substr(8, 16) + b.substr(24, 8);
}

inline Bytes msg_key_for(const Bytes& auth_key, const Bytes& padded,
                         bool to_server) {
  size_t x = to_server ? 0 : 8;
  return sha256(auth_key.substr(88 + x, 32) + padded).substr(8, 16);
}

// SHA1-derived tmp key/iv protecting the DH answer (spec rule).
inline void dh_tmp_key_iv(const Bytes& new_nonce, const Bytes& server_nonce,
                          Bytes* key, Bytes* iv) {
  *key = sha1(new_nonce + server_nonce) +
         sha1(server_nonce + new_nonce).substr(0, 12);
  *iv = sha1(server_nonce + new_nonce).substr(12, 8) +
        sha1(new_nonce + new_nonce) + new_nonce.substr(0, 4);
}

// ---------------------------------------------------------------------------
// RSA public key ({n, e} as big-endian byte strings)
// ---------------------------------------------------------------------------

struct RsaPub {
  Bytes n;  // big-endian modulus
  Bytes e;  // big-endian exponent

  int64_t fingerprint() const {
    Bytes ser;
    tl_bytes(&ser, be_strip(n));
    tl_bytes(&ser, be_strip(e));
    Bytes h = sha1(ser);
    uint64_t v = 0;
    for (int i = 19; i >= 12; --i)
      v = (v << 8) | static_cast<unsigned char>(h[i]);
    return static_cast<int64_t>(v);
  }

  // data_with_hash = SHA1(data) ‖ data ‖ random pad to 255; raw RSA.
  Bytes encrypt_with_hash(const Bytes& data) const {
    if (data.size() > 255 - 20)
      throw MtprotoError("RSA payload too large");
    Bytes dwh = sha1(data) + data;
    dwh += random_bytes(255 - dwh.size());
    return bn_mod_exp(dwh, e, n, 256);
  }
};

inline Bytes hex_to_bytes(const std::string& hex) {
  std::string h = hex;
  if (h.rfind("0x", 0) == 0 || h.rfind("0X", 0) == 0) h = h.substr(2);
  if (h.size() % 2) h = "0" + h;
  Bytes out;
  auto nib = [](char c) -> int {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    throw MtprotoError("bad hex digit");
  };
  for (size_t i = 0; i < h.size(); i += 2)
    out.push_back(static_cast<char>((nib(h[i]) << 4) | nib(h[i + 1])));
  return out;
}

// RFC 3526 MODP-2048 safe prime — the one DH group the gateway serves.
// The spec mandates verifying dh_prime is a known safe prime; per-handshake
// primality checks are too slow, so (like production clients) we pin the
// cached known group.  Parity: DH_PRIME in clients/mtproto_wire.py.
inline const Bytes& dh_prime_pinned() {
  static const Bytes prime = hex_to_bytes(
      "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
      "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
      "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
      "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
      "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
      "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
      "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
      "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF");
  return prime;
}

// ---------------------------------------------------------------------------
// Intermediate transport over a dctnet::Stream
// ---------------------------------------------------------------------------

class Transport {
 public:
  static constexpr size_t kMaxPacket = 64 * 1024 * 1024;

  explicit Transport(dctnet::Stream* stream) : stream_(stream) {
    static const char init[4] = {'\xee', '\xee', '\xee', '\xee'};
    stream_->write_all(init, 4);
  }

  void send(const Bytes& payload) {
    if (payload.size() > kMaxPacket) throw MtprotoError("packet too large");
    char header[4];
    uint32_t n = static_cast<uint32_t>(payload.size());
    header[0] = static_cast<char>(n & 0xff);
    header[1] = static_cast<char>((n >> 8) & 0xff);
    header[2] = static_cast<char>((n >> 16) & 0xff);
    header[3] = static_cast<char>((n >> 24) & 0xff);
    std::lock_guard<std::mutex> lock(write_mu_);
    stream_->write_all(header, 4);
    stream_->write_all(payload.data(), payload.size());
  }

  // Blocking read of one packet; empty on orderly close.
  Bytes recv() {
    char header[4];
    if (!read_exact(header, 4)) return Bytes();
    uint32_t n = static_cast<uint32_t>(static_cast<unsigned char>(header[0])) |
                 (static_cast<uint32_t>(
                      static_cast<unsigned char>(header[1])) << 8) |
                 (static_cast<uint32_t>(
                      static_cast<unsigned char>(header[2])) << 16) |
                 (static_cast<uint32_t>(
                      static_cast<unsigned char>(header[3])) << 24);
    if (n > kMaxPacket) throw MtprotoError("oversized packet");
    Bytes payload(n, '\0');
    if (n > 0 && !read_exact(&payload[0], n))
      throw MtprotoError("truncated packet");
    return payload;
  }

  bool wait_readable(int timeout_ms) {
    return stream_->wait_readable(timeout_ms);
  }

 private:
  bool read_exact(char* buf, size_t len) {
    size_t off = 0;
    while (off < len) {
      size_t n = stream_->read_some(buf + off, len - off);
      if (n == 0) return false;
      off += n;
    }
    return true;
  }

  dctnet::Stream* stream_;
  std::mutex write_mu_;
};

// ---------------------------------------------------------------------------
// The client handshake + session (creating an auth key, then 2.0 messages)
// ---------------------------------------------------------------------------

inline int64_t client_msg_id(int64_t* last) {
  int64_t mid = (static_cast<int64_t>(::time(nullptr)) << 32);
  Bytes r = random_bytes(3);
  mid |= (static_cast<int64_t>(static_cast<unsigned char>(r[0])) << 16 |
          static_cast<int64_t>(static_cast<unsigned char>(r[1])) << 8 |
          static_cast<int64_t>(static_cast<unsigned char>(r[2]))) &
         ~0x3ll;
  if (mid <= *last) mid = *last + 4;
  *last = mid;
  return mid;
}

inline Bytes plain_message(const Bytes& body, int64_t msg_id) {
  Bytes out(8, '\0');  // auth_key_id = 0
  tl_i64(&out, msg_id);
  tl_u32(&out, static_cast<uint32_t>(body.size()));
  out += body;
  return out;
}

inline Bytes parse_plain(const Bytes& packet) {
  TlReader r(packet);
  if (r.i64() != 0) throw MtprotoError("expected plain message");
  r.i64();  // msg_id
  uint32_t n = r.u32();
  return r.raw(n);
}

class MtprotoConnection {
 public:
  // Performs the full auth-key handshake on construction.  The keyring
  // mirrors real Telegram clients: several pinned DC public keys, the one
  // whose fingerprint the server offers in resPQ gets used.
  MtprotoConnection(std::unique_ptr<dctnet::Stream> stream,
                    std::vector<RsaPub> server_keys)
      : stream_(std::move(stream)), transport_(stream_.get()) {
    if (server_keys.empty())
      throw MtprotoError("empty RSA keyring");
    handshake(server_keys);
  }

  MtprotoConnection(std::unique_ptr<dctnet::Stream> stream,
                    const RsaPub& server_key)
      : MtprotoConnection(std::move(stream),
                          std::vector<RsaPub>{server_key}) {}

  // Session-material seam: a connection with CALLER-SUPPLIED key/salt/id,
  // skipping the network handshake — lets the sanitizer stress harness
  // drive the concurrent encrypt+send path (the msg_id-ordering lock)
  // against a peer that only drains bytes.
  MtprotoConnection(std::unique_ptr<dctnet::Stream> stream,
                    Bytes auth_key, Bytes server_salt, Bytes session_id)
      : stream_(std::move(stream)), transport_(stream_.get()),
        auth_key_(std::move(auth_key)),
        server_salt_(std::move(server_salt)),
        session_id_(std::move(session_id)) {
    if (auth_key_.size() != 256)
      throw MtprotoError("auth_key must be 256 bytes");
    if (server_salt_.size() != 8 || session_id_.size() != 8)
      throw MtprotoError("salt/session_id must be 8 bytes");
    auth_key_id_ = sha1(auth_key_).substr(12, 8);
  }

  // Send one raw TL payload (a tl_api.h constructor frame); returns the
  // MTProto msg_id assigned to it — the rpc_result correlation handle.
  // One lock across msg_id assignment + encryption + the wire write:
  // Client::send is called from arbitrary caller threads, and with
  // separate locks a later msg_id could reach the wire first, tripping
  // the peer's strictly-increasing replay check and killing the session.
  int64_t send_payload(const Bytes& payload) {
    std::lock_guard<std::mutex> lock(enc_mu_);
    Bytes packet = encrypt_locked(payload);
    transport_.send(packet);
    return last_sent_msg_id_;
  }

  // Blocking read of one decrypted payload; empty on orderly close.
  // last_recv_msg_id() then identifies the peer frame (server side uses
  // it as rpc_result's req_msg_id).
  Bytes recv_payload() {
    Bytes packet = transport_.recv();
    if (packet.empty()) return Bytes();
    return decrypt(packet);
  }

  int64_t last_recv_msg_id() const { return peer_last_msg_id_; }

  void shutdown() { stream_->shutdown(); }

  bool wait_readable(int timeout_ms) {
    return transport_.wait_readable(timeout_ms);
  }

  const Bytes& auth_key() const { return auth_key_; }

 private:
  void handshake(const std::vector<RsaPub>& server_keys) {
    // 1. req_pq_multi
    Bytes nonce = random_bytes(16);
    Bytes req;
    tl_u32(&req, kReqPqMulti);
    req += nonce;
    transport_.send(plain_message(req, client_msg_id(&last_msg_id_)));

    Bytes res = parse_plain(transport_.recv());
    TlReader r(res);
    if (r.u32() != kResPQ) throw MtprotoError("expected resPQ");
    if (r.raw(16) != nonce) throw MtprotoError("resPQ nonce mismatch");
    Bytes server_nonce = r.raw(16);
    uint64_t pq = u64_from_be(r.bytes());
    if (r.u32() != kVector) throw MtprotoError("expected Vector<long>");
    uint32_t n_fp = r.u32();
    std::vector<int64_t> offered(n_fp);
    for (uint32_t i = 0; i < n_fp; ++i) offered[i] = r.i64();
    const RsaPub* server_key = nullptr;
    int64_t want_fp = 0;
    for (const RsaPub& k : server_keys) {
      int64_t fp = k.fingerprint();
      for (int64_t got : offered)
        if (got == fp) { server_key = &k; want_fp = fp; break; }
      if (server_key) break;
    }
    if (!server_key) throw MtprotoError("server offered no known fingerprint");

    // 2. factor pq, req_DH_params with RSA-encrypted p_q_inner_data
    uint64_t p, q;
    factor_pq(pq, &p, &q);
    Bytes new_nonce = random_bytes(32);
    Bytes inner;
    tl_u32(&inner, kPQInnerData);
    tl_bytes(&inner, be_bytes_u64(pq));
    tl_bytes(&inner, be_bytes_u64(p));
    tl_bytes(&inner, be_bytes_u64(q));
    inner += nonce + server_nonce + new_nonce;
    Bytes dh_req;
    tl_u32(&dh_req, kReqDHParams);
    dh_req += nonce + server_nonce;
    tl_bytes(&dh_req, be_bytes_u64(p));
    tl_bytes(&dh_req, be_bytes_u64(q));
    tl_i64(&dh_req, want_fp);
    tl_bytes(&dh_req, server_key->encrypt_with_hash(inner));
    transport_.send(plain_message(dh_req, client_msg_id(&last_msg_id_)));

    // 3. server_DH_params_ok -> decrypt DH answer with SHA1 tmp key/iv
    Bytes dh_res = parse_plain(transport_.recv());
    TlReader dr(dh_res);
    if (dr.u32() != kServerDHParamsOk)
      throw MtprotoError("expected server_DH_params_ok");
    if (dr.raw(16) != nonce || dr.raw(16) != server_nonce)
      throw MtprotoError("DH params nonce mismatch");
    Bytes tmp_key, tmp_iv;
    dh_tmp_key_iv(new_nonce, server_nonce, &tmp_key, &tmp_iv);
    Bytes awh = ige(tmp_key, tmp_iv, dr.bytes(), /*encrypt=*/false);
    Bytes digest = awh.substr(0, 20);
    Bytes answer = awh.substr(20);
    TlReader ar(answer);
    if (ar.u32() != kServerDHInnerData)
      throw MtprotoError("bad server_DH_inner_data");
    if (ar.raw(16) != nonce || ar.raw(16) != server_nonce)
      throw MtprotoError("server_DH nonce mismatch");
    uint32_t g = ar.u32();
    Bytes dh_prime = ar.bytes();
    Bytes g_a = ar.bytes();
    ar.u32();  // server_time
    if (!ct_eq(sha1(answer.substr(0, ar.offset())), digest))
      throw MtprotoError("server_DH SHA1 mismatch");
    // DH group checks (spec-mandated, parity with the Python twin): the
    // prime must be the pinned known safe prime (subsumes the 2048-bit
    // length check) and 1 < g_a < dh_prime - 1 — a degenerate g_a would
    // yield a constant auth_key any passive observer can derive.
    if (dh_prime != dh_prime_pinned())
      throw MtprotoError("dh_prime is not the pinned RFC 3526 group");
    Bytes one(1, '\x01');
    if (be_cmp(g_a, one) <= 0 ||
        be_cmp(g_a, be_minus_one(dh_prime)) >= 0)
      throw MtprotoError("g_a out of range");

    // 4. client DH: b random, g_b, auth_key = g_a^b mod p
    Bytes b = random_bytes(256);
    // g as canonical big-endian bytes: one truncated byte would silently
    // compute g_b from the wrong base for any g >= 256.
    Bytes g_b = bn_mod_exp(be_bytes_u64(g), b, dh_prime);
    auth_key_ = bn_mod_exp(g_a, b, dh_prime, 256);
    Bytes cinner;
    tl_u32(&cinner, kClientDHInnerData);
    cinner += nonce + server_nonce;
    tl_i64(&cinner, 0);  // retry_id
    tl_bytes(&cinner, be_strip(g_b));
    Bytes iwh = sha1(cinner) + cinner;
    size_t pad = (16 - iwh.size() % 16) % 16;
    iwh += random_bytes(pad);
    Bytes set_req;
    tl_u32(&set_req, kSetClientDHParams);
    set_req += nonce + server_nonce;
    tl_bytes(&set_req, ige(tmp_key, tmp_iv, iwh, /*encrypt=*/true));
    transport_.send(plain_message(set_req, client_msg_id(&last_msg_id_)));

    // 5. dh_gen_ok, verify new_nonce_hash1
    Bytes ok_res = parse_plain(transport_.recv());
    TlReader okr(ok_res);
    if (okr.u32() != kDhGenOk) throw MtprotoError("expected dh_gen_ok");
    if (okr.raw(16) != nonce || okr.raw(16) != server_nonce)
      throw MtprotoError("dh_gen nonce mismatch");
    Bytes aux = sha1(auth_key_).substr(0, 8);
    Bytes expect = sha1(new_nonce + Bytes(1, '\x01') + aux).substr(4, 16);
    if (okr.raw(16) != expect)
      throw MtprotoError("new_nonce_hash1 mismatch");

    auth_key_id_ = sha1(auth_key_).substr(12, 8);
    server_salt_ = Bytes(8, '\0');
    for (int i = 0; i < 8; ++i)
      server_salt_[i] = new_nonce[i] ^ server_nonce[i];
    session_id_ = random_bytes(8);
  }

  // Caller must hold enc_mu_ (send_payload keeps it through the write).
  Bytes encrypt_locked(const Bytes& payload) {
    // seq_no = 2*count_of_content_messages_before + 1 (spec): the FIRST
    // content-related message carries 1, so read seq_ before bumping it.
    uint32_t seq_no = seq_ * 2 + 1;
    seq_ += 1;
    Bytes inner = server_salt_ + session_id_;
    last_sent_msg_id_ = client_msg_id(&last_msg_id_);
    tl_i64(&inner, last_sent_msg_id_);
    tl_u32(&inner, seq_no);
    tl_u32(&inner, static_cast<uint32_t>(payload.size()));
    inner += payload;
    // Padding: ≥12 random bytes, total length % 16 == 0 (spec).
    inner += random_bytes(12 + (16 - (inner.size() + 12) % 16) % 16);
    Bytes mk = msg_key_for(auth_key_, inner, /*to_server=*/true);
    Bytes key, iv;
    kdf2(auth_key_, mk, /*to_server=*/true, &key, &iv);
    return auth_key_id_ + mk + ige(key, iv, inner, /*encrypt=*/true);
  }

  Bytes decrypt(const Bytes& packet) {
    if (packet.size() < 24 + 32) throw MtprotoError("short message");
    if (packet.substr(0, 8) != auth_key_id_)
      throw MtprotoError("unknown auth_key_id");
    Bytes mk = packet.substr(8, 16);
    Bytes key, iv;
    kdf2(auth_key_, mk, /*to_server=*/false, &key, &iv);
    Bytes inner = ige(key, iv, packet.substr(24), /*encrypt=*/false);
    // msg_key check before trusting any field (MTProto 2.0 mandate);
    // constant-time so rejection latency can't leak matched-byte count.
    if (!ct_eq(msg_key_for(auth_key_, inner, /*to_server=*/false), mk))
      throw MtprotoError("msg_key mismatch");
    TlReader r(inner);
    r.raw(8);  // salt
    if (r.raw(8) != session_id_)
      throw MtprotoError("session_id mismatch");
    int64_t msg_id = r.i64();
    // Replay protection (spec rule, parity with the Python twin): peer
    // msg_ids are strictly increasing — a recorded server frame
    // re-injected on this connection fails here instead of being
    // re-processed.
    if (msg_id <= peer_last_msg_id_)
      throw MtprotoError("msg_id not increasing (replay?)");
    peer_last_msg_id_ = msg_id;
    r.u32();  // seq_no
    uint32_t n = r.u32();
    if (n > inner.size() - 32) throw MtprotoError("bad inner length");
    return r.raw(n);
  }

  std::unique_ptr<dctnet::Stream> stream_;
  Transport transport_;
  Bytes auth_key_;
  Bytes auth_key_id_;
  Bytes server_salt_;
  Bytes session_id_;
  uint32_t seq_ = 0;
  int64_t last_msg_id_ = 0;
  int64_t last_sent_msg_id_ = 0;
  int64_t peer_last_msg_id_ = 0;
  std::mutex enc_mu_;
};

}  // namespace dctmtp

#endif  // DCT_NATIVE_MTPROTO_H_
