// TL API constructor layer — the C++ twin of clients/tl_api.py.
//
// Every payload inside the MTProto 2.0 envelope is a TL constructor from
// the schema below: typed functions for the hot crawl RPCs, a declared
// dct.rawRequest/dct.rawResult fallback (one DataJSON-style string) for
// the long tail, responses in the published rpc_result#f35c6d01 envelope
// correlated by MTProto msg_id, and unsolicited server pushes as
// dct.update frames.  Constructor ids are CRC32 of the canonical
// declaration line (the TL standard); the Python side embeds IDENTICAL
// strings, so both derive identical ids by construction — the
// cross-implementation e2e in tests/test_mtproto.py is the parity proof.
//
// Reference boundary: Dockerfile.tdlib:19-36 (TDLib's generated TL layer);
// clients/tl_api.py holds the schema-design rationale.

#ifndef DCT_NATIVE_TL_API_H_
#define DCT_NATIVE_TL_API_H_

#include <cstdint>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.h"
#include "mtproto.h"  // Bytes, tl_bytes, TlReader, kVector

namespace dcttl {

using dctjson::Array;
using dctjson::Object;
using dctjson::Value;
using dctmtp::Bytes;

constexpr uint32_t kRpcResult = 0xF35C6D01u;
constexpr uint32_t kBoolTrue = 0x997275B5u;
constexpr uint32_t kBoolFalse = 0xBC799737u;
constexpr uint32_t kVector = 0x1CB5C415u;

// zlib-compatible CRC32 (IEEE, reflected) — the TL constructor-id rule.
inline uint32_t crc32_ieee(const std::string& s) {
  uint32_t crc = 0xFFFFFFFFu;
  for (unsigned char ch : s) {
    crc ^= ch;
    for (int i = 0; i < 8; ++i)
      crc = (crc >> 1) ^
            (0xEDB88320u & static_cast<uint32_t>(
                               -static_cast<int32_t>(crc & 1u)));
  }
  return ~crc;
}

struct Field {
  std::string name;
  std::string type;
};

struct Constructor {
  std::string name;       // e.g. "dct.chat"
  std::string json_type;  // e.g. "chat" (the JSON @type)
  uint32_t cid = 0;
  std::vector<Field> fields;
  bool is_function = false;
};

// Canonical schema lines — MUST byte-match clients/tl_api.py.
inline const std::vector<std::string>& schema_types() {
  static const std::vector<std::string> lines = {
      "dct.error code:int message:string = dct.Object",
      "dct.ok = dct.Object",
      "dct.chat id:long title:string type:string supergroup_id:long"
      " basic_group_id:long photo_remote_id:string = dct.Object",
      "dct.message id:long chat_id:long date:long view_count:long"
      " forward_count:long reply_count:long message_thread_id:long"
      " reply_to_message_id:long sender_id:long sender_username:string"
      " is_channel_post:Bool content:DataJSON reactions:DataJSON"
      " = dct.Object",
      "dct.messages total_count:long messages:Vector<dct.message>"
      " = dct.Object",
      "dct.messageLink link:string is_public:Bool = dct.Object",
      "dct.messageThreadInfo chat_id:long message_thread_id:long"
      " reply_count:long = dct.Object",
      "dct.supergroup id:long username:string member_count:long"
      " is_channel:Bool date:long is_verified:Bool = dct.Object",
      "dct.supergroupFullInfo description:string member_count:long"
      " photo_remote_id:string = dct.Object",
      "dct.basicGroupFullInfo description:string members_count:long"
      " = dct.Object",
      "dct.file id:long remote_id:string local_path:string size:long"
      " downloaded:Bool = dct.Object",
      "dct.rawResult data:string = dct.Object",
      "dct.update data:string = dct.Update",
  };
  return lines;
}

inline const std::vector<std::string>& schema_functions() {
  static const std::vector<std::string> lines = {
      "dct.searchPublicChat username:string = dct.Object",
      "dct.getChat chat_id:long = dct.Object",
      "dct.getChatHistory chat_id:long from_message_id:long offset:int"
      " limit:int = dct.Object",
      "dct.getMessage chat_id:long message_id:long = dct.Object",
      "dct.getMessageLink chat_id:long message_id:long = dct.Object",
      "dct.getMessageThread chat_id:long message_id:long = dct.Object",
      "dct.getMessageThreadHistory chat_id:long message_id:long"
      " from_message_id:long limit:int = dct.Object",
      "dct.getSupergroup supergroup_id:long = dct.Object",
      "dct.getSupergroupFullInfo supergroup_id:long = dct.Object",
      "dct.getBasicGroupFullInfo basic_group_id:long = dct.Object",
      "dct.getRemoteFile remote_file_id:string = dct.Object",
      "dct.downloadFile file_id:long = dct.Object",
      "dct.rawRequest data:string = dct.Object",
  };
  return lines;
}

struct Registry {
  std::map<std::string, Constructor> by_name;
  std::map<uint32_t, Constructor> by_id;
  std::map<std::string, Constructor> func_by_json_type;
  std::map<std::string, Constructor> type_by_json_type;
};

inline Constructor parse_line(const std::string& line, bool is_function) {
  Constructor c;
  c.cid = crc32_ieee(line);
  c.is_function = is_function;
  std::string decl = line.substr(0, line.find(" = "));
  size_t pos = 0;
  bool first = true;
  while (pos < decl.size()) {
    size_t sp = decl.find(' ', pos);
    std::string tok = decl.substr(pos, sp == std::string::npos
                                           ? std::string::npos
                                           : sp - pos);
    if (first) {
      c.name = tok;
      first = false;
    } else if (!tok.empty()) {
      size_t colon = tok.find(':');
      c.fields.push_back({tok.substr(0, colon), tok.substr(colon + 1)});
    }
    if (sp == std::string::npos) break;
    pos = sp + 1;
  }
  size_t dot = c.name.find('.');
  c.json_type = c.name.substr(dot + 1);
  return c;
}

inline const Registry& registry() {
  static const Registry reg = [] {
    Registry r;
    for (const auto& line : schema_types()) {
      Constructor c = parse_line(line, false);
      r.by_name[c.name] = c;
      r.by_id[c.cid] = c;
      r.type_by_json_type[c.json_type] = c;
    }
    for (const auto& line : schema_functions()) {
      Constructor c = parse_line(line, true);
      r.by_name[c.name] = c;
      r.by_id[c.cid] = c;
      r.func_by_json_type[c.json_type] = c;
    }
    return r;
  }();
  return reg;
}

// -- TL binary primitives ---------------------------------------------------
inline void w_u32(Bytes* out, uint32_t v) {
  for (int i = 0; i < 4; ++i)
    out->push_back(static_cast<char>((v >> (8 * i)) & 0xFF));
}

inline void w_i64(Bytes* out, int64_t v) {
  for (int i = 0; i < 8; ++i)
    out->push_back(static_cast<char>(
        (static_cast<uint64_t>(v) >> (8 * i)) & 0xFF));
}

inline void w_string(Bytes* out, const std::string& s) {
  dctmtp::tl_bytes(out, s);  // TL string framing == TL bytes framing
}

inline void w_bool(Bytes* out, bool v) {
  w_u32(out, v ? kBoolTrue : kBoolFalse);
}

// -- generic constructor <-> JSON codec -------------------------------------
inline void serialize_fields(const Constructor& c, const Value& obj,
                             Bytes* out) {
  w_u32(out, c.cid);
  for (const Field& f : c.fields) {
    const Value& v = obj.get(f.name);
    if (f.type == "int") {
      w_u32(out, static_cast<uint32_t>(
                     static_cast<int32_t>(v.as_int(0))));
    } else if (f.type == "long") {
      w_i64(out, v.as_int(0));
    } else if (f.type == "string") {
      w_string(out, v.as_string());
    } else if (f.type == "Bool") {
      w_bool(out, v.as_bool(false));
    } else if (f.type == "DataJSON") {
      w_string(out, v.is_null() ? std::string("null") : dctjson::dump(v));
    } else if (f.type.rfind("Vector<", 0) == 0) {
      const std::string inner_name =
          f.type.substr(7, f.type.size() - 8);
      const Constructor& inner = registry().by_name.at(inner_name);
      const Array& items = v.as_array();
      w_u32(out, kVector);
      w_u32(out, static_cast<uint32_t>(items.size()));
      for (const Value& item : items) serialize_fields(inner, item, out);
    } else {
      throw std::runtime_error("unknown TL field type " + f.type);
    }
  }
}

inline Value deserialize_fields(const Constructor& c,
                                dctmtp::TlReader* r) {
  Object obj;
  obj["@type"] = Value(c.json_type);
  for (const Field& f : c.fields) {
    if (f.type == "int") {
      obj[f.name] = Value(static_cast<int64_t>(
          static_cast<int32_t>(r->u32())));
    } else if (f.type == "long") {
      obj[f.name] = Value(r->i64());
    } else if (f.type == "string") {
      obj[f.name] = Value(r->bytes());
    } else if (f.type == "Bool") {
      uint32_t b = r->u32();
      if (b != kBoolTrue && b != kBoolFalse)
        throw std::runtime_error("bad Bool constructor");
      obj[f.name] = Value(b == kBoolTrue);
    } else if (f.type == "DataJSON") {
      obj[f.name] = dctjson::parse(r->bytes());
    } else if (f.type.rfind("Vector<", 0) == 0) {
      const std::string inner_name =
          f.type.substr(7, f.type.size() - 8);
      const Constructor& inner = registry().by_name.at(inner_name);
      if (r->u32() != kVector)
        throw std::runtime_error("expected Vector");
      uint32_t n = r->u32();
      if (n > 0x7FFFFFFFu)  // i32-negative on the wire: forged count
        throw std::runtime_error("negative TL vector count");
      Array items;
      for (uint32_t i = 0; i < n; ++i) {
        if (r->u32() != inner.cid)
          throw std::runtime_error("vector element type mismatch");
        items.push_back(deserialize_fields(inner, r));
      }
      obj[f.name] = Value(std::move(items));
    } else {
      throw std::runtime_error("unknown TL field type " + f.type);
    }
  }
  return Value(std::move(obj));
}

// JSON request (no @extra — that is client-local) -> TL function frame.
inline Bytes serialize_request(const Value& req) {
  const Registry& reg = registry();
  const std::string& rtype = req.get("@type").as_string();
  auto it = reg.func_by_json_type.find(rtype);
  Bytes out;
  if (it != reg.func_by_json_type.end() && rtype != "rawRequest") {
    serialize_fields(it->second, req, &out);
    return out;
  }
  Object raw;
  raw["data"] = Value(dctjson::dump(req));
  serialize_fields(reg.by_name.at("dct.rawRequest"), Value(std::move(raw)),
                   &out);
  return out;
}

// A well-formed frame is EXACTLY its constructor; trailing bytes mean a
// forged or corrupted frame and must throw, never parse silently.
inline void expect_consumed(const dctmtp::TlReader& r, size_t size) {
  if (r.offset() != size)
    throw std::runtime_error("trailing bytes after TL frame");
}

// Wire frame -> (has_req_msg_id, req_msg_id, JSON object).
inline Value deserialize_frame(const Bytes& data, bool* has_req_msg_id,
                               int64_t* req_msg_id) {
  dctmtp::TlReader r(data);
  uint32_t cid = r.u32();
  *has_req_msg_id = false;
  *req_msg_id = 0;
  const Registry& reg = registry();
  if (cid == kRpcResult) {
    *has_req_msg_id = true;
    *req_msg_id = r.i64();
    uint32_t inner_cid = r.u32();
    auto it = reg.by_id.find(inner_cid);
    if (it == reg.by_id.end() || it->second.is_function)
      throw std::runtime_error("unknown TL result constructor");
    Value obj = deserialize_fields(it->second, &r);
    expect_consumed(r, data.size());
    if (it->second.name == "dct.rawResult")
      return dctjson::parse(obj.get("data").as_string());
    return obj;
  }
  auto it = reg.by_id.find(cid);
  if (it == reg.by_id.end())
    throw std::runtime_error("unknown TL frame constructor");
  Value obj = deserialize_fields(it->second, &r);
  expect_consumed(r, data.size());
  if (it->second.name == "dct.update" || it->second.name == "dct.rawResult")
    return dctjson::parse(obj.get("data").as_string());
  return obj;
}

}  // namespace dcttl

#endif  // DCT_NATIVE_TL_API_H_
