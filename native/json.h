// Minimal JSON value, parser and serializer for the dct native client ABI.
//
// The reference linked TDLib (C++), whose public surface is the JSON-string
// td_json_client interface; this build's native client mirrors that ABI
// (crawler.TDLibClient semantics, crawler/crawler.go:109-126), so the only
// wire format crossing the C boundary is JSON text.  No third-party
// dependencies: objects, arrays, UTF-8 strings with escapes, doubles,
// int64 (preserved exactly when integral), bool, null.

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace dctjson {

class Value;
using Object = std::map<std::string, Value>;
using Array = std::vector<Value>;

enum class Type { Null, Bool, Int, Double, String, Array, Object };

class Value {
 public:
  Value() : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(int i) : type_(Type::Int), int_(i) {}
  Value(int64_t i) : type_(Type::Int), int_(i) {}
  Value(double d) : type_(Type::Double), double_(d) {}
  Value(const char* s) : type_(Type::String), str_(s) {}
  Value(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Value(Array a) : type_(Type::Array), arr_(std::move(a)) {}
  Value(Object o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    if (type_ == Type::Int) return int_;
    if (type_ == Type::Double) return static_cast<int64_t>(double_);
    return dflt;
  }
  double as_double(double dflt = 0.0) const {
    if (type_ == Type::Double) return double_;
    if (type_ == Type::Int) return static_cast<double>(int_);
    return dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const Array& as_array() const {
    static const Array empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  const Object& as_object() const {
    static const Object empty;
    return type_ == Type::Object ? obj_ : empty;
  }
  Object& obj() {
    if (type_ != Type::Object) throw std::runtime_error("not an object");
    return obj_;
  }
  Array& arr() {
    if (type_ != Type::Array) throw std::runtime_error("not an array");
    return arr_;
  }

  // Convenience: obj["k"] with null default.
  const Value& get(const std::string& key) const {
    static const Value null_value;
    if (type_ != Type::Object) return null_value;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_value : it->second;
  }

 private:
  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string str_;
  Array arr_;
  Object obj_;
};

// ---------------------------------------------------------------------------
// Parser
// ---------------------------------------------------------------------------

class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  Value parse() {
    skip_ws();
    Value v = parse_value();
    skip_ws();
    if (pos_ != s_.size()) throw std::runtime_error("trailing JSON data");
    return v;
  }

 private:
  const std::string& s_;
  size_t pos_ = 0;

  [[noreturn]] void fail(const std::string& what) {
    throw std::runtime_error("JSON parse error at " + std::to_string(pos_) +
                             ": " + what);
  }
  char peek() {
    if (pos_ >= s_.size()) fail("unexpected end");
    return s_[pos_];
  }
  char next() {
    char c = peek();
    ++pos_;
    return c;
  }
  void skip_ws() {
    while (pos_ < s_.size() &&
           (s_[pos_] == ' ' || s_[pos_] == '\t' || s_[pos_] == '\n' ||
            s_[pos_] == '\r'))
      ++pos_;
  }
  void expect(char c) {
    if (next() != c) fail(std::string("expected '") + c + "'");
  }
  bool consume_literal(const char* lit) {
    size_t n = strlen(lit);
    if (s_.compare(pos_, n, lit) == 0) {
      pos_ += n;
      return true;
    }
    return false;
  }

  Value parse_value() {
    skip_ws();
    char c = peek();
    switch (c) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return Value(parse_string());
      case 't':
        if (consume_literal("true")) return Value(true);
        fail("bad literal");
      case 'f':
        if (consume_literal("false")) return Value(false);
        fail("bad literal");
      case 'n':
        if (consume_literal("null")) return Value();
        fail("bad literal");
      default: return parse_number();
    }
  }

  Value parse_object() {
    expect('{');
    Object obj;
    skip_ws();
    if (peek() == '}') {
      next();
      return Value(std::move(obj));
    }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      expect(':');
      obj[key] = parse_value();
      skip_ws();
      char c = next();
      if (c == '}') break;
      if (c != ',') fail("expected ',' or '}'");
    }
    return Value(std::move(obj));
  }

  Value parse_array() {
    expect('[');
    Array arr;
    skip_ws();
    if (peek() == ']') {
      next();
      return Value(std::move(arr));
    }
    while (true) {
      arr.push_back(parse_value());
      skip_ws();
      char c = next();
      if (c == ']') break;
      if (c != ',') fail("expected ',' or ']'");
    }
    return Value(std::move(arr));
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      char c = next();
      if (c == '"') break;
      if (c == '\\') {
        char e = next();
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned cp = parse_hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // surrogate pair
              if (next() != '\\' || next() != 'u') fail("bad surrogate pair");
              unsigned lo = parse_hex4();
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, cp);
            break;
          }
          default: fail("bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  unsigned parse_hex4() {
    unsigned v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = next();
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else fail("bad hex digit");
    }
    return v;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  Value parse_number() {
    size_t start = pos_;
    if (peek() == '-') next();
    while (pos_ < s_.size() &&
           (isdigit(static_cast<unsigned char>(s_[pos_])) || s_[pos_] == '.' ||
            s_[pos_] == 'e' || s_[pos_] == 'E' || s_[pos_] == '+' ||
            s_[pos_] == '-'))
      ++pos_;
    std::string num = s_.substr(start, pos_ - start);
    if (num.find('.') == std::string::npos &&
        num.find('e') == std::string::npos &&
        num.find('E') == std::string::npos) {
      try {
        return Value(static_cast<int64_t>(std::stoll(num)));
      } catch (...) {
      }
    }
    try {
      return Value(std::stod(num));
    } catch (...) {
      fail("bad number");
    }
  }
};

inline Value parse(const std::string& text) { return Parser(text).parse(); }

// ---------------------------------------------------------------------------
// Serializer
// ---------------------------------------------------------------------------

inline void serialize(const Value& v, std::string& out);

inline void serialize_string(const std::string& s, std::string& out) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  out += '"';
}

inline void serialize(const Value& v, std::string& out) {
  switch (v.type()) {
    case Type::Null: out += "null"; break;
    case Type::Bool: out += v.as_bool() ? "true" : "false"; break;
    case Type::Int: out += std::to_string(v.as_int()); break;
    case Type::Double: {
      std::ostringstream ss;
      ss << v.as_double();
      out += ss.str();
      break;
    }
    case Type::String: serialize_string(v.as_string(), out); break;
    case Type::Array: {
      out += '[';
      bool first = true;
      for (const auto& item : v.as_array()) {
        if (!first) out += ',';
        first = false;
        serialize(item, out);
      }
      out += ']';
      break;
    }
    case Type::Object: {
      out += '{';
      bool first = true;
      for (const auto& kv : v.as_object()) {
        if (!first) out += ',';
        first = false;
        serialize_string(kv.first, out);
        out += ':';
        serialize(kv.second, out);
      }
      out += '}';
      break;
    }
  }
}

inline std::string dump(const Value& v) {
  std::string out;
  serialize(v, out);
  return out;
}

}  // namespace dctjson
