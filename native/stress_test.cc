// Concurrency stress harness for the native client core.
//
// The reference had no race detection at all (SURVEY.md §5.2: no -race, no
// sanitizers); this build runs the client under TSan/ASan via `make tsan`
// / `make asan`.  The harness hammers one client from several threads
// (send/receive/execute interleaved) and exits 0 iff every response parses
// and the message totals add up.

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* dct_client_create(const char* config_json);
void dct_client_send(void* client, const char* request_json);
const char* dct_client_receive(void* client, double timeout_s);
const char* dct_client_execute(void* client, const char* request_json);
void dct_client_destroy(void* client);
}

namespace {
const char* kSeedConfig = R"({"seed_json": "{\"channels\": [{\"username\": \"stress\", \"title\": \"S\", \"member_count\": 9, \"messages\": [{\"date\": 1, \"content\": {\"@type\": \"messageText\", \"text\": {\"text\": \"x\", \"entities\": []}}}]}]}"})";
}  // namespace

int main() {
  void* client = dct_client_create(kSeedConfig);
  if (!client) {
    fprintf(stderr, "create failed\n");
    return 1;
  }
  // Drain the ready update.
  dct_client_receive(client, 2.0);

  constexpr int kThreads = 8;
  constexpr int kIters = 200;
  std::atomic<int> errors{0};
  std::atomic<int> responses{0};

  std::vector<std::thread> senders;
  for (int t = 0; t < kThreads; ++t) {
    senders.emplace_back([&, t] {
      for (int i = 0; i < kIters; ++i) {
        char buf[160];
        snprintf(buf, sizeof(buf),
                 "{\"@type\":\"searchPublicChat\",\"username\":\"stress\","
                 "\"@extra\":\"t%d-%d\"}",
                 t, i);
        dct_client_send(client, buf);
        // Interleave synchronous executes on the same client.
        const char* out = dct_client_execute(
            client, "{\"@type\":\"getMe\"}");
        if (!out || strstr(out, "dct_native_client") == nullptr)
          errors.fetch_add(1);
      }
    });
  }
  std::thread receiver([&] {
    while (responses.load() < kThreads * kIters) {
      const char* out = dct_client_receive(client, 2.0);
      if (!out) break;
      if (strstr(out, "\"@extra\"") != nullptr)
        responses.fetch_add(1);
      else if (strstr(out, "updateAuthorizationState") == nullptr)
        errors.fetch_add(1);
    }
  });
  for (auto& s : senders) s.join();
  receiver.join();
  dct_client_destroy(client);

  if (errors.load() != 0 || responses.load() != kThreads * kIters) {
    fprintf(stderr, "errors=%d responses=%d (want %d)\n", errors.load(),
            responses.load(), kThreads * kIters);
    return 1;
  }
  printf("stress ok: %d responses, 0 errors\n", responses.load());
  return 0;
}
