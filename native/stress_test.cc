// Concurrency stress harness for the native client core.
//
// The reference had no race detection at all (SURVEY.md §5.2: no -race, no
// sanitizers); this build runs the client under TSan/ASan via `make tsan`
// / `make asan`.  Two phases:
//   1. offline: hammer one client from several threads
//      (send/receive/execute interleaved);
//   2. remote: an in-process wire-protocol echo server (C++ sockets) with
//      a remote-mode client — concurrent senders racing the reader thread
//      over one TCP connection, the exact interleaving `net.h`'s
//      Connection must survive.
// Exits 0 iff every response parses and the totals add up.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "mtproto.h"
#include "tl_api.h"

using dctjson::Array;
using dctjson::Object;
using dctjson::Value;

extern "C" {
void* dct_client_create(const char* config_json);
void dct_client_send(void* client, const char* request_json);
const char* dct_client_receive(void* client, double timeout_s);
const char* dct_client_execute(void* client, const char* request_json);
void dct_client_destroy(void* client);
}

namespace {
const char* kSeedConfig = R"({"seed_json": "{\"channels\": [{\"username\": \"stress\", \"title\": \"S\", \"member_count\": 9, \"messages\": [{\"date\": 1, \"content\": {\"@type\": \"messageText\", \"text\": {\"text\": \"x\", \"entities\": []}}}]}]}"})";

// --- minimal wire-protocol echo server (frames: u32 BE length + JSON) ----

bool read_exact(int fd, char* buf, size_t len) {
  size_t off = 0;
  while (off < len) {
    ssize_t n = ::recv(fd, buf + off, len - off, 0);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

bool write_all(int fd, const char* buf, size_t len) {
  size_t off = 0;
  while (off < len) {
    ssize_t n = ::send(fd, buf + off, len - off, 0);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

bool send_frame(int fd, const std::string& payload) {
  char header[4];
  uint32_t n = static_cast<uint32_t>(payload.size());
  header[0] = static_cast<char>((n >> 24) & 0xff);
  header[1] = static_cast<char>((n >> 16) & 0xff);
  header[2] = static_cast<char>((n >> 8) & 0xff);
  header[3] = static_cast<char>(n & 0xff);
  return write_all(fd, header, 4) &&
         write_all(fd, payload.data(), payload.size());
}

bool recv_frame(int fd, std::string* out) {
  char header[4];
  if (!read_exact(fd, header, 4)) return false;
  uint32_t n = (static_cast<uint32_t>(
                    static_cast<unsigned char>(header[0])) << 24) |
               (static_cast<uint32_t>(
                    static_cast<unsigned char>(header[1])) << 16) |
               (static_cast<uint32_t>(
                    static_cast<unsigned char>(header[2])) << 8) |
               static_cast<uint32_t>(static_cast<unsigned char>(header[3]));
  out->assign(n, '\0');
  return n == 0 || read_exact(fd, &(*out)[0], n);
}

// Serve one connection: ack the handshake, then echo each request back
// with "echo" stamped in (the @extra survives verbatim inside the JSON).
void serve_conn(int fd, std::atomic<int>* served) {
  std::string frame;
  if (!recv_frame(fd, &frame)) {
    ::close(fd);
    return;
  }
  send_frame(fd, "{\"@type\":\"handshake_ack\",\"transport_version\":1}");
  while (recv_frame(fd, &frame)) {
    // Wrap: {"@type":"echo", ...original fields...}
    std::string resp = "{\"@type\":\"echo\"," + frame.substr(1);
    if (!send_frame(fd, resp)) break;
    served->fetch_add(1);
  }
  ::close(fd);
}

int remote_stress() {
  int lis = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  if (::bind(lis, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(lis, 4) != 0) {
    fprintf(stderr, "remote: bind/listen failed\n");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lis, reinterpret_cast<sockaddr*>(&addr), &alen);
  const int port = ntohs(addr.sin_port);

  std::atomic<int> served{0};
  std::thread acceptor([&] {
    int fd = ::accept(lis, nullptr, nullptr);
    if (fd >= 0) serve_conn(fd, &served);
  });

  char cfg[128];
  snprintf(cfg, sizeof(cfg), "{\"server_addr\": \"127.0.0.1:%d\"}", port);
  void* client = dct_client_create(cfg);
  if (!client) {
    fprintf(stderr, "remote: client create failed\n");
    ::close(lis);     // unblock accept() so the thread is joinable...
    acceptor.join();  // ...never destroy a joinable std::thread
    return 1;
  }

  constexpr int kThreads = 6;
  constexpr int kIters = 150;
  std::vector<std::thread> senders;
  for (int t = 0; t < kThreads; ++t) {
    senders.emplace_back([&, t] {
      for (int i = 0; i < kIters; ++i) {
        char buf[128];
        snprintf(buf, sizeof(buf),
                 "{\"@type\":\"ping\",\"@extra\":\"r%d-%d\"}", t, i);
        dct_client_send(client, buf);
      }
    });
  }
  std::atomic<int> echoed{0};
  std::atomic<int> errors{0};
  std::thread receiver([&] {
    while (echoed.load() < kThreads * kIters) {
      const char* out = dct_client_receive(client, 3.0);
      if (!out) break;
      if (strstr(out, "\"@type\":\"echo\"") != nullptr &&
          strstr(out, "\"@extra\"") != nullptr)
        echoed.fetch_add(1);
      else if (strstr(out, "handshake_ack") == nullptr)
        errors.fetch_add(1);
    }
  });
  for (auto& s : senders) s.join();
  receiver.join();
  dct_client_destroy(client);
  ::close(lis);
  acceptor.join();

  if (errors.load() != 0 || echoed.load() != kThreads * kIters) {
    fprintf(stderr, "remote: errors=%d echoed=%d (want %d)\n",
            errors.load(), echoed.load(), kThreads * kIters);
    return 1;
  }
  printf("remote stress ok: %d echoes over one socket, 0 errors\n",
         echoed.load());
  return 0;
}

// --- mtproto crypto self-test under the sanitizers ------------------------
// Exercises mtproto.h's libcrypto-backed primitives (IGE, SHA KDFs, TL,
// bignum mod-exp, pq factorization) — memory errors in the byte-slicing
// paths are exactly what ASan/UBSan catch here.

int mtproto_crypto_phase() try {
  using namespace dctmtp;
  // AES-128 published IGE vector is key-size-specific; the header is
  // AES-256-only, so verify roundtrip + avalanche instead (the Python
  // twin pins the published vector; parity is proven by the cross-
  // implementation handshake in tests/test_mtproto.py).
  Bytes key(32, '\x07'), iv(32, '\x11');
  Bytes data;
  for (int i = 0; i < 256; ++i) data.push_back(static_cast<char>(i));
  Bytes ct = ige(key, iv, data, true);
  if (ige(key, iv, ct, false) != data) {
    fprintf(stderr, "mtproto: IGE roundtrip failed\n");
    return 1;
  }
  if (ct == data || ct.size() != data.size()) {
    fprintf(stderr, "mtproto: IGE degenerate ciphertext\n");
    return 1;
  }
  // MTProto 2.0 KDF: directions must differ; shapes must hold.  (The
  // auth_key must NOT be constant — x=0 and x=8 would slice identical
  // windows and the directions would legitimately coincide.)
  Bytes auth_key, msg_key(16, '\x24'), k1, iv1, k2, iv2;
  for (int i = 0; i < 256; ++i)
    auth_key.push_back(static_cast<char>((i * 37 + 5) & 0xff));
  kdf2(auth_key, msg_key, true, &k1, &iv1);
  kdf2(auth_key, msg_key, false, &k2, &iv2);
  if (k1.size() != 32 || iv1.size() != 32 || k1 == k2) {
    fprintf(stderr, "mtproto: KDF failure\n");
    return 1;
  }
  // TL bytes framing across the 254 boundary.
  for (size_t n : {size_t(0), size_t(1), size_t(253), size_t(254),
                   size_t(100000)}) {
    Bytes payload(n, '\x5a'), ser;
    tl_bytes(&ser, payload);
    TlReader r(ser);
    if (r.bytes() != payload || ser.size() % 4 != 0 ||
        r.offset() != ser.size()) {  // pad fully consumed
      fprintf(stderr, "mtproto: TL roundtrip failed at %zu\n", n);
      return 1;
    }
  }
  // Pollard rho on a 62-bit semiprime.
  uint64_t p = 2147483647ull;          // 2^31 - 1 (prime)
  uint64_t q = 2147483629ull;          // prime
  uint64_t fp = 0, fq = 0;
  factor_pq(p * q, &fp, &fq);
  if (fp != q || fq != p) {  // sorted ascending: q < p here
    fprintf(stderr, "mtproto: factorization failed (%llu, %llu)\n",
            static_cast<unsigned long long>(fp),
            static_cast<unsigned long long>(fq));
    return 1;
  }
  // mod_exp: 2^10 mod 1000 = 24, with left-padding.
  Bytes base(1, '\x02'), exp(1, '\x0a'), mod;
  mod.push_back('\x03');
  mod.push_back('\xe8');
  Bytes r = bn_mod_exp(base, exp, mod, 4);
  if (r.size() != 4 || static_cast<unsigned char>(r[3]) != 24 ||
      r[0] != '\0' || r[1] != '\0' || r[2] != '\0') {  // left-pad zeros
    fprintf(stderr, "mtproto: mod_exp failed\n");
    return 1;
  }
  printf("mtproto crypto ok: IGE/KDF/TL/rho/modexp\n");
  return 0;
} catch (const std::exception& e) {
  // crypto()/ige/bn_mod_exp throw (e.g. libcrypto missing): report like
  // every other phase instead of std::terminate.
  fprintf(stderr, "mtproto: %s\n", e.what());
  return 1;
}

// --- TL API layer under the sanitizers -------------------------------------
// tl_api.h's generic codec does a lot of byte slicing; roundtrips of the
// typed constructors (incl. the Vector<dct.message> path and the raw
// fallback) are where ASan/UBSan would catch offset bugs.

int tl_api_phase() try {
  using dcttl::deserialize_frame;
  using dcttl::registry;
  using dcttl::serialize_request;

  // Typed function roundtrip: binary TL, no JSON inside.
  Object req;
  req["@type"] = Value("getChatHistory");
  req["chat_id"] = Value(int64_t(4242));
  req["from_message_id"] = Value(int64_t(9));
  req["offset"] = Value(int64_t(-1));
  req["limit"] = Value(int64_t(100));
  dctmtp::Bytes frame = serialize_request(Value(req));
  if (frame.find("getChatHistory") != std::string::npos ||
      frame.find('{') != std::string::npos) {
    fprintf(stderr, "tl: typed frame leaked JSON\n");
    return 1;
  }
  // Result roundtrip through rpc_result, incl. a message vector with a
  // DataJSON content payload.
  Object msg;
  msg["@type"] = Value("message");
  msg["id"] = Value(int64_t(1) << 20);
  msg["chat_id"] = Value(int64_t(4242));
  msg["date"] = Value(int64_t(1700000000));
  msg["view_count"] = Value(int64_t(5));
  msg["sender_username"] = Value("u");
  msg["is_channel_post"] = Value(true);
  msg["content"] = dctjson::parse(
      "{\"@type\":\"messageText\",\"text\":{\"text\":\"hi\"}}");
  Object msgs;
  msgs["@type"] = Value("messages");
  msgs["total_count"] = Value(int64_t(1));
  Array arr;
  arr.push_back(Value(msg));
  msgs["messages"] = Value(std::move(arr));
  dctmtp::Bytes res;
  dcttl::w_u32(&res, dcttl::kRpcResult);
  dcttl::w_i64(&res, 123456789);
  dcttl::serialize_fields(registry().by_name.at("dct.messages"),
                          Value(msgs), &res);
  bool has_req = false;
  int64_t req_msg_id = 0;
  Value back = deserialize_frame(res, &has_req, &req_msg_id);
  if (!has_req || req_msg_id != 123456789 ||
      back.get("messages").as_array().size() != 1 ||
      back.get("messages").as_array()[0].get("content").get("text")
              .get("text").as_string() != "hi") {
    fprintf(stderr, "tl: rpc_result roundtrip failed\n");
    return 1;
  }
  // Raw fallback roundtrip for an unlisted @type.
  Object tail;
  tail["@type"] = Value("setAuthenticationPhoneNumber");
  tail["phone_number"] = Value("+1555");
  dctmtp::Bytes raw_frame = serialize_request(Value(tail));
  dctmtp::TlReader rr(raw_frame);
  if (rr.u32() != registry().by_name.at("dct.rawRequest").cid) {
    fprintf(stderr, "tl: tail request not on the raw fallback\n");
    return 1;
  }
  printf("tl api ok: typed/vector/rpc_result/raw roundtrips\n");
  return 0;
} catch (const std::exception& e) {
  fprintf(stderr, "tl: %s\n", e.what());
  return 1;
}

// --- concurrent mtproto senders under the sanitizers -----------------------
// ADVICE r04 (medium): msg_id assignment + encryption + the wire write must
// hold ONE lock.  Six threads hammering MtprotoConnection::send_payload
// against a draining peer put that path (and Transport's write mutex)
// under TSan; the ordering SEMANTICS are proven by the Python e2e
// (tests/test_mtproto.py concurrent-senders against the live gateway).

int mtproto_concurrent_phase() try {
  int lis = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  if (::bind(lis, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(lis, 1) != 0) {
    fprintf(stderr, "mtp-conc: bind/listen failed\n");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lis, reinterpret_cast<sockaddr*>(&addr), &alen);
  const int port = ntohs(addr.sin_port);

  std::atomic<long> drained{0};
  std::thread drainer([&] {
    int fd = ::accept(lis, nullptr, nullptr);
    if (fd < 0) return;
    char buf[4096];
    for (;;) {
      ssize_t n = ::read(fd, buf, sizeof(buf));
      if (n <= 0) break;
      drained.fetch_add(n);
    }
    ::close(fd);
  });

  {
    using namespace dctmtp;
    std::unique_ptr<dctnet::Stream> stream(
        new dctnet::TcpStream("127.0.0.1", port));
    Bytes key;
    for (int i = 0; i < 256; ++i)
      key.push_back(static_cast<char>((i * 61 + 7) & 0xff));
    MtprotoConnection conn(std::move(stream), key, Bytes(8, '\x01'),
                           Bytes(8, '\x02'));
    constexpr int kThreads = 6;
    constexpr int kIters = 100;
    std::vector<std::thread> senders;
    for (int t = 0; t < kThreads; ++t) {
      senders.emplace_back([&, t] {
        for (int i = 0; i < kIters; ++i) {
          Bytes payload(64 + (t * kIters + i) % 128,
                        static_cast<char>(t));
          conn.send_payload(payload);
        }
      });
    }
    for (auto& s : senders) s.join();
    conn.shutdown();
  }
  drainer.join();
  ::close(lis);
  if (drained.load() <= 0) {
    fprintf(stderr, "mtp-conc: nothing reached the wire\n");
    return 1;
  }
  printf("mtproto concurrent-send ok: %ld bytes drained, 6 threads\n",
         drained.load());
  return 0;
} catch (const std::exception& e) {
  fprintf(stderr, "mtp-conc: %s\n", e.what());
  return 1;
}
}  // namespace

int main() {
  void* client = dct_client_create(kSeedConfig);
  if (!client) {
    fprintf(stderr, "create failed\n");
    return 1;
  }
  // Drain the ready update.
  dct_client_receive(client, 2.0);

  constexpr int kThreads = 8;
  constexpr int kIters = 200;
  std::atomic<int> errors{0};
  std::atomic<int> responses{0};

  std::vector<std::thread> senders;
  for (int t = 0; t < kThreads; ++t) {
    senders.emplace_back([&, t] {
      for (int i = 0; i < kIters; ++i) {
        char buf[160];
        snprintf(buf, sizeof(buf),
                 "{\"@type\":\"searchPublicChat\",\"username\":\"stress\","
                 "\"@extra\":\"t%d-%d\"}",
                 t, i);
        dct_client_send(client, buf);
        // Interleave synchronous executes on the same client.
        const char* out = dct_client_execute(
            client, "{\"@type\":\"getMe\"}");
        if (!out || strstr(out, "dct_native_client") == nullptr)
          errors.fetch_add(1);
      }
    });
  }
  std::thread receiver([&] {
    while (responses.load() < kThreads * kIters) {
      const char* out = dct_client_receive(client, 2.0);
      if (!out) break;
      if (strstr(out, "\"@extra\"") != nullptr)
        responses.fetch_add(1);
      else if (strstr(out, "updateAuthorizationState") == nullptr)
        errors.fetch_add(1);
    }
  });
  for (auto& s : senders) s.join();
  receiver.join();
  dct_client_destroy(client);

  if (errors.load() != 0 || responses.load() != kThreads * kIters) {
    fprintf(stderr, "errors=%d responses=%d (want %d)\n", errors.load(),
            responses.load(), kThreads * kIters);
    return 1;
  }
  printf("stress ok: %d responses, 0 errors\n", responses.load());
  int rc = remote_stress();
  if (rc != 0) return rc;
  rc = mtproto_crypto_phase();
  if (rc != 0) return rc;
  rc = tl_api_phase();
  if (rc != 0) return rc;
  return mtproto_concurrent_phase();
}
