"""Tandem validator tests: single-edge outcomes, blocked-state machine,
walkback batch processing, crash-safe ordering (reference analog:
crawl/validator_test.go)."""

import random

import pytest

from distributed_crawler_tpu.clients import FakeClock, ValidatorRateLimiter
from distributed_crawler_tpu.clients.http_validator import (
    BLOCKED,
    TRANSIENT,
    ChannelValidationResult,
    ValidationHTTPError,
)
from distributed_crawler_tpu.config import CrawlerConfig
from distributed_crawler_tpu.crawl.validator import (
    OUTCOME_BLOCKED,
    OUTCOME_DEFINITIVE,
    OUTCOME_TRANSIENT,
    BlockedState,
    ValidatorConfig,
    edge_validation_step,
    process_walkback_batch,
    validate_single_edge,
    walkback_step,
)
from distributed_crawler_tpu.state import (
    CompositeStateManager,
    PendingEdge,
    PendingEdgeBatch,
    SqlConfig,
    StateConfig,
)


def make_sm(tmp_path):
    sm = CompositeStateManager(StateConfig(
        crawl_id="c1", crawl_execution_id="e1", storage_root=str(tmp_path),
        sampling_method="random-walk", sql=SqlConfig(url=":memory:")))
    return sm


def make_limiter():
    return ValidatorRateLimiter(requests_per_minute=0, jitter_ms=0,
                                clock=FakeClock())


def cfg(**kw):
    base = dict(crawl_id="c1", validator_claim_batch_size=10)
    base.update(kw)
    return CrawlerConfig(**base)


def edge(dest="dst_chan", pending_id=1, **kw):
    base = dict(pending_id=pending_id, batch_id="b1", crawl_id="c1",
                destination_channel=dest, source_channel="src_chan",
                sequence_id="q1", source_type="mention")
    base.update(kw)
    return PendingEdge(**base)


def validator_returning(status, reason=""):
    return lambda username: ChannelValidationResult(status=status, reason=reason)


def validator_raising(kind):
    def fn(username):
        raise ValidationHTTPError(kind, "nope")
    return fn


class TestValidateSingleEdge:
    def test_cached_invalid_skips_http(self, tmp_path):
        sm = make_sm(tmp_path)
        sm.mark_channel_invalid("dst_chan", "not_found")
        calls = []
        update, kind = validate_single_edge(
            sm, cfg(), make_limiter(), edge(),
            lambda u: calls.append(u))
        assert calls == []  # no HTTP
        assert update.validation_status == "invalid"
        assert update.validation_reason == "cached_invalid"
        assert kind == OUTCOME_DEFINITIVE

    def test_already_discovered_is_duplicate(self, tmp_path):
        sm = make_sm(tmp_path)
        sm.claim_discovered_channel("dst_chan", "earlier_crawl")
        update, kind = validate_single_edge(
            sm, cfg(), make_limiter(), edge(), validator_returning("valid"))
        assert update.validation_status == "duplicate"
        assert kind == OUTCOME_DEFINITIVE

    def test_valid_claims_first_discovery(self, tmp_path):
        sm = make_sm(tmp_path)
        update, kind = validate_single_edge(
            sm, cfg(), make_limiter(), edge(), validator_returning("valid"))
        assert update.validation_status == "valid"
        assert sm.is_channel_discovered("dst_chan")
        # Cached for future SearchPublicChat skips.
        assert sm.graph.load_seed_channels()

    def test_valid_but_claim_lost_is_duplicate(self, tmp_path):
        sm = make_sm(tmp_path)
        # Another validator won the race already.
        sm.graph.claim_discovered_channel("dst_chan", "other")
        # in-memory discovered set is empty, DB says discovered.
        update, _ = validate_single_edge(
            sm, cfg(), make_limiter(), edge(), validator_returning("valid"))
        assert update.validation_status == "duplicate"

    def test_not_channel_marks_invalid(self, tmp_path):
        sm = make_sm(tmp_path)
        update, kind = validate_single_edge(
            sm, cfg(), make_limiter(), edge(),
            validator_returning("not_channel", "not_supergroup"))
        assert update.validation_status == "not_channel"
        assert update.validation_reason == "not_supergroup"
        assert sm.is_invalid_channel("dst_chan")

    def test_blocked_leaves_pending(self, tmp_path):
        sm = make_sm(tmp_path)
        update, kind = validate_single_edge(
            sm, cfg(), make_limiter(), edge(), validator_raising(BLOCKED))
        assert update.validation_status == "pending"
        assert kind == OUTCOME_BLOCKED
        assert not sm.is_invalid_channel("dst_chan")  # never invalidated

    def test_transient_leaves_pending(self, tmp_path):
        sm = make_sm(tmp_path)
        update, kind = validate_single_edge(
            sm, cfg(), make_limiter(), edge(), validator_raising(TRANSIENT))
        assert update.validation_status == "pending"
        assert kind == OUTCOME_TRANSIENT


class TestBlockedStateMachine:
    def _seed_edges(self, sm, n):
        sm.create_pending_batch(PendingEdgeBatch(
            batch_id="b1", crawl_id="c1", source_channel="src_chan",
            source_page_id="p1", source_depth=0, sequence_id="q1"))
        for i in range(n):
            sm.insert_pending_edge(edge(dest=f"chan_{i:02d}", pending_id=0))

    def test_enters_blocked_after_threshold_and_emits_access_event(self, tmp_path):
        sm = make_sm(tmp_path)
        self._seed_edges(sm, 6)
        blocked = BlockedState()
        vcfg = ValidatorConfig(blocked_threshold=5)
        clock = FakeClock(start=100.0)
        edge_validation_step(sm, cfg(), vcfg, make_limiter(), blocked,
                             validator_raising(BLOCKED), clock.time)
        assert blocked.active
        assert blocked.consecutive_count >= 5
        events = sm.graph.binding.query("SELECT reason FROM access_events")
        assert events == [("ip_blocked",)]
        # Blocked edges go straight back to 'pending': immediately reclaimable.
        assert len(sm.claim_pending_edges(100)) == 6

    def test_probe_resumes_validation(self, tmp_path):
        sm = make_sm(tmp_path)
        blocked = BlockedState(active=True, consecutive_count=5,
                               last_probe_at=0.0)
        vcfg = ValidatorConfig(probe_interval_s=300)
        clock = FakeClock(start=1000.0)
        probes = []
        def probe_ok(username):
            probes.append(username)
            return ChannelValidationResult(status="valid")
        # First call probes immediately (last_probe_at sentinel 0).
        edge_validation_step(sm, cfg(), vcfg, make_limiter(), blocked,
                             probe_ok, clock.time)
        assert probes == ["telegram"]  # canary channel
        assert not blocked.active and blocked.consecutive_count == 0

    def test_probe_failure_stays_blocked_until_interval(self, tmp_path):
        sm = make_sm(tmp_path)
        blocked = BlockedState(active=True, consecutive_count=5,
                               last_probe_at=0.0)
        vcfg = ValidatorConfig(probe_interval_s=300)
        clock = FakeClock(start=1000.0)
        probes = []
        def probe_fail(username):
            probes.append(clock.time())
            raise ValidationHTTPError(BLOCKED, "still blocked")
        edge_validation_step(sm, cfg(), vcfg, make_limiter(), blocked,
                             probe_fail, clock.time)
        assert blocked.active and len(probes) == 1
        # Within the probe interval: no new probe.
        clock.advance(100)
        edge_validation_step(sm, cfg(), vcfg, make_limiter(), blocked,
                             probe_fail, clock.time)
        assert len(probes) == 1
        # After the interval: probes again.
        clock.advance(250)
        edge_validation_step(sm, cfg(), vcfg, make_limiter(), blocked,
                             probe_fail, clock.time)
        assert len(probes) == 2

    def test_transient_decrements_definitive_resets(self, tmp_path):
        sm = make_sm(tmp_path)
        self._seed_edges(sm, 3)
        blocked = BlockedState(consecutive_count=3)
        vcfg = ValidatorConfig(blocked_threshold=99)
        clock = FakeClock()
        outcomes = iter([validator_raising(TRANSIENT),
                         validator_returning("valid"),
                         validator_raising(BLOCKED)])
        def dispatch(username, _it=[0]):
            fns = [validator_raising(TRANSIENT), validator_returning("valid"),
                   validator_raising(BLOCKED)]
            fn = fns[min(_it[0], 2)]
            _it[0] += 1
            return fn(username)
        edge_validation_step(sm, cfg(), vcfg, make_limiter(), blocked,
                             dispatch, clock.time)
        # transient: 3->2; definitive: ->0; blocked: ->1
        assert blocked.consecutive_count == 1


class TestWalkbackProcessing:
    def _prepare_batch(self, sm, statuses):
        sm.create_pending_batch(PendingEdgeBatch(
            batch_id="b1", crawl_id="c1", source_channel="src_chan",
            source_page_id="pp", source_depth=2, sequence_id="q1"))
        for i, status in enumerate(statuses):
            sm.insert_pending_edge(edge(dest=f"chan_{i:02d}", pending_id=0))
        claimed = sm.claim_pending_edges(100)
        from distributed_crawler_tpu.state import PendingEdgeUpdate
        for e, status in zip(claimed, statuses):
            sm.update_pending_edge(PendingEdgeUpdate(
                pending_id=e.pending_id, validation_status=status))
        sm.close_pending_batch("b1")

    def test_forward_choice_with_skipped_edges(self, tmp_path):
        sm = make_sm(tmp_path)
        self._prepare_batch(sm, ["valid", "valid", "invalid"])
        assert walkback_step(sm, cfg(walkback_rate=0), rng=random.Random(1))
        pages = sm.get_pages_from_page_buffer(10)
        assert len(pages) == 1
        nxt = pages[0]
        assert nxt.url.startswith("chan_0")
        assert nxt.depth == 3 and nxt.parent_id == "pp"
        assert nxt.sequence_id == "q1"  # forward keeps the chain
        # Primary + one skipped edge for the other valid channel.
        primary = sm.get_edge_record("q1", nxt.url)
        assert primary is not None and not primary.skipped
        other_valid = {"chan_00", "chan_01"} - {nxt.url}
        skipped = sm.get_edge_record("q1", other_valid.pop())
        assert skipped is not None and skipped.skipped
        # Batch completed, stats flushed, edges deleted.
        assert sm.count_incomplete_batches("c1") == 0
        rows = sm.graph.binding.query(
            "SELECT total, valid, invalid FROM source_type_stats "
            "WHERE source_type='mention'")
        assert rows == [(3, 2, 1)]
        assert sm.claim_pending_edges(10) == []

    def test_all_invalid_forces_walkback(self, tmp_path):
        sm = make_sm(tmp_path)
        sm.add_discovered_channel("older_chan")
        self._prepare_batch(sm, ["invalid", "not_channel"])
        assert walkback_step(sm, cfg(walkback_rate=0), rng=random.Random(0))
        pages = sm.get_pages_from_page_buffer(10)
        assert [p.url for p in pages] == ["older_chan"]
        assert pages[0].sequence_id != "q1"  # walkback starts a new chain
        edge_rec = sm.get_edge_record("q1", "older_chan")
        assert edge_rec is not None and edge_rec.walkback

    def test_page_carries_batch_crawl_id(self, tmp_path):
        sm = make_sm(tmp_path)
        # Batch from a DIFFERENT crawl than the validator's own.
        sm.create_pending_batch(PendingEdgeBatch(
            batch_id="bx", crawl_id="other_crawl", source_channel="s",
            source_page_id="pp", source_depth=0, sequence_id="q2"))
        sm.insert_pending_edge(edge(dest="somewhere_chan", pending_id=0,
                                    batch_id="bx", crawl_id="other_crawl"))
        claimed = sm.claim_pending_edges(10)
        from distributed_crawler_tpu.state import PendingEdgeUpdate
        sm.update_pending_edge(PendingEdgeUpdate(
            pending_id=claimed[0].pending_id, validation_status="valid"))
        sm.close_pending_batch("bx")
        assert walkback_step(sm, cfg(walkback_rate=0), rng=random.Random(0))
        rows = sm.graph.binding.query(
            "SELECT crawl_id, url FROM page_buffer")
        assert rows == [("other_crawl", "somewhere_chan")]

    def test_no_ready_batch_returns_false(self, tmp_path):
        sm = make_sm(tmp_path)
        assert not walkback_step(sm, cfg())

    def test_crash_between_complete_and_flush_leaves_orphans_only(self, tmp_path):
        sm = make_sm(tmp_path)
        self._prepare_batch(sm, ["valid"])

        real_flush = sm.flush_batch_stats
        def crashing_flush(*a, **kw):
            raise RuntimeError("crash before flush")
        sm.flush_batch_stats = crashing_flush
        # Must not raise: complete already happened; flush failure is logged.
        assert walkback_step(sm, cfg(walkback_rate=0), rng=random.Random(0))
        sm.flush_batch_stats = real_flush
        # Batch completed; leftover edges are orphans swept at startup.
        assert sm.count_incomplete_batches("c1") == 0
        assert sm.recover_orphan_edges() == 1


class TestValidatorTransportConfig:
    def test_bogus_transport_rejected_at_construction(self, tmp_path):
        """cfg.validator_transport reaches make_transport — a bad value
        fails fast when the loop is built, not on the first request."""
        import pytest as _pytest

        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl.validator import RunValidationLoop

        cfg = CrawlerConfig()
        cfg.validator_transport = "carrier-pigeon"
        with _pytest.raises(ValueError, match="unknown validator transport"):
            RunValidationLoop(sm=None, cfg=cfg)

    def test_default_transport_urllib(self):
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl.validator import RunValidationLoop

        cfg = CrawlerConfig()
        loop = RunValidationLoop(sm=None, cfg=cfg)
        assert loop.validate_fn is not None
