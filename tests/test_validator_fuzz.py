"""Fuzz the t.me HTML classifier (clients/http_validator.py).

The validator runs against responses an adversary partially controls (a
channel's title/description is attacker-supplied text inside the page),
and against arbitrarily mangled bytes when t.me is behind interfering
middleboxes.  Contract: `parse_channel_html` returns a classification or
raises ValueError (the caller's soft-block signal) — never any other
exception — and page-BODY text must not be able to spoof a valid
classification (only the <title> element decides)."""

import os
import random

import pytest

from distributed_crawler_tpu.clients.http_validator import (
    parse_channel_html,
)

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "telegram-html")
FIXTURES = [os.path.join(FIXDIR, n) for n in sorted(os.listdir(FIXDIR))]
SEEDS = range(25)


def _load(path):
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


class TestMutationRobustness:
    @pytest.mark.parametrize("seed", SEEDS)
    @pytest.mark.parametrize("path", FIXTURES)
    def test_mutated_fixture_never_crashes(self, path, seed):
        rng = random.Random(seed)
        html = list(_load(path))
        for _ in range(rng.randrange(1, 30)):
            op = rng.randrange(3)
            pos = rng.randrange(len(html)) if html else 0
            if op == 0 and html:
                html[pos] = chr(rng.randrange(32, 0x300))
            elif op == 1 and html:
                del html[pos]
            else:
                html.insert(pos, rng.choice("<>/=\"' &;\x00abct"))
        try:
            result = parse_channel_html("".join(html))
        except ValueError:
            return  # soft-block: the documented failure mode
        assert result.status in ("valid", "invalid", "not_channel")

    @pytest.mark.parametrize("seed", SEEDS)
    def test_random_garbage_is_valueerror_or_classified(self, seed):
        rng = random.Random(500 + seed)
        junk = "".join(chr(rng.randrange(1, 0x500))
                       for _ in range(rng.randrange(0, 3000)))
        try:
            result = parse_channel_html(junk)
        except ValueError:
            return
        assert result.status in ("valid", "invalid", "not_channel")

    def test_truncations_of_every_fixture(self):
        for path in FIXTURES:
            html = _load(path)
            for cut in range(0, len(html), max(1, len(html) // 40)):
                try:
                    parse_channel_html(html[:cut])
                except ValueError:
                    pass


class TestSpoofResistance:
    def test_body_text_cannot_spoof_valid(self):
        """Attacker-controlled page TEXT containing the valid-title marker
        must not classify as valid — only the <title> element decides."""
        html = ("<html><head><title>Telegram Messenger</title></head>"
                "<body><p>Telegram: View @evil_channel</p></body></html>")
        assert parse_channel_html(html).status == "invalid"

    def test_spoofed_marker_in_description_meta(self):
        html = ('<html><head><title>Telegram: Contact @someone</title>'
                '<meta property="og:description" '
                'content="Telegram: View @fake"></head><body></body></html>')
        assert parse_channel_html(html).status == "not_channel"

    def test_second_title_does_not_override_first(self):
        html = ("<html><head><title>Telegram Messenger</title>"
                "<title>Telegram: View @injected</title></head></html>")
        assert parse_channel_html(html).status == "invalid"

    def test_robots_noindex_only_counts_inside_its_own_tag(self):
        # 'noindex' appearing in body text far from the robots meta must
        # not flip a contact page to not_found.
        html = ('<html><head><title>Telegram: Contact @user</title>'
                '<meta name="robots" content="all"></head>'
                "<body>noindex</body></html>")
        assert parse_channel_html(html).status == "not_channel"
