"""Int8 quantized serving path (ops/quant.py + models/quant.py).

Strategy per SURVEY.md §4: pure-function accuracy bounds on the
primitives, float-vs-int8 parity on the full model (the property that
matters: embeddings and logits from the quantized encoder track the f32
encoder), engine e2e, and the mesh path on the 8-device virtual CPU
backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_crawler_tpu.models.encoder import (
    TINY_TEST,
    EmbedderClassifier,
    EncoderConfig,
)
from distributed_crawler_tpu.models.quant import (
    quantize_encoder_params,
    quantized_size_bytes,
)
from distributed_crawler_tpu.ops.quant import (
    int8_dense,
    int8_qkv,
    quantize_activations,
    quantize_weights,
)


def _cos(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


class TestPrimitives:
    def test_weight_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
        w_q, scale = quantize_weights(w)
        deq = w_q.astype(jnp.float32) * scale
        # Symmetric per-channel: error ≤ half a quantization step per column.
        step = np.asarray(scale)
        err = np.abs(np.asarray(deq) - np.asarray(w))
        assert (err <= 0.5 * step[None, :] + 1e-6).all()

    def test_weight_scale_per_output_channel(self):
        w = jnp.ones((16, 4)) * jnp.asarray([1.0, 2.0, 4.0, 8.0])
        w_q, scale = quantize_weights(w)
        np.testing.assert_allclose(np.asarray(scale) * 127.0,
                                   [1.0, 2.0, 4.0, 8.0], rtol=1e-6)

    def test_activation_scale_per_token(self):
        x = jnp.stack([jnp.ones(8), 10.0 * jnp.ones(8)])
        x_q, a_scale = quantize_activations(x)
        assert a_scale.shape == (2, 1)
        assert np.asarray(x_q).max() == 127

    def test_int8_dense_tracks_f32_matmul(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(k1, (8, 64))
        w = jax.random.normal(k2, (64, 32))
        w_q, scale = quantize_weights(w)
        got = int8_dense(x, w_q, scale, out_dtype=jnp.float32)
        want = x @ w
        assert _cos(got, want) > 0.999

    def test_int8_qkv_tracks_f32_einsum(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(2))
        x = jax.random.normal(k1, (2, 4, 32))
        w = jax.random.normal(k2, (32, 3, 32))
        w_q, scale = quantize_weights(w)
        assert w_q.shape == (32, 3, 32) and scale.shape == (3, 32)
        got = int8_qkv(x, w_q, scale, out_dtype=jnp.float32)
        want = jnp.einsum("blh,hto->blto", x, w)
        assert got.shape == want.shape
        assert _cos(got, want) > 0.999


class TestModelParity:
    @pytest.fixture(scope="class")
    def float_setup(self):
        cfg = TINY_TEST
        model = EmbedderClassifier(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                                 cfg.vocab_size)
        mask = jnp.ones((4, 16), jnp.bool_)
        params = model.init(jax.random.PRNGKey(0), ids, mask)
        emb, logits = model.apply(params, ids, mask)
        return cfg, params, ids, mask, emb, logits

    def test_quantized_model_tracks_float(self, float_setup):
        from dataclasses import replace

        cfg, params, ids, mask, emb_f, logits_f = float_setup
        qparams = quantize_encoder_params(params)
        qmodel = EmbedderClassifier(replace(cfg, quant="int8"))
        emb_q, logits_q = qmodel.apply(qparams, ids, mask)
        assert emb_q.shape == emb_f.shape
        # Embeddings are unit vectors: per-row cosine is the right metric.
        for r in range(emb_f.shape[0]):
            assert _cos(emb_q[r], emb_f[r]) > 0.98
        assert _cos(logits_q, logits_f) > 0.95

    def test_converter_shapes_match_quant_init(self, float_setup):
        """The converted tree must be shape/dtype-identical to what the
        quantized model would init — else apply() breaks on real loads."""
        from dataclasses import replace

        cfg, params, ids, mask, _, _ = float_setup
        qparams = quantize_encoder_params(params)
        qinit = EmbedderClassifier(replace(cfg, quant="int8")).init(
            jax.random.PRNGKey(0), ids, mask)
        flat_got = jax.tree_util.tree_flatten_with_path(qparams)[0]
        flat_want = jax.tree_util.tree_flatten_with_path(qinit)[0]
        assert [p for p, _ in flat_got] == [p for p, _ in flat_want]
        for (p, got), (_, want) in zip(flat_got, flat_want):
            assert got.shape == want.shape, p
            assert got.dtype == want.dtype, p

    def test_converter_idempotent(self, float_setup):
        _, params, _, _, _, _ = float_setup
        once = quantize_encoder_params(params)
        twice = quantize_encoder_params(once)
        for a, b in zip(jax.tree.leaves(once), jax.tree.leaves(twice)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_projection_kernels_shrink_4x(self, float_setup):
        _, params, _, _, _, _ = float_setup
        qparams = quantize_encoder_params(params)
        assert quantized_size_bytes(qparams) < quantized_size_bytes(params)
        enc = qparams["params"]["encoder"]["layers_0"]
        assert enc["attn"]["qkv/kernel_q"].dtype == jnp.int8
        assert enc["mlp"]["mlp_up"]["kernel_q"].dtype == jnp.int8


    def test_moe_quantized_model_tracks_float(self):
        from dataclasses import replace

        cfg = replace(TINY_TEST, n_experts=4)
        model = EmbedderClassifier(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(5), (4, 16), 0,
                                 cfg.vocab_size)
        mask = jnp.ones((4, 16), jnp.bool_)
        params = model.init(jax.random.PRNGKey(1), ids, mask)
        emb_f, logits_f = model.apply(params, ids, mask)
        qparams = quantize_encoder_params(params)
        moe = qparams["params"]["encoder"]["layers_0"]["moe"]
        assert moe["experts_up/kernel_q"].dtype == jnp.int8
        assert moe["experts_up/scale"].shape == (4, cfg.mlp_dim)
        assert moe["experts_down/scale"].shape == (4, cfg.hidden)
        assert "router" in moe  # the f32 router must pass through
        qmodel = EmbedderClassifier(replace(cfg, quant="int8"))
        emb_q, logits_q = qmodel.apply(qparams, ids, mask)
        for r in range(emb_f.shape[0]):
            assert _cos(emb_q[r], emb_f[r]) > 0.98
        assert _cos(logits_q, logits_f) > 0.95

    def test_moe_converter_shapes_match_quant_init(self):
        from dataclasses import replace

        cfg = replace(TINY_TEST, n_experts=4)
        ids = jnp.zeros((1, 8), jnp.int32)
        mask = jnp.ones((1, 8), jnp.bool_)
        params = EmbedderClassifier(cfg).init(jax.random.PRNGKey(0), ids,
                                              mask)
        qparams = quantize_encoder_params(params)
        qinit = EmbedderClassifier(replace(cfg, quant="int8")).init(
            jax.random.PRNGKey(0), ids, mask)
        flat_got = jax.tree_util.tree_flatten_with_path(qparams)[0]
        flat_want = jax.tree_util.tree_flatten_with_path(qinit)[0]
        assert [p for p, _ in flat_got] == [p for p, _ in flat_want]
        for (p, got), (_, want) in zip(flat_got, flat_want):
            assert got.shape == want.shape, p
            assert got.dtype == want.dtype, p

    def test_moe_expert_kernels_sharded_over_tp(self):
        from distributed_crawler_tpu.parallel.sharding import (
            ENCODER_PARAM_RULES,
            spec_for_path,
        )

        assert "tp" in str(spec_for_path(
            "encoder/layers_0/moe/experts_up/kernel_q", ENCODER_PARAM_RULES))
        assert "tp" in str(spec_for_path(
            "encoder/layers_0/moe/experts_up/scale", ENCODER_PARAM_RULES))


class TestStaticActivationScales:
    """int8_static: calibrated per-tensor activation scales (VERDICT r03
    #1's prescribed attack on the dynamic-requant overhead)."""

    @pytest.fixture(scope="class")
    def setup(self):
        from dataclasses import replace

        from distributed_crawler_tpu.models.quant import (
            calibrate_activation_scales,
        )

        cfg = TINY_TEST
        model = EmbedderClassifier(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                                 cfg.vocab_size)
        mask = jnp.ones((4, 16), jnp.bool_)
        params = model.init(jax.random.PRNGKey(0), ids, mask)
        emb_f, logits_f = model.apply(params, ids, mask)
        calib_model = EmbedderClassifier(replace(cfg, calibrate=True))
        scales = calibrate_activation_scales(calib_model, params, ids, mask)
        sparams = quantize_encoder_params(params, act_scales=scales)
        return cfg, params, sparams, ids, mask, emb_f, logits_f

    def test_calibration_collects_all_projections(self, setup):
        cfg, params, _, ids, mask, _, _ = setup
        from dataclasses import replace

        from distributed_crawler_tpu.models.quant import (
            calibrate_activation_scales,
        )

        calib_model = EmbedderClassifier(replace(cfg, calibrate=True))
        scales = calibrate_activation_scales(calib_model, params, ids, mask)
        layer0 = scales["encoder"]["layers_0"]
        assert set(layer0["attn"]) == {"qkv_in", "attn_out_in"}
        assert set(layer0["mlp"]) == {"mlp_up_in", "mlp_down_in"}
        val = layer0["attn"]["qkv_in"]
        val = val[0] if isinstance(val, (tuple, list)) else val
        assert float(val) > 0

    def test_static_params_carry_a_scale(self, setup):
        _, _, sparams, _, _, _, _ = setup
        enc = sparams["params"]["encoder"]["layers_0"]
        assert enc["attn"]["qkv/a_scale"].shape == ()
        assert enc["attn"]["attn_out"]["a_scale"].shape == ()
        assert enc["mlp"]["mlp_up"]["a_scale"].shape == ()
        assert enc["mlp"]["mlp_down"]["a_scale"].shape == ()

    def test_static_model_tracks_float(self, setup):
        from dataclasses import replace

        cfg, _, sparams, ids, mask, emb_f, logits_f = setup
        smodel = EmbedderClassifier(replace(cfg, quant="int8_static"))
        emb_s, logits_s = smodel.apply(sparams, ids, mask)
        for r in range(emb_f.shape[0]):
            assert _cos(emb_s[r], emb_f[r]) > 0.97
        assert _cos(logits_s, logits_f) > 0.93

    def test_static_shapes_match_static_init(self, setup):
        from dataclasses import replace

        cfg, _, sparams, ids, mask, _, _ = setup
        sinit = EmbedderClassifier(replace(cfg, quant="int8_static")).init(
            jax.random.PRNGKey(0), ids, mask)
        flat_got = jax.tree_util.tree_flatten_with_path(sparams)[0]
        flat_want = jax.tree_util.tree_flatten_with_path(sinit)[0]
        assert [p for p, _ in flat_got] == [p for p, _ in flat_want]
        for (p, got), (_, want) in zip(flat_got, flat_want):
            assert got.shape == want.shape, p
            assert got.dtype == want.dtype, p

    def test_calibrate_requires_float_path(self):
        from dataclasses import replace

        with pytest.raises(ValueError, match="calibrate"):
            replace(TINY_TEST, calibrate=True, quant="int8").validate()

    def test_static_primitive_matches_dynamic_closely(self):
        from distributed_crawler_tpu.ops.quant import (
            quantize_activations_static,
        )

        x = jax.random.normal(jax.random.PRNGKey(5), (16, 64))
        a_scale = jnp.max(jnp.abs(x)) / 127.0
        x_q = quantize_activations_static(x, a_scale)
        deq = x_q.astype(jnp.float32) * a_scale
        assert float(jnp.max(jnp.abs(deq - x))) <= float(a_scale) * 0.5 + 1e-6


class TestEngine:
    def test_engine_int8_end_to_end(self):
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        cfg = EngineConfig(model="tiny", batch_size=4, buckets=(32,),
                           quantize="int8")
        eng = InferenceEngine(cfg, registry=MetricsRegistry())
        assert eng.ecfg.quant == "int8"
        out = eng.run(["hello world", "quantized serving"])
        assert len(out) == 2
        for r in out:
            n = np.linalg.norm(r["embedding"])
            assert abs(n - 1.0) < 1e-3
            assert 0 <= r["label"] < eng.ecfg.n_labels

    def test_engine_int8_matches_float_embeddings(self):
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        texts = ["a post about cats", "completely different text"]
        base = EngineConfig(model="tiny", batch_size=4, buckets=(32,))
        e_f = InferenceEngine(base, registry=MetricsRegistry())
        from dataclasses import replace as dreplace

        e_q = InferenceEngine(dreplace(base, quantize="int8"),
                              registry=MetricsRegistry())
        emb_f = e_f.embed(texts)
        emb_q = e_q.embed(texts)
        for r in range(len(texts)):
            assert _cos(emb_f[r], emb_q[r]) > 0.98

    def test_engine_int8_static_end_to_end(self):
        """int8_static: the engine calibrates at startup and serves with
        fused static activation quantization."""
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        cfg = EngineConfig(model="tiny", batch_size=4, buckets=(32,),
                           quantize="int8_static")
        eng = InferenceEngine(cfg, registry=MetricsRegistry())
        assert eng.ecfg.quant == "int8_static"
        enc = eng.params["params"]["encoder"]["layers_0"]
        assert enc["attn"]["qkv/a_scale"].shape == ()
        assert float(enc["mlp"]["mlp_up"]["a_scale"]) > 0
        out = eng.run(["static scales", "fused quantize"])
        assert len(out) == 2
        for r in out:
            assert abs(np.linalg.norm(r["embedding"]) - 1.0) < 1e-3

    def test_engine_int8_static_matches_float(self):
        from dataclasses import replace as dreplace

        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        texts = ["a post about cats", "completely different text"]
        base = EngineConfig(model="tiny", batch_size=4, buckets=(32,))
        e_f = InferenceEngine(base, registry=MetricsRegistry())
        e_s = InferenceEngine(dreplace(base, quantize="int8_static"),
                              registry=MetricsRegistry())
        emb_f = e_f.embed(texts)
        emb_s = e_s.embed(texts)
        for r in range(len(texts)):
            assert _cos(emb_f[r], emb_s[r]) > 0.97

    def test_engine_rejects_unknown_mode(self):
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        with pytest.raises(ValueError, match="quantize"):
            InferenceEngine(EngineConfig(model="tiny", quantize="int4"),
                            registry=MetricsRegistry())

    def test_cli_quantize_flag_reaches_engine(self):
        from distributed_crawler_tpu.cli import (
            _make_engine,
            build_parser,
            resolve_config,
        )

        args = build_parser().parse_args(
            ["--urls", "a", "--infer-model", "tiny",
             "--infer-quantize", "int8"])
        cfg, r = resolve_config(args, env={})
        assert cfg.inference.quantize == "int8"
        eng = _make_engine(cfg, r)
        assert eng.ecfg.quant == "int8"
        # train-head's path (cast_params=False) must stay float: fine-tuning
        # on — or persisting — int8 weights would destroy the checkpoint.
        eng_train = _make_engine(cfg, r, cast_params=False)
        assert eng_train.ecfg.quant == "none"

    def test_engine_int8_on_mesh(self):
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.parallel import best_mesh_config, make_mesh
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        mesh = make_mesh(best_mesh_config(8, tp=2))
        cfg = EngineConfig(model="tiny", batch_size=8, buckets=(32,),
                           quantize="int8")
        eng = InferenceEngine(cfg, mesh=mesh, registry=MetricsRegistry())
        out = eng.run(["sharded int8 serving"] * 8)
        assert len(out) == 8
        # The quantized kernels must actually be sharded over tp, not
        # silently replicated by the catch-all rule.
        enc = eng.params["params"]["encoder"]["layers_0"]
        spec = enc["mlp"]["mlp_up"]["kernel_q"].sharding.spec
        assert "tp" in str(spec)
