"""Whole-system topology test: real OS processes over the real gRPC bus.

Spawns the orchestrator (hosting the broker), a crawl worker feeding the
inference bridge, and a TPU worker — the co-scheduled deployment of
SURVEY.md §7.7 — and asserts the crawl completes, posts land, and
inference results are written.  This is the regression net for the
production wiring this repo keeps proving out by hand: pool setup from
config, bus brokering, pre-enabled pull topics, worker URL exemption.

The reference tested multi-node only against in-memory mocks
(`distributed/integration_test.go`); this goes further — three separate
interpreters, real sockets, real seed-DB tarballs.
"""

import json
import os
import subprocess
import sys
import socket
import tarfile
import time

import pytest

pytestmark = pytest.mark.slow

SEED = {
    "channels": [
        {"username": "topoa", "id": 301, "title": "Topo A",
         "member_count": 500,
         "messages": [
             {"date": 1785300000 + i,
              "content": {"@type": "messageText",
                          "text": {"text": f"alpha {i} see t.me/topob"}},
              "view_count": i} for i in range(1, 4)]},
        {"username": "topob", "id": 302, "title": "Topo B",
         "member_count": 400,
         "messages": [
             {"date": 1785300100 + i,
              "content": {"@type": "messageText",
                          "text": {"text": f"beta {i}"}},
              "view_count": i} for i in range(1, 3)]},
    ]
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cpu_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("AXON", "PALLAS_AXON", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _spawn(args, log_path, env=None):
    log = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "distributed_crawler_tpu.cli"] + args,
        stdout=log, stderr=subprocess.STDOUT, env=env or dict(os.environ),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_orchestrator_worker_tpu_worker_processes(tmp_path):
    src = tmp_path / "seed.json"
    src.write_text(json.dumps(SEED))
    tar = tmp_path / "dbs.tar.gz"
    with tarfile.open(tar, "w:gz") as t:
        t.add(src, arcname="db/seed.json")

    port = _free_port()
    addr = f"127.0.0.1:{port}"
    procs = []
    try:
        procs.append(_spawn(
            ["--mode", "orchestrator", "--urls", "topoa",
             "--bus-address", addr, "--crawl-id", "topo1",
             "--storage-root", str(tmp_path / "ostore"),
             "--max-depth", "1", "--skip-media", "--log-level", "info"],
            tmp_path / "orch.log"))
        # TPU worker on CPU jax so CI needs no chip; 'tiny' model keeps
        # warmup fast.
        procs.append(_spawn(
            ["--mode", "tpu-worker", "--infer-model", "tiny",
             "--bus-address", addr,
             "--storage-root", str(tmp_path / "tpustore"),
             "--log-level", "info"],
            tmp_path / "tpu.log", env=_cpu_env()))
        procs.append(_spawn(
            ["--mode", "worker", "--worker-id", "w1",
             "--bus-address", addr, "--crawl-id", "topo1",
             "--tdlib-database-urls", str(tar),
             "--storage-root", str(tmp_path / "wstore"),
             "--skip-media", "--infer", "--log-level", "info"],
            tmp_path / "worker.log", env=_cpu_env()))

        deadline = time.time() + 150
        done = False
        while time.time() < deadline and not done:
            if procs[0].poll() is not None:
                break  # orchestrator exits once the crawl completes
            done = "crawl marked as completed" in \
                (tmp_path / "orch.log").read_text(errors="replace")
            time.sleep(1.0)
        orch_log = (tmp_path / "orch.log").read_text(errors="replace")
        assert "crawl marked as completed" in orch_log, orch_log[-2000:]

        # Crawl output: both channels' posts stored by the worker.
        posts = sorted(p.parent.parent.name
                       for p in (tmp_path / "wstore").rglob("posts.jsonl"))
        assert posts == ["topoa", "topob"], posts

        # Inference output: the bridge shipped post batches, the TPU
        # worker embedded+classified them.  Batches land one file at a
        # time, so poll until ALL 5 uids appear (not merely "some rows"),
        # and skip a partial trailing line from a file mid-append.
        deadline = time.time() + 60
        rows = []
        while time.time() < deadline:
            rows = []
            for f in (tmp_path / "tpustore").rglob("*.jsonl"):
                for line in f.read_text(errors="replace").splitlines():
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        pass  # mid-append tail
            if len({r_["post_uid"] for r_ in rows}) >= 5:
                break
            time.sleep(1.0)
        assert rows, (tmp_path / "tpu.log").read_text(
            errors="replace")[-2000:]
        assert all("embedding" in r_ and "label" in r_ for r_ in rows)
        # 3 posts from topoa + 2 from topob
        assert len({r_["post_uid"] for r_ in rows}) == 5
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)


def test_full_production_shape_with_dc_gateway(tmp_path):
    """The complete deployment: a dc-gateway process owning the store, an
    orchestrator hosting the broker, a crawl worker whose pool DIALS the
    gateway over the FULL MTProto 2.0 wire (auth-key handshake, AES-IGE
    envelope, TL API constructors; credentials minted by gen-code over
    the same wire), and a TPU worker embedding the stream — every seam
    composed in one run."""
    from distributed_crawler_tpu.clients.native import (
        NativeTelegramClient,
        generate_pcode,
    )

    bus_port = _free_port()
    bus_addr = f"127.0.0.1:{bus_port}"
    seed_file = tmp_path / "gwseed.json"
    seed_file.write_text(json.dumps(SEED))
    accounts = tmp_path / "accounts.json"
    accounts.write_text(json.dumps({"accounts": [
        {"phone_number": "+15550004444", "code": "6060"}]}))
    gw_addr_file = tmp_path / "gw.addr"
    tdlib_dir = tmp_path / "td"

    procs = []
    try:
        procs.append(_spawn(
            ["--mode", "dc-gateway", "--gateway-listen", "127.0.0.1:0",
             "--gateway-address-file", str(gw_addr_file),
             "--gateway-accounts", str(accounts),
             "--gateway-seed-json", f"@{seed_file}",
             "--gateway-wire", "mtproto",
             "--storage-root", str(tmp_path / "gwstore"),
             "--log-level", "info"],
            tmp_path / "gw.log", env=_cpu_env()))
        deadline = time.time() + 30
        while not gw_addr_file.exists() and time.time() < deadline:
            assert procs[0].poll() is None, (
                tmp_path / "gw.log").read_text(errors="replace")[-2000:]
            time.sleep(0.1)
        assert gw_addr_file.exists(), (
            "gateway never bound: " +
            (tmp_path / "gw.log").read_text(errors="replace")[-2000:])
        gw_addr = gw_addr_file.read_text()
        gw_pubkey = str(gw_addr_file) + ".pubkey"

        # Mint credentials against the live gateway (the gen-code flow),
        # over the same encrypted wire the pool will use.
        boot = NativeTelegramClient(server_addr=gw_addr, wire="mtproto",
                                    server_pubkey_file=gw_pubkey,
                                    conn_id="topo-boot")
        try:
            generate_pcode(
                tdlib_dir=str(tdlib_dir),
                env={"TG_API_ID": "7", "TG_PHONE_NUMBER": "+15550004444",
                     "TG_PHONE_CODE": "6060"},
                client=boot)
        finally:
            boot.close()

        procs.append(_spawn(
            ["--mode", "orchestrator", "--urls", "topoa",
             "--bus-address", bus_addr, "--crawl-id", "topo2",
             "--storage-root", str(tmp_path / "ostore"),
             "--max-depth", "1", "--skip-media", "--log-level", "info"],
            tmp_path / "orch.log"))
        procs.append(_spawn(
            ["--mode", "tpu-worker", "--infer-model", "tiny",
             "--bus-address", bus_addr,
             "--storage-root", str(tmp_path / "tpustore"),
             "--log-level", "info"],
            tmp_path / "tpu.log", env=_cpu_env()))
        procs.append(_spawn(
            ["--mode", "worker", "--worker-id", "w1",
             "--bus-address", bus_addr, "--crawl-id", "topo2",
             "--dc-address", gw_addr, "--dc-wire", "mtproto",
             "--dc-pubkey-file", gw_pubkey,
             "--tdlib-dir", str(tdlib_dir),
             "--storage-root", str(tmp_path / "wstore"),
             "--skip-media", "--infer", "--log-level", "info"],
            tmp_path / "worker.log", env=_cpu_env()))

        deadline = time.time() + 150
        done = False
        while time.time() < deadline and not done:
            if procs[1].poll() is not None:
                break
            done = "crawl marked as completed" in \
                (tmp_path / "orch.log").read_text(errors="replace")
            time.sleep(1.0)
        orch_log = (tmp_path / "orch.log").read_text(errors="replace")
        worker_log = (tmp_path / "worker.log").read_text(errors="replace")
        assert "crawl marked as completed" in orch_log, (
            orch_log[-1500:] + "\n--- worker ---\n" + worker_log[-1500:])

        posts = sorted(p.parent.parent.name
                       for p in (tmp_path / "wstore").rglob("posts.jsonl"))
        assert posts == ["topoa", "topob"], posts

        # Inference results flowed end to end too.
        deadline = time.time() + 60
        uids = set()
        while time.time() < deadline:
            uids = set()
            for f in (tmp_path / "tpustore").rglob("*.jsonl"):
                for line in f.read_text(errors="replace").splitlines():
                    try:
                        uids.add(json.loads(line)["post_uid"])
                    except (ValueError, KeyError):
                        pass
            if len(uids) >= 5:
                break
            time.sleep(1.0)
        assert len(uids) == 5, (tmp_path / "tpu.log").read_text(
            errors="replace")[-2000:]
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)
