"""ISSUE 15: consistent-hash partitioned message bus + sharded crawl
frontier — scale the control plane 1→N brokers.

Covers:
- ring stability: same key -> same shard across ShardMap instances (and
  therefore across processes/restarts — the points are hashlib-derived,
  never Python's salted hash); adding/removing one shard moves only
  ~1/N of the keyspace;
- routing keys: work-queue frames route by the page's CHANNEL (the
  sharded-frontier lane contract), results by work-item id, record
  batches by batch id, unknown payloads by topic (ordered fallback);
- PartitionedBus semantics: routed topics land on exactly ONE shard and
  redeliveries of the same key land on the SAME shard; fan-out topics
  broadcast to every shard and subscribers dedupe to exactly one
  delivery; a dead shard's frames PARK in that shard's outbox — in
  order, never re-hashed — and replay when the shard returns;
- the loud shared-WAL rejection (validate_shard_spool_dirs + the
  PartitionedBus outbox check + the CLI's shard-address validation);
- the sharded frontier: distribute_work partitions pending pages into
  shard lanes by channel hash and round-robins across them;
- /shards over HTTP + the watch.py panel + the flight-bundle embed;
- gate plumbing: bus_shards scenario validation (unknown keys, shardless
  gate keys) and BOTH checked-in scenario acceptances
  (partitioned-steady, kill-broker-shard).
"""

import json
import threading
import time
import urllib.request

import pytest

from distributed_crawler_tpu.bus.messages import (
    TOPIC_INFERENCE_BATCHES,
    TOPIC_RESULTS,
    TOPIC_WORK_QUEUE,
    TOPIC_WORKER_STATUS,
)
from distributed_crawler_tpu.bus.outbox import OutboxConfig
from distributed_crawler_tpu.bus.partition import (
    BROADCAST_TOPICS,
    PartitionedBus,
    ShardMap,
    channel_of,
    default_shard_ids,
    routing_key,
    shard_spool_dirs,
    validate_shard_spool_dirs,
)
from distributed_crawler_tpu.utils.metrics import MetricsRegistry


class _FakeEndpoint:
    """Bus-shaped endpoint: records publishes, dispatches to local
    subscribers, and can be 'killed' (publish raises, like a BusHandle
    whose server is down)."""

    def __init__(self):
        self.published = []
        self.subs = {}
        self.down = False
        self.address = "fake:0"
        self.generation = 1
        self.server = object()

    def publish(self, topic, payload):
        if self.down:
            raise RuntimeError("bus is down")
        self.published.append((topic, payload))
        for h in self.subs.get(topic, []):
            h(payload)

    def subscribe(self, topic, handler):
        self.subs.setdefault(topic, []).append(handler)

    def pending_count(self, topic):
        return 0

    def kill(self):
        self.down = True
        self.server = None

    def restart(self):
        self.down = False
        self.server = object()
        self.generation += 1


def _pbus(n=3, registry=None, **kw):
    eps = {sid: _FakeEndpoint() for sid in default_shard_ids(n)}
    bus = PartitionedBus(eps, registry=registry or MetricsRegistry(),
                         close_endpoints=False, **kw)
    return bus, eps


# ---------------------------------------------------------------------------
# ShardMap: the ring
# ---------------------------------------------------------------------------
class TestShardMap:
    KEYS = [f"key-{i}" for i in range(4000)]

    def test_same_key_same_shard_across_instances(self):
        # Two independently built rings (== two processes / a restart)
        # must agree on every key: the points are hashlib-derived.
        a = ShardMap(default_shard_ids(4))
        b = ShardMap(default_shard_ids(4))
        assert [a.shard_for(k) for k in self.KEYS] == \
            [b.shard_for(k) for k in self.KEYS]

    def test_spread_is_roughly_uniform(self):
        spread = ShardMap(default_shard_ids(4)).spread(self.KEYS)
        assert set(spread) == set(default_shard_ids(4))
        ideal = len(self.KEYS) / 4
        for sid, n in spread.items():
            assert 0.5 * ideal < n < 1.7 * ideal, spread

    def test_adding_one_shard_moves_about_one_nth(self):
        m4 = ShardMap(default_shard_ids(4))
        m5 = ShardMap(default_shard_ids(5))
        moved = sum(1 for k in self.KEYS
                    if m4.shard_for(k) != m5.shard_for(k))
        frac = moved / len(self.KEYS)
        # Theory: ~1/5 of keys move to the new shard; anything near a
        # full re-deal (modulo hashing would move ~4/5) is a ring bug.
        assert 0.05 < frac < 0.40, frac
        # and every moved key moved TO the new shard, never between
        # old shards (the incremental-migration property).
        for k in self.KEYS:
            if m4.shard_for(k) != m5.shard_for(k):
                assert m5.shard_for(k) == "bus-4"

    def test_removing_one_shard_only_redistributes_its_keys(self):
        m4 = ShardMap(default_shard_ids(4))
        m3 = ShardMap(default_shard_ids(3))
        for k in self.KEYS:
            if m4.shard_for(k) != "bus-3":
                assert m3.shard_for(k) == m4.shard_for(k)

    def test_duplicate_and_empty_ids_rejected(self):
        with pytest.raises(ValueError):
            ShardMap(["a", "a"])
        with pytest.raises(ValueError):
            ShardMap([])


# ---------------------------------------------------------------------------
# routing keys
# ---------------------------------------------------------------------------
class TestRoutingKey:
    def test_work_queue_routes_by_channel(self):
        payload = {"item": {"id": "work_1",
                            "url": "https://t.me/SomeChannel/123"}}
        assert routing_key(TOPIC_WORK_QUEUE, payload) == "123"
        payload = {"item": {"id": "work_1",
                            "url": "https://t.me/SomeChannel"}}
        assert routing_key(TOPIC_WORK_QUEUE, payload) == "somechannel"
        # the one channel rule shared with the cluster guide
        assert channel_of("https://youtube.com/@Handle") == "handle"

    def test_result_routes_by_work_item_id(self):
        assert routing_key(TOPIC_RESULTS,
                           {"result": {"work_item_id": "w9"}}) == "w9"

    def test_batches_route_by_batch_id_and_uid(self):
        assert routing_key(TOPIC_INFERENCE_BATCHES,
                           {"batch_id": "b7", "records": []}) == "b7"
        assert routing_key("t", {"post_uid": "c1_5"}) == "c1_5"

    def test_stable_for_objects_and_redeliveries(self):
        from distributed_crawler_tpu.bus.messages import (
            WorkItem,
            WorkItemConfig,
            WorkQueueMessage,
        )

        item = WorkItem.new("https://t.me/chanA", 0, "p1", "c1",
                            "telegram", WorkItemConfig())
        msg = WorkQueueMessage.new(item)
        # Object and its dict form (a redelivered frame) key identically.
        assert routing_key(TOPIC_WORK_QUEUE, msg) == \
            routing_key(TOPIC_WORK_QUEUE, msg.to_dict()) == "chana"

    def test_unknown_payload_falls_back_to_topic(self):
        assert routing_key("weird-topic", {"x": 1}) == "weird-topic"
        assert routing_key("weird-topic", "not-a-dict") == "weird-topic"


# ---------------------------------------------------------------------------
# the loud shared-WAL rejection
# ---------------------------------------------------------------------------
class TestSpoolDirValidation:
    def test_derived_dirs_are_distinct(self, tmp_path):
        dirs = shard_spool_dirs(str(tmp_path), default_shard_ids(3))
        assert len(set(dirs.values())) == 3

    def test_shared_dir_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="share one spool"):
            validate_shard_spool_dirs({"bus-0": str(tmp_path),
                                       "bus-1": str(tmp_path)})

    def test_empty_dir_rejected(self):
        with pytest.raises(ValueError, match="no spool directory"):
            validate_shard_spool_dirs({"bus-0": "/x", "bus-1": ""})

    def test_partitioned_bus_rejects_shared_outbox_wal(self, tmp_path):
        eps = {sid: _FakeEndpoint() for sid in default_shard_ids(2)}
        with pytest.raises(ValueError, match="share one spool"):
            PartitionedBus(
                eps, registry=MetricsRegistry(),
                outbox=lambda sid: OutboxConfig(dir=str(tmp_path)))

    def test_partitioned_bus_rejects_partial_durability(self, tmp_path):
        eps = {sid: _FakeEndpoint() for sid in default_shard_ids(2)}
        with pytest.raises(ValueError, match="every shard or none"):
            PartitionedBus(
                eps, registry=MetricsRegistry(),
                outbox=lambda sid: OutboxConfig(
                    dir=str(tmp_path / sid) if sid == "bus-0" else ""))

    def test_cli_shard_address_validation(self):
        from distributed_crawler_tpu.cli import (
            CliConfigError,
            _parse_shard_addresses,
        )

        class R:
            def __init__(self, addrs, shards=0):
                self._a, self._s = addrs, shards

            def get(self, key, default=None):
                return self._a if key == "bus.shard_addresses" else default

            def get_int(self, key, default=0):
                return self._s if key == "bus.shards" else default

            def get_str(self, key, default=""):
                return default

        assert _parse_shard_addresses(R("a:1,b:2")) == ["a:1", "b:2"]
        assert _parse_shard_addresses(R(["a:1", "b:2"], 2)) == \
            ["a:1", "b:2"]
        with pytest.raises(CliConfigError, match="mismatched"):
            _parse_shard_addresses(R("a:1,b:2", shards=3))
        with pytest.raises(CliConfigError, match="duplicate"):
            _parse_shard_addresses(R("a:1,a:1"))
        with pytest.raises(CliConfigError, match="needs"):
            _parse_shard_addresses(R("", shards=3))

    def test_cli_rejects_bus_address_plus_shard_addresses(self):
        from distributed_crawler_tpu.cli import CliConfigError, _make_bus

        class R:
            def get(self, key, default=None):
                return "a:1,b:2" if key == "bus.shard_addresses" \
                    else default

            def get_int(self, key, default=0):
                return default

            def get_str(self, key, default=""):
                return "c:3" if key == "distributed.bus_address" \
                    else default

        with pytest.raises(CliConfigError, match="mutually exclusive"):
            _make_bus(R())

    def test_autoscaler_children_dial_every_shard(self):
        from distributed_crawler_tpu.orchestrator.autoscaler import (
            default_subprocess_argv,
        )

        argv = default_subprocess_argv(
            "tpu", "", shard_addresses=["a:1", "b:2", "c:3"])
        joined = " ".join(argv)
        assert "--bus-shard-addresses a:1,b:2,c:3" in joined
        assert "--bus-shards 3" in joined
        assert "--bus-address" not in joined
        # single-broker shape unchanged
        argv = default_subprocess_argv("tpu", "h:1")
        assert "--bus-address h:1" in " ".join(argv)


# ---------------------------------------------------------------------------
# PartitionedBus: routing, broadcast dedupe, failover parking
# ---------------------------------------------------------------------------
class TestPartitionedBus:
    def test_routed_topic_lands_on_exactly_one_shard(self):
        bus, eps = _pbus(3)
        try:
            for i in range(30):
                bus.publish(TOPIC_INFERENCE_BATCHES,
                            {"batch_id": f"b{i}", "records": []})
            assert bus.drain_outboxes(5.0)
            total = sum(len(ep.published) for ep in eps.values())
            assert total == 30
            counts = bus.routed_counts(TOPIC_INFERENCE_BATCHES)
            assert sum(counts.values()) == 30
            assert len([c for c in counts.values() if c]) >= 2, counts
        finally:
            bus.close()

    def test_same_key_always_same_shard(self):
        bus, eps = _pbus(3)
        try:
            for _ in range(5):  # redeliveries of one batch id
                bus.publish(TOPIC_INFERENCE_BATCHES,
                            {"batch_id": "stable", "records": []})
            assert bus.drain_outboxes(5.0)
            landed = [sid for sid, ep in eps.items()
                      for t, _ in ep.published
                      if t == TOPIC_INFERENCE_BATCHES]
            assert len(set(landed)) == 1 and len(landed) == 5
        finally:
            bus.close()

    def test_broadcast_reaches_every_shard_but_delivers_once(self):
        bus, eps = _pbus(3)
        try:
            got = []
            bus.subscribe(TOPIC_WORKER_STATUS, got.append)
            bus.publish(TOPIC_WORKER_STATUS, {"worker_id": "w1"})
            assert bus.drain_outboxes(5.0)
            # every shard carries a copy (a dead shard can't black-hole
            # telemetry) ...
            for ep in eps.values():
                assert sum(1 for t, _ in ep.published
                           if t == TOPIC_WORKER_STATUS) == 1
            # ... but the subscriber saw exactly one, stamp stripped.
            deadline = time.monotonic() + 2.0
            while len(got) < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.1)  # would-be duplicates arrive late
            assert len(got) == 1, got
            assert got[0] == {"worker_id": "w1"}
        finally:
            bus.close()

    def test_broadcast_topics_cover_the_fanout_set(self):
        # The classification is the contract: every announce topic must
        # broadcast (a routed heartbeat would pin telemetry to one
        # shard's liveness).
        assert TOPIC_WORKER_STATUS in BROADCAST_TOPICS
        assert TOPIC_WORK_QUEUE not in BROADCAST_TOPICS
        assert TOPIC_INFERENCE_BATCHES not in BROADCAST_TOPICS

    def test_dead_shard_parks_frames_in_order_no_rehash(self):
        bus, eps = _pbus(3)
        try:
            sid = bus.shard_for_key("stable")
            # redeliveries keep landing on `sid` even while it is down
            eps[sid].kill()
            for i in range(4):
                bus.publish(TOPIC_INFERENCE_BATCHES,
                            {"batch_id": "stable", "records": [],
                             "seq": i})
            time.sleep(0.3)  # flusher retries against the dead shard
            assert bus.outbox_depth() >= 1
            # no frame leaked to a live shard (no silent re-hash)
            for other, ep in eps.items():
                if other != sid:
                    assert not [t for t, _ in ep.published
                                if t == TOPIC_INFERENCE_BATCHES]
            eps[sid].restart()
            assert bus.drain_outboxes(10.0)
            seqs = [p.get("seq") for t, p in eps[sid].published
                    if t == TOPIC_INFERENCE_BATCHES]
            assert seqs == [0, 1, 2, 3]  # parked AND ordered
        finally:
            bus.close()

    def test_per_shard_breaker_targets(self):
        registry = MetricsRegistry()
        bus, eps = _pbus(2, registry=registry)
        try:
            eps["bus-1"].down = True
            bus.publish(TOPIC_INFERENCE_BATCHES,
                        {"batch_id": "k", "records": []})
            sid = bus.shard_for_key("k")
            if sid != "bus-1":
                eps["bus-0"].down = True
            deadline = time.monotonic() + 5.0
            gauge = registry.gauge("resilience_circuit_state")
            while time.monotonic() < deadline:
                states = {lbl.get("target"): v
                          for lbl, v in gauge.series() if lbl}
                if states.get(sid):
                    break
                time.sleep(0.05)
            states = {lbl.get("target"): v
                      for lbl, v in gauge.series() if lbl}
            # the dead shard's breaker opened under ITS OWN target name;
            # the healthy shard's (if present) stayed closed.
            assert states.get(sid) == 1.0, states
            other = next(s for s in eps if s != sid)
            assert states.get(other) in (None, 0.0), states
        finally:
            for ep in eps.values():
                ep.down = False
            bus.close()

    def test_snapshot_shape_and_json_safety(self):
        bus, eps = _pbus(2)
        try:
            bus.enable_pull(TOPIC_INFERENCE_BATCHES)
            bus.publish(TOPIC_INFERENCE_BATCHES,
                        {"batch_id": "b", "records": []})
            bus.publish(TOPIC_WORKER_STATUS, {"worker_id": "w"})
            assert bus.drain_outboxes(5.0)
            snap = json.loads(json.dumps(bus.snapshot()))
            assert set(snap["shards"]) == {"bus-0", "bus-1"}
            row = snap["shards"]["bus-0"]
            for key in ("address", "generation", "alive", "outbox_depth",
                        "breaker", "routed_frames", "pending"):
                assert key in row, row
            assert snap["ring"]["replicas"] >= 1
            assert snap["broadcast_frames"] == 1
            assert TOPIC_INFERENCE_BATCHES in snap["pull_topics"]
        finally:
            bus.close()

    def test_broadcast_survives_minority_outbox_failure(self):
        # One shard down with a FULL (1-frame) outbox: a broadcast must
        # still succeed — subscribers attach to every shard, so one
        # live copy is delivery — and raising after siblings enqueued
        # would make the caller retry into a duplicate (fresh bcast id).
        eps = {sid: _FakeEndpoint() for sid in default_shard_ids(3)}
        bus = PartitionedBus(
            eps, registry=MetricsRegistry(), close_endpoints=False,
            outbox=lambda sid: OutboxConfig(max_frames=1))
        try:
            got = []
            bus.subscribe(TOPIC_WORKER_STATUS, got.append)
            eps["bus-1"].kill()
            bus.publish(TOPIC_WORKER_STATUS, {"worker_id": "a"})  # fills
            time.sleep(0.2)
            bus.publish(TOPIC_WORKER_STATUS, {"worker_id": "b"})  # full
            deadline = time.monotonic() + 3.0
            while len(got) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            time.sleep(0.1)
            assert [p["worker_id"] for p in got] == ["a", "b"], got
        finally:
            bus.close()

    def test_broadcast_skips_open_breaker_shard_no_stale_parking(self):
        # A shard known-dead (breaker OPEN) must not accumulate parked
        # broadcast copies: they would outlive the dedupe window and
        # replay as stale duplicate commands at restart.  Routed frames
        # still park (ordering demands it).
        registry = MetricsRegistry()
        bus, eps = _pbus(2, registry=registry)
        try:
            eps["bus-1"].kill()
            # trip bus-1's breaker with a routed frame owned by it
            key = next(k for k in ("k0", "k1", "k2", "k3", "k4")
                       if bus.shard_for_key(k) == "bus-1")
            bus.publish(TOPIC_INFERENCE_BATCHES,
                        {"batch_id": key, "records": []})
            deadline = time.monotonic() + 5.0
            ob1 = bus._outboxes["bus-1"]
            while ob1.circuit_state != "open" \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ob1.circuit_state == "open"
            depth_before = ob1.depth()
            for i in range(5):
                bus.publish(TOPIC_WORKER_STATUS, {"worker_id": f"w{i}"})
            assert ob1.depth() == depth_before  # no broadcast parking
            # the live shard carried every copy
            assert bus.drain_outboxes(5.0) or True
            n_live = sum(1 for t, _ in eps["bus-0"].published
                         if t == TOPIC_WORKER_STATUS)
            deadline = time.monotonic() + 3.0
            while n_live < 5 and time.monotonic() < deadline:
                time.sleep(0.05)
                n_live = sum(1 for t, _ in eps["bus-0"].published
                             if t == TOPIC_WORKER_STATUS)
            assert n_live == 5, n_live
        finally:
            eps["bus-1"].restart()
            bus.close()

    def test_broadcast_raises_only_when_every_shard_rejects(self):
        from distributed_crawler_tpu.bus.outbox import OutboxFull

        eps = {sid: _FakeEndpoint() for sid in default_shard_ids(2)}
        bus = PartitionedBus(
            eps, registry=MetricsRegistry(), close_endpoints=False,
            outbox=lambda sid: OutboxConfig(max_frames=1))
        try:
            for ep in eps.values():
                ep.kill()
            bus.publish(TOPIC_WORKER_STATUS, {"worker_id": "a"})
            time.sleep(0.2)  # flushers stuck: both outboxes stay full
            with pytest.raises(OutboxFull):
                bus.publish(TOPIC_WORKER_STATUS, {"worker_id": "b"})
        finally:
            for ep in eps.values():
                ep.restart()
            bus.close()

    def test_dlq_snapshot_merges_topics_across_shards(self):
        bus, eps = _pbus(2)
        try:
            bodies = {
                "bus-0": {"enabled": True, "dead_letters_total": 2,
                          "topics": {"t": {"count": 2, "pending": 1,
                                           "entries": [{"id": "a"}]}}},
                "bus-1": {"enabled": True, "dead_letters_total": 1,
                          "topics": {"t": {"count": 1, "pending": 1,
                                           "entries": [{"id": "b"}]}}},
            }
            for sid, ep in eps.items():
                ep.dlq_snapshot = \
                    lambda topic=None, id=None, _b=bodies[sid]: _b
            body = bus.dlq_snapshot()
            assert body["dead_letters_total"] == 3
            assert body["topics"]["t"]["count"] == 3
            assert body["topics"]["t"]["pending"] == 2
            shards_seen = {e["shard"]
                           for e in body["topics"]["t"]["entries"]}
            assert shards_seen == {"bus-0", "bus-1"}
        finally:
            bus.close()

    def test_manual_ack_rejected_on_broadcast(self):
        bus, _ = _pbus(2)
        try:
            with pytest.raises(ValueError, match="auto-ack"):
                bus.subscribe(TOPIC_WORKER_STATUS, lambda p, a: None,
                              manual_ack=True)
        finally:
            bus.close()


# ---------------------------------------------------------------------------
# sharded frontier: distribute_work lanes
# ---------------------------------------------------------------------------
class TestShardedFrontier:
    def _orchestrator(self, bus, tmp_path):
        from distributed_crawler_tpu.config.crawler import CrawlerConfig
        from distributed_crawler_tpu.orchestrator import Orchestrator
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        sm = CompositeStateManager(StateConfig(
            crawl_id="c1", crawl_execution_id="e1",
            storage_root=str(tmp_path / "state"),
            sql=SqlConfig(url=":memory:")))
        cfg = CrawlerConfig(crawl_id="c1", platform="telegram",
                            skip_media_download=True,
                            sampling_method="channel")
        return Orchestrator("c1", cfg, bus, sm,
                            registry=MetricsRegistry())

    def test_lanes_partition_and_interleave(self, tmp_path):
        from distributed_crawler_tpu.bus.inmemory import InMemoryBus
        from distributed_crawler_tpu.utils import flight

        inner = InMemoryBus(sync=True)

        class ShardedBus:
            """InMemoryBus wearing a shard map (the OutboxBus/ChaosBus
            delegation shape the orchestrator sees in production)."""

            shard_map = ShardMap(default_shard_ids(3))

            def __getattr__(self, name):
                return getattr(inner, name)

        bus = ShardedBus()
        orch = self._orchestrator(bus, tmp_path)
        published = []
        inner.subscribe(TOPIC_WORK_QUEUE,
                        lambda p: published.append(p))
        channels = [f"https://t.me/chan{i}" for i in range(9)]
        flight.configure(capacity=512)
        orch.start(channels, background=False)
        try:
            orch.distribute_work()
            assert len(published) == 9
            smap = bus.shard_map
            lanes = [smap.shard_for(channel_of(p["work_item"]["url"]))
                     for p in published]
            # every page went out, lanes interleave (the dispatch order
            # can't be one lane's full run followed by the next's unless
            # everything hashed to one lane)
            status = orch.get_status()
            assert status["frontier_lanes"] is not None
            assert sum(status["frontier_lanes"].values()) == 9
            if len(set(lanes)) > 1:
                first_lane_run = len([1 for s in lanes
                                      if s == lanes[0]])
                assert lanes[1] != lanes[0] or first_lane_run < 9
            kinds = [e for e in flight.RECORDER.events()
                     if e.get("kind") == "frontier_shards"]
            assert kinds and kinds[-1]["lanes"] == \
                status["frontier_lanes"]
        finally:
            orch.stop()
            inner.close()

    def test_no_shard_map_is_identity(self, tmp_path):
        from distributed_crawler_tpu.bus.inmemory import InMemoryBus

        inner = InMemoryBus(sync=True)
        orch = self._orchestrator(inner, tmp_path)
        orch.start(["https://t.me/only"], background=False)
        try:
            orch.distribute_work()
            assert orch.get_status()["frontier_lanes"] is None
        finally:
            orch.stop()
            inner.close()


# ---------------------------------------------------------------------------
# /shards surface + watch panel + bundle embed
# ---------------------------------------------------------------------------
class TestShardsSurface:
    def test_shards_endpoint_over_http(self):
        from distributed_crawler_tpu.utils.metrics import (
            clear_shards_provider,
            serve_metrics,
            set_shards_provider,
        )

        bus, _ = _pbus(2)
        registry = MetricsRegistry()
        server = serve_metrics(0, registry)
        port = server.server_address[1]
        try:
            # no provider yet -> 404
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/shards", timeout=5)
            set_shards_provider(bus.snapshot)
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/shards", timeout=5))
            assert set(body["shards"]) == {"bus-0", "bus-1"}
        finally:
            clear_shards_provider(bus.snapshot)
            server.shutdown()
            bus.close()

    def test_bundle_embeds_shards(self):
        from distributed_crawler_tpu.utils import flight
        from distributed_crawler_tpu.utils.metrics import (
            clear_shards_provider,
            set_shards_provider,
        )

        bus, _ = _pbus(2)
        set_shards_provider(bus.snapshot)
        try:
            bundle = flight.RECORDER.bundle("test")
            assert "bus_shards" in bundle
            assert set(bundle["bus_shards"]["shards"]) == \
                {"bus-0", "bus-1"}
        finally:
            clear_shards_provider(bus.snapshot)
            bus.close()

    def test_watch_renders_shards_panel(self):
        import tools.watch as watch

        bus, eps = _pbus(2)
        try:
            eps["bus-1"].kill()
            out = watch.render_dashboard(None, None, None, now=1000.0,
                                         shards=bus.snapshot())
            assert "bus shards — 2 shard(s)" in out
            assert "DOWN" in out and "bus-0" in out
        finally:
            bus.close()


# ---------------------------------------------------------------------------
# grpc e2e: two real shards, kill one, park + replay
# ---------------------------------------------------------------------------
class TestGrpcShardFailover:
    def test_kill_one_shard_park_and_replay(self, tmp_path):
        pytest.importorskip("grpc")
        from distributed_crawler_tpu.bus.grpc_bus import (
            GrpcBusServer,
            RemoteBus,
        )
        from distributed_crawler_tpu.loadgen.gate import BusHandle

        sids = default_shard_ids(2)
        spools = shard_spool_dirs(str(tmp_path / "spool"), sids)
        handles = {}
        for sid in sids:
            h = BusHandle(lambda bind, _s=spools[sid]: GrpcBusServer(
                bind or "127.0.0.1:0", spool_dir=_s, ack_timeout_s=5.0))
            h.enable_pull(TOPIC_INFERENCE_BATCHES)
            h.start()
            handles[sid] = h
        ring = ShardMap(sids)
        local = PartitionedBus(
            handles, ring,
            outbox=lambda sid: OutboxConfig(
                dir=str(tmp_path / "outbox" / sid), max_frames=64,
                breaker_recovery_s=0.2),
            registry=MetricsRegistry(), close_endpoints=False)
        worker = PartitionedBus(
            {sid: RemoteBus(handles[sid].address) for sid in sids},
            ring, registry=MetricsRegistry())
        got = []
        lock = threading.Lock()

        def _handler(payload, ack):
            with lock:
                got.append(payload["batch_id"])
            ack(True)

        worker.subscribe(TOPIC_INFERENCE_BATCHES, _handler,
                         manual_ack=True)
        try:
            keys = [f"b{i}" for i in range(10)]
            victim = sids[0]
            victim_keys = [k for k in keys
                           if ring.shard_for(k) == victim]
            assert victim_keys, "seeded keys must cover both shards"
            for k in keys[:5]:
                local.publish(TOPIC_INFERENCE_BATCHES,
                              {"batch_id": k, "records": []})
            assert local.drain_outboxes(10.0)
            handles[victim].kill()
            for k in keys[5:]:
                local.publish(TOPIC_INFERENCE_BATCHES,
                              {"batch_id": k, "records": []})
            # survivors' share flows while the victim's share parks
            # (generous deadlines: this 1-core container times out
            # early under concurrent suite load)
            deadline = time.monotonic() + 20.0
            live_keys = [k for k in keys
                         if ring.shard_for(k) != victim]
            while time.monotonic() < deadline:
                with lock:
                    if set(live_keys) <= set(got):
                        break
                time.sleep(0.05)
            with lock:
                assert set(live_keys) <= set(got), (got, live_keys)
            handles[victim].restart()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                with lock:
                    if set(got) == set(keys):
                        break
                time.sleep(0.05)
            with lock:
                # zero lost, zero duplicated, across the shard's
                # generation boundary
                assert sorted(got) == sorted(keys), got
            assert handles[victim].generation == 2
            assert handles[sids[1]].generation == 1
        finally:
            worker.close()
            local.close()
            for h in handles.values():
                h.close()


# ---------------------------------------------------------------------------
# wedged-channel self-healing (found live driving a killed shard)
# ---------------------------------------------------------------------------
class TestChannelSelfHealing:
    def test_rebuild_after_sustained_failures_with_cooldown(self):
        grpc = pytest.importorskip("grpc")
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient

        cli = GrpcBusClient("127.0.0.1:1")  # nothing listens here
        try:
            for _ in range(GrpcBusClient.REBUILD_AFTER_FAILURES):
                with pytest.raises(grpc.RpcError):
                    cli.publish("t", {"x": 1})
            assert cli.rebuilds == 1
            # The cooldown rate-limits: another burst inside the window
            # must NOT rebuild again (an outage longer than the window
            # pays one cheap rebuild per window, not one per RPC).
            for _ in range(GrpcBusClient.REBUILD_AFTER_FAILURES):
                with pytest.raises(grpc.RpcError):
                    cli.publish("t", {"x": 1})
            assert cli.rebuilds == 1
        finally:
            cli.close()

    def test_success_resets_the_failure_count(self):
        pytest.importorskip("grpc")
        from distributed_crawler_tpu.bus.grpc_bus import (
            GrpcBusClient,
            GrpcBusServer,
        )

        server = GrpcBusServer("127.0.0.1:0")
        server.enable_pull(TOPIC_INFERENCE_BATCHES)
        server.start()
        cli = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
        try:
            cli.publish(TOPIC_INFERENCE_BATCHES, {"batch_id": "b"})
            assert cli._consecutive_failures == 0
            assert cli.rebuilds == 0
        finally:
            cli.close()
            server.close(grace=0.1)


# ---------------------------------------------------------------------------
# gate plumbing + scenario acceptances
# ---------------------------------------------------------------------------
class TestGateValidation:
    def _base(self, **kw):
        sc = {"name": "t", "bus": "grpc", "bus_shards": {"count": 3},
              "gate": {}}
        sc.update(kw)
        return sc

    def test_unknown_bus_shards_key_rejected(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        with pytest.raises(ValueError, match="unknown bus_shards"):
            validate_gate_config(
                self._base(bus_shards={"count": 3,
                                       "spool_dir": "/shared"}))

    def test_shards_need_grpc(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        with pytest.raises(ValueError, match="grpc"):
            validate_gate_config(self._base(bus="inmemory"))

    def test_shard_gate_keys_need_block(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        sc = {"name": "t", "bus": "grpc",
              "gate": {"max_shard_skew": 2.0}}
        with pytest.raises(ValueError, match="bus_shards"):
            validate_gate_config(sc)

    def test_generation_map_must_cover_every_shard(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        sc = self._base()
        sc["gate"] = {"bus_shard_generations": {"bus-0": 1}}
        with pytest.raises(ValueError, match="EVERY shard"):
            validate_gate_config(sc)
        sc["gate"] = {"bus_shard_generations":
                      {"bus-0": 1, "bus-1": 2, "bus-2": 1}}
        validate_gate_config(sc)

    def test_checked_in_scenarios_validate(self):
        from distributed_crawler_tpu.loadgen.gate import (
            load_scenario,
            validate_gate_config,
        )

        for name in ("partitioned-steady", "kill-broker-shard"):
            validate_gate_config(load_scenario(name))


class TestScenarioAcceptance:
    def test_partitioned_steady_passes(self):
        pytest.importorskip("grpc")
        from distributed_crawler_tpu.loadgen.gate import (
            load_scenario,
            run_scenario,
        )

        verdict = run_scenario(load_scenario("partitioned-steady"))
        assert verdict["status"] == "pass", json.dumps(verdict)[:2000]
        assert verdict["bus_shards"]["count"] == 3
        assert sum(verdict["bus_shards"]["routed_batches"].values()) > 0

    def test_kill_broker_shard_passes(self):
        pytest.importorskip("grpc")
        from distributed_crawler_tpu.loadgen.gate import (
            load_scenario,
            run_scenario,
        )

        verdict = run_scenario(load_scenario("kill-broker-shard"))
        assert verdict["status"] == "pass", json.dumps(verdict)[:2000]
        assert verdict["bus_shards"]["generations"] == \
            {"bus-0": 1, "bus-1": 2, "bus-2": 1}
        assert verdict["lost"] == 0 and verdict["duplicates"] == 0
