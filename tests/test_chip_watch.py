"""chip_watch.sh recovery path: proves it refreshes AND commits the bench
TPU cache (VERDICT r04 weak #2 — the old script ran the sweeps but never
bench.py, so a healthy window between driver rounds still left
bench_tpu_cache.json absent).

Drives `chip_watch.sh --dry-run` in a throwaway git repo with a stub
"python" that emulates the three harnesses — in particular, the bench stub
writes bench_tpu_cache.json the way the real bench.py does on a live TPU
measurement — then asserts the cache file exists and was committed.
"""

import json
import os
import stat
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "chip_watch.sh")

STUB = """#!/bin/bash
# Stub harness runner: last arg names the harness (or bench.py).
case "${@: -1}" in
  *exp_mfu.py)  echo '{"variant": "base-b256", "mfu": 0.31}' ;;
  *exp_int8.py) echo '{"cfg": "e5_small", "quant": "int8"}' ;;
  *bench.py)
    echo '{"platform": "tpu", "posts_per_sec": 10793.0}' > bench_tpu_cache.json
    echo '{"metric": "posts_per_sec", "value": 10793.0, "unit": "posts/sec"}'
    ;;
  *) exit 9 ;;
esac
"""


@pytest.fixture
def watch_repo(tmp_path):
    repo = tmp_path / "repo"
    (repo / "tools").mkdir(parents=True)
    stub = tmp_path / "stubpython"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    for cmd in (["git", "init", "-q"],
                ["git", "config", "user.email", "t@t"],
                ["git", "config", "user.name", "t"]):
        subprocess.run(cmd, cwd=repo, check=True)
    (repo / "README").write_text("x")
    subprocess.run(["git", "add", "."], cwd=repo, check=True)
    subprocess.run(["git", "commit", "-qm", "init"], cwd=repo, check=True)
    return repo, stub


def _run_dry(repo, stub, commit="1"):
    env = dict(os.environ,
               CHIP_WATCH_REPO=str(repo),
               CHIP_WATCH_PY=str(stub),
               CHIP_WATCH_OUT="docs/sweeps",
               CHIP_WATCH_COMMIT=commit)
    return subprocess.run(["bash", SCRIPT, "--dry-run"], env=env,
                          capture_output=True, text=True, timeout=60)


def test_dry_run_writes_and_commits_cache(watch_repo):
    repo, stub = watch_repo
    proc = _run_dry(repo, stub)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    cache = repo / "bench_tpu_cache.json"
    assert cache.exists(), "recovery path must refresh the bench cache"
    assert json.loads(cache.read_text())["platform"] == "tpu"
    # Sweep outputs land in the tracked sweeps dir.
    sweeps = list((repo / "docs" / "sweeps").iterdir())
    names = sorted(p.name.split("_2")[0] for p in sweeps)
    assert names == ["bench", "exp_int8", "exp_mfu"]
    # The capture was committed: a fresh clone keeps the TPU number.
    log = subprocess.run(["git", "log", "--oneline", "--name-only"],
                         cwd=repo, capture_output=True, text=True).stdout
    assert "chip-watch: TPU measurement capture" in log
    assert "bench_tpu_cache.json" in log


def test_sweeps_commit_even_if_bench_leg_wedges(watch_repo, tmp_path):
    """A bench leg that re-wedges (no cache written) must not cost the
    completed sweeps their commit — the pathspec list is built dynamically."""
    repo, stub = watch_repo
    wedged = tmp_path / "wedgedpython"
    wedged.write_text(STUB.replace(
        "echo '{\"platform\": \"tpu\", \"posts_per_sec\": 10793.0}' "
        "> bench_tpu_cache.json\n", "exit 124\n"))
    wedged.chmod(wedged.stat().st_mode | stat.S_IEXEC)
    proc = _run_dry(repo, wedged)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert not (repo / "bench_tpu_cache.json").exists()
    log = subprocess.run(["git", "log", "--oneline", "--name-only"],
                         cwd=repo, capture_output=True, text=True).stdout
    assert "chip-watch: TPU measurement capture" in log
    assert "exp_mfu" in log and "exp_int8" in log
    # The wedged leg's zero-byte tee artifact is pruned, not committed.
    assert "bench_2" not in log
    assert not list((repo / "docs" / "sweeps").glob("bench_*"))


def test_dry_run_commit_disabled(watch_repo):
    repo, stub = watch_repo
    proc = _run_dry(repo, stub, commit="0")
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert (repo / "bench_tpu_cache.json").exists()
    log = subprocess.run(["git", "log", "--oneline"], cwd=repo,
                         capture_output=True, text=True).stdout
    assert "chip-watch" not in log
