"""The deployable DCT gateway (`dct --mode dc-gateway`) — VERDICT r03 #3:
the production counterpart of the C++ client's remote mode, plus the
gen-code → credentials.json → pool-consumes bootstrap (VERDICT r03 #8;
reference parity: `standalone/runner.go:77-192`,
`telegramhelper/client.go:121-142,319-377`).
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from distributed_crawler_tpu.clients.dc_gateway import (
    DcGateway,
    load_accounts,
)
from distributed_crawler_tpu.clients.native import (
    NativeTelegramClient,
    TelegramError,
    find_library,
    load_credentials,
    native_client_factory,
)

SEED = json.dumps({
    "channels": [{
        "username": "gwchan",
        "id": 777,
        "title": "Gateway Channel",
        "member_count": 1200,
        "messages": [
            {"content": {"@type": "messageText",
                         "text": {"text": f"gw message {i}"}},
             "date": 1700000000 + i, "view_count": i}
            for i in range(4)
        ],
    }],
})

ACCOUNTS = {
    "+15550001111": {"code": "24680", "password": ""},
    "+15550002222": {"code": "13579", "password": "hunter2"},
}


def _lib_available() -> bool:
    try:
        find_library()
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _lib_available(), reason="libdct_client.so not built")


class TestAccountsTable:
    def test_load_accounts_file(self, tmp_path):
        p = tmp_path / "accounts.json"
        p.write_text(json.dumps({"accounts": [
            {"phone_number": "+1555", "code": "1", "password": "pw"},
            {"phone_number": "+1666", "code": "2"},
        ]}))
        acc = load_accounts(str(p))
        assert acc == {"+1555": {"code": "1", "password": "pw"},
                       "+1666": {"code": "2", "password": ""}}

    def test_bare_list_accepted(self, tmp_path):
        p = tmp_path / "accounts.json"
        p.write_text(json.dumps(
            [{"phone_number": "+1777", "code": "9"}]))
        assert load_accounts(str(p))["+1777"]["code"] == "9"

    def test_missing_phone_rejected(self, tmp_path):
        p = tmp_path / "accounts.json"
        p.write_text(json.dumps([{"code": "9"}]))
        with pytest.raises(ValueError, match="phone_number"):
            load_accounts(str(p))

    def test_per_account_auth(self):
        gw = DcGateway(seed_json=SEED, accounts=ACCOUNTS).start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, conn_id="a1")
            try:
                c.authenticate("+15550001111", "24680")
                c.wait_ready(5.0)
                assert c.search_public_chat("gwchan").id == 777
            finally:
                c.close()
            # Second account requires ITS code and password.
            c = NativeTelegramClient(server_addr=gw.address, conn_id="a2")
            try:
                with pytest.raises(TelegramError,
                                   match="PHONE_CODE_INVALID"):
                    c.authenticate("+15550002222", "24680")
                c._call({"@type": "checkAuthenticationCode",
                         "code": "13579"})
                c._call({"@type": "checkAuthenticationPassword",
                         "password": "hunter2"})
                c.wait_ready(5.0)
                assert c.search_public_chat("gwchan").id == 777
            finally:
                c.close()
        finally:
            gw.close()
        assert gw.auth_successes == 2
        assert gw.auth_failures == 1

    def test_unknown_phone_rejected(self):
        gw = DcGateway(seed_json=SEED, accounts=ACCOUNTS).start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, conn_id="u1")
            try:
                with pytest.raises(TelegramError,
                                   match="PHONE_NUMBER_INVALID"):
                    c.authenticate("+19990000000", "24680")
            finally:
                c.close()
        finally:
            gw.close()
        assert gw.auth_failures == 1
        assert gw.auth_successes == 0


class TestStatusAndStore:
    def test_status_map(self):
        gw = DcGateway(seed_json=SEED, expected_code="1").start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, conn_id="s1")
            try:
                c.authenticate("+1555", "1")
                c.wait_ready(5.0)
                c.search_public_chat("gwchan")
                st = gw.status()
                assert st["component"] == "dc-gateway"
                assert st["connections_total"] == 1
                assert st["active_sessions"] == 1
                assert st["auth_successes"] == 1
                assert st["requests_served"] >= 1
            finally:
                c.close()
        finally:
            gw.close()

    def test_seed_source_store_root(self, tmp_path):
        """Tarball/dir/json store materialized per session under the
        persistent store root (server-side `acquire_seed_db` flow)."""
        seed_path = tmp_path / "store.json"
        seed_path.write_text(SEED)
        store_root = tmp_path / "stores"
        gw = DcGateway(seed_source=str(seed_path),
                       store_root=str(store_root),
                       expected_code="1").start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, conn_id="st1")
            try:
                c.authenticate("+1555", "1")
                c.wait_ready(5.0)
                assert c.search_public_chat("gwchan").title == \
                    "Gateway Channel"
            finally:
                c.close()
        finally:
            gw.close()
        assert any(d.startswith("conn_") for d in os.listdir(store_root))

    def test_address_file(self, tmp_path):
        addr_file = tmp_path / "gw.addr"
        gw = DcGateway(seed_json=SEED, port=0,
                       address_file=str(addr_file))
        try:
            assert addr_file.read_text() == gw.address
        finally:
            gw.close()


class TestGenCodeBootstrap:
    """`dct --mode gen-code` against the gateway mints credentials.json;
    the pool consumes it (VERDICT r03 #8 'Done' criterion)."""

    def test_gen_code_against_gateway_then_pool(self, tmp_path):
        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.clients.pool import ConnectionPool

        gw = DcGateway(seed_json=SEED, accounts=ACCOUNTS).start()
        tdlib_dir = tmp_path / "tdlib"
        try:
            rc = main(["--mode", "gen-code",
                       "--dc-address", gw.address,
                       "--tdlib-dir", str(tdlib_dir)],
                      env={"TG_API_ID": "12345", "TG_API_HASH": "h",
                           "TG_PHONE_NUMBER": "+15550001111",
                           "TG_PHONE_CODE": "24680"})
            assert rc == 0
            creds_path = tdlib_dir / "credentials.json"
            assert creds_path.exists()
            assert (os.stat(creds_path).st_mode & 0o777) == 0o600
            creds = load_credentials(str(tdlib_dir))
            assert creds["phone_number"] == "+15550001111"

            # The pool consumes the minted credentials: every connection
            # dials the gateway and walks the ladder before handout.
            factory = native_client_factory(
                server_addr=gw.address, credentials=creds)
            pool = ConnectionPool(factory,
                                  database_urls=[gw.address] * 2)
            assert pool.initialize() == 2
            conn = pool.acquire()
            try:
                assert conn.client.search_public_chat("gwchan").id == 777
            finally:
                pool.release(conn)
            pool.close_all()
            # gen-code session + 2 pool sessions all authenticated.
            assert gw.auth_successes == 3
        finally:
            gw.close()

    def test_gen_code_2fa_account(self, tmp_path):
        """TG_PASSWORD drives the 2FA leg and is persisted so pools can
        replay it (the gap the r04 review caught)."""
        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.clients.pool import ConnectionPool

        gw = DcGateway(seed_json=SEED, accounts=ACCOUNTS).start()
        tdlib_dir = tmp_path / "td2fa"
        try:
            rc = main(["--mode", "gen-code",
                       "--dc-address", gw.address,
                       "--tdlib-dir", str(tdlib_dir)],
                      env={"TG_API_ID": "1", "TG_API_HASH": "h",
                           "TG_PHONE_NUMBER": "+15550002222",
                           "TG_PHONE_CODE": "13579",
                           "TG_PASSWORD": "hunter2"})
            assert rc == 0
            creds = load_credentials(str(tdlib_dir))
            assert creds["password"] == "hunter2"
            factory = native_client_factory(
                server_addr=gw.address, credentials=creds)
            pool = ConnectionPool(factory, database_urls=[gw.address])
            assert pool.initialize() == 1
            conn = pool.acquire()
            try:
                assert conn.client.search_public_chat("gwchan").id == 777
            finally:
                pool.release(conn)
            pool.close_all()
        finally:
            gw.close()

    def test_gen_code_wrong_code_fails(self, tmp_path):
        from distributed_crawler_tpu.cli import main

        gw = DcGateway(seed_json=SEED, accounts=ACCOUNTS).start()
        try:
            rc = main(["--mode", "gen-code",
                       "--dc-address", gw.address,
                       "--tdlib-dir", str(tmp_path / "t")],
                      env={"TG_API_ID": "12345",
                           "TG_PHONE_NUMBER": "+15550001111",
                           "TG_PHONE_CODE": "99999"})
            assert rc == 2
            assert not (tmp_path / "t" / "credentials.json").exists()
        finally:
            gw.close()

    def test_gen_code_offline_engine(self, tmp_path, monkeypatch):
        """Without --dc-address the embedded auth-enabled engine drives
        the ladder (the original --generate-code path)."""
        from distributed_crawler_tpu.cli import main

        monkeypatch.chdir(tmp_path)
        rc = main(["--mode", "gen-code",
                   "--tdlib-dir", str(tmp_path / "td")],
                  env={"TG_API_ID": "777", "TG_PHONE_NUMBER": "+1555",
                       "TG_PHONE_CODE": "1"})
        assert rc == 0
        assert (tmp_path / "td" / "credentials.json").exists()


class TestRemotePoolFromConfig:
    def test_setup_pool_remote_mode(self, tmp_path):
        """setup_pool_from_config with dc_address dials the gateway using
        stored credentials (the full config-driven remote pool path)."""
        from distributed_crawler_tpu.clients.native import generate_pcode
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl import (
            get_connection_from_pool,
            setup_pool_from_config,
            shutdown_connection_pool,
        )

        gw = DcGateway(seed_json=SEED, expected_code="555").start()
        tdlib_dir = str(tmp_path / "td")
        try:
            generate_pcode(
                tdlib_dir=tdlib_dir,
                env={"TG_API_ID": "1", "TG_PHONE_NUMBER": "+1555",
                     "TG_PHONE_CODE": "555"},
                client=NativeTelegramClient(server_addr=gw.address,
                                            conn_id="boot"))
            cfg = CrawlerConfig(dc_address=gw.address, concurrency=2,
                                tdlib_dir=tdlib_dir)
            assert setup_pool_from_config(cfg)
            conn = get_connection_from_pool()
            try:
                assert conn.client.search_public_chat("gwchan").id == 777
            finally:
                from distributed_crawler_tpu.crawl.runner import (
                    release_connection_to_pool,
                )
                release_connection_to_pool(conn)
        finally:
            shutdown_connection_pool()
            gw.close()


class TestCliCrawlThroughGateway:
    def test_standalone_crawl_via_dc_address(self, tmp_path):
        """The full config path: `dct --urls … --dc-address …` builds a
        REMOTE pool from stored credentials and runs the standalone crawl
        through the gateway — no code injection anywhere."""
        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.clients.native import generate_pcode

        gw = DcGateway(
            seed_json=TestTwoProcessE2E.CRAWL_SEED,
            accounts={"+15557770000": {"code": "321", "password": ""}},
        ).start()
        tdlib_dir = str(tmp_path / "td")
        out_root = str(tmp_path / "out")
        try:
            generate_pcode(
                tdlib_dir=tdlib_dir,
                env={"TG_API_ID": "9", "TG_PHONE_NUMBER": "+15557770000",
                     "TG_PHONE_CODE": "321"},
                client=NativeTelegramClient(server_addr=gw.address,
                                            conn_id="cli-boot"))
            rc = main(["--urls", "gwroot", "--storage-root", out_root,
                       "--dc-address", gw.address,
                       "--tdlib-dir", tdlib_dir,
                       "--crawl-id", "cli-gw", "--skip-media",
                       "--max-depth", "1"])
            assert rc == 0
            posts = []
            for dirpath, _dn, files in os.walk(out_root):
                for f in files:
                    if f == "posts.jsonl":
                        with open(os.path.join(dirpath, f)) as fh:
                            posts += [json.loads(x) for x in fh]
            # The root channel's post crawled through the wire.
            assert [p["description"] for p in posts] == ["hi @gwleaf"]
            assert posts[0]["channel_name"] == "Root"
            assert gw.auth_successes >= 2  # gen-code + pool connection(s)
        finally:
            gw.close()


class TestGatewayRestartResilience:
    def test_pool_recreates_after_gateway_restart(self, tmp_path):
        """Gateway dies mid-session → calls fail fast; after it returns on
        the same port, pool.recreate() dials and re-authenticates (the
        reference's connection error-recreate path,
        `connection_pool.go:346-413`, at the wire level)."""
        from distributed_crawler_tpu.clients.pool import ConnectionPool

        gw = DcGateway(seed_json=SEED, expected_code="11").start()
        port = gw.port
        creds = {"api_id": "1", "api_hash": "", "phone_number": "+1555",
                 "phone_code": "11", "password": ""}
        factory = native_client_factory(
            server_addr=gw.address, credentials=creds)
        pool = ConnectionPool(factory, database_urls=[gw.address])
        assert pool.initialize() == 1
        conn = pool.acquire()
        try:
            assert conn.client.search_public_chat("gwchan").id == 777
        finally:
            pool.release(conn)
        gw.close()  # yank the server
        conn = pool.acquire()
        with pytest.raises(TelegramError):
            conn.client.search_public_chat("gwchan")
        # Close the dead client so its half-open socket finishes the TCP
        # teardown — otherwise the server port sits in FIN_WAIT2 for
        # tcp_fin_timeout and the restart below can't bind.
        conn.client.close()
        # Gateway returns on the SAME port (bind retries while the dead
        # server's sockets drain); recreate dials + re-auths.
        deadline = time.time() + 15
        while True:
            try:
                gw2 = DcGateway(seed_json=SEED, expected_code="11",
                                port=port).start()
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.3)
        try:
            # Still holding the broken conn from above: recreate in place.
            fresh = pool.recreate(conn)
            assert fresh.client.search_public_chat("gwchan").id == 777
            pool.release(fresh)
            assert gw2.auth_successes == 1
        finally:
            pool.close_all()
            gw2.close()


@pytest.mark.skipif(shutil.which("openssl") is None,
                    reason="openssl binary needed for the TLS leg")
class TestTwoProcessE2E:
    """VERDICT r03 #3 'Done' criterion: a SEPARATE gateway process, real
    TLS sockets, a full crawl through it."""

    CRAWL_SEED = json.dumps({
        "channels": [
            {"username": "gwroot", "title": "Root", "member_count": 800,
             "messages": [
                 {"date": 1700000000, "view_count": 5,
                  "content": {"@type": "messageText",
                              "text": {"text": "hi @gwleaf",
                                       "entities": [
                                           {"type": {"@type":
                                                     "textEntityTypeMention"},
                                            "offset": 3, "length": 7}]}}},
             ]},
            {"username": "gwleaf", "title": "Leaf", "member_count": 50,
             "messages": [
                 {"date": 1700000050, "view_count": 1,
                  "content": {"@type": "messageText",
                              "text": {"text": "leaf", "entities": []}}},
             ]},
        ],
    })

    def test_crawl_through_gateway_process_over_tls(self, tmp_path):
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl.runner import run_for_channel
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        seed_file = tmp_path / "seed.json"
        seed_file.write_text(self.CRAWL_SEED)
        addr_file = tmp_path / "gw.addr"
        accounts_file = tmp_path / "accounts.json"
        accounts_file.write_text(json.dumps({"accounts": [
            {"phone_number": "+15559990000", "code": "424242"}]}))

        proc = subprocess.Popen(
            [sys.executable, "-m", "distributed_crawler_tpu.cli",
             "--mode", "dc-gateway",
             "--gateway-listen", "127.0.0.1:0",
             "--gateway-address-file", str(addr_file),
             "--gateway-tls",
             "--gateway-accounts", str(accounts_file),
             "--gateway-seed-json", f"@{seed_file}",
             "--storage-root", str(tmp_path / "gwroot")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        try:
            deadline = time.time() + 30
            while not addr_file.exists() and time.time() < deadline:
                assert proc.poll() is None, (
                    f"gateway died: {proc.stderr.read().decode()[-2000:]}")
                time.sleep(0.1)
            assert addr_file.exists(), "gateway never wrote address file"
            address = addr_file.read_text()

            client = NativeTelegramClient(
                server_addr=address, tls=True, tls_insecure=True,
                sni="localhost", conn_id="e2e")
            try:
                client.authenticate("+15559990000", "424242")
                client.wait_ready(5.0)

                sm = CompositeStateManager(StateConfig(
                    crawl_id="gwe2e", crawl_execution_id="x1",
                    storage_root=str(tmp_path / "out"),
                    sql=SqlConfig(url=":memory:")))
                sm.initialize(["gwroot"])
                cfg = CrawlerConfig(crawl_id="gwe2e",
                                    skip_media_download=True)
                page = sm.get_layer_by_depth(0)[0]
                discovered = run_for_channel(client, page, "", sm, cfg)
                assert page.status == "fetched"
                assert {p.url for p in discovered} == {"gwleaf"}
                jsonl = (tmp_path / "out" / "gwe2e" / "gwroot" / "posts"
                         / "posts.jsonl")
                posts = [json.loads(line)
                         for line in jsonl.read_text().splitlines()]
                assert len(posts) == 1
                sm.close()
            finally:
                client.close()
        finally:
            # SIGTERM (the supervisor's stop signal) takes the graceful
            # close path via _serve_forever's handler.
            proc.terminate()
            try:
                rc = proc.wait(timeout=10)
                assert rc == 130  # KeyboardInterrupt exit path
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)


class TestAuthDeadlineAndTlsShutdown:
    """Round-4 review regressions: (a) close() must reach TLS sessions
    (wrap_socket detaches the raw socket the accept loop tracked); (b) the
    auth deadline is ABSOLUTE over the ladder — dripping junk frames (or
    bytes) must not keep resetting an idle window."""

    def test_close_terminates_live_tls_session(self):
        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       tls=True).start()
        c = NativeTelegramClient(server_addr=gw.address, conn_id="tc1",
                                 tls=True, tls_insecure=True)
        try:
            c.authenticate("+15550001111", "13579")
            # The server bumps active_sessions just AFTER replying to the
            # final ladder step — poll briefly instead of racing it.
            deadline = time.time() + 3.0
            while (time.time() < deadline
                   and gw.status()["active_sessions"] != 1):
                time.sleep(0.05)
            assert gw.status()["active_sessions"] == 1
            gw.close()
            deadline = time.time() + 3.0
            while (time.time() < deadline
                   and gw.status()["active_sessions"] != 0):
                time.sleep(0.05)
            assert gw.status()["active_sessions"] == 0, \
                "TLS session survived gateway close()"
        finally:
            try:
                c.close()
            except Exception:
                pass

    def test_auth_deadline_is_absolute_under_frame_drip(self):
        import socket as socket_mod
        import ssl as ssl_mod
        import struct

        gw = DcGateway(seed_json=SEED, expected_code="13579", tls=True,
                       auth_timeout_s=1.5).start()
        try:
            ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl_mod.CERT_NONE
            raw = socket_mod.create_connection((gw.host, gw.port),
                                               timeout=10)
            s = ctx.wrap_socket(raw)

            def frame(payload: bytes) -> bytes:
                return struct.pack(">I", len(payload)) + payload

            s.sendall(frame(json.dumps({"@type": "handshake"}).encode()))
            s.settimeout(1.0)
            t0 = time.time()
            dropped_at = None
            # Drip a junk frame every 0.5 s: each recv under the pre-fix
            # per-recv timeout opened a fresh 1.5 s idle window, so the
            # connection would live indefinitely.
            for i in range(14):
                try:
                    s.sendall(frame(json.dumps({"@type": "junk"}).encode()))
                    s.recv(65536)
                except (OSError, ssl_mod.SSLError):
                    dropped_at = time.time() - t0
                    break
                time.sleep(0.5)
            assert dropped_at is not None, (
                "unauthenticated dripper survived 7s against a 1.5s "
                "auth deadline")
            assert dropped_at < 5.0, f"dropped too late: {dropped_at:.1f}s"
        finally:
            gw.close()


class TestConnectionCap:
    def test_flood_beyond_cap_is_rejected(self):
        """The watchdog bounds unauthenticated thread LIFETIME; the cap
        bounds their COUNT — a connect flood beyond it is closed
        immediately and counted, while existing sessions keep working."""
        import socket as socket_mod

        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       max_connections=2).start()
        held = []
        try:
            # Two idle connections occupy the cap.
            for _ in range(2):
                s = socket_mod.create_connection((gw.host, gw.port),
                                                 timeout=5)
                held.append(s)
            time.sleep(0.2)  # accept loop registers both threads
            # The third is closed by the gateway without service.
            s3 = socket_mod.create_connection((gw.host, gw.port), timeout=5)
            s3.settimeout(5.0)
            assert s3.recv(1) == b""  # immediate orderly close
            s3.close()
            deadline = time.time() + 2.0
            while (time.time() < deadline
                   and gw.status()["rejected_connections"] < 1):
                time.sleep(0.05)
            st = gw.status()
            assert st["rejected_connections"] >= 1
            # A fresh connection gets real service once the slots free.
            # The serve threads must first observe the closes and be
            # reaped, so retry until the ladder succeeds (a fixed sleep
            # here is a race on a loaded host).
            for s in held:
                s.close()
            held.clear()
            deadline = time.time() + 10.0
            while True:
                c = NativeTelegramClient(server_addr=gw.address,
                                         conn_id="cap1")
                try:
                    c.authenticate("+15550001111", "13579")
                    break
                except TelegramError:
                    c.close()
                    if time.time() > deadline:
                        raise
                    time.sleep(0.2)
            assert c.search_public_chat("gwchan").id == 777
            c.close()
        finally:
            for s in held:
                s.close()
            gw.close()


class TestFloodWaitOverWire:
    """VERDICT r04 #8: Telegram's rate discipline emulated AT THE GATEWAY —
    a pooled connection dialing the real wire gets a >=300 s FLOOD_WAIT on
    SearchPublicChat and is retired (`crawl/runner.go:1333-1337` +
    `connection_pool.go:421-439`), while the crawl continues on the
    remaining connections.  Until now flood injection existed only
    in-process (`clients/sim.py`); this drives it through the socket."""

    RW_SEED = json.dumps({
        "channels": [
            {"username": "rwroot", "id": 9100, "title": "RW Root",
             "member_count": 5000,
             "messages": [
                 {"date": 1700000100, "view_count": 7,
                  "content": {"@type": "messageText",
                              "text": {"text": "go see @rwnext",
                                       "entities": [
                                           {"type": {"@type":
                                                     "textEntityTypeMention"},
                                            "offset": 7, "length": 7}]}}},
             ]},
            {"username": "rwnext", "id": 9101, "title": "RW Next",
             "member_count": 4000,
             "messages": [
                 {"date": 1700000200, "view_count": 1,
                  "content": {"@type": "messageText",
                              "text": {"text": "next", "entities": []}}},
             ]},
        ],
    })

    ACCOUNTS = {"+15551110001": {"code": "111", "password": ""},
                "+15551110002": {"code": "222", "password": ""}}

    def test_pooled_connection_retired_crawl_continues(self, tmp_path):
        from distributed_crawler_tpu.clients.pool import ConnectionPool
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl import runner as crawl_runner
        from distributed_crawler_tpu.crawl.errors import (
            FloodWaitRetireError,
        )
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )
        from distributed_crawler_tpu.state.datamodels import Page, new_id

        gw = DcGateway(
            seed_json=self.RW_SEED, accounts=self.ACCOUNTS,
            store_root=str(tmp_path / "gw"),
            # Account 1's SECOND SearchPublicChat is over quota (the first
            # resolves the page's own channel): 400 s > the 300 s retire
            # threshold, so the outlink-validation search trips the retire.
            flood={"+15551110001": {"wait_s": 400, "after_requests": 1,
                                    "methods": ["searchPublicChat"]}},
        ).start()
        clients = {}
        try:
            for i, (phone, acc) in enumerate(sorted(self.ACCOUNTS.items())):
                c = NativeTelegramClient(server_addr=gw.address,
                                         conn_id=f"fw{i}")
                c.authenticate(phone, acc["code"])
                c.wait_ready(5.0)
                clients[f"fw{i}"] = c
            pool = ConnectionPool.for_testing(clients)
            crawl_runner.init_connection_pool(pool)
            sm = CompositeStateManager(StateConfig(
                crawl_id="fwwire", crawl_execution_id="x1",
                storage_root=str(tmp_path / "out"),
                sampling_method="random-walk",
                sql=SqlConfig(url=":memory:")))
            sm.initialize(["rwroot"])
            cfg = CrawlerConfig(crawl_id="fwwire", skip_media_download=True,
                                sampling_method="random-walk")
            page = sm.get_layer_by_depth(0)[0]
            # fw0 (the flooded account) is handed out first and hits the
            # 400 s FLOOD_WAIT on the wire during outlink validation.
            with pytest.raises(FloodWaitRetireError):
                crawl_runner.run_for_channel_with_pool(
                    page, str(tmp_path / "out"), sm, cfg)
            stats = pool.stats()
            assert stats["retired"] == 1 and stats["live"] == 1
            assert gw.status()["flood_rejections"] >= 1
            # The crawl continues on the remaining connection: a retry of
            # the same channel succeeds end to end (search included).
            page2 = Page(id=new_id(), url="rwroot", depth=0,
                         sequence_id=new_id())
            crawl_runner.run_for_channel_with_pool(
                page2, str(tmp_path / "out"), sm, cfg)
            assert page2.status == "fetched"
            assert sm.is_discovered_channel("rwnext")
        finally:
            crawl_runner.shutdown_connection_pool()
            gw.close()


class TestDcMigration:
    """Telegram's DC topology: accounts live on a home DC; dialing the
    wrong one gets 303 PHONE_MIGRATE_X at the phone step and the client
    reconnects via its DC table (Telegram's config dcOptions analog) —
    the flow TDLib performs internally for the reference
    (`telegramhelper/client.go:319-377` drives the ladder over it)."""

    SEED2 = json.dumps({"channels": [{
        "username": "dc2chan", "id": 2200, "title": "DC2 Channel",
        "member_count": 300,
        "messages": [{"content": {"@type": "messageText",
                                  "text": {"text": "hello from dc2"}},
                      "date": 1700000000, "view_count": 2}],
    }]})

    def test_phone_migrate_followed_via_dc_table(self, tmp_path):
        # DC1 knows the account but homes it on DC2; DC2 serves it.
        acct = {"+15559990000": {"code": "777", "password": "",
                                 "dc_id": 2}}
        acct_home = {"+15559990000": {"code": "777", "password": ""}}
        gw1 = DcGateway(seed_json=SEED, accounts=acct, dc_id=1,
                        wire="mtproto",
                        store_root=str(tmp_path / "dc1")).start()
        gw2 = DcGateway(seed_json=self.SEED2, accounts=acct_home, dc_id=2,
                        wire="mtproto",
                        store_root=str(tmp_path / "dc2")).start()
        try:
            table = {"2": {"address": gw2.address,
                           "pubkey_file": gw2.pubkey_file}}
            c = NativeTelegramClient(
                server_addr=gw1.address, wire="mtproto",
                server_pubkey_file=gw1.pubkey_file,
                dc_table=table, conn_id="mig1")
            try:
                c.authenticate("+15559990000", "777")
                c.wait_ready(5.0)
                assert c.current_dc == 2
                # Service comes from DC2's store now.
                assert c.search_public_chat("dc2chan").id == 2200
            finally:
                c.close()
            assert gw1.status()["migrations_issued"] == 1
            assert gw1.status()["auth_successes"] == 0
            assert gw2.status()["auth_successes"] == 1
        finally:
            gw1.close()
            gw2.close()

    def test_migrate_without_table_surfaces_error(self, tmp_path):
        acct = {"+15559990000": {"code": "777", "password": "",
                                 "dc_id": 2}}
        gw1 = DcGateway(seed_json=SEED, accounts=acct, dc_id=1,
                        store_root=str(tmp_path / "dc1")).start()
        try:
            c = NativeTelegramClient(server_addr=gw1.address,
                                     conn_id="mig2")
            try:
                with pytest.raises(TelegramError,
                                   match="PHONE_MIGRATE_2"):
                    c.authenticate("+15559990000", "777")
            finally:
                c.close()
        finally:
            gw1.close()

    def test_accounts_file_carries_dc_id(self, tmp_path):
        p = tmp_path / "accounts.json"
        p.write_text(json.dumps([
            {"phone_number": "+1555", "code": "1", "dc_id": 3},
            {"phone_number": "+1666", "code": "2"},
        ]))
        acc = load_accounts(str(p))
        assert acc["+1555"]["dc_id"] == 3
        assert "dc_id" not in acc["+1666"]
