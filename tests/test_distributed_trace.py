"""Distributed trace collection, occupancy accounting, and /dtraces.

Covers the PR-9 observability layer: SpanBatchMessage codec/bus
round-trips (`bus/messages.py`), the SpanExporter's cursor/sampling/
bounding (`utils/trace.py`), TraceCollector assembly with deliberately
skewed worker clocks (`orchestrator/tracecollect.py`), DeviceTimeline /
QueueDepthSampler math on synthetic timelines (`utils/occupancy.py`),
the ``/dtraces`` endpoint over real HTTP, the critpath/trace-dump
renderers, and the acceptance scenario: an orchestrator + TPU worker on
one in-memory bus producing ONE assembled trace whose spans originate
from both processes.
"""

import json
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from distributed_crawler_tpu.bus import InMemoryBus
from distributed_crawler_tpu.bus.codec import (
    MESSAGE_REGISTRY,
    RecordBatch,
    decode_frame,
    decode_message,
    encode_frame,
)
from distributed_crawler_tpu.bus.messages import (
    MSG_SPAN_BATCH,
    TOPIC_INFERENCE_BATCHES,
    TOPIC_SPANS,
    SpanBatchMessage,
    pubsub_topics,
)
from distributed_crawler_tpu.datamodel.post import Post
from distributed_crawler_tpu.inference.worker import (
    TPUWorker,
    TPUWorkerConfig,
)
from distributed_crawler_tpu.orchestrator.tracecollect import TraceCollector
from distributed_crawler_tpu.utils import trace
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    clear_dtraces_provider,
    serve_metrics,
    set_dtraces_provider,
)
from distributed_crawler_tpu.utils.occupancy import (
    DeviceTimeline,
    QueueDepthSampler,
    merged_length,
)

import tools.critpath as critpath
import tools.trace_dump as trace_dump


def span_row(name="tpu_worker.process", trace_id="t1", span_id="s1",
             parent_id="", start_wall=1000.0, duration_ms=10.0, **attrs):
    return {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "start_wall": start_wall,
            "duration_ms": duration_ms, "attrs": attrs}


def make_batch(n=3, crawl_id="c1"):
    return RecordBatch.from_posts(
        [Post(post_uid=f"p{i}", channel_name="chan",
              description=f"text {i}") for i in range(n)],
        crawl_id=crawl_id)


class FakeEngine:
    """Engine double: enough surface for TPUWorker, no jax."""

    def __init__(self):
        self.cfg = SimpleNamespace(model="fake-tiny")

    def run(self, texts):
        return [{"label": 0, "score": 1.0} for _ in texts]


# ---------------------------------------------------------------------------
class TestSpanBatchMessage:
    def test_dict_round_trip(self):
        msg = SpanBatchMessage.new("tpu-1", [span_row()], dropped=2)
        msg.validate()
        rt = SpanBatchMessage.from_dict(msg.to_dict())
        assert rt.worker_id == "tpu-1"
        assert rt.dropped == 2
        assert rt.sent_wall == msg.sent_wall
        assert rt.spans[0]["name"] == "tpu_worker.process"
        assert rt.trace_id == msg.trace_id

    def test_frame_codec_round_trip(self):
        msg = SpanBatchMessage.new("tpu-1", [span_row()])
        payload, rest = decode_frame(encode_frame(msg.to_dict()))
        assert not rest
        decoded = decode_message(payload)
        assert isinstance(decoded, SpanBatchMessage)
        assert decoded.worker_id == "tpu-1"
        assert len(decoded) == 1

    def test_registered_and_topic_listed(self):
        assert MESSAGE_REGISTRY[MSG_SPAN_BATCH] is SpanBatchMessage
        assert TOPIC_SPANS in pubsub_topics()

    def test_validate_rejects_bad_shapes(self):
        with pytest.raises(ValueError):
            SpanBatchMessage.new("", [span_row()]).validate()
        with pytest.raises(ValueError):
            SpanBatchMessage.new("w", [{"no_name": True}]).validate()
        bad = SpanBatchMessage.new("w", [])
        bad.message_type = "heartbeat"
        with pytest.raises(ValueError):
            bad.validate()

    def test_bus_round_trip(self):
        bus = InMemoryBus()
        got = []
        bus.subscribe(TOPIC_SPANS, lambda p: got.append(
            SpanBatchMessage.from_dict(p)))
        bus.publish(TOPIC_SPANS,
                    SpanBatchMessage.new("w9", [span_row()]).to_dict())
        assert len(got) == 1 and got[0].worker_id == "w9"


# ---------------------------------------------------------------------------
class TestSpanExporter:
    def test_ships_only_spans_completed_after_construction(self):
        tracer = trace.Tracer(capacity=64)
        with tracer.span("old"):
            pass
        exp = trace.SpanExporter(tracer=tracer)
        spans, dropped = exp.collect()
        assert spans == [] and dropped == 0
        with tracer.span("fresh"):
            pass
        spans, dropped = exp.collect()
        assert [s.name for s in spans] == ["fresh"] and dropped == 0
        # Nothing new: the cursor advanced.
        assert exp.collect() == ([], 0)

    def test_max_spans_bound_keeps_newest_and_counts_dropped(self):
        tracer = trace.Tracer(capacity=64)
        exp = trace.SpanExporter(tracer=tracer, max_spans=2)
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        spans, dropped = exp.collect()
        assert [s.name for s in spans] == ["s3", "s4"]
        assert dropped == 3

    def test_ring_eviction_counts_as_dropped(self):
        tracer = trace.Tracer(capacity=2)
        exp = trace.SpanExporter(tracer=tracer)
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        spans, dropped = exp.collect()
        assert len(spans) == 2 and dropped == 3

    def test_sampling_is_stable_per_trace_across_exporters(self):
        tracer = trace.Tracer(capacity=512)
        a = trace.SpanExporter(tracer=tracer, sample_rate=0.5)
        b = trace.SpanExporter(tracer=tracer, sample_rate=0.5)
        decisions_a = [a.keeps(f"trace_{i}") for i in range(200)]
        decisions_b = [b.keeps(f"trace_{i}") for i in range(200)]
        assert decisions_a == decisions_b       # shared subset
        assert any(decisions_a) and not all(decisions_a)  # actually samples
        assert not a.keeps("")  # untraced spans never ship

    def test_sample_rate_zero_drops_everything(self):
        tracer = trace.Tracer(capacity=64)
        exp = trace.SpanExporter(tracer=tracer, sample_rate=0.0)
        with tracer.span("x"):
            pass
        spans, dropped = exp.collect()
        assert spans == [] and dropped == 1

    def test_ownership_prefix_filter_excludes_foreign_spans(self):
        tracer = trace.Tracer(capacity=64)
        exp = trace.SpanExporter(tracer=tracer,
                                 name_prefixes=("asr_worker.",
                                                "media.reentry"))
        for name in ("asr_worker.process", "media.reentry",
                     "engine.compute", "bus.deliver"):
            with tracer.span(name):
                pass
        spans, dropped = exp.collect()
        # Foreign spans are someone else's to ship — excluded, NOT
        # counted as dropped.
        assert sorted(s.name for s in spans) == \
            ["asr_worker.process", "media.reentry"]
        assert dropped == 0

    def test_span_from_dict_inverts_to_dict(self):
        s = trace.Span(name="n", trace_id="t", span_id="s",
                       parent_id="p", start_wall=12.5, duration_s=0.25,
                       attrs={"k": 1})
        rt = trace.span_from_dict(s.to_dict())
        assert (rt.name, rt.trace_id, rt.span_id, rt.parent_id) == \
            ("n", "t", "s", "p")
        assert rt.start_wall == 12.5
        assert abs(rt.duration_s - 0.25) < 1e-9


# ---------------------------------------------------------------------------
class TestDeviceTimeline:
    def _tl(self, clk):
        return DeviceTimeline(registry=MetricsRegistry(), window_s=60.0,
                              clock=lambda: clk[0])

    def test_empty_snapshot_is_empty(self):
        assert self._tl([0.0]).snapshot() == {}

    def test_busy_overlap_bubble_math(self):
        clk = [0.0]
        tl = self._tl(clk)
        clk[0] = 2.0
        tl.record(0.0, 2.0)
        clk[0] = 3.0
        tl.record(1.0, 3.0)      # overlaps [1, 2]
        clk[0] = 6.0
        tl.record(5.0, 6.0)      # 2 s gap -> bubble
        clk[0] = 10.0
        snap = tl.snapshot()
        # union [0,3]+[5,6] = 4 s over a 10 s window
        assert abs(snap["busy_fraction"] - 0.4) < 1e-6
        # total 5 s, union 4 s -> 1/5 overlapped
        assert abs(snap["overlap_fraction"] - 0.2) < 1e-6
        assert abs(snap["bubble_ms_total"] - 2000.0) < 1e-6
        # bubble 2 s vs active (union 4 + bubble 2)
        assert abs(snap["bubble_share"] - 2.0 / 6.0) < 1e-6
        assert snap["batches"] == 3

    def test_stream_boundary_gap_is_not_a_bubble(self):
        clk = [0.0]
        tl = self._tl(clk)
        clk[0] = 1.0
        tl.record(0.0, 1.0)
        tl.start_stream()        # queue ran dry
        clk[0] = 31.0
        tl.record(30.0, 31.0)    # 29 s idle, zero bubble
        assert tl.snapshot()["bubble_ms_total"] == 0.0

    def test_reset_clears_everything(self):
        clk = [1.0]
        tl = self._tl(clk)
        tl.record(0.0, 1.0)
        clk[0] = 3.0
        tl.record(2.5, 3.0)
        tl.reset()
        assert tl.snapshot() == {}

    def test_window_pruning_decays_busy_fraction(self):
        clk = [1.0]
        tl = self._tl(clk)
        tl.record(0.0, 1.0)
        clk[0] = 120.0           # interval aged out of the 60 s window
        snap = tl.snapshot()
        assert snap["batches"] == 0
        assert snap["busy_fraction"] == 0.0

    def test_merged_length(self):
        assert merged_length([]) == 0.0
        assert merged_length([(0, 2), (1, 3), (5, 6)]) == 4.0

    def test_path_labels_keep_two_timelines_distinct(self):
        # The asr-steady rig runs a text engine AND an ASR pipeline on
        # one registry: their busy gauges must be separate labeled
        # children, not one unlabeled series the two clobber.
        reg = MetricsRegistry()
        clk = [0.0]
        text = DeviceTimeline(registry=reg, window_s=60.0,
                              clock=lambda: clk[0], path="text")
        asr = DeviceTimeline(registry=reg, window_s=60.0,
                             clock=lambda: clk[0], path="asr")
        clk[0] = 1.0
        text.record(0.0, 1.0)
        clk[0] = 10.0
        asr.record(9.0, 10.0)
        text.snapshot()
        asr.snapshot()
        g = reg.gauge("tpu_engine_device_busy_fraction")
        assert g.labels(path="text").value == pytest.approx(0.1)
        assert g.labels(path="asr").value == pytest.approx(1.0)

    def test_telemetry_heartbeat_carries_occupancy(self):
        from distributed_crawler_tpu.utils.telemetry import TelemetryEmitter

        class Eng:
            def occupancy_snapshot(self):
                return {"busy_fraction": 0.5}

        snap = TelemetryEmitter(engine=Eng()).snapshot()
        assert snap["occupancy"] == {"busy_fraction": 0.5}


class TestQueueDepthSampler:
    def test_time_weighted_mean(self):
        clk = [0.0]
        reg = MetricsRegistry()
        g = reg.gauge("qd")
        s = QueueDepthSampler(g, window_s=10.0, clock=lambda: clk[0])
        clk[0] = 2.0
        s.update(4)
        clk[0] = 4.0
        s.update(0)
        clk[0] = 10.0
        # depth 0 for [0,2], 4 for [2,4], 0 for [4,10] -> 8/10
        assert abs(s.sample() - 0.8) < 1e-6
        assert abs(g.value - 0.8) < 1e-6

    def test_no_aliasing_between_edges(self):
        # The edge-triggered regression: depth spikes to 32 then drains
        # before the scrape — an edge gauge reads 0, the sampler reads
        # the window's truth.
        clk = [0.0]
        g = MetricsRegistry().gauge("qd")
        s = QueueDepthSampler(g, window_s=10.0, clock=lambda: clk[0])
        s.update(32)
        clk[0] = 5.0
        s.update(0)
        clk[0] = 10.0
        assert s.current() == 0          # the edge value (aliased read)
        assert s.sample() == pytest.approx(16.0)  # the truth

    def test_constant_depth_before_window(self):
        clk = [0.0]
        g = MetricsRegistry().gauge("qd")
        s = QueueDepthSampler(g, window_s=5.0, clock=lambda: clk[0])
        s.update(3)
        clk[0] = 100.0  # the edge aged out entirely
        assert s.sample() == pytest.approx(3.0)

    def test_update_refreshes_gauge_on_every_edge(self):
        # The gauge must not wait for the next heartbeat sample(): a
        # scrape right after an edge reads the current window mean.
        clk = [0.0]
        g = MetricsRegistry().gauge("qd")
        s = QueueDepthSampler(g, window_s=10.0, clock=lambda: clk[0])
        clk[0] = 5.0
        s.update(8)       # depth 0 for [0,5], 8 after -> mean so far 0
        clk[0] = 10.0
        s.update(8)       # 0 for [0,5], 8 for [5,10] -> mean 4
        assert g.value == pytest.approx(4.0)

    def test_incremental_integral_matches_across_pruning(self):
        # Exercise the amortized segment-sum bookkeeping across edge
        # expiry: after pruning, the mean must stay exact.
        clk = [0.0]
        g = MetricsRegistry().gauge("qd")
        s = QueueDepthSampler(g, window_s=10.0, clock=lambda: clk[0])
        for t, d in ((1.0, 2), (3.0, 6), (5.0, 0)):
            clk[0] = t
            s.update(d)
        clk[0] = 12.0  # window [2,12]: first edge aged out mid-segment
        # floor(2)*(3-2) + 6*(5-3) + 0*(12-5) = 14 over 10
        assert s.sample() == pytest.approx(1.4)


# ---------------------------------------------------------------------------
class TestTraceCollector:
    def test_skewed_clock_corrected_via_fleet_offsets(self):
        now = time.time()
        col = TraceCollector(offsets_fn=lambda: {"w-skew": 120.0},
                             process="orch", tracer=trace.Tracer(capacity=8),
                             registry=MetricsRegistry())
        msg = SpanBatchMessage.new("w-skew", [span_row(
            start_wall=now - 120.0)])
        col.observe(msg, now=now)
        t = col.export()["traces"][0]
        corrected = t["spans"][0]
        assert abs(corrected["start_wall"] - now) < 1e-6
        assert corrected["process"] == "w-skew"
        assert corrected["clock_offset_s"] == 120.0

    def test_sent_wall_fallback_when_fleet_has_no_offset(self):
        now = 10_000.0
        col = TraceCollector(offsets_fn=lambda: {}, process="orch",
                             tracer=trace.Tracer(capacity=8),
                             registry=MetricsRegistry())
        msg = SpanBatchMessage.new("w2", [span_row(start_wall=now - 60.0)])
        msg.sent_wall = now - 60.0  # sender clock 60 s behind
        col.observe(msg, now=now)
        corrected = col.export()["traces"][0]["spans"][0]
        # Offset estimated from send/receive walls: within transit slack.
        assert abs(corrected["start_wall"] - now) < 1.0

    def test_local_spans_merge_and_dedup_by_span_id(self):
        tracer = trace.Tracer(capacity=16)
        col = TraceCollector(process="orchestrator", tracer=tracer,
                             registry=MetricsRegistry())
        with tracer.span("orchestrator.dispatch", trace_id="t1"):
            pass
        local = tracer.spans()[0]
        # The worker also ships a copy of the SAME span (single-process
        # rigs see every span twice) — dedup must keep the count at 2.
        rows = [local.to_dict(), span_row(trace_id="t1", span_id="w-span")]
        col.observe(SpanBatchMessage.new("tpu-1", rows), now=time.time())
        t = col.export()["traces"][0]
        assert t["span_count"] == 2
        assert t["processes"] == ["orchestrator", "tpu-1"]

    def test_trace_lru_bound(self):
        col = TraceCollector(process="o", tracer=trace.Tracer(capacity=4),
                             max_traces=3, registry=MetricsRegistry())
        for i in range(6):
            col.observe(SpanBatchMessage.new("w", [span_row(
                trace_id=f"t{i}", span_id=f"s{i}")]), now=float(i))
        out = col.export()
        assert len(out["traces"]) == 3
        assert out["traces"][0]["trace_id"] == "t5"  # newest first

    def test_per_trace_span_bound_counts_dropped(self):
        col = TraceCollector(process="o", tracer=trace.Tracer(capacity=4),
                             max_spans_per_trace=2,
                             registry=MetricsRegistry())
        rows = [span_row(span_id=f"s{i}") for i in range(5)]
        col.observe(SpanBatchMessage.new("w", rows), now=1.0)
        t = col.export()["traces"][0]
        assert t["span_count"] == 2
        assert t["dropped_spans"] == 3

    def test_export_spans_sorted_by_corrected_wall(self):
        col = TraceCollector(process="o", tracer=trace.Tracer(capacity=4),
                             registry=MetricsRegistry())
        rows = [span_row(span_id="late", start_wall=50.0),
                span_row(span_id="early", start_wall=10.0)]
        col.observe(SpanBatchMessage.new("w", rows), now=60.0)
        t = col.export()["traces"][0]
        assert [s["span_id"] for s in t["spans"]] == ["early", "late"]


# ---------------------------------------------------------------------------
class TestDtracesEndpoint:
    def test_served_over_http_with_limit(self):
        col = TraceCollector(process="o", tracer=trace.Tracer(capacity=4),
                             registry=MetricsRegistry())
        for i in range(3):
            col.observe(SpanBatchMessage.new("w", [span_row(
                trace_id=f"t{i}", span_id=f"s{i}")]), now=float(i))
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        set_dtraces_provider(col.export)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/dtraces", timeout=5).read())
            assert len(body["traces"]) == 3
            assert body["collector_process"] == "o"
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/dtraces?limit=1",
                timeout=5).read())
            assert len(body["traces"]) == 1
        finally:
            clear_dtraces_provider(col.export)
            server.shutdown()

    def test_404_without_provider(self):
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/dtraces", timeout=5)
            assert e.value.code == 404
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
class TestWorkerSpanExport:
    def _worker(self, bus, **cfg_kw):
        return TPUWorker(bus, FakeEngine(), registry=MetricsRegistry(),
                         cfg=TPUWorkerConfig(worker_id="tpu-x",
                                             heartbeat_s=3600,
                                             stall_warn_s=0, **cfg_kw))

    def test_export_spans_publishes_batch_on_topic(self):
        trace.configure(capacity=2048)
        bus = InMemoryBus()
        got = []
        bus.subscribe(TOPIC_SPANS, lambda p: got.append(
            SpanBatchMessage.from_dict(p)))
        worker = self._worker(bus)
        worker.start()
        bus.publish(TOPIC_INFERENCE_BATCHES, make_batch().to_dict())
        assert worker.drain(timeout_s=10)
        assert worker.export_spans() > 0
        assert got and got[0].worker_id == "tpu-x"
        names = {s["name"] for s in got[0].spans}
        assert "tpu_worker.process" in names
        # The cursor advanced: nothing new to ship.
        assert worker.export_spans() == 0
        worker.stop(timeout_s=5)

    def test_span_export_cadence_decoupled_from_heartbeat(self):
        # A 3600 s heartbeat must not stretch a short export interval:
        # _wait_with_span_exports fires exports on their own cadence.
        trace.configure(capacity=2048)
        bus = InMemoryBus()
        got = []
        bus.subscribe(TOPIC_SPANS, lambda p: got.append(p))
        worker = self._worker(bus, span_export_interval_s=0.05)
        with trace.span("tpu_worker.process", trace_id="t-cadence"):
            pass
        worker._last_span_export = time.monotonic() - 1.0  # overdue
        worker._wait_with_span_exports(0.2)
        assert got, "export did not fire inside the heartbeat wait"

    def test_queue_gauge_is_time_weighted(self):
        bus = InMemoryBus()
        worker = self._worker(bus)
        worker.start()
        # A burst through the worker leaves the gauge at the window's
        # time-weighted mean (>= 0), not pinned to the last edge value —
        # and the heartbeat's resample keeps it decaying.
        bus.publish(TOPIC_INFERENCE_BATCHES, make_batch().to_dict())
        assert worker.drain(timeout_s=10)
        assert worker._depth.sample() >= 0.0
        worker.stop(timeout_s=5)


# ---------------------------------------------------------------------------
class TestRenderers:
    def _dtraces(self):
        spans = [
            span_row(name="orchestrator.dispatch", span_id="a",
                     start_wall=1000.0, duration_ms=5.0),
            span_row(name="tpu_worker.process", span_id="b", parent_id="a",
                     start_wall=1000.005, duration_ms=100.0),
            span_row(name="engine.compute", span_id="c", parent_id="b",
                     start_wall=1000.010, duration_ms=80.0),
        ]
        for s in spans:
            s["process"] = ("orchestrator" if s["span_id"] == "a"
                            else "tpu-1")
            s["clock_offset_s"] = 0.0 if s["span_id"] == "a" else 0.05
        return {"traces": [{"trace_id": "t1", "span_count": 3,
                            "processes": ["orchestrator", "tpu-1"],
                            "duration_ms": 105.0, "spans": spans}],
                "collector_process": "orchestrator",
                "workers": {"tpu-1": {"applied_offset_s": 0.05,
                                      "spans": 2, "dropped": 0}}}

    def test_critpath_attribution_and_render(self, tmp_path):
        data = self._dtraces()
        att = critpath.attribute(data)
        assert att["traces_attributed"] == 1
        assert max(att["stage_shares"], key=att["stage_shares"].get) == \
            "device"
        report = critpath.render(data)
        assert "engine.compute" in report and "device" in report
        # File + bundle loading both resolve.
        p = tmp_path / "dtraces.json"
        p.write_text(json.dumps(data))
        assert critpath.load(str(p))["traces"]
        b = tmp_path / "bundle.json"
        b.write_text(json.dumps({"schema": "dct-postmortem-v1",
                                 "dtraces": data}))
        assert critpath.load(str(b))["traces"]

    def test_critpath_selfcheck_passes(self, capsys):
        assert critpath.main(["--selfcheck"]) == 0
        assert "selfcheck ok" in capsys.readouterr().out

    def test_stage_map_covers_serving_span_names(self):
        for name, stage in (("engine.run_tokenized", "host"),
                            ("engine.run", "host"),
                            ("engine.compute", "device"),
                            ("asr.transcribe", "device"),
                            ("media.reentry", "reentry"),
                            ("tpu_worker.queue_wait", "queue_wait")):
            assert critpath.stage_of(name) == stage, name

    def test_trace_dump_collector_lanes(self, tmp_path, capsys):
        p = tmp_path / "dtraces.json"
        p.write_text(json.dumps(self._dtraces()))
        assert trace_dump.main([str(p), "--collector"]) == 0
        out = capsys.readouterr().out
        assert "lane orchestrator" in out
        assert "lane tpu-1" in out
        assert "engine.compute" in out

    def test_trace_dump_collector_empty_message(self, tmp_path, capsys):
        p = tmp_path / "empty.json"
        p.write_text(json.dumps({"traces": []}))
        assert trace_dump.main([str(p), "--collector"]) == 0
        assert "no assembled" in capsys.readouterr().out


# ---------------------------------------------------------------------------
class TestEndToEndDistributedTrace:
    """Acceptance: orchestrator + TPU worker on one in-memory bus; the
    worker ships its spans on TOPIC_SPANS, the orchestrator's collector
    assembles ONE trace whose spans originate from both processes, and
    critpath renders a bottleneck attribution for it."""

    def _sm(self, tmp_path, sub):
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        return CompositeStateManager(StateConfig(
            crawl_id="c1", crawl_execution_id="e1",
            storage_root=str(tmp_path / sub),
            sql=SqlConfig(url=":memory:")))

    def test_one_trace_spans_both_processes(self, tmp_path):
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.orchestrator import Orchestrator

        trace.configure(capacity=4096)
        bus = InMemoryBus()
        cfg = CrawlerConfig(crawl_id="c1", platform="telegram",
                            skip_media_download=True,
                            sampling_method="channel")
        orch = Orchestrator("c1", cfg, bus, self._sm(tmp_path, "orch"))
        orch.start(["chana"], background=False)
        worker = TPUWorker(
            bus, FakeEngine(), registry=MetricsRegistry(),
            cfg=TPUWorkerConfig(worker_id="tpu-e2e", heartbeat_s=3600,
                                stall_warn_s=0))
        worker.start()
        try:
            batch = make_batch()
            # The bridge's dispatch leg: the root span of the batch's
            # trace opens in the orchestrator process.
            with trace.span("orchestrator.dispatch",
                            trace_id=batch.trace_id,
                            records=len(batch.records)):
                bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
            assert worker.drain(timeout_s=10)
            assert worker.export_spans() > 0
            out = orch.get_dtraces()
            wanted = [t for t in out["traces"]
                      if t["trace_id"] == batch.trace_id]
            assert wanted, [t["trace_id"] for t in out["traces"]]
            t = wanted[0]
            procs = {s["process"] for s in t["spans"]}
            assert "tpu-e2e" in procs and "orchestrator" in procs
            assert set(t["processes"]) >= {"tpu-e2e", "orchestrator"}
            names = {s["name"] for s in t["spans"]}
            assert "orchestrator.dispatch" in names
            assert "tpu_worker.process" in names
            # Offsets were estimated and applied (in-process: ~0 ms).
            offsets = [abs(s.get("clock_offset_s", 0.0))
                       for s in t["spans"] if s["process"] == "tpu-e2e"]
            assert offsets and max(offsets) < 1.0
            # critpath renders a bottleneck attribution for the
            # assembled trace (the acceptance criterion's last leg).
            report = critpath.render(out, trace_id=batch.trace_id)
            assert "bottleneck shares" in report
            assert batch.trace_id in report
        finally:
            worker.stop(timeout_s=5)
            orch.stop()
