"""Smoke tests for bench.py (ADVICE r2 high: cfg field drift killed every
measurement child).  Runs the real ``_measure`` path in-process on the CPU
test mesh with tiny iteration counts — any EncoderConfig field rename or
result-schema regression fails here instead of in the driver's BENCH run.
"""

import json
import subprocess
import sys
import os

import pytest

import bench
from distributed_crawler_tpu.models import E5_SMALL


def test_encoder_forward_flops_uses_real_config_fields():
    flops = bench._encoder_forward_flops(E5_SMALL, batch=1, seq=1)
    # per token: L * (8 d^2 + 4 seq d + 4 d ff), MACs counted as 2 FLOPs
    d, ff, L = E5_SMALL.hidden, E5_SMALL.mlp_dim, E5_SMALL.n_layers
    assert flops == L * (8 * d * d + 4 * 1 * d + 4 * d * ff)


@pytest.mark.slow
def test_measure_smoke_cpu():
    # _measure itself re-times on an inverted two-point fit and raises if
    # the host stays too noisy — a raise here still catches the field-drift
    # regression this smoke exists for (dead child, missing keys).
    res = bench._measure(batch=8, seq=8, n_short=1, n_long=6,
                         latency_samples=2)
    assert res["metric"] == "embed_classify_posts_per_sec"
    assert res["value"] > 0
    assert res["unit"] == "posts/sec"
    assert res["vs_baseline"] > 0
    assert res["tokens_per_sec"] > 0
    assert res["batch_latency_p50_ms"] > 0
    assert res["platform"] == "cpu"
    assert res["mfu"] is None  # MFU is TPU-only by design


def test_measure_asr_smoke_cpu():
    # Tiny Whisper config so the fixed-length greedy decode runs in
    # milliseconds on CPU; catches field drift against the real model APIs.
    from distributed_crawler_tpu.models.whisper import WHISPER_TEST

    res = bench._measure_asr(batch=2, decode_len=4, samples=2,
                             model_cfg=WHISPER_TEST)
    assert res["asr_rtfx"] > 0
    assert res["asr_decode_tokens_per_sec"] > 0
    assert res["asr_batch"] == 2
    assert res["asr_decode_len"] == 4


def test_measure_moe_smoke_cpu():
    # Tiny switch-MoE config: the dense-vs-capacity dispatch cells must
    # both fit and emit the full result schema — catches EncoderConfig
    # field drift in the MoE leg before the driver's BENCH run.
    from dataclasses import replace

    from distributed_crawler_tpu.models.encoder import TINY_TEST

    res = bench._measure_moe(batch=8, seq=16, n_experts=4,
                             n_short=1, n_long=4, repeats=2,
                             base_cfg=replace(TINY_TEST, vocab_size=512))
    assert res["moe_dense_posts_per_sec"] > 0
    assert res["moe_capacity_posts_per_sec"] > 0
    assert res["moe_capacity_speedup"] > 0
    assert res["moe_experts"] == 4
    assert res["moe_batch"] == 8


def test_measure_bus_codec_smoke():
    res = bench._measure_bus_codec(batch=16, n_batches=3, text_words=10)
    assert res["bus_codec_posts_per_sec"] > 0
    assert res["bus_codec_bytes_per_post"] > 0
    assert res["bus_codec_compression"]


def test_measure_tokenizer_smoke():
    res = bench._measure_tokenizer(batch=32, text_words=8, trials=1)
    assert res["tokenizer_posts_per_sec"] > 0
    assert res["tokenizer_text_words"] == 8


def test_measure_padding_efficiency():
    """The tentpole's acceptance bound: packed real-token density >= 1.5x
    unpacked on the Zipf-length workload (host-side, runs every bench)."""
    res = bench._measure_padding_efficiency(n_texts=1024)
    assert 0 < res["padding_density_unpacked"] < 1
    assert res["padding_density_unpacked"] < \
        res["padding_density_packed"] <= 1
    assert res["padding_packed_density_gain"] >= 1.5


def test_probe_subprocess_emits_json():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("AXON", "PALLAS_AXON", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, bench.__file__, "--probe"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-800:]
    got = json.loads(proc.stdout.strip().splitlines()[-1])
    assert got["ok"] is True
    assert got["platform"] == "cpu"


def test_tpu_cache_roundtrip(tmp_path, monkeypatch):
    """A successful TPU result is cached; a CPU result never overwrites it
    (the cache exists so a wedged-chip run still carries the last REAL TPU
    number, clearly labelled)."""
    monkeypatch.setattr(bench, "TPU_CACHE_PATH",
                        str(tmp_path / "cache.json"))
    bench._cache_tpu_result({"platform": "cpu", "value": 1.0})
    assert bench._load_tpu_cache() is None
    bench._cache_tpu_result({"platform": "tpu", "value": 9000.0,
                             "metric": "embed_classify_posts_per_sec"})
    cached = bench._load_tpu_cache()
    assert cached["value"] == 9000.0
    assert "measured_at" in cached
    bench._cache_tpu_result({"platform": "cpu", "value": 2.0})
    assert bench._load_tpu_cache()["value"] == 9000.0


def test_tpu_cache_per_leg_timestamps(tmp_path, monkeypatch):
    """Carried-forward optional legs keep their OWN measured_at: a later
    run whose int8/serving leg wedged must not re-stamp the old rows."""
    monkeypatch.setattr(bench, "TPU_CACHE_PATH",
                        str(tmp_path / "cache.json"))
    bench._cache_tpu_result({"platform": "tpu", "value": 9000.0,
                             "int8_posts_per_sec": 8000.0,
                             "serving_posts_per_sec": 7000.0})
    first = bench._load_tpu_cache()
    assert first["int8_measured_at"] == first["measured_at"]
    assert first["serving_measured_at"] == first["measured_at"]
    # Force a distinct wall-clock stamp for the second run.
    stamps = iter(["2099-01-01T00:00:00Z"])
    monkeypatch.setattr(bench.time, "strftime",
                        lambda *a, **k: next(stamps))
    bench._cache_tpu_result({"platform": "tpu", "value": 9100.0,
                             "int8_posts_per_sec": None,
                             "serving_posts_per_sec": None})
    second = bench._load_tpu_cache()
    assert second["value"] == 9100.0
    assert second["measured_at"] == "2099-01-01T00:00:00Z"
    # The carried-forward legs keep the FIRST run's stamp and values.
    assert second["int8_posts_per_sec"] == 8000.0
    assert second["int8_measured_at"] == first["int8_measured_at"]
    assert second["serving_measured_at"] == first["serving_measured_at"]
