"""Azure Blob adapter against an in-tree emulator over real HTTP sockets.

The reference's storage binding wrote to Azure blob
(`state/daprstate.go:29-35`); this battery proves the in-tree adapter's
Shared Key signing and block-blob multipart mapping the same way the S3
battery proves SigV4 — the emulator RECOMPUTES every request's signature
with the shared account key and 403s mismatches.
"""

import base64
import datetime
import hashlib
import hmac
import http.server
import re
import threading
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from distributed_crawler_tpu.state.azurestore import AzureBlobObjectClient
from distributed_crawler_tpu.state.objectstore import (
    ObjectStoreUploader,
    TransientStoreError,
    make_object_client,
)

ACCOUNT = "testacct"
KEY_B64 = base64.b64encode(b"azure-test-key-32-bytes-long!!__").decode()


class AzureEmulator:
    """Minimal Blob-service server: in-memory, Shared Key-checked.

    ``account_in_path=True`` emulates Azurite's addressing
    (http://host:port/account/container/blob) — the account segment rides
    the URI path AND appears a second time in CanonicalizedResource.
    """

    PAGE_SIZE = 3  # exercises NextMarker pagination

    def __init__(self, account_in_path: bool = False):
        self.account_in_path = account_in_path
        self.blobs = {}
        self.blocks = {}  # (container, blob) -> {block_id: bytes}
        self.request_log = []
        self.fail_next = []  # (regex, count) -> 500
        emu = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _respond(self, status, body=b"", headers=None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _check_sig(self, body: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                m = re.match(rf"SharedKey {ACCOUNT}:(.+)$", auth)
                if not m:
                    self._respond(403, b"bad credential")
                    return False
                path, _, qs = self.path.partition("?")
                query = sorted(urllib.parse.parse_qsl(
                    qs, keep_blank_values=True))
                xms = sorted(
                    (k.lower(), v.strip()) for k, v in self.headers.items()
                    if k.lower().startswith("x-ms-"))
                canonical_headers = "".join(f"{k}:{v}\n" for k, v in xms)
                resource = f"/{ACCOUNT}{urllib.parse.unquote(path)}"
                canonical_resource = resource + "".join(
                    f"\n{k.lower()}:{v}" for k, v in query)
                cl = len(body)
                string_to_sign = "\n".join([
                    self.command, "", "", str(cl) if cl else "", "",
                    self.headers.get("Content-Type", "") or "",
                    "", "", "", "", "", "",
                ]) + "\n" + canonical_headers + canonical_resource
                want = base64.b64encode(hmac.new(
                    base64.b64decode(KEY_B64),
                    string_to_sign.encode(), hashlib.sha256).digest()
                ).decode()
                if want != m.group(1):
                    self._respond(403, b"SignatureDoesNotMatch")
                    return False
                return True

            def _handle(self):
                body = b""
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    body = self.rfile.read(n)
                emu.request_log.append((self.command, self.path))
                target = f"{self.command} {self.path}"
                bm = re.search(r"blockid=([^&]+)", target)
                if bm:
                    # Expose the decoded block id so fault injection can
                    # target a part number (upload ids carry entropy now).
                    try:
                        target += " decoded=" + base64.b64decode(
                            urllib.parse.unquote(bm.group(1))).decode()
                    except Exception:
                        pass
                for i, (rx, count) in enumerate(emu.fail_next):
                    if count > 0 and re.search(rx, target):
                        emu.fail_next[i] = (rx, count - 1)
                        self._respond(500, b"injected")
                        return
                if not self._check_sig(body):
                    return
                path, _, qs = self.path.partition("?")
                q = dict(urllib.parse.parse_qsl(qs,
                                                keep_blank_values=True))
                decoded = urllib.parse.unquote(path).lstrip("/")
                if emu.account_in_path:
                    # Azurite addressing: strip the leading /account.
                    acct, _, decoded = decoded.partition("/")
                    if acct != ACCOUNT:
                        self._respond(400, b"wrong account segment")
                        return
                parts = decoded.split("/", 2)
                # path-style: /container[/blob...]
                container = parts[0]
                blob = parts[1] if len(parts) > 1 else ""
                if len(parts) > 2:
                    blob = f"{parts[1]}/{parts[2]}"
                cmd = self.command
                bkey = (container, blob)
                if cmd == "PUT" and q.get("comp") == "block":
                    emu.blocks.setdefault(bkey, {})[q["blockid"]] = body
                    self._respond(201)
                    return
                if cmd == "PUT" and q.get("comp") == "blocklist":
                    root = ET.fromstring(body)
                    staged = emu.blocks.get(bkey, {})
                    joined = b""
                    for el in root.iter("Latest"):
                        bid = el.text or ""
                        if bid not in staged:
                            self._respond(400, b"InvalidBlockId")
                            return
                        joined += staged[bid]
                    emu.blobs[bkey] = joined
                    emu.blocks.pop(bkey, None)
                    self._respond(201)
                    return
                if cmd == "PUT":
                    if self.headers.get("x-ms-blob-type") != "BlockBlob":
                        self._respond(400, b"blob type missing")
                        return
                    emu.blobs[bkey] = body
                    self._respond(201)
                    return
                if cmd == "GET" and q.get("comp") == "list":
                    prefix = q.get("prefix", "")
                    names = sorted(b for c, b in emu.blobs
                                   if c == container
                                   and b.startswith(prefix))
                    start = int(q.get("marker") or 0)
                    page = names[start:start + emu.PAGE_SIZE]
                    nxt = (str(start + emu.PAGE_SIZE)
                           if start + emu.PAGE_SIZE < len(names) else "")
                    xml = ["<EnumerationResults><Blobs>"]
                    for b in page:
                        xml.append(f"<Blob><Name>{b}</Name></Blob>")
                    xml.append(f"</Blobs><NextMarker>{nxt}</NextMarker>"
                               f"</EnumerationResults>")
                    self._respond(200, "".join(xml).encode())
                    return
                if cmd in ("GET", "HEAD"):
                    data = emu.blobs.get(bkey)
                    if data is None:
                        self._respond(404, b"NoSuchBlob")
                        return
                    self._respond(200, data)
                    return
                if cmd == "DELETE":
                    emu.blobs.pop(bkey, None)
                    self._respond(202)
                    return
                self._respond(400, b"unsupported")

            do_GET = do_PUT = do_DELETE = do_HEAD = _handle

        self._srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                    Handler)
        self.port = self._srv.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


@pytest.fixture
def emu():
    e = AzureEmulator().start()
    yield e
    e.close()


def make_client(emu, prefix="") -> AzureBlobObjectClient:
    return AzureBlobObjectClient(
        account=ACCOUNT, container="crawls", prefix=prefix,
        endpoint=emu.endpoint, account_key=KEY_B64)


class TestSharedKeyRoundTrip:
    def test_put_get_head_delete(self, emu):
        c = make_client(emu)
        c.put_object("a/b.jsonl", b"hello azure")
        assert c.get_object("a/b.jsonl") == b"hello azure"
        assert c.head_object("a/b.jsonl") == 11
        assert c.get_object("missing") is None
        c.delete_object("a/b.jsonl")
        assert c.get_object("a/b.jsonl") is None

    def test_bad_key_rejected(self, emu):
        wrong = base64.b64encode(b"wrong-key").decode()
        c = AzureBlobObjectClient(account=ACCOUNT, container="crawls",
                                  endpoint=emu.endpoint, account_key=wrong)
        with pytest.raises(ValueError, match="403"):
            c.put_object("k", b"x")

    def test_prefix_and_list_pagination(self, emu):
        c = make_client(emu, prefix="run1")
        for i in range(8):
            c.put_object(f"p/k{i}", b"v")
        assert ("crawls", "run1/p/k0") in emu.blobs
        assert c.list_objects("p/") == [f"p/k{i}" for i in range(8)]

    def test_5xx_transient_and_refused(self, emu):
        c = make_client(emu)
        emu.fail_next.append((r"PUT /crawls/t5", 1))
        with pytest.raises(TransientStoreError):
            c.put_object("t5", b"x")
        dead = AzureBlobObjectClient(
            account=ACCOUNT, container="c", endpoint="http://127.0.0.1:1",
            account_key=KEY_B64, timeout_s=2.0)
        with pytest.raises(TransientStoreError):
            dead.get_object("k")


class TestBlockBlobMultipart:
    def test_multipart_roundtrip(self, emu):
        c = make_client(emu)
        up = ObjectStoreUploader(c, part_size=8, backoff_s=0.01)
        data = b"0123456789" * 5
        up.upload_bytes("mp/big.bin", data)
        assert emu.blobs[("crawls", "mp/big.bin")] == data

    def test_mid_upload_fault_resumes_from_failing_block(self, emu):
        c = make_client(emu)
        up = ObjectStoreUploader(c, part_size=8, backoff_s=0.01)
        # Upload ids carry entropy; the emulator decodes block ids into
        # the fault-match target, so part 2 is addressable directly.
        emu.fail_next.append((r"decoded=.*:000002", 2))
        data = bytes(range(40))  # 5 blocks
        up.upload_bytes("mp/fault.bin", data)
        assert emu.blobs[("crawls", "mp/fault.bin")] == data
        block_puts = [p for m, p in emu.request_log
                      if m == "PUT" and "comp=block" in p
                      and "blocklist" not in p and "fault.bin" in p]
        by_part = {}
        for p in block_puts:
            bid = re.search(r"blockid=([^&]+)", p).group(1)
            part = base64.b64decode(
                urllib.parse.unquote(bid)).decode().split(":")[1]
            by_part[part] = by_part.get(part, 0) + 1
        assert by_part["000002"] == 3      # two failures + success
        assert by_part["000000"] == by_part["000001"] == 1

    def test_commit_with_unstaged_block_rejected(self, emu):
        c = make_client(emu)
        uid = c.create_multipart("mp/bad.bin")
        c.upload_part("mp/bad.bin", uid, 0, b"part0")
        with pytest.raises(ValueError, match="400"):
            c.complete_multipart("mp/bad.bin", uid, ["Ym9ndXM="])


class TestAzuriteStyleEndpoint:
    def test_account_in_path_signing(self):
        """Azurite addressing: the account rides the URI path AND appears
        twice in CanonicalizedResource (/acct/acct/container/blob) — the
        r04 review caught the stripped-base variant 403ing on real
        Azurite."""
        emu = AzureEmulator(account_in_path=True).start()
        try:
            c = AzureBlobObjectClient(
                account=ACCOUNT, container="crawls", prefix="p",
                endpoint=f"{emu.endpoint}/{ACCOUNT}",
                account_key=KEY_B64)
            c.put_object("a.jsonl", b"azurite-style")
            assert emu.blobs[("crawls", "p/a.jsonl")] == b"azurite-style"
            assert c.get_object("a.jsonl") == b"azurite-style"
            assert c.list_objects("") == ["a.jsonl"]
            up = ObjectStoreUploader(c, part_size=8, backoff_s=0.01)
            data = bytes(range(24))
            up.upload_bytes("mp.bin", data)
            assert emu.blobs[("crawls", "p/mp.bin")] == data
        finally:
            emu.close()


class TestMakeObjectClientAzureUrl:
    def test_azure_url_parses(self, emu):
        url = (f"azure://{ACCOUNT}/crawls/pfx?endpoint={emu.endpoint}"
               f"&account_key={urllib.parse.quote(KEY_B64)}")
        c = make_object_client(url)
        c.put_object("k.jsonl", b"via-url")
        assert emu.blobs[("crawls", "pfx/k.jsonl")] == b"via-url"

    def test_missing_key_rejected(self, monkeypatch):
        monkeypatch.delenv("AZURE_STORAGE_KEY", raising=False)
        with pytest.raises(ValueError, match="credentials"):
            make_object_client("azure://acct/cont?endpoint=http://x")

    def test_env_key_used(self, emu, monkeypatch):
        monkeypatch.setenv("AZURE_STORAGE_KEY", KEY_B64)
        c = make_object_client(
            f"azure://{ACCOUNT}/crawls?endpoint={emu.endpoint}")
        c.put_object("envkey", b"ok")
        assert emu.blobs[("crawls", "envkey")] == b"ok"
