"""ISSUE 10: durable message bus — broker WAL spool, publisher outbox,
real dead-letter queue, and the kill-broker chaos closure.

Covers:
- spool replay determinism, torn-tail tolerance, attempt-count
  preservation, atomic compaction (`bus/spool.py`);
- the persisted dead-letter queue + replay marking;
- the bounded durable outbox: buffer-through-outage, hard bound,
  WAL reload, ordering (`bus/outbox.py`);
- broker restart over the same spool dir: queued + unacked-in-flight
  frames redelivered across generations, attempts surviving, dead
  letters landing in the DLQ, unrouted publishes counted and held;
- RemoteBus reconnect backoff (the 1 Hz stampede fix) and reconnect
  across broker generations;
- consumer idempotence under broker-driven duplicate delivery (the
  sweeper-requeue-vs-ack race): ack returns unknown-delivery, the frame
  re-runs, and the PR-7 layers (idempotent per-batch writeback, the
  orchestrator's applied-results window, the bridge's post_uid dedupe
  window) absorb it end to end;
- the orchestrator's outbox-near-full dispatch valve;
- the kill-broker gate acceptance (`loadgen/scenarios/kill-broker.json`).
"""

import base64
import json
import os
import threading
import time

import pytest

from distributed_crawler_tpu.bus.codec import RecordBatch
from distributed_crawler_tpu.bus.grpc_bus import (
    GrpcBusClient,
    GrpcBusServer,
    RemoteBus,
)
from distributed_crawler_tpu.bus.inmemory import InMemoryBus
from distributed_crawler_tpu.bus.messages import TOPIC_INFERENCE_BATCHES
from distributed_crawler_tpu.bus.outbox import (
    DurableOutbox,
    OutboxBus,
    OutboxConfig,
    OutboxFull,
)
from distributed_crawler_tpu.bus.spool import (
    BusSpool,
    DeadLetterSpool,
    TopicSpool,
)
from distributed_crawler_tpu.utils import flight
from distributed_crawler_tpu.utils.metrics import MetricsRegistry


def _counter_total(registry, name):
    return sum(v for _, v in registry.counter(name).series())


# ---------------------------------------------------------------------------
# spool: WAL replay, torn tails, compaction
# ---------------------------------------------------------------------------
class TestTopicSpool:
    def test_replay_deterministic_and_pure(self, tmp_path):
        spool = TopicSpool(str(tmp_path), "t")
        a = spool.enqueue(b"frame-a")
        spool.enqueue(b"frame-b")
        c = spool.enqueue(b"frame-c")
        spool.requeue(c, attempts=2)
        spool.ack(a)
        first = [(f.fid, f.payload, f.attempts) for f in spool.replay()]
        second = [(f.fid, f.payload, f.attempts) for f in spool.replay()]
        assert first == second
        spool.close()
        # A fresh spool over the same directory folds to the same state.
        reopened = TopicSpool(str(tmp_path), "t")
        assert [(f.fid, f.payload, f.attempts)
                for f in reopened.replay()] == first
        # b stays at the head; the requeued c moved to the tail with its
        # bumped attempt count.
        assert [f.payload for f in reopened.replay()] == \
            [b"frame-b", b"frame-c"]
        assert reopened.replay()[1].attempts == 2
        reopened.close()

    def test_torn_tail_dropped_not_fatal(self, tmp_path):
        spool = TopicSpool(str(tmp_path), "t")
        spool.enqueue(b"one")
        spool.enqueue(b"two")
        spool.close()
        with open(spool.wal_path, "a", encoding="utf-8") as f:
            f.write('{"k": "enq", "id": "torn", "d": "AAA')  # crash mid-append
        reopened = TopicSpool(str(tmp_path), "t")
        assert [f.payload for f in reopened.replay()] == [b"one", b"two"]
        reopened.close()

    def test_corrupt_interior_line_skipped(self, tmp_path):
        spool = TopicSpool(str(tmp_path), "t")
        spool.enqueue(b"one", fid="f1")
        spool.close()
        with open(spool.wal_path, "a", encoding="utf-8") as f:
            f.write("NOT JSON AT ALL\n")
            f.write(json.dumps({"k": "enq", "id": "f2",
                                "d": base64.b64encode(b"two").decode()})
                    + "\n")
        reopened = TopicSpool(str(tmp_path), "t")
        assert [f.payload for f in reopened.replay()] == [b"one", b"two"]
        reopened.close()

    def test_compaction_rewrites_live_frames_only(self, tmp_path):
        spool = TopicSpool(str(tmp_path), "t", compact_every=8)
        keep = spool.enqueue(b"keeper")
        for i in range(10):
            fid = spool.enqueue(f"gone-{i}".encode())
            spool.ack(fid)  # acked prefix dominates -> auto compaction
        with open(spool.wal_path, encoding="utf-8") as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        # After compaction the WAL is (close to) just the live set, never
        # the full 21-event history.
        assert len(lines) < 21
        assert [f.fid for f in spool.replay()] == [keep]
        spool.close(compact=True)
        with open(spool.wal_path, encoding="utf-8") as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        assert len(lines) == 1 and json.loads(lines[0])["id"] == keep

    def test_topic_names_roundtrip_through_directories(self, tmp_path):
        spool = BusSpool(str(tmp_path))
        ugly = "weird topic/with:chars✓"
        spool.enqueue(ugly, b"payload")
        assert spool.existing_topics() == [ugly]
        assert [f.payload for f in spool.replay(ugly)] == [b"payload"]
        spool.close()

    def test_closed_spool_refuses_even_first_enqueue_topics(self, tmp_path):
        """A publish racing a broker kill() must fail loudly for EVERY
        topic — a fresh TopicSpool minted after close() would journal
        into a WAL the next generation has already read (acked but
        delivered by no live generation)."""
        spool = BusSpool(str(tmp_path))
        spool.enqueue("seen", b"x")
        spool.close()
        with pytest.raises(RuntimeError):
            spool.enqueue("seen", b"y")
        with pytest.raises(RuntimeError):
            spool.enqueue("never-seen-before", b"z")


class TestDeadLetterSpool:
    def test_append_entries_and_replay_marking(self, tmp_path):
        dlq = DeadLetterSpool(str(tmp_path))
        dlq.append("t", "f1", b"poison", attempts=5, reason="max_attempts")
        dlq.append("t", "f2", b"other", attempts=3, reason="boom")
        entries = dlq.entries("t")
        assert [e.fid for e in entries] == ["f1", "f2"]
        assert entries[0].payload == b"poison"
        assert entries[0].reason == "max_attempts"
        assert not entries[0].replayed
        dlq.mark_replayed("t", "f1")
        entries = dlq.entries("t")
        assert entries[0].replayed and not entries[1].replayed
        snap = dlq.snapshot()
        assert snap["topics"]["t"]["count"] == 2
        assert snap["topics"]["t"]["pending"] == 1
        detail = dlq.snapshot(topic="t", fid="f2")
        assert base64.b64decode(detail["entry"]["payload_b64"]) == b"other"

    def test_replayed_entries_compact_past_retention(self, tmp_path):
        """Replayed entries are audit history with a retention bound:
        pending entries all survive compaction, replayed ones beyond the
        newest N are dropped — the file cannot grow forever."""
        dlq = DeadLetterSpool(str(tmp_path), replayed_retention=2)
        for i in range(5):
            dlq.append("t", f"f{i}", b"x", attempts=1, reason="r")
        dlq.append("t", "pending", b"keep", attempts=1, reason="r")
        for i in range(5):
            dlq.mark_replayed("t", f"f{i}")
        entries = dlq.entries("t")
        replayed = [e.fid for e in entries if e.replayed]
        assert replayed == ["f3", "f4"]  # newest 2 kept, oldest dropped
        assert [e.fid for e in entries if not e.replayed] == ["pending"]
        # The compacted file still folds identically on a fresh instance.
        again = DeadLetterSpool(str(tmp_path), replayed_retention=2)
        assert [e.fid for e in again.entries("t")] == ["f3", "f4",
                                                      "pending"]


# ---------------------------------------------------------------------------
# outbox: buffer-through-outage, bound, WAL reload
# ---------------------------------------------------------------------------
class TestDurableOutbox:
    def _cfg(self, tmp_path=None, **kw):
        base = dict(flush_wait_s=0.01, retry_base_s=0.01, retry_max_s=0.05,
                    breaker_threshold=3, breaker_recovery_s=0.05)
        if tmp_path is not None:
            base["dir"] = str(tmp_path)
        base.update(kw)
        return OutboxConfig(**base)

    def test_buffers_through_outage_then_flushes_in_order(self):
        sent, up = [], threading.Event()

        def send(topic, payload):
            if not up.is_set():
                raise RuntimeError("broker down")
            sent.append((topic, payload["n"]))

        ob = DurableOutbox(send, self._cfg(), registry=MetricsRegistry())
        try:
            for n in range(5):
                ob.publish("t", {"n": n})
            time.sleep(0.1)
            assert ob.depth() == 5 and not sent
            up.set()
            assert ob.drain(timeout_s=5.0)
            assert [n for _, n in sent] == [0, 1, 2, 3, 4]  # ordering kept
        finally:
            ob.close()

    def test_bound_is_hard_and_counted(self):
        reg = MetricsRegistry()
        ob = DurableOutbox(lambda t, p: (_ for _ in ()).throw(
            RuntimeError("down")), self._cfg(max_frames=3), registry=reg)
        try:
            for n in range(3):
                ob.publish("t", {"n": n})
            with pytest.raises(OutboxFull):
                ob.publish("t", {"n": 99})
            assert ob.near_full()
            assert _counter_total(reg, "bus_outbox_rejected_total") == 1
        finally:
            ob.close(drain_s=0.0)

    def test_wal_reload_resends_after_publisher_restart(self, tmp_path):
        down = lambda t, p: (_ for _ in ()).throw(RuntimeError("down"))  # noqa: E731
        ob = DurableOutbox(down, self._cfg(tmp_path),
                           registry=MetricsRegistry())
        ob.publish("t", {"n": 1})
        ob.publish("t", {"n": 2})
        time.sleep(0.05)
        ob.close(drain_s=0.1)  # undelivered frames stay in the WAL
        sent = []
        ob2 = DurableOutbox(lambda t, p: sent.append(p["n"]),
                            self._cfg(tmp_path), registry=MetricsRegistry())
        try:
            assert ob2.drain(timeout_s=5.0)
            assert sent == [1, 2]
        finally:
            ob2.close()

    def test_wal_compacts_with_a_standing_queue_depth(self, tmp_path):
        """The WAL rewrite fires once the done-prefix dominates even
        while frames are still pending — an always-busy publisher must
        not grow the file for the life of the process."""
        down = lambda t, p: (_ for _ in ()).throw(RuntimeError("down"))  # noqa: E731
        ob = DurableOutbox(down, self._cfg(tmp_path, compact_every=4),
                           registry=MetricsRegistry())
        try:
            ob.publish("t", {"n": 1})
            ob.publish("t", {"n": 2})
            with ob._lock:
                # As if many earlier frames had already delivered: the
                # done-prefix dominates, two puts are still pending.
                ob._wal_puts, ob._wal_dones = 10, 8
                ob._wal_maybe_compact_locked()
            with open(ob.wal_path, encoding="utf-8") as f:
                lines = [json.loads(ln) for ln in f.read().splitlines()
                         if ln.strip()]
            assert [ln["k"] for ln in lines] == ["put", "put"]
        finally:
            ob.close(drain_s=0.0)
        # The rewritten WAL still reloads into the exact pending set.
        sent = []
        ob2 = DurableOutbox(lambda t, p: sent.append(p["n"]),
                            self._cfg(tmp_path), registry=MetricsRegistry())
        try:
            assert ob2.drain(timeout_s=5.0)
            assert sent == [1, 2]
        finally:
            ob2.close()

    def test_near_full_and_low_water_are_distinct_marks(self):
        down = lambda t, p: (_ for _ in ()).throw(RuntimeError("down"))  # noqa: E731
        ob = DurableOutbox(down, self._cfg(max_frames=10),
                           registry=MetricsRegistry())
        try:
            for n in range(8):  # high mark = 8, low mark = 4
                ob.publish("t", {"n": n})
            assert ob.near_full() and not ob.below_low_water()
            with ob._lock:
                while len(ob._q) > 5:
                    ob._q.popleft()
            # Between the marks: neither engaged nor released (the
            # valve's hysteresis band).
            assert not ob.near_full() and not ob.below_low_water()
            with ob._lock:
                while len(ob._q) > 4:
                    ob._q.popleft()
            assert ob.below_low_water()
        finally:
            ob.close(drain_s=0.0)

    def test_outbox_bus_wrapper_delegates(self):
        inner = InMemoryBus(sync=True)
        got = []
        inner.subscribe("t", got.append)
        bus = OutboxBus(inner, self._cfg(), registry=MetricsRegistry())
        bus.publish("t", {"n": 7})
        assert bus.outbox.drain(timeout_s=5.0)
        assert got and got[0]["n"] == 7
        assert bus.stats()["published"]["t"] == 1  # __getattr__ delegation
        bus.close()


# ---------------------------------------------------------------------------
# broker restart over the spool
# ---------------------------------------------------------------------------
def _pull_n(client, topic, n, ack=True, ok=True, timeout_s=10.0):
    """Pull n frames (acking each per ``ack``/``ok``), return payload list."""
    got = []
    deadline = time.monotonic() + timeout_s
    it = client.pull(topic)
    try:
        while len(got) < n and time.monotonic() < deadline:
            delivery_id, payload = next(it)
            got.append(json.loads(payload))
            if ack:
                client.ack(topic, delivery_id, ok=ok)
    finally:
        it.close()
    return got


class TestBrokerRestart:
    def test_queued_and_inflight_redelivered_across_generations(
            self, tmp_path):
        flight.RECORDER.reset()
        spool = str(tmp_path / "spool")
        gen1 = GrpcBusServer("127.0.0.1:0", spool_dir=spool,
                             ack_timeout_s=60)
        gen1.enable_pull("t")
        gen1.start()
        for n in range(3):
            gen1.publish("t", {"n": n})
        c1 = GrpcBusClient(f"127.0.0.1:{gen1.bound_port}")
        # One frame goes in flight and is NEVER acked (the consumer "dies"
        # holding it) — the broker dies right after.
        assert _pull_n(c1, "t", 1, ack=False) == [{"n": 0}]
        c1.close()
        gen1.kill()

        gen2 = GrpcBusServer("127.0.0.1:0", spool_dir=spool)
        gen2.start()
        # Queued (1, 2) AND the unacked in-flight frame (0) come back.
        assert gen2.pending_count("t") == 3
        c2 = GrpcBusClient(f"127.0.0.1:{gen2.bound_port}")
        got = sorted(p["n"] for p in _pull_n(c2, "t", 3))
        assert got == [0, 1, 2]
        c2.close()
        assert gen2.pending_count("t") == 0
        gen2.close()
        # Acked everywhere: a third generation starts empty.
        gen3 = GrpcBusServer("127.0.0.1:0", spool_dir=spool)
        assert gen3.pending_count("t") == 0
        gen3.close()
        kinds = [e["kind"] for e in flight.RECORDER.events()]
        assert "bus_kill" in kinds and "bus_resume" in kinds

    def test_attempt_counts_survive_restart_into_dead_letter(self, tmp_path):
        """A frame the dead generation had already redelivered once
        resumes with attempts=1, so ONE more failure in the new
        generation dead-letters it — the attempt budget is global across
        broker generations, not per-generation."""
        reg = MetricsRegistry()
        spool_dir = str(tmp_path / "spool")
        # The dead generation's journaled state, written through the same
        # spool API the live broker uses: enqueued, then requeued once
        # (a nack or ack-timeout bumped attempts to 1), never acked.
        spool = BusSpool(spool_dir)
        fid = spool.enqueue("t", json.dumps({"poison": 1}).encode())
        spool.requeue("t", fid, attempts=1)
        spool.close()

        gen2 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             max_attempts=2, registry=reg)
        gen2.start()
        assert gen2.pending_count("t") == 1
        c2 = GrpcBusClient(f"127.0.0.1:{gen2.bound_port}")
        # One nack in the NEW generation: 1 (inherited) + 1 >= 2 ->
        # dead letter, so the attempt count crossed the restart.
        assert _pull_n(c2, "t", 1, ack=True, ok=False) == [{"poison": 1}]
        c2.close()
        deadline = time.monotonic() + 5
        while gen2.dead_letters < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert gen2.dead_letters == 1
        assert gen2.pending_count("t") == 0
        entries = DeadLetterSpool(spool_dir).entries("t")
        assert len(entries) == 1 and entries[0].attempts == 2
        assert json.loads(entries[0].payload) == {"poison": 1}
        assert _counter_total(reg, "bus_dead_letters_total") == 1
        assert _counter_total(reg, "bus_redeliveries_total") == 0
        gen2.close()

    def test_dlq_replay_re_enters_delivery(self, tmp_path):
        spool = str(tmp_path / "spool")
        server = GrpcBusServer("127.0.0.1:0", spool_dir=spool,
                               max_attempts=1)
        server.enable_pull("t")
        server.start()
        server.publish("t", {"n": 42})
        client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
        _pull_n(client, "t", 1, ack=True, ok=False)  # max_attempts=1 -> dead
        deadline = time.monotonic() + 5
        while server.dead_letters < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        snap = server.dlq_snapshot()
        assert snap["enabled"] and snap["topics"]["t"]["pending"] == 1
        fid = snap["topics"]["t"]["entries"][0]["id"]
        meta = server.dlq_replay("t", fid)
        assert meta["id"] == fid
        assert _pull_n(client, "t", 1) == [{"n": 42}]
        assert server.dlq_snapshot()["topics"]["t"]["pending"] == 0
        client.close()
        server.close()

    def test_unrouted_counted_and_held_durable(self, tmp_path):
        reg = MetricsRegistry()
        server = GrpcBusServer("127.0.0.1:0",
                               spool_dir=str(tmp_path / "spool"),
                               registry=reg)
        server.start()
        server.publish("nobody-home", {"lost?": False})
        assert _counter_total(reg, "bus_dropped_no_route_total") == 1
        # Held in the DLQ spool (reason no_route), replayable later —
        # NOT a phantom pull queue.
        assert server.pending_count("nobody-home") == 0
        snap = server.dlq_snapshot()
        entry = snap["topics"]["nobody-home"]["entries"][0]
        assert entry["reason"] == "no_route"
        server.close()

    def test_local_dead_letter_conjures_no_phantom_pull_topic(
            self, tmp_path):
        """A local-handler dead letter on a fan-out topic lands in the
        DLQ only: it must NOT write the topic's WAL, or a restarted
        broker would rebuild a pull queue nobody drains and every later
        publish on the fan-out topic would accumulate there forever."""
        spool_dir = str(tmp_path / "spool")
        gen1 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             max_attempts=1, registry=MetricsRegistry())

        def boom(payload):
            raise RuntimeError("handler down")

        gen1.subscribe("fanout", boom)
        gen1.start()
        gen1.publish("fanout", {"n": 1})
        assert gen1.flush_local(timeout_s=10.0)
        deadline = time.monotonic() + 5
        while gen1.dead_letters < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert gen1.dead_letters == 1
        gen1.close()
        entries = DeadLetterSpool(spool_dir).entries("fanout")
        assert len(entries) == 1 and entries[0].reason.startswith(
            "local_handler")
        gen2 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             registry=MetricsRegistry())
        assert "fanout" not in gen2._pull_queues  # no phantom pull topic
        assert gen2.pending_count("fanout") == 0
        gen2.close()

    def test_unrouted_hold_cap_survives_restart(self, tmp_path):
        """The per-topic cap on no_route DLQ holds counts what is already
        on disk: a supervisor restart loop must not append another cap's
        worth per generation."""
        spool_dir = str(tmp_path / "spool")
        gen1 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             registry=MetricsRegistry())
        gen1.unrouted_spool_cap = 2
        gen1.start()
        for i in range(3):
            gen1.publish("orphan", {"n": i})
        assert gen1.dlq_snapshot()["topics"]["orphan"]["pending"] == 2
        gen1.close()
        reg2 = MetricsRegistry()
        gen2 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             registry=reg2)
        gen2.unrouted_spool_cap = 2
        gen2.start()
        gen2.publish("orphan", {"n": 99})
        # Counted, but NOT held: the persisted cap is already reached.
        assert _counter_total(reg2, "bus_dropped_no_route_total") == 1
        assert gen2.dlq_snapshot()["topics"]["orphan"]["pending"] == 2
        gen2.close()

    def test_dlq_replay_releases_unrouted_cap_slot(self, tmp_path):
        """Replaying a no_route hold frees its cap slot (and replayed
        entries don't pin the cap across restarts), so a drained DLQ can
        spool fresh unrouted frames again instead of silently dropping
        them forever."""
        spool_dir = str(tmp_path / "spool")
        gen1 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             registry=MetricsRegistry())
        gen1.unrouted_spool_cap = 1
        gen1.start()
        gen1.publish("orphan", {"n": 0})
        snap = gen1.dlq_snapshot()
        assert snap["topics"]["orphan"]["pending"] == 1
        fid = snap["topics"]["orphan"]["entries"][0]["id"]
        gen1.dlq_replay("orphan", fid)  # still unrouted -> re-held, but
        # the replay released the original slot first, so the re-hold
        # fits inside the cap instead of being dropped.
        assert gen1.dlq_snapshot()["topics"]["orphan"]["pending"] == 1
        gen1.close()
        # A restart counts only PENDING holds toward the cap.
        gen2 = GrpcBusServer("127.0.0.1:0", spool_dir=spool_dir,
                             registry=MetricsRegistry())
        assert gen2._unrouted_spooled.get("orphan", 0) == 1
        gen2.close()

    def test_unrouted_counted_and_dropped_without_spool(self):
        reg = MetricsRegistry()
        server = GrpcBusServer("127.0.0.1:0", registry=reg)
        server.start()
        server.publish("nobody-home", {"gone": True})
        assert _counter_total(reg, "bus_dropped_no_route_total") == 1
        assert server.dlq_snapshot()["topics"] == {}
        server.close()


# ---------------------------------------------------------------------------
# RemoteBus: reconnect backoff + reconnect across generations
# ---------------------------------------------------------------------------
class TestRemoteBusReconnect:
    def test_backoff_schedule_is_jittered_exponential(self):
        bus = RemoteBus("127.0.0.1:1")  # never dialed
        try:
            flat = [bus._reconnect.delay_s(a, rng=lambda: 0.5)
                    for a in range(7)]
            # rng 0.5 -> jitter factor exactly 1.0: the raw schedule.
            assert flat == [0.1, 0.2, 0.4, 0.8, 1.6, 2.0, 2.0]
            lo = bus._reconnect.delay_s(3, rng=lambda: 0.0)
            hi = bus._reconnect.delay_s(3, rng=lambda: 1.0)
            assert lo == pytest.approx(0.8 * 0.75)
            assert hi == pytest.approx(0.8 * 1.25)
            # The capped exponent never overflows (the plateau holds).
            assert bus._reconnect.delay_s(16, rng=lambda: 0.5) == 2.0
        finally:
            bus.close()

    def test_reconnects_to_a_new_broker_generation(self, tmp_path):
        spool = str(tmp_path / "spool")
        gen1 = GrpcBusServer("127.0.0.1:0", spool_dir=spool)
        gen1.enable_pull("t")
        gen1.start()
        addr = f"127.0.0.1:{gen1.bound_port}"
        got = []
        done = threading.Event()

        def handler(payload, ack):
            got.append(payload["n"])
            ack(True)
            done.set()

        worker = RemoteBus(addr)
        worker.subscribe("t", handler)
        try:
            gen1.publish("t", {"n": 1})
            assert done.wait(10.0)
            done.clear()
            gen1.kill()
            time.sleep(0.3)  # let the puller hit the backoff path
            # Same port, same spool: the supervisor restart.
            gen2 = GrpcBusServer(addr, spool_dir=spool)
            gen2.start()
            assert gen2.bound_port == gen1.bound_port
            gen2.publish("t", {"n": 2})
            assert done.wait(15.0), "puller never reconnected"
            assert got == [1, 2]
            gen2.close()
        finally:
            worker.close()


# ---------------------------------------------------------------------------
# consumer idempotence under broker-driven duplicates (ISSUE 10 satellite)
# ---------------------------------------------------------------------------
class _StubEngine:
    """Minimal engine for TPUWorker: deterministic per-text results."""

    class cfg:
        model = "stub"

    def run(self, texts, pack=False):
        return [{"label": 0, "score": 1.0} for _ in texts]


class TestDuplicateDeliveryIdempotence:
    def test_ack_loses_race_with_sweeper_requeue(self):
        """The duplicate-delivery mechanism itself: a slow consumer's ack
        lands AFTER the sweeper's ack-timeout requeue — the broker says
        unknown-delivery and the frame runs again on another puller."""
        server = GrpcBusServer("127.0.0.1:0", ack_timeout_s=0.2)
        server.enable_pull("t")
        server.start()
        client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
        try:
            server.publish("t", {"n": 5})
            it = client.pull("t")
            delivery_id, _ = next(it)
            # The stream stays OPEN (the consumer is alive, just slow):
            # the sweeper expires the delivery and requeues the frame,
            # which the same stream immediately redelivers under a NEW
            # delivery id.
            tq = server._pull_queues["t"]
            deadline = time.monotonic() + 10
            while delivery_id in tq.inflight \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            late = client._ack(b"t\x00" + delivery_id.encode("ascii")
                               + b"\x00ok")
            assert late == b"unknown-delivery"  # the ack lost the race
            # ...and the frame runs again: at-least-once, duplicate run.
            redelivery_id, payload = next(it)
            assert redelivery_id != delivery_id
            assert json.loads(payload) == {"n": 5}
            client.ack("t", redelivery_id, ok=True)
            it.close()
        finally:
            client.close()
            server.close()

    def test_worker_writeback_absorbs_redelivered_batch(self):
        """PR-7 layer 1: the per-batch writeback is idempotent, so the
        redelivered frame overwrites the same file instead of duplicating
        rows — the gate's duplicate reconciliation stays zero."""
        from distributed_crawler_tpu.inference.worker import (
            TPUWorker,
            TPUWorkerConfig,
            iter_results,
        )
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
        )

        bus = InMemoryBus(sync=True)
        provider = InMemoryStorageProvider()
        worker = TPUWorker(
            bus, _StubEngine(), provider=provider,
            cfg=TPUWorkerConfig(worker_id="t1", heartbeat_s=30.0,
                                stall_warn_s=0, coalesce_batches=1),
            registry=MetricsRegistry())
        worker.start()
        try:
            batch = RecordBatch.from_dict({
                "batch_id": "b-dup", "crawl_id": "c-dup",
                "records": [{"post_uid": "p1", "description": "hello"},
                            {"post_uid": "p2", "description": "world"}],
            })
            payload = batch.to_dict()
            bus.publish(TOPIC_INFERENCE_BATCHES, payload)
            assert worker.drain(timeout_s=10.0)
            bus.publish(TOPIC_INFERENCE_BATCHES, payload)  # the redelivery
            assert worker.drain(timeout_s=10.0)
            rows = list(iter_results(provider, "c-dup"))
            assert sorted(r["post_uid"] for r in rows) == ["p1", "p2"]
        finally:
            worker.stop(timeout_s=5.0)
            bus.close()

    def test_orchestrator_applied_results_absorb_duplicate(self, tmp_path):
        """PR-7 layer 2: a result replayed by broker redelivery (or
        across an orchestrator restart) single-counts via the
        applied-results idempotence window."""
        from distributed_crawler_tpu.bus.messages import (
            STATUS_SUCCESS,
            ResultMessage,
            WorkResult,
        )
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.orchestrator import Orchestrator
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )
        from distributed_crawler_tpu.state.datamodels import utcnow

        sm = CompositeStateManager(StateConfig(
            crawl_id="c1", crawl_execution_id="e1",
            storage_root=str(tmp_path / "s"), sql=SqlConfig(url=":memory:")))
        orch = Orchestrator(
            "c1", CrawlerConfig(crawl_id="c1", platform="telegram",
                                skip_media_download=True,
                                sampling_method="channel"),
            InMemoryBus(), sm)
        orch.start(["chana"], background=False)
        orch.distribute_work()
        item = next(iter(orch.active_work.values()))
        msg = ResultMessage.new(WorkResult(
            work_item_id=item.id, worker_id="w1", status=STATUS_SUCCESS,
            processed_url=item.url, message_count=1, completed_at=utcnow()))
        orch.handle_result(msg)
        orch.handle_result(msg)   # broker redelivery of the same result
        assert orch.completed_items == 1
        assert sm.get_layer_by_depth(0)[0].status == "fetched"
        orch.stop()

    def test_bridge_post_uid_window_absorbs_recrawl(self, tmp_path):
        """PR-7 layer 3: an at-least-once re-crawl re-stores the same
        posts; the bridge's post_uid dedupe window ships them once."""
        from distributed_crawler_tpu.datamodel import Post
        from distributed_crawler_tpu.inference.bridge import InferenceBridge
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        bus = InMemoryBus(sync=True)
        shipped = []
        bus.subscribe(TOPIC_INFERENCE_BATCHES, shipped.append)
        inner = CompositeStateManager(StateConfig(
            crawl_id="d1", crawl_execution_id="x1",
            storage_root=str(tmp_path / "d"), sql=SqlConfig(url=":memory:")))
        bridge = InferenceBridge(inner, bus, crawl_id="d1", batch_size=100)
        try:
            post = Post(post_uid="p1", channel_id="chan",
                        searchable_text="hello")
            bridge.store_post("chan", post)
            bridge.store_post("chan", post)  # the re-crawl duplicate
            bridge.flush()
            uids = [r.get("post_uid")
                    for b in shipped for r in b.get("records", [])]
            assert uids == ["p1"]
            assert bridge.posts_deduped == 1
        finally:
            bridge.close()
            bus.close()


# ---------------------------------------------------------------------------
# orchestrator: outbox-near-full engages the dispatch valve
# ---------------------------------------------------------------------------
class TestOutboxBackpressureValve:
    def test_near_full_outbox_pauses_dispatch(self, tmp_path):
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.orchestrator import Orchestrator
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        class _FakeOutbox:
            full = True

            def near_full(self):
                return self.full

            def depth(self):
                return 7

        class _FakeBus(InMemoryBus):
            outbox = _FakeOutbox()

        bus = _FakeBus()
        sm = CompositeStateManager(StateConfig(
            crawl_id="c1", crawl_execution_id="e1",
            storage_root=str(tmp_path / "s"), sql=SqlConfig(url=":memory:")))
        orch = Orchestrator(
            "c1", CrawlerConfig(crawl_id="c1", platform="telegram",
                                skip_media_download=True,
                                sampling_method="channel"), bus, sm)
        flight.RECORDER.reset()
        assert orch._backpressure_engaged() is True
        kinds = [(e["kind"], e.get("reason"))
                 for e in flight.RECORDER.events()]
        assert ("backpressure", "bus_outbox_near_full") in kinds
        # Latched once, released the moment the flusher drains.
        assert orch._backpressure_engaged() is True
        bus.outbox.full = False
        assert orch._backpressure_engaged() is False
        sm.close()


# ---------------------------------------------------------------------------
# gate: kill-broker acceptance
# ---------------------------------------------------------------------------
class TestKillBrokerGate:
    def test_down_bus_without_durability_is_a_config_error(self):
        """Without a bus_durability block, `down bus` would report
        phantom lost items (the generator's publish raises into a dead
        broker) — the gate refuses up front instead."""
        from distributed_crawler_tpu.loadgen.gate import (
            load_scenario,
            run_scenario,
        )

        sc = load_scenario("kill-broker")
        del sc["bus_durability"]
        with pytest.raises(ValueError, match="bus_durability"):
            run_scenario(sc)

    def test_kill_broker_scenario_zero_loss_across_generations(self):
        """ISSUE 10 acceptance: the broker is hard-killed mid-load on the
        gRPC leg and restarted as a new generation over the same spool
        dir + port.  Zero lost and zero duplicated items by id
        reconciliation across the generation boundary, the
        bus_kill/bus_resume flight events, a batch_age breach during the
        outage, zero unrouted drops, and a clean recovery tail."""
        from distributed_crawler_tpu.loadgen.gate import (
            load_scenario,
            run_scenario,
        )

        verdict = run_scenario(load_scenario("kill-broker"))
        assert verdict["status"] == "pass", verdict["checks"]
        assert verdict["lost"] == 0 and verdict["duplicates"] == 0
        assert verdict["bus_generations"] == 2
        assert verdict["bus_broker"]["durable"]
        assert verdict["bus_broker"]["outbox_depth_end"] == 0
        assert verdict["fault_breaches"].get("batch_age", 0) > 0
        assert verdict["tail_breaches"] == {}
        assert verdict["checks"]["flight_bus_kill"]["ok"]
        assert verdict["checks"]["flight_bus_resume"]["ok"]
        assert verdict["checks"]["bus_unrouted"]["ok"]
        assert verdict["checks"]["endpoint_dlq"]["ok"]
