"""mode=transcribe e2e (BASELINE config #4): a crawl's media tree of
16 kHz wavs → Whisper batch transcription → transcripts JSONL, plus the
optional hop onto the inference bus so transcripts flow through
embed+classify.  Uses the synthetic tiny HF Whisper checkpoint from
test_hf_convert (real converter path, millisecond-scale decode)."""

import json
import os
import wave

import numpy as np
import pytest

from distributed_crawler_tpu.cli import main
from tests.test_hf_convert import WH_CFG, make_whisper_state


@pytest.fixture()
def whisper_ckpt(tmp_path):
    from safetensors.numpy import save_file

    path = str(tmp_path / "whisper")
    os.makedirs(path)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(WH_CFG, f)
    save_file(make_whisper_state(), os.path.join(path, "model.safetensors"))
    return path


def _write_wav(path, seconds=0.3, rate=16_000, freq=440.0):
    t = np.arange(int(seconds * rate)) / rate
    pcm = (np.sin(2 * np.pi * freq * t) * 0.3 * 32767).astype(np.int16)
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())


class TestTranscribeMode:
    def test_media_tree_to_transcripts_jsonl(self, tmp_path, whisper_ckpt,
                                             capsys):
        media = tmp_path / "media"
        (media / "chan_a").mkdir(parents=True)
        _write_wav(media / "chan_a" / "voice1.wav")
        _write_wav(media / "chan_a" / "voice2.wav", freq=880.0)
        (media / "notes.txt").write_text("not audio")          # ignored
        (media / "bad.wav").write_bytes(b"RIFFgarbage")        # failed row

        rc = main(["--mode", "transcribe",
                   "--transcribe-input", str(media),
                   "--asr-pretrained-dir", whisper_ckpt,
                   "--asr-batch-size", "2",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["transcribed"] == 2
        assert summary["failed"] == 1
        rows = [json.loads(l) for l in
                open(summary["output"], encoding="utf-8")]
        by_path = {r["path"]: r for r in rows}
        assert set(by_path) == {"chan_a/voice1.wav", "chan_a/voice2.wav",
                                "bad.wav"}
        # Random weights decode arbitrary ids, but the pipeline must emit
        # SOME tokens for readable wavs and none for the corrupt one.
        assert by_path["chan_a/voice1.wav"]["tokens"]
        assert by_path["bad.wav"]["tokens"] == []

    def test_missing_args_rejected(self, tmp_path, whisper_ckpt):
        rc = main(["--mode", "transcribe",
                   "--asr-pretrained-dir", whisper_ckpt,
                   "--storage-root", str(tmp_path / "s")])
        assert rc == 2
        rc = main(["--mode", "transcribe",
                   "--transcribe-input", str(tmp_path),
                   "--storage-root", str(tmp_path / "s")])
        assert rc == 2

    def test_all_failed_run_exits_nonzero(self, tmp_path, whisper_ckpt,
                                          capsys):
        media = tmp_path / "media"
        media.mkdir()
        (media / "bad.wav").write_bytes(b"RIFFgarbage")
        rc = main(["--mode", "transcribe",
                   "--transcribe-input", str(media),
                   "--asr-pretrained-dir", whisper_ckpt,
                   "--storage-root", str(tmp_path / "s")])
        assert rc == 1  # gating scripts must not treat this as success
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["transcribed"] == 0 and summary["failed"] == 1

    def test_empty_tree_rejected(self, tmp_path, whisper_ckpt):
        (tmp_path / "media").mkdir()
        rc = main(["--mode", "transcribe",
                   "--transcribe-input", str(tmp_path / "media"),
                   "--asr-pretrained-dir", whisper_ckpt,
                   "--storage-root", str(tmp_path / "s")])
        assert rc == 2

    def test_transcripts_publish_to_inference_bus(self, tmp_path,
                                                  whisper_ckpt):
        from distributed_crawler_tpu.bus.codec import RecordBatch
        from distributed_crawler_tpu.bus.grpc_bus import (
            GrpcBusClient,
            GrpcBusServer,
        )
        from distributed_crawler_tpu.bus.messages import (
            TOPIC_INFERENCE_BATCHES,
        )

        media = tmp_path / "media"
        media.mkdir()
        _write_wav(media / "clip.wav")

        server = GrpcBusServer("127.0.0.1:0")
        server.start()
        server.enable_pull(TOPIC_INFERENCE_BATCHES)
        try:
            rc = main(["--mode", "transcribe",
                       "--transcribe-input", str(media),
                       "--asr-pretrained-dir", whisper_ckpt,
                       "--infer",
                       "--bus-address", f"127.0.0.1:{server.bound_port}",
                       "--crawl-id", "asr1",
                       "--storage-root", str(tmp_path / "s")])
            assert rc == 0
            client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            stream = client.pull(TOPIC_INFERENCE_BATCHES)
            batch = None
            for delivery_id, frame in stream:
                batch = RecordBatch.from_dict(json.loads(frame))
                client.ack(TOPIC_INFERENCE_BATCHES, delivery_id, ok=True)
                break
            stream.close()
            client.close()
            assert batch is not None
            assert batch.crawl_id == "asr1"
            assert batch.records[0]["post_uid"] == "media:clip.wav"
            assert batch.records[0]["channel_name"] == "transcripts"
            assert batch.texts()[0]  # token-id text from random weights
        finally:
            server.close()
