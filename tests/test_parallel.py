"""Mesh/sharding/ring-attention tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_crawler_tpu.ops.attention import attend
from distributed_crawler_tpu.parallel import (
    MeshConfig, best_mesh_config, make_mesh, param_specs, shard_batch,
    shard_params,
)
from distributed_crawler_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP
from distributed_crawler_tpu.parallel.ring import make_ring_attention, ring_attention
from distributed_crawler_tpu.parallel.sharding import spec_for_path, ENCODER_PARAM_RULES


class TestMeshConfig:
    def test_best_config_defaults_to_dp(self):
        cfg = best_mesh_config(8)
        assert (cfg.dp, cfg.sp, cfg.tp) == (8, 1, 1)

    def test_best_config_with_tp_sp(self):
        cfg = best_mesh_config(8, tp=2, sp=2)
        assert (cfg.dp, cfg.sp, cfg.tp) == (2, 2, 2)
        assert cfg.n_devices == 8

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            best_mesh_config(8, tp=3)

    def test_bad_axis_raises(self):
        with pytest.raises(ValueError):
            MeshConfig(dp=0).validate()

    def test_make_mesh_8_devices(self):
        mesh = make_mesh(best_mesh_config(8, tp=2, sp=2))
        assert mesh.shape == {"dp": 2, "sp": 2, "tp": 2}

    def test_make_mesh_wrong_count(self):
        with pytest.raises(ValueError):
            make_mesh(MeshConfig(dp=3))


class TestShardingRules:
    def test_qkv_kernel_tp_sharded(self):
        assert spec_for_path("encoder/layers_0/attn/q/kernel",
                             ENCODER_PARAM_RULES) == P(None, AXIS_TP)

    def test_attn_out_row_sharded(self):
        assert spec_for_path("encoder/layers_3/attn/attn_out/kernel",
                             ENCODER_PARAM_RULES) == P(AXIS_TP, None)

    def test_layernorm_replicated(self):
        assert spec_for_path("encoder/layers_0/ln_attn/scale",
                             ENCODER_PARAM_RULES) == P()

    def test_embed_replicated(self):
        assert spec_for_path("encoder/embed_tokens",
                             ENCODER_PARAM_RULES) == P()

    def test_moe_expert_sharded(self):
        assert spec_for_path("encoder/layers_0/moe/experts_up/kernel",
                             ENCODER_PARAM_RULES) == P(AXIS_TP, None, None)

    def test_shard_params_places_on_mesh(self):
        mesh = make_mesh(best_mesh_config(8, tp=2))
        params = {
            "layers_0": {
                "attn": {"q": {"kernel": jnp.ones((16, 16)),
                               "bias": jnp.ones((16,))}},
                "mlp": {"mlp_up": {"kernel": jnp.ones((16, 32))}},
                "ln_attn": {"scale": jnp.ones((16,))},
            }
        }
        sharded = shard_params(params, mesh)
        q = sharded["layers_0"]["attn"]["q"]["kernel"]
        spec = q.sharding.spec
        assert spec == P(None, AXIS_TP)
        ln = sharded["layers_0"]["ln_attn"]["scale"]
        assert ln.sharding.spec == P()

    def test_prune_indivisible_falls_back_to_replicated(self):
        mesh = make_mesh(best_mesh_config(8, tp=2))
        params = {"attn": {"q": {"kernel": jnp.ones((16, 15))}}}  # 15 % 2 != 0
        sharded = shard_params(params, mesh)
        assert sharded["attn"]["q"]["kernel"].sharding.spec == P(None, None)

    def test_shard_batch(self):
        mesh = make_mesh(best_mesh_config(8, tp=2, sp=2))
        ids = jnp.zeros((8, 64), jnp.int32)
        out = shard_batch({"ids": ids}, mesh)
        assert out["ids"].sharding.spec == P(AXIS_DP, AXIS_SP)


class TestRingAttention:
    def _inputs(self, b=4, l=32, h=4, d=8, seed=0):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        # Padding tail per row, never fully masked.
        mask = np.ones((b, l), dtype=bool)
        for i in range(b):
            mask[i, l - rng.integers(0, l // 2):] = False
        return q, k, v, jnp.asarray(mask)

    def test_matches_reference_full_mask(self):
        mesh = make_mesh(best_mesh_config(8, sp=2, tp=2))
        q, k, v, _ = self._inputs()
        mask = jnp.ones(q.shape[:2], dtype=bool)
        ring = make_ring_attention(mesh)
        out = ring(q, k, v, mask)
        ref = attend(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_matches_reference_padded(self):
        mesh = make_mesh(best_mesh_config(8, sp=4, tp=1))
        q, k, v, mask = self._inputs()
        ring = make_ring_attention(mesh)
        out = ring(q, k, v, mask)
        ref = attend(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_sp1_degenerates_to_reference(self):
        mesh = make_mesh(best_mesh_config(8, sp=1))
        q, k, v, mask = self._inputs(b=8)
        ring = make_ring_attention(mesh)
        out = ring(q, k, v, mask)
        ref = attend(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_jit_compiles_under_mesh(self):
        mesh = make_mesh(best_mesh_config(8, sp=2))
        q, k, v, mask = self._inputs()
        ring = jax.jit(make_ring_attention(mesh))
        out = ring(q, k, v, mask)
        assert out.shape == q.shape


class TestMultihost:
    """Multi-host bring-up + host-major mesh placement (the NCCL/MPI-scale
    analog: tp/sp pinned to ICI within a host, dp across DCN)."""

    def test_config_from_env_and_validation(self):
        from distributed_crawler_tpu.parallel.multihost import (
            MultihostConfig,
        )

        cfg = MultihostConfig.from_env({
            "DCT_COORDINATOR": "10.0.0.1:8476",
            "DCT_NUM_PROCESSES": "4", "DCT_PROCESS_ID": "2"})
        cfg.validate()
        assert cfg.num_processes == 4 and cfg.process_id == 2
        with pytest.raises(ValueError, match="DCT_COORDINATOR"):
            MultihostConfig(num_processes=2).validate()
        with pytest.raises(ValueError, match="out of range"):
            MultihostConfig(coordinator_address="a:1", num_processes=2,
                            process_id=5).validate()

    def test_single_process_initialize_noop(self):
        from distributed_crawler_tpu.parallel.multihost import (
            MultihostConfig,
            initialize_multihost,
        )

        assert initialize_multihost(MultihostConfig()) is False

    def test_hostmajor_keeps_tp_within_host(self):
        from distributed_crawler_tpu.parallel.mesh import MeshConfig
        from distributed_crawler_tpu.parallel.multihost import (
            device_mesh_hostmajor,
        )

        # 8 "devices" on 2 hosts (4 each), interleaved arrival order.
        devices = [f"d{i}" for i in range(8)]
        host_of = [0, 1, 0, 1, 0, 1, 0, 1]
        arranged = device_mesh_hostmajor(
            devices, MeshConfig(dp=2, sp=1, tp=4), host_of=host_of)
        assert arranged.shape == (2, 1, 4)
        # Each dp row (a tp group) must be single-host.
        row0 = {host_of[devices.index(d)] for d in arranged[0, 0]}
        row1 = {host_of[devices.index(d)] for d in arranged[1, 0]}
        assert row0 == {0} and row1 == {1}

    def test_tp_group_straddling_hosts_rejected(self):
        from distributed_crawler_tpu.parallel.mesh import MeshConfig
        from distributed_crawler_tpu.parallel.multihost import (
            device_mesh_hostmajor,
        )

        devices = [f"d{i}" for i in range(8)]
        host_of = [0, 0, 0, 1, 1, 1, 2, 2]  # 3/3/2 split
        with pytest.raises(ValueError, match="straddle"):
            device_mesh_hostmajor(devices, MeshConfig(dp=2, sp=1, tp=4),
                                  host_of=host_of)

    def test_global_mesh_runs_sharded_step(self):
        """make_global_mesh on the 8-device CPU backend drives a real
        sharded computation."""
        import jax
        import jax.numpy as jnp

        from distributed_crawler_tpu.parallel.mesh import MeshConfig
        from distributed_crawler_tpu.parallel.multihost import (
            make_global_mesh,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_global_mesh(MeshConfig(dp=4, sp=1, tp=2))
        assert mesh.shape == {"dp": 4, "sp": 1, "tp": 2}
        x = jnp.arange(32.0).reshape(8, 4)
        placed = jax.device_put(
            x, NamedSharding(mesh, P("dp", None)))
        out = jax.jit(lambda a: (a * 2).sum())(placed)
        assert float(out) == float((x * 2).sum())

    def test_bad_env_int_named_in_error(self):
        from distributed_crawler_tpu.parallel.multihost import (
            MultihostConfig,
        )

        with pytest.raises(ValueError, match="DCT_NUM_PROCESSES"):
            MultihostConfig.from_env({"DCT_NUM_PROCESSES": "four"})
        # Trailing whitespace tolerated.
        assert MultihostConfig.from_env(
            {"DCT_NUM_PROCESSES": "4 ", "DCT_PROCESS_ID": "1",
             "DCT_COORDINATOR": "c:1"}).num_processes == 4


class TestPipelineParallel:
    """GPipe-style pp over a mesh axis (SURVEY §2.3.4-5's task pipelines
    applied to the model): microbatches stream through layer stages via
    ppermute; results must match running every stage sequentially."""

    def _setup(self, n_stages=4, n_micro=6, mb=2, width=8, seed=0):
        import numpy as np

        from distributed_crawler_tpu.parallel.pipeline import (
            make_pp_mesh,
            stack_stage_params,
        )

        rng = np.random.default_rng(seed)
        stages = [{"w": jnp.asarray(rng.standard_normal((width, width)),
                                    jnp.float32) * 0.3,
                   "b": jnp.asarray(rng.standard_normal(width),
                                    jnp.float32) * 0.1}
                  for _ in range(n_stages)]
        x = jnp.asarray(rng.standard_normal((n_micro, mb, width)),
                        jnp.float32)
        mesh = make_pp_mesh(jax.devices()[:n_stages])
        return stages, stack_stage_params(stages), x, mesh

    @staticmethod
    def _stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def _reference(self, stages, x):
        h = x
        for p in stages:
            h = self._stage_fn(p, h)
        return h

    def test_matches_sequential(self):
        from distributed_crawler_tpu.parallel.pipeline import pipeline_apply

        stages, stacked, x, mesh = self._setup()
        got = pipeline_apply(self._stage_fn, stacked, x, mesh)
        want = self._reference(stages, x)
        assert got.shape == x.shape
        assert jnp.allclose(got, want, atol=1e-5), \
            float(jnp.abs(got - want).max())

    def test_micro_equals_stages(self):
        from distributed_crawler_tpu.parallel.pipeline import pipeline_apply

        stages, stacked, x, mesh = self._setup(n_stages=4, n_micro=4)
        got = pipeline_apply(self._stage_fn, stacked, x, mesh)
        assert jnp.allclose(got, self._reference(stages, x), atol=1e-5)

    def test_jittable(self):
        from distributed_crawler_tpu.parallel.pipeline import pipeline_apply

        stages, stacked, x, mesh = self._setup(n_stages=2, n_micro=5)
        fn = jax.jit(lambda p, xx: pipeline_apply(
            self._stage_fn, p, xx, mesh))
        got = fn(stacked, x)
        assert jnp.allclose(got, self._reference(stages, x), atol=1e-5)

    def test_eight_stage_full_mesh(self):
        from distributed_crawler_tpu.parallel.pipeline import pipeline_apply

        stages, stacked, x, mesh = self._setup(n_stages=8, n_micro=10)
        got = pipeline_apply(self._stage_fn, stacked, x, mesh)
        assert jnp.allclose(got, self._reference(stages, x), atol=1e-5)
