"""The elastic-fleet autoscaler (`orchestrator/autoscaler.py`) + its
gate/scenario integration.

Covers, with injected clocks throughout: pool-policy validation and
config parsing; the control loop's hysteresis (per-direction cooldowns,
headroom stabilization, min/max bounds, flap resistance); trend
anticipation straight from the rolling store; alert intake via both the
watchtower read and the TOPIC_ALERTS message seam; decision flight
events + metrics + /autoscaler over real HTTP; the in-process and
subprocess supervisors (retire is ALWAYS drain-then-graceful-stop,
never kill); the serving workers' clean-shutdown announcement (a
retired worker goes OFFLINE, never "stale"); the loadgen rate_profile
and flood/dynamic-target chaos extensions; and the flash-crowd e2e gate
acceptance — breach -> alert -> scale-up -> converge -> scale-down with
zero lost items.
"""

import json
import sys
import time
import urllib.request

import pytest

from distributed_crawler_tpu.orchestrator.autoscaler import (
    Autoscaler,
    InProcessSupervisor,
    PoolPolicy,
    SubprocessSupervisor,
    WorkerHandleAdapter,
    default_subprocess_argv,
    pools_from_config,
)
from distributed_crawler_tpu.utils import flight
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    clear_autoscaler_provider,
    serve_metrics,
    set_autoscaler_provider,
)
from distributed_crawler_tpu.utils.timeseries import TimeSeriesStore


# --- fixtures ----------------------------------------------------------------

class FakeSupervisor:
    """Counts spawns/retires; actual() is the net count."""

    def __init__(self, initial=1, pool="tpu"):
        self.count = {pool: initial}
        self.events = []
        self.fail_spawn = False

    def actual(self, pool):
        return self.count[pool]

    def spawn(self, pool):
        if self.fail_spawn:
            raise RuntimeError("no capacity")
        self.count[pool] += 1
        self.events.append(("spawn", pool))
        return f"{pool}-{self.count[pool]}"

    def retire(self, pool):
        if self.count[pool] <= 0:
            return None
        self.count[pool] -= 1
        self.events.append(("retire", pool))
        return f"{pool}-retired"


class FakeAlerts:
    """A stand-in for the watchtower's get_alerts read."""

    def __init__(self):
        self.firing = []

    def __call__(self):
        return {"alerts": [{"rule": r, "state": "firing",
                            "fired_at": 1.0} for r in self.firing],
                "firing": list(self.firing)}


def make_autoscaler(clock, policy=None, initial=1, alerts=None,
                    store=None, registry=None, supervisor=None):
    policy = policy or PoolPolicy(
        pool="tpu", min_workers=1, max_workers=3,
        up_cooldown_s=5.0, down_cooldown_s=5.0,
        scale_up_alerts=["queue_wait_burn"],
        headroom_series="fleet_queue_depth", headroom_below=2.0,
        stabilization_s=10.0)
    supervisor = supervisor or FakeSupervisor(initial=initial)
    store = store if store is not None else TimeSeriesStore(clock=clock)
    return Autoscaler(
        supervisor, [policy], store=store,
        registry=registry or MetricsRegistry(), clock=clock,
        eval_interval_s=1.0, alerts_fn=alerts), supervisor, store


# --- policy config -----------------------------------------------------------

class TestPoolPolicyConfig:
    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown key"):
            PoolPolicy.from_dict({"pool": "tpu", "max_wrkers": 3})

    def test_bounds_validated(self):
        with pytest.raises(ValueError, match="max_workers"):
            PoolPolicy.from_dict({"pool": "tpu", "min_workers": 4,
                                  "max_workers": 2})
        with pytest.raises(ValueError, match="steps"):
            PoolPolicy.from_dict({"pool": "tpu", "scale_up_step": 0})
        with pytest.raises(ValueError, match="trend_slope_per_s"):
            PoolPolicy.from_dict({"pool": "tpu",
                                  "trend_series": "fleet_queue_depth"})

    def test_pools_from_config_duplicates(self):
        with pytest.raises(ValueError, match="duplicate"):
            pools_from_config([{"pool": "tpu"}, {"pool": "tpu"}])

    def test_roundtrip(self):
        p = PoolPolicy.from_dict({"pool": "asr", "min_workers": 2,
                                  "max_workers": 5,
                                  "scale_up_alerts": ["batch_age_burn"]})
        again = PoolPolicy.from_dict(p.to_dict())
        assert again == p


# --- the control loop --------------------------------------------------------

class TestAutoscalerPolicy:
    def test_scale_up_on_firing_alert(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        aut, sup, store = make_autoscaler(lambda: clk[0], alerts=alerts)
        assert aut.tick(force=True) == []          # quiet: no decision
        alerts.firing = ["queue_wait_burn"]
        decisions = aut.tick(force=True)
        assert len(decisions) == 1
        d = decisions[0]
        assert (d["direction"], d["from"], d["to"]) == ("up", 1, 2)
        assert d["reason"] == "queue_wait_burn"
        assert sup.count["tpu"] == 2
        assert d["actual_after"] == 2

    def test_up_cooldown_blocks_consecutive_ups(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]
        aut, sup, _ = make_autoscaler(lambda: clk[0], alerts=alerts)
        assert aut.tick(force=True)                # up 1 -> 2
        assert aut.tick(force=True) == []          # cooldown holds
        clk[0] += 5.1
        decisions = aut.tick(force=True)           # cooldown elapsed
        assert decisions and decisions[0]["to"] == 3

    def test_max_bound(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]
        aut, sup, _ = make_autoscaler(lambda: clk[0], alerts=alerts)
        for _ in range(6):
            aut.tick(force=True)
            clk[0] += 6.0
        assert sup.count["tpu"] == 3               # max_workers cap

    def test_unrelated_alert_is_not_pressure(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["dlq_growth"]
        aut, sup, _ = make_autoscaler(lambda: clk[0], alerts=alerts)
        assert aut.tick(force=True) == []
        assert sup.count["tpu"] == 1

    def _feed_headroom(self, store, clk, value=0.5, span_s=12.0,
                       step_s=1.0):
        t = clk[0] - span_s
        while t <= clk[0]:
            store.add("fleet_queue_depth", value, {"worker": "tpu-1"},
                      wall=t)
            t += step_s

    def test_scale_down_needs_stabilization(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        aut, sup, store = make_autoscaler(lambda: clk[0], alerts=alerts,
                                          initial=3)
        self._feed_headroom(store, clk)
        assert aut.tick(force=True) == []          # headroom_since set NOW
        clk[0] += 5.0
        self._feed_headroom(store, clk)
        assert aut.tick(force=True) == []          # held 5s < 10s
        clk[0] += 5.1
        self._feed_headroom(store, clk)
        decisions = aut.tick(force=True)           # held 10.1s
        assert decisions and decisions[0]["direction"] == "down"
        assert decisions[0]["reason"] == "headroom"
        assert sup.count["tpu"] == 2

    def test_down_cooldown_paces_consecutive_downs(self):
        clk = [1000.0]
        aut, sup, store = make_autoscaler(lambda: clk[0],
                                          alerts=FakeAlerts(), initial=3)
        self._feed_headroom(store, clk)
        aut.tick(force=True)
        clk[0] += 10.1
        self._feed_headroom(store, clk)
        assert aut.tick(force=True)[0]["direction"] == "down"
        clk[0] += 1.0
        self._feed_headroom(store, clk)
        assert aut.tick(force=True) == []          # down cooldown holds
        clk[0] += 4.2
        self._feed_headroom(store, clk)
        assert aut.tick(force=True)[0]["to"] == 1
        # Floor: no further downs ever.
        clk[0] += 20.0
        self._feed_headroom(store, clk)
        assert aut.tick(force=True) == []
        assert sup.count["tpu"] == 1

    def test_silence_is_not_headroom(self):
        # An EMPTY headroom series must never scale the fleet down.
        clk = [1000.0]
        aut, sup, _ = make_autoscaler(lambda: clk[0],
                                      alerts=FakeAlerts(), initial=3)
        for _ in range(5):
            clk[0] += 11.0
            assert aut.tick(force=True) == []
        assert sup.count["tpu"] == 3

    def test_flapping_alert_cannot_thrash(self):
        """fire/clear alternating every tick: ups are paced by the up
        cooldown, and downs never happen at all — every pressure tick
        resets the headroom stabilization window."""
        clk = [1000.0]
        alerts = FakeAlerts()
        aut, sup, store = make_autoscaler(lambda: clk[0], alerts=alerts)
        for i in range(40):
            alerts.firing = ["queue_wait_burn"] if i % 2 == 0 else []
            self._feed_headroom(store, clk)
            aut.tick(force=True)
            clk[0] += 1.0
        ups = [e for e in sup.events if e[0] == "spawn"]
        downs = [e for e in sup.events if e[0] == "retire"]
        assert len(downs) == 0
        # 40s of flapping with a 5s up-cooldown: at most 8 ups possible,
        # and the max bound caps actual growth at 2 spawns.
        assert len(ups) <= 2
        assert sup.count["tpu"] <= 3

    def test_trend_anticipation_scales_before_any_alert(self):
        clk = [1000.0]
        policy = PoolPolicy(
            pool="tpu", min_workers=1, max_workers=3,
            up_cooldown_s=5.0, scale_up_alerts=["queue_wait_burn"],
            trend_series="fleet_queue_depth", trend_slope_per_s=1.0,
            trend_window_s=10.0, stabilization_s=10.0)
        aut, sup, store = make_autoscaler(lambda: clk[0], policy=policy,
                                          alerts=FakeAlerts())
        # Queue depth climbing 2 units/s over the window: slope 2 > 1.
        for i in range(10):
            store.add("fleet_queue_depth", 2.0 * i, {"worker": "tpu-1"},
                      wall=clk[0] - 10.0 + i)
        decisions = aut.tick(force=True)
        assert decisions and decisions[0]["direction"] == "up"
        assert decisions[0]["reason"].startswith("trend:")
        assert sup.count["tpu"] == 2

    def test_under_min_fleet_grows_to_min(self):
        clk = [1000.0]
        policy = PoolPolicy(pool="tpu", min_workers=2, max_workers=4)
        aut, sup, _ = make_autoscaler(lambda: clk[0], policy=policy,
                                      initial=0, alerts=FakeAlerts())
        aut.tick(force=True)
        assert sup.count["tpu"] == 2

    def test_spawn_failure_reverts_desired(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]
        aut, sup, _ = make_autoscaler(lambda: clk[0], alerts=alerts)
        sup.fail_spawn = True
        flight.configure(capacity=256)
        aut.tick(force=True)
        assert sup.count["tpu"] == 1
        snap = aut.snapshot()
        assert snap["pools"]["tpu"]["desired"] == 1   # reverted
        kinds = [e["kind"] for e in flight.RECORDER.events()]
        assert "autoscale_error" in kinds

    def test_spawn_churn_backs_off(self):
        """Spawns that 'succeed' but whose workers die before the next
        tick (a crash-looping subprocess child) must trip a backoff, not
        a spawn storm."""
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]

        class DyingSupervisor(FakeSupervisor):
            def spawn(self, pool):
                wid = super().spawn(pool)
                self.count[pool] -= 1   # the child dies immediately
                return wid

        sup = DyingSupervisor(initial=1)
        aut, _, _ = make_autoscaler(lambda: clk[0], alerts=alerts,
                                    supervisor=sup)
        flight.configure(capacity=256)
        for _ in range(30):
            aut.tick(force=True)
            clk[0] += 1.0
        spawns = sum(1 for e in sup.events if e[0] == "spawn")
        # Without backoff this would be ~guard spawns on EVERY tick
        # (~180); the churn limit caps the storm at SPAWN_CHURN_LIMIT
        # passes and flags it.
        assert spawns <= 36, spawns
        assert any(e.get("op") == "spawn_churn"
                   for e in flight.RECORDER.events()
                   if e.get("kind") == "autoscale_error")
        snap = aut.snapshot()
        assert "actuation_backoff_s" in snap["pools"]["tpu"]
        # Actuation resumes once the backoff expires.
        clk[0] += 60.0
        aut.tick(force=True)
        assert sum(1 for e in sup.events if e[0] == "spawn") > spawns

    def test_eval_interval_rate_limits_unforced_ticks(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]
        aut, sup, _ = make_autoscaler(lambda: clk[0], alerts=alerts)
        aut.tick()
        assert aut.tick() == []     # limiter: within eval_interval_s
        clk[0] += 1.1
        assert sup.count["tpu"] == 2 or aut.tick()  # next window acts

    def test_bus_seam_observe_alert(self):
        clk = [1000.0]
        aut, sup, _ = make_autoscaler(lambda: clk[0], alerts=None)
        aut.observe_alert({"rule": "queue_wait_burn", "state": "firing",
                           "at_wall": clk[0]})
        decisions = aut.tick(force=True)
        assert decisions and decisions[0]["direction"] == "up"
        aut.observe_alert({"rule": "queue_wait_burn", "state": "resolved"})
        clk[0] += 6.0
        assert aut.tick(force=True) == []   # pressure gone, no headroom

    def test_metrics_and_store_series(self):
        clk = [1000.0]
        registry = MetricsRegistry()
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]
        aut, sup, store = make_autoscaler(lambda: clk[0], alerts=alerts,
                                          registry=registry)
        aut.tick(force=True)
        series = dict()
        for labels, value in registry.counter(
                "autoscaler_decisions_total").series():
            series[(labels.get("pool"), labels.get("direction"))] = value
        assert series[("tpu", "up")] == 1.0
        desired = {tuple(sorted(lbl.items())): v for lbl, v in
                   registry.gauge("autoscaler_desired_workers").series()}
        assert desired[(("pool", "tpu"),)] == 2.0
        assert store.latest("autoscaler_actual_workers",
                            {"pool": "tpu"}) == 2.0
        assert store.latest("autoscaler_desired_workers",
                            {"pool": "tpu"}) == 2.0

    def test_snapshot_shape(self):
        clk = [1000.0]
        aut, _, _ = make_autoscaler(lambda: clk[0], alerts=FakeAlerts())
        aut.tick(force=True)
        snap = aut.snapshot()
        assert snap["pools"]["tpu"]["min"] == 1
        assert snap["pools"]["tpu"]["max"] == 3
        assert snap["pools"]["tpu"]["actual"] == 1
        assert "up_remaining_s" in snap["pools"]["tpu"]["cooldown"]
        assert snap["decisions"] == []
        assert snap["ticks"] == 1
        json.dumps(snap)  # the /autoscaler body must be JSON-safe


# --- supervisors -------------------------------------------------------------

class _FakeWorker:
    def __init__(self, log, name):
        self.log = log
        self.name = name

    def drain(self, timeout_s=10.0):
        self.log.append(("drain", self.name))
        return True

    def stop(self, timeout_s=10.0):
        self.log.append(("stop", self.name))

    def kill(self):  # must NEVER be called by retirement
        self.log.append(("kill", self.name))


class TestInProcessSupervisor:
    def _sup(self, log):
        sup = InProcessSupervisor(drain_timeout_s=1.0)
        seq = [0]

        def spawn():
            seq[0] += 1
            return WorkerHandleAdapter(f"w{seq[0]}",
                                       _FakeWorker(log, f"w{seq[0]}"))

        sup.add_pool("tpu", spawn)
        return sup

    def test_spawn_retire_drain_then_stop_never_kill(self):
        log = []
        sup = self._sup(log)
        sup.attach("tpu", WorkerHandleAdapter("w0", _FakeWorker(log, "w0")))
        assert sup.actual("tpu") == 1
        assert sup.spawn("tpu") == "w1"
        assert sup.actual("tpu") == 2
        retired = sup.retire("tpu")
        assert retired == "w1"                     # newest-first
        assert ("drain", "w1") in log and ("stop", "w1") in log
        assert log.index(("drain", "w1")) < log.index(("stop", "w1"))
        assert not any(op == "kill" for op, _ in log)
        assert sup.actual("tpu") == 1
        assert sup.spawned["tpu"] == 1 and sup.retired["tpu"] == 1

    def test_retire_empty_pool_returns_none(self):
        sup = self._sup([])
        assert sup.retire("tpu") is None

    def test_on_change_fires(self):
        log = []
        changes = []
        sup = InProcessSupervisor(
            on_change=lambda pool, live: changes.append((pool, len(live))))
        sup.add_pool("tpu", lambda: WorkerHandleAdapter(
            "wX", _FakeWorker(log, "wX")))
        sup.spawn("tpu")
        sup.retire("tpu")
        assert changes == [("tpu", 1), ("tpu", 0)]

    def test_dead_handles_not_counted(self):
        log = []
        sup = self._sup(log)
        h = WorkerHandleAdapter("w0", _FakeWorker(log, "w0"))
        sup.attach("tpu", h)
        h.alive = False                            # chaos-killed
        assert sup.actual("tpu") == 0

    def test_stop_all(self):
        log = []
        sup = self._sup(log)
        sup.spawn("tpu")
        sup.spawn("tpu")
        sup.stop_all()
        assert sum(1 for op, _ in log if op == "stop") == 2


class TestSubprocessSupervisor:
    CHILD = ("import signal, sys, time\n"
             "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
             "time.sleep(60)\n")

    def test_spawn_and_graceful_retire(self):
        sup = SubprocessSupervisor(
            {"tpu": [sys.executable, "-c", self.CHILD]},
            term_timeout_s=10.0)
        assert sup.actual("tpu") == 0
        wid = sup.spawn("tpu")
        assert wid == "tpu-auto-1"
        assert sup.actual("tpu") == 1
        assert sup.children("tpu") == ["tpu-auto-1"]
        retired = sup.retire("tpu")
        assert retired == "tpu-auto-1"
        assert sup.actual("tpu") == 0
        assert sup.retire("tpu") is None

    def test_worker_id_substitution_and_reap(self):
        sup = SubprocessSupervisor(
            {"tpu": [sys.executable, "-c",
                     "import sys; sys.exit(0)  # {worker_id}"]})
        sup.spawn("tpu")
        deadline = time.monotonic() + 10.0
        while sup.actual("tpu") and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sup.actual("tpu") == 0              # exited child reaped

    def test_default_argv(self):
        argv = default_subprocess_argv("tpu", "127.0.0.1:7777",
                                       extra_args=["--infer-model", "t"])
        assert "--mode" in argv and "tpu-worker" in argv
        assert "{worker_id}" in argv
        assert "127.0.0.1:7777" in argv and "--infer-model" in argv
        asr = default_subprocess_argv("asr", "127.0.0.1:7777")
        assert "asr-worker" in asr


# --- /autoscaler over HTTP + bundle embedding --------------------------------

class TestAutoscalerSurface:
    def test_http_endpoint(self):
        clk = [1000.0]
        aut, _, _ = make_autoscaler(lambda: clk[0], alerts=FakeAlerts())
        aut.tick(force=True)
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        set_autoscaler_provider(aut.snapshot)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/autoscaler", timeout=5).read())
            assert body["pools"]["tpu"]["actual"] == 1
            assert body["decision_count"] == 0
        finally:
            clear_autoscaler_provider(aut.snapshot)
            server.shutdown()
        # Without a provider the route 404s like the other seams.
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/autoscaler", timeout=5)
            assert err.value.code == 404
        finally:
            server.shutdown()

    def test_flight_bundle_embeds_decision_log(self):
        clk = [1000.0]
        alerts = FakeAlerts()
        alerts.firing = ["queue_wait_burn"]
        aut, _, _ = make_autoscaler(lambda: clk[0], alerts=alerts)
        aut.tick(force=True)
        set_autoscaler_provider(aut.snapshot)
        try:
            bundle = flight.RECORDER.bundle("test")
            assert bundle["autoscaler"]["decision_count"] == 1
            assert bundle["autoscaler"]["decisions"][0]["direction"] == "up"
        finally:
            clear_autoscaler_provider(aut.snapshot)

    def test_watch_panel_and_postmortem_digest(self):
        sys.path.insert(0, "tools")
        try:
            from tools.postmortem import _autoscaler_digest
            from tools.watch import render_dashboard
        except ImportError:
            from postmortem import _autoscaler_digest  # script mode
            from watch import render_dashboard
        snap = {"pools": {"tpu": {"desired": 2, "actual": 1, "min": 1,
                                  "max": 3, "pressure": ["queue_wait_burn"],
                                  "cooldown": {"up_remaining_s": 1.0,
                                               "down_remaining_s": 0.0}}},
                "decisions": [{"at": 10.0, "pool": "tpu",
                               "direction": "up", "from": 1, "to": 2,
                               "reason": "queue_wait_burn"}]}
        page = render_dashboard({}, {}, {}, now=20.0, autoscaler=snap)
        assert "autoscaler pool" in page and "converging" in page
        assert "1 -> 2" in page and "queue_wait_burn" in page
        digest = _autoscaler_digest(snap)
        assert any("desired=2" in line for line in digest)
        assert any("up" in line and "1 -> 2" in line for line in digest)


# --- clean-shutdown announcement ---------------------------------------------

class _CaptureBus:
    def __init__(self):
        self.published = []

    def publish(self, topic, payload):
        self.published.append((topic, payload))

    def subscribe(self, topic, handler):
        pass


class TestStoppingAnnouncement:
    def _worker(self, bus):
        from distributed_crawler_tpu.inference.worker import (
            TPUWorker,
            TPUWorkerConfig,
        )

        class _Engine:
            class cfg:
                model = "fake"

        return TPUWorker(bus, _Engine(), provider=None,
                         cfg=TPUWorkerConfig(worker_id="tpu-x",
                                             span_export_interval_s=0.0),
                         registry=MetricsRegistry())

    def test_graceful_stop_announces_offline(self):
        from distributed_crawler_tpu.bus.messages import (
            MSG_WORKER_STOPPING,
            TOPIC_WORKER_STATUS,
            StatusMessage,
            WORKER_OFFLINE,
        )
        from distributed_crawler_tpu.orchestrator.fleet import FleetView

        bus = _CaptureBus()
        w = self._worker(bus)
        w.stop()
        stopping = [p for t, p in bus.published
                    if t == TOPIC_WORKER_STATUS
                    and p.get("message_type") == MSG_WORKER_STOPPING]
        assert len(stopping) == 1
        msg = StatusMessage.from_dict(stopping[0])
        assert msg.status == WORKER_OFFLINE
        assert msg.worker_type == "tpu"
        # Idempotent: a second stop (gate teardown) announces nothing new.
        w.stop()
        assert len([p for t, p in bus.published
                    if p.get("message_type") == MSG_WORKER_STOPPING]) == 1
        # The fleet fold marks it cleanly OFFLINE — never stale.
        fleet = FleetView(stale_after_s=0.0, registry=MetricsRegistry())
        assert fleet.observe(msg)
        time.sleep(0.01)
        assert fleet.stale_count() == 0
        assert fleet.export()["workers"]["tpu-x"]["status"] == \
            WORKER_OFFLINE

    def test_kill_stays_silent(self):
        from distributed_crawler_tpu.bus.messages import MSG_WORKER_STOPPING

        bus = _CaptureBus()
        w = self._worker(bus)
        w.kill()
        w.stop()   # stop-after-kill (gate teardown) must stay silent too
        assert not any(p.get("message_type") == MSG_WORKER_STOPPING
                       for _, p in bus.published)


# --- loadgen extensions ------------------------------------------------------

class TestRateProfile:
    def test_validation(self):
        from distributed_crawler_tpu.loadgen.generator import LoadGenConfig

        with pytest.raises(ValueError, match="pairs"):
            LoadGenConfig(rate_profile=[[1.0]]).validate()
        with pytest.raises(ValueError, match="ascending"):
            LoadGenConfig(rate_profile=[[2.0, 5], [1.0, 9]]).validate()
        with pytest.raises(ValueError, match="positive"):
            LoadGenConfig(rate_profile=[[1.0, 0]]).validate()
        with pytest.raises(ValueError, match="poisson"):
            LoadGenConfig(arrival="ramp",
                          rate_profile=[[1.0, 5]]).validate()
        LoadGenConfig(rate_profile=[[1.0, 5], [2.0, 50]]).validate()

    def test_rate_at_lookup(self):
        from distributed_crawler_tpu.loadgen.generator import LoadGenConfig

        cfg = LoadGenConfig(rate_batches_per_s=4.0,
                            rate_profile=[[2.0, 40.0], [4.0, 4.0]])
        assert cfg.rate_at(0.0) == 4.0
        assert cfg.rate_at(1.99) == 4.0
        assert cfg.rate_at(2.0) == 40.0
        assert cfg.rate_at(3.9) == 40.0
        assert cfg.rate_at(4.0) == 4.0

    def test_step_plan_is_deterministic_and_denser(self):
        from distributed_crawler_tpu.loadgen.generator import (
            LoadGenConfig,
            SyntheticWorkload,
        )

        cfg = dict(seed=5, duration_s=6.0, rate_batches_per_s=4.0,
                   rate_profile=[[2.0, 40.0], [4.0, 4.0]],
                   records_per_batch=2)
        plan_a = SyntheticWorkload(LoadGenConfig(**cfg)).plan()
        plan_b = SyntheticWorkload(LoadGenConfig(**cfg)).plan()
        assert [p.offset_s for p in plan_a] == [p.offset_s for p in plan_b]
        in_step = sum(1 for p in plan_a if 2.0 <= p.offset_s < 4.0)
        outside = sum(1 for p in plan_a if p.offset_s < 2.0
                      or p.offset_s >= 4.0)
        assert in_step > 3 * outside   # the 10x step dominates arrivals


class TestChaosExtensions:
    def test_flood_line_parses(self):
        from distributed_crawler_tpu.loadgen.chaos import parse_fault

        f = parse_fault("at=1s flood network 2s")
        assert (f.action, f.target, f.at_s, f.arg_s) == \
            ("flood", "network", 1.0, 2.0)
        with pytest.raises(ValueError):
            parse_fault("at=1s flood network")     # duration required

    def test_static_controller_rejects_unknown_target(self):
        from distributed_crawler_tpu.loadgen.chaos import (
            ChaosController,
            parse_timeline,
        )

        timeline = parse_timeline(["at=0s kill tpu-9"])
        with pytest.raises(ValueError, match="unknown target"):
            ChaosController(timeline, targets={})

    def test_dynamic_targets_register_mid_run(self):
        from distributed_crawler_tpu.loadgen.chaos import (
            ChaosController,
            parse_timeline,
        )

        killed = []

        class H:
            def kill(self):
                killed.append(True)

        timeline = parse_timeline(["at=0.5s kill tpu-dyn"])
        ctl = ChaosController(timeline, targets={}, dynamic_targets=True)
        ctl.tick(now_s=1.0)            # target missing -> error event
        assert any(e.get("phase") == "error" for e in ctl.events)
        ctl2 = ChaosController(timeline, targets={}, dynamic_targets=True)
        ctl2.register_target("tpu-dyn", H())
        ctl2.tick(now_s=1.0)
        assert killed == [True]

    def test_flood_handle_injects(self):
        from distributed_crawler_tpu.clients import SimNetwork
        from distributed_crawler_tpu.clients.errors import FloodWaitError
        from distributed_crawler_tpu.loadgen.gate import _SimNetworkHandle

        net = SimNetwork()
        handle = _SimNetworkHandle(net)
        handle.flood(1.0)
        with pytest.raises(FloodWaitError):
            net._check_fault("GetChatHistory")


class TestGateConfigValidation:
    def test_unknown_gate_key_rejected(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        with pytest.raises(ValueError, match="unknown gate key"):
            validate_gate_config({"name": "x",
                                  "gate": {"max_lsot": 0}})

    def test_unknown_autoscaler_key_rejected(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        with pytest.raises(ValueError, match="unknown autoscaler key"):
            validate_gate_config({"name": "x", "gate": {},
                                  "autoscaler": {"poolz": []}})
        with pytest.raises(ValueError, match="non-empty pools"):
            validate_gate_config({"name": "x", "gate": {},
                                  "autoscaler": {"pools": []}})

    def test_asr_scenarios_reject_autoscaler_block(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        with pytest.raises(ValueError, match="kind=asr"):
            validate_gate_config({
                "name": "x", "kind": "asr", "gate": {},
                "autoscaler": {"pools": [{"pool": "asr"}]}})

    def test_scale_event_specs_validated(self):
        from distributed_crawler_tpu.loadgen.gate import (
            validate_gate_config,
        )

        with pytest.raises(ValueError, match="during"):
            validate_gate_config({"name": "x", "gate": {
                "require_scale_event": [
                    {"direction": "up", "during": "recovey"}]}})
        with pytest.raises(ValueError, match="direction"):
            validate_gate_config({"name": "x", "gate": {
                "require_scale_event": [{"direction": "sideways"}]}})
        with pytest.raises(ValueError, match="require_scale_event"):
            validate_gate_config({"name": "x", "gate": {
                "require_scale_event": ["sideways"]}})
        with pytest.raises(ValueError, match="fault_window"):
            validate_gate_config({"name": "x", "gate": {
                "fault_window": [2.0]}})
        with pytest.raises(ValueError, match="fault_window"):
            validate_gate_config({"name": "x", "gate": {
                "fault_window": [3.0, 2.0]}})
        validate_gate_config({"name": "x", "gate": {
            "require_scale_event": ["up", {"pool": "tpu",
                                           "direction": "down",
                                           "during": "recovery"}],
            "fault_window": [1.0, 2.5]}})

    def test_checked_in_scenarios_validate(self):
        from distributed_crawler_tpu import loadgen

        for name in loadgen.scenario_names():
            loadgen.validate_gate_config(loadgen.load_scenario(name))


# --- e2e: the flash-crowd gate acceptance ------------------------------------

class TestFlashCrowdE2E:
    def test_flash_crowd_scenario_passes(self):
        """The tentpole loop, end to end on the real stack: the 10x step
        breaches queue-wait -> the burn alert fires -> the autoscaler
        spawns workers DURING the fault window -> the fleet drains the
        surge -> the alert resolves -> sustained headroom scales the
        pool back to its floor -> converged, with zero lost/duplicated
        items across the dynamic fleet."""
        from distributed_crawler_tpu import loadgen

        scenario = loadgen.load_scenario("flash-crowd")
        verdict = loadgen.run_scenario(scenario)
        assert verdict["status"] == "pass", json.dumps(verdict, indent=2)
        fleet = verdict["autoscaler"]
        assert fleet["fleet_sizes"]["max"] >= 2      # actually scaled up
        assert fleet["fleet_sizes"]["final"] == 1    # and back down
        assert fleet["converge_s"] is not None
        assert verdict["alerts"]["fired"].get("queue_wait_burn")
        assert verdict["lost"] == 0 and verdict["duplicates"] == 0
