"""MTProto 2.0 wire protocol (`clients/mtproto_wire.py` + its C++ twin
`native/mtproto.h`) — the reference's TDLib↔Telegram-DC transport
(`Dockerfile.tdlib:19-36`, `telegramhelper/client.go:319-377`), in-tree.

Layers tested:
- crypto primitives against published vectors (AES-IGE known answer);
- TL serialization roundtrips;
- the creating-an-auth-key handshake Python↔Python over a socketpair;
- MTProto 2.0 message encryption: roundtrip, tamper detection (the
  mandatory msg_key check), wrong-key rejection;
- cross-implementation parity: the C++ client (`native/mtproto.h`) against
  the Python gateway over a real socket — auth ladder + API calls riding
  AES-IGE encrypted messages end to end, plus a full crawl.
"""

import json
import socket
import threading
import time

import pytest

# Every layer here rides AES-IGE (even the TL roundtrips feed the
# handshake tests), so the whole module skips cleanly when the gated
# cryptography dep is absent — a collection ERROR would abort the suite.
pytest.importorskip("cryptography")

from distributed_crawler_tpu.clients.mtproto_wire import (  # noqa: E402
    DH_PRIME,
    RsaKey,
    ServerHandshake,
    Session,
    TlReader,
    Transport,
    client_handshake,
    factor_pq,
    generate_rsa_key,
    ige_decrypt,
    ige_encrypt,
    kdf,
    _small_prime,
    tl_bytes,
)

# One RSA keypair for the whole module (2048-bit generation isn't free).
RSA = generate_rsa_key()


class TestPrimitives:
    def test_ige_known_answer_vector(self):
        # Published AES-128-IGE test vector (OpenSSL's IGE example set).
        key = bytes.fromhex("000102030405060708090A0B0C0D0E0F")
        iv = bytes.fromhex("000102030405060708090A0B0C0D0E0F"
                           "101112131415161718191A1B1C1D1E1F")
        plain = bytes(32)
        cipher = ige_encrypt(key, iv, plain)
        assert cipher.hex().upper() == (
            "1A8519A6557BE652E9DA8E43DA4EF445"
            "3CF456B4CA488AA383C79C98B34797CB")
        assert ige_decrypt(key, iv, cipher) == plain

    def test_ige_roundtrip_aes256(self):
        key = bytes(range(32))
        iv = bytes(range(32, 64))
        data = bytes(range(256)) * 2
        assert ige_decrypt(key, iv, ige_encrypt(key, iv, data)) == data

    def test_ige_rejects_unaligned(self):
        with pytest.raises(ValueError):
            ige_encrypt(bytes(32), bytes(32), b"short")

    def test_tl_bytes_roundtrip(self):
        for payload in (b"", b"x", b"abc", b"\x00" * 253, b"y" * 254,
                        b"z" * 100_000):
            ser = tl_bytes(payload)
            assert len(ser) % 4 == 0
            assert TlReader(ser).tl_bytes() == payload

    def test_factor_pq(self):
        p, q = _small_prime(), _small_prime()
        lo, hi = sorted((p, q))
        assert factor_pq(p * q) == (lo, hi)

    def test_fingerprint_is_stable_and_key_dependent(self):
        pub = RsaKey(n=RSA.n, e=RSA.e)
        assert pub.fingerprint == RSA.fingerprint
        other = RsaKey(n=RSA.n + 2, e=RSA.e)
        assert other.fingerprint != pub.fingerprint

    def test_kdf_directions_differ(self):
        auth_key = bytes(range(256))
        msg_key = bytes(range(16))
        k1, iv1 = kdf(auth_key, msg_key, True)
        k2, iv2 = kdf(auth_key, msg_key, False)
        assert len(k1) == 32 and len(iv1) == 32
        assert (k1, iv1) != (k2, iv2)  # x=0 vs x=8


class TestSession:
    def _pair(self):
        auth_key = bytes((i * 37 + 5) % 256 for i in range(256))
        client = Session(auth_key=auth_key, server_salt=b"SALTSALT",
                         session_id=b"SESSIONi", is_client=True)
        server = Session(auth_key=auth_key, server_salt=b"SALTSALT",
                         session_id=b"SESSIONi", is_client=False)
        return client, server

    def test_roundtrip_both_directions(self):
        client, server = self._pair()
        for payload in (b"", b"x", b"hello world" * 100):
            assert server.decrypt(client.encrypt(payload)) == payload
            assert client.decrypt(server.encrypt(payload)) == payload

    def test_tamper_detected_by_msg_key_check(self):
        client, server = self._pair()
        packet = bytearray(client.encrypt(b"payload"))
        packet[-1] ^= 0x01
        with pytest.raises(ValueError, match="msg_key"):
            server.decrypt(bytes(packet))

    def test_wrong_auth_key_rejected(self):
        client, _ = self._pair()
        stranger = Session(auth_key=bytes(256), server_salt=b"SALTSALT",
                           session_id=b"SESSIONi", is_client=False)
        with pytest.raises(ValueError):
            stranger.decrypt(client.encrypt(b"payload"))

    def test_replay_rejected(self):
        """A recorded encrypted request replayed verbatim must not
        re-execute: peer msg_ids are strictly increasing (spec rule)."""
        client, server = self._pair()
        packet = client.encrypt(b"transfer-money")
        assert server.decrypt(packet) == b"transfer-money"
        with pytest.raises(ValueError, match="replay"):
            server.decrypt(packet)
        # The session keeps working for fresh messages.
        assert server.decrypt(client.encrypt(b"next")) == b"next"

    def test_session_id_switch_rejected(self):
        client, server = self._pair()
        server.decrypt(client.encrypt(b"a"))
        intruder = Session(auth_key=client.auth_key,
                           server_salt=client.server_salt,
                           session_id=b"EVILSESS", is_client=True)
        intruder._last_msg_id = client._last_msg_id  # fresh msg_id
        with pytest.raises(ValueError, match="session_id"):
            server.decrypt(intruder.encrypt(b"b"))

    def test_padding_and_alignment(self):
        client, _ = self._pair()
        packet = client.encrypt(b"q")
        # header(8+16) + ciphertext; ciphertext 16-aligned with >=12 pad.
        assert (len(packet) - 24) % 16 == 0
        assert len(packet) - 24 >= 8 + 8 + 8 + 4 + 4 + 1 + 12


class TestHandshake:
    def test_python_loopback_handshake_and_traffic(self):
        a, b = socket.socketpair()
        server_result = {}

        def serve():
            transport = Transport(a, is_server=True)
            hs = ServerHandshake(rsa=RSA)
            done = False
            while not done:
                reply, done = hs.handle(transport.recv())
                if reply:
                    transport.send(reply)
            sess = Session(auth_key=hs.auth_key,
                           server_salt=hs.server_salt,
                           session_id=b"", is_client=False)
            # decrypt() adopts the client's session_id from the first
            # validated message.
            msg = sess.decrypt(transport.recv())
            server_result["got"] = msg
            transport.send(sess.encrypt(b"pong:" + msg))

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        transport = Transport(b, is_server=False)
        sess = client_handshake(transport, RsaKey(n=RSA.n, e=RSA.e))
        assert len(sess.auth_key) == 256
        # auth_key must be a real DH value, not degenerate.
        assert int.from_bytes(sess.auth_key, "big") > 1
        assert int.from_bytes(sess.auth_key, "big") < DH_PRIME
        transport.send(sess.encrypt(b"ping"))
        reply = sess.decrypt(transport.recv())
        t.join(10)
        assert server_result["got"] == b"ping"
        assert reply == b"pong:ping"

    def test_adversarial_rsa_ciphertext_is_a_protocol_error(self):
        """Garbage encrypted_data must surface as ValueError (the class
        the session loop catches), not OverflowError from the raw-RSA
        range — a remote crash/log-spam vector otherwise."""
        import secrets

        from distributed_crawler_tpu.clients.mtproto_wire import (
            REQ_DH_PARAMS,
            REQ_PQ_MULTI,
            i64,
            int_to_bytes,
            plain_message,
            u32,
        )

        hs = ServerHandshake(rsa=RSA)
        nonce = secrets.token_bytes(16)
        reply, _ = hs.handle(plain_message(u32(REQ_PQ_MULTI) + nonce, 4))
        r = TlReader(reply)
        r.int64(); r.int64(); r.uint32()  # plain header
        rr = TlReader(r.raw(len(reply) - r.off))
        rr.uint32()
        rr.raw(16)
        server_nonce = rr.raw(16)
        pq = int.from_bytes(rr.tl_bytes(), "big")
        p, q = factor_pq(pq)
        req = (u32(REQ_DH_PARAMS) + nonce + server_nonce +
               tl_bytes(int_to_bytes(p)) + tl_bytes(int_to_bytes(q)) +
               i64(RSA.fingerprint) + tl_bytes(secrets.token_bytes(256)))
        with pytest.raises(ValueError):
            hs.handle(plain_message(req, 8))

    def test_keyring_selects_offered_fingerprint(self):
        """Real clients pin SEVERAL DC keys and pick whichever fingerprint
        the server offers in resPQ — a ring with a stale key first must
        still handshake via the matching one."""
        a, b = socket.socketpair()

        def serve():
            transport = Transport(a, is_server=True)
            hs = ServerHandshake(rsa=RSA)
            done = False
            while not done:
                reply, done = hs.handle(transport.recv())
                if reply:
                    transport.send(reply)

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        transport = Transport(b, is_server=False)
        stale = generate_rsa_key(1024)
        sess = client_handshake(transport, [
            RsaKey(n=stale.n, e=stale.e),       # stale pinned key
            RsaKey(n=RSA.n, e=RSA.e),           # the server's actual key
        ])
        assert len(sess.auth_key) == 256
        t.join(10)

    def test_load_keyring_formats(self, tmp_path):
        from distributed_crawler_tpu.clients.mtproto_wire import (
            load_keyring,
            save_pubkey,
        )

        single = tmp_path / "one.json"
        save_pubkey(str(single), RSA)
        assert [k.fingerprint for k in load_keyring(str(single))] == \
            [RSA.fingerprint]
        other = generate_rsa_key(1024)
        ring = tmp_path / "ring.json"
        ring.write_text(json.dumps({"keys": [
            {"n": hex(other.n), "e": other.e},
            {"n": hex(RSA.n), "e": RSA.e}]}))
        assert [k.fingerprint for k in load_keyring(str(ring))] == \
            [other.fingerprint, RSA.fingerprint]

    def test_wrong_pubkey_rejected_by_client(self):
        a, b = socket.socketpair()

        def serve():
            try:
                transport = Transport(a, is_server=True)
                hs = ServerHandshake(rsa=RSA)
                done = False
                while not done:
                    reply, done = hs.handle(transport.recv())
                    if reply:
                        transport.send(reply)
            except Exception:
                pass  # client aborts mid-handshake

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        transport = Transport(b, is_server=False)
        stranger = generate_rsa_key(1024)
        with pytest.raises(ValueError, match="fingerprint"):
            client_handshake(transport,
                             RsaKey(n=stranger.n, e=stranger.e))
        b.close()
        t.join(5)


# -- cross-implementation: the C++ client against the Python gateway --------

def _lib_available() -> bool:
    from distributed_crawler_tpu.clients.native import find_library

    try:
        find_library()
        return True
    except Exception:
        return False


SEED = json.dumps({
    "channels": [
        {"username": "mtroot", "id": 4242, "title": "MTProto Root",
         "member_count": 900,
         "messages": [
             {"date": 1700000000, "view_count": 5,
              "content": {"@type": "messageText",
                          "text": {"text": "go see @mtleaf",
                                   "entities": [
                                       {"type": {"@type":
                                                 "textEntityTypeMention"},
                                        "offset": 7, "length": 7}]}}},
         ]},
        {"username": "mtleaf", "id": 4243, "title": "Leaf",
         "member_count": 40,
         "messages": [
             {"date": 1700000050, "view_count": 1,
              "content": {"@type": "messageText",
                          "text": {"text": "leaf", "entities": []}}},
         ]},
    ],
})


@pytest.mark.skipif(not _lib_available(),
                    reason="libdct_client.so not built")
class TestCppClientAgainstPythonGateway:
    def test_auth_and_api_over_mtproto(self, tmp_path):
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
        )

        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       wire="mtproto", store_root=str(tmp_path)).start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, wire="mtproto",
                                     server_pubkey_file=gw.pubkey_file,
                                     conn_id="mt-e2e")
            try:
                c.authenticate("+15550001111", "13579")
                c.wait_ready(5.0)
                chat = c.search_public_chat("mtroot")
                assert chat.id == 4242
                assert chat.title == "MTProto Root"
                hist = c.get_chat_history(chat.id, limit=10)
                msgs = getattr(hist, "messages", hist)
                assert len(msgs) == 1
            finally:
                c.close()
            st = gw.status()
            assert st["wire"] == "mtproto"
            assert st["auth_successes"] == 1
            assert st["requests_served"] >= 2
        finally:
            gw.close()

    def test_cpp_client_keyring_selects_gateway_key(self, tmp_path):
        """The C++ twin of the keyring rule: a pubkey FILE holding a stale
        key first plus the gateway's real key handshakes fine — the native
        handshake selects by the offered resPQ fingerprint."""
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.mtproto_wire import (
            generate_rsa_key,
            load_pubkey,
        )
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
        )

        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       wire="mtproto", store_root=str(tmp_path)).start()
        try:
            real = load_pubkey(gw.pubkey_file)
            stale = generate_rsa_key(1024)
            ring = tmp_path / "keyring.json"
            ring.write_text(json.dumps({"keys": [
                {"n": hex(stale.n), "e": stale.e},
                {"n": hex(real.n), "e": real.e}]}))
            c = NativeTelegramClient(server_addr=gw.address, wire="mtproto",
                                     server_pubkey_file=str(ring),
                                     conn_id="mt-ring")
            try:
                c.authenticate("+15550001111", "13579")
                c.wait_ready(5.0)
                assert c.search_public_chat("mtroot").id == 4242
            finally:
                c.close()
        finally:
            gw.close()

    def test_concurrent_senders_over_mtproto(self, tmp_path):
        """ADVICE r04 (medium): msg_id assignment and the wire write must be
        ordered under ONE lock — with separate locks a later msg_id can reach
        the wire first, tripping the gateway's strictly-increasing replay
        check (`mtproto_wire.py` Session.decrypt) and killing the whole
        connection.  Six caller threads hammering one mtproto connection
        reproduce the race reliably when the ordering is broken."""
        import threading

        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
        )

        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       wire="mtproto", store_root=str(tmp_path)).start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, wire="mtproto",
                                     server_pubkey_file=gw.pubkey_file,
                                     conn_id="mt-stress")
            try:
                c.authenticate("+15550001111", "13579")
                c.wait_ready(5.0)
                n_threads, n_iters = 6, 25
                errors = []

                def hammer():
                    try:
                        for _ in range(n_iters):
                            assert c.search_public_chat("mtroot").id == 4242
                    except Exception as exc:  # noqa: BLE001 — collected
                        errors.append(exc)

                threads = [threading.Thread(target=hammer)
                           for _ in range(n_threads)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60)
                assert not errors, errors[:3]
                st = gw.status()
                # Every request was served over the ONE surviving session —
                # no replay-check connection kill, no reconnect.
                assert st["requests_served"] >= n_threads * n_iters
                assert st["auth_successes"] == 1
            finally:
                c.close()
        finally:
            gw.close()

    def test_file_lifecycle_over_typed_tl(self, tmp_path):
        """The dct.file constructor family (getRemoteFile/downloadFile —
        the media path transcription consumes) round-trips over the
        encrypted wire as TYPED TL, server-side store materializing the
        download."""
        import os

        from distributed_crawler_tpu.clients import tl_api
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
        )

        seed_with_file = json.loads(SEED)
        seed_with_file["files"] = [{"remote_id": "media-1", "size": 256}]
        before = dict(tl_api.STATS)
        gw = DcGateway(seed_json=json.dumps(seed_with_file),
                       expected_code="13579", wire="mtproto",
                       store_root=str(tmp_path)).start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, wire="mtproto",
                                     server_pubkey_file=gw.pubkey_file,
                                     conn_id="mt-file")
            try:
                c.authenticate("+15550001111", "13579")
                c.wait_ready(5.0)
                f = c.get_remote_file("media-1")
                assert not f.downloaded
                got = c.download_file(f.id)
                assert got.downloaded and got.local_path
                assert os.path.exists(got.local_path)  # same host
            finally:
                c.close()
        finally:
            gw.close()
        # Both file RPCs rode typed constructors, not the raw fallback.
        assert tl_api.STATS["typed_requests"] - before["typed_requests"] >= 2

    def test_persistent_rsa_key_across_restart(self, tmp_path):
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.mtproto_wire import load_pubkey

        gw1 = DcGateway(seed_json=SEED, wire="mtproto",
                        store_root=str(tmp_path)).start()
        fp1 = load_pubkey(gw1.pubkey_file).fingerprint
        gw1.close()
        gw2 = DcGateway(seed_json=SEED, wire="mtproto",
                        store_root=str(tmp_path)).start()
        fp2 = load_pubkey(gw2.pubkey_file).fingerprint
        gw2.close()
        # A restarted gateway serves the SAME key (clients keep their
        # pinned pubkey working), like Telegram's long-lived DC keys.
        assert fp1 == fp2

    def test_crawl_through_mtproto_gateway(self, tmp_path):
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
        )
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl.runner import run_for_channel
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       wire="mtproto", store_root=str(tmp_path)).start()
        try:
            client = NativeTelegramClient(
                server_addr=gw.address, wire="mtproto",
                server_pubkey_file=gw.pubkey_file, conn_id="mt-crawl")
            try:
                client.authenticate("+15550001111", "13579")
                client.wait_ready(5.0)
                sm = CompositeStateManager(StateConfig(
                    crawl_id="mtcrawl", crawl_execution_id="x1",
                    storage_root=str(tmp_path / "out"),
                    sql=SqlConfig(url=":memory:")))
                sm.initialize(["mtroot"])
                cfg = CrawlerConfig(crawl_id="mtcrawl",
                                    skip_media_download=True)
                page = sm.get_layer_by_depth(0)[0]
                discovered = run_for_channel(client, page, "", sm, cfg)
                assert page.status == "fetched"
                assert {p.url for p in discovered} == {"mtleaf"}
                posts_file = (tmp_path / "out" / "mtcrawl" / "mtroot"
                              / "posts" / "posts.jsonl")
                posts = [json.loads(line) for line
                         in posts_file.read_text().splitlines()]
                assert len(posts) == 1
                sm.close()
            finally:
                client.close()
        finally:
            gw.close()

    def test_auth_deadline_covers_mtproto_handshake(self, tmp_path):
        """A client that opens the intermediate transport but never
        finishes the auth-key handshake is dropped at the deadline."""
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway

        gw = DcGateway(seed_json=SEED, wire="mtproto",
                       store_root=str(tmp_path), auth_timeout_s=1.0).start()
        try:
            s = socket.create_connection((gw.host, gw.port), timeout=5)
            s.sendall(b"\xee\xee\xee\xee")  # transport init, then stall
            t0 = time.time()
            s.settimeout(5.0)
            try:
                data = s.recv(4096)
            except (OSError, socket.timeout):
                data = b"err"
            # Orderly close (b"") or reset, well before the recv timeout.
            assert data in (b"", b"err")
            assert time.time() - t0 < 4.0
            s.close()
        finally:
            gw.close()


@pytest.mark.skipif(not _lib_available(),
                    reason="libdct_client.so not built")
class TestCliMtprotoPath:
    def test_standalone_crawl_via_mtproto_wire(self, tmp_path):
        """The full config path over MTProto: `dct --urls … --dc-address …
        --dc-wire mtproto --dc-pubkey-file …` builds a remote pool whose
        connections complete the auth-key handshake and crawl through
        encrypted messages — no code injection anywhere."""
        import os

        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
            generate_pcode,
        )

        gw = DcGateway(
            seed_json=SEED,
            accounts={"+15557770000": {"code": "321", "password": ""}},
            wire="mtproto", store_root=str(tmp_path / "gw"),
        ).start()
        tdlib_dir = str(tmp_path / "td")
        out_root = str(tmp_path / "out")
        try:
            generate_pcode(
                tdlib_dir=tdlib_dir,
                env={"TG_API_ID": "9", "TG_PHONE_NUMBER": "+15557770000",
                     "TG_PHONE_CODE": "321"},
                client=NativeTelegramClient(
                    server_addr=gw.address, wire="mtproto",
                    server_pubkey_file=gw.pubkey_file, conn_id="cli-boot"))
            rc = main(["--urls", "mtroot", "--storage-root", out_root,
                       "--dc-address", gw.address,
                       "--dc-wire", "mtproto",
                       "--dc-pubkey-file", gw.pubkey_file,
                       "--tdlib-dir", tdlib_dir,
                       "--crawl-id", "cli-mt", "--skip-media",
                       "--max-depth", "1"])
            assert rc == 0
            posts = []
            for dirpath, _dn, files in os.walk(out_root):
                for f in files:
                    if f.endswith(".jsonl"):
                        with open(os.path.join(dirpath, f)) as fh:
                            posts += [json.loads(x) for x in fh]
            assert [p["channel_name"] for p in posts] == ["MTProto Root"]
            assert posts[0]["description"] == "go see @mtleaf"
            assert gw.status()["auth_successes"] >= 2
        finally:
            gw.close()


class TestFuzz:
    """Adversarial-input battery (the codec-fuzz pattern of
    tests/test_codec_fuzz.py applied to the wire protocol): malformed
    input must surface as ValueError — or ConnectionError for the
    transport-layer peer-closed signal — never a hang, crash, or other
    exception class escaping to the session loop."""

    def _expect_protocol_error(self, fn):
        try:
            fn()
        except ValueError:
            return
        except Exception as e:  # noqa: BLE001 — the assertion
            pytest.fail(f"non-protocol exception {type(e).__name__}: {e}")
        # Some inputs may parse as no-ops; that's fine too.

    def test_handshake_random_packets(self):
        import random

        rnd = random.Random(0xF00)
        for i in range(200):
            hs = ServerHandshake(rsa=RSA)
            blob = bytes(rnd.getrandbits(8)
                         for _ in range(rnd.randrange(0, 120)))
            self._expect_protocol_error(lambda: hs.handle(blob))

    def test_handshake_bitflipped_valid_flow(self):
        """Flip one byte at every position of a VALID req_pq_multi plain
        message; the server must reject or ignore, never crash."""
        import secrets

        from distributed_crawler_tpu.clients.mtproto_wire import (
            REQ_PQ_MULTI,
            plain_message,
            u32,
        )

        base = plain_message(u32(REQ_PQ_MULTI) + secrets.token_bytes(16), 4)
        for pos in range(len(base)):
            for bit in (0x01, 0x80):
                hs = ServerHandshake(rsa=RSA)
                mutated = bytearray(base)
                mutated[pos] ^= bit
                self._expect_protocol_error(
                    lambda m=bytes(mutated): hs.handle(m))

    def test_session_decrypt_random_packets(self):
        import random

        rnd = random.Random(0xBEEF)
        sess = Session(auth_key=bytes(range(256)), server_salt=b"s" * 8,
                       session_id=b"i" * 8, is_client=False)
        for n in (0, 1, 8, 23, 24, 55, 56, 57, 120, 4096):
            blob = bytes(rnd.getrandbits(8) for _ in range(n))
            with pytest.raises(ValueError):
                sess.decrypt(blob)
        # Correct auth_key_id prefix but garbage ciphertext: caught by
        # alignment (33) or the mandatory msg_key check (the aligned
        # sizes).
        for n in (32, 33, 48, 160):
            blob = sess.auth_key_id + bytes(
                rnd.getrandbits(8) for _ in range(16 + n))
            with pytest.raises(ValueError):
                sess.decrypt(blob)

    def test_live_gateway_survives_tl_garbage_after_handshake(self,
                                                              tmp_path):
        """An AUTHENTICATED-transport attacker (valid auth-key handshake,
        then validly-encrypted garbage TL frames) must cost only their own
        connection: the session thread catches the codec's ValueError,
        drops the connection, and the gateway keeps serving others."""
        import random
        import socket as socket_mod
        import struct as struct_mod

        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.mtproto_wire import (
            Transport as WireTransport,
        )
        from distributed_crawler_tpu.clients.mtproto_wire import (
            client_handshake,
            load_pubkey,
        )
        from distributed_crawler_tpu.clients.tl_api import BY_NAME

        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       wire="mtproto", store_root=str(tmp_path)).start()
        rnd = random.Random(0xD00D)
        try:
            pub = load_pubkey(gw.pubkey_file)
            host, port = gw.address.rsplit(":", 1)
            cases = []
            # Truncations of a real typed function at several cut points,
            # an unknown constructor id, and pure noise.
            whole = struct_mod.pack(
                "<I", BY_NAME["dct.getChat"].cid) + b"\x01\x02"
            cases += [whole[:n] for n in (4, 5)]
            cases.append(struct_mod.pack("<I", 0xDEADBEEF))
            cases += [bytes(rnd.getrandbits(8) for _ in range(n))
                      for n in (0, 3, 17, 64)]
            for payload in cases:
                s = socket_mod.create_connection((host, int(port)), 5)
                try:
                    transport = WireTransport(s, is_server=False)
                    sess = client_handshake(transport, pub)
                    transport.send(sess.encrypt(payload))
                    # The gateway drops us (clean close or reset) without
                    # dying; either is a pass as long as it ANSWERS the
                    # next handshake below.
                    s.settimeout(5)
                    try:
                        s.recv(64)
                    except (socket_mod.timeout, OSError):
                        pass
                finally:
                    s.close()
            # The gateway is still alive and serves a well-behaved client.
            from distributed_crawler_tpu.clients.native import (
                NativeTelegramClient,
            )

            c = NativeTelegramClient(server_addr=gw.address, wire="mtproto",
                                     server_pubkey_file=gw.pubkey_file,
                                     conn_id="post-fuzz")
            try:
                c.authenticate("+15550001111", "13579")
                c.wait_ready(5.0)
                assert c.search_public_chat("mtroot").id == 4242
            finally:
                c.close()
        finally:
            gw.close()

    def test_transport_oversized_and_truncated(self):
        import struct as struct_mod

        a, b = socket.socketpair()
        try:
            # socketpair buffers the 4-byte init, so the server-side
            # constructor can run inline after the client writes it.
            b.sendall(b"\xee\xee\xee\xee")
            t_server = Transport(a, is_server=True)
            # Oversized length prefix rejected without allocation.
            b.sendall(struct_mod.pack("<I", 1 << 31))
            with pytest.raises(ValueError, match="oversized"):
                t_server.recv()
            # Truncated frame surfaces as ConnectionError, not a hang.
            b.sendall(struct_mod.pack("<I", 64) + b"short")
            b.close()
            with pytest.raises(ConnectionError):
                t_server.recv()
        finally:
            a.close()


class TestProperties:
    """Property-based coverage (hypothesis) of the wire primitives: the
    roundtrip laws must hold for ALL inputs, not just the picked cases."""

    hypothesis = pytest.importorskip("hypothesis")

    def test_tl_bytes_roundtrip_property(self):
        from hypothesis import given, settings
        from hypothesis import strategies as st

        @settings(max_examples=200, deadline=None)
        @given(st.binary(max_size=70000))
        def check(payload):
            ser = tl_bytes(payload)
            assert len(ser) % 4 == 0
            r = TlReader(ser)
            assert r.tl_bytes() == payload
            assert r.off == len(ser)  # padding fully consumed

        check()

    def test_ige_roundtrip_property(self):
        from hypothesis import given, settings
        from hypothesis import strategies as st

        @settings(max_examples=100, deadline=None)
        @given(st.binary(min_size=32, max_size=32),
               st.binary(min_size=32, max_size=32),
               st.binary(max_size=512).map(
                   lambda d: d[:len(d) - len(d) % 16]))
        def check(key, iv, data):
            ct = ige_encrypt(key, iv, data)
            assert len(ct) == len(data)
            assert ige_decrypt(key, iv, ct) == data

        check()

    def test_session_roundtrip_property(self):
        from hypothesis import given, settings
        from hypothesis import strategies as st

        auth_key = bytes((i * 41 + 7) % 256 for i in range(256))

        @settings(max_examples=60, deadline=None)
        @given(st.binary(max_size=4096))
        def check(payload):
            client = Session(auth_key=auth_key, server_salt=b"S" * 8,
                             session_id=b"I" * 8, is_client=True)
            server = Session(auth_key=auth_key, server_salt=b"S" * 8,
                             session_id=b"I" * 8, is_client=False)
            assert server.decrypt(client.encrypt(payload)) == payload
            # And the server->client leg (x=8 KDF, server msg_id path).
            assert client.decrypt(server.encrypt(payload)) == payload

        check()


class TestTlLimit:
    def test_tl_bytes_rejects_16mib(self):
        """The TL long form carries a 3-byte length: >=2^24 payloads must
        raise loudly (a silent wrap corrupts the frame); big frames
        belong on the DCT-v1 wire."""
        with pytest.raises(ValueError, match="TL bytes limit"):
            tl_bytes(b"\x00" * (1 << 24))
        # Just under the limit still serializes.
        ser = tl_bytes(b"\x00" * ((1 << 24) - 1))
        assert TlReader(ser).tl_bytes() == b"\x00" * ((1 << 24) - 1)


@pytest.mark.skipif(not _lib_available(),
                    reason="libdct_client.so not built")
class TestGatewayArtifacts:
    def test_default_pubkey_lands_in_owned_tempdir(self):
        """No address_file/store_root: the pubkey must go to a gateway-
        owned tempdir (removed on close), never the process CWD."""
        import os

        from distributed_crawler_tpu.clients.dc_gateway import DcGateway

        cwd_before = set(os.listdir("."))
        gw = DcGateway(seed_json=SEED, wire="mtproto").start()
        pub = gw.pubkey_file
        assert os.path.exists(pub)
        assert os.path.dirname(os.path.abspath(pub)) != os.path.abspath(".")
        gw.close()
        assert not os.path.exists(pub)  # owned tempdir cleaned up
        assert set(os.listdir(".")) == cwd_before

    def test_generate_code_alias_honors_gateway_flags(self, tmp_path):
        """`--generate-code` (the legacy alias) must dial the gateway the
        dc_* flags point at — not silently mint against the embedded
        engine."""
        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway

        gw = DcGateway(
            seed_json=SEED, wire="mtproto", store_root=str(tmp_path / "gw"),
            accounts={"+15551112222": {"code": "99", "password": ""}},
        ).start()
        try:
            rc = main(["--generate-code",
                       "--dc-address", gw.address,
                       "--dc-wire", "mtproto",
                       "--dc-pubkey-file", gw.pubkey_file,
                       "--tdlib-dir", str(tmp_path / "td")],
                      env={"TG_API_ID": "1",
                           "TG_PHONE_NUMBER": "+15551112222",
                           "TG_PHONE_CODE": "99"})
            assert rc == 0
            assert (tmp_path / "td" / "credentials.json").exists()
            assert gw.status()["auth_successes"] == 1  # really dialed it
            # Wrong code against the gateway's account table must FAIL
            # (the embedded engine would have accepted anything).
            rc = main(["--generate-code",
                       "--dc-address", gw.address,
                       "--dc-wire", "mtproto",
                       "--dc-pubkey-file", gw.pubkey_file,
                       "--tdlib-dir", str(tmp_path / "td2")],
                      env={"TG_API_ID": "1",
                           "TG_PHONE_NUMBER": "+15551112222",
                           "TG_PHONE_CODE": "31337"})
            assert rc != 0
            assert not (tmp_path / "td2" / "credentials.json").exists()
        finally:
            gw.close()
