"""End-to-end wedge recovery: the failure mode this framework's watchdog +
at-least-once bus + idempotent writeback were designed around, exercised
together.  A TPU worker whose device step hangs forever stall-exits (via
the test seam standing in for os._exit) and its bus connection dies with
it; the un-acked frame requeues server-side; a replacement worker pulls
it and lands the writeback.  Zero batches lost — the full story behind
the `docs/operations.md` runbook row."""

import threading
import time

from distributed_crawler_tpu.bus.codec import RecordBatch
from distributed_crawler_tpu.bus.grpc_bus import GrpcBusServer, RemoteBus
from distributed_crawler_tpu.bus.messages import TOPIC_INFERENCE_BATCHES
from distributed_crawler_tpu.datamodel.post import Post
from distributed_crawler_tpu.inference.engine import EngineConfig
from distributed_crawler_tpu.inference.worker import TPUWorker, TPUWorkerConfig
from distributed_crawler_tpu.state.providers import InMemoryStorageProvider
from distributed_crawler_tpu.utils.metrics import MetricsRegistry


class WedgedEngine:
    """First call hangs until released — a tunneled chip mid-wedge."""

    cfg = EngineConfig()

    def __init__(self):
        self.release = threading.Event()

    def run(self, texts):
        self.release.wait(timeout=30.0)
        return [{"label": 0, "score": 1.0} for _ in texts]


class GoodEngine:
    cfg = EngineConfig()

    def run(self, texts):
        return [{"label": 1, "score": 0.9} for _ in texts]


def _wait(pred, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def test_stalled_worker_exits_and_replacement_finishes_the_batch():
    server = GrpcBusServer(address="127.0.0.1:0", ack_timeout_s=0.5)
    server.start()
    # Queue frames even before the first worker's pull stream is up —
    # otherwise a loaded host can publish into a topic nobody pulls yet.
    server.enable_pull(TOPIC_INFERENCE_BATCHES)
    addr = f"127.0.0.1:{server.bound_port}"
    wedged = WedgedEngine()
    worker_b = None
    bus_a = bus_b = producer = None
    try:
        # Worker A: wedged device, watchdog armed to exit fast.
        bus_a = RemoteBus(addr)
        worker_a = TPUWorker(bus_a, wedged,
                             cfg=TPUWorkerConfig(worker_id="wedged",
                                                 heartbeat_s=60.0,
                                                 stall_warn_s=0.1,
                                                 stall_exit_s=0.3),
                             registry=MetricsRegistry())
        exits = []
        worker_a._exit_fn = exits.append
        worker_a.start()

        producer = RemoteBus(addr)
        batch = RecordBatch.from_posts(
            [Post(post_uid="p0", channel_name="chan",
                  description="the batch a wedged worker must not lose")],
            crawl_id="c1")
        producer.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())

        # The watchdog detects the wedge and "kills the process".
        assert _wait(lambda: bool(exits)), "watchdog never fired exit"
        assert exits[0] == 17
        # Death of the process == death of its bus connection: the stream
        # teardown (or the 0.5 s ack timeout) requeues the un-acked frame.
        bus_a.close()
        assert _wait(
            lambda: server.pending_count(TOPIC_INFERENCE_BATCHES) >= 1), \
            "frame was not requeued after the stalled worker died"

        # Replacement worker with a healthy device picks it up.
        provider = InMemoryStorageProvider()
        bus_b = RemoteBus(addr)
        worker_b = TPUWorker(bus_b, GoodEngine(), provider=provider,
                             cfg=TPUWorkerConfig(worker_id="fresh",
                                                 heartbeat_s=60.0),
                             registry=MetricsRegistry())
        worker_b.start()
        rel = f"inference/c1/batches/{batch.batch_id}.jsonl"
        assert _wait(lambda: provider.exists(rel)), \
            "replacement worker never landed the writeback"
        text = provider.get_text(rel)
        assert '"label": 1' in text  # processed by the HEALTHY engine
        assert worker_b.drain(timeout_s=10.0)
        assert server.pending_count(TOPIC_INFERENCE_BATCHES) == 0
    finally:
        wedged.release.set()  # unstick worker A's feed thread
        if worker_b is not None:
            worker_b.stop(timeout_s=5.0)
        for b in (bus_b, producer):
            if b is not None:
                try:
                    b.close()
                except Exception:
                    pass
        server.close()
