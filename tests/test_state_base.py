"""Base/local state manager tests (reference analogs: state base behavior,
storageproviders persistence, SURVEY.md §4)."""

import json
import os

import pytest

from distributed_crawler_tpu.datamodel import Post
from distributed_crawler_tpu.state import (
    BaseStateManager,
    LocalConfig,
    LocalStateManager,
    Page,
    StateConfig,
)
from distributed_crawler_tpu.state.datamodels import (
    PAGE_DEADEND,
    PAGE_FETCHED,
    PAGE_UNFETCHED,
)


def cfg(**kw):
    base = dict(crawl_id="c1", crawl_execution_id="e1", platform="telegram")
    base.update(kw)
    return StateConfig(**base)


class TestBaseStateManager:
    def test_initialize_seeds_layer_zero(self):
        sm = BaseStateManager(cfg())
        sm.initialize(["a", "b"])
        pages = sm.get_layer_by_depth(0)
        assert {p.url for p in pages} == {"a", "b"}
        assert all(p.status == PAGE_UNFETCHED for p in pages)
        assert all(p.sequence_id == "" for p in pages)

    def test_random_walk_seeds_get_sequence_ids(self):
        sm = BaseStateManager(cfg(sampling_method="random-walk"))
        sm.initialize(["a", "b"])
        pages = sm.get_layer_by_depth(0)
        seqs = {p.sequence_id for p in pages}
        assert len(seqs) == 2 and "" not in seqs  # each seed starts its own chain
        assert sm.is_discovered_channel("a")

    def test_add_layer_dedups_urls_across_layers(self):
        sm = BaseStateManager(cfg())
        sm.initialize(["a"])
        sm.add_layer([Page(url="a", depth=1), Page(url="b", depth=1)])
        assert [p.url for p in sm.get_layer_by_depth(1)] == ["b"]

    def test_add_layer_max_pages_deadend_replacement(self):
        # state/base.go:219-322: at the cap, only deadend slots are refilled.
        sm = BaseStateManager(cfg(max_pages=2))
        sm.initialize(["a", "b"])
        sm.add_layer([Page(url="c", depth=1)])
        assert sm.get_layer_by_depth(1) == []
        # Mark one page deadend -> one replacement slot opens.
        page = sm.get_layer_by_depth(0)[0]
        page.status = PAGE_DEADEND
        sm.update_page(page)
        sm.add_layer([Page(url="c", depth=1), Page(url="d", depth=1)])
        assert [p.url for p in sm.get_layer_by_depth(1)] == ["c"]

    def test_random_walk_allows_url_revisits(self):
        # daprstate.go:648-656: random-walk skips URL dedup — a walk may return
        # to a channel it has already visited.
        sm = BaseStateManager(cfg(sampling_method="random-walk"))
        sm.initialize(["a"])
        sm.add_layer([Page(url="a", depth=1)])
        assert [p.url for p in sm.get_layer_by_depth(1)] == ["a"]

    def test_update_message_appends_and_updates(self):
        sm = BaseStateManager(cfg())
        sm.initialize(["a"])
        page = sm.get_layer_by_depth(0)[0]
        sm.update_message(page.id, 10, 100, "fetched")
        sm.update_message(page.id, 10, 100, "deleted")
        sm.update_message(page.id, 10, 101, "fetched")
        msgs = sm.get_page(page.id).messages
        assert len(msgs) == 2
        assert msgs[0].status == "deleted"

    def test_get_max_depth(self):
        sm = BaseStateManager(cfg())
        with pytest.raises(LookupError):
            sm.get_max_depth()
        sm.initialize(["a"])
        sm.add_layer([Page(url="b", depth=1)])
        assert sm.get_max_depth() == 1

    def test_metadata_update_guards_crawl_id(self):
        sm = BaseStateManager(cfg())
        with pytest.raises(ValueError):
            sm.update_crawl_metadata("other", {"status": "completed"})
        sm.update_crawl_metadata("c1", {"status": "completed",
                                        "previousCrawlID": "old1"})
        assert sm.metadata.status == "completed"
        assert sm.get_previous_crawls() == ["old1"]

    def test_find_incomplete_crawl(self):
        sm = BaseStateManager(cfg())
        sm.initialize(["a"])
        exec_id, found = sm.find_incomplete_crawl("c1")
        assert found and exec_id == "e1"
        # Complete everything -> no incomplete crawl.
        sm.update_crawl_metadata("c1", {"status": "completed"})
        for p in sm.get_layer_by_depth(0):
            p.status = PAGE_FETCHED
            sm.update_page(p)
        _, found = sm.find_incomplete_crawl("c1")
        assert not found


class TestCombinedUploadLocalFallback:
    def test_cross_filesystem_move(self, tmp_path, monkeypatch):
        """The local fallback survives the chunker write dir and
        storage_root living on different filesystems (rename(2) EXDEV)."""
        import errno
        import os as os_mod

        sm = BaseStateManager(cfg(storage_root=str(tmp_path / "store")))
        src = tmp_path / "combine" / "combined_1.jsonl"
        src.parent.mkdir()
        src.write_text('{"row": 1}\n')

        real_replace = os_mod.replace

        def exdev_replace(a, b, *aa, **kw):
            # Only the direct src→dest rename crosses the "filesystem"
            # boundary; the fallback's same-fs tmp→dest publish must work.
            if str(a).startswith(str(tmp_path / "combine")):
                raise OSError(errno.EXDEV, "Invalid cross-device link")
            return real_replace(a, b, *aa, **kw)

        monkeypatch.setattr(os_mod, "replace", exdev_replace)
        sm.upload_combined_file(str(src))
        dest = tmp_path / "store" / "combined" / "e1" / "combined_1.jsonl"
        assert dest.read_text() == '{"row": 1}\n'
        assert not src.exists()  # chunker contract: source consumed


class TestLocalStateManager:
    def _sm(self, tmp_path, **kw):
        return LocalStateManager(cfg(local=LocalConfig(base_path=str(tmp_path)), **kw))

    def test_state_persistence_roundtrip(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.initialize(["a", "b"])
        page = sm.get_layer_by_depth(0)[0]
        page.status = PAGE_FETCHED
        sm.update_page(page)
        sm.save_state()
        # Fresh manager resumes from disk.
        sm2 = self._sm(tmp_path)
        sm2.initialize([])
        statuses = {p.url: p.status for p in sm2.get_layer_by_depth(0)}
        assert statuses[page.url] == PAGE_FETCHED
        assert os.path.exists(tmp_path / "c1" / "state.json")
        assert os.path.exists(tmp_path / "c1" / "metadata.json")

    def test_store_post_appends_jsonl(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.initialize(["a"])
        post = Post(post_link="x", channel_id="chan", post_uid="1", url="x",
                    platform_name="telegram")
        sm.store_post("chan", post)
        sm.store_post("chan", post)
        path = tmp_path / "c1" / "chan" / "posts" / "posts.jsonl"
        lines = path.read_text().strip().split("\n")
        assert len(lines) == 2
        assert json.loads(lines[0])["post_uid"] == "1"

    def test_store_file_moves_media(self, tmp_path):
        sm = self._sm(tmp_path)
        src = tmp_path / "incoming.bin"
        src.write_bytes(b"\x00\x01media")
        stored, name = sm.store_file("chan", str(src), "photo_1.jpg")
        assert not src.exists()  # source deleted after copy
        assert (tmp_path / "c1" / "media" / "chan" / "photo_1.jpg").read_bytes() == b"\x00\x01media"
        assert name == "photo_1.jpg"

    def test_media_cache_dedup_and_persist(self, tmp_path):
        sm = self._sm(tmp_path)
        assert not sm.has_processed_media("m1")
        sm.mark_media_as_processed("m1")
        assert sm.has_processed_media("m1")
        sm.save_state()
        sm2 = self._sm(tmp_path)
        assert sm2.has_processed_media("m1")
        assert not sm2.has_processed_media("m2")

    def test_find_incomplete_crawl_from_disk(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.initialize(["a"])
        sm.save_state()
        # Fresh process, no in-memory state: finds it from metadata.json.
        sm2 = self._sm(tmp_path)
        exec_id, found = sm2.find_incomplete_crawl("c1")
        assert found and exec_id == "e1"

    def test_random_walk_not_supported_on_local(self, tmp_path):
        sm = self._sm(tmp_path)
        with pytest.raises(NotImplementedError):
            sm.get_pages_from_page_buffer(10)


class TestMediaCacheSharding:
    def test_shard_rotation(self, tmp_path):
        from distributed_crawler_tpu.state import ShardedMediaCache
        from distributed_crawler_tpu.state.providers import LocalStorageProvider
        provider = LocalStorageProvider(str(tmp_path))
        cache = ShardedMediaCache(provider, "c1", max_shard_items=3)
        for i in range(8):
            cache.mark(f"m{i}")
        cache.save()
        # 8 items / 3 per shard -> 3 shards.
        assert len(cache._shard_order) == 3
        index = provider.load_json("c1/media-cache-index.json")
        assert len(index["mediaIndex"]) == 8
        cache2 = ShardedMediaCache(provider, "c1", max_shard_items=3)
        assert cache2.has("m0") and cache2.has("m7")

    def test_legacy_migration(self, tmp_path):
        from datetime import datetime, timedelta, timezone

        from distributed_crawler_tpu.state import ShardedMediaCache
        from distributed_crawler_tpu.state.providers import LocalStorageProvider
        provider = LocalStorageProvider(str(tmp_path))
        # Relative date: a hardcoded firstSeen silently crosses the 30-day
        # expiry as the calendar advances (this test was a time bomb).
        seen = (datetime.now(timezone.utc) - timedelta(days=5)).strftime(
            "%Y-%m-%dT%H:%M:%SZ")
        provider.save_json("c1/media-cache.json", {
            "items": {"legacy1": {"id": "legacy1", "firstSeen": seen}}})
        cache = ShardedMediaCache(provider, "c1")
        assert cache.has("legacy1")

    def test_save_without_load_does_not_wipe(self, tmp_path):
        from distributed_crawler_tpu.state import ShardedMediaCache
        from distributed_crawler_tpu.state.providers import LocalStorageProvider
        provider = LocalStorageProvider(str(tmp_path))
        cache = ShardedMediaCache(provider, "c1")
        cache.mark("m1")
        cache.save()
        # Fresh instance saved before any read must not clobber the index.
        cache2 = ShardedMediaCache(provider, "c1")
        cache2.save()
        cache3 = ShardedMediaCache(provider, "c1")
        assert cache3.has("m1")

    def test_expiry(self, tmp_path):
        from distributed_crawler_tpu.state import ShardedMediaCache
        from distributed_crawler_tpu.state.providers import LocalStorageProvider
        provider = LocalStorageProvider(str(tmp_path))
        cache = ShardedMediaCache(provider, "c1", expiry_days=30)
        provider.save_json("c1/media-cache-index.json", {
            "shards": ["shard-00000"],
            "mediaIndex": {"old": "shard-00000", "new": "shard-00000"}})
        provider.save_json("c1/media-cache-shard-00000.json", {
            "cacheId": "shard-00000",
            "items": {"old": {"id": "old", "firstSeen": "2020-01-01T00:00:00Z"},
                      "new": {"id": "new", "firstSeen": "2026-07-28T00:00:00Z"}}})
        assert not cache.has("old")  # expired (30-day TTL)
        assert cache.has("new")


class TestInMemoryProviderTextFidelity:
    """put_text/get_text must round-trip byte-exact, matching
    LocalStorageProvider (ADVICE r2: newline normalization diverged)."""

    def test_verbatim_roundtrip(self):
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
        )
        p = InMemoryStorageProvider()
        for text in ("", "\n", "a", "a\n", "a\n\nb", "a\nb\n\n"):
            p.put_text("t.txt", text)
            assert p.get_text("t.txt") == text, repr(text)

    def test_matches_local_provider(self, tmp_path):
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
            LocalStorageProvider,
        )
        mem, disk = InMemoryStorageProvider(), LocalStorageProvider(
            str(tmp_path))
        for i, text in enumerate(("", "x", "x\n", "x\n\ny\n")):
            rel = f"d/f{i}.txt"
            mem.put_text(rel, text)
            disk.put_text(rel, text)
            assert mem.get_text(rel) == disk.get_text(rel)
        assert mem.exists("d/f0.txt") and mem.list_dir("d") == [
            "f0.txt", "f1.txt", "f2.txt", "f3.txt"]
        mem.delete("d/f0.txt")
        assert not mem.exists("d/f0.txt")

    def test_append_after_put_text(self):
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
        )
        p = InMemoryStorageProvider()
        p.put_text("a.jsonl", '{"n": 1}\n')
        p.append_jsonl("a.jsonl", '{"n": 2}')
        assert p.get_text("a.jsonl") == '{"n": 1}\n{"n": 2}\n'

    def test_append_after_put_text_matches_local(self, tmp_path):
        """Byte-append semantics for edge-case priors ('' and no trailing
        newline) must match the filesystem provider exactly."""
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
            LocalStorageProvider,
        )
        for i, prior in enumerate(("", "a", "a\n", "a\n\n")):
            mem, disk = InMemoryStorageProvider(), LocalStorageProvider(
                str(tmp_path / str(i)))
            rel = "f.jsonl"
            mem.put_text(rel, prior)
            disk.put_text(rel, prior)
            mem.append_jsonl(rel, "x")
            disk.append_jsonl(rel, "x")
            assert mem.get_text(rel) == disk.get_text(rel), repr(prior)
