"""Platform crawler registry tests.

Reference analogs: crawler/youtube/youtube_crawler_test.go,
crawler/youtube/panic_test.go, crawler/youtube/concurrent_test.go, and the
factory wiring in crawler/common/registrar.go.
"""

import random
from datetime import datetime, timezone

import pytest

from distributed_crawler_tpu.clients import SimNetwork, SimTelegramClient
from distributed_crawler_tpu.clients.youtube import (
    FakeYouTubeTransport,
    YouTubeDataClient,
)
from distributed_crawler_tpu.config import CrawlerConfig
from distributed_crawler_tpu.crawlers import (
    PLATFORM_TELEGRAM,
    PLATFORM_YOUTUBE,
    CrawlerFactory,
    CrawlJob,
    CrawlRunner,
    CrawlTarget,
    TelegramCrawler,
    YouTubeCrawler,
    apply_sampling,
    extract_urls,
    parse_iso8601_duration,
    register_all_crawlers,
    sanitize_filename,
)
from distributed_crawler_tpu.datamodel import NullValidator, Post
from distributed_crawler_tpu.datamodel.youtube import YouTubeVideo
from distributed_crawler_tpu.state import (
    CompositeStateManager,
    SqlConfig,
    StateConfig,
)


def make_sm(tmp_path):
    return CompositeStateManager(StateConfig(
        crawl_id="c1", crawl_execution_id="e1", storage_root=str(tmp_path),
        sql=SqlConfig(url=":memory:")))


def make_yt_client():
    transport = FakeYouTubeTransport()
    transport.add_channel("UC_one", title="Channel One", video_count=10,
                          subscriber_count=1000)
    for i in range(5):
        transport.add_video(f"vid{i}", "UC_one", title=f"Video {i}",
                            description=f"Desc {i} https://example.com/{i}",
                            view_count=100 * (i + 1), like_count=10 * (i + 1),
                            comment_count=i, duration="PT3M20S")
    client = YouTubeDataClient("key", transport)
    client.connect()
    return client


class TestFactory:
    def test_register_and_create(self):
        factory = CrawlerFactory()
        register_all_crawlers(factory)
        assert isinstance(factory.get_crawler(PLATFORM_TELEGRAM),
                          TelegramCrawler)
        assert isinstance(factory.get_crawler(PLATFORM_YOUTUBE),
                          YouTubeCrawler)

    def test_duplicate_registration_rejected(self):
        factory = CrawlerFactory()
        register_all_crawlers(factory)
        with pytest.raises(ValueError, match="already registered"):
            factory.register_crawler(PLATFORM_YOUTUBE, YouTubeCrawler)

    def test_unknown_platform(self):
        with pytest.raises(ValueError, match="no crawler registered"):
            CrawlerFactory().get_crawler("myspace")


class TestHelpers:
    def test_iso8601_duration(self):
        assert parse_iso8601_duration("PT3M20S") == 200
        assert parse_iso8601_duration("PT1H2M3S") == 3723
        assert parse_iso8601_duration("P1DT1S") == 86401
        with pytest.raises(ValueError):
            parse_iso8601_duration("3 minutes")

    def test_extract_urls_trims_and_dedups(self):
        urls = extract_urls(
            "see https://a.example/x, and (https://b.example/y)! "
            "again https://a.example/x")
        assert sorted(urls) == ["https://a.example/x", "https://b.example/y"]

    def test_sanitize_filename(self):
        assert sanitize_filename("a b/c:d") == "a_b_c_d"
        assert len(sanitize_filename("x" * 100)) == 50

    def test_apply_sampling(self):
        posts = [Post(post_uid=str(i)) for i in range(20)]
        sampled = apply_sampling(posts, 5, rng=random.Random(0))
        assert len(sampled) == 5
        assert len({p.post_uid for p in sampled}) == 5
        # No-ops when sample >= population or disabled.
        assert apply_sampling(posts, 0) is posts
        assert apply_sampling(posts, 50) is posts


class TestYouTubeCrawler:
    def _crawler(self, tmp_path, sampling="channel", **extra):
        c = YouTubeCrawler()
        c.initialize({"client": make_yt_client(),
                      "state_manager": make_sm(tmp_path),
                      "sampling_method": sampling, **extra})
        return c

    def test_requires_client(self):
        with pytest.raises(ValueError, match="client"):
            YouTubeCrawler().initialize({})

    def test_validate_target(self, tmp_path):
        c = self._crawler(tmp_path)
        with pytest.raises(ValueError, match="invalid target type"):
            c.validate_target(CrawlTarget(id="UC_one", type="telegram"))
        with pytest.raises(ValueError, match="empty"):
            c.validate_target(CrawlTarget(id="", type="youtube"))

    def test_get_channel_info(self, tmp_path):
        c = self._crawler(tmp_path)
        data = c.get_channel_info(CrawlTarget(id="UC_one", type="youtube"))
        assert data.channel_name == "Channel One"
        assert data.channel_engagement_data.follower_count == 1000
        assert data.channel_url == "https://www.youtube.com/channel/UC_one"

    def test_username_channel_url(self, tmp_path):
        c = self._crawler(tmp_path)
        # Handles resolve via the Data API's forHandle selector.  The
        # emitted identity/URL is the CANONICAL UC… id the API resolved —
        # not the seed's @handle form — so a channel seeded by handle and
        # later discovered by UC id dedups to one record.
        c.client.transport.add_channel("UC_h1", title="H", handle="@handle")
        data = c.get_channel_info(CrawlTarget(id="@handle", type="youtube"))
        assert data.channel_id == "UC_h1"
        assert data.channel_url == "https://www.youtube.com/channel/UC_h1"

    def test_channel_crawl_converts_and_stores(self, tmp_path):
        c = self._crawler(tmp_path)
        job = CrawlJob(target=CrawlTarget(id="UC_one", type="youtube"),
                       null_validator=NullValidator("youtube"))
        result = c.fetch_messages(job)
        assert len(result.posts) == 5
        post = next(p for p in result.posts if p.post_uid == "vid0")
        assert post.platform_name == "youtube"
        assert post.video_length == 200
        assert post.url == "https://www.youtube.com/watch?v=vid0"
        assert post.channel_data.channel_name == "Channel One"
        assert post.outlinks == ["https://example.com/0"]
        assert post.reactions == {"like": 10}
        # engagement = likes + comments + views/100
        assert post.engagement == 10 + 0 + 1

    def test_post_level_sampling(self, tmp_path):
        c = self._crawler(tmp_path)
        job = CrawlJob(target=CrawlTarget(id="UC_one", type="youtube"),
                       sample_size=2)
        assert len(c.fetch_messages(job).posts) == 2

    def test_unknown_sampling_method(self, tmp_path):
        c = self._crawler(tmp_path, sampling="astrology")
        with pytest.raises(ValueError, match="unknown sampling method"):
            c.fetch_messages(CrawlJob(
                target=CrawlTarget(id="UC_one", type="youtube")))

    def test_snowball_requires_seeds(self, tmp_path):
        c = self._crawler(tmp_path, sampling="snowball")
        with pytest.raises(ValueError, match="no seed channels"):
            c.fetch_messages(CrawlJob(
                target=CrawlTarget(id="", type="youtube")))

    def test_snowball_prepends_target(self, tmp_path):
        c = self._crawler(tmp_path, sampling="snowball")
        job = CrawlJob(target=CrawlTarget(id="UC_one", type="youtube"),
                       limit=10)
        result = c.fetch_messages(job)
        assert len(result.posts) > 0

    def test_snowball_zero_limit_means_unlimited(self, tmp_path):
        c = self._crawler(tmp_path, sampling="snowball")
        job = CrawlJob(target=CrawlTarget(id="UC_one", type="youtube"))
        assert len(c.fetch_messages(job).posts) == 5

    def test_random_defaults_to_full_batch(self, tmp_path):
        # samples_remaining unset must not silently request zero videos.
        c = self._crawler(tmp_path, sampling="random")
        requested = []
        original = c.client.get_random_videos
        c.client.get_random_videos = (
            lambda f, t, limit: (requested.append(limit), original(f, t, limit))[1])
        c.fetch_messages(CrawlJob(target=CrawlTarget(id="", type="youtube")))
        assert requested == [50]
        # An explicit samples_remaining still caps the batch.
        c.fetch_messages(CrawlJob(target=CrawlTarget(id="", type="youtube"),
                                  samples_remaining=7))
        assert requested[-1] == 7

    def test_channel_info_cached_per_channel(self, tmp_path):
        c = self._crawler(tmp_path)
        c.fetch_messages(CrawlJob(
            target=CrawlTarget(id="UC_one", type="youtube")))
        calls = [e for e, _ in c.client.transport.calls if e == "channels"]
        assert len(calls) == 1  # 5 videos, one channels.list lookup

    def test_duration_p0d_is_null(self, tmp_path):
        c = self._crawler(tmp_path)
        video = YouTubeVideo(id="v", channel_id="UC_one", title="t",
                             duration="P0D",
                             published_at=datetime.now(timezone.utc))
        assert c.convert_video_to_post(video).video_length is None

    def test_fallback_channel_data(self, tmp_path):
        c = self._crawler(tmp_path)
        video = YouTubeVideo(id="v", channel_id="UC_unknown", title="t",
                             view_count=500, like_count=5,
                             published_at=datetime.now(timezone.utc))
        post = c.convert_video_to_post(video)
        assert post.channel_data.channel_name == "UC_unknown"
        assert post.channel_data.channel_engagement_data.views_count == 500


class TestTelegramCrawler:
    def _crawler(self, tmp_path):
        net = SimNetwork()
        from tests.test_crawl_engine import text_msg
        net.add_channel("mychan", messages=[
            text_msg("hello world", date=1700000000, view_count=10),
            text_msg("see t.me/other", date=1700000100, view_count=20),
        ], member_count=500)
        c = TelegramCrawler()
        c.initialize({"client": SimTelegramClient(net),
                      "state_manager": make_sm(tmp_path),
                      "crawler_config": CrawlerConfig(
                          crawl_id="c1", skip_media_download=True)})
        return c

    def test_get_channel_info(self, tmp_path):
        c = self._crawler(tmp_path)
        data = c.get_channel_info(CrawlTarget(id="mychan", type="telegram"))
        assert data.channel_engagement_data.follower_count == 500
        assert data.channel_url == "https://t.me/mychan"

    def test_fetch_messages(self, tmp_path):
        c = self._crawler(tmp_path)
        result = c.fetch_messages(CrawlJob(
            target=CrawlTarget(id="mychan", type="telegram"),
            null_validator=NullValidator("telegram")))
        assert len(result.posts) == 2
        assert all(p.platform_name == "telegram" for p in result.posts)

    def test_validate_target(self, tmp_path):
        c = self._crawler(tmp_path)
        with pytest.raises(ValueError, match="expected: telegram"):
            c.validate_target(CrawlTarget(id="x", type="youtube"))


class TestCrawlRunner:
    def test_execute_batch_with_failure_isolation(self, tmp_path):
        factory = CrawlerFactory()
        register_all_crawlers(factory)
        sm = make_sm(tmp_path)
        runner = CrawlRunner(factory, sm, base_config={
            "client": make_yt_client(), "sampling_method": "channel"})
        jobs = [
            CrawlJob(target=CrawlTarget(id="UC_one", type="youtube")),
            CrawlJob(target=CrawlTarget(id="", type="youtube")),  # invalid
        ]
        results = runner.execute_batch_jobs(jobs)
        assert len(results[0].posts) == 5
        assert results[1].errors  # failed job isolated, not raised
        runner.close()

    def test_runner_caches_crawler_instances(self, tmp_path):
        factory = CrawlerFactory()
        register_all_crawlers(factory)
        runner = CrawlRunner(factory, make_sm(tmp_path), base_config={
            "client": make_yt_client()})
        a = runner._get_crawler(PLATFORM_YOUTUBE)
        b = runner._get_crawler(PLATFORM_YOUTUBE)
        assert a is b
