"""Whisper ASR + k-means clustering tests (BASELINE configs #4 and #5).

Runs the WHISPER_TEST config on the CPU backend: frontend shapes, teacher
forcing vs KV-cached step equivalence, greedy decode determinism, the ASR
file pipeline over generated WAVs, and k-means correctness incl. the
sharded data-parallel path on the virtual 8-device mesh.
"""

import wave

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from distributed_crawler_tpu.inference.asr import (  # noqa: E402
    ASRPipeline,
    read_wav_mono_16k,
)
from distributed_crawler_tpu.models import clustering  # noqa: E402
from distributed_crawler_tpu.models.whisper import (  # noqa: E402
    N_SAMPLES,
    WHISPER_TEST,
    Whisper,
    greedy_decode,
    log_mel_spectrogram,
    pad_or_trim,
)


@pytest.fixture(scope="module")
def whisper_model():
    cfg = WHISPER_TEST
    model = Whisper(cfg)
    rng = np.random.default_rng(0)
    mel = jnp.asarray(rng.standard_normal(
        (1, cfg.n_audio_ctx * 2, cfg.n_mels)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), mel,
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, model, params


def make_mel(cfg, batch=2, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(
        (batch, cfg.n_audio_ctx * 2, cfg.n_mels)), jnp.float32)


class TestFrontend:
    def test_log_mel_shape_and_range(self):
        audio = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 16000)), jnp.float32)
        mel = log_mel_spectrogram(audio, n_mels=8)
        assert mel.shape == (2, 100, 8)  # 16000 / 160 hop
        assert np.all(np.isfinite(np.asarray(mel)))

    def test_pad_or_trim(self):
        short = jnp.ones((1, 100))
        assert pad_or_trim(short).shape == (1, N_SAMPLES)
        long = jnp.ones((1, N_SAMPLES + 5))
        assert pad_or_trim(long).shape == (1, N_SAMPLES)

    def test_mel_filterbank_matches_slaney_reference(self):
        """The bank must equal librosa.filters.mel(sr=16000, n_fft=400,
        n_mels=80, htk=False, norm='slaney') — the filterbank published
        Whisper checkpoints were trained with.  Independent ramps-based
        reimplementation of librosa's algorithm, compared to 1e-6."""
        from distributed_crawler_tpu.models.whisper import _mel_filterbank

        sr, n_fft, n_mels = 16000, 400, 80

        # librosa's Slaney mel scale, straight-line transcription.
        def hz_to_mel(f):
            f = np.atleast_1d(np.asarray(f, dtype=np.float64))
            mel = f / (200.0 / 3.0)
            log_region = f >= 1000.0
            mel[log_region] = 15.0 + np.log(f[log_region] / 1000.0) / (
                np.log(6.4) / 27.0)
            return mel

        def mel_to_hz(m):
            m = np.atleast_1d(np.asarray(m, dtype=np.float64))
            hz = m * (200.0 / 3.0)
            log_region = m >= 15.0
            hz[log_region] = 1000.0 * np.exp(
                (np.log(6.4) / 27.0) * (m[log_region] - 15.0))
            return hz

        fftfreqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
        mel_f = mel_to_hz(np.linspace(float(hz_to_mel(0.0)[0]),
                                      float(hz_to_mel(sr / 2)[0]),
                                      n_mels + 2))
        fdiff = np.diff(mel_f)
        ramps = np.subtract.outer(mel_f, fftfreqs)
        expected = np.zeros((n_mels, 1 + n_fft // 2))
        for i in range(n_mels):
            lower = -ramps[i] / fdiff[i]
            upper = ramps[i + 2] / fdiff[i + 1]
            expected[i] = np.maximum(0, np.minimum(lower, upper))
        expected *= (2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels]))[:, None]

        got = _mel_filterbank(n_mels, n_fft, sr)
        np.testing.assert_allclose(got, expected, atol=1e-6)

        # Slaney-scale structure: crossover at 1 kHz — center frequencies
        # evenly spaced (~36.9 Hz) below it, geometric above it.
        centers = mel_f[1:n_mels + 1]
        linear = centers[centers < 990.0]
        spacing = np.diff(linear)
        assert np.allclose(spacing, spacing[0], atol=1e-6)
        upper = centers[centers > 1100.0]
        ratios = upper[1:] / upper[:-1]
        assert np.allclose(ratios, ratios[0], rtol=1e-6)
        assert ratios[0] > 1.01


class TestWhisper:
    def test_teacher_forcing_shapes(self, whisper_model):
        cfg, model, params = whisper_model
        mel = make_mel(cfg)
        tokens = jnp.array([[1, 4, 3, 7], [1, 4, 3, 9]], jnp.int32)
        logits = model.apply(params, mel, tokens)
        assert logits.shape == (2, 4, cfg.n_vocab)

    def test_step_matches_teacher_forcing(self, whisper_model):
        """The KV-cached decode path must produce the same logits as the
        full-sequence pass — the core correctness property of the cache."""
        cfg, model, params = whisper_model
        mel = make_mel(cfg, batch=1)
        tokens = jnp.array([[1, 4, 3, 7, 9]], jnp.int32)
        xa = model.apply(params, mel, method=Whisper.encode)
        full = model.apply(params, tokens, xa,
                           method=Whisper.decode_teacher)

        cache, cross = model.apply(params, 1, xa,
                                   method=Whisper.decode_init)
        step_logits = []
        for pos in range(tokens.shape[1]):
            logits, cache = model.apply(
                params, tokens[:, pos:pos + 1], pos, cache, cross,
                method=Whisper.decode_step)
            step_logits.append(np.asarray(logits))
        stepped = np.stack(step_logits, axis=1)
        np.testing.assert_allclose(np.asarray(full), stepped,
                                   rtol=2e-4, atol=2e-4)

    def test_greedy_decode_prompt_and_eot(self, whisper_model):
        cfg, model, params = whisper_model
        tokens = np.asarray(greedy_decode(model, params, make_mel(cfg),
                                          max_len=10))
        assert tokens.shape == (2, 10)
        # Forced decoder prompt: sot, transcribe, no_timestamps.
        assert list(tokens[0][:3]) == [cfg.sot_token, cfg.transcribe_token,
                                       cfg.no_timestamps_token]
        # After an EOT everything stays EOT.
        for row in tokens:
            seen_eot = False
            for t in row[3:]:
                if seen_eot:
                    assert t == cfg.eot_token
                seen_eot = seen_eot or t == cfg.eot_token

    def test_greedy_decode_deterministic_and_jittable(self, whisper_model):
        cfg, model, params = whisper_model
        mel = make_mel(cfg)
        f = jax.jit(lambda p, m: greedy_decode(model, p, m, max_len=8))
        a = np.asarray(f(params, mel))
        b = np.asarray(f(params, mel))
        np.testing.assert_array_equal(a, b)


class TestASRPipeline:
    def _write_wav(self, path, seconds=0.2, rate=16000, channels=1):
        rng = np.random.default_rng(1)
        samples = (rng.standard_normal(int(rate * seconds) * channels)
                   * 3000).astype(np.int16)
        with wave.open(str(path), "wb") as w:
            w.setnchannels(channels)
            w.setsampwidth(2)
            w.setframerate(rate)
            w.writeframes(samples.tobytes())
        return str(path)

    def test_read_wav(self, tmp_path):
        p = self._write_wav(tmp_path / "a.wav")
        audio = read_wav_mono_16k(p)
        assert audio.dtype == np.float32
        assert np.max(np.abs(audio)) <= 1.0

    def test_read_wav_resamples_other_rates(self, tmp_path):
        # A 48 kHz export must load at 16 kHz with 1/3 the samples — a
        # stray high-rate wav must not fail a whole transcription run.
        rate, seconds = 48_000, 0.5
        t = np.arange(int(rate * seconds)) / rate
        pcm = (np.sin(2 * np.pi * 440.0 * t) * 0.5 * 32767).astype(np.int16)
        p = tmp_path / "b.wav"
        with wave.open(str(p), "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(rate)
            w.writeframes(pcm.tobytes())
        audio = read_wav_mono_16k(str(p))
        assert abs(len(audio) - int(16_000 * seconds)) <= 2
        # The tone survives resampling: dominant frequency stays ~440 Hz.
        spec = np.abs(np.fft.rfft(audio))
        peak_hz = np.argmax(spec) / seconds
        assert 420 < peak_hz < 460

    def test_downsampling_attenuates_out_of_band_energy(self, tmp_path):
        """A 15 kHz tone in a 48 kHz file would alias into the speech band
        under naive interpolation; the box pre-filter must knock it down."""
        rate, seconds = 48_000, 0.5
        t = np.arange(int(rate * seconds)) / rate
        pcm = (np.sin(2 * np.pi * 15_000.0 * t) * 0.5
               * 32767).astype(np.int16)
        p = tmp_path / "hiss.wav"
        with wave.open(str(p), "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(rate)
            w.writeframes(pcm.tobytes())
        audio = read_wav_mono_16k(str(p))
        # Original tone RMS ~0.35; surviving (aliased) energy must be
        # strongly attenuated by the anti-alias pre-filter.
        rms = float(np.sqrt(np.mean(audio ** 2)))
        assert rms < 0.1, f"aliased energy too high: rms={rms:.3f}"

    def test_stereo_downmix(self, tmp_path):
        p = self._write_wav(tmp_path / "c.wav", channels=2)
        audio = read_wav_mono_16k(p)
        assert audio.ndim == 1

    def test_transcribe_files_contains_failures(self, whisper_model,
                                                tmp_path):
        cfg, model, params = whisper_model
        pipeline = ASRPipeline(model, params, batch_size=2, max_len=6,
                               detokenize=lambda toks: " ".join(
                                   str(t) for t in toks))
        good = self._write_wav(tmp_path / "ok.wav")
        bad = str(tmp_path / "missing.wav")
        results = {r.path: r for r in pipeline.transcribe_files([good, bad])}
        assert results[bad].tokens == []
        ok = results[good]
        # Specials stripped; whatever remains is the transcript ids.
        special = {cfg.sot_token, cfg.eot_token, cfg.no_timestamps_token,
                   cfg.transcribe_token}
        assert all(t not in special for t in ok.tokens)
        assert ok.text == " ".join(str(t) for t in ok.tokens)


class TestKMeans:
    def _blobs(self, n=60, d=6, k=3, seed=0):
        rng = np.random.default_rng(seed)
        centers = rng.standard_normal((k, d)) * 12
        x = np.vstack([rng.standard_normal((n, d)) + c for c in centers])
        return jnp.asarray(x, jnp.float32), k, n

    def test_recovers_blob_structure(self):
        x, k, n = self._blobs()
        res = clustering.fit(x, k=k, iters=20)
        a = np.asarray(res.assignments)
        # Each blob maps to exactly one cluster and blobs get distinct ones.
        blob_labels = [set(a[i * n:(i + 1) * n]) for i in range(k)]
        assert all(len(s) == 1 for s in blob_labels)
        assert len(set().union(*blob_labels)) == k

    def test_inertia_decreases_with_iters(self):
        x, k, _ = self._blobs(seed=2)
        rough = clustering.fit(x, k=k, iters=1, init="random")
        tight = clustering.fit(x, k=k, iters=20, init="random")
        assert float(tight.inertia) <= float(rough.inertia) + 1e-3

    def test_sharded_fit_on_mesh(self):
        from distributed_crawler_tpu.parallel import (
            best_mesh_config,
            make_mesh,
        )
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        x, k, n = self._blobs(n=64)
        mesh = make_mesh(best_mesh_config(8))
        res = clustering.fit_sharded(x, k, mesh, iters=15)
        a = np.asarray(res.assignments)
        assert len({tuple(sorted(set(a[i * n:(i + 1) * n])))
                    for i in range(k)}) == k
