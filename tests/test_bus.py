"""Bus tests: envelope validation, record-batch codec round-trips, in-memory
at-least-once semantics, gRPC transport round trip (reference analogs:
distributed message validation + integration_test.go)."""

import json

import pytest

from distributed_crawler_tpu.bus import (
    ChaosMessage,
    ControlMessage,
    DiscoveredPage,
    InMemoryBus,
    RecordBatch,
    ResultMessage,
    StatusMessage,
    WorkItem,
    WorkItemConfig,
    WorkQueueMessage,
    WorkResult,
    decode_frames,
    encode_frame,
    pubsub_topics,
)
from distributed_crawler_tpu.bus.codec import (
    COMPRESSION_NONE,
    COMPRESSION_ZLIB,
    BatchAccumulator,
    decode_frame,
)
from distributed_crawler_tpu.datamodel import Post


class TestMessageValidation:
    def test_work_item_constructor_and_roundtrip(self):
        item = WorkItem.new("https://t.me/x", 2, "p1", "c1", "telegram",
                            WorkItemConfig(storage_root="/tmp/s"))
        item.validate()
        assert item.id.startswith("work_")
        assert item.trace_id.startswith("trace_")
        item2 = WorkItem.from_dict(json.loads(json.dumps(item.to_dict())))
        assert item2 == item

    def test_work_item_validation_errors(self):
        item = WorkItem.new("u", 0, "", "c", "telegram", WorkItemConfig())
        item.platform = "tiktok"
        with pytest.raises(ValueError, match="unsupported platform"):
            item.validate()
        item.platform = ""
        with pytest.raises(ValueError, match="platform cannot be empty"):
            item.validate()
        item = WorkItem(id="", url="u", platform="telegram")
        with pytest.raises(ValueError, match="ID cannot be empty"):
            item.validate()

    def test_work_result_validation(self):
        r = WorkResult(work_item_id="w", worker_id="k", status="error")
        with pytest.raises(ValueError, match="requires error message"):
            r.validate()
        r.error = "boom"
        r.validate()
        r.status = "nonsense"
        with pytest.raises(ValueError, match="invalid status"):
            r.validate()

    def test_discovered_page_validation(self):
        with pytest.raises(ValueError, match="URL"):
            DiscoveredPage(platform="telegram").validate()
        with pytest.raises(ValueError, match="depth"):
            DiscoveredPage(url="u", platform="telegram", depth=-1).validate()
        DiscoveredPage(url="u", platform="telegram", depth=1).validate()

    def test_status_message_validation(self):
        s = StatusMessage.new("w1", "heartbeat", "busy", 5, 4, 1, 60.0)
        s.validate()
        s.message_type = "bogus"
        with pytest.raises(ValueError, match="invalid message type"):
            s.validate()
        s = StatusMessage.new("w1", "heartbeat", "bogus")
        with pytest.raises(ValueError, match="invalid status"):
            s.validate()

    def test_queue_message_ttl(self):
        from datetime import timedelta
        from distributed_crawler_tpu.state.datamodels import utcnow
        msg = WorkQueueMessage.new(
            WorkItem.new("u", 0, "", "c", "telegram", WorkItemConfig()),
            ttl_seconds=10)
        assert not msg.expired()
        assert msg.expired(now=utcnow() + timedelta(seconds=11))

    def test_result_message_roundtrip(self):
        result = WorkResult(work_item_id="w", worker_id="k", status="success",
                            message_count=7,
                            discovered_pages=[DiscoveredPage(url="a", depth=1,
                                                             platform="telegram")])
        msg = ResultMessage.new(result, result.discovered_pages)
        msg2 = ResultMessage.from_dict(json.loads(json.dumps(msg.to_dict())))
        assert msg2.work_result.message_count == 7
        assert msg2.discovered_pages[0].url == "a"

    def test_topics(self):
        topics = pubsub_topics()
        assert "crawl-work-queue" in topics
        assert "tpu-inference-batches" in topics


class TestMessageRegistry:
    """`bus.codec.MESSAGE_REGISTRY` + `decode_message`: the typed-decode
    table the crawlint BUS checker statically enforces."""

    def test_every_registered_type_roundtrips(self):
        from distributed_crawler_tpu.bus import MESSAGE_REGISTRY, decode_message

        from distributed_crawler_tpu.bus.messages import (
            AlertMessage,
            AudioBatchMessage,
            AudioRef,
            ClusterUpdateMessage,
            SpanBatchMessage,
            TranscriptMessage,
        )

        samples = {
            WorkQueueMessage: WorkQueueMessage.new(
                WorkItem.new("u", 0, "", "c", "telegram", WorkItemConfig())),
            ResultMessage: ResultMessage.new(
                WorkResult(work_item_id="w", worker_id="k",
                           status="success")),
            StatusMessage: StatusMessage.new("w1", "heartbeat", "idle"),
            ControlMessage: ControlMessage(message_type="pause",
                                           trace_id="trace_x"),
            ChaosMessage: ChaosMessage.new("kill", "tpu-1", at_s=1.5),
            AudioBatchMessage: AudioBatchMessage.new(
                [AudioRef(media_id="m1", path="/a.wav",
                          channel_name="chan")], crawl_id="c1"),
            TranscriptMessage: TranscriptMessage.new(
                "m1", crawl_id="c1", batch_id="b1", text="hi",
                tokens=[1, 2], windows=1),
            SpanBatchMessage: SpanBatchMessage.new(
                "tpu-1", [{"name": "tpu_worker.process",
                           "trace_id": "t1", "span_id": "s1",
                           "parent_id": "", "start_wall": 1.0,
                           "duration_ms": 2.0, "attrs": {}}]),
            AlertMessage: AlertMessage.new(
                "queue_wait_burn", "burn_rate", "fleet_slo_breach_total",
                "firing", prev_state="pending", value=12.5,
                detail={"burn_fast": 12.5, "burn_slow": 7.0}),
            ClusterUpdateMessage: ClusterUpdateMessage.new(
                "cluster-1", k=4, step=7, vectors=120,
                sizes=[50, 40, 20, 10], inertia=0.37,
                underpopulated=[3], channel_clusters={"chan": 3}),
        }
        assert set(MESSAGE_REGISTRY.values()) == set(samples)
        for cls, msg in samples.items():
            payload = json.loads(json.dumps(msg.to_dict()))
            decoded = decode_message(payload)
            assert type(decoded) is cls
            assert decoded.message_type == msg.message_type

    def test_registry_covers_every_declared_message_type(self):
        from distributed_crawler_tpu.bus import MESSAGE_REGISTRY
        from distributed_crawler_tpu.bus import messages as m

        declared = {v for k, v in vars(m).items()
                    if k.startswith("MSG_")
                    and k not in ("MSG_RECORD_BATCH", "MSG_INFERENCE_RESULT")}
        assert declared == set(MESSAGE_REGISTRY)

    def test_unknown_message_type_rejected(self):
        from distributed_crawler_tpu.bus import decode_message

        with pytest.raises(ValueError, match="unknown message_type"):
            decode_message({"message_type": "nope"})
        with pytest.raises(ValueError, match="unknown message_type"):
            decode_message({})

    def test_decoded_envelope_keeps_trace_id(self):
        from distributed_crawler_tpu.bus import decode_message

        item = WorkItem.new("u", 0, "", "c", "telegram", WorkItemConfig())
        msg = WorkQueueMessage.new(item)
        decoded = decode_message(msg.to_dict())
        assert decoded.trace_id == item.trace_id

    def test_tenant_label_roundtrips_and_legacy_frames_default(self):
        from distributed_crawler_tpu.bus import decode_message
        from distributed_crawler_tpu.bus.messages import (
            DEFAULT_TENANT,
            AudioBatchMessage,
            AudioRef,
            TranscriptMessage,
        )

        audio = AudioBatchMessage.new(
            [AudioRef(media_id="m1", path="/a.wav")], crawl_id="c1",
            tenant="interactive")
        transcript = TranscriptMessage.new(
            "m1", crawl_id="c1", batch_id="b1", text="hi",
            tenant="bulk-reembed")
        batch = RecordBatch.from_posts(
            [Post(post_uid="p1", channel_id="c", channel_name="c",
                  platform_name="telegram", description="hello")],
            crawl_id="c1", tenant="interactive")
        for msg, want in ((audio, "interactive"),
                          (transcript, "bulk-reembed")):
            decoded = decode_message(json.loads(json.dumps(msg.to_dict())))
            assert decoded.tenant == want
        assert RecordBatch.from_dict(
            json.loads(json.dumps(batch.to_dict()))).tenant == "interactive"
        # Legacy payloads (pre-tenant spools/outboxes/replay bundles)
        # carry NO tenant key and must decode to the documented default
        # tenant, not raise — the wire-compat clause of ISSUE 17.
        for msg in (audio, transcript):
            legacy = msg.to_dict()
            legacy.pop("tenant")
            assert decode_message(
                json.loads(json.dumps(legacy))).tenant == DEFAULT_TENANT
        legacy_batch = batch.to_dict()
        legacy_batch.pop("tenant")
        assert RecordBatch.from_dict(legacy_batch).tenant == DEFAULT_TENANT
        # Falsy/garbage labels fold to the default instead of minting
        # phantom tenants on /tenants.
        assert AudioBatchMessage.new(
            [], crawl_id="c", tenant="").tenant == DEFAULT_TENANT

    def test_chaos_message_roundtrip_and_fields(self):
        from distributed_crawler_tpu.bus import decode_message

        msg = ChaosMessage.new("delay", "bus", at_s=5.0, until_s=6.0,
                               parameters={"arg_s": 0.2})
        msg.validate()
        assert msg.trace_id.startswith("trace_")
        decoded = decode_message(json.loads(json.dumps(msg.to_dict())))
        assert type(decoded) is ChaosMessage
        assert decoded.action == "delay"
        assert decoded.target_id == "bus"
        assert decoded.at_s == 5.0 and decoded.until_s == 6.0
        assert decoded.parameters == {"arg_s": 0.2}
        assert decoded.trace_id == msg.trace_id
        assert decoded.timestamp == msg.timestamp

    def test_chaos_message_validation_errors(self):
        with pytest.raises(ValueError, match="unknown chaos action"):
            ChaosMessage.new("explode", "tpu-1", at_s=0.0).validate()
        with pytest.raises(ValueError, match="target cannot be empty"):
            ChaosMessage.new("kill", "", at_s=0.0).validate()
        bad = ChaosMessage.new("kill", "tpu-1", at_s=0.0)
        bad.message_type = "bogus"
        with pytest.raises(ValueError, match="invalid chaos message type"):
            bad.validate()

    def test_chaos_actions_match_timeline_parser(self):
        """The envelope's action vocabulary IS the chaos controller's —
        a scenario line that parses must announce as a valid message."""
        from distributed_crawler_tpu.bus.messages import CHAOS_ACTIONS
        from distributed_crawler_tpu.loadgen.chaos import _ACTIONS

        assert set(CHAOS_ACTIONS) == set(_ACTIONS)


def make_posts(n):
    return [Post(post_link=f"l{i}", channel_id="c", post_uid=str(i),
                 url=f"l{i}", platform_name="telegram",
                 description=f"текст сообщения номер {i} " * 10)
            for i in range(n)]


class TestRecordBatchCodec:
    def test_roundtrip_zstd(self):
        batch = RecordBatch.from_posts(make_posts(16), crawl_id="c1")
        data = batch.to_bytes()
        batch2 = RecordBatch.from_bytes(data)
        assert batch2.batch_id == batch.batch_id
        assert len(batch2) == 16
        assert batch2.posts()[3].post_uid == "3"

    def test_compression_shrinks(self):
        batch = RecordBatch.from_posts(make_posts(64))
        raw = len(batch.to_bytes(COMPRESSION_NONE))
        compressed = len(batch.to_bytes())
        assert compressed < raw / 3  # repetitive crawl text compresses hard

    def test_stream_of_frames(self):
        frames = b"".join(
            RecordBatch.from_posts(make_posts(2), crawl_id=f"c{i}").to_bytes(
                COMPRESSION_ZLIB)
            for i in range(3))
        decoded = [RecordBatch.from_dict(d) for d in decode_frames(frames)]
        assert [b.crawl_id for b in decoded] == ["c0", "c1", "c2"]

    def test_corrupt_frames_rejected(self):
        good = encode_frame({"x": 1})
        with pytest.raises(ValueError, match="magic"):
            decode_frame(b"XXXX" + good[4:])
        with pytest.raises(ValueError, match="truncated"):
            decode_frame(good[:-2])
        with pytest.raises(ValueError, match="trailing"):
            RecordBatch.from_bytes(good + b"junk")

    def test_texts_extraction(self):
        batch = RecordBatch.from_posts([
            Post(post_uid="1", all_text="A"),
            Post(post_uid="2", description="D")])
        assert batch.texts() == ["A", "D"]


class TestBatchAccumulator:
    def test_emits_on_size(self):
        acc = BatchAccumulator(batch_size=3, deadline_s=10.0)
        posts = make_posts(7)
        batches = [b for i, p in enumerate(posts)
                   if (b := acc.add(p, now=float(i))) is not None]
        assert [len(b) for b in batches] == [3, 3]
        assert len(acc) == 1
        tail = acc.flush()
        assert tail is not None and len(tail) == 1

    def test_emits_on_deadline(self):
        acc = BatchAccumulator(batch_size=100, deadline_s=0.5)
        acc.add(make_posts(1)[0], now=0.0)
        assert acc.poll(now=0.4) is None
        batch = acc.poll(now=0.6)
        assert batch is not None and len(batch) == 1
        assert acc.poll(now=1.0) is None  # nothing pending


class TestInMemoryBus:
    def test_pubsub_roundtrip(self):
        bus = InMemoryBus()
        got = []
        bus.subscribe("t1", got.append)
        bus.publish("t1", {"a": 1})
        bus.publish("t2", {"b": 2})  # different topic, not delivered to t1
        assert got == [{"a": 1}]

    def test_handler_error_retries_then_dead_letters(self):
        bus = InMemoryBus(max_redeliveries=2)
        attempts = []
        def flaky(msg):
            attempts.append(1)
            raise RuntimeError("boom")
        bus.subscribe("t", flaky)
        bus.publish("t", {"x": 1})
        assert len(attempts) == 3  # 1 + 2 retries
        assert len(bus.dead_letters) == 1
        topic, payload, err = bus.dead_letters[0]
        assert topic == "t" and payload == {"x": 1} and "boom" in err

    def test_handler_recovers_mid_retry(self):
        bus = InMemoryBus(max_redeliveries=3)
        state = {"n": 0}
        def eventually(msg):
            state["n"] += 1
            if state["n"] < 3:
                raise RuntimeError("not yet")
        bus.subscribe("t", eventually)
        bus.publish("t", {})
        assert state["n"] == 3
        assert bus.dead_letters == []

    def test_undecodable_payload_dropped_no_retry(self):
        bus = InMemoryBus()
        calls = []
        bus.subscribe("t", calls.append)
        bus.publish("t", b"\xff\xfenot json")
        assert calls == []
        assert bus.dead_letters == []  # dropped, not dead-lettered

    def test_async_mode(self):
        bus = InMemoryBus(sync=False)
        bus.start()
        got = []
        bus.subscribe("t", got.append)
        for i in range(20):
            bus.publish("t", {"i": i})
        assert bus.drain()
        bus.close()
        import time
        deadline = time.monotonic() + 2
        while len(got) < 20 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(got) == 20

    def test_typed_message_publish(self):
        bus = InMemoryBus()
        got = []
        bus.subscribe("worker-status", got.append)
        bus.publish("worker-status",
                    StatusMessage.new("w1", "heartbeat", "idle"))
        assert got[0]["worker_id"] == "w1"
        parsed = StatusMessage.from_dict(got[0])
        parsed.validate()


class TestGrpcBus:
    def test_publish_and_pull_roundtrip(self):
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient, GrpcBusServer
        server = GrpcBusServer(address="127.0.0.1:0")
        received = []
        server.subscribe("worker-status", received.append)
        server.enable_pull("tpu-inference-batches")
        server.start()
        try:
            client = GrpcBusClient(target=f"127.0.0.1:{server.bound_port}")
            client.publish("worker-status", {"worker_id": "w1"})
            assert server.flush_local()  # local dispatch is off-thread now
            assert received == [{"worker_id": "w1"}]
            # Record-batch frame via pull stream.
            batch = RecordBatch.from_posts(make_posts(4), crawl_id="c1")
            client.publish_frame("tpu-inference-batches", batch.to_bytes())
            stream = client.pull("tpu-inference-batches")
            delivery_id, frame = next(iter(stream))
            got = RecordBatch.from_bytes(frame)
            assert got.crawl_id == "c1" and len(got) == 4
            client.ack("tpu-inference-batches", delivery_id)
            stream.close()
            assert server.pending_count("tpu-inference-batches") == 0
            client.close()
        finally:
            server.close()


def _wait_until(cond, timeout_s=5.0):
    import time
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


class TestGrpcBusAcks:
    """At-least-once delivery via per-frame acks (`pubsub.go:157-254`)."""

    def _server(self, **kw):
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusServer
        server = GrpcBusServer(address="127.0.0.1:0", **kw)
        server.enable_pull("work")
        server.start()
        return server

    def test_nack_requeues_then_dead_letters(self):
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
        server = self._server(max_attempts=3)
        try:
            client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            client.publish("work", {"n": 1})
            seen = 0
            stream = client.pull("work")
            for delivery_id, _frame in stream:
                seen += 1
                client.ack("work", delivery_id, ok=False)
                if seen == 3:
                    break
            stream.close()
            # 3 attempts, then dead-lettered — nothing pending.
            assert server.dead_letters == 1
            assert server.pending_count("work") == 0
            client.close()
        finally:
            server.close()

    def test_drain_waits_for_pull_consumers(self):
        """drain() holds the broker open until queued+in-flight frames are
        consumed — the orchestrator calls it before tearing down the bus
        so late-starting workers don't lose batches."""
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
        server = self._server()
        try:
            client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            client.publish("work", {"n": 1})
            assert server.drain(timeout_s=0.3, poll_s=0.05) is False
            stream = client.pull("work")
            for delivery_id, _frame in stream:
                client.ack("work", delivery_id, ok=True)
                break
            stream.close()
            assert server.drain(timeout_s=5.0, poll_s=0.05) is True
            client.close()
        finally:
            server.close()

    def test_worker_crash_requeues_unacked(self):
        """Kill-a-worker: frames pulled but never acked are redelivered to
        the next worker — zero lost, zero duplicated."""
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
        server = self._server()
        try:
            publisher = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            for i in range(5):
                publisher.publish("work", {"n": i})

            # Worker A pulls all 5, acks only 2, then "crashes" (stream
            # closed without acks).
            worker_a = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            stream = worker_a.pull("work")
            got_a = []
            for delivery_id, frame in stream:
                got_a.append((delivery_id, json.loads(frame)))
                if len(got_a) == 5:
                    break
            for delivery_id, payload in got_a[:2]:
                worker_a.ack("work", delivery_id, ok=True)
            acked_a = [p["n"] for _, p in got_a[:2]]
            stream.close()
            worker_a.close()

            assert _wait_until(lambda: server.pending_count("work") == 3)

            # Worker B drains the requeued 3.
            worker_b = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            stream_b = worker_b.pull("work")
            got_b = []
            for delivery_id, frame in stream_b:
                got_b.append(json.loads(frame)["n"])
                worker_b.ack("work", delivery_id, ok=True)
                if len(got_b) == 3:
                    break
            stream_b.close()
            worker_b.close()

            assert sorted(acked_a + got_b) == [0, 1, 2, 3, 4]
            assert server.pending_count("work") == 0
        finally:
            server.close()

    def test_ack_timeout_requeues(self):
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
        server = self._server(ack_timeout_s=0.2)
        try:
            client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            client.publish("work", {"n": 7})
            stream = client.pull("work")
            first_id, _ = next(iter(stream))
            # Hold the stream open without acking: the sweeper requeues
            # after the deadline and redelivers on the same stream.
            second_id, frame = next(iter(stream))
            assert json.loads(frame) == {"n": 7}
            assert second_id != first_id
            client.ack("work", second_id, ok=True)
            stream.close()
            assert server.pending_count("work") == 0
            client.close()
        finally:
            server.close()

    def test_remote_bus_handler_failure_nacks_for_other_worker(self):
        """An exhausted handler NACKs so ANOTHER worker gets the item —
        the broker-redelivers contract the reference had."""
        import time

        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient, RemoteBus
        server = self._server(max_attempts=5)
        try:
            bad = RemoteBus(f"127.0.0.1:{server.bound_port}",
                            max_redeliveries=1)
            bad.subscribe("work", lambda payload: (_ for _ in ()).throw(
                RuntimeError("always fails")))
            time.sleep(0.3)  # let the bad worker own the stream
            pub = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            pub.publish("work", {"n": 42})
            # Frame bounces off the bad worker and returns to the queue.
            assert _wait_until(
                lambda: server.pending_count("work") >= 1, 5.0)
            bad.close()

            good_got = []
            good = RemoteBus(f"127.0.0.1:{server.bound_port}")
            good.subscribe("work", good_got.append)
            assert _wait_until(lambda: good_got == [{"n": 42}], 5.0)
            good.close()
            pub.close()
        finally:
            server.close()

    def test_remote_bus_manual_ack_handler(self):
        """Two-argument handlers own the ack (TPU-worker pattern)."""
        import time

        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient, RemoteBus
        server = self._server()
        try:
            held = []
            bus = RemoteBus(f"127.0.0.1:{server.bound_port}")
            bus.subscribe("work", lambda payload, ack: held.append(
                (payload, ack)))
            pub = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            pub.publish("work", {"n": 9})
            assert _wait_until(lambda: len(held) == 1)
            # Not acked yet: still pending server-side.
            assert server.pending_count("work") == 1
            held[0][1](True)
            assert _wait_until(
                lambda: server.pending_count("work") == 0)
            bus.close()
            pub.close()
        finally:
            server.close()


class TestLocalSubscriberParity:
    """Local (in-process) subscribers get the same bounded-retry treatment
    as pulled frames (VERDICT r2 weak #4; `distributed/pubsub.go:157-171`
    retried every subscriber on handler error)."""

    def _server(self, **kw):
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusServer
        server = GrpcBusServer(address="127.0.0.1:0", **kw)
        server.start()
        return server

    def test_local_handler_retries_then_delivers(self):
        server = self._server(max_attempts=5)
        try:
            calls = []

            def flaky(payload):
                calls.append(payload)
                if len(calls) <= 2:
                    raise RuntimeError("transient")

            server.subscribe("results", flaky)
            server.publish("results", {"ok": 1})
            assert server.flush_local()
            assert len(calls) == 3  # threw twice, succeeded third
            assert server.dead_letters == 0
        finally:
            server.close()

    def test_local_handler_exhaustion_dead_letters(self):
        server = self._server(max_attempts=2)
        try:
            server.subscribe("results", lambda p: (_ for _ in ()).throw(
                RuntimeError("permanent")))
            server.publish("results", {"ok": 1})
            assert server.flush_local()
            assert server.dead_letters == 1
        finally:
            server.close()

    def test_local_dispatch_off_grpc_thread(self):
        """publish() returns before a slow handler finishes."""
        import time

        server = self._server()
        try:
            done = []

            def slow(payload):
                time.sleep(0.4)
                done.append(payload)

            server.subscribe("results", slow)
            t0 = time.monotonic()
            server.publish("results", {"ok": 1})
            assert time.monotonic() - t0 < 0.3
            assert server.flush_local()
            assert done == [{"ok": 1}]
        finally:
            server.close()

    def test_sweeper_requeues_without_active_puller(self):
        """Expired in-flight frames requeue even when no pull stream is
        alive (ADVICE r2: sweep ran only inside pull loops)."""
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
        server = self._server(ack_timeout_s=0.2)
        server.enable_pull("work")
        try:
            client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            client.publish("work", {"n": 1})
            stream = client.pull("work")
            next(iter(stream))       # deliver without acking...
            stream.close()           # ...then kill the only puller
            # The dedicated sweeper (not a pull loop) must requeue it.
            assert _wait_until(lambda: server.pending_count("work") == 1, 5.0)
            client.close()
        finally:
            server.close()


class TestManualAckSubscribe:
    def _remote(self, **kw):
        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusServer, RemoteBus
        server = GrpcBusServer(address="127.0.0.1:0", **kw)
        server.enable_pull("work")
        server.start()
        return server, RemoteBus(f"127.0.0.1:{server.bound_port}")

    def test_var_positional_not_manual_ack(self):
        """`lambda *a` is plain delivery, not manual-ack (ADVICE r2)."""
        server, bus = self._remote()
        try:
            got = []
            bus.subscribe("work", lambda *a: got.append(a[0]))
            from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
            pub = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            pub.publish("work", {"n": 3})
            assert _wait_until(lambda: got == [{"n": 3}], 5.0)
            # Auto-acked: nothing stays in flight cycling to dead-letter.
            assert _wait_until(lambda: server.pending_count("work") == 0, 5.0)
            pub.close()
            bus.close()
        finally:
            server.close()

    def test_manual_ack_shadowing_rejected(self):
        import pytest

        server, bus = self._remote()
        try:
            bus.subscribe("work", lambda p: None)
            with pytest.raises(ValueError, match="shadow"):
                bus.subscribe("work", lambda p, ack: None)
        finally:
            bus.close()
            server.close()

    def test_subscriber_after_manual_ack_rejected(self):
        import pytest

        server, bus = self._remote()
        try:
            bus.subscribe("work", lambda p, ack: ack(True))
            with pytest.raises(ValueError, match="manual-ack"):
                bus.subscribe("work", lambda p: None)
        finally:
            bus.close()
            server.close()

    def test_explicit_manual_ack_flag(self):
        """`manual_ack=True` forces ack mode for a *args handler."""
        server, bus = self._remote()
        try:
            held = []
            bus.subscribe("work", lambda *a: held.append(a),
                          manual_ack=True)
            from distributed_crawler_tpu.bus.grpc_bus import GrpcBusClient
            pub = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
            pub.publish("work", {"n": 5})
            assert _wait_until(lambda: len(held) == 1, 5.0)
            assert server.pending_count("work") == 1  # unacked
            held[0][1](True)
            assert _wait_until(lambda: server.pending_count("work") == 0, 5.0)
            pub.close()
            bus.close()
        finally:
            server.close()


class TestCloseDrainsLocal:
    def test_close_delivers_queued_local_messages(self):
        """An acked Publish must reach local handlers even when close()
        races the dispatch (review finding on flush-then-stop ordering)."""
        import time

        from distributed_crawler_tpu.bus.grpc_bus import GrpcBusServer
        server = GrpcBusServer(address="127.0.0.1:0")
        server.start()
        got = []

        def slowish(payload):
            time.sleep(0.2)
            got.append(payload)

        server.subscribe("results", slowish)
        for i in range(3):
            server.publish("results", {"n": i})
        server.close()  # must drain all three, not drop the backlog
        assert got == [{"n": 0}, {"n": 1}, {"n": 2}]
