"""TL API constructor layer (`clients/tl_api.py` + `native/tl_api.h`).

The schema-level tests pin the codec (roundtrips, fallback rules,
rpc_result correlation); the cross-implementation e2e asserts the C++
client puts TYPED constructors on the wire for the hot crawl RPCs — the
closed VERDICT r04 delta ("JSON-in-TL-bytes rather than TL API
constructors").
"""

import json
import struct

import pytest

from distributed_crawler_tpu.clients import tl_api
from distributed_crawler_tpu.clients.tl_api import (
    BY_ID,
    BY_NAME,
    FUNC_BY_JSON_TYPE,
    RPC_RESULT,
    TYPE_BY_JSON_TYPE,
    deserialize_frame,
    deserialize_request,
    serialize_request,
    serialize_result,
    serialize_update,
)


class TestSchema:
    def test_ids_unique_and_stable(self):
        ids = list(BY_ID)
        assert len(ids) == len(set(ids))
        # Construction rule: crc32 of the canonical line (TL standard).
        import zlib

        line = tl_api.SCHEMA_FUNCTIONS[0]
        assert BY_NAME["dct.searchPublicChat"].cid == \
            zlib.crc32(line.encode()) & 0xFFFFFFFF

    def test_all_hot_methods_are_typed_functions(self):
        for m in ("searchPublicChat", "getChat", "getChatHistory",
                  "getMessage", "getMessageLink", "getMessageThread",
                  "getMessageThreadHistory", "getSupergroup",
                  "getSupergroupFullInfo", "getBasicGroupFullInfo",
                  "getRemoteFile", "downloadFile"):
            assert m in FUNC_BY_JSON_TYPE, m


class TestRequestCodec:
    def test_typed_request_roundtrip(self):
        req = {"@type": "getChatHistory", "chat_id": 4242,
               "from_message_id": 9, "offset": -1, "limit": 100}
        frame = serialize_request(dict(req))
        # Wire bytes are BINARY TL, not JSON: the typed frame must not
        # contain the method name or any JSON.
        assert frame[:4] == struct.pack(
            "<I", FUNC_BY_JSON_TYPE["getChatHistory"].cid)
        assert b"getChatHistory" not in frame
        assert b"{" not in frame
        assert deserialize_request(frame) == req

    def test_unlisted_type_rides_declared_raw_fallback(self):
        req = {"@type": "setAuthenticationPhoneNumber",
               "phone_number": "+1555"}
        frame = serialize_request(dict(req))
        assert frame[:4] == struct.pack(
            "<I", BY_NAME["dct.rawRequest"].cid)
        assert deserialize_request(frame) == req

    def test_missing_fields_default(self):
        frame = serialize_request({"@type": "searchPublicChat"})
        assert deserialize_request(frame) == {
            "@type": "searchPublicChat", "username": ""}

    def test_unknown_constructor_rejected(self):
        with pytest.raises(ValueError, match="unknown TL function"):
            deserialize_request(struct.pack("<I", 0xDEADBEEF))

    def test_trailing_garbage_rejected(self):
        """A frame followed by extra bytes is forged/corrupt and must raise
        ValueError, not silently parse the prefix."""
        whole = serialize_request({"@type": "getChat", "chat_id": 7})
        for junk in (b"\x00", b"\x00\x00\x00\x00", b"garbage!"):
            with pytest.raises(ValueError, match="trailing"):
                deserialize_request(whole + junk)

    def test_stats_counters_thread_safe(self):
        """Concurrent gateway sessions share STATS; N threads x M frames
        must count exactly N*M (the lock-free read-modify-write undercounts
        under contention)."""
        import threading

        frame = serialize_request({"@type": "getChat", "chat_id": 1})
        n_threads, n_frames = 8, 250
        before = tl_api.STATS["typed_requests"]
        barrier = threading.Barrier(n_threads)

        def worker():
            barrier.wait()
            for _ in range(n_frames):
                deserialize_request(frame)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert tl_api.STATS["typed_requests"] - before == \
            n_threads * n_frames

    def test_truncated_frames_raise_valueerror(self):
        """Adversarial truncation must surface as ValueError — the class
        the gateway session loop catches — never struct.error/IndexError
        (which would kill the session thread with a traceback)."""
        whole = serialize_request({"@type": "getChat", "chat_id": 7})
        for cut in range(len(whole)):
            with pytest.raises(ValueError):
                deserialize_request(whole[:cut])
        # Truncated string field inside a typed frame.
        whole = serialize_request(
            {"@type": "searchPublicChat", "username": "abcdef"})
        for cut in range(4, len(whole)):
            with pytest.raises(ValueError):
                deserialize_request(whole[:cut])


class TestResultCodec:
    def test_typed_result_roundtrip_with_correlation(self):
        chat = {"@type": "chat", "id": 777, "title": "T", "type":
                "supergroup", "supergroup_id": 500777, "basic_group_id": 0,
                "photo_remote_id": ""}
        frame = serialize_result(dict(chat), req_msg_id=123456789)
        assert frame[:4] == struct.pack("<I", RPC_RESULT)
        req_msg_id, obj = deserialize_frame(frame)
        assert req_msg_id == 123456789
        assert obj == chat

    def test_messages_vector_roundtrip(self):
        msgs = {"@type": "messages", "total_count": 2, "messages": [
            {"@type": "message", "id": 1 << 20, "chat_id": 777,
             "date": 1700000000, "view_count": 5, "forward_count": 0,
             "reply_count": 2, "message_thread_id": 0,
             "reply_to_message_id": 0, "sender_id": 9,
             "sender_username": "u", "is_channel_post": True,
             "content": {"@type": "messageText",
                         "text": {"text": "hi", "entities": []}},
             "reactions": None},
            {"@type": "message", "id": 2 << 20, "chat_id": 777,
             "date": 1700000001, "view_count": 6, "forward_count": 1,
             "reply_count": 0, "message_thread_id": 0,
             "reply_to_message_id": 0, "sender_id": 9,
             "sender_username": "u", "is_channel_post": True,
             "content": {"@type": "messageText",
                         "text": {"text": "yo", "entities": []}},
             "reactions": [{"emoji": "x", "count": 3}]},
        ]}
        req_msg_id, obj = deserialize_frame(
            serialize_result(json.loads(json.dumps(msgs)), 42))
        assert req_msg_id == 42
        assert obj == msgs

    def test_error_is_typed(self):
        err = {"@type": "error", "code": 429,
               "message": "Too Many Requests: retry after 400"}
        frame = serialize_result(dict(err), 7)
        assert struct.unpack_from("<I", frame, 12)[0] == \
            TYPE_BY_JSON_TYPE["error"].cid
        assert deserialize_frame(frame)[1] == err

    def test_unlisted_response_rides_raw_result(self):
        resp = {"@type": "user", "id": 5, "username": "u"}
        req_msg_id, obj = deserialize_frame(serialize_result(dict(resp), 9))
        assert req_msg_id == 9
        assert obj == resp

    def test_result_trailing_garbage_rejected(self):
        frame = serialize_result({"@type": "ok"}, 5)
        with pytest.raises(ValueError, match="trailing"):
            deserialize_frame(frame + b"\x00\x00\x00\x00")
        upd = serialize_update({"@type": "updateAuthorizationState"})
        with pytest.raises(ValueError, match="trailing"):
            deserialize_frame(upd + b"x")

    def test_negative_vector_count_rejected(self):
        """Forge the messages vector's count to -1: the old code ranged
        over nothing and returned an empty vector with the element bytes
        left as garbage; now it must raise."""
        msgs = {"@type": "messages", "total_count": 1, "messages": [
            {"@type": "message", "id": 1, "chat_id": 2, "date": 3,
             "view_count": 0, "forward_count": 0, "reply_count": 0,
             "message_thread_id": 0, "reply_to_message_id": 0,
             "sender_id": 0, "sender_username": "u",
             "is_channel_post": True, "content": None,
             "reactions": None}]}
        frame = bytearray(serialize_result(dict(msgs), 42))
        # rpc_result(4) + req_msg_id(8) + messages cid(4) + total_count(8)
        # + Vector cid(4) -> count lives at bytes [28:32).
        assert frame[24:28] == struct.pack("<I", tl_api.VECTOR)
        frame[28:32] = struct.pack("<i", -1)
        with pytest.raises(ValueError, match="negative TL vector count"):
            deserialize_frame(bytes(frame))

    def test_update_frame_has_no_correlation(self):
        upd = {"@type": "updateAuthorizationState",
               "authorization_state": {"@type": "authorizationStateReady"}}
        req_msg_id, obj = deserialize_frame(serialize_update(dict(upd)))
        assert req_msg_id is None
        assert obj == upd


def _lib_available() -> bool:
    from distributed_crawler_tpu.clients.native import find_library

    try:
        find_library()
        return True
    except Exception:
        return False


@pytest.mark.skipif(not _lib_available(),
                    reason="libdct_client.so not built")
class TestCppClientSendsTypedTl:
    def test_hot_rpcs_ride_typed_constructors(self, tmp_path):
        """The C++ twin must serialize the hot crawl RPCs as TYPED TL
        constructors — if it fell back to dct.rawRequest for everything,
        the wire would be the old JSON-in-TL-bytes delta under a new name.
        The gateway-side decoder counts both kinds."""
        from distributed_crawler_tpu.clients.dc_gateway import DcGateway
        from distributed_crawler_tpu.clients.native import (
            NativeTelegramClient,
        )
        from tests.test_mtproto import SEED

        before = dict(tl_api.STATS)
        gw = DcGateway(seed_json=SEED, expected_code="13579",
                       wire="mtproto", store_root=str(tmp_path)).start()
        try:
            c = NativeTelegramClient(server_addr=gw.address, wire="mtproto",
                                     server_pubkey_file=gw.pubkey_file,
                                     conn_id="tl-typed")
            try:
                c.authenticate("+15550001111", "13579")
                c.wait_ready(5.0)
                chat = c.search_public_chat("mtroot")
                hist = c.get_chat_history(chat.id, limit=10)
                msgs = getattr(hist, "messages", hist)
                assert len(msgs) == 1
                c.get_message_thread(chat.id, msgs[0].id)
            finally:
                c.close()
        finally:
            gw.close()
        typed = tl_api.STATS["typed_requests"] - before["typed_requests"]
        raw = tl_api.STATS["raw_requests"] - before["raw_requests"]
        # searchPublicChat + getChatHistory + getMessageThread (+ internal
        # typed calls) are typed; the auth ladder + handshake + close ride
        # the declared raw fallback.
        assert typed >= 3
        assert raw >= 4


class TestProperties:
    """Property-based coverage (hypothesis): the TL codec must roundtrip
    arbitrary field values — unicode, astral chars, negative ints, 64-bit
    extremes, arbitrary JSON content — byte-exactly.

    importorskip runs INSIDE each test (the test_inference.py pattern): a
    class-body skip executes at import time and would skip this whole
    module — including the schema/codec tests above — on hosts without
    hypothesis."""

    def test_typed_function_roundtrip_property(self):
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        @settings(max_examples=200, deadline=None)
        @given(chat_id=st.integers(-2**63, 2**63 - 1),
               from_id=st.integers(-2**63, 2**63 - 1),
               offset=st.integers(-2**31, 2**31 - 1),
               limit=st.integers(-2**31, 2**31 - 1))
        def check(chat_id, from_id, offset, limit):
            req = {"@type": "getChatHistory", "chat_id": chat_id,
                   "from_message_id": from_id, "offset": offset,
                   "limit": limit}
            assert deserialize_request(serialize_request(dict(req))) == req

        check()

    def test_string_field_roundtrip_property(self):
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        @settings(max_examples=200, deadline=None)
        @given(username=st.text(max_size=600))
        def check(username):
            req = {"@type": "searchPublicChat", "username": username}
            assert deserialize_request(serialize_request(dict(req))) == req

        check()

    def test_raw_fallback_roundtrip_property(self):
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        json_vals = st.recursive(
            st.none() | st.booleans() | st.integers(-2**53, 2**53)
            | st.text(max_size=40),
            lambda inner: st.lists(inner, max_size=4)
            | st.dictionaries(st.text(max_size=8), inner, max_size=4),
            max_leaves=12)

        @settings(max_examples=100, deadline=None)
        @given(body=st.dictionaries(st.text(min_size=1, max_size=10),
                                    json_vals, max_size=5))
        def check(body):
            req = {"@type": "someUnlistedThing", **body}
            req.pop("@extra", None)
            assert deserialize_request(serialize_request(dict(req))) == req

        check()

    def test_result_datajson_roundtrip_property(self):
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        @settings(max_examples=100, deadline=None)
        @given(text=st.text(max_size=200),
               req_msg_id=st.integers(-2**63, 2**63 - 1))
        def check(text, req_msg_id):
            msg = {"@type": "message", "id": 1, "chat_id": 2, "date": 3,
                   "view_count": 0, "forward_count": 0, "reply_count": 0,
                   "message_thread_id": 0, "reply_to_message_id": 0,
                   "sender_id": 0, "sender_username": "",
                   "is_channel_post": False,
                   "content": {"@type": "messageText",
                               "text": {"text": text}},
                   "reactions": None}
            got_id, obj = deserialize_frame(
                serialize_result(json.loads(json.dumps(msg)), req_msg_id))
            assert got_id == req_msg_id
            assert obj == msg

        check()
