"""Data-model golden-JSON tests (reference test analog: model round-trips +
null_handler behavior, SURVEY.md §4)."""

import json
from datetime import datetime, timezone

from distributed_crawler_tpu.datamodel import (
    Behavior,
    ChannelData,
    Comment,
    EngagementData,
    FieldRule,
    NullValidator,
    Post,
    default_configs,
    load_config_from_json,
    merge_configs,
)
from distributed_crawler_tpu.datamodel.post import ZERO_TIME_STR, format_time, parse_time

EXPECTED_POST_FIELDS = [
    "post_link", "channel_id", "post_uid", "url", "published_at", "created_at",
    "language_code", "engagement", "view_count", "like_count", "share_count",
    "comment_count", "crawl_label", "list_ids", "channel_name", "search_terms",
    "search_term_ids", "project_ids", "exercise_ids", "label_data",
    "labels_metadata", "project_labeled_post_ids", "labeler_ids", "all_labels",
    "label_ids", "is_ad", "transcript_text", "image_text", "video_length",
    "is_verified", "channel_data", "platform_name", "shared_id", "quoted_id",
    "replied_id", "ai_label", "root_post_id", "engagement_steps_count",
    "ocr_data", "performance_scores", "has_embed_media", "description",
    "repost_channel_data", "post_type", "inner_link", "post_title", "media_data",
    "is_reply", "ad_fields", "likes_count", "shares_count", "comments_count",
    "views_count", "searchable_text", "all_text", "contrast_agent_project_ids",
    "agent_ids", "segment_ids", "thumb_url", "media_url", "comments",
    "reactions", "outlinks", "capture_time", "handle",
]


def make_post(**kw) -> Post:
    base = dict(
        post_link="https://t.me/somechannel/42",
        channel_id="somechannel",
        post_uid="42",
        url="https://t.me/somechannel/42",
        published_at=datetime(2026, 1, 2, 3, 4, 5, tzinfo=timezone.utc),
        platform_name="telegram",
        channel_data=ChannelData(
            channel_id="somechannel",
            channel_name="Some Channel",
            channel_url="https://t.me/somechannel",
        ),
        description="hello world",
    )
    base.update(kw)
    return Post(**base)


class TestPostSchema:
    def test_exact_wire_fields(self):
        # Field-for-field parity with model/data.go:9-75 (65 top-level JSON keys).
        d = make_post().to_dict()
        assert list(d.keys()) == EXPECTED_POST_FIELDS

    def test_json_roundtrip(self):
        p = make_post(
            comments=[Comment(text="hi", reactions={"👍": 3}, view_count=5)],
            reactions={"❤": 2},
            outlinks=["other_channel"],
            video_length=120,
            is_verified=True,
            capture_time=datetime(2026, 2, 2, tzinfo=timezone.utc),
        )
        p2 = Post.from_json(p.to_json())
        assert p2 == p

    def test_zero_time_serialization(self):
        d = make_post(created_at=None).to_dict()
        assert d["created_at"] == ZERO_TIME_STR
        assert parse_time(ZERO_TIME_STR) is None
        assert format_time(None) == ZERO_TIME_STR

    def test_nanosecond_timestamps_parse(self):
        # Go RFC3339Nano emits >6 fractional digits; must not be dropped.
        dt = parse_time("2026-01-02T03:04:05.123456789Z")
        assert dt is not None and dt.microsecond == 123456

    def test_from_dict_tolerates_missing_keys(self):
        p = Post.from_dict({"post_link": "x"})
        assert p.post_link == "x"
        assert p.comments == [] and p.reactions == {}

    def test_text_for_inference_priority(self):
        p = make_post(all_text="A", searchable_text="S", description="D")
        assert p.text_for_inference() == "A"
        p = make_post(all_text="", searchable_text="S")
        assert p.text_for_inference() == "S"
        p = make_post(description="D")
        assert p.text_for_inference() == "D"


class TestNullValidator:
    def test_valid_post_passes(self):
        v = NullValidator("telegram")
        res = v.validate_post(make_post())
        assert res.valid
        assert res.errors == []
        # Platform-unavailable fields are tracked, not errors.
        assert "language_code" in res.unavailable_used

    def test_missing_critical_fails(self):
        v = NullValidator("telegram")
        res = v.validate_post(make_post(post_uid=""))
        assert not res.valid
        assert "post_uid" in res.errors

    def test_missing_critical_channel_field_fails(self):
        v = NullValidator("youtube")
        res = v.validate_channel_data(ChannelData(channel_name="n", channel_url="u"))
        assert not res.valid
        assert "channel_data.channel_id" in res.errors

    def test_warnings_for_log_fields(self):
        v = NullValidator("youtube")
        res = v.validate_post(make_post(platform_name="youtube", description=""))
        assert "description" in res.warnings

    def test_null_log_events_emitted(self):
        v = NullValidator("telegram")
        res = v.validate_post(make_post())
        assert res.null_log_events
        ev = {e.field_name: e for e in res.null_log_events}
        assert ev["language_code"].is_platform_limit is True
        assert ev["language_code"].strategy_used == "unavailable"

    def test_user_config_merge_overrides(self):
        # null_handler/main.go:257-291: user rules override defaults.
        cfg = merge_configs("youtube", {
            "description": FieldRule(Behavior.CRITICAL, "Description is now critical!")})
        v = NullValidator("youtube", config=cfg)
        res = v.validate_post(make_post(platform_name="youtube", description=""))
        assert not res.valid and "description" in res.errors

    def test_load_config_from_json(self):
        user_json = json.dumps({
            "platform": "youtube",
            "rules": {"channel_data.channel_description": {
                "behavior": "critical", "message": "now critical"}},
        })
        cfg = load_config_from_json(user_json, "youtube")
        assert cfg.rules["channel_data.channel_description"].behavior is Behavior.CRITICAL
        # untouched defaults survive the merge
        assert cfg.rules["post_link"].behavior is Behavior.CRITICAL

    def test_unknown_platform_raises(self):
        try:
            merge_configs("myspace", None)
            assert False, "expected ValueError"
        except ValueError:
            pass

    def test_rule_tables_cover_both_platforms(self):
        cfgs = default_configs()
        for platform in ("telegram", "youtube"):
            rules = cfgs[platform].rules
            # Core critical set per null_handler/main.go:70-254.
            for path in ("post_link", "channel_id", "post_uid", "url",
                         "published_at", "platform_name",
                         "channel_data.channel_id", "channel_data.channel_url"):
                assert rules[path].behavior is Behavior.CRITICAL, (platform, path)
            assert len(rules) > 60

    def test_engagement_data_zero_fields_warn(self):
        v = NullValidator("telegram")
        res = v.validate_channel_data(ChannelData(
            channel_id="c", channel_name="n", channel_url="u",
            channel_engagement_data=EngagementData()))
        assert res.valid
        assert "channel_data.channel_engagement_data.follower_count" in res.warnings
