"""Client-layer tests: error taxonomy, rate limiter timing (with a fake
clock), connection pool retire/recreate, sim client, username filter matrix,
t.me HTML validator against fixtures, YouTube sampling methods.

Reference analogs: rate_limiter_test.go (inter-call spacing),
connection_pool_test.go, channelvalidator_test.go (HTML fixtures),
username filter tests, youtube client tests.
"""

import os
import random

import pytest

from distributed_crawler_tpu.clients import (
    BLOCKED,
    TRANSIENT,
    ConnectionPool,
    FakeClock,
    FakeYouTubeTransport,
    FloodWaitError,
    RateLimitedTelegramClient,
    SimNetwork,
    SimTelegramClient,
    TelegramError,
    TokenBucket,
    ValidationHTTPError,
    ValidatorRateLimiter,
    YouTubeDataClient,
    filter_username,
    generate_random_prefix,
    parse_channel_html,
    parse_flood_wait_seconds,
    validate_channel_http,
)
from distributed_crawler_tpu.clients.errors import is_telegram_400
from distributed_crawler_tpu.clients.pool import PoolEmptyError
from distributed_crawler_tpu.clients.telegram import TLMessage
from distributed_crawler_tpu.config import TelegramRateLimitConfig

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "telegram-html")


def fixture(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read()


class TestErrors:
    def test_flood_wait_tdlib_format(self):
        secs, is_fw = parse_flood_wait_seconds(Exception("[429] FLOOD_WAIT_72560"))
        assert (secs, is_fw) == (72560, True)

    def test_flood_wait_http_format(self):
        secs, is_fw = parse_flood_wait_seconds(
            Exception("429 Too Many Requests: retry after 120"))
        assert (secs, is_fw) == (120, True)

    def test_flood_wait_unparseable_is_short_ban(self):
        secs, is_fw = parse_flood_wait_seconds(Exception("FLOOD_WAIT_"))
        assert (secs, is_fw) == (0, True)

    def test_not_flood_wait(self):
        assert parse_flood_wait_seconds(Exception("connection reset")) == (0, False)
        assert parse_flood_wait_seconds(None) == (0, False)

    def test_flood_wait_error_type(self):
        e = FloodWaitError(400)
        assert parse_flood_wait_seconds(e) == (400, True)
        assert e.code == 429

    def test_telegram_400_detection(self):
        assert is_telegram_400(TelegramError(400, "USERNAME_INVALID"))
        assert is_telegram_400(Exception("[400] CHANNEL_INVALID"))
        assert is_telegram_400(Exception("400 USERNAME_NOT_OCCUPIED"))
        assert is_telegram_400(Exception("no messages found in the chat"))
        assert not is_telegram_400(Exception("[500] internal"))
        assert not is_telegram_400(None)


def make_network(n_msgs=5):
    net = SimNetwork()
    msgs = [TLMessage(content={"@type": "messageText", "text": f"msg {i}"},
                      date=1700000000 + i) for i in range(n_msgs)]
    net.add_channel("mychannel", messages=msgs, member_count=5000)
    return net


class TestTokenBucket:
    def test_spacing(self):
        clock = FakeClock()
        bucket = TokenBucket(60.0, clock)  # 1/sec
        assert bucket.wait() == 0.0  # first token free
        waited = bucket.wait()
        assert waited == pytest.approx(1.0)

    def test_unlimited(self):
        clock = FakeClock()
        bucket = TokenBucket(0, clock)
        for _ in range(100):
            assert bucket.wait() == 0.0
        assert clock.now == 0.0


class TestRateLimitedClient:
    def _limited(self, net=None, cfg=None):
        clock = FakeClock()
        net = net or make_network()
        raw = SimTelegramClient(net, clock=clock)
        cfg = cfg or TelegramRateLimitConfig(
            get_chat_history_jitter_ms=0, search_public_chat_jitter_ms=0,
            get_supergroup_info_jitter_ms=0, get_message_server_hit_jitter_ms=0)
        limited = RateLimitedTelegramClient(raw, cfg, clock=clock,
                                            rng=random.Random(0))
        return limited, raw, clock, net

    def test_chat_history_inter_call_spacing(self):
        # 30 cpm -> 2s between calls (rate_limiter_test.go analog).
        limited, raw, clock, net = self._limited()
        chat_id = net.channels["mychannel"].chat_id
        t0 = clock.now
        limited.get_chat_history(chat_id)
        t1 = clock.now
        limited.get_chat_history(chat_id)
        t2 = clock.now
        assert t2 - t1 >= 2.0 - (t1 - t0)

    def test_search_public_chat_rate(self):
        limited, raw, clock, net = self._limited()
        limited.search_public_chat("mychannel")
        before = clock.now
        limited.search_public_chat("mychannel")
        assert clock.now - before >= 9.9  # 6 cpm -> 10s spacing

    def test_reactive_get_message_cache_hits_free(self):
        limited, raw, clock, net = self._limited()
        chat_id = net.channels["mychannel"].chat_id
        msg_id = net.channels["mychannel"].messages[0].id
        # First call: server hit (20ms latency) -> consumes a token.
        limited.get_message(chat_id, msg_id)
        t_after_first = clock.now
        # Second call: local cache (1ms) -> no token, no throttle.
        limited.get_message(chat_id, msg_id)
        elapsed = clock.now - t_after_first
        assert elapsed < 0.01

    def test_reactive_get_message_server_hits_throttled(self):
        limited, raw, clock, net = self._limited()
        chat_id = net.channels["mychannel"].chat_id
        ids = [m.id for m in net.channels["mychannel"].messages]
        # Distinct messages: every call is a server hit; 60 cpm -> 1s apart.
        limited.get_message(chat_id, ids[0])
        t1 = clock.now
        limited.get_message(chat_id, ids[1])
        # Second server hit pays the reactive throttle delay (~1s).
        assert clock.now - t1 >= 0.9

    def test_passthrough_methods_not_limited(self):
        limited, raw, clock, net = self._limited()
        chat_id = net.channels["mychannel"].chat_id
        t0 = clock.now
        for _ in range(10):
            limited.get_chat(chat_id)
        # Only sim cache latency, no limiter waits.
        assert clock.now - t0 < 0.2

    def test_error_still_counts_server_hit(self):
        limited, raw, clock, net = self._limited()
        chat_id = net.channels["mychannel"].chat_id
        with pytest.raises(TelegramError):
            limited.get_message(chat_id, 999999999)  # not found, server hit
        # Error propagates after throttling bookkeeping.


class TestConnectionPool:
    def _pool(self, n=2, net=None):
        net = net or make_network()
        cfg = TelegramRateLimitConfig()
        pool = ConnectionPool(
            factory=lambda cid: SimTelegramClient(net, conn_id=cid),
            database_urls=[f"https://db/{i}.tar.gz" for i in range(n)],
            rate_limit=cfg)
        pool.initialize()
        return pool, net

    def test_acquire_release_reuse(self):
        pool, net = self._pool(2)
        c1 = pool.acquire(timeout_s=1)
        c2 = pool.acquire(timeout_s=1)
        assert c1.conn_id != c2.conn_id
        pool.release(c1)
        c3 = pool.acquire(timeout_s=1)
        assert c3.conn_id == c1.conn_id
        assert c3.uses == 2  # reused without re-login

    def test_clients_wrapped_in_rate_limiter(self):
        pool, _ = self._pool(1)
        conn = pool.acquire(timeout_s=1)
        assert isinstance(conn.client, RateLimitedTelegramClient)

    def test_retire_until_empty(self):
        pool, _ = self._pool(2)
        pool.retire("conn_0", "flood_wait_72560")
        assert not pool.empty()
        pool.retire("conn_1", "flood_wait_90000")
        assert pool.empty()
        with pytest.raises(PoolEmptyError):
            pool.acquire(timeout_s=0.1)
        stats = pool.stats()
        assert stats["retired"] == 2 and stats["live"] == 0

    def test_retired_connection_not_returned(self):
        pool, _ = self._pool(2)
        c1 = pool.acquire(timeout_s=1)
        pool.release(c1)
        pool.retire(c1.conn_id)
        c = pool.acquire(timeout_s=1)
        assert c.conn_id != c1.conn_id

    def test_recreate_after_error(self):
        pool, net = self._pool(1)
        conn = pool.acquire(timeout_s=1)
        conn.client.close()
        fresh = pool.recreate(conn)
        assert fresh.conn_id == conn.conn_id
        assert fresh.errors == 1
        chat_id = net.channels["mychannel"].chat_id
        fresh.client.get_chat(chat_id)  # fresh client works, owned by caller
        pool.release(fresh)
        got = pool.acquire(timeout_s=1)
        assert got is fresh

    def test_recreate_caller_owns_fresh_connection(self):
        # recreate() must not also enqueue the id — otherwise two acquirers
        # could share one client.
        pool, _ = self._pool(1)
        conn = pool.acquire(timeout_s=1)
        fresh = pool.recreate(conn)
        with pytest.raises(TimeoutError):
            pool.acquire(timeout_s=0.1)  # fresh is owned by the caller
        pool.release(fresh)
        again = pool.acquire(timeout_s=1)
        assert again is fresh

    def test_release_of_stale_handle_ignored(self):
        pool, _ = self._pool(1)
        conn = pool.acquire(timeout_s=1)
        fresh = pool.recreate(conn)
        pool.release(conn)  # stale object: must be a no-op
        with pytest.raises(TimeoutError):
            pool.acquire(timeout_s=0.1)
        pool.release(fresh)
        assert pool.acquire(timeout_s=1) is fresh

    def test_for_testing_constructor(self):
        net = make_network()
        pool = ConnectionPool.for_testing(
            {"a": SimTelegramClient(net, "a"), "b": SimTelegramClient(net, "b")})
        assert pool.stats()["total"] == 2
        conn = pool.acquire(timeout_s=1)
        assert conn.conn_id in ("a", "b")


class TestSimClient:
    def test_chat_history_pagination_newest_first(self):
        net = make_network(n_msgs=7)
        client = SimTelegramClient(net)
        chat_id = net.channels["mychannel"].chat_id
        page1 = client.get_chat_history(chat_id, from_message_id=0, limit=3)
        assert len(page1.messages) == 3
        assert page1.messages[0].id > page1.messages[-1].id
        page2 = client.get_chat_history(
            chat_id, from_message_id=page1.messages[-1].id, limit=100)
        assert len(page2.messages) == 4
        assert page2.messages[0].id < page1.messages[-1].id

    def test_flood_wait_injection(self):
        net = make_network()
        net.inject_flood_wait("SearchPublicChat", 400, count=1)
        client = SimTelegramClient(net)
        with pytest.raises(FloodWaitError) as ei:
            client.search_public_chat("mychannel")
        assert ei.value.retry_after_s == 400
        # Fault consumed; next call succeeds.
        chat = client.search_public_chat("mychannel")
        assert chat.id == net.channels["mychannel"].chat_id

    def test_file_download_and_delete(self):
        net = make_network()
        net.add_file("remote123", b"JPEGDATA")
        client = SimTelegramClient(net)
        f = client.get_remote_file("remote123")
        f = client.download_file(f.id)
        assert f.downloaded and os.path.exists(f.local_path)
        with open(f.local_path, "rb") as fh:
            assert fh.read() == b"JPEGDATA"
        client.delete_file(f.id)
        assert not os.path.exists(f.local_path)

    def test_unknown_username_raises_400(self):
        net = make_network()
        client = SimTelegramClient(net)
        with pytest.raises(TelegramError) as ei:
            client.search_public_chat("doesnotexist")
        assert ei.value.code == 400


class TestUsernameFilter:
    @pytest.mark.parametrize("username,reason", [
        ("abcd", "too_short"),
        ("a" * 33, "too_long"),
        ("1channel", "invalid_start_char"),
        ("_underscore", "invalid_start_char"),
        ("trailing_", "ends_with_underscore"),
        ("has space", "invalid_char"),
        ("кириллица", "invalid_start_char"),
        ("somebot", "bot_suffix"),
        ("some_bot", "bot_suffix"),
        ("SomeBot", "bot_suffix"),
    ])
    def test_rejections(self, username, reason):
        res = filter_username(username)
        assert not res.valid and res.reason == reason

    @pytest.mark.parametrize("username", [
        "valid_channel", "NewsRoom24", "abcde", "x1234", "tech_news_daily"])
    def test_accepted(self, username):
        assert filter_username(username).valid


class TestChannelHTMLParsing:
    def test_valid_channel_fixture(self):
        res = parse_channel_html(fixture("valid-channel.html"))
        assert res.status == "valid" and res.reason == ""

    def test_not_supergroup_fixture(self):
        res = parse_channel_html(fixture("not-a-supergroup.html"))
        assert res.status == "not_channel" and res.reason == "not_supergroup"

    def test_username_not_occupied_fixture(self):
        res = parse_channel_html(fixture("username-not-occupied.html"))
        assert res.status == "invalid" and res.reason == "not_found"

    def test_reserved_path_fixture(self):
        res = parse_channel_html(fixture("invalid-channel.html"))
        assert res.status == "invalid" and res.reason == "not_found"

    def test_unrecognised_title_raises(self):
        with pytest.raises(ValueError, match="unrecognised title"):
            parse_channel_html("<html><head><title>Weird</title></head></html>")


class TestValidateChannelHTTP:
    def _transport(self, status, body):
        def t(url, headers):
            self.last_headers = headers
            return status, body
        return t

    def test_ok_flow_sets_chromium_ua(self):
        res = validate_channel_http(
            "examplechannel",
            transport=self._transport(200, fixture("valid-channel.html").encode()))
        assert res.status == "valid"
        assert "Chrome" in self.last_headers["User-Agent"]

    def test_5xx_is_transient(self):
        with pytest.raises(ValidationHTTPError) as ei:
            validate_channel_http("x", transport=self._transport(503, b""))
        assert ei.value.kind == TRANSIENT

    def test_4xx_is_blocked(self):
        for code in (403, 429, 404):
            with pytest.raises(ValidationHTTPError) as ei:
                validate_channel_http("x", transport=self._transport(code, b""))
            assert ei.value.kind == BLOCKED

    def test_unparseable_200_is_blocked(self):
        with pytest.raises(ValidationHTTPError) as ei:
            validate_channel_http("x", transport=self._transport(200, b"<html></html>"))
        assert ei.value.kind == BLOCKED

    def test_connection_error_is_transient(self):
        def boom(url, headers):
            raise OSError("connection reset")
        with pytest.raises(ValidationHTTPError) as ei:
            validate_channel_http("x", transport=boom)
        assert ei.value.kind == TRANSIENT

    def test_validator_rate_limiter_spacing(self):
        clock = FakeClock()
        lim = ValidatorRateLimiter(requests_per_minute=6, jitter_ms=0, clock=clock)
        lim.wait()
        t0 = clock.now
        lim.wait()
        assert clock.now - t0 >= 10.0


class TestYouTubeClient:
    def _client(self):
        transport = FakeYouTubeTransport()
        transport.add_channel("UCabc000000000000000000", "Chan A",
                              video_count=20, subscriber_count=1000)
        for i in range(5):
            transport.add_video(f"vidA{i:07d}", "UCabc000000000000000000",
                                title=f"video {i}",
                                published_at=f"2025-0{i+1}-01T00:00:00Z")
        client = YouTubeDataClient("test-key", transport, rng=random.Random(7))
        client.connect()
        return client, transport

    def test_requires_api_key(self):
        client = YouTubeDataClient("", FakeYouTubeTransport())
        with pytest.raises(ValueError):
            client.connect()

    def test_channel_info(self):
        client, _ = self._client()
        info = client.get_channel_info("UCabc000000000000000000")
        assert info.title == "Chan A"
        assert info.video_count == 20

    def test_channel_not_found(self):
        client, _ = self._client()
        with pytest.raises(LookupError):
            client.get_channel_info("UCmissing00000000000000")

    def test_videos_from_channel_with_window(self):
        from datetime import datetime, timezone
        client, _ = self._client()
        videos = client.get_videos_from_channel(
            "UCabc000000000000000000",
            from_time=datetime(2025, 2, 1, tzinfo=timezone.utc),
            to_time=datetime(2025, 4, 30, tzinfo=timezone.utc), limit=10)
        assert {v.title for v in videos} == {"video 1", "video 2", "video 3"}
        # Newest first.
        assert videos[0].published_at > videos[-1].published_at

    def test_video_without_published_at_sorts_last(self):
        client, transport = self._client()
        transport.add_video("vidA0000009", "UCabc000000000000000000",
                            title="undated", published_at="")
        videos = client.get_videos_from_channel("UCabc000000000000000000",
                                                limit=10)
        assert videos[-1].title == "undated"  # no tz-compare crash

    def test_limit_zero_fetches_all_pages(self):
        client, transport = self._client()
        videos = client.get_videos_from_channel("UCabc000000000000000000",
                                                limit=0)
        assert len(videos) == 5

    def test_videos_by_ids_uses_cache(self):
        client, transport = self._client()
        client.get_videos_by_ids(["vidA0000000", "vidA0000001"])
        calls_before = len([c for c in transport.calls if c[0] == "videos"])
        client.get_videos_by_ids(["vidA0000000", "vidA0000001"])
        calls_after = len([c for c in transport.calls if c[0] == "videos"])
        assert calls_after == calls_before  # fully served from cache

    def test_random_prefix_shape(self):
        rng = random.Random(1)
        p = generate_random_prefix(rng)
        assert p.startswith("watch?v=") and len(p) == len("watch?v=") + 5
        assert p[len("watch?v="):].isalpha() and p[len("watch?v="):].islower()

    def test_random_sampling_verifies_prefix_and_hyphen(self):
        transport = FakeYouTubeTransport()
        rng = random.Random(7)
        prefix = generate_random_prefix(random.Random(7))[len("watch?v="):]
        # True random-hit shape: PREFIX-xxxxx (hyphen at index 5).
        transport.add_video(prefix + "-12345", "UCx", view_count=10)
        # Prefix matches but no hyphen -> must be filtered out.
        transport.add_video(prefix + "z12345"[:6], "UCx")
        client = YouTubeDataClient("k", transport, rng=rng)
        client.connect()
        videos = client.get_random_videos(limit=1)
        assert [v.id for v in videos] == [prefix + "-12345"]

    def test_snowball_expands_via_descriptions(self):
        transport = FakeYouTubeTransport()
        seed = "UC" + "s" * 22
        found = "UC" + "f" * 22
        transport.add_channel(seed, "Seed", video_count=15)
        transport.add_channel(found, "Found", video_count=15)
        transport.add_video("vidseed0001", seed,
                            description=f"check out https://youtube.com/channel/{found}")
        transport.add_video("vidfound001", found, title="from found channel")
        client = YouTubeDataClient("k", transport, rng=random.Random(0))
        client.connect()
        videos = client.get_snowball_videos([seed], limit=10)
        titles = {v.title for v in videos}
        assert "from found channel" in titles

    def test_snowball_skips_small_channels(self):
        transport = FakeYouTubeTransport()
        seed = "UC" + "s" * 22
        small = "UC" + "m" * 22
        transport.add_channel(seed, "Seed", video_count=15)
        transport.add_channel(small, "Small", video_count=3)  # <= 10 videos
        transport.add_video("vidseed0001", seed,
                            description=f"https://youtube.com/channel/{small}")
        transport.add_video("vidsmall001", small, title="small channel video")
        client = YouTubeDataClient("k", transport, rng=random.Random(0))
        client.connect()
        videos = client.get_snowball_videos([seed], limit=10)
        assert "small channel video" not in {v.title for v in videos}
