"""Config layer tests: precedence chain, sampling matrix, time parsing,
distributed config validation (reference analogs: main_test.go,
common/validation_test.go)."""

from datetime import datetime, timezone

import pytest

from distributed_crawler_tpu.config import (
    ConfigResolver,
    CrawlerConfig,
    DistributedConfig,
    SamplingValidationInput,
    TelegramRateLimitConfig,
    generate_crawl_id,
    read_urls_from_file,
    validate_sampling_method,
)
from distributed_crawler_tpu.utils import parse_date_between, parse_duration, parse_time_ago


class TestRateLimitDefaults:
    def test_defaults_match_reference(self):
        # common/utils.go:35-46
        rl = TelegramRateLimitConfig()
        assert rl.get_chat_history_rate == 30
        assert rl.search_public_chat_rate == 6
        assert rl.get_supergroup_info_rate == 20
        assert rl.get_message_server_hit_rate == 60
        assert rl.get_chat_history_jitter_ms == 500
        assert rl.search_public_chat_jitter_ms == 1500


class TestCrawlerConfig:
    def test_defaults(self):
        cfg = CrawlerConfig()
        assert cfg.max_pages == 108000  # main.go:776
        assert cfg.combine_trigger_size == 170 * 1024 * 1024
        assert cfg.combine_hard_cap == 200 * 1024 * 1024
        assert cfg.validator_claim_batch_size == 10
        assert cfg.inference.batch_size == 256

    def test_crawl_id_format(self):
        cid = generate_crawl_id(datetime(2026, 7, 29, 1, 2, 3, tzinfo=timezone.utc))
        assert cid == "20260729010203"
        assert len(cid) == 14


class TestReadURLs:
    def test_skips_comments_and_blanks(self, tmp_path):
        f = tmp_path / "urls.txt"
        f.write_text("https://t.me/a\n\n# comment\n  https://t.me/b  \n")
        assert read_urls_from_file(str(f)) == ["https://t.me/a", "https://t.me/b"]


class TestSamplingValidation:
    def _inp(self, **kw):
        base = dict(platform="telegram", sampling_method="channel",
                    url_list=["https://t.me/x"])
        base.update(kw)
        return SamplingValidationInput(**base)

    def test_valid_matrix(self):
        for platform, method in [("telegram", "channel"), ("telegram", "snowball"),
                                 ("youtube", "channel"), ("youtube", "snowball")]:
            validate_sampling_method(self._inp(platform=platform, sampling_method=method))

    def test_youtube_random_needs_no_urls(self):
        validate_sampling_method(self._inp(platform="youtube", sampling_method="random",
                                           url_list=[]))

    def test_telegram_random_unsupported(self):
        with pytest.raises(ValueError, match="not supported"):
            validate_sampling_method(self._inp(sampling_method="random"))

    def test_youtube_random_walk_unsupported(self):
        with pytest.raises(ValueError, match="not supported"):
            validate_sampling_method(self._inp(platform="youtube",
                                               sampling_method="random-walk"))

    def test_unknown_platform(self):
        with pytest.raises(ValueError, match="unsupported platform"):
            validate_sampling_method(self._inp(platform="tiktok"))

    def test_random_walk_exactly_one_seed_source(self):
        validate_sampling_method(self._inp(sampling_method="random-walk", seed_size=0))
        validate_sampling_method(self._inp(sampling_method="random-walk",
                                           url_list=[], seed_size=5))
        with pytest.raises(ValueError, match="not both or neither"):
            validate_sampling_method(self._inp(sampling_method="random-walk", seed_size=5))
        with pytest.raises(ValueError, match="not both or neither"):
            validate_sampling_method(self._inp(sampling_method="random-walk",
                                               url_list=[], seed_size=0))

    def test_random_walk_crawl_id_length(self):
        with pytest.raises(ValueError, match="32 characters"):
            validate_sampling_method(self._inp(sampling_method="random-walk",
                                               crawl_id="x" * 33))

    def test_channel_requires_urls_except_job_mode(self):
        with pytest.raises(ValueError, match="requires URLs"):
            validate_sampling_method(self._inp(url_list=[]))
        validate_sampling_method(self._inp(url_list=[], mode="job"))


class TestPrecedence:
    def test_flag_beats_env_beats_file_beats_default(self, tmp_path, monkeypatch):
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text("crawler:\n  concurrency: 3\n  storage: /from/file\n")
        env = {"CRAWLER_CRAWLER_CONCURRENCY": "7"}
        r = ConfigResolver(flags={"crawler.concurrency": 9}, env=env,
                           config_file=str(cfg_file),
                           defaults={"crawler": {"concurrency": 1, "maxpages": 108000}})
        assert r.get_int("crawler.concurrency") == 9
        r2 = ConfigResolver(flags={}, env=env, config_file=str(cfg_file),
                            defaults={"crawler": {"concurrency": 1}})
        assert r2.get_int("crawler.concurrency") == 7
        r3 = ConfigResolver(flags={}, env={}, config_file=str(cfg_file),
                            defaults={"crawler": {"concurrency": 1}})
        assert r3.get_int("crawler.concurrency") == 3
        assert r3.get_str("crawler.storage") == "/from/file"
        assert r3.get_int("crawler.maxpages", 108000) == 108000

    def test_missing_explicit_config_file_raises(self):
        with pytest.raises(FileNotFoundError):
            ConfigResolver(config_file="/no/such/config.yaml")

    def test_unset_flag_falls_through(self):
        r = ConfigResolver(flags={"a.b": None}, env={}, search_paths=(),
                           defaults={"a": {"b": 5}})
        assert r.get_int("a.b") == 5

    def test_bool_and_list_coercion(self):
        r = ConfigResolver(flags={}, env={"CRAWLER_X_FLAG": "true",
                                          "CRAWLER_X_URLS": "a, b,c"},
                           search_paths=())
        assert r.get_bool("x.flag") is True
        assert r.get_list("x.urls") == ["a", "b", "c"]


class TestTimeParse:
    def test_time_ago_units(self):
        now = datetime(2026, 7, 29, 12, 0, 0, tzinfo=timezone.utc)
        assert parse_time_ago("6h", now) == datetime(2026, 7, 29, 6, 0, tzinfo=timezone.utc)
        assert parse_time_ago("30d", now) == datetime(2026, 6, 29, 12, 0, tzinfo=timezone.utc)
        assert parse_time_ago("2w", now) == datetime(2026, 7, 15, 12, 0, tzinfo=timezone.utc)
        assert parse_time_ago("1m", now) == datetime(2026, 6, 29, 12, 0, tzinfo=timezone.utc)
        assert parse_time_ago("1y", now) == datetime(2025, 7, 29, 12, 0, tzinfo=timezone.utc)
        assert parse_time_ago("") is None

    def test_time_ago_invalid(self):
        with pytest.raises(ValueError):
            parse_time_ago("abc")
        with pytest.raises(ValueError):
            parse_time_ago("10x")

    def test_date_between(self):
        lo, hi = parse_date_between("2025-01-01,2025-06-30")
        assert lo == datetime(2025, 1, 1, tzinfo=timezone.utc)
        assert hi == datetime(2025, 6, 30, tzinfo=timezone.utc)
        with pytest.raises(ValueError, match="before max"):
            parse_date_between("2025-06-30,2025-01-01")
        with pytest.raises(ValueError, match="format"):
            parse_date_between("2025-01-01")

    def test_duration(self):
        assert parse_duration("2h45m") == 2 * 3600 + 45 * 60
        assert parse_duration("90s") == 90
        assert parse_duration("500ms") == 0.5
        with pytest.raises(ValueError):
            parse_duration("nope")


class TestDistributedConfig:
    def test_defaults_match_reference(self):
        # config/distributed.go:54-79
        c = DistributedConfig()
        assert c.heartbeat_interval_s == 30
        assert c.work_timeout_s == 600
        assert c.worker_timeout_s == 180
        assert c.retry_attempts == 3
        assert c.work_distribution_interval_s == 5
        assert c.bus.work_queue_topic == "crawl-work-queue"
        assert c.bus.results_topic == "crawl-results"
        assert c.bus.worker_status_topic == "worker-status"
        assert c.bus.orchestrator_topic == "orchestrator-commands"
        c.validate()

    def test_worker_mode_requires_id(self):
        c = DistributedConfig(mode="worker")
        with pytest.raises(ValueError, match="worker_id"):
            c.validate()
        c.worker_id = "w1"
        c.validate()

    def test_invalid_mode(self):
        with pytest.raises(ValueError, match="invalid mode"):
            DistributedConfig(mode="bogus").validate()

    def test_numeric_validation(self):
        with pytest.raises(ValueError):
            DistributedConfig(max_workers_per_node=0).validate()
        with pytest.raises(ValueError):
            DistributedConfig(heartbeat_interval_s=0).validate()


class TestInferenceYamlKeys:
    def test_bucket_sizes_and_pretrained_from_yaml(self, tmp_path):
        """inference.* yaml keys reach the resolved config (they drive the
        engine wiring in all three inference-bearing modes)."""
        import yaml

        from distributed_crawler_tpu.cli import build_parser, resolve_config

        path = tmp_path / "config.yaml"
        with open(path, "w") as f:
            yaml.safe_dump({"inference": {
                "bucket_sizes": [32, 64],
                "pretrained_dir": "/models/e5",
                "asr_pretrained_dir": "/models/whisper"}}, f)
        args = build_parser().parse_args(["--config", str(path),
                                          "--urls", "chan"])
        cfg, _ = resolve_config(args, env={})
        assert cfg.inference.bucket_sizes == [32, 64]
        assert cfg.inference.pretrained_dir == "/models/e5"
        assert cfg.inference.asr_pretrained_dir == "/models/whisper"
