"""Classifier-loop e2e (VERDICT r2 weak #3): crawl JSONL + labels →
head fine-tune on the frozen encoder → orbax checkpoint → engine reload
that beats random accuracy.  BASELINE config #3's missing closing move.
"""

import json

import numpy as np
import pytest

from distributed_crawler_tpu.inference.engine import (
    EngineConfig,
    InferenceEngine,
)
from distributed_crawler_tpu.models.train import (
    TrainConfig,
    encode_cls_features,
    finetune_head,
)
from distributed_crawler_tpu.utils.metrics import MetricsRegistry

# Two token-disjoint "languages" a frozen random encoder still separates.
CLASS_WORDS = (["alpha", "beta", "gamma", "delta"],
               ["omega", "sigma", "kappa", "zeta"])


def _dataset(n_per_class=25, seed=0):
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for label, words in enumerate(CLASS_WORDS):
        for _ in range(n_per_class):
            texts.append(" ".join(rng.choice(words, size=6)))
            labels.append(label)
    order = rng.permutation(len(texts))
    return [texts[i] for i in order], [labels[i] for i in order]


def _tiny_engine(n_labels=2, **kw):
    return InferenceEngine(
        EngineConfig(model="tiny", n_labels=n_labels, batch_size=8,
                     buckets=(16,), **kw),
        registry=MetricsRegistry())


class TestFinetuneHead:
    def test_loss_drops_and_beats_random(self):
        engine = _tiny_engine()
        texts, labels = _dataset()
        toks = engine.tokenizer.encode_batch(texts)
        params, history = finetune_head(
            engine.ecfg, engine.params, toks, labels,
            tc=TrainConfig(learning_rate=5e-3, warmup_steps=5),
            epochs=15, batch_size=16)
        assert history[-1]["loss"] < history[0]["loss"] * 0.8
        # Swap the trained head in and classify a held-out set.
        engine.params = params
        held_texts, held_labels = _dataset(n_per_class=10, seed=7)
        out = engine.run(held_texts)
        acc = np.mean([r["label"] == y for r, y in zip(out, held_labels)])
        assert acc >= 0.8, f"held-out accuracy {acc} not above random"

    def test_frozen_encoder_untouched(self):
        engine = _tiny_engine()
        texts, labels = _dataset(n_per_class=5)
        toks = engine.tokenizer.encode_batch(texts)
        params, _ = finetune_head(engine.ecfg, engine.params, toks, labels,
                                  epochs=2, batch_size=8)
        before = engine.params["params"]["encoder"]
        after = params["params"]["encoder"]
        leaves_b = [np.asarray(x) for x in
                    __import__("jax").tree_util.tree_leaves(before)]
        leaves_a = [np.asarray(x) for x in
                    __import__("jax").tree_util.tree_leaves(after)]
        assert all(np.array_equal(a, b)
                   for a, b in zip(leaves_a, leaves_b))

    def test_feature_parity_with_fused_model(self):
        """Features used for training are the exact CLS states the fused
        inference model feeds its head — same encoder, same slice."""
        engine = _tiny_engine()
        toks = engine.tokenizer.encode_batch(["hello", "world wide"])
        feats = encode_cls_features(engine.ecfg, engine.params, toks,
                                    batch_size=2)
        assert feats.shape == (2, engine.ecfg.hidden)
        assert np.isfinite(feats).all()

    def test_label_overflow_rejected(self):
        engine = _tiny_engine()
        toks = engine.tokenizer.encode_batch(["a", "b"])
        with pytest.raises(ValueError, match="exceeds head width"):
            finetune_head(engine.ecfg, engine.params, toks, [0, 5])


class TestCheckpointReload:
    def test_checkpoint_roundtrip_through_engine(self, tmp_path):
        from distributed_crawler_tpu.inference.checkpoint import save_params

        engine = _tiny_engine()
        texts, labels = _dataset()
        toks = engine.tokenizer.encode_batch(texts)
        params, _ = finetune_head(
            engine.ecfg, engine.params, toks, labels,
            tc=TrainConfig(learning_rate=5e-3, warmup_steps=5),
            epochs=15, batch_size=16)
        root = str(tmp_path / "ckpt")
        save_params(root + "/step_15", params)
        with open(tmp_path / "ckpt" / "labels.json", "w") as f:
            json.dump({"labels": ["benign", "spam"]}, f)

        # Fresh engine restores the fine-tuned head from the latest step.
        # NOTE: constructed with the DEFAULT n_labels=8 — the checkpoint's
        # own 2-wide head must win (the tpu-worker reload path has no
        # n_labels flag).
        eng2 = _tiny_engine(n_labels=8, checkpoint_dir=root)
        assert eng2.ecfg.n_labels == 2
        assert eng2.label_names == ["benign", "spam"]
        held_texts, held_labels = _dataset(n_per_class=10, seed=7)
        out = eng2.run(held_texts)
        acc = np.mean([r["label"] == y for r, y in zip(out, held_labels)])
        assert acc >= 0.8
        assert out[0]["label_name"] in ("benign", "spam")


class TestTrainHeadCli:
    def test_cli_end_to_end(self, tmp_path, capsys):
        """dct --mode train-head over a crawl JSONL produces a checkpoint
        the engine reloads to beat random accuracy."""
        from distributed_crawler_tpu.cli import main

        texts, labels = _dataset()
        posts = tmp_path / "posts.jsonl"
        with open(posts, "w", encoding="utf-8") as f:
            for i, text in enumerate(texts):
                f.write(json.dumps({"post_uid": f"p{i}", "all_text": text})
                        + "\n")
        labels_file = tmp_path / "labels.jsonl"
        with open(labels_file, "w", encoding="utf-8") as f:
            for i, y in enumerate(labels):
                f.write(json.dumps({
                    "post_uid": f"p{i}",
                    "label": ["benign", "spam"][y]}) + "\n")
        ckpt = str(tmp_path / "ckpt")

        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", ckpt,
                   "--train-epochs", "15", "--train-lr", "5e-3",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["trained_examples"] == 50
        assert summary["n_labels"] == 2
        assert summary["final_loss"] < 1.0

        eng = _tiny_engine(n_labels=8, checkpoint_dir=ckpt)
        assert eng.ecfg.n_labels == 2  # checkpoint head width wins
        assert eng.label_names == ["benign", "spam"]
        held_texts, held_labels = _dataset(n_per_class=10, seed=7)
        out = eng.run(held_texts)
        acc = np.mean([r["label"] == y for r, y in zip(out, held_labels)])
        assert acc >= 0.8, f"reloaded engine accuracy {acc}"

    def test_param_dtype_config_never_degrades_checkpoint(self, tmp_path,
                                                          capsys):
        """A config that serves bf16 (`--infer-param-dtype bfloat16`) must
        NOT make train-head fine-tune on — or persist — bf16-cast weights:
        the saved checkpoint stays f32."""
        import jax
        import jax.numpy as jnp

        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.inference.checkpoint import (
            latest_step_dir,
            load_params,
        )

        texts, labels = _dataset()
        posts = tmp_path / "posts.jsonl"
        with open(posts, "w", encoding="utf-8") as f:
            for i, text in enumerate(texts):
                f.write(json.dumps({"post_uid": f"p{i}", "all_text": text})
                        + "\n")
        labels_file = tmp_path / "labels.jsonl"
        with open(labels_file, "w", encoding="utf-8") as f:
            for i, y in enumerate(labels):
                f.write(json.dumps({"post_uid": f"p{i}", "label": int(y)})
                        + "\n")
        ckpt = str(tmp_path / "ckpt")
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--infer-param-dtype", "bfloat16",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", ckpt,
                   "--train-epochs", "2",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        saved = load_params(latest_step_dir(ckpt) or ckpt)
        dtypes = {leaf.dtype for leaf in jax.tree.leaves(saved)
                  if hasattr(leaf, "dtype")}
        assert jnp.bfloat16 not in dtypes, dtypes

    def test_mixed_label_kinds_rejected(self, tmp_path, capsys):
        from distributed_crawler_tpu.cli import main

        posts = tmp_path / "posts.jsonl"
        with open(posts, "w") as f:
            for i in range(4):
                f.write(json.dumps({"post_uid": f"p{i}",
                                    "all_text": "t"}) + "\n")
        labels_file = tmp_path / "labels.jsonl"
        with open(labels_file, "w") as f:
            for i in range(3):
                f.write(json.dumps({"post_uid": f"p{i}",
                                    "label": i}) + "\n")
            f.write(json.dumps({"post_uid": "p3", "label": "spam"}) + "\n")
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", str(tmp_path / "ckpt"),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2
        assert "mixes string and integer" in capsys.readouterr().err

    def test_zero_epochs_rejected_cleanly(self, tmp_path, capsys):
        from distributed_crawler_tpu.cli import main

        posts = tmp_path / "posts.jsonl"
        labels_file = tmp_path / "labels.jsonl"
        with open(posts, "w") as f, open(labels_file, "w") as g:
            for i in range(4):
                f.write(json.dumps({"post_uid": f"p{i}",
                                    "all_text": "t"}) + "\n")
                g.write(json.dumps({"post_uid": f"p{i}",
                                    "label": i % 2}) + "\n")
        ckpt = tmp_path / "ckpt"
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", str(ckpt),
                   "--train-epochs", "0",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2
        assert "train-epochs" in capsys.readouterr().err
        assert not ckpt.exists()  # no garbage checkpoint written

    def test_retrain_advances_step(self, tmp_path, capsys):
        """Retraining into the same dir always serves the NEW head, even
        with a smaller epoch count (monotonic step numbering)."""
        from distributed_crawler_tpu.cli import main

        texts, labels = _dataset(n_per_class=8)
        posts = tmp_path / "posts.jsonl"
        labels_file = tmp_path / "labels.jsonl"
        with open(posts, "w") as f, open(labels_file, "w") as g:
            for i, (t, y) in enumerate(zip(texts, labels)):
                f.write(json.dumps({"post_uid": f"p{i}",
                                    "all_text": t}) + "\n")
                g.write(json.dumps({"post_uid": f"p{i}", "label": y}) + "\n")
        ckpt = str(tmp_path / "ckpt")
        base = ["--mode", "train-head", "--infer-model", "tiny",
                "--train-posts", str(posts), "--train-labels",
                str(labels_file), "--head-checkpoint", ckpt,
                "--storage-root", str(tmp_path / "store")]
        assert main(base + ["--train-epochs", "5"]) == 0
        assert main(base + ["--train-epochs", "2"]) == 0  # fewer epochs
        out = [json.loads(line) for line in
               capsys.readouterr().out.strip().splitlines()
               if line.startswith("{")]
        assert out[-2]["checkpoint"].endswith("step_1")
        assert out[-1]["checkpoint"].endswith("step_2")
        from distributed_crawler_tpu.inference.checkpoint import (
            latest_step_dir,
        )
        assert latest_step_dir(ckpt).endswith("step_2")


class TestLegacyCheckpointMigration:
    def test_split_qkv_checkpoint_loads_into_fused_engine(self, tmp_path):
        """Checkpoints written by the pre-fusion encoder (separate attn
        q/k/v trees) still restore: the engine fuses them on load."""
        import jax
        import numpy as np

        from distributed_crawler_tpu.inference.checkpoint import save_params

        engine = _tiny_engine()
        # Rewrite the modern params into the LEGACY split layout.
        params = jax.tree_util.tree_map(np.asarray, engine.params)
        for name, layer in params["params"]["encoder"].items():
            if not name.startswith("layers_"):
                continue
            attn = layer["attn"]
            fused_k = attn.pop("qkv/kernel")
            fused_b = attn.pop("qkv/bias")
            for i, proj in enumerate(("q", "k", "v")):
                attn[proj] = {"kernel": fused_k[:, i, :],
                              "bias": fused_b[i]}
        root = str(tmp_path / "legacy")
        save_params(root + "/step_1", params)

        eng2 = _tiny_engine(checkpoint_dir=root)
        out_new = eng2.run(["hello world"])
        out_ref = engine.run(["hello world"])
        assert np.allclose(out_new[0]["scores"], out_ref[0]["scores"],
                           atol=1e-5)


class TestGradAccumulation:
    """grad_accum_steps: lax.scan microbatching with ONE optimizer update —
    the effective-batch lever for batches beyond a chip's activation
    memory.  Must be numerically equivalent to the unaccumulated step."""

    def _setup(self, accum, batch=8, seed=0):
        import jax
        import jax.numpy as jnp

        from distributed_crawler_tpu.models.encoder import TINY_TEST
        from distributed_crawler_tpu.models.train import make_train_step
        from dataclasses import replace

        cfg = replace(TINY_TEST, n_labels=2, dtype="float32")
        init_fn, step_fn, _ = make_train_step(
            cfg, TrainConfig(warmup_steps=1, grad_accum_steps=accum))
        rng = np.random.default_rng(seed)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, 16)),
                          jnp.int32)
        mask = jnp.ones((batch, 16), jnp.bool_)
        labels = jnp.asarray(rng.integers(0, 2, (batch,)), jnp.int32)
        params, opt_state = init_fn(jax.random.PRNGKey(seed), ids, mask)
        return jax.jit(step_fn), params, opt_state, ids, mask, labels

    def test_equivalent_to_unaccumulated(self):
        import jax

        step1, p1, o1, ids, mask, labels = self._setup(accum=1)
        step4, p4, o4, *_ = self._setup(accum=4)
        n1, _, m1 = step1(p1, o1, ids, mask, labels)
        n4, _, m4 = step4(p4, o4, ids, mask, labels)
        assert np.isclose(float(m1["loss"]), float(m4["loss"]), atol=1e-5)
        assert np.isclose(float(m1["accuracy"]), float(m4["accuracy"]),
                          atol=1e-6)
        leaves1 = jax.tree_util.tree_leaves(n1)
        leaves4 = jax.tree_util.tree_leaves(n4)
        for a, b in zip(leaves1, leaves4):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-4)

    def test_indivisible_batch_rejected(self):
        import jax

        step, p, o, ids, mask, labels = self._setup(accum=3, batch=8)
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(lambda *a: step(*a))(p, o, ids, mask, labels)

    def test_compiles_sharded_over_dp_mesh(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_crawler_tpu.parallel import (
            best_mesh_config, make_mesh, shard_batch, shard_params,
        )

        step, params, opt_state, ids, mask, labels = self._setup(
            accum=2, batch=16)
        mesh = make_mesh(best_mesh_config(8))
        params = shard_params(params, mesh)
        placed = shard_batch({"ids": ids, "mask": mask}, mesh)
        labels = jax.device_put(
            labels, NamedSharding(mesh, P("dp")))
        _, _, metrics = step(params, opt_state, placed["ids"],
                             placed["mask"], labels)
        assert np.isfinite(float(metrics["loss"]))


class TestFullFinetune:
    """--train-scope full: every encoder weight moves through
    make_train_step (the make_train_step path was previously reachable
    only from the dryrun/tests — now it is a product feature)."""

    def test_library_loss_drops_and_beats_random(self):
        from distributed_crawler_tpu.models.train import (
            TrainConfig,
            finetune_full,
        )

        eng = _tiny_engine(n_labels=2)
        texts, labels = _dataset()
        toks = eng.tokenizer.encode_batch(texts)
        params, history = finetune_full(
            eng.ecfg, eng.params, toks, labels,
            tc=TrainConfig(learning_rate=5e-4, warmup_steps=5),
            epochs=8, batch_size=8)
        assert history[-1]["loss"] < history[0]["loss"]
        assert history[-1]["accuracy"] >= 0.8
        # Engine-ready tree: same structure as the input params.
        import jax

        assert (jax.tree_util.tree_structure(params) ==
                jax.tree_util.tree_structure(eng.params))

    def test_cli_full_scope_with_grad_accum(self, tmp_path, capsys):
        from distributed_crawler_tpu.cli import main

        texts, labels = _dataset()
        posts = tmp_path / "posts.jsonl"
        with open(posts, "w", encoding="utf-8") as f:
            for i, text in enumerate(texts):
                f.write(json.dumps({"post_uid": f"p{i}", "all_text": text})
                        + "\n")
        labels_file = tmp_path / "labels.jsonl"
        with open(labels_file, "w", encoding="utf-8") as f:
            for i, y in enumerate(labels):
                f.write(json.dumps({"post_uid": f"p{i}",
                                    "label": ["benign", "spam"][y]}) + "\n")
        ckpt = str(tmp_path / "ckpt")
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", ckpt,
                   "--train-scope", "full", "--train-grad-accum", "2",
                   "--train-epochs", "8", "--train-lr", "5e-4",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["final_loss"] < 1.0
        eng = _tiny_engine(n_labels=8, checkpoint_dir=ckpt)
        assert eng.label_names == ["benign", "spam"]
        held_texts, held_labels = _dataset(n_per_class=10, seed=7)
        out = eng.run(held_texts)
        acc = np.mean([r["label"] == y
                       for r, y in zip(out, held_labels)])
        assert acc >= 0.8, f"reloaded engine accuracy {acc}"

    def test_scope_conflicts_rejected(self, tmp_path, capsys):
        from distributed_crawler_tpu.cli import main

        posts = tmp_path / "posts.jsonl"
        posts.write_text(json.dumps(
            {"post_uid": "p0", "all_text": "alpha beta"}) + "\n")
        labels_file = tmp_path / "labels.jsonl"
        labels_file.write_text(json.dumps(
            {"post_uid": "p0", "label": 0}) + "\n")
        base = ["--mode", "train-head", "--infer-model", "tiny",
                "--train-posts", str(posts),
                "--train-labels", str(labels_file),
                "--head-checkpoint", str(tmp_path / "ckpt"),
                "--storage-root", str(tmp_path / "store")]
        assert main(base + ["--train-scope", "lora"]) == 2
        assert main(base + ["--train-scope", "full",
                            "--train-lora-rank", "4"]) == 2
        assert main(base + ["--train-grad-accum", "0"]) == 2
        # Accumulation outside scope=full is an error, not a silent no-op.
        assert main(base + ["--train-grad-accum", "2"]) == 2


class TestFullFinetuneResume:
    """state_dir checkpoint/resume: a run killed mid-way and restarted
    must reproduce the uninterrupted run exactly (per-epoch rng seeding
    keeps batch order identical)."""

    def test_resume_matches_uninterrupted(self, tmp_path):
        import jax

        from distributed_crawler_tpu.models.train import (
            TrainConfig,
            finetune_full,
        )

        eng = _tiny_engine(n_labels=2)
        texts, labels = _dataset(n_per_class=12)
        toks = eng.tokenizer.encode_batch(texts)
        tc = TrainConfig(learning_rate=5e-4, warmup_steps=3)

        # One-shot reference: 4 epochs, no state dir.
        ref_params, ref_hist = finetune_full(
            eng.ecfg, eng.params, toks, labels, tc=tc,
            epochs=4, batch_size=8)

        # Interrupted run: 2 epochs checkpointed, then "restart" asking
        # for 4 — must resume at epoch 2, not retrain from scratch.
        sd = str(tmp_path / "state")
        finetune_full(eng.ecfg, eng.params, toks, labels, tc=tc,
                      epochs=2, batch_size=8, state_dir=sd)
        resumed_params, resumed_hist = finetune_full(
            eng.ecfg, eng.params, toks, labels, tc=tc,
            epochs=4, batch_size=8, state_dir=sd)

        assert len(resumed_hist) == 4
        for a, b in zip(ref_hist, resumed_hist):
            assert np.isclose(a["loss"], b["loss"], atol=1e-6), (a, b)
        for x, y in zip(jax.tree_util.tree_leaves(ref_params),
                        jax.tree_util.tree_leaves(resumed_params)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=1e-6, rtol=1e-5)

    def test_completed_run_is_a_noop_on_restart(self, tmp_path):
        from distributed_crawler_tpu.models.train import (
            TrainConfig,
            finetune_full,
        )

        eng = _tiny_engine(n_labels=2)
        texts, labels = _dataset(n_per_class=8)
        toks = eng.tokenizer.encode_batch(texts)
        sd = str(tmp_path / "state")
        tc = TrainConfig(learning_rate=5e-4, warmup_steps=3)
        _, h1 = finetune_full(eng.ecfg, eng.params, toks, labels, tc=tc,
                              epochs=2, batch_size=8, state_dir=sd)
        _, h2 = finetune_full(eng.ecfg, eng.params, toks, labels, tc=tc,
                              epochs=2, batch_size=8, state_dir=sd)
        assert h2 == h1  # restored history, zero additional epochs

    def test_incomplete_checkpoint_skipped_and_pruning(self, tmp_path):
        """A crash between the orbax commit and the completion marker must
        not wedge resume: the incomplete dir is skipped in favor of the
        previous complete epoch.  Also: older complete epochs are pruned
        (only the newest is ever read)."""
        import os

        from distributed_crawler_tpu.inference.checkpoint import (
            latest_train_state,
        )
        from distributed_crawler_tpu.models.train import (
            TrainConfig,
            finetune_full,
        )

        eng = _tiny_engine(n_labels=2)
        texts, labels = _dataset(n_per_class=8)
        toks = eng.tokenizer.encode_batch(texts)
        sd = str(tmp_path / "state")
        finetune_full(eng.ecfg, eng.params, toks, labels,
                      tc=TrainConfig(learning_rate=5e-4, warmup_steps=3),
                      epochs=2, batch_size=8, state_dir=sd)
        # Pruning: only the newest epoch dir remains.
        assert sorted(d for d in os.listdir(sd)
                      if d.startswith("epoch_")) == ["epoch_1"]
        # Emulate a crash: epoch_5 exists but has no completion marker.
        os.makedirs(os.path.join(sd, "epoch_5"))
        assert latest_train_state(sd).endswith("epoch_1")
        # Asking for fewer epochs than are already done is an error, not
        # a silent longer-trained model.
        with pytest.raises(ValueError, match="completed epochs"):
            finetune_full(eng.ecfg, eng.params, toks, labels,
                          tc=TrainConfig(learning_rate=5e-4,
                                         warmup_steps=3),
                          epochs=1, batch_size=8, state_dir=sd)

    def test_restore_into_sharded_training(self, tmp_path):
        """Elastic-topology restart: a train state saved from unsharded
        single-process training restores into dp-sharded training on the
        8-device mesh (orbax handles the relayout) and the step runs."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_crawler_tpu.inference.checkpoint import (
            latest_train_state,
            load_train_state,
        )
        from distributed_crawler_tpu.models.encoder import TINY_TEST
        from distributed_crawler_tpu.models.train import (
            TrainConfig,
            finetune_full,
            make_train_step,
        )
        from distributed_crawler_tpu.parallel import (
            best_mesh_config, make_mesh, shard_batch, shard_params,
        )
        from dataclasses import replace

        eng = _tiny_engine(n_labels=2)
        texts, labels = _dataset(n_per_class=8)
        toks = eng.tokenizer.encode_batch(texts)
        sd = str(tmp_path / "state")
        tc = TrainConfig(learning_rate=5e-4, warmup_steps=3)
        finetune_full(eng.ecfg, eng.params, toks, labels, tc=tc,
                      epochs=1, batch_size=8, state_dir=sd)

        cfg = replace(TINY_TEST, n_labels=2)
        init_fn, step_fn, optimizer = make_train_step(cfg, tc)
        batch = 16
        ids = jnp.zeros((batch, 16), jnp.int32)
        mask = jnp.ones((batch, 16), jnp.bool_)
        lab = jnp.asarray(np.arange(batch) % 2, jnp.int32)
        params, opt_state = init_fn(jax.random.PRNGKey(0), ids, mask)
        _, params, opt_state, _hist = load_train_state(
            latest_train_state(sd), params, opt_state)

        mesh = make_mesh(best_mesh_config(8))
        params = shard_params(params, mesh)
        # Optimizer moments follow the params' mesh; replicating is the
        # simplest valid layout for the tiny test (XLA reshards in-step).
        opt_state = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())),
            opt_state)
        placed = shard_batch({"ids": ids, "mask": mask}, mesh)
        lab = jax.device_put(lab, NamedSharding(mesh, P("dp")))
        _, _, metrics = jax.jit(step_fn)(
            params, opt_state, placed["ids"], placed["mask"], lab)
        assert np.isfinite(float(metrics["loss"]))

    def test_cli_state_dir_requires_full_scope(self, tmp_path):
        from distributed_crawler_tpu.cli import main

        posts = tmp_path / "posts.jsonl"
        posts.write_text(json.dumps(
            {"post_uid": "p0", "all_text": "alpha beta"}) + "\n")
        labels_file = tmp_path / "labels.jsonl"
        labels_file.write_text(json.dumps(
            {"post_uid": "p0", "label": 0}) + "\n")
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", str(tmp_path / "ckpt"),
                   "--train-state-dir", str(tmp_path / "state"),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2
