"""Tenant attribution tests (ISSUE 17): the provenance label's journey
through the pipeline — loadgen stamping, bus round-trips (legacy
unlabeled frames included), per-tenant SLO breach children that never
clobber the aggregate, the cost ledger's proportional split and
conservation, the watchtower's error-budget ledger (reset-aware burn,
exhaustion projection), the /tenants + /logs HTTP surfaces, the gate's
tenant key validation, and the tenant-mix-steady scenario acceptance
(docs/operations.md "Tenant attribution & error budgets")."""

import json
import logging
import urllib.error
import urllib.request

import pytest

from distributed_crawler_tpu.bus import decode_message
from distributed_crawler_tpu.bus.codec import RecordBatch
from distributed_crawler_tpu.bus.messages import (
    DEFAULT_TENANT,
    AudioBatchMessage,
    normalize_tenant,
)
from distributed_crawler_tpu.datamodel.post import Post
from distributed_crawler_tpu.loadgen.gate import (
    _breach_counts,
    _tenant_breach_counts,
    load_scenario,
    run_scenario,
    validate_gate_config,
)
from distributed_crawler_tpu.loadgen.generator import (
    LoadGenConfig,
    SyntheticWorkload,
)
from distributed_crawler_tpu.orchestrator.tenants import (
    TenantBudgetLedger,
    budgets_from_config,
)
from distributed_crawler_tpu.utils import structlog, trace
from distributed_crawler_tpu.utils.costmodel import TenantLedger
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    clear_tenants_provider,
    serve_metrics,
    set_tenants_provider,
)
from distributed_crawler_tpu.utils.slo import SLOWatchdog, standard_slos
from distributed_crawler_tpu.utils.timeseries import TimeSeriesStore

MIX = {"interactive": 0.6, "bulk-reembed": 0.4}


def get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read()


# ---------------------------------------------------------------------------
# propagation: loadgen stamping + bus round-trips
# ---------------------------------------------------------------------------
class TestTenantPropagation:
    def test_plan_draws_tenants_deterministically_from_mix(self):
        cfg = lambda: LoadGenConfig(seed=17, duration_s=4.0,
                                    rate_batches_per_s=12, tenants=dict(MIX))
        a = SyntheticWorkload(cfg()).plan()
        b = SyntheticWorkload(cfg()).plan()
        assert [pb.tenant for pb in a] == [pb.tenant for pb in b]
        drawn = {pb.tenant for pb in a}
        assert drawn == set(MIX)  # both tenants present in ~48 draws
        # Roughly the configured split (seeded draw, loose bounds).
        share = sum(pb.tenant == "interactive" for pb in a) / len(a)
        assert 0.35 <= share <= 0.85

    def test_no_mix_means_default_tenant(self):
        wl = SyntheticWorkload(LoadGenConfig(seed=1, duration_s=1.0))
        assert all(pb.tenant == DEFAULT_TENANT for pb in wl.plan())
        assert wl.tenant_for(0) == DEFAULT_TENANT

    def test_build_batch_stamps_tenant_onto_record_batch(self):
        wl = SyntheticWorkload(LoadGenConfig(
            seed=17, duration_s=2.0, tenants=dict(MIX)))
        pb = wl.plan()[0]
        rb = wl.build_batch(pb)
        assert rb.tenant == pb.tenant
        # Survives a bus round-trip (the wire dict carries the label).
        assert RecordBatch.from_dict(rb.to_dict()).tenant == pb.tenant

    def test_tail_batches_draw_the_same_tenant_by_index(self):
        """The gate's tail batches are planned with tenant="" — the
        deterministic by-index draw must attribute them anyway, or the
        recovery tail would show up as unattributed spend."""
        wl = SyntheticWorkload(LoadGenConfig(
            seed=17, duration_s=1.0, tenants=dict(MIX)))
        assert wl.tenant_for(10_000) in MIX
        assert wl.tenant_for(10_000) == wl.tenant_for(10_000)

    def test_legacy_unlabeled_frames_decode_to_default(self):
        rb = RecordBatch.from_posts(
            [Post(post_uid="p0", channel_name="c", description="text")],
            crawl_id="c1", tenant="interactive")
        legacy = rb.to_dict()
        legacy.pop("tenant")
        assert RecordBatch.from_dict(legacy).tenant == DEFAULT_TENANT
        msg = AudioBatchMessage.new([], crawl_id="c1", tenant="interactive")
        wire = json.loads(json.dumps(msg.to_dict()))
        wire.pop("tenant")
        assert decode_message(wire).tenant == DEFAULT_TENANT
        assert normalize_tenant("") == DEFAULT_TENANT
        assert normalize_tenant(None) == DEFAULT_TENANT


# ---------------------------------------------------------------------------
# SLO: per-tenant breach children next to (never instead of) the parent
# ---------------------------------------------------------------------------
class TestSLOTenantChildren:
    def _dog(self, slos):
        tracer = trace.Tracer(capacity=256)
        reg = MetricsRegistry()
        return SLOWatchdog(slos, tracer=tracer, registry=reg), tracer, reg

    def test_children_and_parent_coexist_on_one_counter_family(self):
        dog, tracer, reg = self._dog(standard_slos(batch_p95_ms=100.0))
        for i in range(3):
            tracer.record("tpu_worker.process", 0.5, trace_id=f"t{i}",
                          tenant="interactive")
        breaches = dog.evaluate(now=__import__("time").time() + 1)
        assert len(breaches) == 1  # the aggregate breached too
        text = reg.expose()
        assert 'slo_breach_total{slo="batch_p95"} 1' in text
        assert ('slo_breach_total{slo="batch_p95",tenant="interactive"} 1'
                in text)
        # The gate's two readers partition the family by exact label
        # set: tenant children must not leak into the parent counts.
        assert _breach_counts(reg) == {"batch_p95": 1.0}
        assert _tenant_breach_counts(reg) == {"interactive:batch_p95": 1.0}
        assert dog.snapshot()["tenant_breaches"] == {
            "interactive": {"batch_p95": 1}}

    def test_hot_tenant_breaches_while_aggregate_stays_green(self):
        """One tenant busting its own p95 must be visible even when the
        blended fleet p95 is comfortably under budget."""
        dog, tracer, reg = self._dog(standard_slos(batch_p95_ms=100.0))
        for i in range(20):
            tracer.record("tpu_worker.process", 0.001, trace_id=f"f{i}",
                          tenant="bulk-reembed")
        tracer.record("tpu_worker.process", 0.5, trace_id="slow",
                      tenant="interactive")
        breaches = dog.evaluate(now=__import__("time").time() + 1)
        assert breaches == []  # blended p95 is ~1ms
        assert _breach_counts(reg) == {}
        assert _tenant_breach_counts(reg) == {"interactive:batch_p95": 1.0}

    def test_spans_without_tenant_attr_stay_aggregate_only(self):
        dog, tracer, reg = self._dog(standard_slos(batch_p95_ms=100.0))
        tracer.record("tpu_worker.process", 0.5, trace_id="t0")
        assert len(dog.evaluate(now=__import__("time").time() + 1)) == 1
        assert _breach_counts(reg) == {"batch_p95": 1.0}
        assert _tenant_breach_counts(reg) == {}


# ---------------------------------------------------------------------------
# costmodel: proportional charge + conservation
# ---------------------------------------------------------------------------
class TestTenantLedgerCost:
    def test_charge_splits_proportionally_and_conserves(self):
        ledger = TenantLedger(MetricsRegistry())
        ledger.charge({"interactive": 3.0, "bulk-reembed": 1.0},
                      duration_s=2.0, flops=4e9, real_tokens=400)
        snap = ledger.snapshot()
        rows = {r["tenant"]: r for r in snap["rows"]}
        assert rows["interactive"]["chip_seconds"] == pytest.approx(1.5)
        assert rows["bulk-reembed"]["chip_seconds"] == pytest.approx(0.5)
        assert rows["interactive"]["share"] == pytest.approx(0.75)
        # Conservation: per-tenant rows sum back to the totals (what the
        # gate's require_tenant_conservation asserts over /costs).
        for key in ("chip_seconds", "flops", "real_tokens", "batches"):
            assert sum(r[key] for r in snap["rows"]) == \
                pytest.approx(snap["totals"][key], rel=1e-6)

    def test_unweighted_dispatch_charges_nothing(self):
        """Warmup batches predate any tenant — they must not surface as
        unattributed spend (max_unattributed_share: 0 relies on this)."""
        ledger = TenantLedger(MetricsRegistry())
        ledger.charge({}, duration_s=1.0, flops=1e9, real_tokens=10)
        ledger.charge({"interactive": 0.0}, duration_s=1.0, flops=1e9,
                      real_tokens=10)
        snap = ledger.snapshot()
        assert snap["rows"] == []
        assert snap["totals"]["chip_seconds"] == 0.0

    def test_wait_only_tenant_still_gets_a_row(self):
        ledger = TenantLedger(MetricsRegistry())
        for w in (0.01, 0.02, 0.03):
            ledger.observe_queue_wait("interactive", w)
        rows = ledger.snapshot()["rows"]
        assert rows[0]["tenant"] == "interactive"
        assert rows[0]["chip_seconds"] == 0.0
        assert rows[0]["queue_wait_p95_s"] == pytest.approx(0.03)
        assert rows[0]["queue_wait_samples"] == 3


# ---------------------------------------------------------------------------
# watchtower: the error-budget ledger
# ---------------------------------------------------------------------------
class TestBudgetLedger:
    def test_budgets_from_config_accepts_and_defaults(self):
        budgets, window = budgets_from_config(None)
        assert budgets == {} and window == 300.0
        budgets, window = budgets_from_config({
            "window_s": 60,
            "budgets": {"interactive": {"queue_wait": 5, "batch_p95": 2}}})
        assert window == 60.0
        assert budgets == {"interactive": {"queue_wait": 5.0,
                                           "batch_p95": 2.0}}

    def test_budgets_from_config_is_loud_on_typos(self):
        with pytest.raises(ValueError, match="mapping"):
            budgets_from_config([1, 2])
        with pytest.raises(ValueError, match="unknown tenant_budgets key"):
            budgets_from_config({"budgetz": {}})
        with pytest.raises(ValueError, match="window_s"):
            budgets_from_config({"window_s": 0})
        with pytest.raises(ValueError, match="window_s"):
            budgets_from_config({"window_s": True})
        with pytest.raises(ValueError, match="non-empty"):
            budgets_from_config({"budgets": {"interactive": {}}})
        with pytest.raises(ValueError, match="non-negative"):
            budgets_from_config(
                {"budgets": {"interactive": {"queue_wait": -1}}})
        with pytest.raises(ValueError, match="non-empty tenant"):
            budgets_from_config({"budgets": {"": {"queue_wait": 1}}})

    def _seeded_ledger(self):
        """A fresh store with two workers' spend, a counter that RESETS
        mid-window, and a steadily-rising counter for the projection."""
        store = TimeSeriesStore(clock=lambda: 1000.0)
        for worker, chips in (("tpu-1", 6.0), ("tpu-2", 2.0)):
            store.add("fleet_tenant_chip_seconds_total", chips,
                      {"worker": worker, "tenant": "interactive"},
                      wall=990.0)
        store.add("fleet_tenant_chip_seconds_total", 2.0,
                  {"worker": "tpu-1", "tenant": "bulk-reembed"}, wall=990.0)
        for worker, p95 in (("tpu-1", 0.04), ("tpu-2", 0.09)):
            store.add("fleet_tenant_queue_wait_p95_seconds", p95,
                      {"worker": worker, "tenant": "interactive"},
                      wall=990.0)
        # interactive/queue_wait: 5 -> 8 -> RESET to 2 -> 4.  Reset-aware
        # increase = 3 + 2 + 2 = 7 (the restart contributes its new
        # value, not a negative refund).  Slope over the window is
        # negative -> burn rate clamps to 0, so no exhaustion estimate.
        for wall, v in ((930.0, 5.0), (950.0, 8.0), (970.0, 2.0),
                        (990.0, 4.0)):
            store.add("fleet_tenant_slo_breach_total", v,
                      {"worker": "tpu-1", "tenant": "interactive",
                       "slo": "queue_wait"}, wall=wall)
        # bulk-reembed/batch_age rises 0 -> 5: burn 5, slope 0.1/s.
        for wall, v in ((930.0, 0.0), (950.0, 1.0), (970.0, 3.0),
                        (990.0, 5.0)):
            store.add("fleet_tenant_slo_breach_total", v,
                      {"worker": "tpu-1", "tenant": "bulk-reembed",
                       "slo": "batch_age"}, wall=wall)
        ledger = TenantBudgetLedger(store=store, clock=lambda: 1000.0)
        ledger.configure(budgets={"interactive": {"queue_wait": 10},
                                  "bulk-reembed": {"batch_age": 20}},
                         window_s=60.0)
        return ledger

    def test_view_spend_burn_and_exhaustion_math(self):
        view = self._seeded_ledger().view(now=1000.0)
        assert view["window_s"] == 60.0
        inter = view["tenants"]["interactive"]
        assert inter["spend"]["chip_seconds"] == pytest.approx(8.0)
        assert inter["spend"]["share"] == pytest.approx(0.8)
        # Worst worker's p95, not a fleet mean.
        assert inter["queue_wait_p95_s"] == pytest.approx(0.09)
        cell = inter["budgets"]["queue_wait"]
        assert cell["burned"] == pytest.approx(7.0)
        assert cell["remaining"] == pytest.approx(3.0)
        assert cell["exhausted"] is False
        assert "exhaustion_s" not in cell  # negative slope clamped to 0
        bulk = view["tenants"]["bulk-reembed"]["budgets"]["batch_age"]
        assert bulk["burned"] == pytest.approx(5.0)
        assert bulk["remaining"] == pytest.approx(15.0)
        assert bulk["burn_rate_per_s"] == pytest.approx(0.1, rel=0.05)
        assert bulk["exhaustion_s"] == pytest.approx(150.0, rel=0.05)
        assert view["unattributed_share"] == 0.0  # no default-tenant row

    def test_exhausted_budget_projects_zero(self):
        ledger = self._seeded_ledger()
        ledger.configure(budgets={"interactive": {"queue_wait": 6}})
        cell = ledger.view(now=1000.0)["tenants"]["interactive"][
            "budgets"]["queue_wait"]
        assert cell["exhausted"] is True
        assert cell["remaining"] == pytest.approx(-1.0)
        assert cell["exhaustion_s"] == 0.0

    def test_budget_only_tenant_appears_with_zero_spend(self):
        store = TimeSeriesStore(clock=lambda: 1000.0)
        ledger = TenantBudgetLedger(store=store, clock=lambda: 1000.0)
        ledger.configure(budgets={"interactive": {"queue_wait": 5}})
        view = ledger.view(now=1000.0)
        row = view["tenants"]["interactive"]
        assert row["spend"]["chip_seconds"] == 0.0
        cell = row["budgets"]["queue_wait"]
        assert cell["burned"] == 0.0 and cell["remaining"] == 5.0
        assert cell["exhausted"] is False


# ---------------------------------------------------------------------------
# HTTP: /tenants + /logs on the metrics port
# ---------------------------------------------------------------------------
class TestTenantsAndLogsEndpoints:
    def test_tenants_served_with_provider_404_without(self):
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        provider = lambda: {"tenants": {"interactive": {"spend": {}}},
                            "unattributed_share": 0.0}
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"http://127.0.0.1:{port}/tenants")
            assert e.value.code == 404
            set_tenants_provider(provider)
            try:
                status, body = get(f"http://127.0.0.1:{port}/tenants")
                assert status == 200
                assert "interactive" in json.loads(body)["tenants"]
            finally:
                clear_tenants_provider(provider)
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"http://127.0.0.1:{port}/tenants")
            assert e.value.code == 404
        finally:
            server.shutdown()

    def test_logs_served_unconditionally_with_ring_records(self):
        structlog.install_ring_handler()
        logging.getLogger("dct.tenanttest").warning(
            "tenant smoke warning %d", 17)
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        try:
            status, body = get(f"http://127.0.0.1:{port}/logs")
            assert status == 200
            records = json.loads(body)["records"]
            mine = [r for r in records
                    if r["message"] == "tenant smoke warning 17"]
            assert mine and mine[0]["level"] == "warning"
            assert mine[0]["logger"] == "dct.tenanttest"
            status, body = get(f"http://127.0.0.1:{port}/logs?limit=1")
            assert len(json.loads(body)["records"]) == 1
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# gate: tenant key validation
# ---------------------------------------------------------------------------
class TestGateTenantKeyValidation:
    def test_tenant_keys_require_a_traffic_mix(self):
        with pytest.raises(ValueError, match="load.tenants"):
            validate_gate_config({"name": "x", "gate": {
                "require_tenants": ["interactive"]}})
        with pytest.raises(ValueError, match="load.tenants"):
            validate_gate_config({"name": "x", "gate": {
                "forbid_tenant_breach": {"interactive": ["queue_wait"]}}})

    def test_unknown_tenant_names_rejected(self):
        base = {"name": "x", "load": {"tenants": dict(MIX)}}
        with pytest.raises(ValueError, match="require_tenants"):
            validate_gate_config(
                base | {"gate": {"require_tenants": ["interactivy"]}})
        with pytest.raises(ValueError, match="forbid_tenant_breach"):
            validate_gate_config(base | {"gate": {
                "forbid_tenant_breach": {"nobody": ["queue_wait"]}}})

    def test_breach_spec_shapes_rejected(self):
        base = {"name": "x", "load": {"tenants": dict(MIX)}}
        with pytest.raises(ValueError, match="require_tenant_breach"):
            validate_gate_config(base | {"gate": {
                "require_tenant_breach": ["interactive"]}})
        with pytest.raises(ValueError, match="require_tenant_breach"):
            validate_gate_config(base | {"gate": {
                "require_tenant_breach": {"interactive": []}}})

    def test_share_and_conservation_bounds(self):
        base = {"name": "x", "load": {"tenants": dict(MIX)}}
        with pytest.raises(ValueError, match="max_unattributed_share"):
            validate_gate_config(
                base | {"gate": {"max_unattributed_share": 1.5}})
        with pytest.raises(ValueError, match="max_unattributed_share"):
            validate_gate_config(
                base | {"gate": {"max_unattributed_share": True}})
        with pytest.raises(ValueError, match="require_tenant_conservation"):
            validate_gate_config(
                base | {"gate": {"require_tenant_conservation": 2.0}})

    def test_bad_tenant_mix_and_budgets_are_loud(self):
        with pytest.raises(ValueError, match="load.tenants"):
            validate_gate_config({"name": "x", "gate": {},
                                  "load": {"tenants": {"a": -1}}})
        with pytest.raises(ValueError, match="x"):
            validate_gate_config({"name": "x", "gate": {},
                                  "tenant_budgets": {"budgetz": {}}})

    def test_checked_in_tenant_scenario_validates(self):
        validate_gate_config(load_scenario("tenant-mix-steady"))


# ---------------------------------------------------------------------------
# gate: end-to-end acceptance
# ---------------------------------------------------------------------------
class TestTenantMixSteadyAcceptance:
    def test_tenant_mix_steady_scenario_passes(self):
        """ISSUE 17 acceptance: the tenant-mix-steady scenario — two
        tenants sharing one worker; bulk spend and interactive queue
        wait separately visible on /tenants, attribution conserved
        against /costs, nothing unattributed, no interactive
        queue-wait breach over the whole run."""
        verdict = run_scenario(load_scenario("tenant-mix-steady"))
        assert verdict["status"] == "pass", verdict["checks"]
        assert verdict["lost"] == 0 and verdict["duplicates"] == 0
        tenants = verdict["tenants"]
        spend = tenants["spend"]
        assert set(MIX) <= set(spend)
        for t in MIX:
            assert spend[t]["chip_seconds"] > 0
        shares = {t: spend[t]["share"] for t in MIX}
        assert sum(shares.values()) == pytest.approx(1.0, abs=1e-6)
        assert shares["interactive"] > shares["bulk-reembed"]
        assert tenants["unattributed_share"] == 0.0
        assert tenants["run_breaches"].get("interactive:queue_wait", 0) == 0
        for name in ("tenant_conservation", "unattributed_share",
                     "tenant_visible_interactive",
                     "tenant_visible_bulk-reembed",
                     "tenant_no_breach_interactive_queue_wait",
                     "endpoint_tenants"):
            assert verdict["checks"][name]["ok"], verdict["checks"][name]
