"""Watchtower tests: time-series store, alert engine, fleet wiring.

Covers the PR-12 observability layer end to end: the rolling
`TimeSeriesStore` (bounded rings, aligned downsampling, counter-reset-
aware increase, least-squares slope), the shared exposition parser
(`loadgen/exposition.py`) and the registry self-sampler built on it, the
declarative `AlertEngine` lifecycles (threshold/trend/burn-rate;
pending→firing→resolved with flap suppression), `AlertMessage` bus
round-trips, the orchestrator's `Watchtower` fold + `/alerts` +
`/timeseries` over real HTTP, the FleetView staleness-at-read fix, the
`tools/watch.py` dashboard against a live stack, and the postmortem
bundle's embedded alert log + series.
"""

import json
import threading
import time
import urllib.request
from datetime import timedelta

import pytest

from distributed_crawler_tpu.bus.codec import decode_message
from distributed_crawler_tpu.bus.messages import (
    MSG_HEARTBEAT,
    TOPIC_ALERTS,
    WORKER_IDLE,
    AlertMessage,
    StatusMessage,
)
from distributed_crawler_tpu.loadgen.exposition import (
    metric_samples,
    moving_samples,
    parse_exposition,
)
from distributed_crawler_tpu.orchestrator.fleet import FleetView
from distributed_crawler_tpu.orchestrator.watchtower import Watchtower
from distributed_crawler_tpu.state.datamodels import utcnow
from distributed_crawler_tpu.utils.alerts import (
    ALERT_FIRING,
    ALERT_INACTIVE,
    ALERT_PENDING,
    ALERT_RESOLVED,
    AlertEngine,
    AlertRule,
    default_rules,
    rules_from_config,
)
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    clear_alerts_provider,
    serve_metrics,
    set_alerts_provider,
)
from distributed_crawler_tpu.utils.timeseries import (
    RegistrySampler,
    TimeSeriesStore,
    series_key,
)

import tools.watch as watch


class Clock:
    """Injectable wall clock."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt
        return self.t


def hb(worker_id="tpu-1", usage=None, ts=None, queue_length=0):
    msg = StatusMessage.new(worker_id, MSG_HEARTBEAT, WORKER_IDLE,
                            worker_type="tpu")
    msg.queue_length = queue_length
    msg.resource_usage = usage or {}
    if ts is not None:
        msg.timestamp = ts
    return msg


# --- the store ---------------------------------------------------------------

class TestTimeSeriesStore:
    def test_ring_is_bounded_per_series(self):
        store = TimeSeriesStore(max_samples=4, clock=Clock())
        for i in range(10):
            store.add("m", float(i), wall=float(i))
        assert [v for _, v in store.samples("m")] == [6.0, 7.0, 8.0, 9.0]

    def test_series_key_sorted_and_labeled(self):
        assert series_key("m", {"b": "2", "a": "1"}) == "m{a=1,b=2}"
        assert series_key("m") == "m"

    def test_max_series_bound_drops_new_series_not_samples(self):
        store = TimeSeriesStore(max_series=2, clock=Clock())
        assert store.add("a", 1.0)
        assert store.add("b", 1.0)
        assert not store.add("c", 1.0)   # new series rejected
        assert store.add("a", 2.0)       # existing series still accepts
        assert store.latest("a") == 2.0
        assert store.snapshot()["dropped_series"] == 1

    def test_matching_subset_labels(self):
        store = TimeSeriesStore(clock=Clock())
        store.add("m", 1.0, {"slo": "qw", "worker": "w1"}, wall=1.0)
        store.add("m", 2.0, {"slo": "qw", "worker": "w2"}, wall=1.0)
        store.add("m", 3.0, {"slo": "age", "worker": "w1"}, wall=1.0)
        got = store.matching("m", {"slo": "qw"})
        assert sorted(lbl["worker"] for lbl, _ in got) == ["w1", "w2"]

    def test_increase_is_counter_reset_aware_and_summed(self):
        clock = Clock(100.0)
        store = TimeSeriesStore(clock=clock)
        # w1 counts 0 -> 2, restarts (2 -> 0), then 0 -> 1.
        for wall, value in ((90, 0), (92, 2), (94, 0), (96, 1)):
            store.add("c", float(value), {"w": "1"}, wall=float(wall))
        # w2 counts 5 -> 6.
        store.add("c", 5.0, {"w": "2"}, wall=90.0)
        store.add("c", 6.0, {"w": "2"}, wall=96.0)
        # w1: +2, reset contributes the fresh 0, +1 => 3; w2: +1.
        assert store.increase("c", window_s=20.0) == 4.0

    def test_increase_anchors_on_pre_window_sample(self):
        clock = Clock(100.0)
        store = TimeSeriesStore(clock=clock)
        store.add("c", 5.0, wall=80.0)   # before the window
        store.add("c", 9.0, wall=95.0)   # only sample inside
        assert store.increase("c", window_s=10.0) == 4.0

    def test_slope_least_squares_and_degenerate_cases(self):
        slope = TimeSeriesStore.slope
        assert slope([]) is None
        assert slope([(1.0, 5.0)]) is None            # single sample
        assert slope([(1.0, 5.0), (1.0, 9.0)]) is None  # zero time spread
        got = slope([(0.0, 0.0), (1.0, 2.0), (2.0, 4.0)])
        assert got == pytest.approx(2.0)

    def test_downsample_aligned_buckets(self):
        samples = [(10.2, 1.0), (10.8, 3.0), (12.1, 5.0)]
        got = TimeSeriesStore.downsample(samples, 2.0)
        # Buckets align to floor(wall/2)*2: [10,12) and [12,14).
        assert got == [(10.0, 2.0, 2), (12.0, 5.0, 1)]

    def test_snapshot_filters_and_windows(self):
        clock = Clock(100.0)
        store = TimeSeriesStore(clock=clock, window_s=900.0)
        store.add("a", 1.0, wall=98.0)
        store.add("a", 3.0, wall=99.0)
        store.add("b", 9.0, wall=99.0)
        body = store.snapshot(series="a")
        assert set(body["series"]) == {"a"}
        body = store.snapshot(window_s=10.0)
        pts = body["series"]["a"]["samples"]
        assert pts == [[90.0, 2.0, 2]]  # aligned mean bucket
        assert json.dumps(body)  # JSON-safe

    def test_eviction_during_evaluation_walk_is_safe(self):
        # matching() snapshots under the lock; concurrent adds that
        # evict ring entries must not corrupt an evaluation in progress.
        store = TimeSeriesStore(max_samples=8)
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                store.add("hot", float(i), {"w": "1"}, wall=float(i))
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(200):
                for _, samples in store.matching("hot"):
                    assert all(isinstance(v, float) for _, v in samples)
        finally:
            stop.set()
            t.join(timeout=5)


# --- the shared exposition parser -------------------------------------------

class TestExpositionParser:
    TEXT = ('# HELP x help\n# TYPE x counter\n'
            'x 3.0\nx{a="1",b="two words"} 4.5\n'
            'lat_bucket{le="0.1"} 7\nlat_sum 0.9\nlat_count 9\n'
            'bad line without value\n'
            'esc{v="q\\"uote"} 1\n')

    def test_parse_names_labels_values(self):
        samples = {(s.name, tuple(sorted(s.labels.items()))): s.value
                   for s in parse_exposition(self.TEXT)}
        assert samples[("x", ())] == 3.0
        assert samples[("x", (("a", "1"), ("b", "two words")))] == 4.5
        assert samples[("esc", (("v", 'q"uote'),))] == 1.0

    def test_metric_samples_exact_name(self):
        got = metric_samples(self.TEXT, "x")
        assert ("", 3.0) in got and len(got) == 2
        assert metric_samples(self.TEXT, "lat") == []

    def test_moving_samples_nonzero_lines(self):
        moved = moving_samples("a 0.0\nb 2.0\n# c 9\n")
        assert moved == ["b 2.0"]

    def test_registry_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("c", "h").labels(k="v").inc(2)
        reg.gauge("g").set(1.5)
        reg.histogram("h").observe(0.01)
        samples = parse_exposition(reg.expose())
        names = {s.name for s in samples}
        assert {"c", "g", "h_sum", "h_count", "h_bucket"} <= names

    def test_registry_sampler_skips_buckets(self):
        reg = MetricsRegistry()
        reg.histogram("h").observe(0.01)
        reg.gauge("g").set(2.0)
        store = TimeSeriesStore()
        added = RegistrySampler(reg, store).sample(now=1.0)
        assert added > 0
        assert store.latest("g") == 2.0
        assert not any("_bucket" in k for k in store.keys())


# --- the alert engine --------------------------------------------------------

def mk_engine(rules, clock, store=None):
    store = store or TimeSeriesStore(clock=clock)
    return store, AlertEngine(rules, store=store,
                              registry=MetricsRegistry(), clock=clock)


class TestAlertEngine:
    def test_empty_series_stays_inactive(self):
        clock = Clock()
        _, eng = mk_engine([AlertRule(name="t", kind="threshold",
                                      series="missing", op=">",
                                      value=0.0)], clock)
        assert eng.evaluate() == []
        assert eng.snapshot()["alerts"][0]["state"] == ALERT_INACTIVE

    def test_single_sample_trend_has_no_slope(self):
        clock = Clock()
        store, eng = mk_engine(
            [AlertRule(name="tr", kind="trend", series="s", op=">",
                       slope_per_s=0.0, window_s=60, min_samples=2)],
            clock)
        store.add("s", 5.0, wall=clock() - 1)
        assert eng.evaluate() == []   # one sample: no judgement
        store.add("s", 9.0, wall=clock())
        tr = eng.evaluate()
        assert [e["to"] for e in tr] == [ALERT_PENDING, ALERT_FIRING]

    def test_burn_rate_zero_budget_fires_on_any_breach(self):
        clock = Clock()
        store, eng = mk_engine(
            [AlertRule(name="b", kind="burn_rate", series="c", budget=0.0,
                       fast_window_s=5.0, slow_window_s=10.0,
                       factor=1.0)], clock)
        store.add("c", 0.0, wall=clock() - 1)
        assert eng.evaluate() == []   # no increase: burn 0, not inf
        store.add("c", 1.0, wall=clock())
        tr = eng.evaluate()
        assert [e["to"] for e in tr] == [ALERT_PENDING, ALERT_FIRING]
        body = eng.snapshot()
        assert json.dumps(body)        # inf clamped JSON-safe
        assert body["alerts"][0]["detail"]["burn_fast"] >= 1e9

    def test_burn_rate_needs_both_windows(self):
        clock = Clock(1000.0)
        store, eng = mk_engine(
            [AlertRule(name="b", kind="burn_rate", series="c",
                       budget=10.0, budget_window_s=100.0,
                       fast_window_s=10.0, slow_window_s=100.0,
                       factor=2.0)], clock)
        # Slow window: only 3 events over 100s (rate 0.03 < 0.2 target
        # burn of factor 2 * budget_rate 0.1) — fast spike alone must
        # not fire.
        store.add("c", 0.0, wall=905.0)
        store.add("c", 3.0, wall=998.0)   # fast window: +3 in 10s
        assert eng.evaluate() == []

    def test_pending_that_never_confirms_returns_inactive(self):
        clock = Clock()
        store, eng = mk_engine(
            [AlertRule(name="t", kind="threshold", series="g", op=">",
                       value=5.0, agg="last", for_s=10.0)], clock)
        store.add("g", 9.0, wall=clock())
        tr = eng.evaluate()
        assert [e["to"] for e in tr] == [ALERT_PENDING]
        clock.tick(5.0)
        store.add("g", 1.0, wall=clock())   # clears before for_s
        tr = eng.evaluate()
        assert [e["to"] for e in tr] == [ALERT_INACTIVE]
        assert eng.snapshot()["alerts"][0]["fired_count"] == 0

    def test_for_s_confirms_then_fires(self):
        clock = Clock()
        store, eng = mk_engine(
            [AlertRule(name="t", kind="threshold", series="g", op=">",
                       value=5.0, for_s=10.0)], clock)
        store.add("g", 9.0, wall=clock())
        assert [e["to"] for e in eng.evaluate()] == [ALERT_PENDING]
        clock.tick(9.0)
        store.add("g", 9.0, wall=clock())
        assert eng.evaluate() == []          # still pending
        clock.tick(1.0)
        store.add("g", 9.0, wall=clock())
        assert [e["to"] for e in eng.evaluate()] == [ALERT_FIRING]

    def test_flap_suppression_resolved_must_reconfirm_for_s(self):
        clock = Clock()
        store, eng = mk_engine(
            [AlertRule(name="t", kind="threshold", series="g", op=">",
                       value=5.0, agg="last", window_s=0.0,
                       for_s=10.0)], clock)
        store.add("g", 9.0, wall=clock())
        eng.evaluate()
        clock.tick(10.0)
        store.add("g", 9.0, wall=clock())
        eng.evaluate()
        assert eng.firing() == ["t"]
        clock.tick(1.0)
        store.add("g", 1.0, wall=clock())
        assert [e["to"] for e in eng.evaluate()] == [ALERT_RESOLVED]
        # The condition returns: a resolved alert must re-confirm
        # through pending for the full for_s — no instant re-fire.
        clock.tick(1.0)
        store.add("g", 9.0, wall=clock())
        assert [e["to"] for e in eng.evaluate()] == [ALERT_PENDING]
        assert eng.firing() == []
        clock.tick(10.0)
        store.add("g", 9.0, wall=clock())
        assert [e["to"] for e in eng.evaluate()] == [ALERT_FIRING]
        assert eng.snapshot()["alerts"][0]["fired_count"] == 2

    def test_clear_for_s_holds_resolution(self):
        clock = Clock()
        store, eng = mk_engine(
            [AlertRule(name="t", kind="threshold", series="g", op=">",
                       value=5.0, clear_for_s=10.0)], clock)
        store.add("g", 9.0, wall=clock())
        eng.evaluate()
        assert eng.firing() == ["t"]
        clock.tick(1.0)
        store.add("g", 1.0, wall=clock())
        assert eng.evaluate() == []          # clear streak too short
        clock.tick(10.0)
        store.add("g", 1.0, wall=clock())
        assert [e["to"] for e in eng.evaluate()] == [ALERT_RESOLVED]

    def test_transitions_publish_and_flight(self):
        from distributed_crawler_tpu.utils import flight

        flight.configure(capacity=64)
        flight.RECORDER.reset()
        clock = Clock()
        published = []
        store = TimeSeriesStore(clock=clock)
        eng = AlertEngine(
            [AlertRule(name="t", kind="threshold", series="g", op=">",
                       value=0.0)],
            store=store, registry=MetricsRegistry(), clock=clock,
            publish=published.append)
        store.add("g", 1.0, wall=clock())
        eng.evaluate()
        clock.tick(1.0)
        store.add("g", -1.0, wall=clock())
        eng.evaluate()
        # pending transitions stay local; firing + resolved publish.
        assert [e["to"] for e in published] == [ALERT_FIRING,
                                                ALERT_RESOLVED]
        kinds = [e["kind"] for e in flight.RECORDER.events()]
        assert kinds.count("alert") == 3  # pending, firing, resolved

    def test_duplicate_rule_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            AlertEngine([AlertRule(name="x", kind="threshold", series="s"),
                         AlertRule(name="x", kind="threshold", series="s")],
                        store=TimeSeriesStore(),
                        registry=MetricsRegistry())

    def test_rules_from_config_replaces_same_named_default(self):
        rules = rules_from_config([
            {"name": "queue_wait_burn", "kind": "burn_rate",
             "series": "fleet_slo_breach_total",
             "labels": {"slo": "queue_wait"}, "budget": 0,
             "fast_window_s": 1.0, "slow_window_s": 2.0, "factor": 1.0}])
        assert len(rules) == len(default_rules())
        assert rules[0].name == "queue_wait_burn"
        assert rules[0].fast_window_s == 1.0

    def test_rules_from_config_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="bogus"):
            rules_from_config([{"name": "x", "kind": "threshold",
                                "series": "s", "bogus": 1}])


# --- the bus envelope --------------------------------------------------------

class TestAlertMessage:
    def test_round_trip_and_registry(self):
        msg = AlertMessage.new("queue_wait_burn", "burn_rate",
                               "fleet_slo_breach_total", "firing",
                               prev_state="pending", value=12.5,
                               detail={"burn_fast": 12.5})
        msg.validate()
        back = decode_message(msg.to_dict())
        assert isinstance(back, AlertMessage)
        assert back.rule == "queue_wait_burn" and back.value == 12.5
        assert back.detail["burn_fast"] == 12.5
        assert back.state == "firing" and back.prev_state == "pending"

    def test_validate_rejects_bad_state(self):
        msg = AlertMessage.new("r", "threshold", "s", "exploded")
        with pytest.raises(ValueError, match="alert state"):
            msg.validate()

    def test_none_value_survives(self):
        msg = AlertMessage.new("r", "trend", "s", "resolved", value=None)
        assert decode_message(msg.to_dict()).value is None


# --- FleetView staleness at read time (the PR-12 satellite fix) --------------

class TestStalenessAtReadTime:
    def test_cluster_judges_staleness_at_snapshot_now(self):
        fv = FleetView(stale_after_s=300.0, registry=MetricsRegistry())
        t0 = utcnow()
        fv.observe(hb(worker_id="w1", ts=t0))
        # Fresh at t0; no health tick ever runs.  A scrape AFTER the
        # deadline must judge against its own now, not the last tick.
        assert fv.export(now=t0)["workers"]["w1"]["stale"] is False
        later = t0 + timedelta(seconds=301)
        out = fv.export(now=later)
        assert out["workers"]["w1"]["stale"] is True
        assert out["fleet"]["stale_workers"] == ["w1"]
        assert fv.stale_count(now=later) == 1
        assert fv.stale_count(now=t0) == 0

    def test_metrics_gauge_is_live_between_ticks(self):
        # The fn-bound gauge: a plain /metrics scrape between health
        # ticks reads staleness computed against NOW.
        reg = MetricsRegistry()
        fv = FleetView(stale_after_s=0.05, registry=reg)
        fv.observe(hb(worker_id="w1", ts=utcnow()))
        assert "fleet_stale_workers 0.0" in reg.expose()
        time.sleep(0.06)
        # No refresh_staleness() call in between — the scrape is live.
        assert "fleet_stale_workers 1.0" in reg.expose()


# --- the watchtower ----------------------------------------------------------

class FakeFleet:
    def __init__(self, stale=0):
        self.stale = stale

    def stale_count(self, now=None):
        return self.stale


class TestWatchtower:
    def test_heartbeat_fold_feeds_named_series(self):
        clock = Clock()
        store = TimeSeriesStore(clock=clock)
        wt = Watchtower(FakeFleet(), rules=[], store=store,
                        registry=MetricsRegistry(), clock=clock,
                        eval_interval_s=0.0)
        wt.observe_status(hb(usage={
            "rss_bytes": 1 << 20,
            "queue": {"depth": 3, "depth_time_weighted": 2.5},
            "efficiency": {"mfu": 0.25, "goodput_tokens_per_s": 900.0,
                           "per_chip": [
                               {"device": "cpu:0",
                                "goodput_tokens_per_s": 450.0}]},
            "occupancy": {"busy_fraction": 0.5, "overlap_fraction": 0.1,
                          "bubble_share": 0.2},
            "slo_breaches": {"queue_wait": 2},
        }))
        w = {"worker": "tpu-1"}
        assert store.latest("fleet_queue_depth", w) == 2.5
        assert store.latest("fleet_rss_bytes", w) == float(1 << 20)
        assert store.latest("fleet_mfu", w) == 0.25
        assert store.latest("fleet_per_chip_goodput_tokens_per_s",
                            {"worker": "tpu-1",
                             "device": "cpu:0"}) == 450.0
        assert store.latest("fleet_occupancy_bubble_share", w) == 0.2
        assert store.latest("fleet_slo_breach_total",
                            {"worker": "tpu-1",
                             "slo": "queue_wait"}) == 2.0

    def test_tick_rate_limited_and_forceable(self):
        clock = Clock()
        store = TimeSeriesStore(clock=clock)
        wt = Watchtower(FakeFleet(stale=1), rules=[], store=store,
                        registry=MetricsRegistry(), clock=clock,
                        eval_interval_s=10.0, sample_registry=False)
        wt.tick()
        assert store.latest("fleet_stale_workers") == 1.0
        n0 = len(store.samples("fleet_stale_workers"))
        wt.tick()   # inside the limiter window: no new sample
        assert len(store.samples("fleet_stale_workers")) == n0
        wt.tick(force=True)
        assert len(store.samples("fleet_stale_workers")) == n0 + 1

    def test_burn_alert_fires_from_heartbeats_and_publishes(self):
        clock = Clock()
        store = TimeSeriesStore(clock=clock)
        published = []

        class Bus:
            def publish(self, topic, payload):
                published.append((topic, payload))

        rules = [AlertRule(name="qw", kind="burn_rate",
                           series="fleet_slo_breach_total",
                           labels={"slo": "queue_wait"}, budget=0.0,
                           fast_window_s=5.0, slow_window_s=10.0,
                           factor=1.0)]
        wt = Watchtower(FakeFleet(), rules=rules, store=store,
                        registry=MetricsRegistry(), bus=Bus(),
                        clock=clock, eval_interval_s=0.0,
                        sample_registry=False)
        wt.observe_status(hb(usage={"slo_breaches": {"queue_wait": 0}}))
        wt.tick(force=True)
        clock.tick(1.0)
        wt.observe_status(hb(usage={"slo_breaches": {"queue_wait": 3}}))
        wt.tick(force=True)
        assert wt.firing() == ["qw"]
        assert len(published) == 1
        topic, payload = published[0]
        assert topic == TOPIC_ALERTS
        msg = decode_message(payload)
        assert isinstance(msg, AlertMessage) and msg.state == "firing"
        # /alerts body carries lifecycle + log + watchtower meta.
        body = wt.get_alerts()
        assert body["firing"] == ["qw"]
        assert body["watchtower"]["ticks"] >= 2
        assert json.dumps(body)

    def test_out_of_order_heartbeat_not_folded_into_series(self):
        # A redelivered OLDER heartbeat carries lower cumulative breach
        # counts; FleetView rejects it and the watchtower must follow —
        # folding it would look like a counter reset to increase() and
        # fire zero-budget burn rules on a healthy fleet.
        from distributed_crawler_tpu.config.crawler import CrawlerConfig
        from distributed_crawler_tpu.orchestrator import Orchestrator
        from distributed_crawler_tpu.utils import timeseries as ts_mod

        ts_mod.STORE.reset()
        try:
            orch = Orchestrator(
                "c1", CrawlerConfig(crawl_id="c1", platform="telegram"),
                None, _NullSM(), registry=MetricsRegistry(),
                alert_rules=[])
            t0 = utcnow()
            fresh = hb(usage={"slo_breaches": {"queue_wait": 5}}, ts=t0)
            stale = hb(usage={"slo_breaches": {"queue_wait": 3}},
                       ts=t0 - timedelta(seconds=10))
            orch.handle_status(fresh)
            orch.handle_status(stale)  # out-of-order: dropped, not folded
            samples = ts_mod.STORE.samples(
                "fleet_slo_breach_total",
                {"worker": "tpu-1", "slo": "queue_wait"})
            assert [v for _, v in samples] == [5.0]
        finally:
            ts_mod.STORE.reset()

    def test_outbox_utilization_derived_from_gauges(self):
        clock = Clock()
        reg = MetricsRegistry()
        reg.gauge("bus_outbox_depth").labels(publisher="orch").set(90.0)
        reg.gauge("bus_outbox_capacity").labels(
            publisher="orch").set(100.0)
        store = TimeSeriesStore(clock=clock)
        rules = [AlertRule(name="outbox_near_full", kind="threshold",
                           series="watchtower_outbox_utilization",
                           op=">=", value=0.8, agg="last", group="max")]
        wt = Watchtower(FakeFleet(), rules=rules, store=store,
                        registry=reg, clock=clock, eval_interval_s=0.0)
        wt.tick(force=True)
        assert store.latest("watchtower_outbox_utilization",
                            {"publisher": "orch"}) == pytest.approx(0.9)
        assert wt.firing() == ["outbox_near_full"]


# --- live surfaces + dashboard + bundle -------------------------------------

class TestLiveSurfaces:
    def test_alerts_and_timeseries_over_http(self):
        from distributed_crawler_tpu.utils import timeseries as ts_mod

        clock = Clock()
        store = TimeSeriesStore(clock=clock)
        rules = [AlertRule(name="hot", kind="threshold", series="g",
                           op=">", value=0.5)]
        wt = Watchtower(FakeFleet(), rules=rules, store=store,
                        registry=MetricsRegistry(), clock=clock,
                        eval_interval_s=0.0, sample_registry=False)
        store.add("g", 1.0, wall=clock())
        wt.tick(force=True)
        set_alerts_provider(wt.get_alerts)
        # /timeseries serves the process-global store: point it at ours
        # for the duration.
        old_store = ts_mod.STORE
        ts_mod.STORE = store
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        try:
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/alerts", timeout=5))
            assert body["firing"] == ["hot"]
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/timeseries?series=g", timeout=5))
            assert set(body["series"]) == {"g"}
            # window= downsamples into aligned buckets (3-col points).
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/timeseries?window=2", timeout=5))
            assert all(len(p) == 3
                       for p in body["series"]["g"]["samples"])
            # The dashboard renders from the same live surfaces.
            page = watch.render_once(f"http://127.0.0.1:{port}")
            assert "FIRING" in page and "hot" in page
        finally:
            server.shutdown()
            ts_mod.STORE = old_store
            clear_alerts_provider(wt.get_alerts)

    def test_bundle_embeds_alert_log_and_series(self):
        from distributed_crawler_tpu.utils import timeseries as ts_mod
        from distributed_crawler_tpu.utils.flight import FlightRecorder

        clock = Clock()
        store = TimeSeriesStore(clock=clock)
        rules = [AlertRule(name="hot", kind="threshold", series="g",
                           op=">", value=0.5)]
        wt = Watchtower(FakeFleet(), rules=rules, store=store,
                        registry=MetricsRegistry(), clock=clock,
                        eval_interval_s=0.0, sample_registry=False)
        store.add("g", 1.0, wall=clock())
        wt.tick(force=True)
        set_alerts_provider(wt.get_alerts)
        old_store = ts_mod.STORE
        ts_mod.STORE = store
        try:
            rec = FlightRecorder(capacity=8)
            bundle = rec.bundle("test")
            assert bundle["alerts"]["firing"] == ["hot"]
            assert "g" in bundle["timeseries"]["series"]
            # The postmortem renderer shows the trend + the alert log.
            import tools.postmortem as postmortem

            store.add("g", 5.0, wall=clock() + 1)
            out = postmortem.render_bundle(rec.bundle("test2"))
            assert "alert log" in out and "hot" in out
            assert "trending before the crash" in out
        finally:
            ts_mod.STORE = old_store
            clear_alerts_provider(wt.get_alerts)


class TestEndToEndWatchtower:
    def test_orchestrator_worker_alert_e2e(self, tmp_path):
        """One real stack on the in-memory bus: TPU worker heartbeats
        carry breach counts, the orchestrator's watchtower folds them,
        a zero-budget burn rule fires, /alerts serves it over HTTP, and
        tools/watch.py --once renders the live dashboard."""
        from distributed_crawler_tpu.bus import InMemoryBus
        from distributed_crawler_tpu.config.crawler import CrawlerConfig
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.inference.worker import (
            TPUWorker,
            TPUWorkerConfig,
        )
        from distributed_crawler_tpu.orchestrator import Orchestrator
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
        )
        from distributed_crawler_tpu.utils import timeseries as ts_mod
        from distributed_crawler_tpu.utils import trace

        trace.configure(capacity=4096)
        ts_mod.STORE.reset()
        registry = MetricsRegistry()
        bus = InMemoryBus(sync=True)
        rules = [AlertRule(name="queue_wait_burn", kind="burn_rate",
                           series="fleet_slo_breach_total",
                           labels={"slo": "queue_wait"}, budget=0.0,
                           fast_window_s=30.0, slow_window_s=60.0,
                           factor=1.0)]
        orch = Orchestrator(
            "c1", CrawlerConfig(crawl_id="c1", platform="telegram"),
            bus, _NullSM(), registry=registry, alert_rules=rules)
        orch.ocfg.alert_eval_interval_s = 0.0
        bus.subscribe("worker-status", orch.handle_status_payload)
        bus.subscribe(TOPIC_ALERTS, lambda p: None)
        engine = InferenceEngine(EngineConfig(model="tiny", batch_size=2,
                                              buckets=[16]),
                                 registry=registry)
        worker = TPUWorker(
            bus, engine, provider=InMemoryStorageProvider(),
            cfg=TPUWorkerConfig(worker_id="tpu-1", heartbeat_s=0.1,
                                stall_warn_s=0.0,
                                slo_queue_wait_ms=0.001),
            registry=registry)
        worker.start()
        server = serve_metrics(0, registry)
        port = server.server_address[1]
        set_alerts_provider(orch.get_alerts)
        try:
            from distributed_crawler_tpu.bus.codec import RecordBatch
            from distributed_crawler_tpu.datamodel.post import Post

            batch = RecordBatch.from_posts(
                [Post(post_uid="p1", description="hello world")],
                crawl_id="c1")
            bus.publish("tpu-inference-batches", batch.to_dict())
            assert worker.drain(timeout_s=30)
            deadline = time.monotonic() + 15
            fired = False
            while time.monotonic() < deadline and not fired:
                orch.watchtower.tick(force=True)
                fired = "queue_wait_burn" in orch.watchtower.firing()
                time.sleep(0.05)
            assert fired, orch.get_alerts()
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/alerts", timeout=5))
            assert "queue_wait_burn" in body["firing"]
            # /timeseries carries BOTH the fleet fold and the worker's
            # own self-samples (one process here, one store).
            ts_body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/timeseries", timeout=5))
            keys = set(ts_body["series"])
            assert any(k.startswith("fleet_slo_breach_total")
                       for k in keys)
            assert any(k.startswith("tpu_worker_batches_total")
                       for k in keys), sorted(keys)[:20]
            page = watch.render_once(f"http://127.0.0.1:{port}")
            assert "queue_wait_burn" in page and "FIRING" in page
        finally:
            set_alerts_provider(None)
            worker.stop(timeout_s=5)
            server.shutdown()
            bus.close()
            ts_mod.STORE.reset()


class _NullSM:
    def initialize(self, seeds):
        pass

    def save_state(self):
        pass

    def close(self):
        pass

    def get_layer_by_depth(self, depth):
        return []

    def get_max_depth(self):
        raise LookupError

    def update_page(self, page):
        pass
