"""Crawl-engine tests: parsing, fetch windows, dedup/resample, the channel
pipeline, random-walk walkback + tandem batching, FLOOD_WAIT policy, pool
facade, 400-replacement.

Reference analogs: crawl/channel_info_test.go, fetch_messages_test.go,
message_processing_test.go, runner_flood_wait_test.go, runner_400_test.go,
runner_tandem_test.go.
"""

import random

import pytest

from distributed_crawler_tpu.clients import SimNetwork, SimTelegramClient
from distributed_crawler_tpu.clients.telegram import TLMessage
from distributed_crawler_tpu.config import CrawlerConfig
from distributed_crawler_tpu.crawl import (
    FloodWaitRetireError,
    TDLib400Error,
    WalkbackExhaustedError,
    add_new_messages,
    handle_400_replacement,
    pick_walkback_channel,
    resample_marker,
    run_for_channel,
)
from distributed_crawler_tpu.crawl.runner import (
    DefaultMessageProcessor,
    process_all_messages,
)
from distributed_crawler_tpu.state import (
    CompositeStateManager,
    Page,
    SqlConfig,
    StateConfig,
)
from distributed_crawler_tpu.state.datamodels import EdgeRecord, Message
from distributed_crawler_tpu.telegram import (
    build_telegram_link,
    extract_channel_links_with_source,
    fetch_channel_messages_with_sampling,
    parse_message,
    utf16_slice,
)


def text_msg(text, entities=None, **kw):
    content = {"@type": "messageText",
               "text": {"text": text, "entities": entities or []}}
    return TLMessage(content=content, **kw)


def make_sm(tmp_path, sampling="channel", crawl_id="c1"):
    return CompositeStateManager(StateConfig(
        crawl_id=crawl_id, crawl_execution_id="e1",
        storage_root=str(tmp_path), sampling_method=sampling,
        sql=SqlConfig(url=":memory:")))


def make_cfg(**kw):
    base = dict(crawl_id="c1", skip_media_download=True)
    base.update(kw)
    return CrawlerConfig(**base)


class TestParsing:
    def test_utf16_slice_with_surrogates(self):
        # Emoji occupies 2 UTF-16 units; offsets after it shift.
        s = "😀 @chan_one rest"
        assert utf16_slice(s, 3, 9) == "@chan_one"

    def test_extract_links_source_priority(self):
        text = "see @mention_chan and t.me/plain_chan"
        entities = [
            {"type": {"@type": "textEntityTypeMention"}, "offset": 4,
             "length": 13},
            {"type": {"@type": "textEntityTypeTextUrl",
                      "url": "https://t.me/hyperlink_chan"}, "offset": 0,
             "length": 3},
        ]
        links = {l.name: l.source_type
                 for l in extract_channel_links_with_source(text_msg(text, entities))}
        assert links["mention_chan"] == "mention"
        assert links["hyperlink_chan"] == "text_url"
        assert links["plain_chan"] == "plaintext"

    def test_reserved_tme_paths_ignored(self):
        msg = text_msg("join t.me/joinchat/abcdef and t.me/realchan")
        names = [l.name for l in extract_channel_links_with_source(msg)]
        assert "joinchat" not in names
        assert "realchan" in names

    def test_public_link_uses_shifted_id(self):
        assert build_telegram_link("chan", 5 * 1048576) == "https://t.me/chan/5"

    def test_parse_message_end_to_end(self, tmp_path):
        net = SimNetwork()
        msg = text_msg("hello @other_chan", view_count=100, forward_count=5,
                       reply_count=2, reactions={"👍": 9}, date=1700000000)
        ch = net.add_channel("mychan", messages=[msg], member_count=777)
        client = SimTelegramClient(net)
        chat = client.search_public_chat("mychan")
        sg = client.get_supergroup(chat.supergroup_id)
        sgi = client.get_supergroup_full_info(chat.supergroup_id)
        sm = make_sm(tmp_path)
        post = parse_message("c1", msg, chat, sg, sgi, 50, 1000, "mychan",
                             client, sm, make_cfg())
        assert post.platform_name == "telegram"
        assert post.view_count == 100 and post.shares_count == 5
        assert post.engagement == 107
        assert post.outlinks == ["other_chan"]
        assert post.reactions == {"👍": 9}
        assert post.channel_data.channel_engagement_data.follower_count == 777
        assert post.post_link.startswith("https://t.me/mychan/")
        assert post.post_uid == f"{chat.id}_{msg.id}"

    def test_parse_message_media_and_cap(self, tmp_path):
        net = SimNetwork()
        net.add_file("small_file", b"x" * 100)
        msg = TLMessage(content={"@type": "messageVideo",
                                 "caption": {"text": "vid"},
                                 "video": {"remote_id": "small_file"}},
                        date=1700000000)
        ch = net.add_channel("mychan", messages=[msg])
        client = SimTelegramClient(net)
        chat = client.search_public_chat("mychan")
        sm = make_sm(tmp_path)
        cfg = make_cfg(skip_media_download=False)
        post = parse_message("c1", msg, chat, None, None, 1, 0, "mychan",
                             client, sm, cfg)
        assert post.media_data.document_name
        assert sm.has_processed_media("small_file")
        # Second parse: dedup — media not re-stored.
        post2 = parse_message("c1", msg, chat, None, None, 1, 0, "mychan",
                              client, sm, cfg)
        assert post2.media_data.document_name == ""

    def test_parse_message_comments(self, tmp_path):
        net = SimNetwork()
        msg = text_msg("post with comments", reply_count=2, date=1700000000)
        ch = net.add_channel("mychan", messages=[msg])
        net.add_comments(ch.chat_id, msg.id, [
            text_msg("first!", sender_username="fan1"),
            text_msg("second", sender_username="fan2")])
        client = SimTelegramClient(net)
        chat = client.search_public_chat("mychan")
        sm = make_sm(tmp_path)
        post = parse_message("c1", msg, chat, None, None, 1, 0, "mychan",
                             client, sm, make_cfg(max_comments=10))
        assert [c.handle for c in post.comments] == ["fan1", "fan2"]


class TestFetch:
    def _client(self, dates):
        net = SimNetwork()
        msgs = [text_msg(f"m{i}", date=d) for i, d in enumerate(dates)]
        ch = net.add_channel("chan", messages=msgs)
        return SimTelegramClient(net), ch

    def test_min_date_cutoff(self):
        from datetime import datetime, timezone
        client, ch = self._client([1000, 2000, 3000, 4000])
        msgs = fetch_channel_messages_with_sampling(
            client, ch.chat_id, Page(url="chan"),
            min_post_date=datetime.fromtimestamp(2500, tz=timezone.utc))
        assert sorted(m.date for m in msgs) == [3000, 4000]

    def test_max_posts_truncates(self):
        client, ch = self._client(list(range(1000, 1500)))
        msgs = fetch_channel_messages_with_sampling(
            client, ch.chat_id, Page(url="chan"), max_posts=7)
        assert len(msgs) == 7

    def test_sampling_applied(self):
        client, ch = self._client(list(range(1000, 1300)))
        msgs = fetch_channel_messages_with_sampling(
            client, ch.chat_id, Page(url="chan"), sample_size=10,
            rng=random.Random(0))
        assert len(msgs) == 10

    def test_date_between_window(self):
        from datetime import datetime, timezone
        client, ch = self._client([1000, 2000, 3000, 4000, 5000])
        msgs = fetch_channel_messages_with_sampling(
            client, ch.chat_id, Page(url="chan"),
            min_post_date=datetime.fromtimestamp(1500, tz=timezone.utc),
            max_post_date=datetime.fromtimestamp(4500, tz=timezone.utc))
        assert sorted(m.date for m in msgs) == [2000, 3000, 4000]


class TestMessageBookkeeping:
    def test_add_new_messages_dedups(self):
        owner = Page(id="p1", messages=[Message(chat_id=1, message_id=10,
                                                status="fetched")])
        merged = add_new_messages([Message(chat_id=1, message_id=10),
                                   Message(chat_id=1, message_id=20)], owner)
        assert len(merged) == 2

    def test_resample_marker_rules(self):
        msgs = [Message(chat_id=1, message_id=1, status="fetched"),
                Message(chat_id=1, message_id=2, status="unfetched"),
                Message(chat_id=1, message_id=3, status="failed")]
        discovered = [Message(chat_id=1, message_id=1),
                      Message(chat_id=1, message_id=2)]
        out = resample_marker(msgs, discovered)
        assert out[0].status == "fetched"  # never touched
        assert out[1].status == "resample"  # still exists
        assert out[2].status == "deleted"  # gone from latest fetch


def build_channel_network(outlink_targets=("target_one", "target_two")):
    """A source channel whose messages mention other channels that also exist."""
    net = SimNetwork()
    mentions = " ".join(f"@{t}" for t in outlink_targets)
    msgs = [text_msg(f"post {i} {mentions}", date=1700000000 + i,
                     view_count=10) for i in range(3)]
    src = net.add_channel("source_chan", messages=msgs, member_count=1000)
    for t in outlink_targets:
        net.add_channel(t, messages=[text_msg("hi", date=1700000005)],
                        member_count=500)
    return net, src


class TestRunForChannelBFS:
    def test_happy_path_stores_posts_and_discovers(self, tmp_path):
        net, src = build_channel_network()
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path)
        page = Page(id="p1", url="source_chan", depth=0)
        discovered = run_for_channel(client, page, "", sm, make_cfg())
        urls = {p.url for p in discovered}
        assert urls == {"target_one", "target_two"}
        assert all(p.depth == 1 for p in discovered)
        assert page.status == "fetched"
        # Posts landed in per-channel JSONL.
        jsonl = tmp_path / "c1" / "source_chan" / "posts" / "posts.jsonl"
        assert jsonl.exists()
        assert len(jsonl.read_text().strip().split("\n")) == 3

    def test_min_users_gate_marks_deadend(self, tmp_path):
        net, src = build_channel_network()
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path)
        page = Page(id="p1", url="source_chan", depth=0)
        out = run_for_channel(client, page, "", sm,
                              make_cfg(min_users=999999))
        assert out == []
        assert page.status == "deadend"

    def test_post_recency_gate(self, tmp_path):
        from datetime import datetime, timezone
        net, src = build_channel_network()
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path)
        page = Page(id="p1", url="source_chan", depth=0)
        out = run_for_channel(client, page, "", sm, make_cfg(
            post_recency=datetime(2030, 1, 1, tzinfo=timezone.utc)))
        assert out == [] and page.status == "deadend"

    def test_unknown_channel_raises_400(self, tmp_path):
        net = SimNetwork()
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path)
        with pytest.raises(TDLib400Error):
            run_for_channel(client, Page(id="p1", url="ghost_chan"), "", sm,
                            make_cfg())

    def test_failed_message_marked_and_others_continue(self, tmp_path):
        net, src = build_channel_network()
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path)
        page = Page(id="p1", url="source_chan", depth=0)

        class FlakyProcessor(DefaultMessageProcessor):
            count = 0
            def process_message(self, *a, **kw):
                FlakyProcessor.count += 1
                if FlakyProcessor.count == 2:
                    raise RuntimeError("boom on message 2")
                return super().process_message(*a, **kw)

        run_for_channel(client, page, "", sm, make_cfg(),
                        processor=FlakyProcessor())
        statuses = sorted(m.status for m in sm.get_page("p1").messages)
        assert statuses.count("failed") == 1
        assert statuses.count("fetched") == 2


class TestRandomWalk:
    def _run(self, tmp_path, walkback_rate, seed=3, targets=("target_one",
                                                             "target_two"),
             pre_discovered=("earlier_chan",)):
        net, src = build_channel_network(targets)
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path, sampling="random-walk")
        for ch in pre_discovered:
            sm.add_discovered_channel(ch)
        sm.initialize(["source_chan"])
        page = sm.get_layer_by_depth(0)[0]
        cfg = make_cfg(sampling_method="random-walk",
                       walkback_rate=walkback_rate)
        run_for_channel(client, page, "", sm, cfg, rng=random.Random(seed))
        return sm, page

    def test_forward_walk_writes_primary_and_skipped_edges(self, tmp_path):
        sm, page = self._run(tmp_path, walkback_rate=0)
        pages = sm.get_pages_from_page_buffer(10)
        assert len(pages) == 1
        nxt = pages[0]
        assert nxt.url in ("target_one", "target_two")
        assert nxt.sequence_id == page.sequence_id  # forward keeps the chain
        primary = sm.get_edge_record(page.sequence_id, nxt.url)
        assert primary is not None and not primary.walkback and not primary.skipped
        other = ({"target_one", "target_two"} - {nxt.url}).pop()
        skipped = sm.get_edge_record(page.sequence_id, other)
        assert skipped is not None and skipped.skipped

    def test_walkback_rate_100_walks_back(self, tmp_path):
        sm, page = self._run(tmp_path, walkback_rate=100)
        pages = sm.get_pages_from_page_buffer(10)
        assert len(pages) == 1
        nxt = pages[0]
        # Walkback goes to a discovered channel, new chain for the page.
        assert nxt.sequence_id != page.sequence_id
        edge = sm.get_edge_record(page.sequence_id, nxt.url)
        assert edge is not None and edge.walkback

    def test_discovered_channels_cached_as_seeds(self, tmp_path):
        sm, page = self._run(tmp_path, walkback_rate=0)
        # SearchPublicChat result cached for future runs.
        chat_id, ok = sm.get_cached_chat_id("target_one")
        assert ok and chat_id > 0
        assert sm.is_discovered_channel("target_one")

    def test_channel_marked_crawled_with_incremental_window(self, tmp_path):
        sm, page = self._run(tmp_path, walkback_rate=0)
        assert sm.get_channel_last_crawled("source_chan") is not None

    def test_invalid_outlinks_marked(self, tmp_path):
        # target mentioned but does not exist in the network -> not_found.
        net, src = build_channel_network(outlink_targets=("ghost_channel",))
        del net.channels["ghost_channel"]
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize(["source_chan"])
        page = sm.get_layer_by_depth(0)[0]
        cfg = make_cfg(sampling_method="random-walk", walkback_rate=0)
        # Only outlink is invalid -> no new channels -> forced walkback, but
        # the only discovered channel is the source itself -> exhausted.
        with pytest.raises(WalkbackExhaustedError):
            run_for_channel(client, page, "", sm, cfg, rng=random.Random(0))
        assert sm.is_invalid_channel("ghost_channel")

    def test_short_floodwait_sleeps_and_retries(self, tmp_path):
        net, src = build_channel_network(outlink_targets=("target_one",))
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize(["source_chan"])
        page = sm.get_layer_by_depth(0)[0]
        sleeps = []
        info_msgs = None
        from distributed_crawler_tpu.crawl.channelinfo import get_channel_info
        cfg = make_cfg(sampling_method="random-walk", walkback_rate=0)
        info, msgs = get_channel_info(client, page, 0, cfg)
        net.inject_flood_wait("SearchPublicChat", 5, count=1)
        process_all_messages(client, info, msgs, "c1", "source_chan", sm,
                             page, cfg, rng=random.Random(1),
                             sleep=sleeps.append)
        assert sleeps == [5]  # slept the FLOOD_WAIT then retried
        assert sm.is_discovered_channel("target_one")

    def test_long_floodwait_raises_retire(self, tmp_path):
        net, src = build_channel_network(outlink_targets=("target_one",))
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize(["source_chan"])
        page = sm.get_layer_by_depth(0)[0]
        from distributed_crawler_tpu.crawl.channelinfo import get_channel_info
        cfg = make_cfg(sampling_method="random-walk", walkback_rate=0)
        info, msgs = get_channel_info(client, page, 0, cfg)
        # SearchPublicChat for the outlink flood-waits beyond threshold.
        net.inject_flood_wait("SearchPublicChat", 72560, count=1)
        with pytest.raises(FloodWaitRetireError):
            process_all_messages(client, info, msgs, "c1", "source_chan", sm,
                                 page, cfg, rng=random.Random(1))


class TestTandem:
    def _run(self, tmp_path, targets=("target_one", "target_two")):
        net, src = build_channel_network(targets)
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize(["source_chan"])
        page = sm.get_layer_by_depth(0)[0]
        cfg = make_cfg(sampling_method="random-walk", tandem_crawl=True,
                       walkback_rate=0)
        run_for_channel(client, page, "", sm, cfg, rng=random.Random(3))
        return sm, page, client

    def test_edges_streamed_and_batch_closed(self, tmp_path):
        sm, page, client = self._run(tmp_path)
        # No SearchPublicChat for outlinks in tandem mode.
        searches = [c for c in client.calls if c[0] == "SearchPublicChat"
                    and c[1][0] != "source_chan"]
        assert searches == []
        # Batch closed with both edges pending validation.
        edges = sm.claim_pending_edges(10)
        assert {e.destination_channel for e in edges} == {"target_one",
                                                          "target_two"}
        assert all(e.sequence_id == page.sequence_id for e in edges)
        assert sm.count_incomplete_batches("c1") == 1
        # Page buffer untouched: the validator owns the next page.
        assert sm.get_pages_from_page_buffer(10) == []

    def test_bot_usernames_prefiltered(self, tmp_path):
        sm, page, client = self._run(tmp_path,
                                     targets=("real_channel", "spam_bot"))
        edges = sm.claim_pending_edges(10)
        assert {e.destination_channel for e in edges} == {"real_channel"}

    def test_no_edges_forces_walkback(self, tmp_path):
        net = SimNetwork()
        msgs = [text_msg("no mentions here", date=1700000000)]
        net.add_channel("source_chan", messages=msgs, member_count=100)
        net.add_channel("other_chan", messages=[text_msg("x", date=1)])
        client = SimTelegramClient(net)
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize(["source_chan", "other_chan"])
        page = [p for p in sm.get_layer_by_depth(0)
                if p.url == "source_chan"][0]
        cfg = make_cfg(sampling_method="random-walk", tandem_crawl=True)
        run_for_channel(client, page, "", sm, cfg, rng=random.Random(0))
        pages = sm.get_pages_from_page_buffer(10)
        assert len(pages) == 1 and pages[0].url == "other_chan"
        edge = sm.get_edge_record(page.sequence_id, "other_chan")
        assert edge is not None and edge.walkback


class TestPoolFacade:
    def test_retire_on_floodwait_release_otherwise(self, tmp_path):
        from distributed_crawler_tpu.clients import ConnectionPool
        from distributed_crawler_tpu.crawl import (
            init_connection_pool,
            run_for_channel_with_pool,
            set_run_for_channel_fn,
            shutdown_connection_pool,
        )
        net, _ = build_channel_network()
        pool = ConnectionPool(factory=lambda cid: SimTelegramClient(net, cid),
                              database_urls=["a", "b"])
        pool.initialize()
        shutdown_connection_pool()
        init_connection_pool(pool)
        sm = make_sm(tmp_path)

        calls = []
        def fail_with_floodwait(client, page, prefix, sm_, cfg, processor=None):
            calls.append(page.connection_id)
            raise FloodWaitRetireError(90000)
        set_run_for_channel_fn(fail_with_floodwait)
        try:
            with pytest.raises(FloodWaitRetireError):
                run_for_channel_with_pool(Page(id="p", url="source_chan"),
                                          "", sm, make_cfg())
            assert pool.stats()["retired"] == 1
            # Normal failure: released, not retired.
            def fail_normal(client, page, prefix, sm_, cfg, processor=None):
                raise RuntimeError("plain error")
            set_run_for_channel_fn(fail_normal)
            with pytest.raises(RuntimeError):
                run_for_channel_with_pool(Page(id="p2", url="source_chan"),
                                          "", sm, make_cfg())
            assert pool.stats()["retired"] == 1  # unchanged
            conn = pool.acquire(timeout_s=1)  # still acquirable
            pool.release(conn)
        finally:
            set_run_for_channel_fn(None)
            shutdown_connection_pool()


class TestSetupPoolFromConfig:
    """Production pool wiring: `crawl.InitConnectionPool` analog that every
    telegram entry path calls (`standalone/runner.go:478`,
    `worker.go:96-133`)."""

    def _seed_tarball(self, tmp_path, name="dbs.tar.gz"):
        import json
        import tarfile

        seed = {"channels": [{"username": "poolchan", "chat_id": 71,
                              "title": "Pool Chan", "member_count": 10,
                              "messages": []}]}
        src = tmp_path / f"src-{name}"
        src.mkdir()
        (src / "seed.json").write_text(json.dumps(seed))
        path = tmp_path / name
        with tarfile.open(path, "w:gz") as tar:
            tar.add(src / "seed.json", arcname="db/seed.json")
        return str(path)

    def test_builds_pool_from_database_urls(self, tmp_path):
        from distributed_crawler_tpu.crawl import (
            get_connection_from_pool,
            setup_pool_from_config,
            shutdown_connection_pool,
        )
        from distributed_crawler_tpu.crawl.runner import (
            release_connection_to_pool,
        )

        shutdown_connection_pool()
        tar1 = self._seed_tarball(tmp_path, "one.tar.gz")
        tar2 = self._seed_tarball(tmp_path, "two.tar.gz")
        cfg = make_cfg(tdlib_database_urls=[tar1, tar2],
                       storage_root=str(tmp_path / "store"))
        try:
            assert setup_pool_from_config(cfg) is True
            conn = get_connection_from_pool(timeout_s=2)
            try:
                chat = conn.client.search_public_chat("poolchan")
                assert chat.title == "Pool Chan"
            finally:
                release_connection_to_pool(conn)
            # One extracted conn dir per connection, under storage_root.
            import os as os_mod
            dbs = tmp_path / "store" / ".tdlib" / "databases"
            assert len([d for d in os_mod.listdir(dbs)
                        if d.startswith("conn_")]) == 2
        finally:
            shutdown_connection_pool()

    def test_noop_without_urls_or_with_existing_pool(self, tmp_path):
        from distributed_crawler_tpu.clients import ConnectionPool
        from distributed_crawler_tpu.crawl import (
            init_connection_pool,
            setup_pool_from_config,
            shutdown_connection_pool,
        )

        shutdown_connection_pool()
        assert setup_pool_from_config(make_cfg()) is False  # no URLs
        net, _ = build_channel_network()
        pool = ConnectionPool(factory=lambda cid: SimTelegramClient(net, cid))
        pool.initialize()
        init_connection_pool(pool)
        try:
            # Already-installed pool (the sim/test seam) is left alone.
            tar = self._seed_tarball(tmp_path)
            assert setup_pool_from_config(
                make_cfg(tdlib_database_urls=[tar])) is True
            from distributed_crawler_tpu.crawl.runner import _pool
            assert _pool is pool
        finally:
            shutdown_connection_pool()


class TestWalkbackPicker:
    def test_excludes_source_and_excluded(self, tmp_path):
        sm = make_sm(tmp_path, sampling="random-walk")
        for ch in ("a_chan", "b_chan", "c_chan"):
            sm.add_discovered_channel(ch)
        picked = set()
        for i in range(20):
            try:
                picked.add(pick_walkback_channel(sm, "a_chan",
                                                 {"b_chan": True},
                                                 rng=random.Random(i)))
            except WalkbackExhaustedError:
                pass  # possible with 10 bounded random draws — reference parity
        assert picked == {"c_chan"}

    def test_exhaustion(self, tmp_path):
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.add_discovered_channel("only_chan")
        with pytest.raises(WalkbackExhaustedError):
            pick_walkback_channel(sm, "only_chan", rng=random.Random(0))


class Test400Replacement:
    def _sm(self, tmp_path):
        sm = make_sm(tmp_path, sampling="random-walk")
        for ch in ("src_chan", "dead_chan", "alt_chan", "walk_chan"):
            sm.add_discovered_channel(ch)
        return sm

    def test_forward_edge_promotes_skipped_sibling(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.save_edge_records([
            EdgeRecord(destination_channel="dead_chan", source_channel="src_chan",
                       skipped=False, sequence_id="q1"),
            EdgeRecord(destination_channel="alt_chan", source_channel="src_chan",
                       skipped=True, sequence_id="q1"),
        ])
        page = Page(id="pdead", url="dead_chan", sequence_id="q1", depth=3,
                    parent_id="pp")
        handle_400_replacement(sm, page, make_cfg(sampling_method="random-walk"),
                               rng=random.Random(0))
        assert sm.is_invalid_channel("dead_chan")
        assert sm.get_edge_record("q1", "dead_chan") is None  # edge deleted
        pages = sm.get_pages_from_page_buffer(10)
        assert [p.url for p in pages] == ["alt_chan"]
        assert pages[0].sequence_id == "q1" and pages[0].depth == 3
        promoted = sm.get_edge_record("q1", "alt_chan")
        assert promoted is not None and not promoted.skipped

    def test_walkback_edge_walks_back_again(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.save_edge_records([
            EdgeRecord(destination_channel="dead_chan", source_channel="src_chan",
                       walkback=True, skipped=False, sequence_id="q1")])
        page = Page(id="pdead", url="dead_chan", sequence_id="q1", depth=2)
        handle_400_replacement(sm, page, make_cfg(sampling_method="random-walk"),
                               rng=random.Random(0))
        pages = sm.get_pages_from_page_buffer(10)
        assert len(pages) == 1
        nxt = pages[0]
        assert nxt.url not in ("dead_chan",)
        assert nxt.sequence_id != "q1"  # new chain
        edge = sm.get_edge_record("q1", nxt.url)
        assert edge is not None and edge.walkback

    def test_no_edge_seed_channel_replaced_from_seed_pool(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.mark_channel_crawled("dead_chan", 1)
        sm.mark_channel_crawled("fresh_seed", 2)
        sm.load_seed_channels()
        page = Page(id="pdead", url="dead_chan", sequence_id="q9", depth=0)
        handle_400_replacement(sm, page, make_cfg(sampling_method="random-walk"),
                               rng=random.Random(0))
        pages = sm.get_pages_from_page_buffer(10)
        assert len(pages) == 1
        # dead_chan was invalidated in seed_channels, so only fresh_seed remains.
        assert pages[0].url == "fresh_seed"
