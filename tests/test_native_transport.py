"""Native transport seam (VERDICT r2 missing #1): the C++ client speaking
the DCT wire protocol over REAL sockets to the in-tree mock DC server —
auth lifecycle (phone/code/password) + fetches, plain TCP and TLS with a
Chrome-shaped ClientHello (`native/net.h`; reference parity:
`telegramhelper/client.go:319-377`, `standalone/runner.go:77-192`,
`utlstransport.go:19-57`).
"""

import json
import os
import shutil
import subprocess

import pytest

from distributed_crawler_tpu.clients.native import (
    NativeTelegramClient,
    TelegramError,
    find_library,
)
from distributed_crawler_tpu.clients.mock_dc import MockDcServer

SEED = json.dumps({
    "channels": [{
        "username": "wirechan",
        "id": 4242,
        "title": "Wire Channel",
        "member_count": 900,
        "messages": [
            {"content": {"@type": "messageText",
                         "text": {"text": f"wire message {i}"}},
             "date": 1700000000 + i, "view_count": 10 + i}
            for i in range(5)
        ],
    }],
})


def _lib_available() -> bool:
    try:
        find_library()
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _lib_available(), reason="libdct_client.so not built")


@pytest.fixture
def server():
    srv = MockDcServer(seed_json=SEED, expected_code="24680").start()
    yield srv
    srv.close()


class TestAuthLifecycleOverSocket:
    def test_full_ladder_then_fetch(self, server):
        client = NativeTelegramClient(server_addr=server.address,
                                      conn_id="t1")
        try:
            client.authenticate("+15550001111", "24680")
            client.wait_ready(timeout_s=5.0)
            chat = client.search_public_chat("wirechan")
            assert chat.id == 4242 and chat.title == "Wire Channel"
            msgs = client.get_chat_history(chat.id, limit=3)
            assert len(msgs.messages) == 3
            assert msgs.total_count == 5
            assert "wire message" in \
                msgs.messages[0].content["text"]["text"]
        finally:
            client.close()
        assert server.auth_successes == 1

    def test_wrong_code_rejected_then_recovers(self, server):
        client = NativeTelegramClient(server_addr=server.address,
                                      conn_id="t2")
        try:
            with pytest.raises(TelegramError, match="PHONE_CODE_INVALID"):
                client.authenticate("+15550001111", "00000")
            # Ladder stays in WaitCode: the right code still lands.
            client._call({"@type": "checkAuthenticationCode",
                          "code": "24680"})
            client.wait_ready(timeout_s=5.0)
            assert client.search_public_chat("wirechan").id == 4242
        finally:
            client.close()

    def test_unauthorized_fetch_rejected(self, server):
        client = NativeTelegramClient(server_addr=server.address,
                                      conn_id="t3")
        try:
            with pytest.raises(TelegramError, match="UNAUTHORIZED"):
                client._call({"@type": "searchPublicChat",
                              "username": "wirechan"})
        finally:
            client.close()

    def test_password_leg(self):
        srv = MockDcServer(seed_json=SEED, expected_code="11111",
                           expected_password="hunter2").start()
        try:
            client = NativeTelegramClient(server_addr=srv.address,
                                          conn_id="t4")
            try:
                with pytest.raises(TelegramError,
                                   match="PASSWORD_HASH_INVALID"):
                    client.authenticate("+15550001111", "11111",
                                        password="wrong")
                client._call({"@type": "checkAuthenticationPassword",
                              "password": "hunter2"})
                client.wait_ready(timeout_s=5.0)
                assert client.search_public_chat("wirechan").id == 4242
            finally:
                client.close()
        finally:
            srv.close()

    def test_connect_refused_fails_fast(self):
        with pytest.raises(Exception, match="failed to create"):
            NativeTelegramClient(server_addr="127.0.0.1:1", conn_id="t5")

    def test_error_taxonomy_over_wire(self, server):
        client = NativeTelegramClient(server_addr=server.address,
                                      conn_id="t6")
        try:
            client.authenticate("+15550001111", "24680")
            client.wait_ready(timeout_s=5.0)
            with pytest.raises(TelegramError,
                               match="USERNAME_NOT_OCCUPIED"):
                client.search_public_chat("missing_channel")
        finally:
            client.close()


@pytest.mark.skipif(shutil.which("openssl") is None,
                    reason="openssl binary needed to mint the test cert")
class TestTlsTransport:
    def test_auth_and_fetch_over_tls(self):
        srv = MockDcServer(seed_json=SEED, expected_code="33333",
                           tls=True).start()
        try:
            client = NativeTelegramClient(server_addr=srv.address,
                                          tls=True, tls_insecure=True,
                                          sni="localhost", conn_id="tls1")
            try:
                client.authenticate("+15550002222", "33333")
                client.wait_ready(timeout_s=5.0)
                chat = client.search_public_chat("wirechan")
                assert chat.title == "Wire Channel"
                msgs = client.get_chat_history(chat.id, limit=5)
                assert len(msgs.messages) == 5
            finally:
                client.close()
        finally:
            srv.close()

    def test_tls_client_hello_is_chrome_shaped(self):
        """Capture the raw ClientHello the native TLS stream sends and
        assert the Chrome-fingerprint properties `native/net.h` encodes:
        TLS1.2 cipher ordering, SNI, ALPN h2+http/1.1, X25519-first
        groups (uTLS parity target: `utlstransport.go:19-57`)."""
        import socket
        import threading

        captured = {}
        lis = socket.socket()
        lis.bind(("127.0.0.1", 0))
        lis.listen(1)
        port = lis.getsockname()[1]

        def capture():
            conn, _ = lis.accept()
            conn.settimeout(3.0)
            data = b""
            try:
                while len(data) < 5:
                    data += conn.recv(4096)
                rec_len = int.from_bytes(data[3:5], "big")
                while len(data) < 5 + rec_len:
                    data += conn.recv(4096)
            except OSError:
                pass
            captured["hello"] = data
            conn.close()

        t = threading.Thread(target=capture)
        t.start()
        # The handshake will fail (capturer never answers) — expected.
        with pytest.raises(Exception):
            NativeTelegramClient(server_addr=f"127.0.0.1:{port}",
                                 tls=True, tls_insecure=True,
                                 sni="web.telegram.org", conn_id="fp1")
        t.join(timeout=5)
        lis.close()
        hello = captured.get("hello", b"")
        assert hello[:1] == b"\x16", "not a TLS handshake record"
        assert hello[5:6] == b"\x01", "not a ClientHello"

        # Parse cipher suites out of the ClientHello body.
        body = hello[9:]  # skip record(5) + hs type(1) + length(3)
        pos = 2 + 32  # client_version + random
        sid_len = body[pos]
        pos += 1 + sid_len
        cs_len = int.from_bytes(body[pos:pos + 2], "big")
        pos += 2
        suites = [int.from_bytes(body[pos + i:pos + i + 2], "big")
                  for i in range(0, cs_len, 2)]
        pos += cs_len
        # TLS1.3 suites first (Chrome order: 0x1301, 0x1302, 0x1303),
        # then Chrome's TLS1.2 list headed by ECDHE-ECDSA-AES128-GCM.
        tls13 = [s for s in suites if s in (0x1301, 0x1302, 0x1303)]
        assert tls13 == [0x1301, 0x1302, 0x1303]
        tls12 = [s for s in suites if s not in (0x1301, 0x1302, 0x1303)
                 and s != 0x00ff]  # minus EMPTY_RENEGOTIATION_INFO_SCSV
        assert tls12[:6] == [0xc02b, 0xc02f, 0xc02c, 0xc030,
                             0xcca9, 0xcca8], \
            f"TLS1.2 cipher order not Chrome's: {[hex(s) for s in tls12]}"

        raw = bytes(hello)
        assert b"web.telegram.org" in raw, "SNI missing"
        assert b"\x02h2" in raw and b"http/1.1" in raw, "ALPN missing"
        # X25519 (0x001d) appears before P-256 (0x0017) in groups.
        assert raw.find(b"\x00\x1d") != -1
        assert raw.find(b"\x00\x1d") < raw.find(b"\x00\x17")


@pytest.mark.skipif(shutil.which("openssl") is None,
                    reason="openssl binary needed to mint the test cert")
class TestChromeHttpTransport:
    """The validator's fingerprint-matched transport: native TLS GET
    against a local HTTPS server serving t.me-style HTML."""

    @pytest.fixture
    def https_server(self, tmp_path):
        import http.server
        import ssl
        import threading

        from distributed_crawler_tpu.clients.mock_dc import (
            make_self_signed_cert,
        )

        html = ('<html><head><title>Telegram: View @wirechan</title>'
                '</head><body>ok</body></html>')

        class Handler(http.server.BaseHTTPRequestHandler):
            seen_headers: list = []

            def do_GET(self):
                Handler.seen_headers.append(dict(self.headers))
                body = html.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        cert, key = make_self_signed_cert(str(tmp_path))
        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key)
        srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield srv, Handler
        srv.shutdown()

    def test_fetch_and_parse(self, https_server):
        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
            parse_channel_html,
        )

        srv, handler = https_server
        port = srv.server_address[1]
        status, body = chrome_transport(
            f"https://127.0.0.1:{port}/wirechan",
            {"User-Agent": "Mozilla/5.0 test-chrome"},
            tls_insecure=True)
        assert status == 200
        result = parse_channel_html(body.decode())
        assert result.status == "valid"
        assert handler.seen_headers[0]["User-Agent"] == \
            "Mozilla/5.0 test-chrome"

    def test_make_transport_selection(self):
        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
            make_transport,
            urllib_transport,
        )

        assert make_transport("urllib") is urllib_transport
        assert make_transport("") is urllib_transport
        assert callable(make_transport("chrome"))
        with pytest.raises(ValueError, match="unknown validator transport"):
            make_transport("curl")

    def test_validator_uses_configured_transport(self, https_server):
        """validate_channel_http end to end through the chrome transport."""
        import functools

        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
            validate_channel_http,
        )

        srv, _ = https_server
        port = srv.server_address[1]

        def transport(url, headers):
            # Redirect t.me to the local server, keeping the URL shape.
            username = url.rsplit("/", 1)[1]
            return chrome_transport(
                f"https://127.0.0.1:{port}/{username}", headers,
                tls_insecure=True)

        result = validate_channel_http("wirechan", transport=transport)
        assert result.status == "valid"

    def test_validator_base_url_routes_whole_pod(self, https_server,
                                                 monkeypatch):
        """The RunValidationLoop's DEFAULT validate_fn honors
        validator_base_url + validator_transport=chrome — the pod is
        drivable against a mirror without code injection."""
        import distributed_crawler_tpu.clients.http_validator as hv
        from distributed_crawler_tpu.config.crawler import CrawlerConfig
        from distributed_crawler_tpu.crawl.validator import (
            RunValidationLoop,
        )

        srv, _ = https_server
        port = srv.server_address[1]
        cfg = CrawlerConfig(
            platform="telegram",
            validator_transport="chrome",
            validator_base_url=f"https://127.0.0.1:{port}")

        class _SM:  # the loop only needs construction here
            pass

        # tls_insecure isn't reachable through config (production verifies
        # real certs); inject it at the transport layer — the same trust
        # override SSL_CERT_FILE provides operationally — and let the
        # loop's REAL default validate_fn do everything else.
        real = hv.chrome_transport
        monkeypatch.setattr(
            hv, "chrome_transport",
            lambda url, headers, **kw: real(
                url, headers, **{**kw, "tls_insecure": True}))
        loop = RunValidationLoop(_SM(), cfg)
        assert loop.validate_fn("wirechan").status == "valid"


class TestSeedDbAcquisition:
    """Pre-seeded client-DB tarball flow (VERDICT r2 missing #5; parity:
    `telegramhelper/client.go:232-260,433-533`)."""

    def _tarball(self, tmp_path, name="dbs.tar.gz"):
        import tarfile

        src = tmp_path / "src"
        src.mkdir(exist_ok=True)
        (src / "seed.json").write_text(SEED)
        path = tmp_path / name
        with tarfile.open(path, "w:gz") as tar:
            tar.add(src / "seed.json", arcname="db/seed.json")
        return str(path)

    def test_extract_into_unique_conn_dirs(self, tmp_path):
        from distributed_crawler_tpu.clients.native import (
            acquire_seed_db,
            fnv32,
        )

        tar = self._tarball(tmp_path)
        base = str(tmp_path / "dbs")
        seed1 = acquire_seed_db(f"file://{tar}", base, "conn-a")
        seed2 = acquire_seed_db(tar, base, "conn-b")
        assert seed1 != seed2
        assert f"conn_{fnv32('conn-a'):08x}" in seed1
        assert f"conn_{fnv32('conn-b'):08x}" in seed2
        assert json.loads(open(seed1).read())["channels"][0][
            "username"] == "wirechan"
        # Idempotent: second acquisition reuses the extracted dir.
        assert acquire_seed_db(tar, base, "conn-a") == seed1

    def test_pool_preload_from_tarball(self, tmp_path):
        from distributed_crawler_tpu.clients.native import (
            native_client_factory,
        )
        from distributed_crawler_tpu.clients.pool import ConnectionPool

        tar = self._tarball(tmp_path)
        factory = native_client_factory(
            db_source=tar, db_base_dir=str(tmp_path / "dbs"))
        pool = ConnectionPool(factory,
                              database_urls=["file:///a", "file:///b"])
        assert pool.initialize() == 2
        conn = pool.acquire()
        try:
            chat = conn.client.search_public_chat("wirechan")
            assert chat.title == "Wire Channel"
        finally:
            pool.release(conn)
        # Each connection got its own extracted database dir.
        dirs = [d for d in os.listdir(tmp_path / "dbs")
                if d.startswith("conn_")]
        assert len(dirs) == 2
        pool.close_all()

    def test_bad_scheme_rejected(self, tmp_path):
        from distributed_crawler_tpu.clients.native import acquire_seed_db
        from distributed_crawler_tpu.clients.native import (
            NativeClientError,
        )

        with pytest.raises(NativeClientError, match="file://"):
            acquire_seed_db("https://example.com/dbs.tgz",
                            str(tmp_path), "c1")

    def test_changed_source_reextracts(self, tmp_path):
        """A replaced/updated tarball at the same path must re-extract —
        stale conn dirs silently serving old seed data is the failure."""
        import time as time_mod

        from distributed_crawler_tpu.clients.native import acquire_seed_db

        tar = self._tarball(tmp_path)
        base = str(tmp_path / "dbs")
        seed1 = acquire_seed_db(tar, base, "conn-s")
        v1 = open(seed1).read()
        # Same source untouched: reuse (no re-extract).
        assert acquire_seed_db(tar, base, "conn-s") == seed1
        # Replace the tarball content (ensure a different mtime).
        time_mod.sleep(0.01)
        src = tmp_path / "src"
        (src / "seed.json").write_text(SEED.replace("wirechan", "newchan"))
        import tarfile as tarfile_mod
        with tarfile_mod.open(tar, "w:gz") as t:
            t.add(src / "seed.json", arcname="db/seed.json")
        os.utime(tar)
        seed2 = acquire_seed_db(tar, base, "conn-s")
        assert "newchan" in open(seed2).read()
        assert "newchan" not in v1

    def test_extract_without_filter_kwarg(self, tmp_path, monkeypatch):
        """Pythons without the `filter=` backport (<3.10.12/<3.11.4) still
        extract — via the manual path-safety fallback."""
        import tarfile as tarfile_mod

        from distributed_crawler_tpu.clients.native import acquire_seed_db

        orig = tarfile_mod.TarFile.extractall

        def no_filter(self, path=".", members=None, **kw):
            if "filter" in kw:
                raise TypeError("extractall() got an unexpected keyword "
                                "argument 'filter'")
            return orig(self, path=path, members=members)

        monkeypatch.setattr(tarfile_mod.TarFile, "extractall", no_filter)
        tar = self._tarball(tmp_path)
        seed = acquire_seed_db(tar, str(tmp_path / "dbs"), "conn-old-py")
        assert json.loads(open(seed).read())["channels"][0][
            "username"] == "wirechan"

    def test_traversal_tarball_rejected_without_filter(self, tmp_path,
                                                       monkeypatch):
        import tarfile as tarfile_mod

        from distributed_crawler_tpu.clients.native import (
            NativeClientError,
            acquire_seed_db,
        )

        def no_filter(self, path=".", members=None, **kw):
            if "filter" in kw:
                raise TypeError("no filter kwarg")
            raise AssertionError("unsafe tarball must not be extracted")

        monkeypatch.setattr(tarfile_mod.TarFile, "extractall", no_filter)
        evil = tmp_path / "evil.tar.gz"
        (tmp_path / "payload").write_text("x")
        with tarfile_mod.open(evil, "w:gz") as tar:
            tar.add(tmp_path / "payload", arcname="../escape.json")
        with pytest.raises(NativeClientError, match="unsafe path"):
            acquire_seed_db(str(evil), str(tmp_path / "dbs"), "conn-evil")

    def test_symlink_tarball_rejected_without_filter(self, tmp_path,
                                                     monkeypatch):
        """Symlink members can escape the staging dir on Pythons without
        `filter=`; the fallback refuses them outright."""
        import tarfile as tarfile_mod

        from distributed_crawler_tpu.clients.native import (
            NativeClientError,
            acquire_seed_db,
        )

        orig = tarfile_mod.TarFile.extractall

        def no_filter(self, path=".", members=None, **kw):
            if "filter" in kw:
                raise TypeError("no filter kwarg")
            return orig(self, path=path, members=members)

        monkeypatch.setattr(tarfile_mod.TarFile, "extractall", no_filter)
        evil = tmp_path / "links.tar.gz"
        with tarfile_mod.open(evil, "w:gz") as tar:
            link = tarfile_mod.TarInfo("db")
            link.type = tarfile_mod.SYMTYPE
            link.linkname = "/"
            tar.addfile(link)
        with pytest.raises(NativeClientError, match="link member"):
            acquire_seed_db(str(evil), str(tmp_path / "dbs"), "conn-sym")


@pytest.mark.skipif(shutil.which("openssl") is None,
                    reason="openssl binary needed to mint the test cert")
class TestHttpEdgeCases:
    def _serve(self, tmp_path, handler_cls):
        import http.server
        import ssl
        import threading

        from distributed_crawler_tpu.clients.mock_dc import (
            make_self_signed_cert,
        )

        cert, key = make_self_signed_cert(str(tmp_path))
        srv = http.server.HTTPServer(("127.0.0.1", 0), handler_cls)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key)
        srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_chunked_response_dechunked(self, tmp_path):
        """Transfer-Encoding: chunked bodies come back clean, framing
        stripped — even with a chunk boundary splitting the <title>."""
        import http.server

        html = ('<html><head><title>Telegram: View @wirechan</title>'
                '</head><body>chunky</body></html>')

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                self.send_response(200)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                # Split mid-<title> on purpose.
                for part in (html[:30], html[30:37], html[37:]):
                    data = part.encode()
                    self.wfile.write(
                        f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")

            def log_message(self, *a):
                pass

        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
            parse_channel_html,
        )

        srv = self._serve(tmp_path, Handler)
        try:
            status, body = chrome_transport(
                f"https://127.0.0.1:{srv.server_address[1]}/wirechan",
                {}, tls_insecure=True)
            assert status == 200
            assert body.decode() == html  # no chunk-size lines embedded
            assert parse_channel_html(body.decode()).status == "valid"
        finally:
            srv.shutdown()

    def test_chunked_body_containing_bare_zero_line(self, tmp_path):
        """Chunk DATA containing a lone '0' line must not be mistaken for
        the terminal chunk — completion is framing-walked."""
        import http.server

        html = ('<html><head><title>Telegram: View @wirechan</title>'
                '</head><body>count:\r\n0\r\nmore text after zero'
                '</body></html>')

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                self.send_response(200)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                data = html.encode()
                self.wfile.write(f"{len(data):x}\r\n".encode() + data +
                                 b"\r\n0\r\n\r\n")

            def log_message(self, *a):
                pass

        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
        )

        srv = self._serve(tmp_path, Handler)
        try:
            status, body = chrome_transport(
                f"https://127.0.0.1:{srv.server_address[1]}/wirechan",
                {}, tls_insecure=True)
            assert status == 200
            assert body.decode() == html  # nothing truncated at the '0'
        finally:
            srv.shutdown()

    def test_x_content_length_header_ignored(self, tmp_path):
        """Only the real Content-Length header frames the body."""
        import http.server

        html = ('<html><head><title>Telegram: View @wirechan</title>'
                '</head><body>long enough body text here</body></html>')

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = html.encode()
                self.send_response(200)
                self.send_header("X-Content-Length", "5")  # red herring
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
        )

        srv = self._serve(tmp_path, Handler)
        try:
            status, body = chrome_transport(
                f"https://127.0.0.1:{srv.server_address[1]}/wirechan",
                {}, tls_insecure=True)
            assert status == 200
            assert body.decode() == html  # not truncated to 5 bytes
        finally:
            srv.shutdown()

    def test_redirect_location_last_header_with_body(self, tmp_path):
        """Location as the FINAL header of a redirect that also carries a
        body: the extracted value must stop at the header block, not
        swallow the blank line + body into the redirect URL."""
        import http.server

        html = ('<html><head><title>Telegram: View @wirechan</title>'
                '</head><body>ok</body></html>')

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/wirechan":
                    stub = b"<html>moved</html>"
                    self.send_response(301)
                    self.send_header("Content-Length", str(len(stub)))
                    self.send_header("Location", "/s/wirechan")  # last header
                    self.end_headers()
                    self.wfile.write(stub)
                    return
                if self.path != "/s/wirechan":
                    self.send_error(404)
                    return
                body = html.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
        )

        srv = self._serve(tmp_path, Handler)
        try:
            status, body = chrome_transport(
                f"https://127.0.0.1:{srv.server_address[1]}/wirechan",
                {}, tls_insecure=True)
            assert status == 200
            assert b"View @wirechan" in body
        finally:
            srv.shutdown()

    def test_redirect_followed_like_urllib(self, tmp_path):
        import http.server

        html = ('<html><head><title>Telegram: View @wirechan</title>'
                '</head><body>ok</body></html>')

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/wirechan":
                    self.send_response(302)
                    self.send_header("Location", "/s/wirechan")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = html.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        from distributed_crawler_tpu.clients.http_validator import (
            chrome_transport,
        )

        srv = self._serve(tmp_path, Handler)
        try:
            status, body = chrome_transport(
                f"https://127.0.0.1:{srv.server_address[1]}/wirechan",
                {}, tls_insecure=True)
            assert status == 200  # followed the 302, like urllib does
            assert b"View @wirechan" in body
        finally:
            srv.shutdown()


class TestTransportErrorFastFail:
    def test_connection_loss_fails_calls_immediately(self, server):
        """After the server dies, calls raise the transport error at once
        instead of burning the receive timeout per call."""
        import time

        client = NativeTelegramClient(server_addr=server.address,
                                      conn_id="tf1")
        try:
            client.authenticate("+15550001111", "24680")
            client.wait_ready(timeout_s=5.0)
            server.close()  # yank the server mid-session
            t0 = time.monotonic()
            with pytest.raises(TelegramError,
                               match="connection|transport"):
                client.search_public_chat("wirechan")
            # Next call fails fast from the cached transport error.
            t1 = time.monotonic()
            with pytest.raises(TelegramError,
                               match="connection|transport"):
                client.search_public_chat("wirechan")
            assert time.monotonic() - t1 < 1.0
            assert t1 - t0 < client.receive_timeout_s
        finally:
            client.close()


class TestConcurrentConnections:
    def test_parallel_sessions_isolated(self, server):
        """Multiple clients authenticate and fetch concurrently; each
        session owns its state (per-connection engine isolation)."""
        import threading

        results = {}

        def session(n):
            c = NativeTelegramClient(server_addr=server.address,
                                     conn_id=f"cc{n}")
            try:
                c.authenticate(f"+1555000{n}", "24680")
                c.wait_ready(5.0)
                chat = c.search_public_chat("wirechan")
                msgs = c.get_chat_history(chat.id, limit=5)
                results[n] = len(msgs.messages)
            finally:
                c.close()

        threads = [threading.Thread(target=session, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert results == {0: 5, 1: 5, 2: 5, 3: 5}
        assert server.auth_successes == 4


class TestCrawlEngineOverWire:
    """The full parity proof at the network level: the crawl engine runs
    against a REMOTE native client — every TDLib-class call rides the wire
    protocol to the mock DC after a real auth ladder."""

    CRAWL_SEED = json.dumps({
        "channels": [
            {"username": "natchan", "title": "Native Chan",
             "member_count": 500, "description": "desc",
             "messages": [
                 {"date": 1700000000, "view_count": 9, "reply_count": 1,
                  "content": {"@type": "messageText",
                              "text": {"text": "hello @linked_chan",
                                       "entities": [
                                           {"type": {"@type":
                                                     "textEntityTypeMention"},
                                            "offset": 6, "length": 12}]}}},
                 {"date": 1700000100, "view_count": 4,
                  "content": {"@type": "messageText",
                              "text": {"text": "plain post",
                                       "entities": []}}},
             ]},
            {"username": "linked_chan", "title": "Linked",
             "member_count": 60,
             "messages": [
                 {"date": 1700000050, "view_count": 2,
                  "content": {"@type": "messageText",
                              "text": {"text": "leaf", "entities": []}}},
             ]},
        ],
    })

    def test_run_for_channel_over_socket(self, tmp_path):
        from distributed_crawler_tpu.config import CrawlerConfig
        from distributed_crawler_tpu.crawl.runner import run_for_channel
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        srv = MockDcServer(seed_json=self.CRAWL_SEED,
                           expected_code="777").start()
        client = NativeTelegramClient(server_addr=srv.address,
                                      conn_id="wirecrawl")
        try:
            client.authenticate("+15550009999", "777")
            client.wait_ready(5.0)

            sm = CompositeStateManager(StateConfig(
                crawl_id="wire1", crawl_execution_id="e1",
                storage_root=str(tmp_path), sql=SqlConfig(url=":memory:")))
            sm.initialize(["natchan"])
            cfg = CrawlerConfig(crawl_id="wire1", skip_media_download=True)
            page = sm.get_layer_by_depth(0)[0]
            discovered = run_for_channel(client, page, "", sm, cfg)
            assert page.status == "fetched"
            assert {p.url for p in discovered} == {"linked_chan"}
            jsonl = (tmp_path / "wire1" / "natchan" / "posts"
                     / "posts.jsonl")
            posts = [json.loads(line)
                     for line in jsonl.read_text().splitlines()]
            assert len(posts) == 2
            assert {p["view_count"] for p in posts} == {9, 4}
        finally:
            client.close()
            srv.close()
