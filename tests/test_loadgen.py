"""loadgen tests: seeded determinism, chaos timelines + controller,
ledger reconciliation, replay-from-bundle fidelity, the e2e SLO gate,
the loadtest CLI contract, and the multichip guaranteed-verdict wrapper
(ISSUE 6; docs/operations.md "Load testing & chaos")."""

import json
import time

import pytest

from distributed_crawler_tpu.bus.messages import (
    TOPIC_CHAOS,
    TOPIC_INFERENCE_BATCHES,
    ChaosMessage,
)
from distributed_crawler_tpu.loadgen.chaos import (
    ChaosBus,
    ChaosController,
    ChaosEngine,
    parse_duration_s,
    parse_fault,
    parse_timeline,
)
from distributed_crawler_tpu.loadgen.gate import (
    load_scenario,
    merge_overrides,
    run_scenario,
    scenario_names,
)
from distributed_crawler_tpu.loadgen.generator import (
    LoadGenConfig,
    SyntheticWorkload,
    workload_from_bundle,
    zipf_text,
)
from distributed_crawler_tpu.utils import flight


class RecordingBus:
    """Minimal bus double: remembers every publish."""

    def __init__(self):
        self.published = []  # (topic, payload)

    def publish(self, topic, payload):
        self.published.append((topic, payload))

    def payloads(self, topic):
        return [p for t, p in self.published if t == topic]


# ---------------------------------------------------------------------------
# generator: seeded determinism
# ---------------------------------------------------------------------------
class TestSeededDeterminism:
    def test_same_seed_identical_plan(self):
        """The headline property: same seed -> identical batch shapes AND
        identical arrival schedule (PlannedBatch is frozen, so == is deep)."""
        a = SyntheticWorkload(LoadGenConfig(seed=42, duration_s=3.0)).plan()
        b = SyntheticWorkload(LoadGenConfig(seed=42, duration_s=3.0)).plan()
        assert a == b
        assert [pb.offset_s for pb in a] == [pb.offset_s for pb in b]

    def test_different_seed_different_plan(self):
        a = SyntheticWorkload(LoadGenConfig(seed=1, duration_s=3.0)).plan()
        b = SyntheticWorkload(LoadGenConfig(seed=2, duration_s=3.0)).plan()
        assert a != b

    def test_poisson_offsets_monotonic_and_bounded(self):
        cfg = LoadGenConfig(seed=5, duration_s=2.0, rate_batches_per_s=20)
        plan = SyntheticWorkload(cfg).plan()
        offsets = [pb.offset_s for pb in plan]
        assert offsets == sorted(offsets)
        assert all(0 <= t < cfg.duration_s for t in offsets)
        # ~40 expected arrivals; a seeded run is a fixed draw, so just
        # require the order of magnitude (catches rate being ignored).
        assert 15 <= len(plan) <= 80

    def test_ramp_plan_has_no_offsets(self):
        cfg = LoadGenConfig(seed=0, arrival="ramp", ramp_batches=12)
        plan = SyntheticWorkload(cfg).plan()
        assert len(plan) == 12
        assert all(pb.offset_s is None for pb in plan)

    def test_record_shapes_respect_config(self):
        cfg = LoadGenConfig(seed=3, duration_s=2.0, records_per_batch=5,
                            max_words=40,
                            platform_mix={"telegram": 1.0})
        for pb in SyntheticWorkload(cfg).plan():
            assert len(pb.records) == 5
            for rec in pb.records:
                assert rec.platform == "telegram"
                assert 1 <= rec.words <= 40

    def test_platform_mix_both_platforms_present(self):
        cfg = LoadGenConfig(seed=9, duration_s=4.0, rate_batches_per_s=20,
                            records_per_batch=8,
                            platform_mix={"telegram": 0.5, "youtube": 0.5})
        platforms = {rec.platform
                     for pb in SyntheticWorkload(cfg).plan()
                     for rec in pb.records}
        assert platforms == {"telegram", "youtube"}

    def test_build_batch_deterministic_and_decodable(self):
        from distributed_crawler_tpu.bus.codec import RecordBatch

        cfg = LoadGenConfig(seed=7, duration_s=1.0)
        w1, w2 = SyntheticWorkload(cfg), SyntheticWorkload(cfg)
        b1 = w1.build_batch(w1.plan()[0])
        b2 = w2.build_batch(w2.plan()[0])
        p1, p2 = b1.posts(), b2.posts()
        assert [p.post_uid for p in p1] == [p.post_uid for p in p2]
        assert [p.description for p in p1] == [p.description for p in p2]
        again = RecordBatch.from_bytes(b1.to_bytes())
        assert [p.post_uid for p in again.posts()] == \
            [p.post_uid for p in p1]

    def test_validate_rejects_bad_config(self):
        with pytest.raises(ValueError, match="arrival"):
            LoadGenConfig(arrival="burst").validate()
        with pytest.raises(ValueError, match="duration_s"):
            LoadGenConfig(duration_s=0).validate()
        with pytest.raises(ValueError, match="rate_batches_per_s"):
            LoadGenConfig(rate_batches_per_s=0).validate()
        with pytest.raises(ValueError, match="unknown platforms"):
            LoadGenConfig(platform_mix={"tiktok": 1.0}).validate()
        with pytest.raises(ValueError, match="positive weight"):
            LoadGenConfig(platform_mix={}).validate()

    def test_open_loop_run_publishes_whole_plan(self):
        cfg = LoadGenConfig(seed=4, duration_s=0.4, rate_batches_per_s=30,
                            records_per_batch=2)
        w = SyntheticWorkload(cfg)
        bus = RecordingBus()
        stats = w.run(bus, record_flight=False)
        assert stats.batches == len(w.plan())
        assert stats.records == sum(len(pb.records) for pb in w.plan())
        assert len(bus.payloads(TOPIC_INFERENCE_BATCHES)) == stats.batches

    def test_closed_loop_needs_pending_fn(self):
        cfg = LoadGenConfig(seed=0, arrival="ramp", duration_s=0.2,
                            ramp_batches=3)
        with pytest.raises(ValueError, match="pending_fn"):
            SyntheticWorkload(cfg).run(RecordingBus())

    def test_zipf_text_word_count(self):
        assert len(zipf_text(3, 17).split()) == 17
        assert len(zipf_text(3, 0).split()) == 1  # floor at one word


# ---------------------------------------------------------------------------
# chaos: timeline parsing
# ---------------------------------------------------------------------------
class TestChaosParsing:
    def test_durations(self):
        assert parse_duration_s("2s") == 2.0
        assert parse_duration_s("1.5s") == 1.5
        assert parse_duration_s("200ms") == 0.2
        assert parse_duration_s("3") == 3.0
        with pytest.raises(ValueError, match="bad duration"):
            parse_duration_s("2m")

    def test_point_faults(self):
        f = parse_fault("at=2s kill tpu-1")
        assert (f.action, f.target, f.at_s, f.until_s) == \
            ("kill", "tpu-1", 2.0, None)
        assert not f.windowed
        s = parse_fault("at=3s stall tpu-1 1.5s")
        assert s.arg_s == 1.5

    def test_window_faults(self):
        f = parse_fault("from=5s..6s delay bus 200ms")
        assert f.windowed and f.at_s == 5.0 and f.until_s == 6.0
        assert f.arg_s == 0.2
        w = parse_fault("from=1s..2.5s wedge tpu-1")
        assert w.until_s == 2.5

    def test_parse_errors(self):
        for line, msg in [
            ("at=2s explode tpu-1", "unknown chaos action"),
            ("from=1s..2s kill tpu-1", "point fault"),
            ("at=2s delay bus 10ms", "needs a window"),
            ("sometime kill tpu-1", "bad anchor"),
            ("from=2s..1s drop bus", "empty window"),
            ("from=1s..2s delay tpu-1 10ms", "targets 'bus'"),
            ("at=2s poison bus", "targets 'batch'"),
            ("at=2s kill", "needs a target"),
            ("at=2s kill tpu-1 extra", "trailing tokens"),
            ("from=1s..2s delay bus", "needs a duration"),
            ("kill", "bad chaos line"),
        ]:
            with pytest.raises(ValueError, match=msg):
                parse_fault(line)

    def test_timeline_sorted_and_comments_skipped(self):
        faults = parse_timeline([
            "# the fault plan",
            "at=4s kill tpu-1",
            "",
            "from=1s..2s drop bus",
        ])
        assert [f.action for f in faults] == ["drop", "kill"]

    def test_down_is_windowed(self):
        f = parse_fault("from=1.2s..2.4s down orchestrator")
        assert f.windowed and f.action == "down"
        assert (f.target, f.at_s, f.until_s) == ("orchestrator", 1.2, 2.4)
        with pytest.raises(ValueError, match="needs a window"):
            parse_fault("at=2s down orchestrator")


# ---------------------------------------------------------------------------
# chaos: controller + bus + engine
# ---------------------------------------------------------------------------
class StubTarget:
    def __init__(self):
        self.calls = []

    def kill(self):
        self.calls.append("kill")

    def restart(self):
        self.calls.append("restart")

    def stall(self, seconds):
        self.calls.append(("stall", seconds))


class TestChaosController:
    def setup_method(self):
        flight.RECORDER.configure(capacity=1024)
        flight.RECORDER.reset()

    def test_every_fault_fires_once_and_unwinds(self):
        """The full action vocabulary through a fake clock: each fault
        applies exactly once, windows unwind cleanly, everything is
        flight-recorded, applications are announced as ChaosMessage."""
        target = StubTarget()
        inner = RecordingBus()
        cbus = ChaosBus(inner)
        announce = RecordingBus()
        timeline = parse_timeline([
            "at=1s kill tpu-1",
            "at=2s restart tpu-1",
            "at=3s stall tpu-1 1.5s",
            "from=4s..5s wedge tpu-1",
            "from=6s..7s delay bus 50ms",
            "from=8s..9s drop bus",
            "at=10s poison batch",
        ])
        ctl = ChaosController(timeline, targets={"tpu-1": target},
                              bus=cbus, publish_bus=announce)
        for t in [0.0, 0.5, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 6.5,
                  7.0, 8.0, 9.0, 10.0, 11.0, 11.0]:
            ctl.tick(now_s=t)
        assert ctl.done()
        assert target.calls == ["kill", "restart", ("stall", 1.5),
                                ("stall", 1.0)]  # wedge -> window stall
        # Windows unwound: the bus is clean for the next phase.
        assert cbus._delay_s == 0.0 and not cbus._dropping
        applies = [e for e in flight.RECORDER.events()
                   if e["kind"] == "chaos" and e["phase"] == "apply"]
        unwinds = [e for e in flight.RECORDER.events()
                   if e["kind"] == "chaos" and e["phase"] == "unwind"]
        assert len(applies) == len(timeline)          # each fired ONCE
        assert len(unwinds) == 3                      # wedge, delay, drop
        msgs = [ChaosMessage.from_dict(p)
                for p in announce.payloads(TOPIC_CHAOS)]
        assert [m.action for m in msgs] == \
            [f.action for f in timeline]
        for m in msgs:
            m.validate()

    def test_down_window_kills_then_restarts(self):
        """`down` = kill at window start, supervisor restart at window
        end — one line for the coordinator-outage pattern."""
        target = StubTarget()
        ctl = ChaosController(parse_timeline(["from=1s..2s down orch-x"]),
                              targets={"orch-x": target})
        ctl.tick(now_s=0.5)
        assert target.calls == []
        ctl.tick(now_s=1.1)
        assert target.calls == ["kill"]
        ctl.tick(now_s=2.1)
        assert target.calls == ["kill", "restart"]
        assert ctl.done()
        phases = [(e["action"], e["phase"])
                  for e in flight.RECORDER.events() if e["kind"] == "chaos"]
        assert ("down", "apply") in phases and ("down", "unwind") in phases

    def test_stop_mid_window_still_restarts_down_target(self):
        target = StubTarget()
        ctl = ChaosController(parse_timeline(["from=1s..50s down orch-x"]),
                              targets={"orch-x": target})
        ctl.tick(now_s=1.5)
        assert target.calls == ["kill"]
        ctl.stop()  # unwinds open windows: the target must come back
        assert target.calls == ["kill", "restart"]

    def test_unknown_target_rejected_at_construction(self):
        with pytest.raises(ValueError, match="unknown target"):
            ChaosController(parse_timeline(["at=1s kill ghost"]),
                            targets={})

    def test_bus_faults_need_a_chaos_bus(self):
        with pytest.raises(ValueError, match="needs a ChaosBus"):
            ChaosController(parse_timeline(["from=1s..2s drop bus"]),
                            targets={}, bus=None)

    def test_stop_unwinds_open_windows(self):
        cbus = ChaosBus(RecordingBus())
        ctl = ChaosController(parse_timeline(["from=0s..60s drop bus"]),
                              targets={}, bus=cbus)
        ctl.tick(now_s=1.0)   # window open, far from expiring
        assert cbus._dropping
        ctl.stop()
        assert not cbus._dropping

    def test_failed_apply_is_recorded_not_raised(self):
        class Broken:
            def kill(self):
                raise RuntimeError("no such process")

        ctl = ChaosController(parse_timeline(["at=0s kill tpu-1"]),
                              targets={"tpu-1": Broken()})
        ctl.tick(now_s=1.0)
        assert any(e.get("phase") == "error" for e in ctl.events)


class TestChaosBus:
    def _batch_payload(self, batch_id, uids):
        return {"batch_id": batch_id,
                "records": [{"post_uid": u} for u in uids]}

    def test_non_chaos_topic_passes_through(self):
        inner = RecordingBus()
        cbus = ChaosBus(inner)
        cbus.set_drop(True)
        cbus.publish("worker-status", {"records": "not-a-batch"})
        assert inner.published == [("worker-status",
                                    {"records": "not-a-batch"})]
        assert cbus.published == {}

    def test_drop_window_excludes_from_expected(self):
        inner = RecordingBus()
        cbus = ChaosBus(inner)
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b1", ["u1", "u2"]))
        cbus.set_drop(True)
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b2", ["u3"]))
        cbus.set_drop(False)
        assert len(inner.payloads(TOPIC_INFERENCE_BATCHES)) == 1
        assert cbus.dropped == ["b2"]
        assert sorted(cbus.expected_uids()) == ["u1", "u2"]

    def test_poison_fires_once_and_mangles_records(self):
        inner = RecordingBus()
        cbus = ChaosBus(inner)
        cbus.poison_next()
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b1", ["u1", "u2"]))
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b2", ["u3"]))
        sent = inner.payloads(TOPIC_INFERENCE_BATCHES)
        assert sent[0]["records"] == [None, None]  # delivered but broken
        assert sent[1]["records"] == [{"post_uid": "u3"}]
        assert cbus.poisoned == ["b1"]
        assert cbus.expected_uids() == ["u3"]

    def test_drop_window_does_not_consume_scheduled_poison(self):
        """A poison scheduled inside a drop window waits for the first
        batch that actually goes out — the drop must not swallow it."""
        inner = RecordingBus()
        cbus = ChaosBus(inner)
        cbus.set_drop(True)
        cbus.poison_next()
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b1", ["u1"]))   # dropped
        cbus.set_drop(False)
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b2", ["u2"]))   # poisoned
        assert cbus.dropped == ["b1"]
        assert cbus.poisoned == ["b2"]
        assert inner.payloads(TOPIC_INFERENCE_BATCHES)[0]["records"] == \
            [None]

    def test_delay_applies_to_batch_traffic(self):
        inner = RecordingBus()
        cbus = ChaosBus(inner)
        cbus.set_delay(0.05)
        t0 = time.monotonic()
        cbus.publish(TOPIC_INFERENCE_BATCHES,
                     self._batch_payload("b1", ["u1"]))
        assert time.monotonic() - t0 >= 0.05
        assert len(inner.payloads(TOPIC_INFERENCE_BATCHES)) == 1

    def test_attribute_passthrough(self):
        inner = RecordingBus()
        inner.custom = 7
        assert ChaosBus(inner).custom == 7


class TestChaosEngine:
    class FakeEngine:
        def run(self, texts, pack=False):
            return ("ran", len(texts), pack)

        def run_tokenized(self, token_lists, pack=False):
            return ("tok", len(token_lists), pack)

        def warmup(self, buckets=None, pack=False):
            return "warm"

    def test_passthrough_and_signature(self):
        import inspect

        eng = ChaosEngine(self.FakeEngine())
        assert eng.run(["a", "b"], pack=True) == ("ran", 2, True)
        assert eng.run_tokenized([[1]], pack=False) == ("tok", 1, False)
        assert eng.warmup() == "warm"
        # TPUWorker probes `pack` by name on the proxy's own signature.
        assert "pack" in inspect.signature(eng.run).parameters

    def test_block_for_blocks_calls(self):
        eng = ChaosEngine(self.FakeEngine())
        eng.block_for(0.08)
        t0 = time.monotonic()
        eng.run(["x"])
        assert time.monotonic() - t0 >= 0.07


# ---------------------------------------------------------------------------
# replay: a recorded run is a reproducible workload
# ---------------------------------------------------------------------------
class TestReplay:
    def test_bundle_replay_matches_original_within_1pct(self, tmp_path):
        """ISSUE 6 acceptance: replay reproduces a recorded bundle's
        workload — batch count and total token (word) volume within 1%
        of the original run, arrival span preserved."""
        flight.RECORDER.configure(capacity=1024)
        flight.RECORDER.reset()
        cfg = LoadGenConfig(seed=21, duration_s=0.6,
                            rate_batches_per_s=25, records_per_batch=3)
        original = SyntheticWorkload(cfg)
        stats = original.run(RecordingBus())  # flight-records each batch
        assert stats.batches > 3
        path = flight.RECORDER.dump("loadgen_replay_test",
                                    dump_dir=str(tmp_path))
        assert path is not None

        replay = workload_from_bundle(path)
        totals = replay.totals()
        assert totals["batches"] == stats.batches
        assert totals["records"] == stats.records
        assert abs(totals["words"] - stats.words) <= \
            max(1, 0.01 * stats.words)
        # Arrival gaps survive: offsets are monotonic and the replay's
        # span stays within 1% + scheduler jitter of the recorded one.
        offsets = [pb.offset_s for pb in replay.plan()]
        assert offsets == sorted(offsets)
        recorded_span = stats.last_at - stats.first_at
        assert abs((offsets[-1] - offsets[0]) - recorded_span) \
            <= 0.01 * recorded_span + 0.05

    def test_replay_of_replay_is_identical(self, tmp_path):
        """Replaying a bundle twice gives the SAME plan (replay is a
        plan, not a re-draw)."""
        flight.RECORDER.configure(capacity=1024)
        flight.RECORDER.reset()
        cfg = LoadGenConfig(seed=2, duration_s=0.4, rate_batches_per_s=20)
        SyntheticWorkload(cfg).run(RecordingBus())
        path = flight.RECORDER.dump("loadgen_replay_twice",
                                    dump_dir=str(tmp_path))
        assert workload_from_bundle(path).plan() == \
            workload_from_bundle(path).plan()

    def test_organic_bundle_via_dispatch_spans(self, tmp_path):
        bundle = {
            "flight": [],
            "traces": {"traces": [
                {"spans": [
                    {"name": "orchestrator.dispatch", "start_wall": 100.0,
                     "attrs": {"records": 4}},
                    {"name": "orchestrator.dispatch", "start_wall": 100.5,
                     "attrs": {"records": 2}},
                ]},
            ]},
        }
        path = tmp_path / "organic.json"
        path.write_text(json.dumps(bundle))
        replay = workload_from_bundle(str(path), mean_words=10)
        totals = replay.totals()
        assert totals["batches"] == 2
        assert totals["records"] == 6
        assert totals["words"] == 60
        assert [pb.offset_s for pb in replay.plan()] == [0.0, 0.5]

    def test_empty_bundle_rejected(self, tmp_path):
        path = tmp_path / "empty.json"
        path.write_text(json.dumps({"flight": [], "traces": {}}))
        with pytest.raises(ValueError, match="nothing to replay"):
            workload_from_bundle(str(path))


# ---------------------------------------------------------------------------
# gate: scenario plumbing
# ---------------------------------------------------------------------------
class TestScenarioPlumbing:
    def test_checked_in_scenarios_parse(self):
        names = scenario_names()
        assert {"steady-state", "kill-worker", "backend-wedge"} <= set(names)
        for name in names:
            sc = load_scenario(name)
            parse_timeline(sc.get("chaos", []))
            cfg = LoadGenConfig(**sc.get("load", {}))
            cfg.validate()
            assert SyntheticWorkload(cfg).plan()
            assert "gate" in sc

    def test_unknown_scenario_lists_known(self):
        with pytest.raises(ValueError, match="steady-state"):
            load_scenario("no-such-scenario")

    def test_merge_overrides_deep(self):
        base = {"load": {"seed": 1, "rate_batches_per_s": 5},
                "gate": {"max_lost": 0}}
        out = merge_overrides(base, {"load": {"seed": 9}})
        assert out["load"] == {"seed": 9, "rate_batches_per_s": 5}
        assert out["gate"] == {"max_lost": 0}
        assert base["load"]["seed"] == 1  # original untouched

    def test_kill_faults_require_grpc_bus(self):
        sc = {"name": "x", "bus": "inmemory",
              "chaos": ["at=1s kill tpu-1"], "load": {"duration_s": 0.1}}
        with pytest.raises(ValueError, match="grpc"):
            run_scenario(sc)


# ---------------------------------------------------------------------------
# gate: end-to-end acceptance
# ---------------------------------------------------------------------------
class TestGateE2E:
    def test_kill_worker_scenario_breach_and_recovery(self):
        """ISSUE 6 acceptance: the kill-worker scenario — worker killed
        mid-stream on the gRPC bus, restarted under load — ends with
        zero lost/duplicated items, a batch_age SLO breach during the
        fault window, recovery (tail p95 under budget), verdict PASS."""
        verdict = run_scenario(load_scenario("kill-worker"))
        assert verdict["status"] == "pass", verdict["checks"]
        assert verdict["lost"] == 0
        assert verdict["duplicates"] == 0
        assert verdict["fault_breaches"].get("batch_age", 0) > 0
        assert verdict["tail_breaches"] == {}
        assert verdict["worker_generations"] == 2
        budget = verdict["checks"]["tail_queue_wait_p95_ms"]
        assert budget["ok"] and budget["value"] <= budget["budget"]
        assert verdict["checks"]["endpoint_cluster"]["ok"]

    def test_kill_orchestrator_scenario_resumes_from_journal(self):
        """ISSUE 7 acceptance: the kill-orchestrator scenario — the
        coordinator dies mid-run on the gRPC bus and a fresh generation
        resumes from its journal.  Zero lost/duplicated records by
        post_uid reconciliation (the record stream must not depend on
        coordinator liveness), orchestrator-side id reconciliation
        (every page terminal exactly once), the kill/resume flight
        events, and the recovery tail inside its p95 budgets."""
        verdict = run_scenario(load_scenario("kill-orchestrator"))
        assert verdict["status"] == "pass", verdict["checks"]
        assert verdict["lost"] == 0 and verdict["duplicates"] == 0
        orch = verdict["orchestrator"]
        assert orch["generations"] == 2 and orch["resumed"]
        assert orch["pages_by_status"] == {"fetched": 2}
        assert orch["completed_items"] == 2
        assert verdict["checks"]["orch_pages_lost"]["ok"]
        assert verdict["checks"]["orch_result_duplicates"]["ok"]
        assert verdict["checks"]["flight_orch_kill"]["ok"]
        assert verdict["checks"]["flight_orch_resume"]["ok"]
        assert verdict["tail_breaches"] == {}
        budget = verdict["checks"]["tail_queue_wait_p95_ms"]
        assert budget["ok"] and budget["value"] <= budget["budget"]

    def test_replay_through_gate_loses_nothing(self, tmp_path):
        """The dump-bundle → replay workflow end to end: a recorded run
        replayed through run_scenario reconciles clean (the replay
        workload's own crawl_id is part of the id reconciliation) and
        offers the identical workload."""
        # The bundle replays EVERY loadgen_batch event in the ring —
        # drop what earlier tests recorded so it carries only this run.
        flight.RECORDER.reset()
        sc = {
            "name": "tiny-replay", "bus": "inmemory",
            "engine": {"model": "tiny", "n_labels": 2, "batch_size": 4,
                       "buckets": [32]},
            "worker": {"worker_id": "tpu-1", "heartbeat_s": 0.5,
                       "write_embeddings": False, "stall_warn_s": 0},
            "load": {"seed": 3, "duration_s": 0.5,
                     "rate_batches_per_s": 12, "records_per_batch": 2},
            "tail": {"batches": 1, "gap_s": 0.02},
            "gate": {"max_lost": 0, "max_duplicates": 0},
        }
        first = run_scenario(sc)
        assert first["status"] == "pass", first["checks"]
        path = flight.RECORDER.dump("loadgen_gate_replay",
                                    dump_dir=str(tmp_path))
        replay = workload_from_bundle(path)
        assert replay.totals()["batches"] == first["published"]["batches"]
        second = run_scenario(sc, workload=replay)
        assert second["status"] == "pass", second["checks"]
        assert second["lost"] == 0 and second["duplicates"] == 0
        assert second["published"]["batches"] == \
            first["published"]["batches"]
        assert second["published"]["words"] == first["published"]["words"]

    def test_envelope_failure_yields_fail_verdict(self):
        """An impossible envelope fails the named check but still returns
        a full verdict (the gate judges, it does not crash)."""
        sc = {
            "name": "tiny-fail", "bus": "inmemory",
            "engine": {"model": "tiny", "n_labels": 2, "batch_size": 4,
                       "buckets": [32]},
            "worker": {"worker_id": "tpu-1", "heartbeat_s": 0.5,
                       "write_embeddings": False, "stall_warn_s": 0},
            "load": {"seed": 1, "duration_s": 0.4,
                     "rate_batches_per_s": 10, "records_per_batch": 2},
            "tail": {"batches": 2, "gap_s": 0.02},
            "gate": {"max_lost": 0,
                     "goodput_min_posts_per_s": 10_000_000},
        }
        verdict = run_scenario(sc)
        assert verdict["status"] == "fail"
        assert not verdict["checks"]["goodput_posts_per_s"]["ok"]
        assert verdict["checks"]["lost"]["ok"]
        assert verdict["lost"] == 0


# ---------------------------------------------------------------------------
# loadtest CLI: the one-JSON-line contract
# ---------------------------------------------------------------------------
class TestLoadtestCli:
    def _main(self, argv, capsys):
        from tools import loadtest

        rc = loadtest.main(argv)
        return rc, capsys.readouterr().out.strip().splitlines()

    def test_list(self, capsys):
        rc, lines = self._main(["--list"], capsys)
        assert rc == 0
        assert any(line.startswith("steady-state") for line in lines)

    def test_smoke_verdict(self, capsys):
        rc, lines = self._main(["--smoke"], capsys)
        assert rc == 0
        verdict = json.loads(lines[-1])
        assert verdict["status"] == "pass"
        assert "kill-worker" in verdict["scenarios"]

    def test_unknown_scenario_still_emits_json(self, capsys):
        rc, lines = self._main(["--scenario", "no-such"], capsys)
        assert rc == 1
        verdict = json.loads(lines[-1])
        assert verdict["status"] == "error"
        assert "no-such" in verdict["error"]

    def test_parse_mix_and_gate(self, tmp_path):
        from tools.loadtest import _parse_gate, _parse_mix

        assert _parse_mix("telegram=0.8,youtube=0.2") == \
            {"telegram": 0.8, "youtube": 0.2}
        with pytest.raises(ValueError, match="name=weight"):
            _parse_mix("telegram")
        assert _parse_gate('{"max_lost": 1}') == {"max_lost": 1}
        gate_file = tmp_path / "gate.json"
        gate_file.write_text('{"batch_p95_ms": 9}')
        assert _parse_gate(f"@{gate_file}") == {"batch_p95_ms": 9}
        with pytest.raises(ValueError, match="JSON object"):
            _parse_gate("[1]")

    def test_config_file_supplies_defaults_flags_win(self, tmp_path):
        """The loadgen.* `_KEY_MAP` keys resolve through the cli.py
        precedence chain: config file < explicit flag."""
        from tools.loadtest import _resolve, build_parser

        cfg = tmp_path / "conf.yaml"
        cfg.write_text(
            "loadgen:\n"
            "  scenario: backend-wedge\n"
            "  seed: 123\n"
            "  rate_batches_per_s: 7\n"
            '  platform_mix: "telegram=0.6,youtube=0.4"\n'
            '  gate: \'{"max_lost": 2}\'\n')
        args = build_parser().parse_args(["--config", str(cfg)])
        name, overrides = _resolve(args)
        assert name == "backend-wedge"
        assert overrides["load"]["seed"] == 123
        assert overrides["load"]["rate_batches_per_s"] == 7.0
        assert overrides["load"]["platform_mix"] == \
            {"telegram": 0.6, "youtube": 0.4}
        assert overrides["gate"] == {"max_lost": 2}

        args = build_parser().parse_args(
            ["--config", str(cfg), "--scenario", "steady-state",
             "--seed", "9"])
        name, overrides = _resolve(args)
        assert name == "steady-state"
        assert overrides["load"]["seed"] == 9

    def test_zero_config_values_keep_scenario(self, tmp_path):
        """config.example.yaml's inert defaults (0 / "") must not
        override the scenario's own load block."""
        from tools.loadtest import _resolve, build_parser

        cfg = tmp_path / "conf.yaml"
        cfg.write_text("loadgen:\n  seed: 0\n  duration_s: 0\n"
                       '  arrival: ""\n  rate_batches_per_s: 0\n'
                       '  platform_mix: ""\n  gate: ""\n')
        args = build_parser().parse_args(["--config", str(cfg)])
        _, overrides = _resolve(args)
        assert overrides == {"load": {}}


# ---------------------------------------------------------------------------
# multichip probe: the guaranteed-verdict wrapper (MULTICHIP_r01 fix)
# ---------------------------------------------------------------------------
class TestMultichipVerdict:
    def _patch(self, monkeypatch, outcomes):
        import __graft_entry__ as g

        calls = []

        def fake_child(n_devices, timeout_s, legs="all"):
            calls.append({"n": n_devices, "timeout_s": timeout_s,
                          "legs": legs})
            return outcomes[len(calls) - 1]

        monkeypatch.setattr(g, "_dryrun_child", fake_child)
        return g, calls

    def test_full_run_ok_no_retry(self, monkeypatch):
        g, calls = self._patch(monkeypatch, [(True, "")])
        verdict = g.dryrun_verdict(8)
        assert verdict["status"] == "ok"
        assert verdict["legs"] == "all"
        assert "sized_down" not in verdict
        assert len(calls) == 1

    def test_timeout_falls_back_to_sized_down_core(self, monkeypatch):
        """The MULTICHIP_r01 rc=124 mode: the full run times out, ONE
        sized-down retry (fewer devices, core leg, smaller budget) still
        produces a parseable ok verdict."""
        g, calls = self._patch(
            monkeypatch, [(False, "timed out after 360s"), (True, "")])
        verdict = g.dryrun_verdict(8)
        assert verdict["status"] == "ok"
        assert verdict["sized_down"]["ok"]
        assert verdict["sized_down"]["legs"] == "core"
        assert verdict["full_run_error"].startswith("timed out")
        assert calls[1]["n"] == g.MULTICHIP_RETRY_DEVICES
        assert calls[1]["legs"] == "core"
        assert calls[1]["timeout_s"] == g.MULTICHIP_RETRY_S

    def test_both_failures_still_yield_verdict(self, monkeypatch):
        g, _ = self._patch(
            monkeypatch, [(False, "timed out after 360s"),
                          (False, "rc=1: boom")])
        verdict = g.dryrun_verdict(8)
        assert verdict["status"] == "error"
        assert "full:" in verdict["error"] and "sized-down:" in verdict["error"]
        json.dumps(verdict)  # the contract: always JSON-serializable

    def test_retry_never_exceeds_requested_devices(self, monkeypatch):
        g, calls = self._patch(monkeypatch, [(False, "x"), (True, "")])
        g_retry = g.dryrun_verdict(1)
        assert g_retry["sized_down"]["n_devices"] == 1
        assert calls[1]["n"] == 1
