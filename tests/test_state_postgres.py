"""Live-PostgreSQL conformance battery for ``DbApiBinding`` (VERDICT r2 #1).

Opt-in: set ``POSTGRES_DSN`` (e.g. ``postgresql://user:pw@host/db``) and the
full ``SqlGraphStore`` claim battery — the `FOR UPDATE SKIP LOCKED` path the
reference relied on (`state/daprstate.go:3944-4034`) — runs against a real
server.  Unset, every test skips so CI without a socket stays green.

The driver is discovered at runtime (psycopg 3, then psycopg2, then pg8000);
with a DSN set but no driver installed the tests fail loudly rather than
skip, so a misconfigured CI job cannot silently pass.
"""

import concurrent.futures
import os
import uuid

import pytest

from distributed_crawler_tpu.state.datamodels import (
    PendingEdge,
    PendingEdgeBatch,
)
from distributed_crawler_tpu.state.sqlstore import DbApiBinding, SqlGraphStore

DSN = os.environ.get("POSTGRES_DSN", "")

pytestmark = pytest.mark.skipif(
    not DSN, reason="POSTGRES_DSN not set; live-PG conformance is opt-in")


def _connect():
    try:
        import psycopg  # psycopg 3

        return psycopg.connect(DSN), "format"
    except ImportError:
        pass
    try:
        import psycopg2

        return psycopg2.connect(DSN), "format"
    except ImportError:
        pass
    try:
        import pg8000.dbapi

        return pg8000.dbapi.connect(DSN), "format"
    except ImportError:
        raise RuntimeError(
            "POSTGRES_DSN is set but no PG driver (psycopg/psycopg2/pg8000) "
            "is importable — install one or unset the DSN")


@pytest.fixture
def store():
    """A SqlGraphStore on a throwaway PG schema, dropped after the test."""
    conn, paramstyle = _connect()
    schema = "dct_test_" + uuid.uuid4().hex[:12]
    with conn.cursor() as cur:
        cur.execute(f"CREATE SCHEMA {schema}")
        cur.execute(f"SET search_path TO {schema}")
    conn.commit()

    def factory():
        c, _ = _connect()
        with c.cursor() as cur:
            cur.execute(f"SET search_path TO {schema}")
        c.commit()
        return c

    binding = DbApiBinding(factory, paramstyle=paramstyle,
                           dialect="postgres")
    s = SqlGraphStore(binding, "pg1")
    s.ensure_schema()
    yield s
    binding.close()
    with conn.cursor() as cur:
        cur.execute(f"DROP SCHEMA {schema} CASCADE")
    conn.commit()
    conn.close()


class TestLivePostgresConformance:
    def test_schema_applies(self, store):
        # ensure_schema ran in the fixture; idempotency check:
        store.ensure_schema()

    def test_edge_claim_battery(self, store):
        for b in range(5):
            store.create_pending_batch(PendingEdgeBatch(
                batch_id=f"b{b}", crawl_id="pg1", source_channel="src",
                sequence_id=f"s{b}"))
            for e in range(20):
                store.insert_pending_edge(PendingEdge(
                    batch_id=f"b{b}", crawl_id="pg1",
                    destination_channel=f"dst{b}_{e}",
                    source_channel="src", sequence_id=f"s{b}"))

        def worker():
            claimed = []
            while True:
                edges = store.claim_pending_edges(7)
                if not edges:
                    return claimed
                claimed.extend(e.pending_id for e in edges)

        with concurrent.futures.ThreadPoolExecutor(4) as ex:
            outs = [f.result() for f in
                    [ex.submit(worker) for _ in range(4)]]
        all_claims = [pid for out in outs for pid in out]
        assert len(all_claims) == 100
        assert len(set(all_claims)) == 100, "SKIP LOCKED double-claim"

    def test_walkback_batch_claims(self, store):
        for b in range(8):
            store.create_pending_batch(PendingEdgeBatch(
                batch_id=f"wb{b}", crawl_id="pg1", source_channel="src",
                sequence_id=f"s{b}"))
            store.close_pending_batch(f"wb{b}")
        seen = []
        while True:
            batch, _ = store.claim_walkback_batch()
            if batch is None:
                break
            seen.append(batch.batch_id)
        assert sorted(seen) == sorted(f"wb{b}" for b in range(8))

    def test_discovered_channel_single_winner(self, store):
        assert store.claim_discovered_channel("chanx", "pg1")
        assert not store.claim_discovered_channel("chanx", "pg1")
