"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's hermetic test strategy (SURVEY.md §4): no real
Telegram/YouTube/bus/DB — and, new for the TPU build, no real TPU: multi-chip
code paths run against a virtual 8-device CPU backend so sharding logic is
exercised in CI.
"""

import os

# Overrides (not setdefault): the host environment may preset JAX_PLATFORMS
# to a real accelerator tunnel — and a sitecustomize may have imported jax
# already, freezing the env-var snapshot — so force the config directly too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:
    import jax  # noqa: E402  (after env setup on purpose)

    jax.config.update("jax_platforms", "cpu")
    try:
        # XLA_FLAGS may have been frozen by a pre-import; this config is
        # honored any time before CPU backend initialization (and agrees
        # with the flag when both are set).
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass  # backend already initialized (flag took effect) or old jax
except ImportError:  # jax-less env: non-TPU tests still collect and run
    pass

# Runtime lock-order witness (ISSUE 18): CRAWLINT_LOCKWITNESS=1 arms the
# creation-site interposition HERE — at conftest import, before any
# package module is imported — so every lock the suite's workers,
# brokers, and registries create is graphed.  The package __init__ chain
# above this import is docstring-only, so no package lock predates it.
if os.environ.get("CRAWLINT_LOCKWITNESS", "") == "1":
    from distributed_crawler_tpu.utils import lockwitness as _lockwitness

    _lockwitness.install()


def pytest_addoption(parser):
    parser.addoption(
        "--lockwitness", action="store_true", default=False,
        help="arm the runtime lock-order witness "
             "(distributed_crawler_tpu/utils/lockwitness.py) for this "
             "run; equivalent to CRAWLINT_LOCKWITNESS=1 but later — "
             "module-level locks of already-imported modules are not "
             "wrapped")


def pytest_configure(config):
    if config.getoption("--lockwitness"):
        from distributed_crawler_tpu.utils import lockwitness

        lockwitness.install()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    from distributed_crawler_tpu.utils import lockwitness

    if not lockwitness.enabled():
        return
    terminalreporter.write_line(lockwitness.WITNESS.summary_line())
    out = os.environ.get("CRAWLINT_LOCKWITNESS_OUT", "")
    if out:
        lockwitness.WITNESS.dump(out)
        terminalreporter.write_line(
            f"lockwitness: report written to {out} "
            "(render: python -m tools.analyze --lock-report)")


def pytest_sessionfinish(session, exitstatus):
    """CRAWLINT_LOCKWITNESS_STRICT=1: a witnessed lock-order cycle fails
    the session even when every test passed."""
    if os.environ.get("CRAWLINT_LOCKWITNESS_STRICT", "") != "1":
        return
    from distributed_crawler_tpu.utils import lockwitness

    if lockwitness.enabled() and lockwitness.WITNESS.cycle_count() > 0:
        try:
            session.exitstatus = 1
        except Exception:
            pass
