"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's hermetic test strategy (SURVEY.md §4): no real
Telegram/YouTube/bus/DB — and, new for the TPU build, no real TPU: multi-chip
code paths run against a virtual 8-device CPU backend so sharding logic is
exercised in CI.
"""

import os

# Overrides (not setdefault): the host environment may preset JAX_PLATFORMS
# to a real accelerator tunnel — and a sitecustomize may have imported jax
# already, freezing the env-var snapshot — so force the config directly too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:
    import jax  # noqa: E402  (after env setup on purpose)

    jax.config.update("jax_platforms", "cpu")
    try:
        # XLA_FLAGS may have been frozen by a pre-import; this config is
        # honored any time before CPU backend initialization (and agrees
        # with the flag when both are set).
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass  # backend already initialized (flag took effect) or old jax
except ImportError:  # jax-less env: non-TPU tests still collect and run
    pass
