"""CLI tests: flag precedence, date parsing, validation, mode dispatch.

Reference analogs: main_test.go (sampling validation matrix, time parsing)
and the viper precedence wiring of main.go:185-520.
"""

import json
import shutil

import pytest

from distributed_crawler_tpu.cli import (
    build_parser,
    collect_urls,
    main,
    resolve_config,
)


def parse(argv):
    return build_parser().parse_args(argv)


def resolve(argv, env=None):
    return resolve_config(parse(argv), env=env or {})


class TestPrecedence:
    def test_flag_beats_env(self):
        cfg, _ = resolve(["--concurrency", "7", "--urls", "a"],
                         env={"CRAWLER_CRAWLER_CONCURRENCY": "3"})
        assert cfg.concurrency == 7

    def test_env_beats_default(self):
        cfg, _ = resolve(["--urls", "a"],
                         env={"CRAWLER_CRAWLER_CONCURRENCY": "3"})
        assert cfg.concurrency == 3

    def test_config_file(self, tmp_path):
        f = tmp_path / "config.yaml"
        f.write_text("crawler:\n  maxposts: 42\n  platform: telegram\n")
        cfg, _ = resolve(["--config", str(f), "--urls", "a"])
        assert cfg.max_posts == 42

    def test_missing_explicit_config_file_errors(self):
        with pytest.raises(FileNotFoundError):
            resolve(["--config", "/nonexistent/config.yaml", "--urls", "a"])

    def test_defaults(self):
        cfg, _ = resolve(["--urls", "a"])
        assert cfg.max_pages == 108000
        assert cfg.min_users == 100
        assert cfg.walkback_rate == 15
        assert cfg.platform == "telegram"
        assert cfg.sampling_method == "channel"
        assert cfg.combine_trigger_size == 170 * 1024 * 1024


class TestDateWindows:
    def test_date_between(self):
        cfg, _ = resolve(["--date-between", "2025-01-01,2025-02-01",
                          "--urls", "a"])
        assert cfg.date_between_min.year == 2025
        assert cfg.date_between_max.month == 2

    def test_time_ago(self):
        cfg, _ = resolve(["--time-ago", "30d", "--urls", "a"])
        assert cfg.post_recency is not None

    def test_min_post_date(self):
        cfg, _ = resolve(["--min-post-date", "2024-06-15", "--urls", "a"])
        assert cfg.min_post_date.day == 15

    def test_date_between_wins(self):
        cfg, _ = resolve(["--date-between", "2025-01-01,2025-02-01",
                          "--time-ago", "7d", "--urls", "a"])
        assert cfg.date_between_min is not None
        assert cfg.post_recency is None

    def test_max_crawl_duration(self):
        cfg, _ = resolve(["--max-crawl-duration", "1h30m", "--urls", "a"])
        assert cfg.max_crawl_duration_s == 5400.0


class TestValidation:
    def test_invalid_platform_sampling_combo(self):
        with pytest.raises(ValueError, match="not supported"):
            resolve(["--platform", "youtube", "--sampling", "random-walk",
                     "--urls", "a"])

    def test_random_walk_needs_seeds_xor_seed_size(self):
        with pytest.raises(ValueError, match="seed"):
            resolve(["--sampling", "random-walk"])
        cfg, _ = resolve(["--sampling", "random-walk", "--seed-size", "5"])
        assert cfg.seed_size == 5

    def test_channel_requires_urls(self):
        with pytest.raises(ValueError):
            resolve([])

    def test_validate_only_needs_no_urls(self):
        cfg, _ = resolve(["--validate-only", "--sampling", "random-walk"])
        assert cfg.validate_only

    def test_worker_mode_defers_urls(self):
        # Work items arrive over the bus, so worker mode needs no seed URLs
        # (orchestrator mode still does — it seeds the crawl with them).
        cfg = resolve(["--mode", "worker", "--worker-id", "w1"])[0]
        assert cfg.platform == "telegram"

    def test_validate_only_routes_to_validator(self, tmp_path, monkeypatch):
        """Bare `--validate-only` must run the validator pod, not a
        sequential crawl of zero URLs."""
        from distributed_crawler_tpu import cli as cli_mod
        from distributed_crawler_tpu.cli import main

        ran = []
        import distributed_crawler_tpu.modes.runner as runner_mod

        def fake_validate_only(sm, cfg, validate_fn=None, **kw):
            ran.append(cfg.validate_only)

        monkeypatch.setattr(runner_mod, "run_validate_only",
                            fake_validate_only)
        rc = main(["--validate-only", "--storage-root",
                   str(tmp_path / "s"), "--log-level", "error"], env={})
        assert rc == 0
        assert ran == [True]

    def test_job_mode_defers_urls(self):
        cfg, _ = resolve(["--mode", "job"])
        assert cfg is not None


class TestUrls:
    def test_urls_flag_and_file(self, tmp_path):
        f = tmp_path / "urls.txt"
        f.write_text("one\n# comment\n\ntwo\n")
        _, r = resolve(["--urls", "zero", "--url-file", str(f)])
        assert collect_urls(r) == ["zero", "one", "two"]


class TestStandaloneTelegramE2E:
    """The full production wiring through `main()`: seed tarball →
    setup_pool_from_config → native client → crawl → JSONL posts +
    completed metadata.  Regression for three coupled bugs: no production
    pool init, raw small seed ids reading as zero posts (deadend), and the
    CLI-owned state manager never being closed (completed status lost)."""

    @pytest.mark.skipif(shutil.which("g++") is None,
                        reason="no C++ toolchain")
    def test_crawl_from_seed_tarball(self, tmp_path):
        import tarfile

        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.crawl import shutdown_connection_pool

        seed = {"channels": [{
            "username": "clichan", "id": 99, "title": "CLI Chan",
            "member_count": 250,
            "messages": [{"id": i, "date": 1785300000 + i,
                          "content": {"@type": "messageText",
                                      "text": {"text": f"cli post {i}"}},
                          "view_count": i}
                         for i in range(1, 4)]}]}
        src = tmp_path / "seed.json"
        src.write_text(json.dumps(seed))
        tar = tmp_path / "dbs.tar.gz"
        with tarfile.open(tar, "w:gz") as t:
            t.add(src, arcname="db/seed.json")

        store = tmp_path / "store"
        try:
            rc = main(["--mode", "standalone", "--urls", "clichan",
                       "--tdlib-database-urls", str(tar),
                       "--storage-root", str(store),
                       "--skip-media", "--max-depth", "0",
                       "--log-level", "warn"], env={})
        finally:
            shutdown_connection_pool()
        assert rc == 0
        posts = list(store.glob("*/clichan/posts/posts.jsonl"))
        assert len(posts) == 1
        rows = [json.loads(l) for l in posts[0].read_text().splitlines()]
        assert len(rows) == 3
        assert any("cli post" in r.get("description", "") for r in rows)
        meta = json.loads(next(store.glob("*/metadata.json")).read_text())
        assert meta["status"] == "completed"


class TestJobSubmit:
    def test_requires_name_and_bus(self, capsys):
        from distributed_crawler_tpu.cli import main

        assert main(["--mode", "job-submit"], env={}) == 2
        assert "--job-name" in capsys.readouterr().err
        assert main(["--mode", "job-submit", "--job-name", "j1"],
                    env={}) == 2
        assert "--bus-address" in capsys.readouterr().err
        rc = main(["--mode", "job-submit", "--job-name", "j1",
                   "--bus-address", "127.0.0.1:1", "--job-data", "notjson"],
                  env={})
        assert rc == 2
        assert "not valid JSON" in capsys.readouterr().err

    def test_submit_reaches_scheduler_over_grpc(self, tmp_path, capsys):
        """job-submit → gRPC bus → a job service's scheduler."""
        import socket
        import time

        from distributed_crawler_tpu.bus.grpc_bus import RemoteBus
        from distributed_crawler_tpu.bus.messages import TOPIC_JOBS
        from distributed_crawler_tpu.cli import _make_bus, main
        from distributed_crawler_tpu.config.crawler import CrawlerConfig
        from distributed_crawler_tpu.modes.jobs import (
            JobScheduler,
            JobService,
        )

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        class _R:
            def get_str(self, key, default=""):
                return f"127.0.0.1:{port}" \
                    if key == "distributed.bus_address" else default

            def get_int(self, key, default=0):
                return default

            def get_float(self, key, default=0.0):
                return default

        server = _make_bus(_R(), serve=True)
        consumer = RemoteBus(f"127.0.0.1:{port}")
        class _StubCleaner:
            def __init__(self, *a, **kw): ...
            def start(self): ...
            def stop(self): ...

        launches = []
        svc = JobService(CrawlerConfig(platform="telegram"),
                         launch_fn=lambda urls, cfg: launches.append(urls),
                         file_cleaner_factory=_StubCleaner)
        sched = JobScheduler(svc)
        consumer.subscribe(TOPIC_JOBS, sched.handle_command)
        sched.start()
        try:
            rc = main(["--mode", "job-submit", "--job-name",
                       "telegram-crawl-t", "--bus-address",
                       f"127.0.0.1:{port}",
                       "--job-data", '{"urls": ["grpcchan"]}'], env={})
            assert rc == 0
            deadline = time.monotonic() + 10
            while not launches and time.monotonic() < deadline:
                time.sleep(0.05)
            assert launches == [["grpcchan"]]
        finally:
            sched.stop()
            consumer.close()
            server.close()


class TestBusServe:
    def test_tpu_worker_hosts_broker_and_consumes(self, tmp_path):
        """--bus-serve: one process brokers AND infers (BASELINE #2/#3 as
        a two-command deployment).  A separate RemoteBus client publishes
        an inference batch; results land in the worker's sink."""
        import socket
        import time

        from distributed_crawler_tpu.bus.grpc_bus import RemoteBus
        from distributed_crawler_tpu.bus.messages import (
            TOPIC_INFERENCE_BATCHES,
        )
        from distributed_crawler_tpu.cli import _build_tpu_worker

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        cfg, r = resolve(
            ["--mode", "tpu-worker", "--infer-model", "tiny",
             "--bus-serve", "--bus-address", f"127.0.0.1:{port}",
             "--infer-batch-size", "4",
             "--storage-root", str(tmp_path / "results")])
        worker = _build_tpu_worker(cfg, r)
        worker.start()
        producer = RemoteBus(f"127.0.0.1:{port}")
        try:
            producer.publish(TOPIC_INFERENCE_BATCHES, {
                "batch_id": "b1", "crawl_id": "c1",
                "records": [{"post_uid": f"p{i}", "text": f"text {i}"}
                            for i in range(3)]})
            deadline = time.time() + 30
            files = []
            while time.time() < deadline and not files:
                files = list((tmp_path / "results").rglob("*.jsonl"))
                time.sleep(0.2)
            assert files, "no inference results written"
            rows = [json.loads(l)
                    for l in files[0].read_text().splitlines()]
            assert {r_["post_uid"] for r_ in rows} == {"p0", "p1", "p2"}
        finally:
            producer.close()
            worker.stop()
            worker.bus.close()


class TestMain:
    def test_version(self, capsys):
        assert main(["--version"]) == 0
        assert "distributed_crawler_tpu" in capsys.readouterr().out

    def test_unknown_mode(self, capsys):
        rc = main(["--mode", "quantum", "--urls", "a"], env={})
        assert rc == 2
        assert "unknown execution mode" in capsys.readouterr().err

    def test_validation_error_exit_code(self, capsys):
        rc = main(["--platform", "youtube", "--sampling", "random-walk",
                   "--urls", "a"], env={})
        assert rc == 2

    def test_infer_flag_wraps_state_manager_with_bridge(self, tmp_path):
        from distributed_crawler_tpu.cli import _maybe_bridge, resolve_config
        from distributed_crawler_tpu.inference.bridge import InferenceBridge
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        cfg, r = resolve(["--urls", "a", "--infer",
                          "--storage-root", str(tmp_path)])
        inner = CompositeStateManager(StateConfig(
            crawl_id="b1", crawl_execution_id="e1",
            storage_root=str(tmp_path), sql=SqlConfig(url=":memory:")))
        sm, closer = _maybe_bridge(inner, cfg, r)
        try:
            assert isinstance(sm, InferenceBridge)
            from distributed_crawler_tpu.datamodel import Post
            sm.store_post("chan", Post(post_uid="p", channel_id="chan",
                                       searchable_text="t"))
            assert sm.posts_bridged == 1
        finally:
            closer()
        # Without --infer: passthrough.
        cfg2, r2 = resolve(["--urls", "a"])
        inner2 = CompositeStateManager(StateConfig(
            crawl_id="b2", crawl_execution_id="e1",
            storage_root=str(tmp_path / "x"), sql=SqlConfig(url=":memory:")))
        sm2, closer2 = _maybe_bridge(inner2, cfg2, r2)
        assert sm2 is inner2
        closer2()

    def test_standalone_run_with_stubbed_engine(self, tmp_path, monkeypatch):
        """Full CLI -> standalone mode -> stubbed channel run."""
        from distributed_crawler_tpu.clients import (
            SimNetwork,
            SimTelegramClient,
        )
        from distributed_crawler_tpu.clients.pool import ConnectionPool
        from distributed_crawler_tpu.crawl import runner as crawl_runner
        from distributed_crawler_tpu.crawl.runner import set_run_for_channel_fn

        crawl_runner.shutdown_connection_pool()
        net = SimNetwork()
        crawl_runner.init_connection_pool(ConnectionPool.for_testing(
            {"c0": SimTelegramClient(net, conn_id="c0")}))
        calls = []
        set_run_for_channel_fn(
            lambda client, page, prefix, sm, cfg, processor=None, rng=None:
            calls.append(page.url) or [])
        try:
            rc = main(["--urls", "chanx", "--storage-root",
                       str(tmp_path / "s"), "--skip-media",
                       "--log-level", "error"], env={})
            assert rc == 0
            assert calls == ["chanx"]
        finally:
            crawl_runner.shutdown_connection_pool()
            set_run_for_channel_fn(None)


class TestClusterMode:
    """BASELINE config #5's closing move: embeddings -> k-means -> clusters."""

    def test_cluster_embeddings_e2e(self, tmp_path, capsys):
        import json

        import numpy as np

        from distributed_crawler_tpu.cli import main

        rng = np.random.default_rng(0)
        rows = []
        # Three well-separated blobs in 8-D.
        for c, center in enumerate(([5, 0], [0, 5], [-5, -5])):
            for i in range(20):
                vec = rng.standard_normal(8) * 0.1
                vec[0] += center[0]
                vec[1] += center[1]
                rows.append({"post_uid": f"p{c}_{i}",
                             "embedding": vec.tolist()})
        inp = tmp_path / "emb.jsonl"
        with open(inp, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        out = tmp_path / "clusters.json"

        rc = main(["--mode", "cluster", "--cluster-input", str(inp),
                   "--cluster-k", "3", "--cluster-output", str(out),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["clustered"] == 60
        assert sorted(summary["cluster_sizes"]) == [20, 20, 20]

        result = json.load(open(out))
        # Every blob lands in exactly one cluster.
        by_blob = {}
        for a in result["assignments"]:
            blob = a["post_uid"].split("_")[0]
            by_blob.setdefault(blob, set()).add(a["cluster"])
        assert all(len(cs) == 1 for cs in by_blob.values())

    def test_cluster_text_rows_embedded_on_the_fly(self, tmp_path, capsys):
        import json

        from distributed_crawler_tpu.cli import main

        inp = tmp_path / "posts.jsonl"
        with open(inp, "w") as f:
            for i in range(12):
                words = ["alpha beta", "omega sigma"][i % 2]
                f.write(json.dumps({"post_uid": f"p{i}",
                                    "all_text": words * 3}) + "\n")
        out = tmp_path / "clusters.json"
        rc = main(["--mode", "cluster", "--infer-model", "tiny",
                   "--cluster-input", str(inp), "--cluster-k", "2",
                   "--cluster-output", str(out),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["clustered"] == 12 and summary["k"] == 2

    def test_too_few_rows_rejected(self, tmp_path, capsys):
        import json

        from distributed_crawler_tpu.cli import main

        inp = tmp_path / "emb.jsonl"
        with open(inp, "w") as f:
            f.write(json.dumps({"post_uid": "p0",
                                "embedding": [1.0, 2.0]}) + "\n")
        rc = main(["--mode", "cluster", "--cluster-input", str(inp),
                   "--cluster-k", "3",
                   "--cluster-output", str(tmp_path / "o.json"),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2
        assert "cannot form" in capsys.readouterr().err

    def test_ragged_embeddings_rejected(self, tmp_path, capsys):
        import json

        from distributed_crawler_tpu.cli import main

        inp = tmp_path / "emb.jsonl"
        with open(inp, "w") as f:
            f.write(json.dumps({"post_uid": "a",
                                "embedding": [1.0, 2.0]}) + "\n")
            f.write(json.dumps({"post_uid": "b",
                                "embedding": [1.0, 2.0, 3.0]}) + "\n")
            f.write(json.dumps({"post_uid": "c", "embedding": []}) + "\n")
        rc = main(["--mode", "cluster", "--cluster-input", str(inp),
                   "--cluster-k", "2",
                   "--cluster-output", str(tmp_path / "o.json"),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2
        assert "inconsistent widths" in capsys.readouterr().err

    def test_zero_iters_rejected(self, tmp_path, capsys):
        import json

        from distributed_crawler_tpu.cli import main

        inp = tmp_path / "emb.jsonl"
        with open(inp, "w") as f:
            for i in range(4):
                f.write(json.dumps({"post_uid": str(i),
                                    "embedding": [float(i), 0.0]}) + "\n")
        rc = main(["--mode", "cluster", "--cluster-input", str(inp),
                   "--cluster-k", "2", "--cluster-iters", "0",
                   "--cluster-output", str(tmp_path / "o.json"),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2
        assert "cluster-iters" in capsys.readouterr().err

    def test_sharded_path_on_virtual_mesh(self, tmp_path, capsys):
        """Row count divisible by the 8-device CPU mesh exercises
        fit_sharded (the v5e-8 data-parallel shape) through the CLI."""
        import json

        import numpy as np

        from distributed_crawler_tpu.cli import main

        rng = np.random.default_rng(1)
        inp = tmp_path / "emb.jsonl"
        with open(inp, "w") as f:
            for c in range(2):
                for i in range(32):  # 64 rows over 8 devices
                    vec = rng.standard_normal(4) * 0.1
                    vec[0] += (c * 2 - 1) * 6
                    f.write(json.dumps({
                        "post_uid": f"b{c}_{i}",
                        "embedding": vec.tolist()}) + "\n")
        out = tmp_path / "clusters.json"
        rc = main(["--mode", "cluster", "--cluster-input", str(inp),
                   "--cluster-k", "2", "--cluster-output", str(out),
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert sorted(summary["cluster_sizes"]) == [32, 32]


class TestTpuWorkerWiring:
    def _resolver(self, extra=None):
        from distributed_crawler_tpu.cli import build_parser, resolve_config

        argv = ["--mode", "tpu-worker", "--infer-model", "tiny"]
        if extra:
            argv += extra
        args = build_parser().parse_args(argv)
        return resolve_config(args, env={})

    def test_object_store_results_sink(self, tmp_path):
        from distributed_crawler_tpu.cli import _build_tpu_worker
        from distributed_crawler_tpu.state.objectstore import (
            ObjectStorageProvider,
        )

        cfg, r = self._resolver(["--object-store",
                                 f"file://{tmp_path}/objstore",
                                 "--storage-root", str(tmp_path / "store")])
        worker = _build_tpu_worker(cfg, r)
        try:
            assert isinstance(worker.provider, ObjectStorageProvider)
        finally:
            worker.bus.close()

    def test_local_results_sink_default(self, tmp_path):
        from distributed_crawler_tpu.cli import _build_tpu_worker
        from distributed_crawler_tpu.state.providers import (
            LocalStorageProvider,
        )

        cfg, r = self._resolver(["--storage-root", str(tmp_path / "store")])
        worker = _build_tpu_worker(cfg, r)
        try:
            assert isinstance(worker.provider, LocalStorageProvider)
        finally:
            worker.bus.close()
