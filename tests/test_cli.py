"""CLI tests: flag precedence, date parsing, validation, mode dispatch.

Reference analogs: main_test.go (sampling validation matrix, time parsing)
and the viper precedence wiring of main.go:185-520.
"""

import pytest

from distributed_crawler_tpu.cli import (
    build_parser,
    collect_urls,
    main,
    resolve_config,
)


def parse(argv):
    return build_parser().parse_args(argv)


def resolve(argv, env=None):
    return resolve_config(parse(argv), env=env or {})


class TestPrecedence:
    def test_flag_beats_env(self):
        cfg, _ = resolve(["--concurrency", "7", "--urls", "a"],
                         env={"CRAWLER_CRAWLER_CONCURRENCY": "3"})
        assert cfg.concurrency == 7

    def test_env_beats_default(self):
        cfg, _ = resolve(["--urls", "a"],
                         env={"CRAWLER_CRAWLER_CONCURRENCY": "3"})
        assert cfg.concurrency == 3

    def test_config_file(self, tmp_path):
        f = tmp_path / "config.yaml"
        f.write_text("crawler:\n  maxposts: 42\n  platform: telegram\n")
        cfg, _ = resolve(["--config", str(f), "--urls", "a"])
        assert cfg.max_posts == 42

    def test_missing_explicit_config_file_errors(self):
        with pytest.raises(FileNotFoundError):
            resolve(["--config", "/nonexistent/config.yaml", "--urls", "a"])

    def test_defaults(self):
        cfg, _ = resolve(["--urls", "a"])
        assert cfg.max_pages == 108000
        assert cfg.min_users == 100
        assert cfg.walkback_rate == 15
        assert cfg.platform == "telegram"
        assert cfg.sampling_method == "channel"
        assert cfg.combine_trigger_size == 170 * 1024 * 1024


class TestDateWindows:
    def test_date_between(self):
        cfg, _ = resolve(["--date-between", "2025-01-01,2025-02-01",
                          "--urls", "a"])
        assert cfg.date_between_min.year == 2025
        assert cfg.date_between_max.month == 2

    def test_time_ago(self):
        cfg, _ = resolve(["--time-ago", "30d", "--urls", "a"])
        assert cfg.post_recency is not None

    def test_min_post_date(self):
        cfg, _ = resolve(["--min-post-date", "2024-06-15", "--urls", "a"])
        assert cfg.min_post_date.day == 15

    def test_date_between_wins(self):
        cfg, _ = resolve(["--date-between", "2025-01-01,2025-02-01",
                          "--time-ago", "7d", "--urls", "a"])
        assert cfg.date_between_min is not None
        assert cfg.post_recency is None

    def test_max_crawl_duration(self):
        cfg, _ = resolve(["--max-crawl-duration", "1h30m", "--urls", "a"])
        assert cfg.max_crawl_duration_s == 5400.0


class TestValidation:
    def test_invalid_platform_sampling_combo(self):
        with pytest.raises(ValueError, match="not supported"):
            resolve(["--platform", "youtube", "--sampling", "random-walk",
                     "--urls", "a"])

    def test_random_walk_needs_seeds_xor_seed_size(self):
        with pytest.raises(ValueError, match="seed"):
            resolve(["--sampling", "random-walk"])
        cfg, _ = resolve(["--sampling", "random-walk", "--seed-size", "5"])
        assert cfg.seed_size == 5

    def test_channel_requires_urls(self):
        with pytest.raises(ValueError):
            resolve([])

    def test_validate_only_needs_no_urls(self):
        cfg, _ = resolve(["--validate-only", "--sampling", "random-walk"])
        assert cfg.validate_only

    def test_job_mode_defers_urls(self):
        cfg, _ = resolve(["--mode", "job"])
        assert cfg is not None


class TestUrls:
    def test_urls_flag_and_file(self, tmp_path):
        f = tmp_path / "urls.txt"
        f.write_text("one\n# comment\n\ntwo\n")
        _, r = resolve(["--urls", "zero", "--url-file", str(f)])
        assert collect_urls(r) == ["zero", "one", "two"]


class TestMain:
    def test_version(self, capsys):
        assert main(["--version"]) == 0
        assert "distributed_crawler_tpu" in capsys.readouterr().out

    def test_unknown_mode(self, capsys):
        rc = main(["--mode", "quantum", "--urls", "a"], env={})
        assert rc == 2
        assert "unknown execution mode" in capsys.readouterr().err

    def test_validation_error_exit_code(self, capsys):
        rc = main(["--platform", "youtube", "--sampling", "random-walk",
                   "--urls", "a"], env={})
        assert rc == 2

    def test_infer_flag_wraps_state_manager_with_bridge(self, tmp_path):
        from distributed_crawler_tpu.cli import _maybe_bridge, resolve_config
        from distributed_crawler_tpu.inference.bridge import InferenceBridge
        from distributed_crawler_tpu.state import (
            CompositeStateManager,
            SqlConfig,
            StateConfig,
        )

        cfg, r = resolve(["--urls", "a", "--infer",
                          "--storage-root", str(tmp_path)])
        inner = CompositeStateManager(StateConfig(
            crawl_id="b1", crawl_execution_id="e1",
            storage_root=str(tmp_path), sql=SqlConfig(url=":memory:")))
        sm, closer = _maybe_bridge(inner, cfg, r)
        try:
            assert isinstance(sm, InferenceBridge)
            from distributed_crawler_tpu.datamodel import Post
            sm.store_post("chan", Post(post_uid="p", channel_id="chan",
                                       searchable_text="t"))
            assert sm.posts_bridged == 1
        finally:
            closer()
        # Without --infer: passthrough.
        cfg2, r2 = resolve(["--urls", "a"])
        inner2 = CompositeStateManager(StateConfig(
            crawl_id="b2", crawl_execution_id="e1",
            storage_root=str(tmp_path / "x"), sql=SqlConfig(url=":memory:")))
        sm2, closer2 = _maybe_bridge(inner2, cfg2, r2)
        assert sm2 is inner2
        closer2()

    def test_standalone_run_with_stubbed_engine(self, tmp_path, monkeypatch):
        """Full CLI -> standalone mode -> stubbed channel run."""
        from distributed_crawler_tpu.clients import (
            SimNetwork,
            SimTelegramClient,
        )
        from distributed_crawler_tpu.clients.pool import ConnectionPool
        from distributed_crawler_tpu.crawl import runner as crawl_runner
        from distributed_crawler_tpu.crawl.runner import set_run_for_channel_fn

        crawl_runner.shutdown_connection_pool()
        net = SimNetwork()
        crawl_runner.init_connection_pool(ConnectionPool.for_testing(
            {"c0": SimTelegramClient(net, conn_id="c0")}))
        calls = []
        set_run_for_channel_fn(
            lambda client, page, prefix, sm, cfg, processor=None, rng=None:
            calls.append(page.url) or [])
        try:
            rc = main(["--urls", "chanx", "--storage-root",
                       str(tmp_path / "s"), "--skip-media",
                       "--log-level", "error"], env={})
            assert rc == 0
            assert calls == ["chanx"]
        finally:
            crawl_runner.shutdown_connection_pool()
            set_run_for_channel_fn(None)
