"""S3 object-store adapter (VERDICT r03 #5) against an in-tree S3 REST
emulator over real HTTP sockets.

The emulator implements the slice of the S3 API the adapter speaks
(put/get/head/delete/ListObjectsV2 + multipart) and — crucially —
RECOMPUTES the AWS SigV4 signature of every request with the shared
secret, rejecting mismatches with 403: the tests prove the signing
implementation, not just the happy path.  Reference parity: the Azure
blob output binding seam, `state/daprstate.go:29-35`.
"""

import hashlib
import hmac
import http.server
import json
import os
import re
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from distributed_crawler_tpu.state.objectstore import (
    ObjectStoreUploader,
    TransientStoreError,
    make_object_client,
)
from distributed_crawler_tpu.state.s3store import S3ObjectClient

ACCESS, SECRET = "AKIATEST12345", "s3cr3t-key-for-tests"


class S3Emulator:
    """Minimal S3-compatible server: in-memory, path-style, SigV4-checked."""

    PAGE_SIZE = 3  # small: exercises ListObjectsV2 continuation

    def __init__(self):
        self.objects = {}
        self.uploads = {}  # upload_id -> {"key": str, "parts": {n: bytes}}
        self.request_log = []  # (method, path-with-query)
        self.fail_next = []  # list of (regex, count) -> 500
        self._uid = 0
        emu = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _fail_injected(self) -> bool:
                target = f"{self.command} {self.path}"
                for i, (rx, count) in enumerate(emu.fail_next):
                    if count > 0 and re.search(rx, target):
                        emu.fail_next[i] = (rx, count - 1)
                        self._respond(500, b"<Error>injected</Error>")
                        return True
                return False

            def _check_sig(self, body: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                m = re.match(
                    r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d+)/([^/]+)/"
                    r"([^/]+)/aws4_request, SignedHeaders=([^,]+), "
                    r"Signature=([0-9a-f]+)", auth)
                if not m or m.group(1) != ACCESS:
                    self._respond(403, b"<Error>bad credential</Error>")
                    return False
                datestamp, region, service = m.group(2), m.group(3), \
                    m.group(4)
                signed_names, got_sig = m.group(5), m.group(6)
                payload_hash = self.headers.get("x-amz-content-sha256", "")
                if hashlib.sha256(body).hexdigest() != payload_hash:
                    self._respond(403, b"<Error>payload hash</Error>")
                    return False
                path, _, query = self.path.partition("?")
                canonical_headers = "".join(
                    f"{name}:{self.headers.get(name, '').strip()}\n"
                    for name in signed_names.split(";"))
                canonical_request = "\n".join([
                    self.command, path or "/", query, canonical_headers,
                    signed_names, payload_hash])
                scope = f"{datestamp}/{region}/{service}/aws4_request"
                string_to_sign = "\n".join([
                    "AWS4-HMAC-SHA256",
                    self.headers.get("x-amz-date", ""), scope,
                    hashlib.sha256(
                        canonical_request.encode()).hexdigest()])

                def h(key, msg):
                    return hmac.new(key, msg.encode(),
                                    hashlib.sha256).digest()

                key = h(h(h(h(("AWS4" + SECRET).encode(), datestamp),
                            region), service), "aws4_request")
                want = hmac.new(key, string_to_sign.encode(),
                                hashlib.sha256).hexdigest()
                if want != got_sig:
                    self._respond(403, b"<Error>SignatureDoesNotMatch"
                                       b"</Error>")
                    return False
                return True

            def _respond(self, status, body=b"", headers=None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _parse(self):
                path, _, query = self.path.partition("?")
                q = dict(urllib.parse.parse_qsl(query,
                                                keep_blank_values=True))
                # path-style: /bucket/key...
                parts = urllib.parse.unquote(path).lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                return bucket, key, q

            def _handle(self):
                body = b""
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    body = self.rfile.read(n)
                emu.request_log.append((self.command, self.path))
                if self._fail_injected():
                    return
                if not self._check_sig(body):
                    return
                _bucket, key, q = self._parse()
                cmd = self.command
                if cmd == "POST" and "uploads" in q:
                    emu._uid += 1
                    uid = f"up-{emu._uid}"
                    emu.uploads[uid] = {"key": key, "parts": {},
                                        "etags": {}}
                    self._respond(200, (
                        "<InitiateMultipartUploadResult>"
                        f"<UploadId>{uid}</UploadId>"
                        "</InitiateMultipartUploadResult>").encode())
                    return
                if cmd == "PUT" and "partNumber" in q:
                    up = emu.uploads.get(q.get("uploadId", ""))
                    if up is None:
                        self._respond(404, b"<Error>NoSuchUpload</Error>")
                        return
                    pn = int(q["partNumber"])
                    up["parts"][pn] = body
                    etag = '"%s"' % hashlib.md5(body).hexdigest()
                    up["etags"][pn] = etag
                    self._respond(200, headers={"ETag": etag})
                    return
                if cmd == "POST" and "uploadId" in q:
                    up = emu.uploads.pop(q["uploadId"], None)
                    if up is None:
                        self._respond(404, b"<Error>NoSuchUpload</Error>")
                        return
                    root = ET.fromstring(body)
                    joined = b""
                    for part in root.iter("Part"):
                        pn = int(part.find("PartNumber").text)
                        etag = part.find("ETag").text
                        if up["etags"].get(pn) != etag:
                            self._respond(400,
                                          b"<Error>InvalidPart</Error>")
                            return
                        joined += up["parts"][pn]
                    emu.objects[up["key"]] = joined
                    self._respond(200, b"<CompleteMultipartUploadResult/>")
                    return
                if cmd == "DELETE" and "uploadId" in q:
                    emu.uploads.pop(q["uploadId"], None)
                    self._respond(204)
                    return
                if cmd == "GET" and q.get("list-type") == "2":
                    prefix = q.get("prefix", "")
                    keys = sorted(k for k in emu.objects
                                  if k.startswith(prefix))
                    start = 0
                    token = q.get("continuation-token", "")
                    if token:
                        start = int(token)
                    page = keys[start:start + emu.PAGE_SIZE]
                    truncated = start + emu.PAGE_SIZE < len(keys)
                    xml = ["<ListBucketResult>"]
                    for k in page:
                        xml.append(f"<Contents><Key>{k}</Key></Contents>")
                    xml.append(f"<IsTruncated>{str(truncated).lower()}"
                               f"</IsTruncated>")
                    if truncated:
                        xml.append(f"<NextContinuationToken>"
                                   f"{start + emu.PAGE_SIZE}"
                                   f"</NextContinuationToken>")
                    xml.append("</ListBucketResult>")
                    self._respond(200, "".join(xml).encode())
                    return
                if cmd == "PUT":
                    emu.objects[key] = body
                    self._respond(200)
                    return
                if cmd in ("GET", "HEAD"):
                    data = emu.objects.get(key)
                    if data is None:
                        self._respond(404, b"<Error>NoSuchKey</Error>")
                        return
                    self._respond(200, data)
                    return
                if cmd == "DELETE":
                    emu.objects.pop(key, None)
                    self._respond(204)
                    return
                self._respond(400, b"<Error>unsupported</Error>")

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _handle

        self._srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                    Handler)
        self.port = self._srv.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


@pytest.fixture
def emu():
    e = S3Emulator().start()
    yield e
    e.close()


def make_client(emu, prefix="") -> S3ObjectClient:
    return S3ObjectClient(bucket="crawl", prefix=prefix,
                          endpoint=emu.endpoint,
                          access_key=ACCESS, secret_key=SECRET)


class TestSignedRoundTrip:
    def test_put_get_head_delete(self, emu):
        c = make_client(emu)
        c.put_object("a/b.jsonl", b"hello s3")
        assert c.get_object("a/b.jsonl") == b"hello s3"
        assert c.head_object("a/b.jsonl") == 8
        assert c.get_object("missing") is None
        assert c.head_object("missing") is None
        c.delete_object("a/b.jsonl")
        assert c.get_object("a/b.jsonl") is None

    def test_bad_secret_rejected(self, emu):
        c = S3ObjectClient(bucket="crawl", endpoint=emu.endpoint,
                           access_key=ACCESS, secret_key="wrong-secret")
        with pytest.raises(ValueError, match="403"):
            c.put_object("k", b"x")

    def test_special_chars_in_key_sign_correctly(self, emu):
        c = make_client(emu)
        key = "dir with space/post+plus=eq~tilde.jsonl"
        c.put_object(key, b"data")
        assert c.get_object(key) == b"data"

    def test_prefix_scoping(self, emu):
        c = make_client(emu, prefix="crawls/c1")
        c.put_object("combined/a.jsonl", b"x")
        assert "crawls/c1/combined/a.jsonl" in emu.objects
        assert c.list_objects("combined/") == ["combined/a.jsonl"]
        assert c.get_object("combined/a.jsonl") == b"x"

    def test_list_paginates_through_continuation(self, emu):
        c = make_client(emu)
        for i in range(8):  # PAGE_SIZE=3 -> 3 pages
            c.put_object(f"p/k{i}", b"v")
        assert c.list_objects("p/") == [f"p/k{i}" for i in range(8)]

    def test_5xx_is_transient(self, emu):
        c = make_client(emu)
        emu.fail_next.append((r"PUT /crawl/t5", 1))
        with pytest.raises(TransientStoreError):
            c.put_object("t5", b"x")

    def test_connection_refused_is_transient(self):
        c = S3ObjectClient(bucket="b", endpoint="http://127.0.0.1:1",
                           access_key=ACCESS, secret_key=SECRET,
                           timeout_s=2.0)
        with pytest.raises(TransientStoreError):
            c.get_object("k")


class TestMultipartRetryResume:
    def test_multipart_roundtrip(self, emu):
        c = make_client(emu)
        up = ObjectStoreUploader(c, part_size=8, backoff_s=0.01)
        data = b"0123456789" * 5  # 50 B -> 7 parts of 8
        up.upload_bytes("mp/big.bin", data)
        assert emu.objects["mp/big.bin"] == data

    def test_mid_upload_fault_resumes_from_last_part(self, emu):
        """The VERDICT 'Done' criterion: a part-level 500 mid-upload is
        retried at THAT part — earlier parts are never re-sent."""
        c = make_client(emu)
        up = ObjectStoreUploader(c, part_size=8, backoff_s=0.01)
        # partNumber=3 (0-based part 2) fails twice, then succeeds.
        emu.fail_next.append((r"PUT /crawl/mp/fault\.bin\?partNumber=3&", 2))
        data = bytes(range(40))  # 5 parts
        up.upload_bytes("mp/fault.bin", data)
        assert emu.objects["mp/fault.bin"] == data
        sends = [p for m, p in emu.request_log
                 if m == "PUT" and "partNumber=" in p
                 and "fault.bin" in p]
        by_part = {}
        for p in sends:
            n = int(re.search(r"partNumber=(\d+)", p).group(1))
            by_part[n] = by_part.get(n, 0) + 1
        assert by_part[3] == 3          # two failures + one success
        assert by_part[1] == by_part[2] == 1  # never resent from byte 0
        assert by_part[4] == by_part[5] == 1

    def test_complete_with_wrong_etag_rejected(self, emu):
        c = make_client(emu)
        uid = c.create_multipart("mp/etag.bin")
        c.upload_part("mp/etag.bin", uid, 0, b"part0")
        with pytest.raises(ValueError, match="400"):
            c.complete_multipart("mp/etag.bin", uid, ['"bogus-etag"'])


class TestMakeObjectClientUrl:
    def test_s3_url_parses(self, emu):
        url = (f"s3://crawl/pfx?endpoint={emu.endpoint}"
               f"&access_key={ACCESS}&secret_key={SECRET}")
        c = make_object_client(url)
        c.put_object("k.jsonl", b"via-url")
        assert emu.objects["pfx/k.jsonl"] == b"via-url"

    def test_missing_credentials_rejected(self, monkeypatch):
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
        with pytest.raises(ValueError, match="credentials"):
            make_object_client("s3://bucket/p?endpoint=http://x")

    def test_env_credentials_used(self, emu, monkeypatch):
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS)
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET)
        c = make_object_client(f"s3://crawl?endpoint={emu.endpoint}")
        c.put_object("envkey", b"ok")
        assert emu.objects["envkey"] == b"ok"


class TestChunkerToS3:
    def test_chunker_combined_file_lands_in_emulator(self, emu, tmp_path):
        """Chunker e2e → S3: shards combine, the multipart upload rides
        out an injected mid-upload fault, and the combined object lands in
        the emulator (`chunk/main.go:349-421` shipped to the blob binding
        the same way)."""
        from distributed_crawler_tpu.chunk.chunker import Chunker
        from distributed_crawler_tpu.state import LocalStateManager
        from distributed_crawler_tpu.state.interface import (
            LocalConfig,
            StateConfig,
        )

        watch = str(tmp_path / "watch")
        combine = str(tmp_path / "combine")
        os.makedirs(watch)
        for i in range(3):
            with open(os.path.join(watch, f"s{i}.jsonl"), "w") as f:
                for j in range(20):
                    f.write(json.dumps({"s": i, "r": j, "pad": "x" * 64})
                            + "\n")
        expected_rows = 60

        url = (f"s3://crawl/combined-store?endpoint={emu.endpoint}"
               f"&access_key={ACCESS}&secret_key={SECRET}")
        sm = LocalStateManager(StateConfig(
            storage_root=str(tmp_path / "root"), crawl_id="s3e2e",
            local=LocalConfig(base_path=str(tmp_path / "root")),
            object_store_url=url))
        # Small parts force the multipart path; one injected part fault.
        from distributed_crawler_tpu.state.s3store import parse_s3_url
        sm._object_uploader = ObjectStoreUploader(
            parse_s3_url(url), part_size=1024, backoff_s=0.01)
        emu.fail_next.append((r"partNumber=2&", 1))

        chunker = Chunker(sm, str(tmp_path / "temp"), watch, combine,
                          trigger_size=1, scan_interval_s=0.05)
        chunker.start()
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not any(
                    k.startswith("combined-store/combined/s3e2e/")
                    for k in emu.objects):
                time.sleep(0.05)
        finally:
            chunker.shutdown()
        keys = [k for k in emu.objects
                if k.startswith("combined-store/combined/s3e2e/")]
        assert keys, "combined file never landed in the S3 emulator"
        rows = b"".join(emu.objects[k] for k in sorted(keys))
        assert rows.count(b"\n") == expected_rows
        # The injected fault really happened and was ridden out.
        part2 = [p for m, p in emu.request_log
                 if m == "PUT" and "partNumber=2&" in p]
        assert len(part2) >= 2
