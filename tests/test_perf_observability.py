"""Hardware-efficiency observability tests (ISSUE 5 tentpole).

Covers the cost model (XLA ``cost_analysis`` capture vs the analytic
fallback, on CPU), the MFU/goodput meter math on synthetic batch
records, the ``/costs`` and ``/profile`` HTTP endpoints (including the
capture-already-running 409 path), the SLO watchdog (breach → counter +
WARNING + flight event), and the e2e acceptance: a live TPU worker on
the in-memory bus serving a non-empty ``/costs``, exporting
``tpu_engine_mfu``, breaching a forced-tiny SLO into the postmortem
bundle, and rendering through ``tools/perfreport.py``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_crawler_tpu.bus import InMemoryBus
from distributed_crawler_tpu.bus.codec import RecordBatch
from distributed_crawler_tpu.bus.messages import TOPIC_INFERENCE_BATCHES
from distributed_crawler_tpu.datamodel.post import Post
from distributed_crawler_tpu.inference.engine import (
    EngineConfig,
    InferenceEngine,
)
from distributed_crawler_tpu.inference.worker import (
    TPUWorker,
    TPUWorkerConfig,
)
from distributed_crawler_tpu.utils import flight, profiling, trace
from distributed_crawler_tpu.utils.costmodel import (
    CPU_PEAK_FLOPS_ESTIMATE,
    CostModel,
    EfficiencyMeter,
    encoder_forward_flops,
    peak_flops,
)
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    clear_costs_provider,
    serve_metrics,
    set_costs_provider,
)
from distributed_crawler_tpu.utils.profiling import ProfileCapture
from distributed_crawler_tpu.utils.slo import (
    SLO,
    SLOWatchdog,
    standard_slos,
)

import tools.perfreport as perfreport


def tiny_engine(reg=None, buckets=(16, 32), batch=4):
    return InferenceEngine(
        EngineConfig(model="tiny", batch_size=batch, buckets=buckets),
        registry=reg or MetricsRegistry())


def get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def wait_for(pred, timeout_s=60.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


# ---------------------------------------------------------------------------
class TestCostModel:
    def test_analytic_fallback_when_lowering_fails(self):
        reg = MetricsRegistry()
        cm = CostModel(registry=reg)

        def boom():
            raise RuntimeError("backend wedged")

        entry = cm.capture(128, "unpacked", boom, fallback_flops=1.5e9,
                           batch=256)
        assert entry["source"] == "analytic"
        assert entry["flops"] == 1.5e9
        assert cm.has(128, "unpacked")
        assert cm.flops_for(128, "unpacked") == 1.5e9
        # Idempotent: a second capture never overwrites the first entry.
        again = cm.capture(128, "unpacked", boom, fallback_flops=7.0)
        assert again["flops"] == 1.5e9

    def test_xla_capture_matches_matmul_flops(self):
        import jax
        import jax.numpy as jnp

        m = n = k = 128
        fn = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((m, k), jnp.float32)
        b = jnp.ones((k, n), jnp.float32)
        fn(a, b)  # the dispatch that pays the compile, as in the engine
        cm = CostModel(registry=MetricsRegistry())
        entry = cm.capture(128, "unpacked", lambda: fn.lower(a, b),
                           fallback_flops=1.0)
        assert entry["source"] == "xla"
        # 2*m*n*k MAC-as-2-FLOPs, within XLA bookkeeping slack.
        assert entry["flops"] == pytest.approx(2 * m * n * k, rel=0.05)
        assert entry["bytes_accessed"] and entry["bytes_accessed"] > 0

    def test_engine_capture_parity_with_analytic_on_cpu(self):
        """The ISSUE's parity check: the XLA-sourced cost of a real
        compiled bucket program agrees with the promoted analytic formula
        to well within an order of magnitude (the analytic count skips
        LN/softmax/embedding, XLA counts them)."""
        reg = MetricsRegistry()
        eng = tiny_engine(reg, buckets=(16,), batch=4)
        eng.run_tokenized([[1, 2, 3]] * 4)
        snap = eng.cost_snapshot()
        assert snap["costs"], "no cost entry captured at first dispatch"
        entry = snap["costs"][0]
        assert entry["source"] == "xla"
        analytic = encoder_forward_flops(eng.ecfg, 4, 16)
        assert 0.2 <= entry["flops"] / analytic <= 5.0
        # The gauge rides along, labeled by bucket and path.
        expo = reg.expose()
        assert 'tpu_engine_bucket_flops{bucket="16",path="unpacked"}' \
            in expo

    def test_packed_path_captures_its_own_program(self):
        eng = tiny_engine(buckets=(16,), batch=4)
        eng.run_tokenized([[1, 2, 3]] * 6, pack=True)
        paths = {e["path"] for e in eng.costs.snapshot()}
        assert "packed" in paths

    def test_peak_flops_table(self):
        peak, source = peak_flops("TPU v5e", "tpu", n_devices=4)
        assert peak == 197e12 * 4
        assert source == "tpu:v5e"
        peak, source = peak_flops("cpu", "cpu")
        assert peak == CPU_PEAK_FLOPS_ESTIMATE
        assert source == "cpu_estimate"
        assert peak_flops("H100", "gpu") == (0.0, "unknown")
        assert peak_flops("TPU v99", "tpu")[1] == "unknown"


# ---------------------------------------------------------------------------
class TestEfficiencyMeter:
    def test_mfu_goodput_density_math(self):
        reg = MetricsRegistry()
        meter = EfficiencyMeter(registry=reg, peak=1e9,
                                peak_source="test")
        meter.record(duration_s=0.5, flops=1e8, real_tokens=800,
                     slot_tokens=1000)
        snap = meter.snapshot()
        assert snap["batches"] == 1
        assert snap["padding_density"] == 0.8
        assert snap["peak_source"] == "test"
        # Window span floors at the batch duration: achieved ~2e8 FLOP/s
        # against a 1e9 peak -> mfu just under 0.2.
        assert 0.1 < snap["mfu"] <= 0.2
        assert snap["mfu_busy"] == pytest.approx(0.2, rel=0.01)
        assert snap["goodput_tokens_per_s"] <= 1600
        assert snap["goodput_tokens_per_s"] > 100
        expo = reg.expose()
        assert "tpu_engine_mfu" in expo
        assert "tpu_engine_goodput_tokens_per_s" in expo
        assert "tpu_engine_padding_density 0.8" in expo

    def test_empty_meter_snapshots_empty(self):
        meter = EfficiencyMeter(registry=MetricsRegistry(), peak=1e9)
        assert meter.snapshot() == {}

    def test_window_prunes_old_records(self):
        meter = EfficiencyMeter(registry=MetricsRegistry(), peak=1e9,
                                window_s=0.05)
        meter.record(0.001, 1e6, 10, 20)
        time.sleep(0.1)
        meter.record(0.001, 2e6, 5, 20)
        snap = meter.snapshot()
        assert snap["batches"] == 1
        assert snap["real_tokens"] == 5

    def test_idle_window_decays_gauges_to_zero(self):
        # A worker that WAS busy and then starved must report MFU 0, not
        # freeze the gauges at the last busy window's values.
        reg = MetricsRegistry()
        meter = EfficiencyMeter(registry=reg, peak=1e9, peak_source="test",
                                window_s=0.05)
        meter.record(0.01, 1e7, 100, 200)
        assert meter.snapshot()["mfu"] > 0
        time.sleep(0.1)
        snap = meter.snapshot()  # the heartbeat's periodic read
        assert snap["batches"] == 0
        assert snap["mfu"] == 0.0
        assert snap["goodput_tokens_per_s"] == 0.0
        assert "tpu_engine_mfu 0.0" in reg.expose()

    def test_unknown_peak_omits_mfu(self):
        meter = EfficiencyMeter(registry=MetricsRegistry(), peak=0.0,
                                peak_source="unknown")
        meter.record(0.01, 1e6, 10, 20)
        snap = meter.snapshot()
        assert snap["mfu"] is None
        assert snap["goodput_tokens_per_s"] > 0


# ---------------------------------------------------------------------------
class TestCostsEndpoint:
    def test_costs_served_and_cleared(self):
        reg = MetricsRegistry()
        server = serve_metrics(0, reg)
        port = server.server_address[1]
        provider = lambda: {"worker_id": "w1", "costs": [{"bucket": 16}]}
        set_costs_provider(provider)
        try:
            status, body = get(f"http://127.0.0.1:{port}/costs")
            assert status == 200
            assert json.loads(body)["worker_id"] == "w1"
        finally:
            clear_costs_provider(provider)
            server.shutdown()

    def test_costs_provider_error_is_500(self):
        reg = MetricsRegistry()
        server = serve_metrics(0, reg)
        port = server.server_address[1]

        def bad():
            raise RuntimeError("engine gone")

        set_costs_provider(bad)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"http://127.0.0.1:{port}/costs")
            assert e.value.code == 500
        finally:
            clear_costs_provider(bad)
            server.shutdown()

    def test_costs_404_without_provider(self):
        reg = MetricsRegistry()
        server = serve_metrics(0, reg)
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"http://127.0.0.1:{port}/costs")
            assert e.value.code == 404
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
class TestProfileEndpoint:
    def _serve(self):
        reg = MetricsRegistry()
        server = serve_metrics(0, reg)
        return server, server.server_address[1]

    def test_capture_writes_a_trace_bundle(self, tmp_path, monkeypatch):
        import os

        monkeypatch.setattr(profiling, "PROFILER",
                            ProfileCapture(dump_dir=str(tmp_path)))
        server, port = self._serve()
        try:
            # First capture pays the jax profiler's one-time session init
            # (~10 s observed on CPU) — time out generously.
            status, body = get(
                f"http://127.0.0.1:{port}/profile?seconds=0.2",
                timeout=90)
            assert status == 200
            result = json.loads(body)
            assert result["ok"] is True
            files = [f for _r, _d, fs in os.walk(result["path"])
                     for f in fs]
            assert files, "capture produced no trace files"
        finally:
            server.shutdown()

    def test_no_dump_dir_is_503(self, monkeypatch):
        monkeypatch.setattr(profiling, "PROFILER", ProfileCapture())
        server, port = self._serve()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"http://127.0.0.1:{port}/profile?seconds=0.1")
            assert e.value.code == 503
            assert "dump-dir" in json.loads(e.value.read())["error"]
        finally:
            server.shutdown()

    def test_bad_seconds_is_400(self, tmp_path, monkeypatch):
        monkeypatch.setattr(profiling, "PROFILER",
                            ProfileCapture(dump_dir=str(tmp_path)))
        server, port = self._serve()
        try:
            for q in ("seconds=abc", "seconds=0", "seconds=-3"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    get(f"http://127.0.0.1:{port}/profile?{q}")
                assert e.value.code == 400
        finally:
            server.shutdown()

    def test_concurrent_capture_is_409(self, tmp_path, monkeypatch):
        cap = ProfileCapture(dump_dir=str(tmp_path))
        monkeypatch.setattr(profiling, "PROFILER", cap)
        server, port = self._serve()
        t = threading.Thread(target=cap.capture, args=(1.0,))
        t.start()
        try:
            deadline = time.monotonic() + 5.0
            while not cap.active and time.monotonic() < deadline:
                time.sleep(0.01)
            assert cap.active, "background capture never started"
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"http://127.0.0.1:{port}/profile?seconds=0.1")
            assert e.value.code == 409
        finally:
            t.join(timeout=10)
            server.shutdown()

    def test_capture_async_dedupes(self, tmp_path):
        cap = ProfileCapture(dump_dir=str(tmp_path))
        with cap._lock:
            cap._active = True  # simulate a capture in flight
        assert cap.capture_async(0.1) is False

    def test_capture_async_refuses_without_dump_dir(self):
        # No dump dir = the capture can never land: must not claim
        # 'started' (nor spawn a doomed thread per slow batch).
        assert ProfileCapture().capture_async(0.1) is False

    def test_seconds_bounded_by_max(self, tmp_path):
        cap = ProfileCapture(dump_dir=str(tmp_path), max_seconds=0.1)
        t0 = time.monotonic()
        result = cap.capture(60.0)
        assert result["ok"] is True
        assert result["seconds"] == 0.1
        assert time.monotonic() - t0 < 30.0

    def test_old_bundles_pruned_past_max_keep(self, tmp_path):
        import os

        cap = ProfileCapture(dump_dir=str(tmp_path), max_keep=2)
        for stamp in ("profile_20260101000001_1", "profile_20260101000002_1",
                      "profile_20260101000003_1", "not_a_profile"):
            (tmp_path / stamp).mkdir()
        cap._prune_old()
        kept = sorted(os.listdir(tmp_path))
        assert kept == ["not_a_profile", "profile_20260101000002_1",
                        "profile_20260101000003_1"]

    def test_duplicate_server_start_warns_not_raises(self):
        # Port is never bound (first start is simulated), so this only
        # exercises the duplicate guard.
        monkey_state = profiling._server_port
        try:
            profiling._server_port = 9999
            assert profiling.start_profiler_server(9998) is False
        finally:
            profiling._server_port = monkey_state


# ---------------------------------------------------------------------------
class TestSLOWatchdog:
    def _dog(self, slos, name, durations_s):
        """Watchdog over a fresh tracer, with the spans recorded AFTER
        construction (the eval window opens at construction time, as in
        the workers where the watchdog exists before any batch)."""
        tracer = trace.Tracer(capacity=256)
        reg = MetricsRegistry()
        dog = SLOWatchdog(slos, tracer=tracer, registry=reg)
        for d in durations_s:
            tracer.record(name, d, trace_id=f"trace_{name}_{d}")
        return dog, reg

    def test_standard_slos_skip_zero_budgets(self):
        assert standard_slos() == []
        slos = standard_slos(batch_p95_ms=100.0)
        assert [s.name for s in slos] == ["batch_p95"]
        slos = standard_slos(batch_p95_ms=100.0, queue_wait_ms=5.0)
        assert [s.name for s in slos] == ["batch_p95", "queue_wait"]

    def test_breach_counts_and_flight_event(self):
        dog, reg = self._dog(standard_slos(batch_p95_ms=100.0),
                             "tpu_worker.process", [0.001, 0.5])
        flight.RECORDER.reset()
        breaches = dog.evaluate(now=time.time() + 1)
        assert len(breaches) == 1
        b = breaches[0]
        assert b["slo"] == "batch_p95"
        assert b["p95_ms"] == 500.0
        assert b["worst_trace_id"] == "trace_tpu_worker.process_0.5"
        assert reg.expose().count('slo_breach_total{slo="batch_p95"} 1')
        events = [e for e in flight.RECORDER.events()
                  if e["kind"] == "slo_breach"]
        assert len(events) == 1
        assert events[0]["trace_id"] == "trace_tpu_worker.process_0.5"
        assert events[0]["budget_ms"] == 100.0
        assert dog.snapshot()["breaches"]["batch_p95"] == 1

    def test_under_budget_no_breach(self):
        dog, _reg = self._dog(standard_slos(batch_p95_ms=100.0),
                              "tpu_worker.process", [0.001, 0.002])
        assert dog.evaluate(now=time.time() + 1) == []

    def test_window_is_since_last_eval(self):
        dog, _reg = self._dog(standard_slos(queue_wait_ms=10.0),
                              "tpu_worker.queue_wait", [0.9])
        assert len(dog.evaluate(now=time.time() + 1)) == 1
        # Same spans, next tick: already judged, no double count.
        assert dog.evaluate(now=time.time() + 2) == []

    def test_disabled_tracer_warns_instead_of_silent_green(self, caplog):
        # --trace-buffer 0 disables span recording; a declared budget
        # must say it cannot be evaluated rather than stay green forever.
        tracer = trace.Tracer(capacity=0)
        dog = SLOWatchdog(standard_slos(batch_p95_ms=100.0),
                          tracer=tracer, registry=MetricsRegistry())
        with caplog.at_level("WARNING", logger="dct.slo"):
            assert dog.evaluate() == []
            assert dog.evaluate() == []  # warned once, not per tick
        warnings = [r for r in caplog.records
                    if "will NOT be evaluated" in r.getMessage()]
        assert len(warnings) == 1

    def test_custom_slo_span_set(self):
        dog, _reg = self._dog([SLO("crawl", ("worker.process",), 50.0)],
                              "worker.process", [0.4])
        assert dog.evaluate(now=time.time() + 1)[0]["slo"] == "crawl"


# ---------------------------------------------------------------------------
def make_batch(n=3, crawl_id="c1"):
    return RecordBatch.from_posts(
        [Post(post_uid=f"p{i}", channel_name="chan",
              description=f"some text {i}") for i in range(n)],
        crawl_id=crawl_id)


class TestWorkerEndToEnd:
    """Acceptance: live worker -> non-empty /costs, tpu_engine_mfu
    exported, forced-slow batch -> slo_breach_total + flight event in the
    bundle, perfreport renders from the live endpoints."""

    def test_live_worker_costs_mfu_slo_and_perfreport(self, monkeypatch):
        captures = []
        monkeypatch.setattr(profiling, "PROFILER", _FakeCapture(captures))
        reg = MetricsRegistry()
        engine = tiny_engine(reg, buckets=(16,), batch=4)
        bus = InMemoryBus(sync=False)
        bus.start()
        worker = TPUWorker(
            bus, engine,
            cfg=TPUWorkerConfig(worker_id="tpu-e2e",
                                heartbeat_s=30.0,
                                slo_batch_p95_ms=0.0001,
                                profile_on_slow_ms=0.0001),
            registry=reg)
        server = serve_metrics(0, reg)
        port = server.server_address[1]
        flight.RECORDER.reset()
        worker.start()
        try:
            bus.publish(TOPIC_INFERENCE_BATCHES, make_batch().to_dict())
            # The in-memory bus delivers asynchronously: wait for the
            # batch to be ACCEPTED (drain alone races an empty queue).
            assert wait_for(
                lambda: worker._processed + worker._errors >= 1)
            assert worker.drain(timeout_s=60.0)
            assert worker._processed == 1
            # /costs over HTTP: non-empty compiled-cost entries.
            status, body = get(f"http://127.0.0.1:{port}/costs")
            assert status == 200
            costs = json.loads(body)
            assert costs["worker_id"] == "tpu-e2e"
            assert costs["costs"], "live worker served an empty cost map"
            assert costs["efficiency"]["batches"] >= 1
            # The MFU gauge is exported on /metrics.
            _, metrics_text = get(f"http://127.0.0.1:{port}/metrics")
            assert "tpu_engine_mfu" in metrics_text
            assert "tpu_engine_goodput_tokens_per_s" in metrics_text
            # Forced-slow batch (budget 0.0001 ms): the SLO tick breaches
            # and the auto profiler hook fired on the slow step.
            breaches = worker._slo.evaluate()
            assert breaches and breaches[0]["slo"] == "batch_p95"
            _, metrics_text = get(f"http://127.0.0.1:{port}/metrics")
            assert 'slo_breach_total{slo="batch_p95"} 1' in metrics_text
            assert captures, "profile_on_slow_ms never fired"
            # Breach + slow-batch events land in the postmortem bundle.
            kinds = {e["kind"] for e in flight.RECORDER.events()}
            assert {"slo_breach", "slow_batch"} <= kinds
            bundle = flight.RECORDER.bundle("perf_test")
            assert any(e["kind"] == "slo_breach" for e in bundle["flight"])
            # perfreport renders the whole story from the live endpoints.
            live = perfreport.load_live(f"http://127.0.0.1:{port}")
            out = perfreport.render_report(*live)
            assert "tpu-e2e" in out
            assert "MFU" in out
            assert "per-bucket compiled cost" in out
            assert "batch_p95" in out
        finally:
            worker.stop()
            server.shutdown()
            bus.close()
            flight.RECORDER.reset()

    def test_slow_batch_hook_failure_never_nacks_the_batch(
            self, monkeypatch):
        # _after_step runs in the serving path's finally: an
        # observability failure (thread exhaustion, broken profiler)
        # must not turn a successful batch into outcome=error.
        class Exploding:
            def capture_async(self, seconds=1.0, reason=""):
                raise RuntimeError("can't start new thread")

        monkeypatch.setattr(profiling, "PROFILER", Exploding())
        reg = MetricsRegistry()
        engine = tiny_engine(reg, buckets=(16,), batch=4)
        bus = InMemoryBus(sync=False)
        bus.start()
        worker = TPUWorker(
            bus, engine,
            cfg=TPUWorkerConfig(worker_id="tpu-hook",
                                profile_on_slow_ms=0.0001),
            registry=reg)
        worker.start()
        try:
            bus.publish(TOPIC_INFERENCE_BATCHES, make_batch().to_dict())
            assert wait_for(
                lambda: worker._processed + worker._errors >= 1)
            assert worker._processed == 1
            assert worker._errors == 0
        finally:
            worker.stop()
            bus.close()

    def test_heartbeat_carries_efficiency(self):
        reg = MetricsRegistry()
        engine = tiny_engine(reg, buckets=(16,), batch=4)
        engine.run_tokenized([[1, 2, 3]] * 2)
        bus = InMemoryBus(sync=False)
        bus.start()
        worker = TPUWorker(bus, engine,
                           cfg=TPUWorkerConfig(worker_id="tpu-hb"),
                           registry=reg)
        try:
            snap = worker._telemetry.snapshot()
            assert snap["efficiency"]["batches"] >= 1
            assert "goodput_tokens_per_s" in snap["efficiency"]
        finally:
            bus.close()


class _FakeCapture:
    """Stands in for profiling.PROFILER in the e2e test: records the
    auto-capture requests instead of sleeping through real ones."""

    def __init__(self, calls):
        self.calls = calls

    def capture_async(self, seconds=1.0, reason=""):
        self.calls.append((seconds, reason))
        return True

    def capture(self, seconds):
        self.calls.append((seconds, "sync"))
        return {"ok": True, "code": 200, "path": "", "seconds": seconds}

    def snapshot(self):
        return {"active": False, "captures": len(self.calls),
                "last_path": "", "dump_dir": "", "max_seconds": 60.0}
