"""LoRA fine-tune (models/lora.py): adapters on the projection GEMMs,
merged into a plain float tree that the engine and the int8 converter
consume unchanged."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_crawler_tpu.inference.engine import (
    EngineConfig,
    InferenceEngine,
)
from distributed_crawler_tpu.models.encoder import TINY_TEST, EmbedderClassifier
from distributed_crawler_tpu.models.lora import (
    finetune_lora,
    init_lora_params,
    merge_lora,
)
from distributed_crawler_tpu.models.train import TrainConfig
from distributed_crawler_tpu.utils.metrics import MetricsRegistry
from tests.test_train_head import _dataset, _tiny_engine


def _params():
    model = EmbedderClassifier(TINY_TEST)
    ids = jnp.zeros((1, 8), jnp.int32)
    mask = jnp.ones((1, 8), jnp.bool_)
    return model.init(jax.random.PRNGKey(0), ids, mask)


class TestAdapters:
    def test_init_covers_all_four_projections(self):
        lora = init_lora_params(jax.random.PRNGKey(0), _params(), rank=4)
        layer = lora["layers_0"]
        assert set(layer) == {"attn/qkv/kernel", "attn/attn_out/kernel",
                              "mlp/mlp_up/kernel", "mlp/mlp_down/kernel"}
        qkv = layer["attn/qkv/kernel"]
        h = TINY_TEST.hidden
        assert qkv["a"].shape == (h, 4)
        assert qkv["b"].shape == (4, 3, h)          # fused-QKV layout kept
        assert float(jnp.abs(qkv["b"]).max()) == 0  # zero-init b

    def test_merge_with_zero_b_is_identity(self):
        params = _params()
        lora = init_lora_params(jax.random.PRNGKey(0), params, rank=4)
        merged = merge_lora(params, lora, rank=4)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(merged)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_merge_does_not_mutate_base(self):
        params = _params()
        lora = init_lora_params(jax.random.PRNGKey(0), params, rank=2)
        lora["layers_0"]["attn/qkv/kernel"]["b"] = jnp.ones_like(
            lora["layers_0"]["attn/qkv/kernel"]["b"])
        before = np.asarray(
            params["params"]["encoder"]["layers_0"]["attn"]["qkv/kernel"])
        merged = merge_lora(params, lora, rank=2)
        after = np.asarray(
            params["params"]["encoder"]["layers_0"]["attn"]["qkv/kernel"])
        np.testing.assert_array_equal(before, after)
        changed = np.asarray(
            merged["params"]["encoder"]["layers_0"]["attn"]["qkv/kernel"])
        assert not np.allclose(before, changed)

    def test_merge_rank_mismatch_rejected(self):
        params = _params()
        lora = init_lora_params(jax.random.PRNGKey(0), params, rank=4)
        with pytest.raises(ValueError, match="does not match"):
            merge_lora(params, lora, rank=2)

    def test_rank_and_label_validation(self):
        params = _params()
        with pytest.raises(ValueError, match="rank"):
            finetune_lora(TINY_TEST, params, [[1, 2]], [0], rank=0)
        with pytest.raises(ValueError, match="negative"):
            finetune_lora(TINY_TEST, params, [[1, 2]], [-1], rank=2)


class TestFinetuneLora:
    def test_loss_drops_and_adapters_move_encoder(self):
        engine = _tiny_engine()
        texts, labels = _dataset()
        toks = engine.tokenizer.encode_batch(texts)
        merged, history = finetune_lora(
            engine.ecfg, engine.params, toks, labels, rank=4,
            tc=TrainConfig(learning_rate=5e-3, warmup_steps=5),
            epochs=8, batch_size=16)
        assert history[-1]["loss"] < history[0]["loss"] * 0.8
        # The encoder itself moved (not just the head) ...
        k0 = np.asarray(engine.params["params"]["encoder"]["layers_0"]
                        ["attn"]["qkv/kernel"])
        k1 = np.asarray(merged["params"]["encoder"]["layers_0"]
                        ["attn"]["qkv/kernel"])
        assert not np.allclose(k0, k1)
        # ... and the merged tree serves: held-out accuracy beats random.
        engine.params = merged
        held_texts, held_labels = _dataset(n_per_class=10, seed=7)
        out = engine.run(held_texts)
        acc = np.mean([r["label"] == y for r, y in zip(out, held_labels)])
        assert acc >= 0.8, f"held-out accuracy {acc} not above random"

    def test_lora_on_moe_config(self):
        """LoRA adapters compose with switch-MoE encoders: projections
        get adapters, router/expert weights stay frozen, and the aux-loss
        sow in SwitchMoE is a no-op under LoRA's non-mutable apply."""
        from dataclasses import replace

        from distributed_crawler_tpu.models.encoder import Classifier

        cfg = replace(TINY_TEST, n_experts=4, n_labels=2)
        model = Classifier(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        mask = jnp.ones((1, 8), jnp.bool_)
        params = model.init(jax.random.PRNGKey(0), ids, mask)
        rng = np.random.default_rng(0)
        toks = [[1 + int(rng.integers(0, 50))] * 12 for _ in range(16)]
        labels = [i % 2 for i in range(16)]
        merged, history = finetune_lora(
            cfg, params, toks, labels, rank=2,
            tc=TrainConfig(learning_rate=5e-3, warmup_steps=2),
            epochs=3, batch_size=8)
        assert history[-1]["loss"] < history[0]["loss"]
        # Expert weights were NOT touched (LoRA targets projections only).
        e0 = np.asarray(params["params"]["encoder"]["layers_0"]["moe"]
                        ["experts_up/kernel"])
        e1 = np.asarray(merged["params"]["encoder"]["layers_0"]["moe"]
                        ["experts_up/kernel"])
        np.testing.assert_array_equal(e0, e1)

    def test_merged_tree_quantizes(self):
        from distributed_crawler_tpu.models.quant import (
            quantize_encoder_params,
        )

        engine = _tiny_engine()
        texts, labels = _dataset(n_per_class=8)
        toks = engine.tokenizer.encode_batch(texts)
        merged, _ = finetune_lora(engine.ecfg, engine.params, toks, labels,
                                  rank=2, epochs=1, batch_size=8)
        q = quantize_encoder_params(merged)
        assert (q["params"]["encoder"]["layers_0"]["attn"]
                ["qkv/kernel_q"].dtype == jnp.int8)


class TestCli:
    def test_negative_lora_rank_rejected(self, tmp_path, capsys):
        from distributed_crawler_tpu.cli import main

        posts = tmp_path / "posts.jsonl"
        posts.write_text(json.dumps({"post_uid": "p0", "all_text": "x"})
                         + "\n")
        labels = tmp_path / "labels.jsonl"
        labels.write_text(json.dumps({"post_uid": "p0", "label": 0}) + "\n")
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels),
                   "--head-checkpoint", str(tmp_path / "ckpt"),
                   "--train-lora-rank", "-8",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 2

    def test_train_head_mode_with_lora_rank(self, tmp_path):
        from distributed_crawler_tpu.cli import main
        from distributed_crawler_tpu.inference.checkpoint import (
            latest_step_dir,
            load_params,
        )

        texts, labels = _dataset(n_per_class=12)
        posts = tmp_path / "posts.jsonl"
        with open(posts, "w", encoding="utf-8") as f:
            for i, text in enumerate(texts):
                f.write(json.dumps({"post_uid": f"p{i}", "all_text": text})
                        + "\n")
        labels_file = tmp_path / "labels.jsonl"
        with open(labels_file, "w", encoding="utf-8") as f:
            for i, y in enumerate(labels):
                f.write(json.dumps({"post_uid": f"p{i}", "label": int(y)})
                        + "\n")
        ckpt = str(tmp_path / "ckpt")
        rc = main(["--mode", "train-head", "--infer-model", "tiny",
                   "--train-posts", str(posts),
                   "--train-labels", str(labels_file),
                   "--head-checkpoint", ckpt,
                   "--train-epochs", "2",
                   "--train-lora-rank", "4",
                   "--train-lr", "0.005",
                   "--storage-root", str(tmp_path / "store")])
        assert rc == 0
        saved = load_params(latest_step_dir(ckpt) or ckpt)
        # The merged checkpoint must be full-precision and engine-loadable.
        dtypes = {leaf.dtype for leaf in jax.tree.leaves(saved)
                  if hasattr(leaf, "dtype")}
        assert dtypes == {np.dtype("float32")}
        eng = InferenceEngine(
            EngineConfig(model="tiny", batch_size=8, buckets=(16,),
                         checkpoint_dir=ckpt),
            registry=MetricsRegistry())
        out = eng.run(["alpha beta gamma"])
        assert out[0]["label"] in (0, 1)
