"""Attention + padding op tests (flash kernel runs in Pallas interpret mode)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_crawler_tpu.ops import (
    BucketSpec,
    attend,
    bucket_for,
    flash_attention,
    mha,
    pack_batch,
    pack_rows,
    pad_to_bucket,
)
from distributed_crawler_tpu.ops.padding import group_by_bucket


def _inputs(b=2, l=64, h=2, d=16, seed=1):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    mask = np.ones((b, l), dtype=bool)
    mask[0, l // 2:] = False
    return q, k, v, jnp.asarray(mask)


class TestAttention:
    def test_attend_shape_dtype(self):
        q, k, v, mask = _inputs()
        out = attend(q, k, v, mask)
        assert out.shape == q.shape and out.dtype == q.dtype

    def test_masked_keys_ignored(self):
        q, k, v, mask = _inputs()
        # Perturb masked-out keys/values: output must not change.
        k2 = k.at[0, 40:].set(99.0)
        v2 = v.at[0, 40:].set(-99.0)
        np.testing.assert_allclose(np.asarray(attend(q, k, v, mask)),
                                   np.asarray(attend(q, k2, v2, mask)),
                                   atol=1e-6)

    def test_flash_matches_reference(self):
        q, k, v, mask = _inputs()
        ref = attend(q, k, v, mask)
        out = flash_attention(q, k, v, mask, block_q=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_flash_no_mask(self):
        q, k, v, _ = _inputs()
        ref = attend(q, k, v)
        out = flash_attention(q, k, v, block_q=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_flash_matches_reference_bench_shape(self):
        """The serving-bench geometry (seq 128, head_dim 32, bf16): parity
        within bf16 tolerance so the short-seq flash policy is safe."""
        q, k, v, mask = _inputs(b=3, l=128, h=4, d=32, seed=7)
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        ref = attend(q, k, v, mask)
        out = flash_attention(q, k, v, mask, block_q=128, interpret=True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=2e-2, rtol=2e-2)

    def test_flash_fully_masked_row_zeros(self):
        """A fully-padded sequence must come out all-zero (matching
        attend's masked-softmax convention), not NaN."""
        q, k, v, mask = _inputs(b=2, l=64, h=2, d=16)
        mask = mask.at[1, :].set(False)
        out = flash_attention(q, k, v, mask, block_q=32, interpret=True)
        got = np.asarray(out)
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got[1], 0.0, atol=1e-6)
        ref = np.asarray(attend(q, k, v, mask))
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)

    def test_flash_indivisible_block_raises(self):
        q, k, v, mask = _inputs(l=48)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, mask, block_q=32, interpret=True)

    def test_mha_dispatches_xla_on_cpu(self):
        q, k, v, mask = _inputs()
        out = mha(q, k, v, mask)  # auto: CPU backend -> XLA path
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(attend(q, k, v, mask)),
                                   atol=1e-6)


class TestPadding:
    def test_bucket_for(self):
        spec = BucketSpec((32, 64, 128))
        assert bucket_for(1, spec) == 32
        assert bucket_for(32, spec) == 32
        assert bucket_for(33, spec) == 64
        assert bucket_for(999, spec) == 128  # over-long truncates to max

    def test_bucket_spec_validation(self):
        with pytest.raises(ValueError):
            BucketSpec((64, 32))
        with pytest.raises(ValueError):
            BucketSpec(())

    def test_pad_to_bucket(self):
        ids, mask = pad_to_bucket([5, 6, 7], 8)
        assert ids.tolist() == [5, 6, 7, 0, 0, 0, 0, 0]
        assert mask.tolist() == [True] * 3 + [False] * 5

    def test_pad_truncates(self):
        ids, mask = pad_to_bucket(list(range(10)), 4)
        assert ids.tolist() == [0, 1, 2, 3]
        assert mask.all()

    def test_pack_batch_shapes(self):
        ids, mask = pack_batch([[1, 2], [3, 4, 5, 6, 7]],
                               BucketSpec((4, 8)))
        assert ids.shape == (2, 8)
        assert mask.sum() == 7

    def test_pack_batch_pads_batch_dim(self):
        ids, mask = pack_batch([[1, 2]], BucketSpec((4,)), batch_pad_to=4)
        assert ids.shape == (4, 4)
        assert mask[1:].sum() == 0

    def test_pack_empty_raises(self):
        with pytest.raises(ValueError):
            pack_batch([])

    def test_group_by_bucket(self):
        groups = group_by_bucket([[1] * 3, [1] * 60, [1] * 5],
                                 BucketSpec((32, 64)))
        assert groups[32] == [0, 2]
        assert groups[64] == [1]


class TestPackRows:
    def test_every_sequence_placed_exactly_once(self):
        seqs = [[i] * n for i, n in enumerate([3, 5, 10, 2, 7, 4, 6, 1])]
        p = pack_rows(seqs, 16, max_segments=4)
        placed = sorted(i for row in p.assignments for i in row)
        assert placed == list(range(len(seqs)))

    def test_row_arrays_match_assignments(self):
        seqs = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]
        p = pack_rows(seqs, 8, max_segments=4)
        for r, row in enumerate(p.assignments):
            off = 0
            for s, orig in enumerate(row, start=1):
                n = len(seqs[orig])
                assert p.ids[r, off:off + n].tolist() == seqs[orig]
                assert p.mask[r, off:off + n].all()
                assert (p.segment_ids[r, off:off + n] == s).all()
                # Positions restart at 0 per segment: packed sequences see
                # the same absolute position embeddings as unpacked ones.
                assert p.positions[r, off:off + n].tolist() == list(range(n))
                off += n
            assert not p.mask[r, off:].any()
            assert (p.segment_ids[r, off:] == 0).all()

    def test_occupancy_bounds(self):
        seqs = [[1]] * 40  # 40 one-token sequences
        p = pack_rows(seqs, 16, max_segments=8)
        assert max(len(row) for row in p.assignments) <= 8
        assert p.n_rows == 5  # 40 / 8 slots per row
        assert (p.mask.sum(axis=1) <= 16).all()

    def test_token_capacity_respected(self):
        seqs = [[1] * 10, [2] * 10, [3] * 10]
        p = pack_rows(seqs, 16, max_segments=8)
        # 10+10 > 16: each row holds one sequence despite free slots.
        assert p.n_rows == 3

    def test_overlong_truncates_to_bucket(self):
        p = pack_rows([list(range(20))], 8)
        assert p.ids[0].tolist() == list(range(8))
        assert p.mask[0].all()

    def test_denser_than_one_row_each(self):
        seqs = [[1] * 4 for _ in range(32)]
        p = pack_rows(seqs, 32, max_segments=8)
        assert p.n_rows == 4  # 8 x 4 tokens per 32-row, vs 32 unpacked rows

    def test_bad_args_raise(self):
        with pytest.raises(ValueError):
            pack_rows([[1]], 0)
        with pytest.raises(ValueError):
            pack_rows([[1]], 8, max_segments=0)
        with pytest.raises(ValueError):
            pack_rows([[1], [2]], 8, indices=[5])


def _packed_attention_fixture(seed=3):
    """Two packed rows: row 0 = segments 1 (6 tok) + 2 (6 tok) + padding,
    row 1 = one segment of 10 + padding."""
    rng = np.random.default_rng(seed)
    b, l, h, d = 2, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    seg = np.zeros((b, l), np.int32)
    seg[0, :6] = 1
    seg[0, 6:12] = 2
    seg[1, :10] = 1
    mask = jnp.asarray(seg > 0)
    return q, k, v, mask, jnp.asarray(seg)


class TestSegmentAttention:
    def test_segments_isolated_bit_identical(self):
        """Perturbing every tensor of segment 1 leaves segment 2's output
        BIT-identical: masked scores are replaced by a constant before the
        softmax and re-zeroed after, so neighbor values never reach it."""
        q, k, v, mask, seg = _packed_attention_fixture()
        base = np.asarray(attend(q, k, v, mask, segment_ids=seg))
        q2 = q.at[0, :6].set(77.0)
        k2 = k.at[0, :6].set(99.0)
        v2 = v.at[0, :6].set(-55.0)
        out = np.asarray(attend(q2, k2, v2, mask, segment_ids=seg))
        assert np.array_equal(base[0, 6:12], out[0, 6:12])
        assert np.array_equal(base[1], out[1])  # other row untouched

    def test_packed_matches_each_segment_alone(self):
        """A packed segment's output equals running that segment through
        attention on its own (the packing-changes-nothing contract)."""
        q, k, v, mask, seg = _packed_attention_fixture()
        packed = np.asarray(attend(q, k, v, mask, segment_ids=seg))
        for row, sl in ((0, slice(0, 6)), (0, slice(6, 12)),
                        (1, slice(0, 10))):
            alone = attend(q[row:row + 1, sl], k[row:row + 1, sl],
                           v[row:row + 1, sl])
            np.testing.assert_allclose(packed[row, sl],
                                       np.asarray(alone)[0], atol=1e-6)

    def test_flash_matches_attend_with_segments(self):
        q, k, v, mask, seg = _packed_attention_fixture()
        ref = attend(q, k, v, mask, segment_ids=seg)
        out = flash_attention(q, k, v, mask, block_q=8, interpret=True,
                              segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_flash_segments_isolated(self):
        q, k, v, mask, seg = _packed_attention_fixture()
        base = np.asarray(flash_attention(q, k, v, mask, block_q=8,
                                          interpret=True, segment_ids=seg))
        k2 = k.at[0, :6].set(99.0)
        v2 = v.at[0, :6].set(-55.0)
        out = np.asarray(flash_attention(q, k2, v2, mask, block_q=8,
                                         interpret=True, segment_ids=seg))
        assert np.array_equal(base[0, 6:12], out[0, 6:12])
        assert np.array_equal(base[1], out[1])

    def test_mha_threads_segment_ids(self):
        q, k, v, mask, seg = _packed_attention_fixture()
        out = mha(q, k, v, mask, segment_ids=seg)  # CPU -> XLA path
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(attend(q, k, v, mask, segment_ids=seg)), atol=1e-6)
