"""Execution-mode tests: router, layered drivers, layerless random walk,
validator mode, YouTube random sampling, resume helpers.

Reference analogs: standalone/runner_test.go (1162 LoC), the driver logic of
dapr/standalone.go exercised here through the injection seams (stubbed
run_for_channel, fake YouTube transport, in-memory state).
"""

import threading
import time
from datetime import datetime, timezone

import pytest

from distributed_crawler_tpu.clients import SimNetwork, SimTelegramClient
from distributed_crawler_tpu.clients.pool import ConnectionPool
from distributed_crawler_tpu.clients.youtube import FakeYouTubeTransport
from distributed_crawler_tpu.config import CrawlerConfig
from distributed_crawler_tpu.crawl import runner as crawl_runner
from distributed_crawler_tpu.crawl.errors import (
    FloodWaitRetireError,
    TDLib400Error,
    WalkbackExhaustedError,
)
from distributed_crawler_tpu.crawl.runner import set_run_for_channel_fn
from distributed_crawler_tpu.modes import (
    ValidatorCircuitBreakerError,
    YtWorkerPool,
    calculate_date_filters,
    determine_crawl_id,
    launch,
    normalize_seed_urls,
    process_layer_in_parallel,
    process_layers_iteratively,
    run_random_walk_layerless,
    run_random_youtube_sample,
    run_sequential_layers,
    seed_random_walk,
)
from distributed_crawler_tpu.state import (
    CompositeStateManager,
    Page,
    SqlConfig,
    StateConfig,
)
from distributed_crawler_tpu.state.datamodels import Layer, new_id
from tests.test_crawl_engine import text_msg


def make_sm(tmp_path, crawl_id="c1", sampling="channel", sub="s"):
    return CompositeStateManager(StateConfig(
        crawl_id=crawl_id, crawl_execution_id="e1",
        storage_root=str(tmp_path / sub), sampling_method=sampling,
        sql=SqlConfig(url=":memory:")))


def make_cfg(**kw):
    base = dict(crawl_id="c1", platform="telegram", skip_media_download=True,
                sampling_method="channel", concurrency=2)
    base.update(kw)
    return CrawlerConfig(**base)


@pytest.fixture
def stub_pool():
    """A pool of dummy clients so the facade hands out connections."""
    crawl_runner.shutdown_connection_pool()
    net = SimNetwork()
    clients = {f"conn{i}": SimTelegramClient(net, conn_id=f"conn{i}")
               for i in range(3)}
    crawl_runner.init_connection_pool(ConnectionPool.for_testing(clients))
    yield net
    crawl_runner.shutdown_connection_pool()
    set_run_for_channel_fn(None)


class TestHelpers:
    def test_normalize_seed_urls(self):
        assert normalize_seed_urls([
            "https://t.me/Alpha", "http://t.me/BETA", "t.me/gamma",
            "@Delta", "plain"]) == [
            "alpha", "beta", "gamma", "delta", "plain"]

    def test_date_filters_precedence(self):
        a = datetime(2025, 1, 1, tzinfo=timezone.utc)
        b = datetime(2025, 6, 1, tzinfo=timezone.utc)
        c = datetime(2025, 3, 1, tzinfo=timezone.utc)
        cfg = make_cfg(date_between_min=a, date_between_max=b, post_recency=c)
        assert calculate_date_filters(cfg) == (a, b)
        cfg = make_cfg(post_recency=c)
        lo, hi = calculate_date_filters(cfg)
        assert lo == c and hi is not None
        cfg = make_cfg(min_post_date=a)
        lo, hi = calculate_date_filters(cfg)
        assert lo == a and hi is not None

    def test_determine_crawl_id_resume(self):
        class TempSM:
            def find_incomplete_crawl(self, crawl_id):
                return "prev-exec", True

            def close(self):
                pass

        exec_id, resuming = determine_crawl_id(TempSM(), make_cfg())
        assert exec_id == "prev-exec" and resuming

    def test_determine_crawl_id_fresh(self):
        class TempSM:
            def find_incomplete_crawl(self, crawl_id):
                return "", False

            def close(self):
                pass

        exec_id, resuming = determine_crawl_id(TempSM(), make_cfg())
        assert exec_id and not resuming


class TestLayerDrivers:
    def _seed(self, sm, urls, depth=0):
        sm.initialize([])
        sm.add_layer([Page(id=new_id(), url=u, depth=depth) for u in urls])

    def test_parallel_layer_processes_and_builds_next(self, tmp_path,
                                                      stub_pool):
        sm = make_sm(tmp_path)
        self._seed(sm, ["a", "b"])

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            if page.url == "a":
                return [Page(id=new_id(), url="c", depth=page.depth + 1,
                             parent_id=page.id)]
            return []

        set_run_for_channel_fn(fake_run)
        layer = Layer(depth=0, pages=sm.get_layer_by_depth(0))
        n = process_layer_in_parallel(layer, 2, sm, make_cfg())
        assert n == 2
        assert all(p.status == "fetched" for p in sm.get_layer_by_depth(0))
        assert [p.url for p in sm.get_layer_by_depth(1)] == ["c"]

    def test_parallel_layer_contains_failures(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path)
        self._seed(sm, ["ok", "boom"])

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            if page.url == "boom":
                raise RuntimeError("kaput")
            return []

        set_run_for_channel_fn(fake_run)
        layer = Layer(depth=0, pages=sm.get_layer_by_depth(0))
        process_layer_in_parallel(layer, 2, sm, make_cfg())
        by_url = {p.url: p for p in sm.get_layer_by_depth(0)}
        assert by_url["ok"].status == "fetched"
        assert by_url["boom"].status == "error"
        assert "kaput" in by_url["boom"].error

    def test_iterative_walk_to_max_depth(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path)
        self._seed(sm, ["a"])
        calls = []

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            calls.append(page.url)
            if page.depth < 2:
                return [Page(id=new_id(), url=page.url + "x",
                             depth=page.depth + 1, parent_id=page.id)]
            return []

        set_run_for_channel_fn(fake_run)
        total = process_layers_iteratively(sm, make_cfg(), True)
        assert calls == ["a", "ax", "axx"]
        assert total == 3

    def test_sequential_walk_follows_discoveries(self, tmp_path, stub_pool):
        """Standalone BFS must persist discovered pages as the next layer
        (`standalone/runner.go:834-847`) — regression: discoveries were
        returned but dropped, so every standalone crawl stopped at the
        seed layer."""
        sm = make_sm(tmp_path)
        self._seed(sm, ["a"])
        calls = []

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            calls.append(page.url)
            if page.depth < 2:
                return [Page(id=new_id(), url=page.url + "x",
                             depth=page.depth + 1, parent_id=page.id)]
            return []

        set_run_for_channel_fn(fake_run)
        total = run_sequential_layers(sm, make_cfg(), True)
        assert calls == ["a", "ax", "axx"]
        assert total == 3
        assert [p.url for p in sm.get_layer_by_depth(2)] == ["axx"]

    def test_sequential_walk_skips_fetched_on_resume(self, tmp_path,
                                                     stub_pool):
        sm = make_sm(tmp_path)
        self._seed(sm, ["a", "b"])
        pages = sm.get_layer_by_depth(0)
        pages[0].status = "fetched"
        sm.update_page(pages[0])
        calls = []

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            calls.append(page.url)
            return []

        set_run_for_channel_fn(fake_run)
        n = run_sequential_layers(sm, make_cfg(), True)
        assert calls == ["b"]
        assert n == 1

    def test_duplicate_urls_in_layer_skipped(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path)
        sm.initialize([])
        calls = []

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            calls.append(page.url)
            return []

        set_run_for_channel_fn(fake_run)
        layer = Layer(depth=0, pages=[
            Page(id=new_id(), url="dup", depth=0),
            Page(id=new_id(), url="dup", depth=0)])
        process_layer_in_parallel(layer, 2, sm, make_cfg())
        assert calls == ["dup"]


class TestLayerless:
    def test_walk_until_buffer_empty(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize([])
        chain = {"a": "b", "b": "c"}

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            nxt = chain.get(page.url)
            if nxt:
                sm_.add_page_to_page_buffer(Page(
                    id=new_id(), url=nxt, depth=page.depth + 1,
                    sequence_id=new_id()))
            return []

        set_run_for_channel_fn(fake_run)
        sm.add_page_to_page_buffer(Page(id=new_id(), url="a", depth=0,
                                        sequence_id=new_id()))
        cfg = make_cfg(sampling_method="random-walk", concurrency=2)
        run_random_walk_layerless(sm, cfg, poll_interval_s=0.01)
        assert sm.get_pages_from_page_buffer(10) == []

    def test_400_replacement_and_delete(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize([])
        sm.initialize_discovered_channels()
        sm.add_discovered_channel("fallback")
        replaced = []

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            if page.url == "bad":
                raise TDLib400Error("USERNAME_NOT_OCCUPIED")
            replaced.append(page.url)
            return []

        set_run_for_channel_fn(fake_run)
        sm.add_page_to_page_buffer(Page(id=new_id(), url="bad", depth=0,
                                        sequence_id=new_id()))
        cfg = make_cfg(sampling_method="random-walk", concurrency=1)
        run_random_walk_layerless(sm, cfg, poll_interval_s=0.01)
        # 400 page replaced by a walkback to the discovered channel, which
        # then got processed and drained.
        assert replaced == ["fallback"]
        assert sm.is_invalid_channel("bad")

    def test_floodwait_retire_empties_pool_aborts(self, tmp_path):
        crawl_runner.shutdown_connection_pool()
        net = SimNetwork()
        crawl_runner.init_connection_pool(ConnectionPool.for_testing(
            {"c0": SimTelegramClient(net, conn_id="c0")}))
        try:
            sm = make_sm(tmp_path, sampling="random-walk")
            sm.initialize([])

            def fake_run(client, page, prefix, sm_, cfg, processor=None,
                         rng=None):
                raise FloodWaitRetireError(400)

            set_run_for_channel_fn(fake_run)
            sm.add_page_to_page_buffer(Page(id=new_id(), url="x", depth=0,
                                            sequence_id=new_id()))
            cfg = make_cfg(sampling_method="random-walk", concurrency=1)
            run_random_walk_layerless(sm, cfg, poll_interval_s=0.01)
            # Page left in buffer for a future restart.
            assert [p.url for p in sm.get_pages_from_page_buffer(10)] == ["x"]
        finally:
            crawl_runner.shutdown_connection_pool()
            set_run_for_channel_fn(None)

    def test_tandem_circuit_breaker(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize([])

        class StuckSM:
            """Empty buffer but forever-incomplete batches."""

            def __getattr__(self, name):
                return getattr(sm, name)

            def get_pages_from_page_buffer(self, limit):
                return []

            def count_incomplete_batches(self, crawl_id):
                return 3

        cfg = make_cfg(sampling_method="random-walk", tandem_crawl=True,
                       validator_timeout_s=0.05)
        with pytest.raises(ValidatorCircuitBreakerError):
            run_random_walk_layerless(StuckSM(), cfg, poll_interval_s=0.01)

    def test_walkback_exhausted_page_parked_not_respun(self, tmp_path,
                                                       stub_pool):
        """A page that deterministically exhausts walkback must be parked
        (left for the next run), not re-dispatched in a hot loop."""
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize([])
        attempts = []

        def fake_run(client, page, prefix, sm_, cfg, processor=None,
                     rng=None):
            attempts.append(page.url)
            raise WalkbackExhaustedError("no discovered channels")

        set_run_for_channel_fn(fake_run)
        sm.add_page_to_page_buffer(Page(id=new_id(), url="deadend", depth=0,
                                        sequence_id=new_id()))
        cfg = make_cfg(sampling_method="random-walk", concurrency=1)
        run_random_walk_layerless(sm, cfg, poll_interval_s=0.01)
        # Dispatched exactly once, then parked; page still buffered for
        # the next run.
        assert attempts == ["deadend"]
        assert [p.url for p in sm.get_pages_from_page_buffer(5)] \
            == ["deadend"]

    def test_tandem_completes_when_no_batches(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path, sampling="random-walk")
        sm.initialize([])
        cfg = make_cfg(sampling_method="random-walk", tandem_crawl=True)
        # Empty buffer + zero incomplete batches -> immediate completion.
        run_random_walk_layerless(sm, cfg, poll_interval_s=0.01)


class TestYtPool:
    def test_rotation_after_retirement(self):
        created = []

        class FakeCrawler:
            def __init__(self):
                created.append(self)
                self.closed = False

            def close(self):
                self.closed = True

        import random as _random
        pool = YtWorkerPool(FakeCrawler, size=1, rng=_random.Random(0))
        first = created[0]
        w = pool.acquire()
        w.usage = w.retire_at - 1  # next release triggers rotation
        pool.release(w)
        assert first.closed
        assert len(created) == 2
        pool.close()


class TestYoutubeRandom:
    def test_sampling_until_target(self, tmp_path):
        from distributed_crawler_tpu.datamodel import Post

        class FakeCrawler:
            """Two posts per fetch; first call fails to exercise the retry."""

            def __init__(self):
                self.calls = 0

            def fetch_messages(self, job):
                from distributed_crawler_tpu.crawlers.base import CrawlResult
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("quota hiccup")
                return CrawlResult(posts=[
                    Post(post_uid=f"p{self.calls}-{i}") for i in range(2)])

        sm = make_sm(tmp_path)
        sm.initialize([])
        cfg = make_cfg(platform="youtube", sampling_method="random",
                       sample_size=5, youtube_api_key="k")
        crawler = FakeCrawler()
        total = run_random_youtube_sample(sm, cfg, crawler=crawler,
                                          sleep=lambda s: None)
        # 3 successful fetches x 2 posts >= 5 target; retry absorbed the
        # first failure.
        assert total == 6
        assert crawler.calls == 4

    def test_zero_sample_size_noop(self, tmp_path):
        sm = make_sm(tmp_path)
        total = run_random_youtube_sample(
            sm, make_cfg(platform="youtube", sample_size=0),
            transport=FakeYouTubeTransport())
        assert total == 0


class TestLaunchRouter:
    def test_layered_telegram_end_to_end(self, tmp_path):
        """Full launch() through the REAL crawl engine over the sim network."""
        crawl_runner.shutdown_connection_pool()
        net = SimNetwork()
        net.add_channel("alpha", messages=[
            text_msg("see t.me/beta", date=1700000000, view_count=4)],
            member_count=60)
        net.add_channel("beta", messages=[
            text_msg("the end", date=1700000050, view_count=2)],
            member_count=70)
        crawl_runner.init_connection_pool(ConnectionPool.for_testing(
            {"c0": SimTelegramClient(net, conn_id="c0")}))
        try:
            sm = make_sm(tmp_path)
            launch(["alpha"], make_cfg(concurrency=1), sm=sm)
            assert all(p.status == "fetched"
                       for p in sm.get_layer_by_depth(0))
            assert [p.url for p in sm.get_layer_by_depth(1)] == ["beta"]
        finally:
            crawl_runner.shutdown_connection_pool()

    def test_random_walk_seeding(self, tmp_path, stub_pool):
        sm = make_sm(tmp_path, sampling="random-walk")
        seed_random_walk(sm, ["alpha", "beta"])
        urls = {p.url for p in sm.get_pages_from_page_buffer(10)}
        assert urls == {"alpha", "beta"}
        # Re-seeding on resume leaves the buffer untouched.
        seed_random_walk(sm, ["gamma"])
        urls = {p.url for p in sm.get_pages_from_page_buffer(10)}
        assert urls == {"alpha", "beta"}
