"""Inference stack tests: tokenizer, engine, worker service, metrics."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from distributed_crawler_tpu.bus.codec import RecordBatch
from distributed_crawler_tpu.bus.inmemory import InMemoryBus
from distributed_crawler_tpu.bus.messages import (
    TOPIC_INFERENCE_BATCHES,
    TOPIC_INFERENCE_RESULTS,
    TOPIC_WORKER_STATUS,
)
from distributed_crawler_tpu.datamodel import Post
from distributed_crawler_tpu.inference import (
    EngineConfig,
    HashingTokenizer,
    InferenceEngine,
    TPUWorker,
    TPUWorkerConfig,
)
from distributed_crawler_tpu.inference.tokenizer import CLS_ID, SEP_ID
from distributed_crawler_tpu.state.providers import InMemoryStorageProvider
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    serve_metrics,
)


class TestHashingTokenizer:
    def test_deterministic(self):
        tok = HashingTokenizer(1000)
        assert tok.encode("Hello World") == tok.encode("hello  world")

    def test_cls_sep_framing(self):
        ids = HashingTokenizer(1000).encode("abc")
        assert ids[0] == CLS_ID and ids[-1] == SEP_ID

    def test_ids_in_range(self):
        ids = HashingTokenizer(100).encode("the quick brown fox jumps")
        assert all(0 <= i < 100 for i in ids)

    def test_long_token_split(self):
        tok = HashingTokenizer(10_000, max_word_len=4)
        a = tok.encode("abcdefgh")
        b = tok.encode("abcdzzzz")
        assert a[1] == b[1]          # shared 4-char prefix piece
        assert a[2] != b[2]          # differing second piece

    def test_unicode_normalized(self):
        tok = HashingTokenizer(1000)
        assert tok.encode("Ｃａｆé") == tok.encode("café")  # NFKC fold

    def test_tiny_vocab_rejected(self):
        with pytest.raises(ValueError):
            HashingTokenizer(3)

    def test_token_memo_matches_whole_text_regex(self):
        """The whitespace-token memo fast path must produce ids IDENTICAL
        to running the word regex over the whole text (the memo is an
        optimization, never a semantic change) — incl. punctuation glued
        to words, long-token splitting, unicode, and repeat calls that
        hit the warm path."""
        import re
        import unicodedata

        word_re = re.compile(r"\w+|[^\w\s]", re.UNICODE)
        tok = HashingTokenizer(50_000, max_word_len=6)

        def reference(text):
            text = unicodedata.normalize("NFKC", text or "").lower()
            ids = [CLS_ID]
            for w in word_re.findall(text):
                if len(w) <= tok.max_word_len:
                    ids.append(tok._fnv_id(w))
                else:
                    ids += [tok._fnv_id(w[i:i + tok.max_word_len])
                            for i in range(0, len(w), tok.max_word_len)]
            return ids + [SEP_ID]

        samples = [
            "Hello, WORLD! visit https://t.me/chan/12345",
            "glued,punct...and--dashes (parens) [brackets]",
            "  spaces\ttabs\nnewlines  ",
            "",
            "İstanbul Über straße \U0001F600",
            "x" * 50 + " short " + "y" * 50,
        ]
        for s in samples:
            assert tok.encode(s) == reference(s), repr(s)
            assert tok.encode(s) == reference(s), f"warm path: {s!r}"

    def test_token_memo_equivalence_property(self):
        """Property form of the equivalence: arbitrary unicode (exotic
        whitespace, astral chars, control chars) must tokenize identically
        on the memoized fast path and the whole-text regex."""
        hypothesis = pytest.importorskip("hypothesis")
        import re
        import unicodedata

        from hypothesis import given, settings
        from hypothesis import strategies as st

        word_re = re.compile(r"\w+|[^\w\s]", re.UNICODE)
        tok = HashingTokenizer(50_000, max_word_len=5)

        def reference(text):
            text = unicodedata.normalize("NFKC", text or "").lower()
            ids = [CLS_ID]
            for w in word_re.findall(text):
                if len(w) <= tok.max_word_len:
                    ids.append(tok._fnv_id(w))
                else:
                    ids += [tok._fnv_id(w[i:i + tok.max_word_len])
                            for i in range(0, len(w), tok.max_word_len)]
            return ids + [SEP_ID]

        @settings(max_examples=500, deadline=None)
        @given(st.text(max_size=80))
        def check(s):
            assert tok.encode(s) == reference(s)

        check()


def _engine(registry=None, **kw):
    cfg = EngineConfig(model="tiny", n_labels=3, batch_size=4,
                       buckets=(16, 32), **kw)
    return InferenceEngine(cfg, registry=registry or MetricsRegistry())


class TestInferenceEngine:
    def test_run_returns_per_text_results(self):
        eng = _engine()
        out = eng.run(["hello world", "a much longer piece of text " * 3,
                       "third"])
        assert len(out) == 3
        for r in out:
            assert len(r["embedding"]) == 64
            assert 0 <= r["label"] < 3
            np.testing.assert_allclose(sum(r["scores"]), 1.0, atol=1e-5)

    def test_results_in_input_order(self):
        eng = _engine()
        texts = ["short", "x " * 25, "short again"]  # mixed buckets
        out1 = eng.run(texts)
        out2 = eng.run(list(texts))
        for a, b in zip(out1, out2):
            np.testing.assert_allclose(a["embedding"], b["embedding"],
                                       atol=1e-6)

    def test_embedding_unit_norm(self):
        eng = _engine()
        emb = eng.embed(["some text", "other text"])
        np.testing.assert_allclose(np.linalg.norm(emb, axis=-1), 1.0,
                                   atol=1e-5)

    def test_oversize_batch_chunks(self):
        eng = _engine()  # batch_size=4
        out = eng.run([f"text {i}" for i in range(11)])
        assert len(out) == 11

    def test_attention_mode_plumbs_to_encoder(self):
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        eng = InferenceEngine(
            EngineConfig(model="tiny", batch_size=4, buckets=(32,),
                         attention="xla"),
            registry=MetricsRegistry())
        assert eng.ecfg.attention == "xla"
        assert _engine().ecfg.attention == "auto"  # default untouched
        with pytest.raises(ValueError, match="attention"):
            InferenceEngine(
                EngineConfig(model="tiny", attention="paged"),
                registry=MetricsRegistry())

    def test_cli_attention_flag_reaches_engine(self):
        from distributed_crawler_tpu.cli import (
            _make_engine,
            build_parser,
            resolve_config,
        )

        args = build_parser().parse_args(
            ["--urls", "a", "--infer-model", "tiny",
             "--infer-attention", "xla"])
        cfg, r = resolve_config(args, env={})
        eng = _make_engine(cfg, r)
        assert eng.ecfg.attention == "xla"

    def test_moe_dispatch_override_plumbs_to_encoder(self):
        from distributed_crawler_tpu.cli import (
            _make_engine,
            build_parser,
            resolve_config,
        )
        from distributed_crawler_tpu.inference.engine import (
            EngineConfig,
            InferenceEngine,
        )
        from distributed_crawler_tpu.utils.metrics import MetricsRegistry

        eng = InferenceEngine(
            EngineConfig(model="tiny", batch_size=4, buckets=(32,),
                         moe_dispatch="capacity"),
            registry=MetricsRegistry())
        assert eng.ecfg.moe_dispatch == "capacity"
        with pytest.raises(ValueError, match="moe_dispatch"):
            InferenceEngine(
                EngineConfig(model="tiny", moe_dispatch="scatter"),
                registry=MetricsRegistry())
        args = build_parser().parse_args(
            ["--urls", "a", "--infer-model", "tiny",
             "--infer-moe-dispatch", "capacity"])
        cfg, r = resolve_config(args, env={})
        assert _make_engine(cfg, r).ecfg.moe_dispatch == "capacity"

    def test_pipelined_chunks_keep_order_across_buckets(self):
        """The one-deep dispatch/readback pipeline must not reorder or
        drop results when inputs span several buckets and ragged chunk
        boundaries."""
        from dataclasses import replace as dc_replace

        eng = _engine()
        eng.cfg = dc_replace(eng.cfg, batch_size=3)
        texts = [f"w{i} " * (3 if i % 3 == 0 else 20) for i in range(11)]
        out = eng.run(texts)
        assert len(out) == 11
        assert all(r is not None and "embedding" in r for r in out)
        # Same inputs twice -> identical labels in identical positions.
        again = eng.run(texts)
        assert [r["label"] for r in out] == [r["label"] for r in again]

    def test_metrics_recorded(self):
        reg = MetricsRegistry()
        eng = _engine(registry=reg)
        eng.run(["a", "b"])
        assert eng.m_posts.value == 2
        assert eng.m_latency.count >= 1

    def test_unknown_model_raises(self):
        with pytest.raises(ValueError):
            InferenceEngine(EngineConfig(model="nope"),
                            registry=MetricsRegistry())

    def test_param_dtype_cast_matches_f32(self):
        """param_dtype='bfloat16' halves weight bytes without changing
        predictions meaningfully (serving-time cast, engine.py)."""
        import jax
        import jax.numpy as jnp

        f32 = _engine()
        bf16 = _engine(param_dtype="bfloat16")
        leaves = jax.tree.leaves(bf16.params)
        assert all(leaf.dtype != jnp.float32 for leaf in leaves)
        texts = ["hello world", "a longer piece of text " * 2]
        out32, out16 = f32.run(texts), bf16.run(texts)
        for a, b in zip(out32, out16):
            np.testing.assert_allclose(a["embedding"], b["embedding"],
                                       atol=0.05)
            assert a["label"] == b["label"]

    def test_mesh_sharded_run(self):
        from distributed_crawler_tpu.parallel import best_mesh_config, make_mesh

        mesh = make_mesh(best_mesh_config(8, tp=2))
        cfg = EngineConfig(model="tiny", n_labels=3, batch_size=8,
                           buckets=(16,))
        eng = InferenceEngine(cfg, mesh=mesh, registry=MetricsRegistry())
        out = eng.run(["hello"] * 5)
        assert len(out) == 5


class TestPackedEngine:
    """run_tokenized(..., pack=True): several short sequences share one
    bucket row behind segment masks; results must match the unpacked path
    (tiny config is f32 — tolerances far under bf16) in input order, with
    no extra compiled programs beyond one packed step per bucket."""

    def test_packed_matches_unpacked(self):
        eng = _engine()
        texts = ["hello world", "a much longer piece of text " * 3,
                 "third", "x", "y z w", "more words in this one now"]
        u = eng.run(texts)
        p = eng.run(texts, pack=True)
        for a, b in zip(u, p):
            np.testing.assert_allclose(a["embedding"], b["embedding"],
                                       atol=2e-5)
            assert a["label"] == b["label"]
            np.testing.assert_allclose(a["scores"], b["scores"], atol=2e-5)

    def test_packed_run_tokenized_order_and_chunking(self):
        eng = _engine()  # batch_size=4, buckets (16, 32)
        toks = [[3 + i] * (2 + i % 9) for i in range(23)]
        u = eng.run_tokenized(toks)
        p = eng.run_tokenized(toks, pack=True)
        assert len(p) == 23 and all(r is not None for r in p)
        for a, b in zip(u, p):
            np.testing.assert_allclose(a["embedding"], b["embedding"],
                                       atol=2e-5)

    def test_one_packed_program_per_bucket(self):
        """Different fill levels (3 vs 23 sequences, partial final rows)
        must reuse ONE compiled packed program per bucket — packing adds
        the segment-id/position operands, never a new (bucket, batch)
        shape."""
        eng = _engine()
        eng.run_tokenized([[5] * 3] * 3, pack=True)
        eng.run_tokenized([[5 + i % 7] * (2 + i % 11) for i in range(23)],
                          pack=True)
        assert eng._packed_steps, "packed path compiled nothing"
        for bucket, fn in eng._packed_steps.items():
            assert fn._cache_size() == 1, \
                f"bucket {bucket} compiled {fn._cache_size()} variants"

    def test_packed_fewer_dispatches_for_short_texts(self):
        """32 two-token sequences at batch_size=4: unpacked needs 8 device
        batches; packed (8 segments per 16-bucket row -> 4 rows) needs 1 —
        the pad-token FLOPs the tentpole removes."""
        reg = MetricsRegistry()
        eng = _engine(registry=reg)
        toks = [[7, 8] for _ in range(32)]
        eng.run_tokenized(toks, pack=True)
        assert eng.m_packed.value == 32
        # 32 seqs / 8-per-row = 4 rows = exactly one batch of 4.
        assert eng.m_latency.count == 1

    def test_packed_metrics_recorded(self):
        reg = MetricsRegistry()
        eng = _engine(registry=reg)
        eng.run(["a", "b", "c"], pack=True)
        assert eng.m_posts.value == 3
        assert eng.m_packed.value == 3

    def test_packed_matches_unpacked_property(self):
        """Property form: arbitrary ragged length mixes (1..40 tokens,
        spanning both buckets and chunk boundaries) produce identical
        embeddings/labels packed vs unpacked."""
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        eng = _engine()

        @settings(max_examples=15, deadline=None)
        @given(lengths=st.lists(st.integers(1, 40), min_size=1,
                                max_size=24))
        def check(lengths):
            toks = [[(7 * i + j) % 500 + 3 for j in range(n)]
                    for i, n in enumerate(lengths)]
            u = eng.run_tokenized(toks)
            p = eng.run_tokenized(toks, pack=True)
            for a, b in zip(u, p):
                np.testing.assert_allclose(a["embedding"], b["embedding"],
                                           atol=2e-5)
                np.testing.assert_allclose(a["scores"], b["scores"],
                                           atol=2e-5)

        check()

    def test_empty_token_lists_identical_both_paths(self):
        """Empty inputs (media-only posts) get ONE canonical result —
        zero embedding, uniform scores — identical packed and unpacked,
        so a fallback path switch can never flip a stored label."""
        eng = _engine()
        toks = [[5, 6, 7], [], [8, 9], []]
        u = eng.run_tokenized(toks)
        p = eng.run_tokenized(toks, pack=True)
        for i in (1, 3):
            assert u[i] == p[i]
            assert u[i]["embedding"] == [0.0] * 64
            np.testing.assert_allclose(u[i]["scores"], 1.0 / 3, atol=1e-9)
        np.testing.assert_allclose(u[0]["embedding"], p[0]["embedding"],
                                   atol=2e-5)
        np.testing.assert_allclose(u[2]["embedding"], p[2]["embedding"],
                                   atol=2e-5)

    def test_warmup_compiles_the_packed_path(self):
        eng = _engine()
        eng.warmup(pack=True)
        assert set(eng._packed_steps) == set(eng.bucket_spec.lengths)
        assert not eng._steps  # unpacked programs not paid for
        eng2 = _engine()
        eng2.warmup()  # default warms BOTH paths
        assert set(eng2._steps) == set(eng2.bucket_spec.lengths)
        assert set(eng2._packed_steps) == set(eng2.bucket_spec.lengths)

    def test_packed_mesh_sharded_run(self):
        from distributed_crawler_tpu.parallel import (
            best_mesh_config,
            make_mesh,
        )

        mesh = make_mesh(best_mesh_config(8, tp=2))
        cfg = EngineConfig(model="tiny", n_labels=3, batch_size=8,
                           buckets=(16,))
        eng = InferenceEngine(cfg, mesh=mesh, registry=MetricsRegistry())
        out = eng.run(["hello"] * 5, pack=True)
        assert len(out) == 5
        assert all(r is not None for r in out)


def _posts(n):
    return [Post(post_uid=f"p{i}", channel_name="chan",
                 description=f"message text {i}") for i in range(n)]


class TestTPUWorker:
    def _make(self, provider=None):
        bus = InMemoryBus()
        eng = _engine()
        worker = TPUWorker(bus, eng, provider=provider,
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=0.05),
                           registry=MetricsRegistry())
        return bus, worker

    def test_processes_batch_and_publishes_results(self):
        bus, worker = self._make()
        got = []
        bus.subscribe(TOPIC_INFERENCE_RESULTS, got.append)
        bus.start()
        worker.start()
        batch = RecordBatch.from_posts(_posts(3), crawl_id="c1")
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        worker.stop()
        bus.close()
        assert got, "no results published"
        rb = RecordBatch.from_dict(got[0])
        assert len(rb.results) == 3
        assert rb.results[0]["label"] in (0, 1, 2)

    def test_writeback_jsonl(self):
        provider = InMemoryStorageProvider()
        bus, worker = self._make(provider=provider)
        bus.start()
        worker.start()
        batch = RecordBatch.from_posts(_posts(2), crawl_id="c9")
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
        deadline = time.monotonic() + 10
        while worker.status()["processed"] == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        worker.stop()
        bus.close()
        from distributed_crawler_tpu.inference.worker import iter_results
        lines = list(iter_results(provider, "c9"))
        assert len(lines) == 2
        assert lines[0]["post_uid"] == "p0"
        assert "embedding" in lines[0] and "label" in lines[0]

    def test_writeback_idempotent_on_redelivery(self):
        """A bus redelivery of the same batch overwrites the same per-batch
        file — zero duplicated rows (SURVEY.md §7 hard part (d))."""
        provider = InMemoryStorageProvider()
        bus, worker = self._make(provider=provider)
        bus.start()
        worker.start()
        batch = RecordBatch.from_posts(_posts(2), crawl_id="c9")
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())  # redelivery
        deadline = time.monotonic() + 10
        while worker.status()["processed"] < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        worker.stop()
        bus.close()
        from distributed_crawler_tpu.inference.worker import iter_results
        lines = list(iter_results(provider, "c9"))
        assert len(lines) == 2  # not 4
        assert {l["post_uid"] for l in lines} == {"p0", "p1"}

    def test_manual_ack_after_processing(self):
        """With an ack-capable bus, the ack fires only after writeback."""
        provider = InMemoryStorageProvider()
        eng = _engine()
        acks = []

        class AckBus(InMemoryBus):
            def subscribe(self, topic, handler):
                if topic == TOPIC_INFERENCE_BATCHES:
                    # Deliver with an ack callable, RemoteBus-style.
                    super().subscribe(
                        topic, lambda payload: handler(
                            payload, lambda ok=True: acks.append(ok)))
                else:
                    super().subscribe(topic, handler)

        bus = AckBus()
        worker = TPUWorker(bus, eng, provider=provider,
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=3600),
                           registry=MetricsRegistry())
        bus.start()
        worker.start()
        batch = RecordBatch.from_posts(_posts(2), crawl_id="ack1")
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
        deadline = time.monotonic() + 10
        while not acks and time.monotonic() < deadline:
            time.sleep(0.01)
        worker.stop()
        bus.close()
        assert acks == [True]
        from distributed_crawler_tpu.inference.worker import iter_results
        assert len(list(iter_results(provider, "ack1"))) == 2

    def test_heartbeats_published(self):
        bus, worker = self._make()
        beats = []
        bus.subscribe(TOPIC_WORKER_STATUS, beats.append)
        bus.start()
        worker.start()
        deadline = time.monotonic() + 5
        while len(beats) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        worker.stop()
        bus.close()
        assert len(beats) >= 2
        assert beats[0]["worker_id"] == "w1"

    def test_empty_batch_ignored(self):
        bus, worker = self._make()
        bus.start()
        worker.start()
        bus.publish(TOPIC_INFERENCE_BATCHES, RecordBatch().to_dict())
        time.sleep(0.2)
        assert worker.status()["processed"] == 0
        worker.stop()
        bus.close()


class _GateTokenizer:
    """Tokenizer that raises on a poison marker — the per-record failure
    front door the coalescing feed must isolate per batch."""

    def __init__(self, inner):
        self.inner = inner

    def encode_batch(self, texts):
        if any("POISON" in t for t in texts):
            raise ValueError("poisoned record")
        return self.inner.encode_batch(texts)

    def encode(self, text):
        return self.inner.encode(text)


class TestCoalescingFeed:
    """The feed loop drains up to coalesce_batches queued RecordBatches
    into ONE (packed) engine stream, then fans results back so each batch
    keeps its own ack + idempotent writeback, and a poisoned batch fails
    only its own ack."""

    def _make(self, provider=None, coalesce=4, pack=True, engine=None):
        bus = InMemoryBus()
        eng = engine or _engine()
        worker = TPUWorker(bus, eng, provider=provider,
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=3600,
                                               coalesce_batches=coalesce,
                                               pack=pack),
                           registry=MetricsRegistry())
        return bus, worker, []

    def _run_batches(self, bus, worker, acks, batches, n_expected):
        """Enqueue all batches (RemoteBus-style manual acks) BEFORE the
        feed thread starts, so one dequeue coalesces them into a single
        group deterministically."""
        bus.start()
        for b in batches:
            worker._handle_payload(
                b.to_dict(),
                (lambda bid: lambda ok=True: acks.append((bid, ok)))(
                    b.batch_id))
        worker.start()
        deadline = time.monotonic() + 10
        while len(acks) < n_expected and time.monotonic() < deadline:
            time.sleep(0.01)
        assert worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()

    def test_coalesced_group_acks_and_writes_per_batch(self):
        provider = InMemoryStorageProvider()
        bus, worker, acks = self._make(provider=provider)
        batches = [RecordBatch.from_posts(_posts(3), crawl_id=f"co{i}")
                   for i in range(3)]
        self._run_batches(bus, worker, acks, batches, n_expected=3)
        assert sorted(acks) == sorted(
            [(b.batch_id, True) for b in batches])
        assert worker.m_coalesce.count >= 1  # the group actually coalesced
        from distributed_crawler_tpu.inference.worker import iter_results
        for i, b in enumerate(batches):
            lines = list(iter_results(provider, f"co{i}"))
            assert len(lines) == 3, f"batch {i} writeback missing"
            assert {l["batch_id"] for l in lines} == {b.batch_id}

    def test_coalesced_results_match_solo_run(self):
        """Fan-out must hand each batch ITS rows: labels equal a
        non-coalesced run of the same posts."""
        eng = _engine()
        solo = eng.run([f"message text {i}" for i in range(3)])
        provider = InMemoryStorageProvider()
        bus, worker, acks = self._make(provider=provider,
                                       engine=_engine())
        batches = [RecordBatch.from_posts(_posts(3), crawl_id=f"cm{i}")
                   for i in range(2)]
        self._run_batches(bus, worker, acks, batches, n_expected=2)
        from distributed_crawler_tpu.inference.worker import iter_results
        for i in range(2):
            lines = list(iter_results(provider, f"cm{i}"))
            assert [l["label"] for l in lines] == \
                [r["label"] for r in solo]

    def test_poisoned_batch_fails_only_its_own_ack(self):
        provider = InMemoryStorageProvider()
        eng = _engine()
        eng.tokenizer = _GateTokenizer(eng.tokenizer)
        bus, worker, acks = self._make(provider=provider, engine=eng)
        good1 = RecordBatch.from_posts(_posts(2), crawl_id="g1")
        bad = RecordBatch.from_posts(
            [Post(post_uid="px", channel_name="chan",
                  description="POISON pill")], crawl_id="bad")
        good2 = RecordBatch.from_posts(_posts(2), crawl_id="g2")
        self._run_batches(bus, worker, acks, [good1, bad, good2],
                          n_expected=3)
        by_id = dict(acks)
        assert by_id[good1.batch_id] is True
        assert by_id[bad.batch_id] is False
        assert by_id[good2.batch_id] is True
        from distributed_crawler_tpu.inference.worker import iter_results
        assert len(list(iter_results(provider, "g1"))) == 2
        assert len(list(iter_results(provider, "g2"))) == 2
        assert len(list(iter_results(provider, "bad"))) == 0
        assert worker.status()["errors"] == 1

    def test_coalesce_disabled_processes_singly(self):
        provider = InMemoryStorageProvider()
        bus, worker, acks = self._make(provider=provider, coalesce=1)
        batches = [RecordBatch.from_posts(_posts(2), crawl_id=f"s{i}")
                   for i in range(2)]
        self._run_batches(bus, worker, acks, batches, n_expected=2)
        assert all(ok for _, ok in acks) and len(acks) == 2
        assert worker.m_coalesce.count == 0  # never grouped

    def test_coalesced_step_failure_isolates_per_batch(self):
        """If the COMBINED device step fails, each batch re-runs alone on
        its already-tokenized ids: all good batches still succeed, no
        batch's age is double-counted, nothing re-tokenizes."""

        class FlakyEngine(InferenceEngine):
            tokenize_calls = 0

            def run_tokenized(self, toks, pack=False):
                if len(toks) > 4:  # the 2x3-text coalesced stream only
                    raise RuntimeError("combined step wedged")
                return super().run_tokenized(toks, pack=pack)

        eng = FlakyEngine(
            EngineConfig(model="tiny", n_labels=3, batch_size=4,
                         buckets=(16, 32)), registry=MetricsRegistry())
        inner = eng.tokenizer
        calls = []

        class CountingTokenizer:
            def encode_batch(self, texts):
                calls.append(len(texts))
                return inner.encode_batch(texts)

        eng.tokenizer = CountingTokenizer()
        provider = InMemoryStorageProvider()
        bus, worker, acks = self._make(provider=provider, engine=eng)
        batches = [RecordBatch.from_posts(_posts(3), crawl_id=f"fl{i}")
                   for i in range(2)]
        self._run_batches(bus, worker, acks, batches, n_expected=2)
        assert sorted(acks) == sorted(
            [(b.batch_id, True) for b in batches])
        from distributed_crawler_tpu.inference.worker import iter_results
        for i in range(2):
            assert len(list(iter_results(provider, f"fl{i}"))) == 3
        assert len(calls) == 2  # once per batch at group time; no re-tokenize
        assert worker.m_batch_age.count <= 2  # never double-observed

    def test_worker_warmup_warms_served_path(self):
        bus, worker, _ = self._make()
        worker.warmup()
        eng = worker.engine
        assert set(eng._packed_steps) == set(eng.bucket_spec.lengths)
        assert not eng._steps  # pack=True serves ONLY packed programs

    def test_engine_without_coalesce_support_falls_back(self):
        """Engines predating run_tokenized/pack (test doubles, older
        deployments) must still work through the one-batch path."""

        class MinimalEngine:
            cfg = EngineConfig()

            def run(self, texts):
                return [{"label": 0, "scores": [1.0]} for _ in texts]

        bus, worker, acks = self._make(engine=MinimalEngine())
        assert worker._engine_coalesces is False
        batches = [RecordBatch.from_posts(_posts(2), crawl_id=f"f{i}")
                   for i in range(3)]
        self._run_batches(bus, worker, acks, batches, n_expected=3)
        assert all(ok for _, ok in acks) and len(acks) == 3
        assert worker.status()["processed"] == 3


class TestMetricsEndpoint:
    def test_serve_and_scrape(self):
        reg = MetricsRegistry()
        reg.counter("test_total", "help").inc(5)
        h = reg.histogram("lat_seconds", "help")
        h.observe(0.02)
        server = serve_metrics(0, reg)
        port = server.server_address[1]
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
            assert "test_total 5.0" in body
            assert 'lat_seconds_bucket{le="+Inf"} 1' in body
            health = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5).read()
            assert health == b"ok\n"
        finally:
            server.shutdown()

    def test_status_endpoint(self):
        """/status serves the registered get_status map as JSON — the
        orchestrator/worker status surface (`orchestrator.go:596`)."""
        import json as _json

        from distributed_crawler_tpu.utils.metrics import (
            set_status_provider,
        )

        reg = MetricsRegistry()
        set_status_provider(None)  # a prior test's worker may have left one
        server = serve_metrics(0, reg)
        port = server.server_address[1]
        try:
            # No provider: 404.
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=5)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
            set_status_provider(lambda: {"workers": 3, "depth": 1})
            got = _json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status?pretty=1",
                timeout=5).read())
            assert got == {"workers": 3, "depth": 1}
            # A raising provider surfaces as a 500 with the error body so
            # status-code monitors see the breakage.
            set_status_provider(lambda: 1 / 0)
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=5)
                assert False, "expected 500"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert "error" in _json.loads(e.read())
        finally:
            set_status_provider(None)
            server.shutdown()

    def test_quantiles(self):
        reg = MetricsRegistry()
        h = reg.histogram("q_seconds", "")
        for v in [0.01] * 50 + [0.1] * 50:
            h.observe(v)
        assert h.quantile(0.25) == pytest.approx(0.01)
        assert h.quantile(0.9) == pytest.approx(0.1)

    def test_type_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x", "")
        with pytest.raises(ValueError):
            reg.gauge("x", "")


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        import jax.numpy as jnp

        from distributed_crawler_tpu.inference.checkpoint import (
            latest_step_dir,
            load_params,
            save_params,
        )

        params = {"a": jnp.arange(4.0), "b": {"c": jnp.ones((2, 2))}}
        path = str(tmp_path / "ck" / "step_3")
        save_params(path, params)
        restored = load_params(path, like=params)
        np.testing.assert_allclose(np.asarray(restored["b"]["c"]), 1.0)
        assert latest_step_dir(str(tmp_path / "ck")) == path

    def test_latest_step_dir_empty(self, tmp_path):
        from distributed_crawler_tpu.inference.checkpoint import latest_step_dir

        assert latest_step_dir(str(tmp_path / "missing")) is None


class TestCompilationCache:
    def test_programs_persist_to_cache_dir(self, tmp_path):
        import jax

        from distributed_crawler_tpu.inference.engine import (
            enable_compilation_cache,
        )

        cache = str(tmp_path / "xla-cache")
        assert enable_compilation_cache(cache, min_compile_time_s=0.0)
        try:
            eng = _engine()
            eng.run(["persist me"])
            import os

            entries = os.listdir(cache) if os.path.isdir(cache) else []
            assert entries, "no compiled programs persisted"
        finally:
            jax.config.update("jax_compilation_cache_dir", None)


class TestStallWatchdog:
    """A wedged device step must surface (warn + counter + /status flag),
    and optionally hard-exit so a supervisor restarts the worker — shared
    tunneled chips have been observed to hang a ~100 ms step for minutes."""

    class _SlowEngine:
        cfg = EngineConfig()

        def __init__(self, delay_s):
            self.delay_s = delay_s

        def run(self, texts):
            time.sleep(self.delay_s)
            return [{"label": 0, "score": 1.0} for _ in texts]

        def warmup(self):
            self.run(["w"])

    def _run_with(self, stall_warn_s, stall_exit_s, delay_s):
        reg = MetricsRegistry()
        bus = InMemoryBus()
        worker = TPUWorker(bus, self._SlowEngine(delay_s),
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=60.0,
                                               stall_warn_s=stall_warn_s,
                                               stall_exit_s=stall_exit_s),
                           registry=reg)
        exits = []
        worker._exit_fn = exits.append
        bus.start()
        worker.start()
        bus.publish(TOPIC_INFERENCE_BATCHES,
                    RecordBatch.from_posts(_posts(2), crawl_id="c1")
                    .to_dict())
        return bus, worker, exits

    def test_stall_warns_and_flags_status(self):
        bus, worker, exits = self._run_with(
            stall_warn_s=0.1, stall_exit_s=0.0, delay_s=0.8)
        deadline = time.monotonic() + 5
        stalled = False
        while time.monotonic() < deadline and not stalled:
            stalled = worker.get_status()["device_stalled"]
            time.sleep(0.02)
        assert stalled, "status never flagged the stalled step"
        assert worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()
        assert worker.m_stalls.value >= 1
        assert not exits  # warn-only config must never exit
        # After the step completes the flag clears.
        assert worker.get_status()["device_stalled"] is False

    def test_stall_exit_fires_supervisor_restart(self):
        bus, worker, exits = self._run_with(
            stall_warn_s=0.05, stall_exit_s=0.15, delay_s=0.8)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not exits:
            time.sleep(0.02)
        worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()
        assert exits == [17], "stall_exit_s did not trigger the exit path"

    def test_exit_only_config_still_exits(self):
        # stall_warn_s=0 must not silently disable the hard-exit safety.
        bus, worker, exits = self._run_with(
            stall_warn_s=0.0, stall_exit_s=0.15, delay_s=0.8)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not exits:
            time.sleep(0.02)
        worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()
        assert exits and exits[0] == 17

    def test_negative_exit_threshold_means_disabled(self):
        # -1 is a common "off" convention; it must not exit on every poll.
        bus, worker, exits = self._run_with(
            stall_warn_s=0.05, stall_exit_s=-1.0, delay_s=0.3)
        assert worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()
        assert not exits

    def test_warmup_is_guarded_by_watchdog(self):
        # Bring-up compiles are the longest on-chip window: a wedge inside
        # warmup() must still fire the exit path (pre-start()).
        reg = MetricsRegistry()
        worker = TPUWorker(InMemoryBus(), self._SlowEngine(0.8),
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=60.0,
                                               stall_warn_s=0.05,
                                               stall_exit_s=0.15),
                           registry=reg)
        exits = []
        worker._exit_fn = exits.append
        t = threading.Thread(target=worker.warmup, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not exits:
            time.sleep(0.02)
        t.join(timeout=5)
        assert exits and exits[0] == 17, "warmup wedge did not trigger exit"

    def test_fast_steps_never_stall(self):
        bus, worker, exits = self._run_with(
            stall_warn_s=5.0, stall_exit_s=0.0, delay_s=0.01)
        assert worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()
        assert worker.m_stalls.value == 0
        assert not exits


class TestDrainInflight:
    """drain() must cover the batch being processed, not just the queue
    (VERDICT r2 weak #6): drain-then-stop always lands the last writeback."""

    class _SlowEngine:
        cfg = EngineConfig()

        def __init__(self, delay_s=0.5):
            self.delay_s = delay_s

        def run(self, texts):
            time.sleep(self.delay_s)
            return [{"label": 0, "score": 1.0} for _ in texts]

    def test_drain_waits_for_inflight_batch(self):
        provider = InMemoryStorageProvider()
        bus = InMemoryBus()
        worker = TPUWorker(bus, self._SlowEngine(0.5), provider=provider,
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=60.0),
                           registry=MetricsRegistry())
        bus.start()
        worker.start()
        batch = RecordBatch.from_posts(_posts(2), crawl_id="c1")
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
        # Let the feed thread dequeue it (queue empties immediately) while
        # the slow engine is still mid-run.
        deadline = time.monotonic() + 5
        while not worker._queue.empty() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert worker.drain(timeout_s=10.0)
        worker.stop()
        bus.close()
        # The writeback landed BEFORE drain returned.
        rel = f"inference/c1/batches/{batch.batch_id}.jsonl"
        assert provider.exists(rel), "drain returned before final writeback"

    def test_drain_times_out_when_stuck(self):
        bus = InMemoryBus()
        worker = TPUWorker(bus, self._SlowEngine(3.0),
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=60.0),
                           registry=MetricsRegistry())
        bus.start()
        worker.start()
        batch = RecordBatch.from_posts(_posts(1), crawl_id="c1")
        bus.publish(TOPIC_INFERENCE_BATCHES, batch.to_dict())
        time.sleep(0.2)  # engine is now sleeping inside _process
        assert not worker.drain(timeout_s=0.3)
        worker.stop()
        bus.close()


class TestProfilerEndpoint:
    def test_profiler_port_serves(self):
        """profiler_port starts a jax.profiler server that accepts TCP
        connections (the reference ran pprof on :6060, `main.go:60-80`)."""
        import socket

        bus = InMemoryBus()
        worker = TPUWorker(bus, _engine(),
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=60.0,
                                               profiler_port=0),
                           registry=MetricsRegistry())
        # Pick a free port, then start with it.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        worker.cfg.profiler_port = port
        bus.start()
        worker.start()
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as conn:
                assert conn  # something is listening
        finally:
            worker.stop()
            bus.close()


class TestOrchestratorSeesTpuWorker:
    def test_tpu_worker_heartbeats_register_with_orchestrator(self, tmp_path):
        """Crawl orchestrator and TPU worker share one bus: the TPU
        worker's heartbeats land in the orchestrator's worker registry
        (SURVEY §2.3.3's co-scheduling-on-one-slice story)."""
        import time

        from distributed_crawler_tpu.config.crawler import CrawlerConfig
        from distributed_crawler_tpu.orchestrator.orchestrator import (
            Orchestrator,
        )
        from distributed_crawler_tpu.state.interface import (
            LocalConfig,
            StateConfig,
        )
        from distributed_crawler_tpu.state.local import LocalStateManager

        bus = InMemoryBus()
        sm = LocalStateManager(StateConfig(
            storage_root=str(tmp_path), crawl_id="co1",
            local=LocalConfig(base_path=str(tmp_path))))
        cfg = CrawlerConfig()
        cfg.platform = "telegram"
        orch = Orchestrator("co1", cfg, bus, sm)
        orch.start(["chana"], background=False)

        worker = TPUWorker(bus, _engine(),
                           cfg=TPUWorkerConfig(worker_id="tpu-w7",
                                               heartbeat_s=0.05),
                           registry=MetricsRegistry())
        bus.start()
        worker.start()
        deadline = time.monotonic() + 10
        while "tpu-w7" not in orch.workers and time.monotonic() < deadline:
            time.sleep(0.02)
        assert "tpu-w7" in orch.workers
        assert orch.workers["tpu-w7"].status in ("idle", "busy")
        worker.stop()
        # Graceful stop announces worker_stopping: the registry marks the
        # worker cleanly OFFLINE (the autoscaler-retirement contract) —
        # poll briefly, the announcement rides the async bus.
        deadline = time.monotonic() + 5
        while orch.workers["tpu-w7"].status != "offline" \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        bus.close()
        assert orch.workers["tpu-w7"].status == "offline"
