"""Labeled metrics, the span tracer, and the /traces surface.

Covers the observability layer end to end: label-child exposition in valid
Prometheus text format, the expose-vs-observe race fix (snapshot under the
lock), HELP/label escaping, the metrics HTTP server's edge paths (port-0
auto-bind, /status 500, 404, concurrent scrape-under-load), span nesting +
propagation through bus envelopes, and the acceptance path: one batch on
the bus -> one trace covering dispatch, queue wait, coalesce, and every
engine stage, retrievable as JSON from /traces.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_crawler_tpu.bus.codec import RecordBatch
from distributed_crawler_tpu.bus.inmemory import InMemoryBus
from distributed_crawler_tpu.bus.messages import (
    TOPIC_INFERENCE_BATCHES,
    WorkItem,
    WorkItemConfig,
    WorkQueueMessage,
)
from distributed_crawler_tpu.datamodel import Post
from distributed_crawler_tpu.utils import trace
from distributed_crawler_tpu.utils.metrics import (
    MetricsRegistry,
    serve_metrics,
    set_status_provider,
)


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts with an empty, default-configured tracer (it is
    process-global by design — the /traces endpoint serves it)."""
    trace.TRACER.configure(capacity=trace.DEFAULT_CAPACITY, slow_span_s=0.0)
    trace.TRACER.reset()
    yield
    trace.TRACER.configure(capacity=trace.DEFAULT_CAPACITY, slow_span_s=0.0)
    trace.TRACER.reset()


# ---------------------------------------------------------------------------
# labels
# ---------------------------------------------------------------------------
class TestLabeledMetrics:
    def test_counter_children_exposed(self):
        reg = MetricsRegistry()
        c = reg.counter("posts_total", "posts")
        c.inc(2)
        c.labels(platform="telegram").inc(3)
        c.labels(platform="youtube").inc()
        body = c.expose()
        assert "posts_total 2.0" in body
        assert 'posts_total{platform="telegram"} 3.0' in body
        assert 'posts_total{platform="youtube"} 1.0' in body
        # One HELP/TYPE header for the whole family.
        assert body.count("# HELP") == 1 and body.count("# TYPE") == 1

    def test_same_labels_return_same_child(self):
        reg = MetricsRegistry()
        c = reg.counter("x_total", "")
        assert c.labels(a="1", b="2") is c.labels(b="2", a="1")
        assert c.labels(a="1", b="2") is not c.labels(a="1", b="3")

    def test_labels_on_child_rejected(self):
        c = MetricsRegistry().counter("x_total", "")
        with pytest.raises(ValueError):
            c.labels(a="1").labels(b="2")

    def test_no_labels_returns_parent(self):
        c = MetricsRegistry().counter("x_total", "")
        assert c.labels() is c

    def test_gauge_labels(self):
        g = MetricsRegistry().gauge("depth", "")
        g.labels(topic="work").set(4)
        g.labels(topic="results").set(7)
        body = g.expose()
        assert 'depth{topic="work"} 4' in body
        assert 'depth{topic="results"} 7' in body

    def test_histogram_labels_merge_le(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "", buckets=(0.1, 1.0))
        h.labels(bucket="16").observe(0.05)
        h.labels(bucket="32").observe(0.5)
        body = h.expose()
        assert 'lat_seconds_bucket{bucket="16",le="0.1"} 1' in body
        assert 'lat_seconds_bucket{bucket="32",le="0.1"} 0' in body
        assert 'lat_seconds_bucket{bucket="32",le="+Inf"} 1' in body
        assert 'lat_seconds_sum{bucket="32"} 0.5' in body
        assert 'lat_seconds_count{bucket="16"} 1' in body

    def test_label_value_escaping(self):
        c = MetricsRegistry().counter("x_total", "")
        c.labels(q='a"b\\c\nd').inc()
        body = c.expose()
        assert 'x_total{q="a\\"b\\\\c\\nd"} 1.0' in body

    def test_help_escaping(self):
        c = MetricsRegistry().counter("x_total", "line one\nline \\two")
        body = c.expose()
        # Multi-line HELP must not corrupt the text format: the escaped
        # help stays on ONE line.
        assert "# HELP x_total line one\\nline \\\\two\n" in body
        for line in body.splitlines():
            assert line.startswith(("# HELP", "# TYPE", "x_total"))

    def test_registry_exposes_children(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "").labels(k="v").inc()
        reg.histogram("b_seconds", "", buckets=(1.0,)).labels(k="v").observe(0.5)
        body = reg.expose()
        assert 'a_total{k="v"} 1.0' in body
        assert 'b_seconds_count{k="v"} 1' in body


class TestExposeConsistency:
    def test_histogram_expose_atomic_under_observe(self):
        """The satellite race: cumulative +Inf bucket must equal _count in
        EVERY scrape, even with four writers hammering observe()."""
        reg = MetricsRegistry()
        h = reg.histogram("race_seconds", "", buckets=(0.01, 0.1, 1.0))
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                h.observe(0.005 * (i % 50))
                i += 1

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(300):
                body = h.expose()
                inf = int(re.search(
                    r'race_seconds_bucket\{le="\+Inf"\} (\d+)', body).group(1))
                cnt = int(re.search(
                    r"race_seconds_count (\d+)", body).group(1))
                assert inf == cnt, body
        finally:
            stop.set()
            for t in threads:
                t.join()


# ---------------------------------------------------------------------------
# metrics HTTP server
# ---------------------------------------------------------------------------
class TestMetricsServer:
    def _serve(self, reg=None):
        server = serve_metrics(0, reg or MetricsRegistry())
        return server, server.server_address[1]

    def test_port_zero_autobinds(self):
        server, port = self._serve()
        try:
            assert port > 0
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5).read()
            assert body == b"ok\n"
        finally:
            server.shutdown()

    def test_unknown_path_404(self):
        server, port = self._serve()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5)
            assert e.value.code == 404
        finally:
            server.shutdown()

    def test_status_provider_raises_500(self):
        server, port = self._serve()
        set_status_provider(lambda: 1 / 0)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=5)
            assert e.value.code == 500
            assert "error" in json.loads(e.value.read())
        finally:
            set_status_provider(None)
            server.shutdown()

    def test_traces_endpoint_json(self):
        server, port = self._serve()
        try:
            with trace.span("outer", kind="test"):
                with trace.span("inner"):
                    pass
            got = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces", timeout=5).read())
            names = {s["name"] for t in got["traces"] for s in t["spans"]}
            assert {"outer", "inner"} <= names
            limited = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces?limit=1", timeout=5).read())
            assert len(limited["traces"]) <= 1
        finally:
            server.shutdown()

    def test_scrape_while_observing(self):
        """Threaded stress: /metrics scrapes stay internally consistent
        while writers observe concurrently (the HTTP face of the expose
        race fix)."""
        reg = MetricsRegistry()
        h = reg.histogram("srv_seconds", "", buckets=(0.01, 1.0))
        server, port = self._serve(reg)
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                h.observe(0.005)
                h.labels(outcome="ok").observe(0.005)

        threads = [threading.Thread(target=writer) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for _ in range(50):
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=5).read().decode()
                inf = int(re.search(
                    r'srv_seconds_bucket\{le="\+Inf"\} (\d+)', body).group(1))
                cnt = int(re.search(
                    r"srv_seconds_count (\d+)", body).group(1))
                assert inf == cnt
        finally:
            stop.set()
            for t in threads:
                t.join()
            server.shutdown()


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------
class TestTracer:
    def test_nesting_inherits_trace_and_parent(self):
        with trace.span("parent") as p:
            with trace.span("child"):
                pass
        spans = {s.name: s for s in trace.TRACER.spans()}
        assert spans["child"].trace_id == spans["parent"].trace_id
        assert spans["child"].parent_id == p.span_id
        assert spans["parent"].parent_id == ""

    def test_explicit_trace_id_reroots(self):
        with trace.span("publisher"):
            with trace.span("deliver", trace_id="trace_X", parent_id="sp_Y"):
                pass
        spans = {s.name: s for s in trace.TRACER.spans()}
        assert spans["deliver"].trace_id == "trace_X"
        # The publisher thread's unrelated span must NOT become the parent.
        assert spans["deliver"].parent_id == "sp_Y"

    def test_record_retroactive(self):
        trace.record("queue_wait", 0.25, trace_id="trace_Q", batch="b1")
        (s,) = trace.TRACER.spans()
        assert s.name == "queue_wait" and s.trace_id == "trace_Q"
        assert s.duration_s == pytest.approx(0.25)
        assert s.attrs["batch"] == "b1"

    def test_record_without_context_or_id_drops(self):
        trace.record("orphan", 0.1)
        assert trace.TRACER.spans() == []

    def test_ring_bounded(self):
        trace.TRACER.configure(capacity=4)
        for i in range(10):
            trace.record("s", 0.001, trace_id="trace_ring", i=i)
        spans = trace.TRACER.spans()
        assert len(spans) == 4
        assert [s.attrs["i"] for s in spans] == [6, 7, 8, 9]

    def test_capacity_zero_disables(self):
        trace.TRACER.configure(capacity=0)
        with trace.span("nothing"):
            pass
        assert trace.TRACER.spans() == []

    def test_slow_span_logged(self):
        # Attach a handler directly: caplog listens on the root logger, but
        # setup_logging (run by CLI tests in the same session) sets
        # propagate=False on the 'dct' tree, so records never reach root.
        import logging

        trace.TRACER.configure(slow_span_s=0.01)
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        lg = logging.getLogger("dct.trace")
        old_level = lg.level
        lg.addHandler(handler)
        lg.setLevel(logging.WARNING)
        try:
            trace.record("slow_stage", 0.05, trace_id="trace_slow")
            trace.record("fast_stage", 0.001, trace_id="trace_slow")
        finally:
            lg.removeHandler(handler)
            lg.setLevel(old_level)
        msgs = [r.getMessage() for r in records]
        assert any("slow span slow_stage" in m for m in msgs), msgs
        assert not any("fast_stage" in m for m in msgs)

    def test_error_attr_on_exception(self):
        with pytest.raises(RuntimeError):
            with trace.span("boom"):
                raise RuntimeError("x")
        (s,) = trace.TRACER.spans()
        assert s.attrs["error"] is True

    def test_export_groups_by_trace_newest_first(self):
        with trace.span("a", trace_id="trace_1"):
            pass
        with trace.span("b", trace_id="trace_2"):
            pass
        out = trace.TRACER.export()
        assert [t["trace_id"] for t in out["traces"]] == \
            ["trace_2", "trace_1"]
        assert out["traces"][0]["spans"][0]["name"] == "b"

    def test_export_recency_is_last_span_not_first(self):
        """A long-lived trace whose final leg just completed outranks a
        short trace that finished in between (its dispatch span being old
        must not bury it)."""
        trace.record("dispatch", 0.001, trace_id="trace_long")
        trace.record("whole", 0.001, trace_id="trace_short")
        trace.record("handle_result", 0.001, trace_id="trace_long")
        out = trace.TRACER.export(limit=1)
        assert [t["trace_id"] for t in out["traces"]] == ["trace_long"]


class TestPropagation:
    def test_inject_stamps_parent_span(self):
        with trace.span("pub") as p:
            out = trace.inject({"trace_id": "trace_A", "x": 1})
        assert out["parent_span"] == p.span_id
        assert out["x"] == 1

    def test_inject_leaves_untraced_payloads_alone(self):
        payload = {"x": 1}
        with trace.span("pub"):
            assert trace.inject(payload) is payload  # no trace_id -> as-is
        assert trace.inject({"trace_id": "t"}) == {"trace_id": "t"}  # no ctx
        assert trace.inject(b"raw") == b"raw"

    def test_inmemory_bus_carries_parent_span(self):
        bus = InMemoryBus()
        seen = []
        bus.subscribe("topic", seen.append)
        with trace.span("publisher") as p:
            bus.publish("topic", {"trace_id": "trace_B", "v": 7})
        assert seen[0]["parent_span"] == p.span_id
        deliver = [s for s in trace.TRACER.spans() if s.name == "bus.deliver"]
        assert deliver and deliver[0].trace_id == "trace_B"
        assert deliver[0].parent_id == p.span_id
        assert deliver[0].attrs["topic"] == "topic"

    def test_untraced_payload_passes_byte_identical(self):
        bus = InMemoryBus()
        seen = []
        bus.subscribe("topic", seen.append)
        bus.publish("topic", {"v": 7})
        assert seen == [{"v": 7}]
        assert all(s.name != "bus.deliver" for s in trace.TRACER.spans())

    def test_work_queue_message_inherits_item_trace(self):
        item = WorkItem.new("https://t.me/x", 0, "", "c1", "telegram",
                            WorkItemConfig())
        msg = WorkQueueMessage.new(item)
        assert msg.trace_id == item.trace_id

    def test_record_batch_gets_trace_id(self):
        batch = RecordBatch.from_posts(
            [Post(post_uid="p", channel_name="c", description="t")])
        assert batch.trace_id.startswith("trace_")


# ---------------------------------------------------------------------------
# acceptance: one batch -> one trace across the whole pipeline
# ---------------------------------------------------------------------------
class TestEndToEndTrace:
    ENGINE_STAGES = {"engine.tokenize", "engine.pack", "engine.device_put",
                     "engine.compute", "engine.unpack"}

    def test_batch_trace_covers_every_stage(self):
        from distributed_crawler_tpu.inference import (
            EngineConfig,
            InferenceEngine,
            TPUWorker,
            TPUWorkerConfig,
        )
        from distributed_crawler_tpu.inference.bridge import InferenceBridge
        from distributed_crawler_tpu.state.providers import (
            InMemoryStorageProvider,
        )

        class _NullSM:
            def store_post(self, channel_id, post):
                pass

            def close(self):
                pass

        reg = MetricsRegistry()
        bus = InMemoryBus()
        engine = InferenceEngine(
            EngineConfig(model="tiny", n_labels=3, batch_size=4,
                         buckets=(16, 32)), registry=reg)
        worker = TPUWorker(bus, engine, provider=InMemoryStorageProvider(),
                           cfg=TPUWorkerConfig(worker_id="w1",
                                               heartbeat_s=3600,
                                               coalesce_batches=2, pack=True),
                           registry=reg)
        published = []
        bus.subscribe(TOPIC_INFERENCE_BATCHES, published.append)
        # Subscribe the worker BEFORE starting its feed thread so both
        # bridge batches queue up and coalesce into one device stream.
        bus.subscribe(TOPIC_INFERENCE_BATCHES, worker._handle_payload)
        bus.start()
        bridge = InferenceBridge(_NullSM(), bus, crawl_id="c1", batch_size=3,
                                 deadline_s=3600)
        try:
            for i in range(6):  # two full batches of 3
                bridge.store_post("chan", Post(
                    post_uid=f"p{i}", channel_name="chan",
                    description=f"trace me {i}"))
            assert len(published) == 2
            # start() subscribes _handle_payload a second time — harmless
            # here, nothing publishes after this point.
            worker.start()
            assert worker.drain(timeout_s=30.0)
        finally:
            worker.stop()
            bridge.close()
            bus.close()

        tid = published[0]["trace_id"]
        spans = [s for s in trace.TRACER.spans() if s.trace_id == tid]
        names = {s.name for s in spans}
        assert {"orchestrator.dispatch", "bus.deliver",
                "tpu_worker.queue_wait", "tpu_worker.coalesce",
                "tpu_worker.commit"} <= names, names
        assert self.ENGINE_STAGES <= names, names
        # The second batch correlates too: its own queue-wait and commit,
        # and the coalesce span points at it via batch_ids.
        tid2 = published[1]["trace_id"]
        names2 = {s.name for s in trace.TRACER.spans()
                  if s.trace_id == tid2}
        assert {"tpu_worker.queue_wait", "tpu_worker.commit"} <= names2
        coalesce = next(s for s in spans if s.name == "tpu_worker.coalesce")
        assert published[1]["batch_id"] in coalesce.attrs["batch_ids"]

        # Retrievable as JSON from /traces, and /metrics carries the
        # labeled splits (by bucket and by outcome) in valid text format.
        server = serve_metrics(0, reg)
        port = server.server_address[1]
        try:
            got = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces", timeout=5).read())
            ours = [t for t in got["traces"] if t["trace_id"] == tid]
            assert ours, "trace missing from /traces"
            assert self.ENGINE_STAGES <= {s["name"]
                                          for s in ours[0]["spans"]}
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=5).read().decode()
            assert re.search(
                r'tpu_inference_bucket_posts_total\{bucket="\d+"\} \d', body)
            assert 'tpu_worker_batch_outcomes_total{outcome="ok"} 2.0' \
                in body
            for line in body.splitlines():
                assert re.match(
                    r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*'
                    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+)$', line), \
                    f"invalid exposition line: {line!r}"
        finally:
            server.shutdown()
