"""SQL graph store tests: binding-boundary SQL assertions (the reference's
validator_db_test.go strategy) + end-to-end behavior on sqlite, including the
atomic claim semantics and stale/orphan recovery."""

import threading

import pytest

from distributed_crawler_tpu.state import (
    CompositeStateManager,
    EdgeRecord,
    Page,
    PendingEdge,
    PendingEdgeBatch,
    PendingEdgeUpdate,
    SqlConfig,
    SqlGraphStore,
    SqliteBinding,
    StateConfig,
)
from distributed_crawler_tpu.state.sqlstore import RecordingBinding


def store():
    s = SqlGraphStore(SqliteBinding(":memory:"), "crawl1")
    s.ensure_schema()
    return s


def make_batch(batch_id="b1", **kw):
    base = dict(batch_id=batch_id, crawl_id="crawl1", source_channel="src",
                source_page_id="p1", source_depth=2, sequence_id="seq1")
    base.update(kw)
    return PendingEdgeBatch(**base)


def make_edge(batch_id="b1", dest="dst", **kw):
    base = dict(batch_id=batch_id, crawl_id="crawl1", destination_channel=dest,
                source_channel="src", sequence_id="seq1", source_type="mention")
    base.update(kw)
    return PendingEdge(**base)


class TestEdgeRecords:
    def test_save_and_get(self):
        s = store()
        s.save_edge_records([EdgeRecord(destination_channel="d1",
                                        source_channel="s1", walkback=False,
                                        skipped=False, sequence_id="q1")])
        rec = s.get_edge_record("q1", "d1")
        assert rec is not None
        assert rec.source_channel == "s1" and rec.crawl_id == "crawl1"
        assert s.get_edge_record("q1", "nope") is None

    def test_skipped_edge_promotion_flow(self):
        # 400-replacement repair: pick a random skipped edge and promote it.
        s = store()
        s.save_edge_records([
            EdgeRecord(destination_channel="d1", source_channel="s1",
                       skipped=False, sequence_id="q1"),
            EdgeRecord(destination_channel="d2", source_channel="s1",
                       skipped=True, sequence_id="q1"),
        ])
        edge = s.get_random_skipped_edge("q1", "s1")
        assert edge is not None and edge.destination_channel == "d2"
        s.promote_edge("q1", "d2")
        assert s.get_random_skipped_edge("q1", "s1") is None
        assert s.get_edge_record("q1", "d2").skipped is False

    def test_delete_edge_record(self):
        s = store()
        s.save_edge_records([EdgeRecord(destination_channel="d1",
                                        source_channel="s1", sequence_id="q1")])
        s.delete_edge_record("q1", "d1")
        assert s.get_edge_record("q1", "d1") is None


class TestPageBuffer:
    def test_add_get_delete(self):
        s = store()
        s.add_page_to_page_buffer(Page(id="p1", url="chan1", depth=1,
                                       parent_id="p0", sequence_id="q1"))
        s.add_page_to_page_buffer(Page(id="p2", url="chan2", depth=1,
                                       parent_id="p0"))
        pages = s.get_pages_from_page_buffer(10)
        assert {p.url for p in pages} == {"chan1", "chan2"}
        # Targeted delete only removes named pages (tandem safety).
        s.delete_page_buffer_pages(["p1"], [])
        assert [p.url for p in s.get_pages_from_page_buffer(10)] == ["chan2"]
        s.delete_page_buffer_pages([], ["chan2"])
        assert s.get_pages_from_page_buffer(10) == []

    def test_crawl_scoping(self):
        binding = SqliteBinding(":memory:")
        s1 = SqlGraphStore(binding, "crawl1")
        s1.ensure_schema()
        s2 = SqlGraphStore(binding, "crawl2")
        s1.add_page_to_page_buffer(Page(id="p1", url="chan1"))
        assert s2.get_pages_from_page_buffer(10) == []


class TestSeedAndInvalidChannels:
    def test_seed_chat_id_cache_and_watermark(self):
        s = store()
        s.upsert_seed_channel_chat_id("chan1", 12345)
        assert s.get_channel_last_crawled("chan1") is None
        s.mark_channel_crawled("chan1", 12345)
        assert s.get_channel_last_crawled("chan1") is not None
        assert ("chan1", 12345) in s.load_seed_channels()

    def test_seed_invalidation_filtered_from_load(self):
        s = store()
        s.mark_channel_crawled("chan1", 1)
        s.mark_channel_crawled("chan2", 2)
        s.mark_seed_channel_invalid("chan1")
        names = [u for u, _ in s.load_seed_channels()]
        assert names == ["chan2"]
        assert s.get_random_seed_channel() == "chan2"

    def test_invalid_channel_ttl_cache(self):
        s = store()
        s.mark_channel_invalid("badchan", "not_found")
        assert s.load_invalid_channels() == ["badchan"]
        # Expired rows (beyond TTL) are filtered.
        assert s.load_invalid_channels(ttl_days=0) in ([], ["badchan"])


class TestDiscoveredChannels:
    def test_first_claim_wins_once(self):
        s = store()
        assert s.claim_discovered_channel("chan1", "crawl1") is True
        assert s.claim_discovered_channel("chan1", "crawl2") is False
        assert s.is_channel_discovered("chan1")
        assert not s.is_channel_discovered("chan2")

    def test_concurrent_claims_exactly_one_winner(self):
        s = store()
        wins = []
        def claim(i):
            if s.claim_discovered_channel("contested", f"crawl{i}"):
                wins.append(i)
        threads = [threading.Thread(target=claim, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1


class TestTandemQueue:
    def test_batch_lifecycle(self):
        s = store()
        s.create_pending_batch(make_batch())
        s.insert_pending_edge(make_edge(dest="d1"))
        s.insert_pending_edge(make_edge(dest="d2"))
        # Batch still open -> not claimable for walkback even when validated.
        assert s.claim_walkback_batch() == (None, [])
        claimed = s.claim_pending_edges(10)
        assert len(claimed) == 2
        # Claimed edges are in 'validating'; a second claim returns nothing.
        assert s.claim_pending_edges(10) == []
        for e in claimed:
            s.update_pending_edge(PendingEdgeUpdate(
                pending_id=e.pending_id, validation_status="valid"))
        s.close_pending_batch("b1")
        batch, edges = s.claim_walkback_batch()
        assert batch is not None and batch.batch_id == "b1"
        assert batch.status == "processing" and batch.attempt_count == 1
        assert len(edges) == 2
        # While processing, nothing else claimable.
        assert s.claim_walkback_batch() == (None, [])
        s.complete_pending_batch("b1")
        assert s.count_incomplete_batches("crawl1") == 0

    def test_walkback_waits_for_pending_validation(self):
        s = store()
        s.create_pending_batch(make_batch())
        s.insert_pending_edge(make_edge(dest="d1"))
        s.close_pending_batch("b1")
        # Edge still pending -> batch not ready.
        assert s.claim_walkback_batch() == (None, [])
        e = s.claim_pending_edges(1)[0]
        # Edge mid-validation ('validating') also blocks the walkback claim
        # (daprstate.go:4017-4034).
        assert s.claim_walkback_batch() == (None, [])
        s.update_pending_edge(PendingEdgeUpdate(pending_id=e.pending_id,
                                                validation_status="invalid",
                                                validation_reason="not_found"))
        batch, edges = s.claim_walkback_batch()
        assert batch is not None
        assert edges[0].validation_status == "invalid"
        assert edges[0].validation_reason == "not_found"

    def test_claim_order_fifo(self):
        s = store()
        s.create_pending_batch(make_batch())
        from datetime import datetime, timezone
        s.insert_pending_edge(make_edge(
            dest="late", discovery_time=datetime(2026, 2, 1, tzinfo=timezone.utc)))
        s.insert_pending_edge(make_edge(
            dest="early", discovery_time=datetime(2026, 1, 1, tzinfo=timezone.utc)))
        claimed = s.claim_pending_edges(1)
        assert claimed[0].destination_channel == "early"

    def test_stale_batch_recovery_and_poison(self):
        s = store()
        s.create_pending_batch(make_batch())
        s.close_pending_batch("b1")
        batch, _ = s.claim_walkback_batch()
        assert batch is not None
        # Not yet stale: nothing recovered.
        assert s.recover_stale_batch_claims(stale_threshold_s=3600) == 0
        # Stale (threshold 0 via negative): recovered back to closed.
        assert s.recover_stale_batch_claims(stale_threshold_s=-1) == 1
        batch2, _ = s.claim_walkback_batch()
        assert batch2 is not None and batch2.attempt_count == 2
        # Drive to poison: attempt_count reaches MAX_BATCH_ATTEMPTS.
        assert s.recover_stale_batch_claims(-1) == 1
        batch3, _ = s.claim_walkback_batch()
        assert batch3.attempt_count == 3
        # Poison batches are NOT recovered.
        assert s.recover_stale_batch_claims(-1) == 0

    def test_stale_edge_recovery(self):
        s = store()
        s.create_pending_batch(make_batch())
        s.insert_pending_edge(make_edge(dest="d1"))
        assert len(s.claim_pending_edges(1)) == 1
        assert s.recover_stale_edge_claims(stale_threshold_s=-1) == 1
        # Edge is pending again and reclaimable.
        assert len(s.claim_pending_edges(1)) == 1

    def test_orphan_edge_recovery(self):
        s = store()
        s.create_pending_batch(make_batch())
        s.insert_pending_edge(make_edge(dest="d1"))
        s.close_pending_batch("b1")
        # Simulate crash after complete, before flush:
        s.complete_pending_batch("b1")
        assert s.recover_orphan_edges() == 1
        assert s.claim_pending_edges(10) == []

    def test_flush_batch_stats_aggregates_and_deletes(self):
        s = store()
        s.create_pending_batch(make_batch())
        edges = [
            make_edge(dest="d1", source_type="mention", validation_status="valid"),
            make_edge(dest="d2", source_type="mention", validation_status="invalid"),
            make_edge(dest="d3", source_type="url", validation_status="duplicate"),
        ]
        for e in edges:
            s.insert_pending_edge(e)
        s.flush_batch_stats("b1", "crawl1", edges)
        rows = s.binding.query(
            "SELECT source_type, total, valid, invalid, duplicate FROM "
            "source_type_stats WHERE crawl_id = 'crawl1' ORDER BY source_type")
        assert rows == [("mention", 2, 1, 1, 0), ("url", 1, 0, 0, 1)]
        assert s.binding.query("SELECT COUNT(*) FROM pending_edges")[0][0] == 0
        # Second flush accumulates.
        s.flush_batch_stats("b1", "crawl1", edges[:1])
        rows = s.binding.query(
            "SELECT total FROM source_type_stats WHERE source_type='mention'")
        assert rows[0][0] == 3

    def test_access_events(self):
        s = store()
        s.insert_access_event("ip_blocked")
        rows = s.binding.query("SELECT reason FROM access_events")
        assert rows == [("ip_blocked",)]


class TestBindingBoundary:
    """Protocol-level assertions on the SQL the store emits, mirroring the
    reference's fake-Dapr-client tests (`state/validator_db_test.go`)."""

    def test_claim_sql_shape(self):
        rec = RecordingBinding()
        s = SqlGraphStore(rec, "crawl1")
        rec.canned = [[]]
        s.claim_pending_edges(10)
        sql, params = rec.calls[0]
        assert "validation_status = 'validating'" in sql
        assert "WHERE validation_status = 'pending'" in sql
        assert "ORDER BY discovery_time" in sql
        assert "RETURNING" in sql
        assert params[-1] == 10

    def test_insert_access_event_sql(self):
        rec = RecordingBinding()
        SqlGraphStore(rec, "crawl1").insert_access_event("blocked")
        sql, params = rec.calls[0]
        assert sql.startswith("INSERT INTO access_events")
        assert params[0] == "blocked"

    def test_promote_edge_scoped_to_crawl(self):
        rec = RecordingBinding()
        SqlGraphStore(rec, "crawl1").promote_edge("q1", "d1")
        sql, params = rec.calls[0]
        assert "SET skipped = 0" in sql and "crawl_id = ?" in sql
        assert params == ("crawl1", "q1", "d1")


class TestCompositeStateManager:
    def _sm(self, tmp_path):
        return CompositeStateManager(StateConfig(
            crawl_id="c1", crawl_execution_id="e1",
            storage_root=str(tmp_path), sampling_method="random-walk",
            seed_size=2, sql=SqlConfig(url=":memory:")))

    def test_full_surface(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.initialize(["seed1"])
        # seed channels + chat-ID cache
        sm.mark_channel_crawled("seed1", 111)
        sm.load_seed_channels()
        assert sm.get_cached_chat_id("seed1") == (111, True)
        assert sm.is_seed_channel("seed1")
        # invalid channels
        sm.mark_channel_invalid("bad", "not_found")
        assert sm.is_invalid_channel("bad")
        # discovered claim
        assert sm.claim_discovered_channel("newchan", "c1")
        assert sm.is_channel_discovered("newchan")
        # page buffer
        sm.add_page_to_page_buffer(Page(url="chanX", depth=1, parent_id="p0"))
        assert len(sm.get_pages_from_page_buffer(5)) == 1
        # edge records via interface
        sm.save_edge_records([EdgeRecord(destination_channel="d",
                                         source_channel="s", sequence_id="q")])
        assert sm.get_edge_record("q", "d") is not None
        sm.close()

    def test_seed_urls_from_previous_crawl_skipped(self, tmp_path):
        # daprstate.go:487-500: a seed already processed by a previous crawl
        # execution is not re-seeded.
        import json
        prev_state = {"layers": [{"depth": 0, "pages": [
            {"id": "old", "url": "already_done", "status": "fetched"}]}]}
        (tmp_path / "prev1").mkdir()
        (tmp_path / "prev1" / "state.json").write_text(json.dumps(prev_state))
        (tmp_path / "c1").mkdir()
        (tmp_path / "c1" / "metadata.json").write_text(json.dumps(
            {"crawlId": "c1", "previousCrawlId": ["prev1"]}))
        sm = CompositeStateManager(StateConfig(
            crawl_id="c1", crawl_execution_id="e2", storage_root=str(tmp_path),
            sql=SqlConfig(url=":memory:")))
        sm.initialize(["already_done", "fresh"])
        assert {p.url for p in sm.get_layer_by_depth(0)} == {"fresh"}
        assert sm.seen_url("already_done")

    def test_random_walk_layer_from_seed_db(self, tmp_path):
        sm = self._sm(tmp_path)
        sm.mark_channel_crawled("s1", 1)
        sm.mark_channel_crawled("s2", 2)
        sm.initialize_random_walk_layer()
        urls = {p.url for p in sm.get_layer_by_depth(0)}
        assert urls == {"s1", "s2"}
        assert all(p.sequence_id for p in sm.get_layer_by_depth(0))
